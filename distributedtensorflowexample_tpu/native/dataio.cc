// Native data-loader runtime for the TPU framework.
//
// The reference fed its trainers through TensorFlow's native input stack
// (tf.data C++ kernels / input_data readers — SURVEY.md §2 C10/C11, native
// dependency table).  This is the TPU-native equivalent: the per-step
// host-side work — dataset parsing, shuffled batch gather, CIFAR crop/flip
// augmentation — done in C++ with OpenMP, so the host never becomes the
// bottleneck that kills scaling at MNIST-sized per-step compute
// (SURVEY.md §7 "hard parts").
//
// Randomness is drawn by the Python caller and passed in (crop offsets,
// flip bits), so the native and numpy paths are bit-identical and runs
// stay deterministic per seed.
//
// Build: g++ -O3 -march=native -shared -fPIC -fopenmp (see loader.py).

#include <cstddef>
#include <cstdint>
#include <cstring>

#if defined(_OPENMP)
#include <omp.h>
#endif

namespace {

// Big-endian u32 read (IDX headers are big-endian).
inline uint32_t be32(const unsigned char* p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
         (uint32_t(p[2]) << 8) | uint32_t(p[3]);
}

// numpy 'reflect' padding index map for pad=4: padded coord p -> source
// coord in [0, n): mirror without repeating the edge sample.
inline int64_t reflect4(int64_t p, int64_t n) {
  int64_t m = p - 4;
  if (m < 0) m = -m;
  if (m >= n) m = 2 * n - 2 - m;
  return m;
}

}  // namespace

extern "C" {

// ---- IDX (MNIST) ----------------------------------------------------------

// Header query. Returns 0 on success, nonzero error code otherwise.
int idx_images_dims(const unsigned char* buf, size_t len, int64_t* n,
                    int64_t* rows, int64_t* cols) {
  if (len < 16 || be32(buf) != 2051) return 1;
  *n = be32(buf + 4);
  *rows = be32(buf + 8);
  *cols = be32(buf + 12);
  if (len < 16 + size_t(*n) * size_t(*rows) * size_t(*cols)) return 2;
  return 0;
}

// Parse pixels into out[n*rows*cols] floats scaled to [0, 1].
int idx_images_parse(const unsigned char* buf, size_t len, float* out) {
  int64_t n, rows, cols;
  int rc = idx_images_dims(buf, len, &n, &rows, &cols);
  if (rc) return rc;
  const unsigned char* px = buf + 16;
  const int64_t total = n * rows * cols;
  // Multiply by the rounded f32 reciprocal (data/dequant.py
  // U8_UNIT_SCALE): the repo-wide canonical byte->float arithmetic —
  // bit-identical to the numpy loader AND to the in-step affine dequant
  // of a uint8-resident split.  A division would round differently on
  // 126 of the 256 byte values.
  const float kScale = 1.0f / 255.0f;  // constant-folded to the f32 value
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < total; ++i) out[i] = float(px[i]) * kScale;
  return 0;
}

int idx_labels_dims(const unsigned char* buf, size_t len, int64_t* n) {
  if (len < 8 || be32(buf) != 2049) return 1;
  *n = be32(buf + 4);
  if (len < 8 + size_t(*n)) return 2;
  return 0;
}

int idx_labels_parse(const unsigned char* buf, size_t len, int32_t* out) {
  int64_t n;
  int rc = idx_labels_dims(buf, len, &n);
  if (rc) return rc;
  const unsigned char* p = buf + 8;
  for (int64_t i = 0; i < n; ++i) out[i] = int32_t(p[i]);
  return 0;
}

// ---- CIFAR-10 binary ------------------------------------------------------

// Records of [label u8][3072 u8, CHW].  Emits NHWC floats in [0, 1] and
// int32 labels.  n_records = len / 3073.
int cifar_parse(const unsigned char* buf, size_t len, float* out_images,
                int32_t* out_labels) {
  if (len % 3073 != 0) return 1;
  const int64_t n = int64_t(len / 3073);
  const float kScale = 1.0f / 255.0f;  // canonical affine scale (see above)
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    const unsigned char* rec = buf + i * 3073;
    out_labels[i] = int32_t(rec[0]);
    const unsigned char* chw = rec + 1;
    float* img = out_images + i * 3072;
    for (int64_t y = 0; y < 32; ++y)
      for (int64_t x = 0; x < 32; ++x)
        for (int64_t c = 0; c < 3; ++c)
          img[(y * 32 + x) * 3 + c] = float(chw[c * 1024 + y * 32 + x]) * kScale;
  }
  return 0;
}

// ---- Batch assembly -------------------------------------------------------
// Templates need C++ linkage; the extern "C" block reopens for the
// concrete entry points below.
}  // extern "C"

namespace {

// out[i, :] = src[idx[i], :] — the per-step shuffled-minibatch gather.
// T = float (f32 splits) or uint8_t (quantized splits: 4x fewer bytes
// through the gather AND the later host->device copy).
template <typename T>
void gather_rows(const T* src, const int64_t* idx, int64_t batch,
                 int64_t row_elems, T* out) {
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < batch; ++i)
    std::memcpy(out + i * row_elems, src + idx[i] * row_elems,
                size_t(row_elems) * sizeof(T));
}

// One implementation of the crop/flip indexing for every entry point:
// idx == nullptr means identity (output row i sources input row i).
// Pure pixel rearrangement, so it is dtype-generic (f32 and u8).
template <typename T>
void crop_flip_impl(const T* src, const int64_t* idx, int64_t batch,
                    int64_t h, int64_t w, int64_t c, const int32_t* ys,
                    const int32_t* xs, const uint8_t* flips, T* out) {
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < batch; ++i) {
    const T* img = src + (idx ? idx[i] : i) * h * w * c;
    T* dst = out + i * h * w * c;
    const int64_t y0 = ys[i], x0 = xs[i];
    const bool flip = flips[i] != 0;
    for (int64_t y = 0; y < h; ++y) {
      const int64_t sy = reflect4(y0 + y, h);
      for (int64_t x = 0; x < w; ++x) {
        const int64_t ox = flip ? (w - 1 - x) : x;
        const int64_t sx = reflect4(x0 + ox, w);
        const T* s = img + (sy * w + sx) * c;
        T* d = dst + (y * w + x) * c;
        for (int64_t ch = 0; ch < c; ++ch) d[ch] = s[ch];
      }
    }
  }
}

}  // namespace

extern "C" {

void gather_f32(const float* src, const int64_t* idx, int64_t batch,
                int64_t row_elems, float* out) {
  gather_rows(src, idx, batch, row_elems, out);
}

void gather_u8(const unsigned char* src, const int64_t* idx, int64_t batch,
               int64_t row_elems, unsigned char* out) {
  gather_rows(src, idx, batch, row_elems, out);
}

void gather_i32(const int32_t* src, const int64_t* idx, int64_t batch,
                int32_t* out) {
  for (int64_t i = 0; i < batch; ++i) out[i] = src[idx[i]];
}

// ---- CIFAR train augmentation --------------------------------------------

// Random crop from a reflect-padded (pad=4) image + horizontal flip,
// fused: the padded image is never materialized.  src/out are
// [batch, h, w, c]; ys/xs in [0, 8], flips in {0, 1}.
void augment_crop_flip(const float* src, int64_t batch, int64_t h, int64_t w,
                       int64_t c, const int32_t* ys, const int32_t* xs,
                       const uint8_t* flips, float* out) {
  crop_flip_impl(src, nullptr, batch, h, w, c, ys, xs, flips, out);
}

void augment_crop_flip_u8(const unsigned char* src, int64_t batch, int64_t h,
                          int64_t w, int64_t c, const int32_t* ys,
                          const int32_t* xs, const uint8_t* flips,
                          unsigned char* out) {
  crop_flip_impl(src, nullptr, batch, h, w, c, ys, xs, flips, out);
}

// Gather + augment in one pass: rows are pulled from the full training
// array and augmented straight into the output batch (no intermediate
// batch copy).
void gather_augment_f32(const float* src, const int64_t* idx, int64_t batch,
                        int64_t h, int64_t w, int64_t c, const int32_t* ys,
                        const int32_t* xs, const uint8_t* flips, float* out) {
  crop_flip_impl(src, idx, batch, h, w, c, ys, xs, flips, out);
}

void gather_augment_u8(const unsigned char* src, const int64_t* idx,
                       int64_t batch, int64_t h, int64_t w, int64_t c,
                       const int32_t* ys, const int32_t* xs,
                       const uint8_t* flips, unsigned char* out) {
  crop_flip_impl(src, idx, batch, h, w, c, ys, xs, flips, out);
}

int omp_max_threads() {
#if defined(_OPENMP)
  return omp_get_max_threads();
#else
  return 1;
#endif
}

}  // extern "C"
