"""ctypes bindings for the native C++ data-loader (dataio.cc).

Build model: the shared library is compiled lazily on first use with the
image's ``g++`` (no pip/pybind11 — plain ctypes over an ``extern "C"``
surface) and cached next to the source, keyed by a content hash so edits
rebuild automatically.  Every entry point degrades gracefully: if the
toolchain or build is unavailable, ``available()`` is False and callers
fall back to the pure-numpy path (same results, bit-identical — the
randomness is drawn by the caller either way).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
import threading

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "dataio.cc")
_LOCK = threading.Lock()
_LIB: ctypes.CDLL | None = None
_FAILED = False

_F32 = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
_I64 = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
_I32 = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
_U8 = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")


def _so_path() -> str:
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    cache = os.environ.get("DTFE_NATIVE_CACHE",
                           os.path.join(tempfile.gettempdir(),
                                        "dtfe_tpu_native"))
    os.makedirs(cache, exist_ok=True)
    return os.path.join(cache, f"dataio-{digest}.so")


def _build(so: str) -> None:
    # Unique temp name per process: concurrent builds (multi-host tests,
    # parallel pytest) must not interleave linker writes; os.replace makes
    # the final publish atomic whoever finishes last.
    tmp = f"{so}.{os.getpid()}.tmp"
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-fopenmp",
           "-o", tmp, _SRC]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, so)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)


def _bind(lib: ctypes.CDLL) -> None:
    lib.idx_images_dims.argtypes = [_U8, ctypes.c_size_t,
                                    ctypes.POINTER(ctypes.c_int64),
                                    ctypes.POINTER(ctypes.c_int64),
                                    ctypes.POINTER(ctypes.c_int64)]
    lib.idx_images_dims.restype = ctypes.c_int
    lib.idx_images_parse.argtypes = [_U8, ctypes.c_size_t, _F32]
    lib.idx_images_parse.restype = ctypes.c_int
    lib.idx_labels_dims.argtypes = [_U8, ctypes.c_size_t,
                                    ctypes.POINTER(ctypes.c_int64)]
    lib.idx_labels_dims.restype = ctypes.c_int
    lib.idx_labels_parse.argtypes = [_U8, ctypes.c_size_t, _I32]
    lib.idx_labels_parse.restype = ctypes.c_int
    lib.cifar_parse.argtypes = [_U8, ctypes.c_size_t, _F32, _I32]
    lib.cifar_parse.restype = ctypes.c_int
    lib.gather_f32.argtypes = [_F32, _I64, ctypes.c_int64, ctypes.c_int64,
                               _F32]
    lib.gather_f32.restype = None
    lib.gather_u8.argtypes = [_U8, _I64, ctypes.c_int64, ctypes.c_int64, _U8]
    lib.gather_u8.restype = None
    lib.gather_i32.argtypes = [_I32, _I64, ctypes.c_int64, _I32]
    lib.gather_i32.restype = None
    lib.augment_crop_flip.argtypes = [_F32, ctypes.c_int64, ctypes.c_int64,
                                      ctypes.c_int64, ctypes.c_int64, _I32,
                                      _I32, _U8, _F32]
    lib.augment_crop_flip.restype = None
    lib.augment_crop_flip_u8.argtypes = [_U8, ctypes.c_int64, ctypes.c_int64,
                                         ctypes.c_int64, ctypes.c_int64,
                                         _I32, _I32, _U8, _U8]
    lib.augment_crop_flip_u8.restype = None
    lib.gather_augment_f32.argtypes = [_F32, _I64, ctypes.c_int64,
                                       ctypes.c_int64, ctypes.c_int64,
                                       ctypes.c_int64, _I32, _I32, _U8, _F32]
    lib.gather_augment_f32.restype = None
    lib.gather_augment_u8.argtypes = [_U8, _I64, ctypes.c_int64,
                                      ctypes.c_int64, ctypes.c_int64,
                                      ctypes.c_int64, _I32, _I32, _U8, _U8]
    lib.gather_augment_u8.restype = None
    lib.omp_max_threads.argtypes = []
    lib.omp_max_threads.restype = ctypes.c_int


def _get() -> ctypes.CDLL | None:
    global _LIB, _FAILED
    if _LIB is not None or _FAILED:
        return _LIB
    with _LOCK:
        if _LIB is not None or _FAILED:
            return _LIB
        try:
            so = _so_path()
            if not os.path.exists(so):
                _build(so)
            lib = ctypes.CDLL(so)
            _bind(lib)
            _LIB = lib
        except Exception as e:  # toolchain absent, build error, bad cache
            _FAILED = True
            import warnings
            warnings.warn(f"native data loader unavailable, using numpy "
                          f"fallback: {e}")
    return _LIB


def available() -> bool:
    return _get() is not None


def omp_threads() -> int:
    lib = _get()
    return lib.omp_max_threads() if lib else 1


def parse_idx_images(raw: bytes) -> np.ndarray:
    """IDX image bytes -> [N, rows, cols, 1] float32 in [0, 1]."""
    lib = _get()
    buf = np.frombuffer(raw, dtype=np.uint8)
    n = ctypes.c_int64()
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    rc = lib.idx_images_dims(buf, buf.size, ctypes.byref(n),
                             ctypes.byref(rows), ctypes.byref(cols))
    if rc:
        raise ValueError(f"bad IDX image data (code {rc})")
    out = np.empty(n.value * rows.value * cols.value, dtype=np.float32)
    rc = lib.idx_images_parse(buf, buf.size, out)
    if rc:
        raise ValueError(f"bad IDX image data (code {rc})")
    return out.reshape(n.value, rows.value, cols.value, 1)


def parse_idx_labels(raw: bytes) -> np.ndarray:
    """IDX label bytes -> [N] int32."""
    lib = _get()
    buf = np.frombuffer(raw, dtype=np.uint8)
    n = ctypes.c_int64()
    rc = lib.idx_labels_dims(buf, buf.size, ctypes.byref(n))
    if rc:
        raise ValueError(f"bad IDX label data (code {rc})")
    out = np.empty(n.value, dtype=np.int32)
    rc = lib.idx_labels_parse(buf, buf.size, out)
    if rc:
        raise ValueError(f"bad IDX label data (code {rc})")
    return out


def parse_cifar(raw: bytes) -> tuple[np.ndarray, np.ndarray]:
    """CIFAR-10 binary record bytes -> ([N,32,32,3] f32 in [0,1], [N] i32)."""
    lib = _get()
    buf = np.frombuffer(raw, dtype=np.uint8)
    if buf.size % 3073:
        raise ValueError("CIFAR binary length not a multiple of 3073")
    n = buf.size // 3073
    images = np.empty((n, 32, 32, 3), dtype=np.float32)
    labels = np.empty(n, dtype=np.int32)
    rc = lib.cifar_parse(buf, buf.size, images.reshape(-1), labels)
    if rc:
        raise ValueError(f"bad CIFAR data (code {rc})")
    return images, labels


def gather(src: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """out[i] = src[idx[i]] — parallel row gather (f32/u8 ND or i32 1D;
    uint8 moves 4x fewer bytes — the quantized host path)."""
    lib = _get()
    idx = np.ascontiguousarray(idx, dtype=np.int64)
    if src.dtype == np.int32 and src.ndim == 1:
        out = np.empty(idx.size, dtype=np.int32)
        lib.gather_i32(np.ascontiguousarray(src), idx, idx.size, out)
        return out
    if src.dtype not in (np.float32, np.uint8):
        raise TypeError(f"native gather supports f32/u8/i32, got {src.dtype}")
    src = np.ascontiguousarray(src)
    row = int(np.prod(src.shape[1:], dtype=np.int64))
    out = np.empty((idx.size,) + src.shape[1:], dtype=src.dtype)
    fn = lib.gather_f32 if src.dtype == np.float32 else lib.gather_u8
    fn(src.reshape(-1), idx, idx.size, row, out.reshape(-1))
    return out


def gather_augment(src: np.ndarray, idx: np.ndarray, ys: np.ndarray,
                   xs: np.ndarray, flips: np.ndarray) -> np.ndarray:
    """Fused row gather + reflect-pad-4 crop + hflip for [N,H,W,C] f32 or
    uint8 (dtype-preserving: pure pixel rearrangement)."""
    lib = _get()
    idx = np.ascontiguousarray(idx, dtype=np.int64)
    src = np.ascontiguousarray(src)
    if src.dtype not in (np.float32, np.uint8):
        raise TypeError(f"native gather_augment supports f32/u8, "
                        f"got {src.dtype}")
    n, h, w, c = (idx.size,) + src.shape[1:]
    out = np.empty((n, h, w, c), dtype=src.dtype)
    fn = (lib.gather_augment_f32 if src.dtype == np.float32
          else lib.gather_augment_u8)
    fn(src.reshape(-1), idx, n, h, w, c,
       np.ascontiguousarray(ys, dtype=np.int32),
       np.ascontiguousarray(xs, dtype=np.int32),
       np.ascontiguousarray(flips, dtype=np.uint8),
       out.reshape(-1))
    return out


def augment_crop_flip(images: np.ndarray, ys: np.ndarray, xs: np.ndarray,
                      flips: np.ndarray) -> np.ndarray:
    """Reflect-pad-4 random crop + hflip for [N,H,W,C] f32/u8 batches
    (dtype-preserving)."""
    lib = _get()
    images = np.ascontiguousarray(images)
    if images.dtype not in (np.float32, np.uint8):
        raise TypeError(f"native augment supports f32/u8, "
                        f"got {images.dtype}")
    n, h, w, c = images.shape
    out = np.empty_like(images)
    fn = (lib.augment_crop_flip if images.dtype == np.float32
          else lib.augment_crop_flip_u8)
    fn(images.reshape(-1), n, h, w, c,
       np.ascontiguousarray(ys, dtype=np.int32),
       np.ascontiguousarray(xs, dtype=np.int32),
       np.ascontiguousarray(flips, dtype=np.uint8),
       out.reshape(-1))
    return out
