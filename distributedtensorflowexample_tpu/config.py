"""Run configuration and the reference-compatible CLI flag surface.

The reference's trainer.py scripts expose TF-1.x cluster flags
(``--job_name --task_index --ps_hosts --worker_hosts``) plus the usual
hyper-parameter flags (capability contract: BASELINE.json "configs" +
north-star "existing trainer.py entrypoints keep their CLI").  We keep every
flag name; the cluster-topology flags no longer spawn gRPC processes — they
are resolved onto a single SPMD mesh spec (see ``cluster.py``).
"""

from __future__ import annotations

import argparse
import dataclasses
from typing import Sequence


@dataclasses.dataclass
class RunConfig:
    """Everything a trainer needs, parsed from flags.

    Mirrors the flag surface of the reference scripts; cluster fields are
    compatibility aliases interpreted by :mod:`..cluster` rather than a
    description of real parameter-server processes.
    """

    # --- cluster compatibility flags (reference: tf.train.ClusterSpec) ---
    job_name: str = ""              # "", "ps", "worker"
    task_index: int = 0
    ps_hosts: str = ""              # comma-separated host:port (compat alias)
    worker_hosts: str = ""          # comma-separated host:port (compat alias)

    # --- multi-host bootstrap (replaces TF_CONFIG / tf.train.Server) ---
    coordinator_address: str = ""   # host:port of process 0; "" = single host
    num_processes: int = 1
    process_id: int = -1            # -1 = derive from task_index

    # --- training hyper-parameters ---
    batch_size: int = 100           # per-replica batch size (reference semantics:
                                    # per-worker batching; global = batch*replicas)
    global_batch: bool = False      # if True, batch_size is the global batch
    train_steps: int = 1000
    learning_rate: float = 0.01
    momentum: float = 0.0
    weight_decay: float = 0.0
    lr_schedule: str = "constant"   # constant | cosine | step
    warmup_steps: int = 0
    dropout: float = 0.5
    label_smoothing: float = 0.0
    seed: int = 0

    # --- data / logging ---
    data_dir: str = "/tmp/data"
    log_dir: str = "/tmp/train_logs"
    dataset: str = "mnist"          # mnist | cifar10 | synthetic
    eval_every: int = 0             # 0 = eval only at end
    log_every: int = 100
    checkpoint_every: int = 0       # 0 = no periodic checkpoints
    keep_checkpoints: int = 3
    async_checkpoint: bool = True   # background (async) Orbax saves;
                                    # false = synchronous saves (the
                                    # reference Saver's behavior)
    resume: bool = True             # auto-restore latest checkpoint if present
    profile_dir: str = ""           # "" = no trace; else jax.profiler logdir
    profile_start_step: int = 10    # trace starts after this step completes
                                    # (first traced step is start+1, past compile)
    profile_num_steps: int = 5      # trace window length

    # --- parallelism ---
    num_devices: int = 0            # 0 = all visible devices
    sync_mode: str = "sync"         # sync | async (async = local-SGD emulation)
    async_period: int = 8           # param-averaging period for async emulation
    replicas_to_aggregate: int = 0  # SyncReplicasOptimizer partial
                                    # aggregation: R of N replica gradients
                                    # enter each update (rotating subset);
                                    # 0 = all
    dtype: str = "bfloat16"         # compute dtype on TPU (params stay f32)

    # --- memory-traffic knobs (PR-2 bytes diet) ---
    remat: str = "none"             # none | block — checkpoint each residual
                                    # block: backward recomputes the block's
                                    # forward instead of keeping activations
                                    # resident (~1 extra forward of flops for
                                    # an activation footprint of one block);
                                    # resnet20 only, other models ignore it
    shard_update: bool = False      # shard the f32 master-param update +
                                    # optimizer state across the data mesh
                                    # (arXiv:2004.13336): per-chip weight-
                                    # update bytes drop ~1/D; params stay
                                    # replicated for fwd/bwd (sync mode only)
    bucket_grads: str = ""          # "" | auto | <bytes> — fuse the
                                    # per-parameter gradient all-reduces
                                    # into knee-sized buckets (one psum
                                    # per bucket; auto = the measured
                                    # collective knee, bench_collectives).
                                    # With --shard_update: the explicit
                                    # per-bucket reduce-scatter + sharded
                                    # update + all-gather ZeRO-1 schedule.
                                    # Async mode buckets the worker-
                                    # average psums.  No BatchNorm models
    shard_params: bool = False      # ZeRO-3/FSDP (parallel/zero3.py):
                                    # params AND grads live as 1/D
                                    # bucket rows; each bucket's params
                                    # all-gathered just before use and
                                    # freed after, grads reduce-
                                    # scattered per bucket by the
                                    # gather's transpose.  Requires
                                    # --bucket_grads (the row layout);
                                    # sync mode only; no BN models
    zero3_overlap: bool = True      # --shard_params gather schedule:
                                    # true = double-buffered prefetch
                                    # (bucket i+1's all-gather issues
                                    # while bucket i's compute runs);
                                    # false = strictly serial gathers
                                    # (the A/B control bench_lm times).
                                    # Pure scheduling — bitwise-same

    # --- hand-written TPU kernels (ops/pallas) ---
    pallas_ce: bool = False         # fused Pallas loss head in the train step
    fused_optimizer: bool = False   # fused Pallas momentum-SGD apply; measured
                                    # 2.3x SLOWER than XLA's fused apply on a
                                    # v5e chip (flatten/unflatten HBM traffic,
                                    # see BASELINE.md round-2) — kept opt-in
                                    # as the kernel-authoring reference

    # --- input pipeline ---
    device_data: str = "auto"       # auto | on | off — dataset resident in
                                    # HBM with on-device batch gather (kills
                                    # the per-step H2D copy). auto ≡ on in
                                    # EVERY mode (sync, async, augmented)
                                    # since the round-2 unfencing; "off"
                                    # selects the host Batcher+prefetch path
    steps_per_loop: int = 0         # SGD steps fused into one compiled call
                                    # (lax.scan); device_data path only.
                                    # Amortizes dispatch latency like Keras
                                    # steps_per_execution.  0 = AUTO: the
                                    # largest divisor of the remaining
                                    # steps AND the log/eval/checkpoint
                                    # intervals, <= min(64, steps_per_
                                    # epoch) — out-of-box dispatch
                                    # amortization with hooks still on
                                    # their exact steps; pass 1 for one
                                    # dispatch per step
    quantize: str = "auto"          # auto | off | exact | scale — hold
                                    # 8-bit-exact splits as uint8 (4x less
                                    # HBM + gather/upload bytes); all of
                                    # auto/exact/scale select uint8
                                    # storage, off keeps float32
    dequant_impl: str = "auto"      # auto | affine | onehot | lut |
                                    # pallas — the in-step dequant kernel
                                    # for quantized splits.  auto lowers
                                    # to the fused affine (bitwise-
                                    # verified against the 256-entry LUT
                                    # per split; true for MNIST/CIFAR),
                                    # falling back to the one-hot form
                                    # only for non-affine-representable
                                    # splits; lut is the known-slow
                                    # elementwise-gather diagnostic;
                                    # pallas fuses gather+dequant into
                                    # one kernel (replicated data only)
    data_sharding: str = "replicated"  # replicated | sharded — sharded
                                    # splits the resident dataset row-wise
                                    # over the mesh (per-device HBM /
                                    # mesh_size; per-shard shuffling like
                                    # the reference's per-worker dataset
                                    # sharding); device_data path only

    @property
    def ps_host_list(self) -> list[str]:
        return [h for h in self.ps_hosts.split(",") if h]

    @property
    def worker_host_list(self) -> list[str]:
        return [h for h in self.worker_hosts.split(",") if h]


# --help text per flag, kept in sync with actual behavior (round-2 verdict
# caught "auto = sync mode without augmentation" surviving the async
# unfencing; tests/test_config.py asserts the corrected semantics).
_FLAG_HELP = {
    "job_name": 'reference role: "", "ps", or "worker" (ps exits with a '
                "notice: no parameter servers exist on the SPMD mesh)",
    "task_index": "reference task index within --job_name",
    "ps_hosts": "compat alias (comma-separated host:port); no gRPC PS "
                "processes are spawned",
    "worker_hosts": "compat alias; worker list maps onto the device mesh",
    "coordinator_address": "host:port of process 0 for multi-host "
                           "jax.distributed; empty = single host",
    "num_processes": "number of participating host processes",
    "process_id": "this process's id; -1 = derive from --task_index",
    "batch_size": "per-replica batch (reference per-worker semantics; "
                  "global = batch_size x replicas)",
    "global_batch": "if true, --batch_size is the GLOBAL batch",
    "train_steps": "total optimizer steps",
    "learning_rate": "SGD learning rate",
    "momentum": "SGD momentum (0 = plain SGD)",
    "weight_decay": "decoupled weight decay",
    "lr_schedule": "constant | cosine | step",
    "warmup_steps": "linear LR warmup steps",
    "dropout": "dropout rate for CNN FC head",
    "label_smoothing": "cross-entropy label smoothing",
    "seed": "global RNG seed (data order + init)",
    "data_dir": "dataset directory (IDX/.gz MNIST, pickle/binary CIFAR); "
                "missing files are an error unless --dataset synthetic",
    "log_dir": "logs, scalars.jsonl, tfevents, checkpoints",
    "dataset": "mnist | cifar10 | synthetic — synthetic is the explicit "
               "opt-in to the deterministic synthetic split (missing real "
               "bytes never silently substitute)",
    "eval_every": "eval every N steps (0 = only at end)",
    "log_every": "log scalars every N steps",
    "checkpoint_every": "checkpoint every N steps (0 = none periodic)",
    "keep_checkpoints": "keep newest N checkpoints",
    "async_checkpoint": "background Orbax saves (training does not stall "
                        "on serialization); false = synchronous saves "
                        "like the reference's Saver",
    "resume": "auto-restore latest checkpoint in --log_dir",
    "profile_dir": "jax.profiler trace output dir (empty = no trace)",
    "profile_start_step": "trace starts after this step (skips compile)",
    "profile_num_steps": "trace window length in steps",
    "num_devices": "mesh size (0 = all visible devices)",
    "sync_mode": "sync (psum all-reduce per step) | async (local-SGD "
                 "emulation of PS staleness, averaged every --async_period)",
    "async_period": "async mode: steps between parameter averagings",
    "replicas_to_aggregate": "SyncReplicasOptimizer parity: R of N replica "
                             "gradients enter each update (rotating "
                             "subset); 0 = all",
    "dtype": "compute dtype (params stay float32)",
    "remat": "none | block — rematerialize each residual block in the "
             "backward pass (recompute instead of store; trades ~1 extra "
             "forward of flops for an activation HBM footprint of one "
             "block). Same math bitwise; resnet20 only",
    "shard_update": "shard the optimizer state + weight-update compute "
                    "across the data-parallel mesh (ZeRO-1 / "
                    "arXiv:2004.13336): each chip updates 1/D of the "
                    "params and the update is all-gathered; params stay "
                    "replicated for compute. Sync mode only",
    "bucket_grads": "'' | auto | <bytes> — fuse per-parameter gradient "
                    "all-reduces into buckets of at most this many bytes "
                    "(strictly fewer, larger collectives; same gradient "
                    "math — see DESIGN.md §15). auto = sized from the "
                    "measured collective knee (bench_collectives.py; "
                    "BUCKET_GRADS_AUTO_BYTES overrides). Composes with "
                    "--shard_update into the explicit per-bucket "
                    "reduce-scatter + sharded-update + all-gather ZeRO-1 "
                    "schedule; in async mode buckets the worker-average "
                    "psums. Refused by name for BatchNorm models and "
                    "--fused_optimizer",
    "shard_params": "ZeRO-3/FSDP full param+grad sharding "
                    "(arXiv:2004.13336 stage 3): params and grads live "
                    "resident as 1/D bucket rows, each bucket's params "
                    "all-gathered just before its layer consumes them "
                    "(double-buffered prefetch — see --zero3_overlap) "
                    "and freed after last use, grads reduce-scattered "
                    "per bucket, the 1/D update written straight back "
                    "(no step-closing all-gather). Per-device "
                    "param+grad+opt residency ~1/D. Requires "
                    "--bucket_grads; sync mode only; changes the "
                    "checkpoint layout (zero3_rows — cross-layout and "
                    "cross-mesh-size resume refused by name)",
    "zero3_overlap": "with --shard_params: true (default) issues bucket "
                     "i+1's all-gather while bucket i's compute runs "
                     "(at most two gathered buckets in flight — the "
                     "double buffer); false chains the gathers strictly "
                     "serially. Scheduling only, bitwise-identical "
                     "results — the overlap A/B bench_lm.py measures",
    "pallas_ce": "fused Pallas cross-entropy head",
    "fused_optimizer": "fused Pallas momentum-SGD (measured 2.3x slower "
                       "than XLA on v5e — kept as kernel reference; "
                       "rejected under async)",
    "device_data": "auto | on | off — dataset resident in HBM with "
                   "on-device batch gather; auto is equivalent to on in "
                   "every mode (sync, async, augmented CIFAR); off = host "
                   "Batcher + prefetch",
    "steps_per_loop": "SGD steps fused per compiled call (lax.scan over "
                      "the device-resident dataset); like Keras "
                      "steps_per_execution. 0 = auto: largest divisor of "
                      "the remaining steps and the log/eval/checkpoint "
                      "intervals, <= min(64, steps_per_epoch); 1 = one "
                      "dispatch per step",
    "quantize": "auto | off | exact | scale — store 8-bit-exact splits "
                "as uint8 in HBM/host memory (4x less gather and upload "
                "traffic; 8-bit recoverability verified bitwise at build "
                "time); off = always float32.  Which dequant kernel runs "
                "in-step is --dequant_impl's decision",
    "dequant_impl": "auto | affine | onehot | lut | pallas — in-step "
                    "dequant kernel for quantized splits. auto = fused "
                    "affine (u8 * scale + bias, one fused multiply-add) "
                    "when it reproduces the 256-entry LUT bitwise "
                    "(verified per split at quantize time; true for the "
                    "MNIST/CIFAR loader specs — measured 4.1x over the "
                    "round-4 LUT gather on chip), else one-hot-matmul "
                    "LUT (bitwise on any backend). lut = elementwise "
                    "gather diagnostic (the known-slow round-4 form); "
                    "pallas = fused Pallas gather+dequant kernel "
                    "(replicated device_data only)",
    "data_sharding": "replicated | sharded — sharded stores the resident "
                     "split row-wise across the mesh (per-device HBM "
                     "divided by mesh size; shuffling becomes per-shard, "
                     "like the reference's per-worker dataset sharding); "
                     "requires the device_data path",
}


def build_parser(description: str = "TPU-native trainer") -> argparse.ArgumentParser:
    """Argparse parser exposing the full reference-compatible flag surface."""
    p = argparse.ArgumentParser(description=description)
    fields = {f.name: f for f in dataclasses.fields(RunConfig)}
    for name, f in fields.items():
        arg = "--" + name
        doc = _FLAG_HELP.get(name, "")
        helptext = f"{doc} (default: {f.default})" if doc else \
            f"(default: {f.default})"
        if f.type in ("bool", bool):
            p.add_argument(arg, type=_str2bool, default=f.default,
                           help=helptext)
        else:
            typ = {"int": int, "float": float, "str": str}.get(str(f.type), str)
            if isinstance(f.default, int) and not isinstance(f.default, bool):
                typ = int
            elif isinstance(f.default, float):
                typ = float
            p.add_argument(arg, type=typ, default=f.default, help=helptext)
    return p


def _str2bool(v: str) -> bool:
    if isinstance(v, bool):
        return v
    return str(v).lower() in ("1", "true", "t", "yes", "y")


def parse_flags(argv: Sequence[str] | None = None,
                description: str = "TPU-native trainer",
                **overrides) -> RunConfig:
    """Parse argv into a RunConfig; ``overrides`` win over defaults."""
    parser = build_parser(description)
    parser.set_defaults(**overrides)
    ns, _ = parser.parse_known_args(argv)
    kwargs = {f.name: getattr(ns, f.name) for f in dataclasses.fields(RunConfig)}
    return RunConfig(**kwargs)
