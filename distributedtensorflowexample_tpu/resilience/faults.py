"""Deterministic, seed-addressable fault injection for the train loop.

Every fault the rounds-3-5 outage (OUTAGE_r05.md) and the round-2/3
postmortems actually produced, reproducible on CPU at will:

==================  =====================================================
kind                models
==================  =====================================================
``preemption``      the platform's SIGTERM before slice reclaim — raised
                    at an exact step boundary via the process's real
                    signal path, so the loop's cooperative-stop +
                    save-on-exit machinery is what gets exercised
``wedge``           a dispatch that blocks without raising (the
                    ``bench._probe_backend`` 300-s hang / round-3
                    mid-run backend loss) — a boundary sleep that
                    starves the supervisor's heartbeat
``nan_loss``        numeric blowup: the covered FLOAT batch is poisoned
                    so the loss goes non-finite (NaNGuardHook fails fast
                    before the poisoned state can be snapshotted);
                    refused loudly on uint8 batches — no NaN byte exists
                    (use ``corrupt_batch`` there)
``corrupt_batch``   a corrupted batch off the wire: deterministic
                    garbage bytes for uint8 images, wide garbage ids
                    for integer token batches (out-of-vocab by
                    construction — the LM's OOV poison turns them into
                    the NaN the guard fails fast on), non-finite-
                    driving magnitudes for float images.  Rank-
                    targeted (``corrupt_batch@N%RANK`` or the named
                    ``corrupt_batch_rank`` plan) it is the one-bad-
                    host ingest scenario for gang drills
``torn_snapshot``   a checkpoint write torn mid-file — applied to the
                    newest snapshot AFTER the final save (see
                    tools/faultline.py), so recovery must fall back to
                    the previous manifest-valid snapshot
``heartbeat_flap``  a beat delayed to exactly the supervisor's timeout
                    edge, measured from the LAST beat (arg = delay
                    seconds; 0 reads the edge from
                    ``SUPERVISE_HEARTBEAT_TIMEOUT_S``): the boundary
                    blocks until the beat file's age reaches the edge,
                    then touches it — a slow-but-alive run skating the
                    watchdog line, the near-miss a hard wedge never
                    exercises
``journal_torn``    the supervisor's own journal truncated mid-line
                    (post-exit, like torn_snapshot): ``Journal.replay``
                    must skip the torn tail and at worst re-run the one
                    idempotent task whose completion record tore
``kill``            a hard host/process loss — SIGKILL to self at the
                    boundary: no cooperative save, no exit hooks, no
                    flight dump (SIGKILL cannot be caught).  What
                    distinguishes a lost rank from a clean preemption;
                    the gang-supervision drill's "kill rank 1 at
                    step 37" (resilience/fleet.py)
``host_loss``       a host loss, not just a process loss: the rank
                    writes its fleet-exported tombstone
                    (``FLEET_HOST_DOWN_FILE``, the spawn-OSError seam
                    resilience/fleet.py checks before every spawn) and
                    then SIGKILLs itself — the next respawn of this
                    rank FAILS like a dead host, driving the fleet's
                    rank-loss taxonomy (elastic shrink / refusal) as
                    policy.  ``arg`` = seconds until the host answers
                    again (the tombstone self-expires, so the recovery
                    re-probe grows the gang back); 0 = down until the
                    tombstone is removed.  ``host_loss@N:SECS%rank``
``slow_rank``       a PERSISTENT straggler: every step boundary from the
                    fault step onward is delayed ``arg`` seconds
                    (default 0.25) — slow-but-alive, heartbeats keep
                    flowing, nothing crashes; only throughput suffers.
                    Pinned to one rank (``slow_rank@10:0.5%1``) it is
                    the reproducible scenario the lockstep-SPMD
                    ``replicas_to_aggregate`` shape exists for, and the
                    control case for the bucketed/overlapped collective
                    schedules (--bucket_grads): a straggler stretches
                    every rendezvous, so fewer collectives per step =
                    fewer stretch points.  Survives resume: a plan step
                    already passed at restart re-activates the delay
                    (the rank is still slow) instead of dropping it
``shard_loss``      one rank's shard directory deleted from the newest
                    shard-redundant snapshot set AFTER the final save
                    (post-exit, like torn_snapshot) — recovery must
                    reconstruct the missing shard from its ring mirror
                    (resilience/shardstore.py); ``%RANK`` names the
                    MESH-SHARD index inside this process's own store,
                    not a process rank
``bitflip``         silent bit rot: one payload byte of one rank's own
                    shard flipped in place (post-exit) — the sha256
                    digest must catch it and restore must reconstruct
                    from the mirror, never silently load the rotten
                    bytes.  ``%RANK`` = mesh-shard index, as above
==================  =====================================================

A plan is addressed by ``(text, num_steps, seed)``: unpinned fault steps
are drawn from ``random.Random`` seeded with those, so the same CLI line
reproduces the same scenario anywhere (tools/faultline.py), and a
different seed explores a different schedule with no code change.

Multi-process drills add per-rank targeting: a spec may carry
``rank=N`` (CLI grammar ``kind[@step][:arg][%rank]``, e.g.
``kill@37%1`` = "kill rank 1 at step 37"), and each rank filters the
shared plan text through :meth:`FaultPlan.for_rank` — every rank parses
the SAME text with the SAME seed, so unpinned steps land on the same
anchor fleet-wide and the scenario stays one reproducible triple.

Loop-level faults ride the Hook surface (training/hooks.py); batch-level
faults wrap the batch iterator (FaultyBatches mirrors TrainLoop's
``steps_per_call`` arithmetic so a fault step inside a fused window
poisons exactly the window that covers it).
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import signal
import time

import jax.numpy as jnp
import numpy as np

from distributedtensorflowexample_tpu.obs import metrics as obs_metrics
from distributedtensorflowexample_tpu.obs import recorder as obs_recorder
from distributedtensorflowexample_tpu.obs import trace as obs_trace
from distributedtensorflowexample_tpu.refusal import ModeRefusal
from distributedtensorflowexample_tpu.training.hooks import (
    Hook, _EveryN, touch_heartbeat)

FAULT_KINDS = ("preemption", "wedge", "nan_loss", "corrupt_batch",
               "torn_snapshot", "heartbeat_flap", "journal_torn", "kill",
               "slow_rank", "host_loss", "shard_loss", "bitflip")
_BATCH_KINDS = ("nan_loss", "corrupt_batch")
_POST_EXIT_KINDS = ("torn_snapshot", "journal_torn", "shard_loss",
                    "bitflip")
# Shard-store faults address a MESH-SHARD index inside one process's own
# ShardStore (a single process owns all D shard files on a D-device CPU
# mesh), so %RANK on them must survive FaultPlan.for_rank's process-rank
# filter.
_SHARD_KINDS = ("shard_loss", "bitflip")

_INJECTED = obs_metrics.counter(
    "faults_injected_total", "fault-plan specs that fired, by kind")

# heartbeat_flap aims its beat at the watchdog edge MINUS this margin:
# time.sleep only ever overshoots, so aiming at the edge itself would
# land the beat strictly past it and a supervisor poll in that overshoot
# window would kill the child the drill says must survive.  The margin
# keeps the near-miss deterministic-survivable while staying far inside
# the supervisor's 0.2-s poll granularity.
FLAP_EDGE_MARGIN_S = 0.05

# Named plans: the scenario library tools/faultline.py exposes.  A None
# step is drawn deterministically from the plan seed (one shared anchor
# per plan, so e.g. torn_snapshot+preemption land at the SAME step — the
# "final write torn" shape).  Entries are (kind, step, arg) or
# (kind, step, arg, rank) — a 4-tuple pins the spec to one rank, the
# grammar's %RANK suffix as a named scenario.
NAMED_PLANS = {
    "none": [],
    "preempt": [("preemption", None, 0.0)],
    "wedge": [("wedge", None, 2.0)],
    "nan_loss": [("nan_loss", None, 0.0)],
    "corrupt_batch": [("corrupt_batch", None, 0.0)],
    # Rank-targeted corruption (the ROADMAP round-8 candidate): ONE
    # rank's batch goes bad off the wire — on a token pipeline the LM's
    # OOV poison NaNs that rank's loss, NaNGuard kills it, and the gang
    # supervisor must tear down + agree a resume step while the healthy
    # ranks were mid-stride.  Rank 1 by convention (the 2-rank drills'
    # non-chief rank); pin others with corrupt_batch@N%RANK.
    "corrupt_batch_rank": [("corrupt_batch", None, 0.0, 1)],
    "torn_snapshot": [("torn_snapshot", None, 0.0),
                      ("preemption", None, 0.0)],
    # arg 0.0: the flap delay defaults to the supervisor-exported
    # timeout itself — the exact edge.
    "heartbeat_flap": [("heartbeat_flap", None, 0.0)],
    # Paired with a preemption (same anchor step) so a supervised run
    # HAS a next attempt — the torn journal only matters at replay.
    "journal_torn": [("journal_torn", None, 0.0),
                     ("preemption", None, 0.0)],
    # Mild persistent straggle from the anchor step on; pin a rank with
    # the spec grammar (slow_rank@N:SECS%RANK) for gang drills.
    "slow_rank": [("slow_rank", None, 0.25)],
    # Rank 1's HOST dies at the anchor step and answers again 2 s later
    # (tombstone self-expiry): the elastic shrink-then-grow scenario the
    # scheduler's autoscaling policy drills.  Pin others / change the
    # outage length with the grammar (host_loss@N:SECS%RANK).
    "host_loss": [("host_loss", None, 2.0, 1)],
    # Mesh-shard 1's snapshot directory vanishes after the final save,
    # paired with a preemption at the same anchor so a supervised run
    # HAS a next attempt — which must reconstruct the shard from its
    # ring mirror and resume bitwise (the "any single-rank shard loss"
    # drill).  Pin another shard with the grammar (shard_loss@N%RANK).
    "shard_loss": [("shard_loss", None, 0.0, 1),
                   ("preemption", None, 0.0)],
    # One payload byte of mesh-shard 1's own file flips after the final
    # save (silent bit rot); the next attempt's restore must DETECT the
    # digest mismatch and reconstruct — never silently load rot.
    "bitflip": [("bitflip", None, 0.0, 1),
                ("preemption", None, 0.0)],
}


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    kind: str
    step: int           # global step the fault fires at (boundary/window)
    arg: float = 0.0    # kind-specific (wedge: seconds to block)
    rank: int | None = None   # None = every rank; N = that rank only

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} "
                             f"(one of {FAULT_KINDS})")
        if self.step < 1:
            raise ValueError(f"fault step {self.step} must be >= 1")
        if self.rank is not None and self.rank < 0:
            raise ValueError(f"fault rank {self.rank} must be >= 0")


class FaultPlan:
    """An ordered set of FaultSpecs plus the seed that addressed them."""

    def __init__(self, specs: list[FaultSpec], seed: int = 0,
                 name: str = ""):
        self.specs = sorted(specs, key=lambda s: (s.step, s.kind))
        self.seed = seed
        self.name = name

    def __bool__(self) -> bool:
        return bool(self.specs)

    @property
    def batch_specs(self) -> list[FaultSpec]:
        return [s for s in self.specs if s.kind in _BATCH_KINDS]

    @property
    def loop_specs(self) -> list[FaultSpec]:
        return [s for s in self.specs
                if s.kind not in _BATCH_KINDS + _POST_EXIT_KINDS]

    @property
    def post_exit_specs(self) -> list[FaultSpec]:
        return [s for s in self.specs if s.kind in _POST_EXIT_KINDS]

    def for_rank(self, rank: int) -> "FaultPlan":
        """This rank's view of a fleet-shared plan: specs pinned to
        another rank drop out; unpinned (rank=None) specs apply
        everywhere.  Every rank filters the SAME parsed plan, so the
        shared seed anchor stays identical fleet-wide — 'kill rank 1 at
        the seed-drawn step' names one step, not one per rank.  Shard-
        store faults (``_SHARD_KINDS``) are exempt: their %RANK names a
        mesh-shard index in THIS process's own store, so every process
        keeps them."""
        keep = [s for s in self.specs
                if s.rank is None or s.rank == rank
                or s.kind in _SHARD_KINDS]
        return FaultPlan(keep, seed=self.seed,
                         name=f"{self.name}[rank {rank}]")

    @classmethod
    def parse(cls, text: str, num_steps: int, seed: int = 0) -> "FaultPlan":
        """Build a plan from CLI text: comma-separated tokens, each a
        named plan from NAMED_PLANS or ``kind[@step][:arg][%rank]``
        (e.g. ``preemption@3``, ``wedge:5.0``, ``kill@37%1`` = kill
        rank 1 at step 37).  Unpinned steps share one anchor drawn
        deterministically from ``(text, num_steps, seed)`` in
        ``[1, num_steps-1]`` — mid-run, never the final step, so
        there is always work left for the recovery to prove itself on."""
        rng = random.Random(f"{text}|{num_steps}|{seed}")
        anchor = rng.randrange(1, max(2, num_steps))
        specs: list[FaultSpec] = []
        for token in filter(None, (t.strip() for t in text.split(","))):
            if token in NAMED_PLANS:
                for entry in NAMED_PLANS[token]:
                    kind, step, arg = entry[:3]
                    rank = entry[3] if len(entry) > 3 else None
                    specs.append(FaultSpec(kind, anchor if step is None
                                           else step, arg, rank=rank))
                continue
            body, _, ranktxt = token.partition("%")
            body, _, argtxt = body.partition(":")
            kind, _, steptxt = body.partition("@")
            specs.append(FaultSpec(
                kind, int(steptxt) if steptxt else anchor,
                float(argtxt) if argtxt else
                (2.0 if kind == "wedge" else
                 0.25 if kind == "slow_rank" else 0.0),
                rank=int(ranktxt) if ranktxt else None))
        return cls(specs, seed=seed, name=text)


def _mark_fired(spec: FaultSpec, step: int) -> None:
    """Every fired fault is telemetry: counted by kind and recorded as
    a zero-duration span, so a flight dump names the injection that
    preceded the death it documents."""
    _INJECTED.labels(kind=spec.kind).inc()
    obs_trace.event("fault", 0.0, kind=spec.kind, step=step)


def mark_host_down(path: str, down_s: float = 0.0,
                   rank: int | None = None) -> None:
    """Write the host-loss tombstone (atomically — the reader must see
    a whole record or none): ``down_s`` > 0 makes the outage self-heal
    after that long (resilience/fleet.py removes the expired tombstone
    at the next probe), 0 means down until the file is removed.  Split
    out of the hook so the seam is unit-testable without SIGKILLing the
    test process."""
    rec = {"ts": obs_metrics._wall(), "down_s": float(down_s),
           "rank": rank, "pid": os.getpid()}
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(rec, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def tear_journal(path: str) -> bool:
    """Truncate ``path`` mid-way through its LAST line — a journal
    append that died between bytes (the ``journal_torn`` fault).  The
    torn tail is exactly what ``supervisor.Journal.replay`` skips; at
    worst the one task whose completion record tore re-runs, and every
    capture phase is idempotent by design.  Returns False (no tear) on
    a missing or empty file."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return False
    body = data.rstrip(b"\n")
    if not body:
        return False
    start = body.rfind(b"\n") + 1
    cut = start + max(1, (len(body) - start) // 2)
    with open(path, "r+b") as f:
        f.truncate(cut)
    return True


class FaultInjectionHook(Hook):
    """Fires loop-level faults at their exact step boundaries.

    Boundary placement is load-bearing: the train step DONATES its input
    state, so faults must land where the loop's own interruption
    machinery lands (see TrainLoop.should_stop) — after a completed
    step, never inside the dispatched call.  A resumed loop whose
    ``start_step`` already passed a fault marks it fired (the run
    already lived through it)."""

    def __init__(self, plan: FaultPlan):
        self._plan = plan
        self._fired: set[int] = set()
        # slow_rank accumulator: once its spec fires, every later
        # boundary sleeps this long (a straggler is a CONDITION, not an
        # event — unlike wedge's one-shot block).
        self._slow_s = 0.0

    def begin(self, loop) -> None:
        self._slow_s = 0.0
        for i, s in enumerate(self._plan.loop_specs):
            if s.step <= loop.start_step:
                self._fired.add(i)
                if s.kind == "slow_rank":
                    # A resumed run past the fault step is STILL slow —
                    # the condition re-activates without re-counting as
                    # a fresh injection.
                    self._slow_s += s.arg

    def after_step(self, step, state, metrics) -> bool:
        for i, s in enumerate(self._plan.loop_specs):
            if i in self._fired or step < s.step:
                continue
            self._fired.add(i)
            _mark_fired(s, step)
            if s.kind == "slow_rank":
                self._slow_s += s.arg
            elif s.kind == "wedge":
                # Blocks without raising — exactly what a dead tunnel
                # does to a jit call.  The heartbeat goes stale; only an
                # external watchdog (resilience.supervisor) can act.
                time.sleep(s.arg)
            elif s.kind == "heartbeat_flap":
                # The near-miss: delay the NEXT beat to exactly the
                # watchdog's timeout edge (arg overrides; 0 reads the
                # edge the supervisor exported), then beat.  The edge
                # is measured from the LAST beat — the age the watchdog
                # actually polls — not from this boundary: the previous
                # boundary's beat landed a step ago, and sleeping the
                # full timeout on top of that would blow past the edge
                # and get the child killed mid-drill.  The staleness
                # check is strictly `age > timeout`, so a beat landing
                # ON the edge must survive — this fault is what keeps
                # that boundary honest.
                delay = s.arg or float(os.environ.get(
                    "SUPERVISE_HEARTBEAT_TIMEOUT_S", "0"))
                if not delay:
                    # Refused loudly, like nan_loss on uint8 batches: a
                    # flap with no edge to aim at would sleep 0 s and
                    # beat into nothing, yet report the drill as fired.
                    raise ValueError(
                        "heartbeat_flap has no timeout edge to aim at: "
                        "pass an explicit delay (heartbeat_flap@N:SECS) "
                        "or run under the supervisor, which exports "
                        "SUPERVISE_HEARTBEAT_TIMEOUT_S")
                hb = os.environ.get("SUPERVISE_HEARTBEAT", "")
                if not hb:
                    # Same discipline: without a beat file the "flap"
                    # would stall the boundary and beat into nothing.
                    raise ModeRefusal(
                        "heartbeat_flap has no heartbeat file to beat "
                        "(SUPERVISE_HEARTBEAT unset) — run under "
                        "supervise.py with --heartbeat/"
                        "--heartbeat_timeout_s, or export "
                        "SUPERVISE_HEARTBEAT")
                try:
                    delay -= time.time() - os.path.getmtime(hb)
                except OSError:
                    pass        # no beat yet: the full delay IS the edge
                time.sleep(max(0.0, delay - FLAP_EDGE_MARGIN_S))
                touch_heartbeat(hb)
            elif s.kind == "preemption":
                # Through the real signal path, not a direct flag poke:
                # the handler installation, the cooperative poll, and
                # the save-on-exit are all under test.
                signal.raise_signal(signal.SIGTERM)
            elif s.kind == "kill":
                # A lost host, not a preemption: SIGKILL is uncatchable,
                # so no save-on-exit, no exit hooks, no flight dump run
                # — recovery must come entirely from what was already on
                # disk (the snapshot this boundary's SnapshotHook wrote
                # before this hook fired) plus an external supervisor.
                os.kill(os.getpid(), signal.SIGKILL)
            elif s.kind == "host_loss":
                # kill's bigger sibling: the HOST goes too.  Tombstone
                # first (the fleet's spawn-OSError seam — the respawn of
                # this rank must fail like a dead host, for `arg`
                # seconds), then the uncatchable SIGKILL.  Refused
                # loudly without the seam: a "host loss" whose respawn
                # would quietly succeed drills nothing.
                down_file = os.environ.get("FLEET_HOST_DOWN_FILE", "")
                if not down_file:
                    raise ValueError(
                        "host_loss has no tombstone seam to write "
                        "(FLEET_HOST_DOWN_FILE unset) — run the drill "
                        "under tools/supervise_fleet.py or "
                        "tools/schedule.py, which export it per rank")
                mark_host_down(
                    down_file, down_s=s.arg,
                    rank=int(os.environ.get("OBS_RANK", "0") or 0))
                os.kill(os.getpid(), signal.SIGKILL)
        if self._slow_s:
            # The straggler condition: pure boundary delay, heartbeats
            # and hooks untouched — slow-but-alive by construction.
            time.sleep(self._slow_s)
        return False


class FaultyBatches:
    """Batch-iterator wrapper that corrupts the batch whose step window
    covers a batch-fault step.  Tracks the loop's position with the same
    ``start_step``/``steps_per_next`` arithmetic as DeviceDataset, so it
    composes with fused multi-step calls."""

    def __init__(self, batches, plan: FaultPlan, start_step: int = 0,
                 steps_per_next: int = 1):
        self._it = iter(batches)
        self._plan = plan
        self._step = int(start_step)
        self._spn = max(1, steps_per_next)
        self._rng = np.random.default_rng(plan.seed)
        self._fired = {i for i, s in enumerate(plan.batch_specs)
                       if s.step <= start_step}
        # TrainLoop reads .prefetch at construction; forward the wrapped
        # iterator's (None when absent keeps the loop's skip behavior).
        self.prefetch = getattr(batches, "prefetch", None)

    def __iter__(self):
        return self

    def __next__(self):
        batch = next(self._it)
        lo, hi = self._step + 1, self._step + self._spn
        self._step = hi
        for i, s in enumerate(self._plan.batch_specs):
            if i in self._fired or not (lo <= s.step <= hi):
                continue
            self._fired.add(i)
            _mark_fired(s, s.step)
            batch = self._corrupt(batch, s.kind)
        return batch

    def _corrupt(self, batch, kind: str):
        img = np.asarray(batch["image"])
        if kind == "nan_loss":
            # The kind check comes FIRST: a nan_loss that silently
            # degraded to legal random values on an integer pipeline
            # would make the NaN-guard drill pass vacuously — the guard
            # never fires, yet the scenario reports success.  (np.full
            # with NaN into an int dtype would not even produce a legal
            # batch — it raises or wraps to garbage silently.)
            if np.issubdtype(img.dtype, np.integer):
                raise ValueError(
                    f"nan_loss cannot be represented in a {img.dtype} "
                    f"batch (no NaN integer exists); use corrupt_batch "
                    f"for uint8/token pipelines or inject on the float "
                    f"(host-fed) path")
            bad = np.full(img.shape, np.nan, img.dtype)
        elif img.dtype == np.uint8:
            # A corrupted uint8 batch off the wire: every value is still
            # a legal byte, so only training dynamics (or a checksum
            # upstream) can notice — deterministic from the plan seed.
            # On a TOKEN pipeline (vocab < 256 by design — transformer_
            # lm.LM_VOCAB) random bytes land out-of-vocab and the LM's
            # OOV poison turns them into the NaN the guard fails fast on.
            bad = self._rng.integers(0, 256, img.shape, dtype=np.uint8)
        elif np.issubdtype(img.dtype, np.integer):
            # Wide-integer token ids off the wire: garbage ids far
            # outside any vocab — XLA gathers would CLAMP them silently,
            # which is exactly why the LM poisons its logits instead
            # (models/transformer_lm.py OOV guard).
            bad = self._rng.integers(0, np.iinfo(np.int32).max,
                                     img.shape).astype(img.dtype)
        else:
            # Finite but loss-exploding magnitudes: overflow to inf/nan
            # inside the forward pass, not in the input itself.
            bad = (self._rng.standard_normal(img.shape) * 1e38).astype(
                img.dtype)
        return {**batch, "image": jnp.asarray(bad)}


class NaNGuardHook(Hook):
    """Fail fast on a non-finite loss.

    Raises at the call boundary (safe: donation completed) so the
    process dies BEFORE the poisoned state reaches a snapshot — the
    exception propagates past the end hooks, the last save on disk is
    the last healthy step, and a supervisor restart resumes from there
    instead of training forward on garbage."""

    def __init__(self, every: int = 1):
        self._due = _EveryN(max(1, every))

    def begin(self, loop) -> None:
        self._due = _EveryN(self._due._every, int(loop.start_step))

    def after_step(self, step, state, metrics) -> bool:
        if self._due(step):
            loss = float(np.asarray(metrics["loss"]))
            if not np.isfinite(loss):
                # Dump the flight BEFORE raising: the exception kills
                # the process, and the poisoned-loss evidence (span
                # ring, counters, loss tail) is the postmortem.
                obs_recorder.dump_global("nan_guard")
                raise FloatingPointError(
                    f"non-finite loss {loss} at step {step} — refusing to "
                    f"snapshot a poisoned state; restart resumes from the "
                    f"last healthy snapshot")
        return False


class MetricsTapeHook(Hook):
    """Record the (step, loss) trajectory — the metric half of the
    bitwise resume-parity contract (a resumed run must reproduce not
    just the final params but every logged value along the way)."""

    def __init__(self):
        self.tape: list[tuple[int, float]] = []

    def after_step(self, step, state, metrics) -> bool:
        self.tape.append((step, float(np.asarray(metrics["loss"]))))
        return False
