"""Supervised recovery: heartbeat watchdog, bounded retries with jittered
exponential backoff, and a journaled priority task queue.

The supervisor runs any entrypoint as a child in its OWN process group
and watches two liveness signals the rounds-3-5 outage proved necessary:

- a **wall deadline** (the driver's outer ``timeout`` shape, but with
  SIGTERM + grace before SIGKILL — a hard kill on a chip-holding process
  has wedged the shared tunnel before, see tools/bench_capture.sh);
- a **heartbeat file** the child touches at step boundaries
  (training/hooks.HeartbeatHook): a slow-but-alive run keeps touching,
  a wedged dispatch stops — the one failure a wall deadline alone either
  kills too early or notices too late.

Exit-code protocol (shared with bench.py and trainers/common.py):

====  ====================================================================
rc    meaning / supervisor reaction
====  ====================================================================
0     done — task complete
143   preempted-with-save (SIGTERM honored, checkpoint written) —
      restart immediately; the child's own ``--resume`` picks up the
      latest snapshot
3     watchdog: backend provably wedged (bench.py's os._exit(3)) — do
      NOT retry; surface "wedged" so a task queue can stop burning the
      window on chip-bound work
else  crash — retry with jittered exponential backoff, bounded
====  ====================================================================

The task queue is the productized replacement for bench_capture.sh's
inline phase ordering: tasks run in priority order, every state change
is journaled (JSON lines, append-only), and a supervisor restarted after
its own death replays the journal and resumes exactly where the previous
one died — a 9-minute recovery window converts the contract headline
first, and the next window picks up from the first unfinished phase.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import signal
import subprocess
import sys
import time
from typing import Callable

from distributedtensorflowexample_tpu.obs import ledger as obs_ledger
from distributedtensorflowexample_tpu.obs import metrics as obs_metrics
from distributedtensorflowexample_tpu.obs import recorder as obs_recorder
from distributedtensorflowexample_tpu.obs import trace as obs_trace
from distributedtensorflowexample_tpu.utils.signals import (
    installed_signal_handler)

RC_PREEMPTED = 143   # SIGTERM honored, state saved (trainers, bench)
RC_WEDGED = 3        # bench watchdog: backend provably wedged

# Child-lifecycle telemetry (obs/): what the watcher-log grep
# archaeology of rounds 3-5 could only approximate.  The heartbeat-age
# gauge is the live "how close is this child to the kill line" signal;
# the kill counter is labeled by escalation reason.
_ATTEMPTS = obs_metrics.counter(
    "supervisor_attempts_total", "child attempts spawned")
_EXITS = obs_metrics.counter(
    "supervisor_child_exits_total",
    "child attempt outcomes, by rc classification")
_KILLS = obs_metrics.counter(
    "supervisor_kills_total", "watchdog group-kills, by reason")
_HB_AGE = obs_metrics.gauge(
    "supervisor_heartbeat_age_seconds",
    "age of the child's newest heartbeat at the last poll")

# Clean preemptions don't consume the crash-retry budget (each one saved
# state and resumes further along — dropping the run after N of them
# would abandon progressing work); this absolute ceiling only backstops
# a pathological preempt storm that never lets an attempt finish.
MAX_PREEMPTIONS = 1000


def _log(msg: str) -> None:
    print(f"supervise: {msg}", file=sys.stderr, flush=True)


def kill_process_group(proc: subprocess.Popen, grace_s: float) -> None:
    """SIGTERM the whole group, grace, then SIGKILL — THE one escalation
    (tpu_watch.sh's shape), shared by the single-child supervisor and
    the fleet gang teardown (resilience/fleet.py) so the grace
    semantics — the window a trainer's SIGTERM handler has to write its
    final checkpoint — can't drift between the two."""
    for sig in (signal.SIGTERM, signal.SIGKILL):
        try:
            os.killpg(proc.pid, sig)
        except (ProcessLookupError, PermissionError):
            pass
        try:
            proc.wait(timeout=grace_s)
            return
        except subprocess.TimeoutExpired:
            continue
    proc.wait()


def export_prometheus_collector(name: str = "supervise") -> str | None:
    """Write the metrics registry to ``$OBS_PROM_DIR/<name>.prom`` (the
    node-exporter textfile-collector dialect) — the round-7 ROADMAP
    leftover: ``obs.export.write_prometheus_textfile`` was wired and
    golden-tested but nothing periodic called it.  Now every completed
    supervisor task (and every fleet gang attempt) refreshes the
    collector file, so a scraper on the box sees attempt/kill/restart
    counters without any HTTP server to babysit.  No-op without
    OBS_PROM_DIR; never raises — telemetry must not kill the run."""
    directory = os.environ.get("OBS_PROM_DIR", "")
    if not directory:
        return None
    try:
        os.makedirs(directory, exist_ok=True)
        from distributedtensorflowexample_tpu.obs import export as obs_export
        return obs_export.write_prometheus_textfile(
            os.path.join(directory, f"{name}.prom"))
    except Exception:
        return None


@dataclasses.dataclass
class RetryPolicy:
    """Bounded retries with jittered exponential backoff.  Jitter is the
    fleet lesson: synchronized retry storms from N supervisors hitting a
    shared tunnel at the same instant look exactly like the outage they
    are recovering from."""

    retries: int = 3            # restarts after the first attempt
    backoff_base_s: float = 1.0
    backoff_factor: float = 2.0
    backoff_max_s: float = 60.0
    jitter: float = 0.5         # +/- fraction of the computed delay

    def delay_s(self, attempt: int, rand01: float) -> float:
        base = min(self.backoff_max_s,
                   self.backoff_base_s * self.backoff_factor ** attempt)
        return max(0.0, base * (1.0 + self.jitter * (2.0 * rand01 - 1.0)))


@dataclasses.dataclass
class SupervisedResult:
    status: str                 # ok | wedged | exhausted
    returncode: int | None
    attempts: int
    reasons: list[str] = dataclasses.field(default_factory=list)


class Journal:
    """Append-only JSON-lines journal; replay() folds it back into the
    task-state map a restarted supervisor resumes from."""

    def __init__(self, path: str | None):
        self._path = path
        if path:
            os.makedirs(os.path.dirname(os.path.abspath(path)),
                        exist_ok=True)

    @property
    def path(self) -> str | None:
        return self._path

    def write(self, event: str, **fields) -> None:
        if not self._path:
            return
        # Through the obs/metrics.py wall seam, not bare time.time():
        # journal rows are the WAL the sim's virtual clock must pin, or
        # two same-seed sim runs differ in every ts field.
        rec = {"ts": round(obs_metrics._wall(), 3), "event": event,
               **fields}
        # Heal a torn tail BEFORE appending: a journal write that died
        # mid-line (or the journal_torn fault) leaves no trailing
        # newline, and appending straight onto the fragment would merge
        # it with THIS record into one unparseable line — replay would
        # then lose a live record, not just skip the dead fragment.
        heal = False
        try:
            with open(self._path, "rb") as f:
                f.seek(-1, os.SEEK_END)
                heal = f.read(1) != b"\n"
        except (OSError, ValueError):
            pass    # missing or empty file: nothing to heal
        with open(self._path, "a") as f:
            if heal:
                f.write("\n")
            f.write(json.dumps(rec) + "\n")
            f.flush()
            os.fsync(f.fileno())

    def events(self) -> list[dict]:
        """Every parseable record, in write order; torn lines skipped
        (the journal itself can die mid-write) — the shared read for
        :meth:`replay` and the fleet's agreement-replay pass."""
        out: list[dict] = []
        if not self._path or not os.path.exists(self._path):
            return out
        with open(self._path) as f:
            for line in f:
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
        return out

    def replay(self) -> dict:
        """{"done": set[str], "wedged": bool} from prior runs; torn tail
        lines are skipped, not fatal — the cost is re-running the task
        whose completion record tore, which is idempotent-by-design for
        every capture phase."""
        done: set[str] = set()
        wedged = False
        for rec in self.events():
            if rec.get("event") == "task_done":
                done.add(rec.get("task", ""))
            elif rec.get("event") == "chip_wedged":
                wedged = True
        return {"done": done, "wedged": wedged}


class Supervisor:
    def __init__(self, policy: RetryPolicy | None = None,
                 journal: Journal | None = None,
                 heartbeat_timeout_s: float = 0.0,
                 wall_timeout_s: float = 0.0,
                 kill_grace_s: float = 10.0,
                 poll_s: float = 0.2,
                 seed: int | None = None):
        self.policy = policy or RetryPolicy()
        self.journal = journal or Journal(None)
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.wall_timeout_s = wall_timeout_s
        self.kill_grace_s = kill_grace_s
        self.poll_s = poll_s
        self._rng = random.Random(seed)

    # --- one attempt ------------------------------------------------------
    def _escalated(self, why: str) -> None:
        """A watchdog kill is exactly the moment a postmortem matters:
        the CHILD is wedged (it can't dump its own flight), so the
        supervisor — the one process still alive and informed — counts
        the kill and dumps ITS flight (heartbeat-age gauge, attempt
        counters, span ring) if one is installed (tools/supervise.py)."""
        _KILLS.labels(why=why).inc()
        # final=False: the supervisor usually OUTLIVES the escalation
        # (retry loop, next queue task), and the atexit dump must still
        # refresh the flight with the true final state — a flight frozen
        # at attempt 1 of 3 would contradict the journal it cross-checks.
        obs_recorder.dump_global(f"escalation_{why}", final=False)

    def _kill_group(self, proc: subprocess.Popen) -> None:
        kill_process_group(proc, self.kill_grace_s)

    def _run_once(self, argv: list[str], env: dict, stdout_file,
                  stderr_file, heartbeat_path: str | None,
                  wall_timeout_s: float) -> tuple[int | None, str]:
        """Returns (returncode, reason) — returncode None on a watchdog
        kill (the child never exited on its own).  stdout and stderr are
        SEPARATE sinks on purpose: bench-family children speak a pure
        JSON-lines protocol on fd 1 (the driver parses the LAST line),
        and stderr prose merged into that artifact would tear it."""
        if heartbeat_path:
            # A heartbeat file left by a PREVIOUS run (or attempt) has a
            # stale mtime; without this reset the first poll would read
            # it as a wedge and kill the fresh child before it can even
            # import jax.  Removing (not touching) routes the no-beat-yet
            # case through the measure-from-spawn fallback below.
            try:
                os.remove(heartbeat_path)
            except OSError:
                pass
        proc = subprocess.Popen(argv, env=env, stdout=stdout_file,
                                stderr=stderr_file,
                                start_new_session=True)
        # The child lives in its OWN session (so the watchdog's killpg
        # can't suicide the supervisor) — which means a SIGTERM aimed at
        # the SUPERVISOR's group (tpu_watch.sh's stale-capture kill)
        # does not reach it.  Forward: on SIGTERM, kill the child group
        # and report, so a watcher group-kill can never orphan a live
        # chip-holding phase behind a dead supervisor.
        sigterm_seen = []

        def _on_term(signum, frame):
            sigterm_seen.append(True)

        start = time.monotonic()
        with installed_signal_handler(signal.SIGTERM, _on_term):
            while True:
                rc = proc.poll()
                if rc is not None:
                    return rc, "exit"
                now = time.monotonic()
                if sigterm_seen:
                    _log(f"supervisor SIGTERM — forwarding to child group "
                         f"{proc.pid} and stopping")
                    self._kill_group(proc)
                    self._escalated("supervisor_sigterm")
                    return None, "supervisor_sigterm"
                if wall_timeout_s and now - start > wall_timeout_s:
                    _log(f"wall timeout {wall_timeout_s:.0f}s — killing "
                         f"group {proc.pid}")
                    self._kill_group(proc)
                    self._escalated("wall_timeout")
                    return None, "wall_timeout"
                if self.heartbeat_timeout_s and heartbeat_path:
                    # Armed only once the FIRST beat lands: heartbeat
                    # participation is the child's opt-in (run_training
                    # and faultline install HeartbeatHook when
                    # SUPERVISE_HEARTBEAT is exported; bench.py does
                    # not).  Measuring from spawn instead would turn the
                    # heartbeat timeout into a hard wall clock for every
                    # beat-less child — killing a healthy bench deep in
                    # its legitimate probe-retry budget.  A child wedged
                    # BEFORE its first beat is the wall timeout's job.
                    try:
                        hb_age = (time.time()
                                  - os.path.getmtime(heartbeat_path))
                    except OSError:
                        hb_age = None       # no first beat: not armed
                    if hb_age is not None:
                        _HB_AGE.set(round(hb_age, 3))
                    if (hb_age is not None
                            and hb_age > self.heartbeat_timeout_s):
                        _log(f"heartbeat stale {hb_age:.1f}s > "
                             f"{self.heartbeat_timeout_s:.0f}s — killing "
                             f"group {proc.pid} (wedged dispatch)")
                        self._kill_group(proc)
                        self._escalated("heartbeat_timeout")
                        return None, "heartbeat_timeout"
                time.sleep(self.poll_s)

    # --- the retry loop ---------------------------------------------------
    @staticmethod
    def _default_name(argv: list[str]) -> str:
        """First operand that names the actual work: skips interpreter
        wrappers, env assignments and flags, and resolves ``-m pkg.mod``
        to the module's last component — so the documented
        ``supervise.py -- python -m ...trainer_sync_mnist`` journals as
        task="trainer_sync_mnist", not task="-m"."""
        toks = list(argv)
        while toks:
            tok = toks.pop(0)
            base = os.path.basename(tok)
            if tok == "-m":
                return toks[0].rsplit(".", 1)[-1] if toks else "-m"
            if (tok.startswith("-") or "=" in tok or base == "env"
                    or base.startswith("python")):
                continue
            return base
        return os.path.basename(argv[0])

    def run(self, argv: list[str], name: str = "",
            stdout_path: str | None = None,
            stderr_path: str | None = None,
            heartbeat_path: str | None = None,
            env_extra: dict | None = None,
            wall_timeout_s: float | None = None) -> SupervisedResult:
        try:
            return self._run(argv, name, stdout_path, stderr_path,
                             heartbeat_path, env_extra, wall_timeout_s)
        finally:
            # Post-task collector refresh (OBS_PROM_DIR): the queue
            # calls run() once per task, so this IS "after every task"
            # — and a single supervised command gets the same export.
            export_prometheus_collector()

    def _run(self, argv: list[str], name: str = "",
             stdout_path: str | None = None,
             stderr_path: str | None = None,
             heartbeat_path: str | None = None,
             env_extra: dict | None = None,
             wall_timeout_s: float | None = None) -> SupervisedResult:
        name = name or self._default_name(argv)
        wall = (self.wall_timeout_s if wall_timeout_s is None
                else wall_timeout_s)
        reasons: list[str] = []
        last_rc: int | None = None
        attempt = -1
        failures = 0    # crash-budget counter; preemptions excluded
        while attempt < self.policy.retries + MAX_PREEMPTIONS:
            attempt += 1
            _ATTEMPTS.inc()
            env = dict(os.environ)
            # The attempt counter lets a child treat injected faults as
            # transient (fire on attempt 0 only) and lets logs attribute
            # output to the retry that produced it.
            env["SUPERVISE_ATTEMPT"] = str(attempt)
            # Telemetry context for the child's obs surface: spans and
            # flight dumps carry the task name as their phase (what
            # makes the capture journal and the telemetry agree), the
            # heartbeat-flap fault reads the exact watchdog edge, and
            # journal_torn finds the journal it tears.
            env.setdefault("OBS_PHASE", name)
            if self.heartbeat_timeout_s and heartbeat_path:
                # Exported only when a beat PATH exists too: the
                # watchdog never arms without one, and advertising an
                # edge no one is watching would let a heartbeat_flap
                # drill stall against nothing and claim success.
                env["SUPERVISE_HEARTBEAT_TIMEOUT_S"] = str(
                    self.heartbeat_timeout_s)
            if self.journal.path:
                env.setdefault("SUPERVISE_JOURNAL", self.journal.path)
            if heartbeat_path:
                env["SUPERVISE_HEARTBEAT"] = heartbeat_path
            if env_extra:
                env.update(env_extra)
            self.journal.write("attempt_start", task=name, attempt=attempt,
                               argv=argv)
            # Per-attempt ledger rows (OBS_LEDGER, inherited by the
            # child which writes its OWN run rows too): the supervisor
            # is the authoritative rc source — a SIGKILLed child never
            # gets to close its own row, this one always closes.
            # wall-ms in the id (the RunLedger/fleet idiom): the ledger
            # is append-only for months and a recycled pid would fold
            # two invocations' attempt rows into one run on read.
            ledger_run = (f"sup:{name}:a{attempt}:"
                          f"{int(obs_metrics._wall() * 1000):x}"
                          f"-{os.getpid()}")
            obs_ledger.log_event(
                "run_start", run=ledger_run, src="supervisor",
                entrypoint=name, attempt=attempt, pid=os.getpid())
            tmp = f"{stdout_path}.tmp" if stdout_path else None
            out = open(tmp, "wb") if tmp else None
            # Append mode: one log accumulates every attempt's prose,
            # like bench_capture.sh's `2>> "$LOG"`.
            err = open(stderr_path, "ab") if stderr_path else None
            try:
                # No stdout artifact but a log sink: archive stdout in
                # the log too (bench_capture.sh's `>> "$LOG" 2>&1` for
                # the bytes-audit table) instead of dropping it.
                rc, reason = self._run_once(argv, env, out or err, err,
                                            heartbeat_path, wall)
            finally:
                if out:
                    out.close()
                if err:
                    err.close()
            if tmp:
                # keep() semantics from bench_capture.sh: every line was
                # flushed as it completed, so a non-empty partial file is
                # a valid partial capture; an empty one must not clobber
                # a previous attempt's output.
                if os.path.getsize(tmp):
                    os.replace(tmp, stdout_path)
                else:
                    os.remove(tmp)
            self.journal.write("attempt_end", task=name, attempt=attempt,
                               rc=rc, reason=reason)
            obs_ledger.log_event("run_end", run=ledger_run,
                                 src="supervisor", rc=rc, reason=reason)
            _EXITS.labels(outcome=(
                "ok" if rc == 0 else
                "terminated" if reason == "supervisor_sigterm" else
                "wedged" if rc == RC_WEDGED else
                "preempted" if rc == RC_PREEMPTED else
                "killed" if rc is None else "crash")).inc()
            last_rc = rc
            reasons.append(f"attempt {attempt}: rc={rc} ({reason})")
            if rc == 0:
                return SupervisedResult("ok", 0, attempt + 1, reasons)
            if reason == "supervisor_sigterm":
                # The supervisor itself is being killed (watcher stale
                # sweep / operator): child group already TERM'd — no
                # retry, report terminated so the queue stops too.
                return SupervisedResult("terminated", rc, attempt + 1,
                                        reasons)
            if rc == RC_WEDGED:
                # The backend is provably gone; a retry burns window
                # wall time against a dead tunnel and resolves nothing.
                _log(f"{name}: watchdog rc={RC_WEDGED} (backend wedged) — "
                     f"not retrying")
                return SupervisedResult("wedged", rc, attempt + 1, reasons)
            if rc == RC_PREEMPTED:
                # Clean preemption already saved and resumes further
                # along: restart now (the backoff exists for crash
                # storms) and do NOT charge the crash budget — N
                # preemptions across a long run must not abandon
                # progressing work as "exhausted".
                _log(f"{name}: rc={RC_PREEMPTED} (preempted, state "
                     f"saved); restarting")
                continue
            failures += 1
            if failures > self.policy.retries:
                break
            delay = self.policy.delay_s(failures - 1, self._rng.random())
            _log(f"{name}: rc={rc} ({reason}); retry "
                 f"{failures}/{self.policy.retries} in {delay:.2f}s")
            if delay:
                time.sleep(delay)
        return SupervisedResult("exhausted", last_rc, attempt + 1, reasons)


@dataclasses.dataclass
class Task:
    """One queue entry.  ``priority``: lower runs first (the capture
    queue's artifact-value order).  ``needs_chip``: skipped once a
    wedge verdict lands.  ``gate``: zero-arg predicate checked at pop
    time (phase 4's fresh-measured-line gate).  ``post``: callable run
    after an ok result (phase 2's trace tar)."""

    name: str
    argv: list[str]
    priority: int = 0
    stdout_path: str | None = None
    stderr_path: str | None = None
    wall_timeout_s: float = 0.0
    needs_chip: bool = True
    env: dict = dataclasses.field(default_factory=dict)
    heartbeat_path: str | None = None
    gate: Callable[[], bool] | None = None
    pre: Callable[[], None] | None = None
    post: Callable[[], None] | None = None


class TaskQueue:
    """Journaled priority queue over a Supervisor.  Replays the journal
    at start: tasks already recorded done are skipped, and a recorded
    wedge verdict keeps chip-bound tasks skipped — resume exactly where
    the previous supervisor died."""

    def __init__(self, tasks: list[Task], supervisor: Supervisor):
        self._tasks = sorted(tasks, key=lambda t: t.priority)
        self._sup = supervisor

    def run(self) -> dict:
        state = self._sup.journal.replay()
        done, chip_dead = state["done"], state["wedged"]
        results: dict[str, str] = {}
        for task in self._tasks:
            if task.name in done:
                _log(f"{task.name}: already done (journal) — skipping")
                results[task.name] = "done_prior"
                continue
            if chip_dead and task.needs_chip:
                self._sup.journal.write("task_skipped", task=task.name,
                                        why="chip wedged")
                results[task.name] = "skipped_wedged"
                continue
            if task.gate is not None and not task.gate():
                self._sup.journal.write("task_skipped", task=task.name,
                                        why="gate")
                results[task.name] = "skipped_gate"
                continue
            if task.pre is not None:
                task.pre()
            with obs_trace.span("task", task=task.name) as attrs:
                res = self._sup.run(task.argv, name=task.name,
                                    stdout_path=task.stdout_path,
                                    stderr_path=task.stderr_path,
                                    heartbeat_path=task.heartbeat_path,
                                    env_extra=task.env,
                                    wall_timeout_s=task.wall_timeout_s)
                attrs["status"] = res.status
                attrs["attempts"] = res.attempts
            if res.status == "ok":
                if task.post is not None:
                    task.post()
                self._sup.journal.write("task_done", task=task.name)
                results[task.name] = "done"
            elif res.status == "terminated":
                # The supervisor is dying (SIGTERM forwarded to the
                # child); no capture_end is journaled, so the NEXT
                # window's supervisor resumes from this exact task.
                results[task.name] = "terminated"
                break
            elif res.status == "wedged":
                chip_dead = True
                self._sup.journal.write("chip_wedged", task=task.name)
                self._sup.journal.write("task_failed", task=task.name,
                                        rc=res.returncode)
                results[task.name] = "wedged"
            else:
                # Keep going — bench_capture.sh also runs later phases
                # after a non-wedge failure (each phase's partial output
                # is already kept).
                self._sup.journal.write("task_failed", task=task.name,
                                        rc=res.returncode)
                results[task.name] = "failed"
        return results
