"""Ledger-driven control plane: a journaled multi-run scheduler with
elastic autoscaling and loss-free SLO preemption.

arXiv:1605.08695's system claim is ONE runtime hosting many execution
modes under a single control plane, and TF-Replicator (arXiv:1902.00465)
separates the job description from its placement.  Until round 14 this
repo had every ingredient — gang supervision with a loss-free 143
preemption protocol (resilience/fleet.py), an elastic rank-loss path
nothing exercised as policy, a queryable run ledger (obs/ledger.py) and
bench-family trajectories that predict a job's cost — but no component
turning faults and load into *decisions*.  This module is that
component: a crash-tolerant queue of heterogeneous jobs (train / bench
/ faultline drill / future serving load tests) admitted against
measured cost, packed onto the available device mesh, and supervised
with robustness as policy:

- **admission against measured cost** — a job's step time is predicted
  from its BENCH_trajectory.json family (the newest round's
  ``*steps_per_sec`` metric, conservatively the slowest), falling back
  to the job's declared estimate; the prediction prices the admission
  row and, unless the job pins its own wall timeout, derives the
  fleet's per-attempt deadline (``cost_margin`` x predicted).
- **packing** — jobs take ``ranks`` devices each and launch, priority
  order, whenever they fit the free mesh.  A job wider than the mesh is
  refused at admission, never queued forever.
- **elastic shrink / grow-on-recovery** — each gang runs under the
  existing :class:`~distributedtensorflowexample_tpu.resilience.fleet.
  FleetSupervisor`; a lost host shrinks an ``elastic`` job's gang (the
  PR 5 path, now exercised end-to-end via the ``host_loss`` fault) and
  the scheduler records the shrink, then drives the recovery re-probe:
  when the lost rank answers again and the mesh has room, the job is
  cleanly stopped (TERM→143→snapshot) and relaunched at FULL width.
- **SLO preemption, loss-free** — a higher-priority job that cannot fit
  evicts the least-urgent running job(s) through
  ``FleetSupervisor.request_stop``: the victim's ranks save and exit
  143, the job requeues (preemptions are never charged to its retry
  budget), and its relaunch resumes from the agreed snapshot step with
  zero lost steps — bitwise-identical to an uninterrupted run.
- **bounded retry / quarantine** — crashes and exhausted fleets requeue
  with jittered exponential backoff up to the job's ``retries``; a
  gang that reports the backend wedged (rc 3) is QUARANTINED, never
  requeued — the supervisor protocol's "stop burning the window" rule
  as queue policy.

Every decision lands twice: in the scheduler's own write-ahead journal
(``sched.jsonl`` — the crash-tolerance surface) and as a ``sched_*``
row in the run ledger (``RUNS.jsonl`` — the query surface), so
``tools/obs_query.py why <job>`` answers "why was this job preempted /
shrunk / quarantined" after the fact from ledger rows alone.

Crash tolerance is the PR 12 ``resume_agreement`` pattern: mutating
decisions write an INTENT record before the side effect and an applied
record after, so a scheduler SIGKILLed mid-decision replays its journal
on restart — unmatched terminal intents are re-applied idempotently,
non-terminal jobs requeue, and rank process groups orphaned by the dead
incarnation are swept (their pids are in each job's fleet journal —
``rank_spawn`` rows with no matching ``rank_exit``) before anything
relaunches over their stores.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import sys
import threading
import time

from distributedtensorflowexample_tpu.obs import ledger as obs_ledger
from distributedtensorflowexample_tpu.obs import metrics as obs_metrics
from distributedtensorflowexample_tpu.resilience import (
    remediate as heal_mod)
from distributedtensorflowexample_tpu.resilience.fleet import (
    FleetSupervisor, GangResult, RankLostError)
from distributedtensorflowexample_tpu.resilience.supervisor import (
    RC_PREEMPTED, Journal, RetryPolicy)
from distributedtensorflowexample_tpu.utils.signals import sigterm_flag

# The sched_* ledger-row schema: every decision class the scheduler can
# take, written with src="sched" plus a "job" field (and queue-level
# rows with job=None).  tools/obs_query.py's `why` verb renders exactly
# this set — the reader and this writer must not drift.
# KEEP-IN-SYNC(sched-events) digest=d37469a5064a
SCHED_EVENTS = (
    "sched_submit",       # job registered (kind, priority, ranks, argv)
    "sched_admit",        # admitted: predicted cost + its source
    "sched_refuse",       # refused at admission (unplaceable/over budget)
    "sched_place",        # gang launched onto the mesh (devices, attempt)
    "sched_shrink",       # elastic gang lost a rank and runs narrower
    "sched_grow",         # lost rank recovered; relaunch at full width
    "sched_evict",        # SLO preemption: TERM→143→snapshot, requeued
    "sched_retry",        # crash/exhaustion: requeued with backoff
    "sched_quarantine",   # backend wedged (rc 3): never requeued
    "sched_fail",         # retry budget exhausted
    "sched_done",         # job completed (rc 0 on every rank)
    "sched_orphan_killed",  # restart swept a dead incarnation's gang
    "sched_queue_done",   # queue drained; outcome counts
)
# KEEP-IN-SYNC-END(sched-events)

# The tick-loop sleep seam: sim/clock.py swaps this for a virtual
# sleep that advances the simulated clock and fires due world events,
# so the REAL policy loop below runs unmodified at fleet scale.  All
# in-loop clock reads go through obs_metrics._now/_wall for the same
# reason (the clock-seam lint rule proves no bare read sneaks back in).
_sleep = time.sleep

_DECISIONS = obs_metrics.counter(
    "sched_decisions_total", "scheduler decisions applied, by action")
_QUEUE_DEPTH = obs_metrics.gauge(
    "sched_queue_depth", "queued (not yet terminal, not running) jobs")
_DEVICES_BUSY = obs_metrics.gauge(
    "sched_devices_busy", "mesh devices held by running gangs")

#: States a job never leaves.
TERMINAL = ("done", "failed", "quarantined", "refused")

DEFAULT_TICK_S = 0.25
#: Default SLO priority per job kind — lower runs (and evicts) first.
#: Serving load tests outrank everything (the north star's traffic);
#: drills yield to real work.
DEFAULT_SLO_PRIORITIES = {"serve": 0, "train": 10, "bench": 20,
                          "drill": 30}


def _log(msg: str) -> None:
    print(f"sched: {msg}", file=sys.stderr, flush=True)


def queue_path_default() -> str:
    """``SCHED_QUEUE``: the queue file tools/schedule.py loads when
    ``--queue`` is not passed — empty means the flag is required."""
    return os.environ.get("SCHED_QUEUE", "")


def tick_default() -> float:
    """``SCHED_TICK_S``: the policy-loop cadence (reap, observe,
    evict/grow/admit) — the latency floor on every decision."""
    try:
        return float(os.environ.get("SCHED_TICK_S", ""))
    except ValueError:
        return DEFAULT_TICK_S


def slo_priorities() -> dict[str, int]:
    """Per-kind default priorities, env-overridable:
    ``SCHED_SLO_PRIORITIES=serve=0,bench=5`` updates/extends the
    defaults.  Malformed tokens are skipped loudly — a typo must not
    silently re-rank the queue to the hardcoded table."""
    out = dict(DEFAULT_SLO_PRIORITIES)
    txt = os.environ.get("SCHED_SLO_PRIORITIES", "")
    for token in filter(None, (t.strip() for t in txt.split(","))):
        kind, _, num = token.partition("=")
        try:
            out[kind.strip()] = int(num)
        except ValueError:
            _log(f"SCHED_SLO_PRIORITIES token {token!r} is not "
                 f"kind=int — ignored")
    return out


# --- job description -------------------------------------------------------

@dataclasses.dataclass
class Job:
    """One queue entry — the job DESCRIPTION, placement-free (the
    TF-Replicator separation): what to run, how wide, how urgent, and
    what it is predicted to cost."""

    job: str                       # unique id (also the workdir segment)
    argv: list                     # {rank}/{num_ranks} substituted
    kind: str = "train"            # train | bench | drill | serve | ...
    ranks: int = 1                 # gang width = device demand
    priority: int | None = None    # lower = more urgent; None = by kind
    steps: int | None = None       # work size, for the cost prediction
    family: str = ""               # BENCH_trajectory family for cost
    est_step_time_s: float | None = None   # declared fallback estimate
    retries: int = 1               # scheduler-level requeues (crashes)
    fleet_retries: int = 1         # gang restarts INSIDE one placement
    snapshots: str = ""            # per-rank SnapshotStore template
    state_bytes: int = 0           # snapshot state size — prices the
    #                              # cross-slice migration a multi-slice
    #                              # eviction may force on the victim
    elastic: bool = True           # shrink on rank loss (sync state)
    worker_tiled: bool = False     # async state: shrink is illegal
    wall_timeout_s: float = 0.0    # 0 = derive from predicted cost
    kill_grace_s: float = 10.0     # TERM→KILL grace (covers the save)
    heartbeat_timeout_s: float = 0.0
    start_after_s: float = 0.0     # ready this long after queue start
    after_file: str = ""           # ready once this path exists
    env: dict = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        if (not self.job or os.sep in self.job
                or (os.altsep and os.altsep in self.job)
                or self.job in (".", "..")
                or self.job != self.job.strip()):
            raise ValueError(f"job id {self.job!r} must be a non-empty "
                             f"path-safe token")
        if self.ranks < 1:
            raise ValueError(f"job {self.job}: ranks {self.ranks} "
                             f"must be >= 1")
        if not self.argv:
            raise ValueError(f"job {self.job}: empty argv")
        bad = [t for t in self.argv if not isinstance(t, str)]
        if bad:
            # A natural queue-file mistake ({"argv": [..., "--steps",
            # 12]}) must refuse loudly here, not burn the retry budget
            # on a deterministic AttributeError deep in rank spawn.
            raise ValueError(f"job {self.job}: argv tokens must be "
                             f"strings, got {bad!r}")

    @classmethod
    def from_dict(cls, rec: dict) -> "Job":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = sorted(set(rec) - known)
        if unknown:
            raise ValueError(
                f"job {rec.get('job')!r}: unknown field(s) {unknown} "
                f"(known: {sorted(known)})")
        return cls(**rec)

    def resolved_priority(self, slo: dict[str, int]) -> int:
        if self.priority is not None:
            return self.priority
        return slo.get(self.kind, max(slo.values(), default=99) + 1)


# --- the cost model --------------------------------------------------------

def trajectory_rows(path: str) -> list[dict]:
    """The checked-in BENCH_trajectory.json: one JSON line per bench
    family per round (tools/bench_ratchet.py --trajectory).  Missing or
    torn lines read as no data — cost prediction degrades to declared
    estimates, never raises."""
    rows: list[dict] = []
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except OSError:
        return rows
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and rec.get("family"):
            rows.append(rec)
    return rows


def predict_cost(job: Job, trajectory_path: str = "") -> dict:
    """{"step_time_s", "predicted_s", "source"} — the admission price.

    Measured first: the NEWEST trajectory row whose family contains the
    job's ``family`` string, read at its slowest ``*steps_per_sec``
    metric (admission should be conservative — over-predicting cost
    reserves too much wall budget, under-predicting kills the job at a
    cost-derived deadline it never had a chance to meet).  Declared
    ``est_step_time_s`` is the fallback; no estimate at all prices the
    job as unknown (admitted, but with no derived deadline)."""
    step_time = None
    source = None
    if job.family and trajectory_path:
        rows = [r for r in trajectory_rows(trajectory_path)
                if job.family in str(r.get("family", ""))]
        if rows:
            newest = max(rows, key=lambda r: (r.get("round") is not None,
                                              r.get("round") or -1))
            rates = [v for k, v in (newest.get("metrics") or {}).items()
                     if k.endswith("steps_per_sec")
                     and isinstance(v, (int, float)) and v > 0]
            if rates:
                step_time = 1.0 / min(rates)
                source = f"trajectory:{newest.get('file')}"
    if step_time is None and job.est_step_time_s:
        step_time = float(job.est_step_time_s)
        source = "declared"
    predicted = (round(step_time * job.steps, 3)
                 if step_time and job.steps else None)
    return {"step_time_s": (round(step_time, 6) if step_time else None),
            "predicted_s": predicted, "source": source}


def load_collective_fit(path: str, devices: int) -> dict | None:
    """Read the fitted ``t(S) = alpha + S/beta`` psum line for the
    nearest measured device count out of a BENCH_collectives record
    (``knees.psum.<devices>.{alpha_s, beta_bytes_per_s}``) — the price
    model for moving a victim's snapshot state across slices.  Missing
    or malformed records read as "no fit" (pricing degrades to
    unpriced), never raise."""
    try:
        with open(path) as f:
            rec = json.load(f)
        knees = rec["detail"]["knees"]["psum"]
        fits = {int(k): v for k, v in knees.items()}
        nearest = min(fits, key=lambda d: (abs(d - devices), d))
        fit = fits[nearest]
        return {"alpha_s": float(fit["alpha_s"]),
                "beta_bytes_per_s": float(fit["beta_bytes_per_s"]),
                "fit_devices": nearest, "file": os.path.basename(path)}
    except (OSError, KeyError, TypeError, ValueError,
            json.JSONDecodeError):
        return None


# --- per-job runtime state -------------------------------------------------

@dataclasses.dataclass
class _JobState:
    job: Job
    priority: int
    submit_idx: int
    state: str = "queued"
    width: int = 0                 # devices currently held (0 = none)
    retries_used: int = 0
    preemptions: int = 0
    shrinks: int = 0
    grows: int = 0
    launches: int = 0
    not_before: float = 0.0        # backoff gate (monotonic)
    admitted: bool = False
    cost: dict = dataclasses.field(default_factory=dict)
    ran: bool = False              # a previous placement left snapshots
    slice_name: str = ""           # which mesh slice the gang holds
    fleet: FleetSupervisor | None = None
    thread: threading.Thread | None = None
    result: list = dataclasses.field(default_factory=list)
    stop: tuple | None = None      # (reason, seq, detail) once requested
    why_last: str = ""


class Scheduler:
    """The control plane: one single-threaded policy loop (tick) over
    per-job FleetSupervisor run threads.  See the module docstring for
    the decision rules; see DESIGN.md §21 for the state machine."""

    def __init__(self, jobs: list[Job], devices: int = 4,
                 workdir: str = "/tmp/sched",
                 journal: Journal | None = None,
                 ledger_path: str | None = None,
                 tick_s: float | None = None,
                 poll_s: float = 0.05,
                 seed: int | None = 0,
                 cost_margin: float = 16.0,
                 max_job_s: float = 0.0,
                 trajectory_path: str = "",
                 retry_policy: RetryPolicy | None = None,
                 heal: bool = True,
                 slices: dict[str, int] | None = None,
                 collective_fit: dict | None = None,
                 fleet_factory=None):
        # Multi-slice packing: ``slices`` maps mesh-slice name →
        # device capacity (TF-Replicator's placement separation one
        # level up: a gang holds ONE slice, never spans two).  None =
        # the classic single-mesh mode — one implicit slice named
        # "mesh", every row byte-identical to the pre-slice scheduler.
        if slices is not None:
            if not slices:
                raise ValueError("slices must name at least one slice")
            for name, cap in slices.items():
                if not name or not isinstance(name, str):
                    raise ValueError(f"slice name {name!r} must be a "
                                     f"non-empty string")
                if not isinstance(cap, int) or cap < 1:
                    raise ValueError(f"slice {name}: capacity {cap!r} "
                                     f"must be an int >= 1")
            self.slices = dict(slices)
            devices = sum(self.slices.values())
        else:
            if devices < 1:
                raise ValueError(f"devices {devices} must be >= 1")
            self.slices = {"mesh": devices}
        self._multi = slices is not None
        self.devices = devices
        # The fitted collective model (load_collective_fit) pricing a
        # cross-slice eviction: the victim's snapshot state may have to
        # move slices on relaunch, t(S) = alpha + S/beta per rank.
        self.collective_fit = collective_fit
        # The spawn seam: sim/fleet.py injects a factory returning
        # simulated gangs with the FleetSupervisor run/stop/ranks
        # surface; the DECISION code below stays identical either way.
        self.fleet_factory = fleet_factory or FleetSupervisor
        self.workdir = os.path.abspath(workdir)
        os.makedirs(self.workdir, exist_ok=True)
        self.journal = journal or Journal(
            os.path.join(self.workdir, "sched.jsonl"))
        # None = the workdir default (one RUNS.jsonl holding the whole
        # queue: sched rows + every gang's and rank's own rows); "" =
        # no ledger.  Same convention as the fleet.
        self.ledger_path = (os.path.join(self.workdir, "RUNS.jsonl")
                            if ledger_path is None else ledger_path)
        self.tick_s = tick_default() if tick_s is None else tick_s
        self.poll_s = poll_s
        self.seed = seed
        self.cost_margin = cost_margin
        self.max_job_s = max_job_s
        self.trajectory_path = trajectory_path
        self.retry_policy = retry_policy or RetryPolicy(
            retries=10**6,      # the per-job budget gates, not this
            backoff_base_s=0.25, backoff_max_s=10.0)
        import random as _random
        self._rng = _random.Random(seed)
        self._slo = slo_priorities()
        self._seq = 0
        self._submitted: set[str] = set()
        self._jobs: dict[str, _JobState] = {}
        for i, job in enumerate(jobs):
            if job.job in self._jobs:
                raise ValueError(f"duplicate job id {job.job!r}")
            self._jobs[job.job] = _JobState(
                job=job, priority=job.resolved_priority(self._slo),
                submit_idx=i)
        # ROADMAP direction 5's named rung: anomaly detections feed
        # eviction policy — a straggling job yields its devices to
        # queued healthy work (resilience/remediate.py; flap/cooldown/
        # budget guardrails + HEAL_DRY_RUN apply, and the heal_* rows
        # land in the same ledger the sched_* rows do).  The policy
        # engine shares this scheduler's journal: its WAL replays with
        # ours, and _replay ignores the heal_* rows it doesn't own.
        # Constructed AFTER _jobs: construction replays unmatched
        # heal_intents through _heal_evict, which reads _jobs (every
        # job is still "queued" here, so the replay resolves to the
        # documented idempotent noop, not an AttributeError row).
        self._remediator = heal_mod.Remediator(
            journal=self.journal, ledger_path=self.ledger_path or "",
            actuators={"evict": self._heal_evict},
            policy={"straggler": heal_mod.HealRule("evict")},
        ) if heal else None

    # --- journal + ledger plumbing ----------------------------------------
    def _wal(self, event: str, **fields) -> None:
        self.journal.write(event, **fields)
        die = os.environ.get("SCHED_DRILL_DIE_AT", "")
        if die:
            token = (f"{event}:{fields.get('action', '')}:"
                     f"{fields.get('job', '')}")
            if die in token:
                # The crash drill: die IMMEDIATELY after committing this
                # record — mid-decision, exactly between intent and
                # effect.  SIGKILL, not raise: no atexit, no cleanup,
                # like the real OOM-killer/power-loss shape.
                _log(f"SCHED_DRILL_DIE_AT={die}: dying after {token}")
                os.kill(os.getpid(), signal.SIGKILL)

    def _ledger(self, event: str, **fields) -> None:
        if self.ledger_path:
            obs_ledger.log_event(event, path=self.ledger_path,
                                 src="sched", **fields)

    def _intent(self, action: str, job: str | None, **fields) -> int:
        """Write-ahead half of a mutating decision (the PR 12
        ``resume_agreement`` pattern): the intent commits to the journal
        BEFORE the side effect, so a scheduler death in between leaves
        a record the restarted incarnation replays."""
        self._seq += 1
        self._wal("sched_intent", action=action, job=job, seq=self._seq,
                  **fields)
        return self._seq

    def _applied(self, seq: int | None, action: str, job: str | None,
                 **fields) -> None:
        """Completion half: the journal's applied record (matching the
        intent's seq) plus the ledger's queryable sched_* row."""
        _DECISIONS.labels(action=action).inc()
        self._wal(f"sched_{action}", job=job, seq=seq, **fields)
        self._ledger(f"sched_{action}", job=job, **fields)

    def _observe(self, event: str, job: str | None, **fields) -> None:
        """A decision the WORLD made (shrink; the fleet's own internal
        grow): recorded, not intended — there is no side effect to
        replay."""
        _DECISIONS.labels(action=event.removeprefix("sched_")).inc()
        self._wal(event, job=job, **fields)
        self._ledger(event, job=job, **fields)

    # --- replay (crash tolerance) -----------------------------------------
    def _replay(self) -> None:
        """Fold the journal back into job states: terminal decisions
        stick, retry counters restore, everything else requeues.  An
        INTENT with no applied record is a decision the dead scheduler
        committed to but never finished — terminal ones are re-applied
        here (idempotently), placement/eviction ones need no re-apply
        beyond the orphan sweep (the job requeues and relaunches
        through the normal path)."""
        intents: dict[int, dict] = {}
        for rec in self.journal.events():
            ev = rec.get("event", "")
            if not ev.startswith("sched_"):
                continue
            seq = rec.get("seq")
            if isinstance(seq, int):
                self._seq = max(self._seq, seq)
            if ev == "sched_intent":
                intents[seq] = rec
                continue
            if isinstance(seq, int):
                intents.pop(seq, None)
            if ev == "sched_submit":
                self._submitted.add(rec.get("job") or "")
            st = self._jobs.get(rec.get("job") or "")
            if st is None:
                continue
            if ev == "sched_done":
                st.state = "done"
            elif ev == "sched_quarantine":
                st.state = "quarantined"
            elif ev == "sched_fail":
                st.state = "failed"
            elif ev == "sched_refuse":
                st.state = "refused"
            elif ev == "sched_retry":
                st.retries_used = int(rec.get("retry") or 0)
            elif ev == "sched_evict":
                st.preemptions += 1
            elif ev == "sched_shrink":
                st.shrinks += 1
            elif ev == "sched_grow":
                st.grows += 1
            elif ev == "sched_place":
                # A placed job left snapshots behind: its relaunch must
                # run the resume agreement (agree_first) — and must not
                # reuse the dead placement's stdout dir.
                st.ran = True
                st.launches = max(st.launches,
                                  int(rec.get("attempt") or 0))
        for seq in sorted(intents):
            rec = intents[seq]
            action, job_id = rec.get("action"), rec.get("job")
            st = self._jobs.get(job_id or "")
            if action in ("done", "quarantine", "fail", "refuse") \
                    and st is not None:
                # Terminal decision committed but unapplied: finish it.
                st.state = {"done": "done", "quarantine": "quarantined",
                            "fail": "failed", "refuse": "refused"}[action]
                self._applied(seq, action, job_id, replayed=True,
                              **{k: v for k, v in rec.items()
                                 if k not in ("ts", "event", "action",
                                              "job", "seq")})
            elif action == "retry" and st is not None:
                st.retries_used = max(st.retries_used,
                                      int(rec.get("retry") or 0))
                self._applied(seq, action, job_id, replayed=True,
                              retry=st.retries_used)
            else:
                # place/evict/grow: the gang (victim or launch) died
                # with the scheduler; the orphan sweep below clears the
                # mesh and the job relaunches through the normal path.
                if action == "place" and st is not None:
                    # The spawn may have happened before the death —
                    # treat the placement as real (resume + fresh
                    # stdout dir), same as an applied place row.
                    st.ran = True
                    st.launches = max(st.launches,
                                      int(rec.get("attempt") or 0))
                self._applied(seq, "intent_dropped", job_id,
                              replayed=True, dropped=action)
        # Sweep gangs orphaned by the dead incarnation BEFORE anything
        # relaunches over their snapshot stores.
        for st in self._jobs.values():
            if st.state not in TERMINAL:
                self._sweep_orphans(st.job.job)

    def _job_dir(self, job_id: str) -> str:
        return os.path.join(self.workdir, "jobs", job_id)

    def _sweep_orphans(self, job_id: str) -> None:
        """Kill rank process groups a DEAD scheduler incarnation left
        running: every ``rank_spawn`` pid in the job's fleet journal
        with no matching ``rank_exit`` may still be alive (ranks live in
        their own sessions — they survive their supervisor).  Two gangs
        of one job writing the same store concurrently is the
        corruption this sweep exists to prevent.  Pid-reuse is the
        accepted residual risk: these pids come from THIS queue's own
        journal, and a vanished pid is simply skipped."""
        jp = os.path.join(self._job_dir(job_id), "fleet.jsonl")
        if not os.path.exists(jp):
            return
        spawned: dict[tuple, int] = {}
        intents: set[tuple] = set()
        for rec in Journal(jp).events():
            key = (rec.get("task"), rec.get("attempt"), rec.get("rank"))
            if rec.get("event") == "rank_spawn_intent":
                intents.add(key)
            elif rec.get("event") == "rank_spawn":
                spawned[key] = rec.get("pid")
                intents.discard(key)
            elif rec.get("event") == "rank_exit":
                spawned.pop(key, None)
                intents.discard(key)
            elif rec.get("event") == "rank_lost":
                # Popen itself raised (the genuine dead-host path): no
                # process ever existed, so the dangling intent must not
                # read as a maybe-orphan forever after.
                intents.discard(key)
        for key in sorted(intents, key=str):
            # Spawn intent with no pid row: the dead incarnation was
            # killed inside the spawn itself — an orphan MAY exist that
            # this sweep cannot address.  Loud, not silent.
            _log(f"{job_id}: spawn intent {key} has no recorded pid — "
                 f"an unswept orphan may exist; check `ps` before "
                 f"trusting this job's store")
        # TERM every orphan group first, then ONE shared grace window,
        # then KILL the stragglers — the fleet teardown's shape ("N
        # ranks pay one grace, not N"): a multi-gang sweep must not
        # serialize 5 s of grace per pid into a minute of startup.
        live: list[tuple[tuple, int]] = []
        for (task, attempt, rank), pid in sorted(spawned.items()):
            if not isinstance(pid, int):
                continue
            try:
                os.killpg(pid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                continue
            live.append(((task, attempt, rank), pid))
            self._observe("sched_orphan_killed", job_id, rank=rank,
                          attempt=attempt, pid=pid)
            _log(f"{job_id}: swept orphaned rank {rank} group (pid "
                 f"{pid}) from a dead scheduler incarnation")
        # TERM first (lets a live trainer save); escalate after the
        # shared grace — the relaunch must not race a dying writer.
        deadline = obs_metrics._now() + 5.0
        while live and obs_metrics._now() < deadline:
            still = []
            for key, pid in live:
                try:
                    os.killpg(pid, 0)
                    still.append((key, pid))
                except ProcessLookupError:
                    continue
            live = still
            if live:
                _sleep(0.05)
        for _, pid in live:
            try:
                os.killpg(pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass

    # --- admission + placement --------------------------------------------
    def _admit(self, st: _JobState) -> bool:
        """First time a job comes up for placement: price it (measured
        trajectory first, declared estimate second) and either admit —
        the sched_admit row carries the prediction — or refuse
        (unplaceable width / over the per-job cost ceiling)."""
        job = st.job
        cost = predict_cost(job, self.trajectory_path)
        widest = max(self.slices.values())
        if job.ranks > widest:
            seq = self._intent("refuse", job.job)
            st.state = "refused"
            if self._multi:
                # A gang holds ONE slice: wider than the widest slice
                # is unplaceable even with the whole fleet idle.
                st.why_last = (f"needs {job.ranks} device(s), widest "
                               f"slice has {widest} "
                               f"(slices: {self.slices})")
                self._applied(seq, "refuse", job.job, why=st.why_last,
                              ranks=job.ranks, devices=self.devices,
                              slices=dict(self.slices))
            else:
                st.why_last = (f"needs {job.ranks} device(s), mesh has "
                               f"{self.devices}")
                self._applied(seq, "refuse", job.job, why=st.why_last,
                              ranks=job.ranks, devices=self.devices)
            return False
        if self.max_job_s and cost["predicted_s"] \
                and cost["predicted_s"] > self.max_job_s:
            seq = self._intent("refuse", job.job)
            st.state = "refused"
            st.why_last = (f"predicted {cost['predicted_s']:g}s "
                           f"({cost['source']}) exceeds the per-job "
                           f"ceiling {self.max_job_s:g}s")
            self._applied(seq, "refuse", job.job, why=st.why_last,
                          **cost)
            return False
        st.admitted = True
        st.cost = cost
        self._applied(None, "admit", job.job, priority=st.priority,
                      ranks=job.ranks, **cost)
        return True

    def _wall_timeout(self, st: _JobState) -> float:
        if st.job.wall_timeout_s:
            return st.job.wall_timeout_s
        if st.cost.get("predicted_s"):
            return self.cost_margin * st.cost["predicted_s"]
        return 0.0

    def _launch(self, st: _JobState, free: int,
                slice_name: str = "mesh") -> None:
        job = st.job
        st.launches += 1
        seq = self._intent("place", job.job, ranks=job.ranks,
                           attempt=st.launches,
                           **({"slice": slice_name} if self._multi
                              else {}))
        jdir = self._job_dir(job.job)
        fleet = self.fleet_factory(
            job.ranks,
            policy=RetryPolicy(retries=job.fleet_retries,
                               backoff_base_s=0.05, backoff_max_s=0.5),
            journal=Journal(os.path.join(jdir, "fleet.jsonl")),
            heartbeat_timeout_s=job.heartbeat_timeout_s,
            wall_timeout_s=self._wall_timeout(st),
            kill_grace_s=job.kill_grace_s,
            poll_s=self.poll_s,
            seed=self.seed,
            elastic=job.elastic,
            worker_tiled=job.worker_tiled,
            workdir=os.path.join(jdir, "fleet"),
            ledger_path=self.ledger_path or "",
            # The fleet must not regrow itself mid-placement: a
            # recovered rank consumes a mesh device the scheduler may
            # have backfilled — only _drive_grow's capacity-gated
            # stop-and-relaunch may widen the gang.
            reprobe_on_relaunch=False)
        st.fleet = fleet
        st.state = "running"
        st.width = job.ranks
        st.slice_name = slice_name
        st.stop = None
        st.result = []
        resumed = st.ran

        def _run():
            try:
                st.result.append(fleet.run(
                    list(job.argv), name=job.job,
                    snapshot_dir_template=job.snapshots,
                    # per-placement stdout: a relaunch restarts the
                    # fleet's attempt numbering at 0, and the resumed
                    # run must not clobber the evicted placement's
                    # JSON tail (both are evidence).
                    stdout_dir=os.path.join(jdir, "out",
                                            f"place{st.launches}"),
                    env_extra=dict(job.env) or None,
                    # A relaunch resumes over stores a PREVIOUS fleet
                    # wrote; the agreement must run before the first
                    # gang too, or each rank restores its own newest.
                    agree_first=resumed))
            except BaseException as e:       # noqa: BLE001 — reap sorts it
                st.result.append(e)

        st.thread = threading.Thread(target=_run, daemon=True,
                                     name=f"sched-{job.job}")
        st.thread.start()
        self._applied(seq, "place", job.job, ranks=job.ranks,
                      attempt=st.launches, resumed=resumed,
                      free_before=free, devices=self.devices,
                      wall_timeout_s=round(self._wall_timeout(st), 3)
                      or None,
                      **({"slice": slice_name} if self._multi else {}),
                      **st.cost)
        where = (f"slice {slice_name}" if self._multi
                 else f"{job.ranks}/{self.devices} device(s)")
        _log(f"{job.job}: placed on {where} "
             f"(attempt {st.launches}"
             + (f", resuming" if resumed else "") + ")")

    # --- the policy tick ---------------------------------------------------
    def _running(self) -> list[_JobState]:
        return [s for s in self._jobs.values() if s.state == "running"]

    def _free(self) -> int:
        return self.devices - sum(s.width for s in self._running())

    def _slice_free(self) -> dict[str, int]:
        """Free devices per slice (single-mesh mode: one entry)."""
        free = dict(self.slices)
        for s in self._running():
            if s.slice_name in free:
                free[s.slice_name] -= s.width
        return free

    def _pick_slice(self, ranks: int, frees: dict[str, int]) -> str | None:
        """Best-fit packing: the slice with the LEAST free capacity
        that still fits ``ranks`` — wide future jobs keep a wide slice
        open instead of every slice fragmenting a little.  Name-sorted
        tie-break keeps placement deterministic."""
        fits = [(free, name) for name, free in sorted(frees.items())
                if free >= ranks]
        return min(fits)[1] if fits else None

    def _migrate_price_s(self, st: _JobState) -> float | None:
        """What evicting ``st`` may cost in collective-model time: its
        per-rank snapshot state crossing slices on relaunch, priced at
        the fitted ``t(S) = alpha + S/beta`` per rank.  None = unpriced
        (no fit, or the job declares no state)."""
        fit = self.collective_fit
        if not fit or not st.job.state_bytes:
            return None
        t = (fit["alpha_s"]
             + st.job.state_bytes / fit["beta_bytes_per_s"])
        return round(t * max(1, st.width or st.job.ranks), 6)

    def _reap(self) -> None:
        for st in self._running():
            if st.thread is None or st.thread.is_alive():
                continue
            st.thread.join()
            res = st.result[-1] if st.result else None
            stop = st.stop
            st.thread = None
            st.fleet = None
            st.ran = True
            if isinstance(res, GangResult):
                self._classify(st, res, stop)
                continue
            if stop is not None and stop[1] is not None:
                # The gang died of its own cause (exception) while a
                # stop was pending: the stop decision is moot, but its
                # intent must still resolve or the WAL never balances.
                self._wal("sched_stop_superseded", job=st.job.job,
                          seq=stop[1], reason=stop[0],
                          outcome="exception")
            if isinstance(res, RankLostError):
                # Non-elastic (or worker-tiled) job on a dead host:
                # retrying is still meaningful — the host may answer
                # again within the backoff — but it is budgeted.
                self._retry_or_fail(st, f"rank {res.rank} lost: "
                                        f"{res.cause}")
            else:
                self._retry_or_fail(st, f"fleet thread died: {res!r}")

    def _classify(self, st: _JobState, res: GangResult,
                  stop: tuple | None) -> None:
        job = st.job
        rcs = {str(r): rc for r, rc in sorted(res.last_rcs.items())}
        clean = bool(res.last_rcs) and all(
            rc in (0, RC_PREEMPTED) for rc in res.last_rcs.values())
        if stop is not None and res.status != "evicted" \
                and stop[1] is not None:
            # A stop was requested but the gang ended on its own terms
            # first (finished, crashed, wedged) — the decision is moot;
            # resolve its intent so the WAL balances.
            self._wal("sched_stop_superseded", job=job.job, seq=stop[1],
                      reason=stop[0], outcome=res.status)
        if res.status == "ok":
            seq = self._intent("done", job.job)
            st.state = "done"
            st.width = 0
            st.why_last = ""        # a retried-then-done job is done
            self._applied(seq, "done", job.job, rcs=rcs,
                          gang_attempts=res.gang_attempts,
                          restarts=res.restarts,
                          preempt_resumes=st.preemptions,
                          ranks=res.ranks)
            _log(f"{job.job}: done (gang_attempts={res.gang_attempts}, "
                 f"restarts={res.restarts})")
            return
        if res.status == "evicted" and stop is not None:
            reason, seq, detail = stop
            st.width = 0
            st.state = "queued"
            st.not_before = 0.0
            if reason == "grow":
                st.grows += 1
                self._applied(seq, "grow", job.job, recovered=detail,
                              rcs=rcs, clean=clean)
                _log(f"{job.job}: stopped cleanly to grow back to "
                     f"{job.ranks} rank(s) (recovered {detail})")
            elif reason == "evicted":
                st.preemptions += 1
                for_job, why = detail
                extra = ({"slice": st.slice_name} if self._multi
                         else {})
                price = (self._migrate_price_s(st) if self._multi
                         else None)
                if price is not None:
                    extra["price_s"] = price
                self._applied(seq, "evict", job.job, for_job=for_job,
                              why=why, rcs=rcs, clean=clean, **extra)
                _log(f"{job.job}: evicted ({why}); requeued — "
                     f"preemptions are not charged to the retry budget")
            # scheduler_terminated: queued for the next incarnation,
            # no decision row — the shutdown is the decision.
            return
        if res.status in ("evicted", "terminated"):
            # The scheduler itself is going down (SIGTERM) — leave the
            # job queued for the next incarnation; no decision row.
            st.width = 0
            st.state = "queued"
            return
        if res.status == "wedged":
            seq = self._intent("quarantine", job.job)
            st.state = "quarantined"
            st.width = 0
            st.why_last = ("a rank reported the backend provably "
                           "wedged (rc 3) — requeueing would burn the "
                           "window against a dead tunnel")
            self._applied(seq, "quarantine", job.job, rcs=rcs,
                          why=st.why_last)
            _log(f"{job.job}: QUARANTINED (rc 3)")
            return
        # exhausted (or any unknown outcome): budgeted retry.
        self._retry_or_fail(
            st, f"gang {res.status} after {res.gang_attempts} "
                f"attempt(s) (rcs {rcs})")

    def _retry_or_fail(self, st: _JobState, why: str) -> None:
        job = st.job
        st.width = 0
        st.retries_used += 1
        st.why_last = why
        if st.retries_used > job.retries:
            seq = self._intent("fail", job.job)
            st.state = "failed"
            self._applied(seq, "fail", job.job, why=why,
                          retries=st.retries_used - 1)
            _log(f"{job.job}: FAILED ({why}); retry budget "
                 f"{job.retries} exhausted")
            return
        delay = self.retry_policy.delay_s(st.retries_used - 1,
                                          self._rng.random())
        st.state = "queued"
        st.not_before = obs_metrics._now() + delay
        seq = self._intent("retry", job.job, retry=st.retries_used)
        self._applied(seq, "retry", job.job, retry=st.retries_used,
                      of=job.retries, backoff_s=round(delay, 3), why=why)
        _log(f"{job.job}: retry {st.retries_used}/{job.retries} in "
             f"{delay:.2f}s ({why})")

    def _observe_running(self) -> None:
        """Width observations: an elastic gang that shrank (rank lost
        mid-placement) or grew back through the fleet's OWN re-probe
        changes the mesh occupancy the packer plans against — and both
        are ledger rows, because 'why is this job half-width' must be
        answerable later."""
        for st in self._running():
            fleet = st.fleet
            if fleet is None:
                continue
            cur = len(fleet.ranks)
            if cur < st.width:
                st.shrinks += 1
                self._observe("sched_shrink", st.job.job, ranks=cur,
                              was=st.width, lost=fleet.lost_ranks)
                _log(f"{st.job.job}: elastic shrink to {cur} rank(s) "
                     f"(lost {fleet.lost_ranks})")
                st.width = cur
            elif cur > st.width and st.width:
                st.grows += 1
                self._observe("sched_grow", st.job.job, ranks=cur,
                              was=st.width, internal=True)
                st.width = cur

    def _drive_grow(self) -> None:
        """Grow-on-recovery as scheduler policy: a running-shrunken
        elastic job whose lost rank answers the recovery probe is
        cleanly stopped (TERM→143→snapshot) and requeued, so its next
        placement relaunches at FULL width — gated on the mesh having
        room for the regrown gang."""
        # Count every job with a PENDING grow-stop at its full relaunch
        # width, not its current width: the reservation must survive
        # across ticks while the stopped gang drains, or a second
        # shrunken job recovering one tick later double-books the same
        # devices — giving up its working gang for capacity that was
        # never there.  (Multi-slice: the relaunch may land on ANY
        # slice, so the gate is "some slice fits the full width once
        # this gang's devices return", with the pending reservations
        # held against it conservatively.)
        frees = self._slice_free()
        reserved = sum(
            s.job.ranks - s.width for s in self._running()
            if s.stop is not None and s.stop[0] == "grow")
        for st in self._running():
            fleet = st.fleet
            if (fleet is None or st.stop is not None
                    or not st.job.elastic or not fleet.lost_ranks):
                continue
            recovered = fleet.probe_lost_ranks(list(st.job.argv))
            if not recovered:
                continue
            roomiest = max(
                frees.get(name, 0)
                + (st.width if name == st.slice_name else 0)
                for name in self.slices)
            if roomiest - reserved < st.job.ranks:
                continue        # no room for the regrown width yet
            reserved += st.job.ranks - st.width
            seq = self._intent("grow", st.job.job, recovered=recovered)
            st.stop = ("grow", seq, recovered)
            fleet.request_stop("grow")

    def _drive_heal(self) -> None:
        """Anomaly-driven eviction policy: each tick, a running job
        whose monitor pass has NAMED a straggler (lag + slowness
        evidence, never lag alone — obs/anomaly.detect_skew's bar)
        feeds the remediation engine; after the flap/cooldown
        guardrails clear, the job is evicted loss-free (TERM→143→
        snapshot→requeue) so its devices go to queued healthy work and
        its own relaunch sheds the transient slowdown.  Detection-only
        when nothing is queued — evicting a straggler with no
        beneficiary buys nothing but churn (the actuator answers
        ``noop`` and no budget is spent)."""
        if self._remediator is None:
            return
        waiting = [s for s in self._jobs.values() if s.state == "queued"]
        for st in self._running():
            fleet = st.fleet
            if fleet is None or st.stop is not None:
                continue
            for r in fleet.stragglers:
                # Keyed per PLACEMENT (launches): a second straggler
                # episode of the same (job, rank) after an eviction +
                # relaunch is a fresh anomaly and gets its own
                # heal_detect row; within one placement, re-observed
                # polls dedup as one detection.  The guardrail key
                # (kind, job) is launch-free, so cooldown still spans
                # relaunches — no evict storm.
                self._remediator.observe(heal_mod.AnomalyEvent(
                    kind="straggler",
                    key=f"{st.job.job}:l{st.launches}:straggler:rank{r}",
                    scope=st.job.job, rank=r, source="fleet",
                    detail={"waiting": [w.job.job for w in waiting]}))

    def _heal_evict(self, ev: heal_mod.AnomalyEvent) -> dict:
        """The straggler-eviction actuator: routed through the normal
        sched WAL (intent → request_stop → the reap's sched_evict row),
        so the eviction story reads identically to an SLO preemption —
        plus the heal_* rows naming the anomaly that caused it."""
        st = self._jobs.get(ev.scope or "")
        if st is None or st.state != "running" or st.fleet is None \
                or st.stop is not None:
            return {"noop": "job not running (or a stop is already "
                            "pending)"}
        waiting = sorted(
            (s for s in self._jobs.values() if s.state == "queued"),
            key=lambda s: (s.priority, s.submit_idx))
        if not waiting:
            return {"noop": "no queued job waiting for capacity"}
        # The eviction must have a beneficiary that can actually PLACE
        # in what it frees (plus what is already free) — evicting a
        # straggler for a head job still too wide to fit is pure
        # evict-relaunch churn, burning the action budget and the
        # victim's wall time with zero queued work served.  Multi-
        # slice: the beneficiary may land on the victim's slice (its
        # free + the victim's width) or any other slice's own free.
        frees = self._slice_free()
        fits = max(frees.get(st.slice_name, 0) + st.width,
                   max(frees.values()))
        head = next((w for w in waiting if w.job.ranks <= fits), None)
        if head is None:
            return {"noop": f"no queued job fits the {fits} device(s) "
                            f"this eviction would make available"}
        stragglers = st.fleet.stragglers
        why = (f"rank(s) {stragglers} named straggler by the anomaly "
               f"monitor — yielding {st.width} device(s) to queued job "
               f"`{head.job.job}` (anomaly-driven heal policy)")
        seq = self._intent("evict", st.job.job, for_job=head.job.job,
                           heal=True)
        st.stop = ("evicted", seq, (head.job.job, why))
        st.fleet.request_stop("heal_evict")
        _log(f"{st.job.job}: requesting clean stop — {why}")
        return {"for_job": head.job.job, "stragglers": stragglers}

    def _evict_plan(self, head: _JobState, slice_name: str,
                    free: int) -> tuple | None:
        """One slice's eviction plan for ``head``: the strictly-less-
        urgent victims (least urgent first, youngest first among
        equals) whose widths cover the shortfall, plus the plan's
        cross-slice migration price (sum of the victims' fitted
        collective-model costs; unpriced victims count separately so a
        zero price is never confused with an unknown one).  None = the
        slice cannot be cleared for ``head`` at all."""
        need = head.job.ranks - free
        victims = sorted(
            (s for s in self._running()
             if s.stop is None and s.priority > head.priority
             and s.slice_name == slice_name),
            key=lambda s: (-s.priority, -s.submit_idx))
        chosen: list[_JobState] = []
        for v in victims:
            if need <= 0:
                break
            chosen.append(v)
            need -= v.width
        if need > 0:
            return None
        prices = [self._migrate_price_s(v) for v in chosen]
        priced = round(sum(p for p in prices if p), 6)
        unpriced = sum(1 for p in prices if p is None)
        return (priced, unpriced, len(chosen), slice_name, chosen)

    def _evict_for(self, head: _JobState,
                   frees: dict[str, int]) -> bool:
        """SLO preemption: free enough devices for ``head`` by cleanly
        stopping strictly-less-urgent running jobs in ONE slice —
        cheapest clearable slice first, priced by the fitted collective
        model (a victim with snapshot state pays its possible
        cross-slice move).  Returns whether enough capacity is (or will
        shortly be) freed."""
        plans = [p for p in (
            self._evict_plan(head, name, frees[name])
            for name in sorted(self.slices)
            if self.slices[name] >= head.job.ranks) if p is not None]
        if not plans:
            return False
        priced, unpriced, nvict, slice_name, chosen = min(
            plans, key=lambda p: p[:4])
        free = frees[slice_name]
        for v in chosen:
            why = (f"evicted for higher-priority job `{head.job.job}` "
                   f"(priority {head.priority} {head.job.kind} vs "
                   f"{v.priority} {v.job.kind}; it needs "
                   f"{head.job.ranks} device(s), {free} free"
                   + (f" in slice {slice_name}" if self._multi else "")
                   + ")")
            extra = {}
            if self._multi:
                extra["slice"] = slice_name
                price = self._migrate_price_s(v)
                if price is not None:
                    extra["price_s"] = price
            seq = self._intent("evict", v.job.job,
                               for_job=head.job.job, **extra)
            v.stop = ("evicted", seq, (head.job.job, why))
            v.fleet.request_stop("evicted")
            _log(f"{v.job.job}: requesting clean stop — {why}")
        return True

    def _tick(self, t0: float) -> None:
        self._reap()
        self._observe_running()
        self._drive_grow()
        self._drive_heal()
        now = obs_metrics._now()
        frees = self._slice_free()
        _DEVICES_BUSY.set(self.devices - sum(frees.values()))
        ready = [s for s in self._jobs.values()
                 if s.state == "queued" and now >= s.not_before
                 and now - t0 >= s.job.start_after_s
                 and (not s.job.after_file
                      or os.path.exists(s.job.after_file))]
        _QUEUE_DEPTH.set(len([s for s in self._jobs.values()
                              if s.state == "queued"]))
        ready.sort(key=lambda s: (s.priority, s.submit_idx))
        evicting = any(s.stop is not None for s in self._running())
        for st in ready:
            if not st.admitted and not self._admit(st):
                continue
            slice_name = self._pick_slice(st.job.ranks, frees)
            if slice_name is not None:
                self._launch(st, frees[slice_name], slice_name)
                frees[slice_name] -= st.job.ranks
            else:
                if not evicting:
                    self._evict_for(st, frees)
                # Head-of-priority capacity blocking: once the most
                # urgent ready job cannot be placed, nothing less
                # urgent may admit this tick.  Backfilling a just-freed
                # device with a lower-priority job is a LIVELOCK when
                # that job is the eviction's own victim: requeued →
                # backfilled → evicted again, forever (observed in the
                # first demo run — victims reap on different ticks, so
                # the waiting job sees partial capacity while its
                # victims relaunch into the rest).
                break

    def _fail_dead_gates(self) -> None:
        """Liveness backstop: when nothing is running, every remaining
        queued job waits on an ``after_file`` that does not exist, and
        no other job is left to produce it, the queue would tick
        forever — fail the gated jobs with a why instead of spinning.
        Time-bound gates (backoff, start_after_s) resolve on their own
        and never trip this."""
        queued = [s for s in self._jobs.values() if s.state == "queued"]
        if not queued or self._running():
            return
        if any(not s.job.after_file or os.path.exists(s.job.after_file)
               for s in queued):
            return
        for st in queued:
            seq = self._intent("fail", st.job.job)
            st.state = "failed"
            st.why_last = (
                f"after_file gate {st.job.after_file!r} can no longer "
                f"be satisfied: nothing is running and every other job "
                f"is terminal — the queue would wait forever")
            self._applied(seq, "fail", st.job.job, why=st.why_last,
                          retries=st.retries_used)
            _log(f"{st.job.job}: FAILED — {st.why_last}")

    # --- the queue loop ----------------------------------------------------
    def run(self) -> dict:
        """Drive the queue to quiescence: every job in a terminal state
        (done / failed / quarantined / refused).  Returns the summary
        dict tools/schedule.py renders and records.  SIGTERM stops the
        scheduler cleanly: running gangs are evicted (they save), queued
        jobs stay queued, and a rerun of the same command resumes from
        the journal."""
        t0 = obs_metrics._now()
        self._replay()
        for st in sorted(self._jobs.values(), key=lambda s: s.submit_idx):
            if st.job.job not in self._submitted:
                self._wal("sched_submit", job=st.job.job,
                          kind=st.job.kind, priority=st.priority,
                          ranks=st.job.ranks, argv=list(st.job.argv),
                          retries=st.job.retries)
                self._ledger("sched_submit", job=st.job.job,
                             kind=st.job.kind, priority=st.priority,
                             ranks=st.job.ranks, retries=st.job.retries)
                self._submitted.add(st.job.job)
        status = "ok"
        with sigterm_flag() as term:
            while any(s.state not in TERMINAL
                      for s in self._jobs.values()):
                if term:
                    status = "terminated"
                    self._shutdown()
                    break
                self._tick(t0)
                self._fail_dead_gates()
                _sleep(self.tick_s)
            else:
                self._reap()
        return self._summary(status, obs_metrics._now() - t0)

    def _shutdown(self) -> None:
        for st in self._running():
            if st.fleet is not None:
                st.stop = ("scheduler_terminated", None, None)
                st.fleet.request_stop("scheduler_terminated")
        deadline = obs_metrics._now() + 30.0
        while self._running() and obs_metrics._now() < deadline:
            self._reap()
            _sleep(self.poll_s)
        _log("terminated — running gangs stopped cleanly; rerun the "
             "same command to resume the queue from the journal")

    def _summary(self, status: str, makespan_s: float) -> dict:
        states = {jid: st.state for jid, st in self._jobs.items()}
        counts = {s: sum(1 for v in states.values() if v == s)
                  for s in TERMINAL + ("queued", "running")}
        evictions = sum(st.preemptions for st in self._jobs.values())
        shrinks = sum(st.shrinks for st in self._jobs.values())
        grows = sum(st.grows for st in self._jobs.values())
        retries = sum(st.retries_used for st in self._jobs.values())
        if status == "ok" and (counts["failed"] or counts["quarantined"]):
            status = "degraded"
        summary = {
            "status": status, "jobs": states, "counts": counts,
            "devices": self.devices,
            **({"slices": dict(self.slices)} if self._multi else {}),
            "makespan_s": round(makespan_s, 3),
            "evictions": evictions, "shrinks": shrinks, "grows": grows,
            "retries": retries,
            "why": {jid: st.why_last for jid, st in self._jobs.items()
                    if st.why_last}}
        if status != "terminated":
            self._wal("sched_queue_done", status=status, **{
                k: summary[k] for k in ("counts", "makespan_s",
                                        "evictions", "shrinks", "grows",
                                        "retries")})
            self._ledger("sched_queue_done", job=None, status=status,
                         jobs=states, **{
                             k: summary[k]
                             for k in ("counts", "makespan_s",
                                       "evictions", "shrinks", "grows",
                                       "retries")})
        return summary


def load_queue(path: str) -> list[Job]:
    """Parse a queue file: either ``{"jobs": [...]}`` or a bare JSON
    list of job dicts (see :class:`Job` for the fields)."""
    with open(path) as f:
        payload = json.load(f)
    if isinstance(payload, dict):
        payload = payload.get("jobs", [])
    if not isinstance(payload, list):
        raise ValueError(f"queue file {path}: expected a list of jobs "
                         f"(or {{'jobs': [...]}})")
    return [Job.from_dict(rec) for rec in payload]
