"""Crash-consistent snapshots: atomic payload + manifest-last commit.

Orbax (training/checkpoint.py) remains the trainer-surface manager; this
store is the RECOVERY format the supervisor trusts after an unclean
death, built so every failure mode of the write path is detectable:

- payload first: the full state pytree (``saveable_state_dict`` — the
  same field set Orbax saves) as one ``.npz`` blob, written to a tmp
  file, ``fsync``ed, then ``os.replace``d into place (atomic on POSIX);
- manifest last: a small JSON carrying step, payload byte size, crc32,
  leaf count, the dataset cursor (seed + step — a ``DeviceDataset``
  rebuilt with that ``start_step`` replays the identical batch order),
  and caller metadata.  A manifest only exists once its payload rename
  committed, and validation re-checks size+crc, so a write torn ANYWHERE
  (mid-payload, mid-rename, post-hoc truncation) is detected and that
  snapshot discarded in favor of the previous valid one — never
  restored.

Resume is bitwise: params, optimizer state, BN stats and the RNG key
round-trip exactly (npz preserves dtype+bits), and the manifest cursor
lines the data pipeline up with the restored global step — the same
parity discipline the dequant and remat work established, verified in
tests/test_resilience.py.
"""

from __future__ import annotations

import io
import json
import os
import re
import sys
import zlib

import jax
import numpy as np

from distributedtensorflowexample_tpu.obs import metrics as obs_metrics
from distributedtensorflowexample_tpu.obs.trace import span
from distributedtensorflowexample_tpu.training.checkpoint import (
    saveable_state_dict)
from distributedtensorflowexample_tpu.training.hooks import Hook, _EveryN
from distributedtensorflowexample_tpu.training.state import TrainState

MANIFEST_VERSION = 1
_PAYLOAD_RE = re.compile(r"^snap_(\d{8})\.npz$")

_SAVES = obs_metrics.counter(
    "snapshot_saves_total", "committed snapshot writes (payload+manifest)")
# The round-6 ROADMAP names this metric verbatim: a failed save (disk
# full) is logged + counted, never fatal — hence no _total suffix.
_SAVE_FAILURES = obs_metrics.counter(
    "snapshot_save_failures", "snapshot writes refused by the OS "
    "(disk full et al.) that the run survived")
_RESTORES = obs_metrics.counter(
    "snapshot_restores_total", "successful restores from a snapshot")
_FALLBACKS = obs_metrics.counter(
    "snapshot_fallbacks_total",
    "invalid (torn/corrupt) snapshots discarded in favor of an older one")


def _log(msg: str) -> None:
    # stderr: tools with a JSON-lines stdout protocol (bench, faultline)
    # must never see prose on fd 1.
    print(f"snapshot: {msg}", file=sys.stderr, flush=True)


class SnapshotStore:
    """Keep-N rotating store of crash-consistent state snapshots."""

    def __init__(self, directory: str, keep: int = 3):
        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self._keep = keep

    # --- paths -----------------------------------------------------------
    def _payload_path(self, step: int) -> str:
        return os.path.join(self._dir, f"snap_{step:08d}.npz")

    def _manifest_path(self, step: int) -> str:
        return os.path.join(self._dir, f"snap_{step:08d}.json")

    def steps(self) -> list[int]:
        """Steps with a committed payload file, ascending (a payload may
        still fail validation — see :meth:`latest_valid`)."""
        out = []
        for name in os.listdir(self._dir):
            m = _PAYLOAD_RE.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    # --- write -----------------------------------------------------------
    def _atomic_write(self, path: str, data: bytes) -> None:
        # Kept as a method (the fault tests' monkeypatch seam for
        # disk-full injection); the mechanism is the shared obs one.
        from distributedtensorflowexample_tpu.obs.recorder import (
            atomic_write)
        atomic_write(path, data)

    def save(self, state: TrainState, cursor: dict | None = None,
             meta: dict | None = None, force: bool = False) -> bool:
        """Write one snapshot; returns False if ``step`` already has a
        committed manifest (periodic + final hooks overlap, like the
        Orbax manager's duplicate-step no-op) unless ``force``."""
        step = int(state.step)
        if not force and os.path.exists(self._manifest_path(step)):
            if self.validate(step)[0]:
                return False
            # An INVALID snapshot at this step (torn payload behind an
            # intact manifest) must not dedupe away its own repair: the
            # redo of the lost step is exactly what heals it.
            _log(f"re-writing invalid snapshot {step}")
        saveable = saveable_state_dict(state)
        leaves = [np.asarray(x) for x in jax.tree.leaves(saveable)]
        buf = io.BytesIO()
        # Zero-padded index keys: np.load returns files in archive order,
        # but the restore sorts by key so the leaf order is structural,
        # not an artifact of zip internals.
        np.savez(buf, **{f"leaf_{i:05d}": a for i, a in enumerate(leaves)})
        payload = buf.getvalue()
        self._atomic_write(self._payload_path(step), payload)
        manifest = {
            "version": MANIFEST_VERSION,
            "step": step,
            "nbytes": len(payload),
            "crc32": zlib.crc32(payload),
            "leaves": len(leaves),
            "cursor": cursor,
            "meta": meta,
        }
        self._atomic_write(self._manifest_path(step),
                           json.dumps(manifest).encode())
        _SAVES.inc()
        self._prune()
        return True

    def _prune(self) -> None:
        for step in self.steps()[:-self._keep] if self._keep else []:
            for p in (self._payload_path(step), self._manifest_path(step)):
                try:
                    os.remove(p)
                except OSError:
                    pass

    # --- validate / read -------------------------------------------------
    def manifest(self, step: int) -> dict | None:
        try:
            with open(self._manifest_path(step)) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return None

    def _checked_payload(self, step: int) -> tuple[bytes | None, str]:
        """One read serving both validation and restore: the payload
        bytes iff the manifest parses AND size+crc32 match what it
        committed, else (None, why)."""
        man = self.manifest(step)
        if man is None:
            return None, "manifest missing or unreadable"
        try:
            with open(self._payload_path(step), "rb") as f:
                payload = f.read()
        except OSError:
            return None, "payload missing"
        if len(payload) != man.get("nbytes"):
            return None, (f"payload torn: {len(payload)} bytes on disk, "
                          f"manifest committed {man.get('nbytes')}")
        if zlib.crc32(payload) != man.get("crc32"):
            return None, "payload corrupt: crc32 mismatch"
        return payload, "ok"

    def validate(self, step: int) -> tuple[bool, str]:
        payload, why = self._checked_payload(step)
        return payload is not None, why

    def latest_valid(self) -> int | None:
        """Newest step that passes validation; every newer invalid one is
        logged as discarded (the supervisor's fallback contract: a torn
        final write costs one snapshot interval, never the run)."""
        for step in reversed(self.steps()):
            ok, why = self.validate(step)
            if ok:
                return step
            _FALLBACKS.inc()
            _log(f"discarding snapshot {step} ({why}); "
                 f"falling back to the previous one")
        return None

    def restore(self, state: TrainState, step: int | None = None) -> TrainState:
        """Restore into the structure (and shardings) of ``state``;
        identity when the store is empty (CheckpointManager parity)."""
        step = self.latest_valid() if step is None else step
        if step is None:
            return state
        # Single read: _checked_payload validates from the same bytes it
        # returns, so restoring a large state costs one payload pass
        # here, not separate validate + load reads.
        payload, why = self._checked_payload(step)
        if payload is None:
            raise ValueError(f"snapshot {step} failed validation: {why}")
        with np.load(io.BytesIO(payload)) as z:
            loaded = [z[k] for k in sorted(z.files)]
        template = saveable_state_dict(state)
        t_leaves, treedef = jax.tree.flatten(template)
        if len(loaded) != len(t_leaves):
            raise ValueError(
                f"snapshot {step} holds {len(loaded)} leaves; this run's "
                f"state has {len(t_leaves)} — the model/optimizer changed "
                f"since the snapshot was written")
        restored_leaves = [
            jax.device_put(r, t.sharding) if isinstance(t, jax.Array) else r
            for t, r in zip(t_leaves, loaded)]
        restored = jax.tree.unflatten(treedef, restored_leaves)
        _RESTORES.inc()
        return state.replace(**restored)

    def discard_newer(self, step: int) -> list[int]:
        """Delete every snapshot (payload + manifest) newer than
        ``step`` — the fleet agreement pass's divergence discard.  A
        rank that ran AHEAD of the agreed resume step holds snapshots
        from a timeline the gang is abandoning; leaving them on disk
        would poison the NEXT recovery (save() dedupes against an
        existing valid manifest, so the stale future step would never
        be overwritten by the replayed one, and a later restore would
        silently jump onto the abandoned timeline).  Returns the
        discarded steps, ascending."""
        dropped = []
        for s in self.steps():
            if s <= step:
                continue
            failed = None
            for p in (self._payload_path(s), self._manifest_path(s)):
                try:
                    os.remove(p)
                except FileNotFoundError:
                    pass
                except OSError as e:
                    failed = e
            if failed is not None and self.validate(s)[0]:
                # A still-VALID snapshot the OS would not let us delete
                # must not be reported discarded: the caller journals
                # this list as the agreement's proof, and a later
                # restore-newest would silently jump onto the abandoned
                # timeline the record claims is gone.  (A half-removed
                # snapshot that now fails validation is harmless — the
                # fallback path already skips it.)
                _log(f"FAILED to discard snapshot {s} ({failed}) — it is "
                     f"still restorable as newest; fix the store "
                     f"permissions before trusting a resume from here")
                continue
            dropped.append(s)
        # Shard sets past the agreed step are the SAME divergent
        # timeline in the row-layout format — the agreement's discard
        # must cover both or a later quorum-valid shard step would
        # resurrect it (resilience/shardstore.py).
        from distributedtensorflowexample_tpu.resilience import (
            shardstore as _shardstore)
        dropped = sorted(set(dropped)
                         | set(_shardstore.discard_newer(self._dir, step)))
        if dropped:
            _log(f"discarded snapshot(s) {dropped} newer than agreed "
                 f"step {step} (divergent timeline)")
        return dropped

    # --- fault-injection surface -----------------------------------------
    def tear_latest(self) -> int | None:
        """Truncate the newest payload mid-file (fault injection: a
        checkpoint write that died between payload bytes and the torn
        half surviving a rename — or post-hoc media loss).  Returns the
        torn step, or None if the store is empty."""
        steps = self.steps()
        if not steps:
            return None
        path = self._payload_path(steps[-1])
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(size // 2)
        return steps[-1]


def valid_steps(directory: str) -> list[int]:
    """Steps in ``directory`` that pass validation, ascending — one
    rank's input to the fleet's resume-step agreement and the
    Remediator rollback actuator's notion of "good".  Both snapshot
    formats count: monolithic payloads here (size + crc32) UNIONed
    with the shard store's quorum-valid sets (every 1/D shard + the
    replicated payload digest-intact, resilience/shardstore.py) — so
    "the newest step the gang can provably agree on" already means
    shard quorum for row-layout runs.  Reads manifests and payload
    bytes only, never deserializes state."""
    from distributedtensorflowexample_tpu.resilience import (
        shardstore as _shardstore)
    store = SnapshotStore(directory)
    steps = {s for s in store.steps() if store.validate(s)[0]}
    steps.update(_shardstore.quorum_valid_steps(directory))
    return sorted(steps)


def newest_common_step(manifest_dirs: list[str]) -> int | None:
    """The maximum step EVERY directory holds a valid snapshot for —
    the gang's agreed resume point (resilience/fleet.py).

    Each rank snapshots independently, so after an unclean gang death
    the newest steps diverge: the killed rank stopped at k, a survivor
    ran on to k+m before teardown, and a torn final write fails
    validation entirely.  Restoring per-rank newest would silently
    resume DIFFERENT global steps on different ranks (the divergence
    this helper exists to make visible); the newest COMMON valid step
    is the latest state the whole fleet can provably agree on, and
    resuming there is bitwise-identical to an uninterrupted run.

    Returns None when no common valid step exists (some rank has
    nothing valid) — the gang must start fresh."""
    common: set[int] | None = None
    for d in manifest_dirs:
        steps = set(valid_steps(d))
        common = steps if common is None else common & steps
        if not common:
            return None
    return max(common) if common else None


class SnapshotHook(Hook):
    """Periodic + final crash-consistent snapshot (CheckpointHook's shape,
    SnapshotStore's format).  ``cursor`` is the static part of the dataset
    cursor (e.g. ``{"seed": cfg.seed}``); the step is stamped at save
    time so the manifest always names the batch-stream position a resume
    must rebuild (``DeviceDataset(..., start_step=cursor["step"])``)."""

    def __init__(self, store: SnapshotStore, every: int = 1,
                 cursor: dict | None = None):
        self._store = store
        self._due = _EveryN(every)
        self._cursor = dict(cursor or {})
        self._last_saved: int | None = None

    def _stamped(self, state) -> dict:
        return {**self._cursor, "step": int(state.step)}

    def begin(self, loop) -> None:
        self._due = _EveryN(self._due._every, int(loop.start_step))
        self._last_saved = None

    def _save(self, state, force: bool = False) -> bool:
        """One guarded write.  An OSError (disk full, the round-6
        ROADMAP fault) is logged and counted, never raised: losing ONE
        snapshot interval is recoverable by design (that's what keep-N
        and the manifest fallback exist for), while killing the run
        here would convert a full /tmp into a lost training job.  The
        next interval retries against whatever space exists then."""
        step = int(state.step)
        try:
            with span("snapshot", step=step):
                self._store.save(state, cursor=self._stamped(state),
                                 force=force)
            return True
        except OSError as e:
            _SAVE_FAILURES.inc()
            _log(f"save at step {step} failed ({e}) — continuing; the "
                 f"newest valid snapshot on disk is unchanged and the "
                 f"next interval retries")
            return False

    def after_step(self, step, state, metrics) -> bool:
        if self._due(step) and self._save(state):
            self._last_saved = int(state.step)
        return False

    def end(self, state) -> None:
        # force is for an OFF-GRID final step; when the last periodic
        # save already covered this exact step, a forced rewrite would
        # re-serialize and double-fsync the whole state for nothing.
        if int(state.step) == self._last_saved:
            return
        self._save(state, force=True)
