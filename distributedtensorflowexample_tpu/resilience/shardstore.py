"""Shard-redundant crash-consistent snapshots for the 1/D row layouts.

``resilience/snapshot.py`` writes ONE monolithic payload per step — the
right recovery format for a tree-layout run, and exactly the wrong one
for zero1/zero3 (``--bucket_grads`` / ``--shard_params``): there each
device owns a 1/D row of every bucket, so a full-state payload both
gathers state the rank doesn't own and couples every rank's save to one
file.  This store writes what the layout actually is:

- **per-rank shards**: rank r saves only ITS row of every bucket flat
  (``own.npz`` under ``shards_<step>/rank_<r>/``) — params rows under
  zero3, optimizer-moment rows under both row layouts;
- **ring mirrors** (redundancy R, ``SNAPSHOT_REDUNDANCY``, default 2):
  rank ``(s+m) % D`` additionally holds a byte-identical copy of rank
  s's shard for ``m < R`` — so ANY R-1 lost/corrupt rank directories
  still leave every shard at least one intact copy, and restore
  reconstructs the missing ones from their mirrors;
- **replicated leaves** (step, RNG, schedule counts — and the full
  params tree under zero1, where params stay replicated) land in
  ``repl.npz`` on ranks ``0..R-1``: the same survive-any-R-1-losses
  guarantee without D full copies;
- **quorum manifest, written LAST**: sha256 per shard + the layout
  facts (mesh width D, bucket plan, param leaf specs, bucket_bytes) —
  a step is quorum-valid iff every shard and the replicated payload
  have at least one digest-intact copy.  A write torn anywhere before
  the manifest rename leaves no manifest and the step reads as absent;
  a bit flipped after commit fails its sha256 and that COPY is
  refused, never silently restored.

Every payload write goes through the obs atomic-write discipline
(tmp + fsync + rename) with bounded retry/backoff on OSError
(``SNAPSHOT_IO_RETRIES`` / ``SNAPSHOT_IO_BACKOFF_S``) — a flaky disk
costs retries, a dead one costs ONE snapshot interval, never the run.

Restore comes in two shapes:

- :meth:`ShardStore.restore` — same mesh width only (refused BY NAME
  across widths: the 1/D row layout is structural), positional
  row/replicated install into an already-laid-out row state;
- :meth:`ShardStore.restore_elastic` — any mesh width.  The saved
  bucket plan is a pure function of the param leaf specs + byte cap
  (``plan_buckets``), so it is D-independent; only the per-leaf zero
  padding ``ceil(n/D)`` inside each bucket changes with D.  Elastic
  restore therefore (1) reassembles each bucket flat from the shards,
  (2) strips the old padding back to exact leaf values (pure byte
  moves — numpy twins of ``parallel/bucketing._unbucket_rows``),
  (3) rebuilds the full param tree and hands it to the engine's ONE
  re-layout pass (``engine.apply_update_layout``) on the new mesh, and
  (4) grafts the optimizer-moment rows in with the same regroup.
  Every move is byte movement around zero padding, so a D=4 shard set
  restored onto D=2 (or D=8) materializes BITWISE the state the saver
  held — proven in tests/test_checkpoint.py.

When shard loss exceeds redundancy the restore refuses loudly, naming
the shard, its copy census, and the knob (``SNAPSHOT_REDUNDANCY``)
that bounds what is survivable — a half-reconstructed state must never
train.  Fleet resume agreement and the Remediator's rollback actuator
see these steps through ``snapshot.valid_steps`` (monolithic-valid ∪
quorum-valid), so "the newest step the gang can provably agree on"
already means shard quorum.  Restore/reconstruction events land in the
run ledger as ``ckpt_*`` rows (rendered by tools/obs_query.py).
"""

from __future__ import annotations

import dataclasses
import hashlib
import io
import json
import os
import re
import shutil
import sys
import time

import jax
import numpy as np

from distributedtensorflowexample_tpu.obs import ledger as obs_ledger
from distributedtensorflowexample_tpu.obs import metrics as obs_metrics
from distributedtensorflowexample_tpu.obs import recorder as obs_recorder
from distributedtensorflowexample_tpu.obs.trace import span
from distributedtensorflowexample_tpu.parallel.bucketing import plan_buckets
from distributedtensorflowexample_tpu.refusal import ModeRefusal
from distributedtensorflowexample_tpu.training.checkpoint import (
    saveable_state_dict)
from distributedtensorflowexample_tpu.training.hooks import Hook, _EveryN
from distributedtensorflowexample_tpu.training.state import TrainState

MANIFEST_VERSION = 1
_STEP_DIR_RE = re.compile(r"^shards_(\d{8})$")

_SAVES = obs_metrics.counter(
    "ckpt_shard_saves_total", "committed shard-set writes "
    "(all rank payloads + manifest)")
_SAVE_FAILURES = obs_metrics.counter(
    "ckpt_shard_save_failures", "shard-set writes refused by the OS "
    "after retries, survived by the run (keep-N covers the gap)")
_RESTORES = obs_metrics.counter(
    "ckpt_shard_restores_total", "successful restores from a shard set "
    "(same-width and elastic)")
_RECONSTRUCTIONS = obs_metrics.counter(
    "ckpt_shard_reconstructions_total",
    "shards rebuilt from a ring mirror (own copy missing or corrupt)")
_DIGEST_MISMATCHES = obs_metrics.counter(
    "ckpt_digest_mismatches_total",
    "shard copies refused by sha256 — bit rot detected, never restored")
_IO_RETRIES = obs_metrics.counter(
    "ckpt_io_retries_total", "payload writes retried after an OSError "
    "(SNAPSHOT_IO_RETRIES bounds the attempts)")
_REFUSALS = obs_metrics.counter(
    "ckpt_restore_refusals_total",
    "restores refused loudly (loss beyond redundancy, width mismatch "
    "on the non-elastic path, structural drift)")


def _log(msg: str) -> None:
    print(f"shardstore: {msg}", file=sys.stderr, flush=True)


def _event(event: str, **fields) -> None:
    # Gang ranks inherit OBS_PHASE = the gang/job name (resilience/
    # fleet.py exports it per rank), so stamping it as job= threads
    # ckpt_* rows into the same per-job `why` timeline obs_query builds
    # from the sched_*/heal_* rows.
    obs_ledger.log_event(event, src="shardstore",
                         job=os.environ.get("OBS_PHASE", ""), **fields)


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


# --- the layout facts the manifest records -----------------------------

@dataclasses.dataclass(frozen=True)
class _Spec:
    """A param leaf as shape+dtype — what ``plan_buckets`` and the
    regroup need, with no array attached."""
    shape: tuple
    dtype: np.dtype

    @property
    def size(self) -> int:
        n = 1
        for d in self.shape:
            n *= int(d)
        return n


class ShardLayout:
    """Plain-data description of a run's row layout: everything the
    store needs to slice rows at save time and regroup them at restore
    time, recorded verbatim in the manifest so restoring onto a
    DIFFERENT mesh width recomputes nothing it can't verify."""

    def __init__(self, update_layout: str, bucket_bytes: int,
                 param_specs: list[_Spec], num_ranks: int,
                 plan: list[list[int]] | None = None):
        if update_layout not in ("zero3_rows", "bucket_rows"):
            raise ValueError(
                f"unknown row layout {update_layout!r} — the shard store "
                f"is the zero1/zero3 snapshot format (tree-layout runs "
                f"use resilience/snapshot.py)")
        if num_ranks < 2:
            raise ValueError(f"row layouts shard over >= 2 ranks, "
                             f"got {num_ranks}")
        self.update_layout = update_layout
        self.bucket_bytes = int(bucket_bytes)
        self.param_specs = list(param_specs)
        self.num_ranks = int(num_ranks)
        # The plan is a pure function of (leaf specs, byte cap) — NOT
        # of D — which is the whole reason a shard set can regroup onto
        # another width.  Recomputing here (instead of trusting a
        # caller) keeps the manifest honest.
        self.plan = plan if plan is not None else plan_buckets(
            self.param_specs, self.bucket_bytes)

    @classmethod
    def for_params(cls, update_layout: str, bucket_bytes: int, params,
                   num_ranks: int) -> "ShardLayout":
        """From the TREE-form params (before the row re-layout)."""
        specs = [_Spec(tuple(int(d) for d in l.shape), np.dtype(l.dtype))
                 for l in jax.tree.leaves(params)]
        return cls(update_layout, bucket_bytes, specs, num_ranks)

    def bucket_width(self, b: int, num_ranks: int) -> int:
        """Columns of bucket ``b``'s ``[D, W]`` layout at width
        ``num_ranks`` — per-leaf zero padding to ``ceil(n/D)``, summed
        (the one D-dependent part of the layout)."""
        return sum(-(-self.param_specs[i].size // num_ranks)
                   for i in self.plan[b])

    def to_manifest(self) -> dict:
        return {"update_layout": self.update_layout,
                "bucket_bytes": self.bucket_bytes,
                "param_specs": [[list(s.shape), s.dtype.name]
                                for s in self.param_specs],
                "plan": [list(b) for b in self.plan]}

    @classmethod
    def from_manifest(cls, m: dict) -> "ShardLayout":
        specs = [_Spec(tuple(shape), np.dtype(dt))
                 for shape, dt in m["param_specs"]]
        return cls(m["update_layout"], m["bucket_bytes"], specs,
                   m["num_ranks"], plan=[list(b) for b in m["plan"]])


# --- pure-numpy regroup (byte-movement twins of parallel/bucketing) ----

def _unbucket(flat: np.ndarray, specs: list[_Spec],
              num_ranks: int) -> list[np.ndarray]:
    """Inverse of the bucket row layout at width ``num_ranks``: slice
    the ``[D*W]`` flat back into exact leaf values, padding dropped —
    ``parallel/bucketing._unbucket_rows`` in numpy (bitwise: both only
    move bytes)."""
    rows = np.asarray(flat).reshape(num_ranks, -1)
    out, off = [], 0
    for spec in specs:
        w = -(-spec.size // num_ranks)
        out.append(rows[:, off:off + w].ravel()[:spec.size]
                   .reshape(spec.shape))
        off += w
    if off != rows.shape[1]:
        raise ValueError(
            f"bucket flat has {rows.shape[1]} columns; its leaf specs "
            f"account for {off} — the saved plan does not describe this "
            f"shard set")
    return out


def _rebucket(values: list[np.ndarray], num_ranks: int) -> np.ndarray:
    """The bucket flat at width ``num_ranks``: per-leaf zero-pad to a
    multiple of D, ``[D, ceil(n/D)]`` blocks concatenated column-wise,
    raveled — ``parallel/bucketing._bucket_flat2d(...).ravel()`` in
    numpy."""
    cols = []
    for v in values:
        flat = np.asarray(v).ravel()
        pad = (-flat.size) % num_ranks
        if pad:
            flat = np.concatenate([flat, np.zeros(pad, flat.dtype)])
        cols.append(flat.reshape(num_ranks, -1))
    return np.concatenate(cols, axis=1).ravel()


def _is_row(leaf, num_ranks: int) -> bool:
    """A saveable leaf is a 1/D row iff it is a flat vector whose length
    the mesh divides AND it is actually sharded (the RNG key is a flat
    replicated vector — replication is the discriminator, not shape)."""
    return (isinstance(leaf, jax.Array) and leaf.ndim == 1
            and leaf.size > 0 and leaf.size % num_ranks == 0
            and not leaf.sharding.is_fully_replicated)


def _classify(saveable: dict, num_ranks: int):
    """Split each field's flatten-order leaves into (row, replicated)
    position lists — THE one classification save and restore share, so
    the positional correspondence between a shard set and a live state
    cannot drift."""
    out = {}
    for fname, sub in saveable.items():
        rows, repl = [], []
        for j, leaf in enumerate(jax.tree.leaves(sub)):
            (rows if _is_row(leaf, num_ranks) else repl).append(j)
        out[fname] = (rows, repl)
    return out


# --- the store ---------------------------------------------------------

class ShardStore:
    """Per-rank shard files + ring mirrors + quorum manifest under
    ``directory`` (one ``shards_<step>/`` dir per step; coexists with
    SnapshotStore's monolithic files in the same directory — the fleet's
    ``valid_steps`` unions both formats)."""

    def __init__(self, directory: str, layout: ShardLayout | None = None,
                 keep: int = 3, redundancy: int | None = None):
        self._dir = directory
        self._layout = layout
        self._keep = keep
        r = (redundancy if redundancy is not None
             else _env_int("SNAPSHOT_REDUNDANCY", 2))
        self._redundancy = max(1, r)

    # -- paths ----------------------------------------------------------

    def _step_dir(self, step: int) -> str:
        return os.path.join(self._dir, f"shards_{step:08d}")

    def _rank_dir(self, step: int, rank: int) -> str:
        return os.path.join(self._step_dir(step), f"rank_{rank:05d}")

    def _manifest_path(self, step: int) -> str:
        return os.path.join(self._step_dir(step), "manifest.json")

    def steps(self) -> list[int]:
        try:
            names = os.listdir(self._dir)
        except FileNotFoundError:
            return []
        return sorted(int(m.group(1)) for n in names
                      if (m := _STEP_DIR_RE.match(n)))

    def manifest(self, step: int) -> dict | None:
        try:
            with open(self._manifest_path(step)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    # -- write path -----------------------------------------------------

    def _atomic_write(self, path: str, data: bytes) -> None:
        """Monkeypatch seam (tests inject ENOSPC/EIO here), delegating
        to THE atomic-write implementation (obs/recorder.py)."""
        obs_recorder.atomic_write(path, data)

    def _write_retrying(self, path: str, data: bytes) -> None:
        """Bounded retry/backoff around one atomic payload write: a
        flaky disk costs ``SNAPSHOT_IO_RETRIES`` extra attempts with
        ``SNAPSHOT_IO_BACKOFF_S``-doubling sleeps; a dead one re-raises
        to the save's OSError contract (logged + counted, never fatal)."""
        retries = max(0, _env_int("SNAPSHOT_IO_RETRIES", 2))
        backoff = max(0.0, _env_float("SNAPSHOT_IO_BACKOFF_S", 0.05))
        for attempt in range(retries + 1):
            try:
                self._atomic_write(path, data)
                return
            except OSError as e:
                if attempt == retries:
                    raise
                _IO_RETRIES.inc()
                _log(f"write {os.path.basename(path)} failed ({e}) — "
                     f"retry {attempt + 1}/{retries} in "
                     f"{backoff * (2 ** attempt):.3f}s")
                time.sleep(backoff * (2 ** attempt))

    def _serialize(self, state: TrainState):
        """(per-rank own bytes, repl bytes, per-field row/repl census).
        Refuses a state whose row leaves don't match the layout's
        bucket plan — a manifest must describe what is actually on
        disk, or quorum means nothing."""
        lay = self._layout
        if lay is None:
            raise ValueError("ShardStore.save needs the run's "
                             "ShardLayout (see ShardLayout.for_params)")
        D = lay.num_ranks
        saveable = saveable_state_dict(state)
        rank_payload: dict[int, dict[str, np.ndarray]] = {
            r: {} for r in range(D)}
        repl_payload: dict[str, np.ndarray] = {}
        fields: dict[str, dict] = {}
        n_buckets = len(lay.plan)
        for fname, sub in saveable.items():
            leaves = jax.tree.leaves(sub)
            rows_meta, n_repl, ri = [], 0, 0
            for leaf in leaves:
                if _is_row(leaf, D):
                    arr = np.asarray(leaf)
                    mat = arr.reshape(D, -1)
                    key = f"{fname}__{ri:05d}"
                    for r in range(D):
                        rank_payload[r][key] = mat[r]
                    rows_meta.append({"size": int(arr.size)})
                    ri += 1
                else:
                    repl_payload[f"{fname}__{n_repl:05d}"] = np.asarray(leaf)
                    n_repl += 1
            if rows_meta:
                # Bucket correspondence: a field's row leaves come
                # bucket-major with a uniform per-bucket count M (1 for
                # zero3 params; the optimizer's moment count for opt
                # state), sized D*W_b.  Anything else means the state
                # is not the layout this store was built for.
                if len(rows_meta) % n_buckets:
                    raise ValueError(
                        f"field {fname!r} holds {len(rows_meta)} row "
                        f"leaves over {n_buckets} buckets — not a whole "
                        f"number per bucket; this state does not match "
                        f"the store's bucket plan")
                m_per = len(rows_meta) // n_buckets
                for j, rm in enumerate(rows_meta):
                    want = D * lay.bucket_width(j // m_per, D)
                    if rm["size"] != want:
                        raise ValueError(
                            f"field {fname!r} row leaf {j} has "
                            f"{rm['size']} elements; bucket "
                            f"{j // m_per} at D={D} lays out {want} — "
                            f"this state does not match the store's "
                            f"bucket plan")
            fields[fname] = {"rows": rows_meta, "repl": n_repl}
        if not any(f["rows"] for f in fields.values()):
            raise ValueError(
                "state holds no 1/D row leaves — the shard store is the "
                "row-layout snapshot format; tree-layout runs use "
                "resilience/snapshot.py SnapshotStore")

        def _npz(payload: dict) -> bytes:
            buf = io.BytesIO()
            np.savez(buf, **payload)
            return buf.getvalue()

        # One serialization per logical payload: mirrors are the SAME
        # bytes, so copy digests are comparable by construction.
        own = {r: _npz(rank_payload[r]) for r in range(D)}
        return own, _npz(repl_payload), fields

    def save(self, state: TrainState, cursor: dict | None = None,
             meta: dict | None = None) -> int:
        """Write one quorum-committed shard set for ``state``'s step:
        every rank's ``own.npz``, its ring mirrors, the replicated
        payload on ranks ``0..R-1`` — all atomic, all fsynced — and the
        manifest LAST.  Returns the step.  Raises OSError only after
        the bounded retries are exhausted (hook callers log + count)."""
        lay = self._layout
        step = int(state.step)
        own, repl_bytes, fields = self._serialize(state)
        D = lay.num_ranks
        R = min(self._redundancy, D)
        sdir = self._step_dir(step)
        with span("shard_snapshot", step=step):
            os.makedirs(sdir, exist_ok=True)
            digests = {f"own_{s:05d}": hashlib.sha256(own[s]).hexdigest()
                       for s in range(D)}
            digests["repl"] = hashlib.sha256(repl_bytes).hexdigest()
            for r in range(D):
                rdir = self._rank_dir(step, r)
                os.makedirs(rdir, exist_ok=True)
                self._write_retrying(os.path.join(rdir, "own.npz"), own[r])
                # Ring mirrors: rank r holds a byte-identical copy of
                # the R-1 shards BEHIND it on the ring, so any R-1
                # contiguous (or scattered) rank-dir losses leave every
                # shard one intact copy.
                for m in range(1, R):
                    s = (r - m) % D
                    self._write_retrying(
                        os.path.join(rdir, f"mirror_{s:05d}.npz"), own[s])
                if r < R:
                    self._write_retrying(
                        os.path.join(rdir, "repl.npz"), repl_bytes)
            manifest = {"version": MANIFEST_VERSION, "step": step,
                        "num_ranks": D, "redundancy": R,
                        "fields": fields, "digests": digests,
                        "cursor": dict(cursor or {}),
                        "meta": dict(meta or {}),
                        **lay.to_manifest()}
            self._write_retrying(
                self._manifest_path(step),
                json.dumps(manifest, sort_keys=True).encode())
            # Durability of the renames themselves: fsync the step dir
            # (atomic_write fsyncs file CONTENTS; the directory entry
            # needs its own).
            try:
                fd = os.open(sdir, os.O_RDONLY)
                try:
                    os.fsync(fd)
                finally:
                    os.close(fd)
            except OSError:
                pass
        self._trim()
        _SAVES.inc()
        _event("ckpt_save", step=step, ranks=D, redundancy=R,
               nbytes=sum(len(b) for b in own.values()))
        return step

    def _trim(self) -> None:
        if self._keep <= 0:
            return
        for s in self.steps()[:-self._keep]:
            shutil.rmtree(self._step_dir(s), ignore_errors=True)

    def discard_newer(self, step: int) -> list[int]:
        """Delete every shard set newer than ``step`` — the fleet
        agreement's divergence discard, same contract as
        ``SnapshotStore.discard_newer``."""
        dropped = []
        for s in self.steps():
            if s > step:
                shutil.rmtree(self._step_dir(s), ignore_errors=True)
                if not os.path.isdir(self._step_dir(s)):
                    dropped.append(s)
        return dropped

    # -- validation / quorum --------------------------------------------

    def _copies(self, step: int, shard: int, manifest: dict):
        """Every on-disk location shard ``shard`` may live at, own
        first, ring mirrors after — ``(path, holder_rank)`` pairs."""
        D = manifest["num_ranks"]
        out = [(os.path.join(self._rank_dir(step, shard), "own.npz"),
                shard)]
        for m in range(1, manifest["redundancy"]):
            h = (shard + m) % D
            out.append((os.path.join(self._rank_dir(step, h),
                                     f"mirror_{shard:05d}.npz"), h))
        return out

    def _good_bytes(self, path: str, want_digest: str):
        """(bytes, why_bad): read one copy and check its sha256 — a
        mismatch is COUNTED (that is the bit-rot detection the digests
        exist for) and the copy refused."""
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError as e:
            return None, f"unreadable ({e.__class__.__name__})"
        if hashlib.sha256(data).hexdigest() != want_digest:
            _DIGEST_MISMATCHES.inc()
            return None, "digest mismatch"
        return data, None

    def shard_census(self, step: int, manifest: dict | None = None):
        """Per-shard intact-copy count + repl count — the quorum facts
        (validate's detail and the refusal message's evidence)."""
        manifest = manifest or self.manifest(step)
        if manifest is None:
            return None
        D = manifest["num_ranks"]
        census = {}
        for s in range(D):
            ok = 0
            for path, _holder in self._copies(step, s, manifest):
                data, _why = self._good_bytes(
                    path, manifest["digests"][f"own_{s:05d}"])
                if data is not None:
                    ok += 1
            census[s] = ok
        repl_ok = 0
        for r in range(manifest["redundancy"]):
            data, _why = self._good_bytes(
                os.path.join(self._rank_dir(step, r), "repl.npz"),
                manifest["digests"]["repl"])
            if data is not None:
                repl_ok += 1
        return {"shards": census, "repl": repl_ok}

    def validate(self, step: int):
        """(ok, why): quorum-valid iff the manifest parses AND every
        shard has >= 1 digest-intact copy AND the replicated payload
        does too."""
        manifest = self.manifest(step)
        if manifest is None:
            return False, "missing or unparseable manifest"
        census = self.shard_census(step, manifest)
        bad = [s for s, n in census["shards"].items() if n == 0]
        if bad:
            return False, (f"shards {bad} have no intact copy "
                           f"(R={manifest['redundancy']})")
        if census["repl"] == 0:
            return False, "replicated payload has no intact copy"
        return True, "ok"

    def quorum_steps(self) -> list[int]:
        return [s for s in self.steps() if self.validate(s)[0]]

    def latest_valid(self) -> int | None:
        steps = self.quorum_steps()
        return steps[-1] if steps else None

    # -- read path ------------------------------------------------------

    def _load(self, step: int):
        """(manifest, {field: [row flats at D_saved]}, {field: [repl
        arrays]}, reconstructed shard list).  Refuses BY NAME when any
        shard's loss exceeds redundancy — a half-reconstructed state
        must never train."""
        manifest = self.manifest(step)
        if manifest is None:
            raise ValueError(f"shard set {step} has no readable "
                             f"manifest — the write never committed")
        D = manifest["num_ranks"]
        shard_rows: list[dict] = []
        reconstructed: list[int] = []
        for s in range(D):
            data = None
            for path, holder in self._copies(step, s, manifest):
                data, why = self._good_bytes(
                    path, manifest["digests"][f"own_{s:05d}"])
                if data is not None:
                    if holder != s:
                        reconstructed.append(s)
                        _RECONSTRUCTIONS.inc()
                        _event("ckpt_reconstruct", step=step, shard=s,
                               source_rank=holder)
                        _log(f"step {step}: shard {s} rebuilt from rank "
                             f"{holder}'s ring mirror")
                    break
                _event("ckpt_digest_mismatch" if why == "digest mismatch"
                       else "ckpt_copy_unreadable", step=step, shard=s,
                       file=os.path.relpath(path, self._dir))
            if data is None:
                census = self.shard_census(step, manifest)
                _REFUSALS.inc()
                _event("ckpt_refused", step=step, shard=s,
                       census=census["shards"],
                       redundancy=manifest["redundancy"])
                raise ModeRefusal(
                    f"shard {s} of step {step} has NO intact copy (own "
                    f"and every ring mirror missing or digest-refused; "
                    f"census {census['shards']}) — loss exceeds "
                    f"redundancy R={manifest['redundancy']}. Refusing "
                    f"to restore a partial state; resume from an older "
                    f"quorum-valid step, or raise SNAPSHOT_REDUNDANCY "
                    f"at save time to survive more")
            with np.load(io.BytesIO(data)) as z:
                shard_rows.append({k: z[k] for k in z.files})
        repl_data = None
        for r in range(manifest["redundancy"]):
            repl_data, _why = self._good_bytes(
                os.path.join(self._rank_dir(step, r), "repl.npz"),
                manifest["digests"]["repl"])
            if repl_data is not None:
                break
        if repl_data is None:
            _REFUSALS.inc()
            raise ModeRefusal(
                f"step {step}: the replicated payload has no intact "
                f"copy on ranks 0..{manifest['redundancy'] - 1} — loss "
                f"exceeds redundancy R={manifest['redundancy']}")
        field_rows: dict[str, list[np.ndarray]] = {}
        for fname, fmeta in manifest["fields"].items():
            flats = []
            for j in range(len(fmeta["rows"])):
                key = f"{fname}__{j:05d}"
                flats.append(np.concatenate(
                    [shard_rows[s][key] for s in range(D)]))
            field_rows[fname] = flats
        field_repl: dict[str, list[np.ndarray]] = {}
        with np.load(io.BytesIO(repl_data)) as z:
            for fname, fmeta in manifest["fields"].items():
                field_repl[fname] = [z[f"{fname}__{j:05d}"]
                                     for j in range(fmeta["repl"])]
        return manifest, field_rows, field_repl, reconstructed

    def _install(self, state: TrainState, manifest, field_rows,
                 field_repl, num_ranks: int) -> TrainState:
        """Positional install into ``state``'s structure+shardings —
        row leaves from the reassembled flats, replicated leaves from
        the repl payload, each put back with its template's sharding."""
        template = saveable_state_dict(state)
        restored = {}
        for fname, sub in template.items():
            leaves, treedef = jax.tree.flatten(sub)
            fmeta = manifest["fields"].get(fname)
            if fmeta is None:
                raise ValueError(
                    f"shard set {manifest['step']} has no field "
                    f"{fname!r} — the state structure changed since it "
                    f"was written")
            rows = list(field_rows[fname])
            repl = list(field_repl[fname])
            new_leaves = []
            for leaf in leaves:
                src = (rows if _is_row(leaf, num_ranks) else repl)
                if not src:
                    raise ValueError(
                        f"shard set {manifest['step']} field {fname!r} "
                        f"ran out of saved leaves — the model/optimizer "
                        f"changed since it was written")
                val = src.pop(0)
                new_leaves.append(
                    jax.device_put(val, leaf.sharding)
                    if isinstance(leaf, jax.Array) else val)
            if rows or repl:
                raise ValueError(
                    f"shard set {manifest['step']} field {fname!r} holds "
                    f"{len(rows)} row + {len(repl)} replicated leaves "
                    f"this run's state has no position for — the "
                    f"model/optimizer changed since it was written")
            restored[fname] = jax.tree.unflatten(treedef, new_leaves)
        return state.replace(**restored)

    def restore(self, state: TrainState, mesh,
                step: int | None = None) -> TrainState:
        """Same-width restore into an already-laid-out ROW state.
        Refuses a width mismatch by name: the 1/D row layout is
        structural, and the sanctioned cross-width path is
        :meth:`restore_elastic` (the engine re-layout pass)."""
        step = self.latest_valid() if step is None else step
        if step is None:
            return state
        manifest = self.manifest(step)
        if manifest is None:
            raise ValueError(f"shard set {step} has no readable manifest")
        if manifest["num_ranks"] != mesh.size:
            _REFUSALS.inc()
            raise ModeRefusal(
                f"shard set at step {step} was written by "
                f"{manifest['num_ranks']} ranks; this mesh has "
                f"{mesh.size} — the 1/D row layout is structural, so a "
                f"positional restore would interleave rows from the "
                f"wrong width. Use ShardStore.restore_elastic (the "
                f"engine layout regroup) to restore across widths")
        manifest, field_rows, field_repl, recon = self._load(step)
        out = self._install(state, manifest, field_rows, field_repl,
                            mesh.size)
        _RESTORES.inc()
        _event("ckpt_restore", step=step,
               from_ranks=manifest["num_ranks"], to_ranks=mesh.size,
               elastic=False, reconstructed=recon)
        return out

    def restore_elastic(self, state: TrainState, tx, *, mesh,
                        step: int | None = None):
        """Restore a shard set of ANY width onto ``mesh``: reassemble
        exact param values from the saved rows, run them through the
        engine's ONE re-layout pass (``apply_update_layout``) at the
        new width, and regroup the optimizer-moment rows with the same
        byte movement.  ``state`` must be the fresh TREE-layout state
        on the new mesh (params as the param tree — what
        ``TrainState.create`` builds, BEFORE any row re-layout).

        Returns ``(row_state, aux)`` with ``aux`` carrying the layout
        object the engine pass built (``zero3_layout``, None for
        zero1), the restored ``step``, the saved dataset ``cursor``,
        and ``from_ranks``.  Bitwise: every move here and in the
        engine pass is byte movement around zero padding — a D=4 set
        restored at D=2 materializes exactly the saver's state
        (tests/test_checkpoint.py pins it)."""
        step = self.latest_valid() if step is None else step
        if step is None:
            raise ValueError(
                f"no quorum-valid shard step in {self._dir} — nothing "
                f"to restore")
        manifest, field_rows, field_repl, recon = self._load(step)
        lay = ShardLayout.from_manifest(manifest)
        d_old, d_new = lay.num_ranks, mesh.size
        n_buckets = len(lay.plan)
        # The NEW mesh is the placement authority for everything the
        # engine pass consumes: a template built off-mesh (plain
        # TrainState.create) must not leak single-device placement into
        # the re-layout.
        from jax.sharding import NamedSharding, PartitionSpec
        repl_sharding = NamedSharding(mesh, PartitionSpec())

        # (1) Exact param values back from the saved width's rows.
        if lay.update_layout == "zero3_rows":
            if len(field_rows["params"]) != n_buckets:
                raise ValueError(
                    f"shard set {step} holds "
                    f"{len(field_rows['params'])} param buckets; its "
                    f"plan names {n_buckets} — manifest is inconsistent")
            values = []
            for b, flat in enumerate(field_rows["params"]):
                values.extend(_unbucket(
                    flat, [lay.param_specs[i] for i in lay.plan[b]],
                    d_old))
            # _unbucket emits bucket-member order == plan order ==
            # canonical flatten order (plan_buckets is order-preserving).
            param_values = values
        else:                                  # bucket_rows: params repl
            param_values = list(field_repl["params"])
        t_leaves, treedef = jax.tree.flatten(state.params)
        if len(param_values) != len(t_leaves):
            raise ValueError(
                f"shard set {step} restores {len(param_values)} param "
                f"leaves; this run's model has {len(t_leaves)} — the "
                f"model changed since it was written")
        for v, t in zip(param_values, t_leaves):
            if tuple(v.shape) != tuple(t.shape):
                raise ValueError(
                    f"shard set {step} param leaf shape {tuple(v.shape)} "
                    f"does not match the model's {tuple(t.shape)} — the "
                    f"model changed since it was written")
        params = jax.tree.unflatten(
            treedef, [jax.device_put(v, repl_sharding)
                      for v in param_values])

        # (2) Replicated fields (step, rng, BN stats) install as-is.
        state = state.replace(params=params)
        for fname in ("step", "rng", "batch_stats"):
            vals = list(field_repl.get(fname, []))
            leaves, fdef = jax.tree.flatten(
                saveable_state_dict(state)[fname])
            if len(vals) != len(leaves):
                raise ValueError(
                    f"shard set {step} field {fname!r} holds "
                    f"{len(vals)} leaves; this run's state has "
                    f"{len(leaves)} — the state structure changed")
            if leaves:
                state = state.replace(**{fname: jax.tree.unflatten(
                    fdef, [jax.device_put(v, repl_sharding)
                           if isinstance(t, jax.Array) else v
                           for v, t in zip(vals, leaves)])})

        # (3) The engine's one re-layout pass, at the NEW width.
        # Lazy import: the engine owns layout wiring; this module only
        # feeds it (and nothing above engine imports shardstore at
        # module scope, so no cycle).
        from distributedtensorflowexample_tpu.engine.engine import (
            apply_update_layout)
        state, zero3_layout = apply_update_layout(
            state, tx, update_layout=lay.update_layout,
            bucket_bytes=lay.bucket_bytes, mesh=mesh)

        # (4) Graft the optimizer-moment rows: unbucket at the saved
        # width, rebucket at the new one (same plan — it is
        # D-independent), and put each flat back with its target row
        # sharding.  Scalars (schedule counts) come from repl.
        saved_rows = list(field_rows.get("opt_state", []))
        saved_repl = list(field_repl.get("opt_state", []))
        leaves, odef = jax.tree.flatten(state.opt_state)
        row_pos = [j for j, l in enumerate(leaves) if _is_row(l, d_new)]
        repl_pos = [j for j in range(len(leaves)) if j not in row_pos]
        if len(saved_rows) != len(row_pos) \
                or len(saved_repl) != len(repl_pos):
            raise ValueError(
                f"shard set {step} optimizer state holds "
                f"{len(saved_rows)} row + {len(saved_repl)} replicated "
                f"leaves; this run's has {len(row_pos)} + "
                f"{len(repl_pos)} — the optimizer changed since it was "
                f"written")
        if saved_rows:
            m_per = len(saved_rows) // n_buckets
            for k, (j, flat_old) in enumerate(zip(row_pos, saved_rows)):
                specs = [lay.param_specs[i] for i in lay.plan[k // m_per]]
                flat_new = _rebucket(_unbucket(flat_old, specs, d_old),
                                     d_new)
                if flat_new.size != leaves[j].size:
                    raise ValueError(
                        f"regrouped opt row {k} has {flat_new.size} "
                        f"elements; the new layout expects "
                        f"{leaves[j].size} — bucket plans diverged")
                leaves[j] = jax.device_put(flat_new, leaves[j].sharding)
        for j, v in zip(repl_pos, saved_repl):
            leaves[j] = (jax.device_put(v, leaves[j].sharding)
                         if isinstance(leaves[j], jax.Array) else v)
        state = state.replace(opt_state=jax.tree.unflatten(odef, leaves))

        _RESTORES.inc()
        _event("ckpt_restore", step=step, from_ranks=d_old,
               to_ranks=d_new, elastic=d_old != d_new,
               reconstructed=recon)
        if d_old != d_new:
            _log(f"elastic restore: step {step} regrouped "
                 f"D={d_old} -> D={d_new} through the engine layout "
                 f"pass")
        return state, {"zero3_layout": zero3_layout, "step": step,
                       "cursor": manifest.get("cursor", {}),
                       "from_ranks": d_old,
                       "reconstructed": recon}

    # -- fault seams (tools/faultline.py's shard_loss / bitflip) --------

    def drop_rank_dir(self, rank: int, step: int | None = None):
        """Delete one rank's whole directory in the newest shard set —
        the ``shard_loss`` fault (a lost host's local disk)."""
        step = self.steps()[-1] if step is None and self.steps() else step
        if step is None:
            return None
        shutil.rmtree(self._rank_dir(step, rank), ignore_errors=True)
        return step

    def flip_payload_byte(self, rank: int, step: int | None = None):
        """Flip one byte in the middle of one rank's ``own.npz``,
        in place and deliberately NOT atomically — silent bit rot the
        manifest digest must catch (the ``bitflip`` fault)."""
        step = self.steps()[-1] if step is None and self.steps() else step
        if step is None:
            return None
        path = os.path.join(self._rank_dir(step, rank), "own.npz")
        try:
            with open(path, "r+b") as f:
                f.seek(0, os.SEEK_END)
                off = f.tell() // 2
                f.seek(off)
                b = f.read(1)
                f.seek(off)
                f.write(bytes([b[0] ^ 0xFF]))
            return step, off
        except OSError:
            return None


# --- module helpers (the fleet/remediator quorum seam) -----------------

def shard_steps(directory: str) -> list[int]:
    return ShardStore(directory).steps()


def quorum_valid_steps(directory: str) -> list[int]:
    """Steps whose shard set reaches quorum (every shard + repl has an
    intact copy) — unioned into ``snapshot.valid_steps``, which is what
    the fleet resume agreement and the Remediator's rollback actuator
    rank steps by."""
    return ShardStore(directory).quorum_steps()


def discard_newer(directory: str, step: int) -> list[int]:
    return ShardStore(directory).discard_newer(step)


# --- the hook ----------------------------------------------------------

class ShardSnapshotHook(Hook):
    """Periodic + final shard-set save (SnapshotHook's shape, the shard
    store's format).  An OSError that survives the bounded retries is
    logged + counted, never raised — losing one snapshot interval is
    recoverable by design; killing the run here is not."""

    def __init__(self, store: ShardStore, every: int = 1,
                 cursor: dict | None = None):
        self._store = store
        self._due = _EveryN(every)
        self._cursor = dict(cursor or {})
        self._last_saved: int | None = None

    def begin(self, loop) -> None:
        self._due = _EveryN(self._due._every, int(loop.start_step))
        self._last_saved = None

    def _save(self, state) -> bool:
        step = int(state.step)
        try:
            self._store.save(state,
                             cursor={**self._cursor, "step": step})
            return True
        except OSError as e:
            _SAVE_FAILURES.inc()
            _log(f"shard save at step {step} failed ({e}) — continuing; "
                 f"the newest quorum-valid set on disk is unchanged and "
                 f"the next interval retries")
            return False

    def after_step(self, step, state, metrics) -> bool:
        if self._due(step) and self._save(state):
            self._last_saved = int(state.step)
        return False

    def end(self, state) -> None:
        if int(state.step) == self._last_saved:
            return
        self._save(state)
