"""resilience/ — fault injection + crash-consistent snapshots + supervised
recovery (the subsystem the rounds-3-5 outage said this repo needed).

Three cooperating pieces, each usable alone:

- :mod:`.faults` — deterministic, seed-addressable fault plans injected at
  train-loop boundaries and into the batch stream (preemption, wedged
  dispatch, NaN loss, corrupted uint8 batch, torn checkpoint write).
- :mod:`.snapshot` — atomic write-tmp/fsync/rename snapshots with a
  manifest (step, optimizer state, RNG key, dataset cursor, crc32), so a
  resume is bitwise-identical to an uninterrupted run and a torn write is
  detected and discarded instead of restored.
- :mod:`.shardstore` — the row-layout (zero1/zero3) twin: per-rank
  shard files under a sha256 quorum manifest, ring-mirror redundancy
  (``SNAPSHOT_REDUNDANCY``), reconstruction of any lost/corrupt shard
  within redundancy, and the ``apply_update_layout``-backed ELASTIC
  restore that regroups a D=4 shard set bitwise onto a D=2/D=8 mesh.
- :mod:`.supervisor` — runs any entrypoint under a heartbeat watchdog
  with exponential backoff + jitter, bounded retries, and a journaled
  priority task queue that survives the supervisor's own death.
- :mod:`.fleet` — gang supervision over N-process clusters: per-rank
  heartbeats, whole-gang teardown on any rank loss, and gang restarts
  from the maximum common valid snapshot step (the resume-step
  agreement that keeps a restarted fleet bitwise-consistent).
- :mod:`.scheduler` — the control plane over all of it: a journaled
  multi-job queue admitted against measured cost, packed onto the
  device mesh, with elastic shrink/grow-on-recovery and loss-free
  SLO preemption as policy (tools/schedule.py).
- :mod:`.remediate` — the self-healing layer: anomaly detections
  (health.json flags, ledger rows, serve_* scrapes) mapped through
  declared, rate-limited policies onto the actuators above —
  guardrailed (flap damping, cooldowns, a global action budget,
  dry-run), write-ahead journaled, every decision a ``heal_*`` ledger
  row (tools/heal_drill.py measures MTTD/MTTR per fault class).

Everything here runs on CPU — the outage this subsystem exists for can
never block its own tests.
"""

from distributedtensorflowexample_tpu.resilience.faults import (  # noqa: F401
    FAULT_KINDS, FaultInjectionHook, FaultPlan, FaultSpec, FaultyBatches,
    MetricsTapeHook, NaNGuardHook, mark_host_down, tear_journal)
from distributedtensorflowexample_tpu.resilience.fleet import (  # noqa: F401
    FleetSupervisor, GangResult, RankLossRefused,
    RankLossStructurallyIllegal, RankLostError)
from distributedtensorflowexample_tpu.resilience.remediate import (  # noqa: F401
    HEAL_EVENTS, AnomalyEvent, FleetTarget, Guardrails, HealRule,
    HealthWatcher, LedgerWatcher, Remediator, ServeWatcher,
    make_evict_actuator, make_quarantine_actuator,
    make_rollback_actuator, make_slo_actuator, run_remediated)
from distributedtensorflowexample_tpu.resilience.scheduler import (  # noqa: F401
    Job, Scheduler, load_queue)
from distributedtensorflowexample_tpu.resilience.shardstore import (  # noqa: F401
    ShardLayout, ShardSnapshotHook, ShardStore, quorum_valid_steps)
from distributedtensorflowexample_tpu.resilience.snapshot import (  # noqa: F401
    SnapshotHook, SnapshotStore, newest_common_step, valid_steps)
from distributedtensorflowexample_tpu.resilience.supervisor import (  # noqa: F401
    RetryPolicy, SupervisedResult, Supervisor, Task, TaskQueue)
