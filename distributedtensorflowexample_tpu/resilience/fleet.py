"""Gang supervision: fleet-level fault tolerance for multi-process runs.

The single-child supervisor (resilience/supervisor.py) restarts ONE
process; the paper's whole subject is a cluster of them — PS/worker
``ClusterSpec`` processes whose failure TF-Replicator (arXiv:1902.00465)
and TensorFlow (arXiv:1605.08695) both treat as a CLUSTER-level event:
detect, tear down the gang, restart from a mutually consistent
checkpoint.  This module is that layer.

State machine (one "gang attempt" = one co-scheduled launch of all
surviving ranks)::

    launch gang (rank r: own process group; env: TF_CONFIG via
      cluster.tf_config_env, OBS_RANK=r, FLEET_NUM_RANKS,
      SUPERVISE_ATTEMPT=a, SUPERVISE_HEARTBEAT=<per-rank beat file>,
      FLEET_RESUME_STEP=<agreed step, once an agreement pass ran>)
      └─ monitor: per-rank exit | per-rank heartbeat age | wall clock
           ├─ all ranks rc 0            → ok
           ├─ all ranks rc ∈ {0, 143},
           │   some 143                 → clean preemption: gang
           │                              restarts NOW, exempt from the
           │                              retry budget (MAX_PREEMPTIONS
           │                              backstop only)
           ├─ any rank rc 3             → backend wedged → STOP
           ├─ any rank crashes/killed   → TEAR DOWN THE WHOLE GANG
           │                              (TERM-grace-KILL per process
           │                              group), budgeted gang restart
           ├─ a rank's heartbeat stale  → same teardown ("wedged rank")
           ├─ some ranks 143 but others
           │   still running past the
           │   preempt grace            → "preempt divergence": the gang
           │                              lost a member cleanly but NOT
           │                              unanimously — budgeted restart
           └─ spawn fails (OSError)     → rank permanently LOST: named
                                          error (see below), never a
                                          silent shrink

Resume-step agreement (the restart half): each rank snapshots
independently (resilience/snapshot.py), so after an unclean gang death
the per-rank newest steps diverge — the killed rank stopped at k, a
survivor ran to k+m before teardown, a torn final write fails
validation.  Before every relaunch the fleet reads every rank's
manifests, takes the **maximum common valid step**
(``snapshot.newest_common_step``), DISCARDS every newer snapshot on
every rank (``SnapshotStore.discard_newer`` — an abandoned timeline
must not poison the next recovery), and exports the agreed step as
``FLEET_RESUME_STEP`` to every child, so all ranks resume the same
global step and the resumed run is bitwise-identical to an
uninterrupted one.  No common step → ``FLEET_RESUME_STEP=0`` (fresh
start, all snapshots discarded).

Rank-loss taxonomy — a host that cannot be respawned degrades LOUDLY:

- :class:`RankLossStructurallyIllegal` when the run's state is
  worker-tiled (``sync_mode=async``): the leading worker axis is
  structural (trainers/common.py refuses the same restore by name), so
  restarting with fewer workers is not a degraded run, it is a
  DIFFERENT program.
- :class:`RankLossRefused` when fewer workers would be legal
  (sync-replicated state) but ``elastic`` was not requested: silently
  shrinking changes the global batch and the data order mid-training.
- with ``elastic=True`` (and replicated state) the fleet drops the
  lost rank, rebuilds TF_CONFIG from the survivors, and restarts the
  gang through the normal budgeted path.

Online health (round 10, obs/anomaly.py): each rank's AnomalyHook
writes a per-rank ``health.json`` (this fleet exports ``OBS_HEALTH``
per child); the monitor loop reads them on a ~0.5 s cadence, runs the
cross-rank skew/straggler pass (:func:`obs.anomaly.detect_skew`), and
surfaces detections as gauges (``fleet_rank_step``,
``fleet_step_skew_steps``), journal ``anomaly`` annotations, an
aggregate fleet ``health.json``, and a flight dump on a new straggler.
DETECTION ONLY — nothing it finds feeds the restart state machine.

Everything here is CPU-testable with real OS processes — the same
two-process pattern tests/test_multihost.py uses, no TPU required.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import shutil
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.request

from distributedtensorflowexample_tpu.cluster import tf_config_env
from distributedtensorflowexample_tpu.obs import anomaly as obs_anomaly
from distributedtensorflowexample_tpu.obs import ledger as obs_ledger
from distributedtensorflowexample_tpu.obs import metrics as obs_metrics
from distributedtensorflowexample_tpu.obs import recorder as obs_recorder
from distributedtensorflowexample_tpu.obs import trace as obs_trace
from distributedtensorflowexample_tpu.resilience.supervisor import (
    MAX_PREEMPTIONS, RC_PREEMPTED, RC_WEDGED, Journal, RetryPolicy,
    Supervisor, export_prometheus_collector)
from distributedtensorflowexample_tpu.utils.signals import (
    installed_signal_handler)


def _log(msg: str) -> None:
    print(f"fleet: {msg}", file=sys.stderr, flush=True)

# Fleet-level telemetry: the counters the ISSUE names, plus per-rank
# exit/heartbeat detail — what a fleet operator greps OBS_PROM_DIR for.
_GANG_RESTARTS = obs_metrics.counter(
    "fleet_gang_restarts_total",
    "whole-gang teardown+relaunch cycles (crash-budgeted and preempted)")
_RANKS_LOST = obs_metrics.counter(
    "fleet_ranks_lost_total", "ranks whose host could not be respawned")
_RANKS_RECOVERED = obs_metrics.counter(
    "fleet_ranks_recovered_total",
    "previously lost ranks re-added by a recovery re-probe")
_AGREEMENTS = obs_metrics.counter(
    "fleet_resume_step_agreements_total",
    "resume-step agreement passes run before a gang relaunch")
_RANK_EXITS = obs_metrics.counter(
    "fleet_rank_exits_total", "per-rank attempt outcomes, by rank and class")
_KILLS = obs_metrics.counter(
    "fleet_kills_total", "gang teardowns, by reason")
_HB_AGE = obs_metrics.gauge(
    "fleet_rank_heartbeat_age_seconds",
    "age of each live rank's newest heartbeat at the last poll")
_RANK_STEP = obs_metrics.gauge(
    "fleet_rank_step", "each rank's last health-reported step")
_SKEW = obs_metrics.gauge(
    "fleet_step_skew_steps",
    "max step lag between the front rank and the rest (health reports)")
_STRAGGLERS = obs_metrics.counter(
    "fleet_stragglers_detected_total",
    "straggler detections (lagging rank with slowness evidence), by rank")


class RankLostError(RuntimeError):
    """A rank's host is permanently gone (its respawn failed)."""

    def __init__(self, rank: int, attempt: int, cause: str, msg: str):
        self.rank = rank
        self.attempt = attempt
        self.cause = cause
        super().__init__(msg)


class RankLossStructurallyIllegal(RankLostError):
    """Fewer workers would change the STATE LAYOUT, not just the speed:
    async local-SGD state is worker-tiled (leading axis = num_workers —
    the same topology fact trainers/common.py refuses to restore across
    by name), so a shrunken gang cannot load any surviving snapshot."""

    def __init__(self, rank: int, attempt: int, cause: str):
        super().__init__(rank, attempt, cause, (
            f"rank {rank} permanently lost at gang attempt {attempt} "
            f"({cause}) and this run's state is worker-tiled "
            f"(sync_mode=async): the leading worker axis is structural "
            f"— restarting with fewer workers is ILLEGAL, not degraded "
            f"(see trainers/common.py's num_workers restore refusal). "
            f"Re-provision the host, or start fresh on the smaller "
            f"fleet with a new workdir"))


class RankLossRefused(RankLostError):
    """Fewer workers would be legal (sync-replicated state restores
    across mesh sizes) but was not requested: a silent shrink changes
    the global batch and the data order mid-training."""

    def __init__(self, rank: int, attempt: int, cause: str):
        super().__init__(rank, attempt, cause, (
            f"rank {rank} permanently lost at gang attempt {attempt} "
            f"({cause}); sync-replicated state COULD legally continue "
            f"on fewer workers, but that silently changes the global "
            f"batch and the data order mid-training — refused without "
            f"--elastic"))


@dataclasses.dataclass
class GangResult:
    status: str                  # ok | exhausted | wedged | terminated
                                 # | evicted (request_stop — no restart)
    gang_attempts: int           # launches, including the first
    restarts: int                # teardown+relaunch cycles (all causes)
    preemptions: int             # clean unanimous-143 restarts (exempt)
    agreed_steps: list           # agreement outcomes, in relaunch order
    last_rcs: dict               # rank -> rc of the final gang attempt
    ranks: list                  # surviving rank ids
    reasons: list[str] = dataclasses.field(default_factory=list)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _classify(rc: int | None) -> str:
    if rc == 0:
        return "ok"
    if rc == RC_PREEMPTED:
        return "preempted"
    if rc == RC_WEDGED:
        return "wedged"
    if rc is None or rc < 0:
        return "killed"
    return "crash"


def resolve_ledger_dest(configured: str) -> str:
    """The ONE run-ledger destination rule, shared by the fleet's own
    rows, the env each rank inherits (spawn uses ``env.setdefault``),
    and every layer that must watch the same file (the ``--heal``
    remediator): an operator's box-wide ``OBS_LEDGER`` export wins over
    ``configured``, and a PRESENT-but-empty export is "set to disabled"
    (``setdefault`` skips a present key; ``maybe_begin`` treats "" as
    no ledger) — never a fall-through to the default.  One drill must
    land in ONE file; rows split across two files would show half the
    story to either reader."""
    if "OBS_LEDGER" in os.environ:
        return os.environ["OBS_LEDGER"]
    return configured


class FleetSupervisor:
    """Launch and babysit an N-rank gang; see the module docstring for
    the state machine.  ``workdir`` holds per-rank heartbeat files and
    stderr logs; ``worker_tiled``/``elastic`` select the rank-loss
    reaction."""

    # How long a rank's failed /health scrape keeps the monitor off its
    # endpoint (file fallback continues) — see _read_rank_health.
    _HTTP_BACKOFF_S = 5.0

    def __init__(self, num_ranks: int,
                 policy: RetryPolicy | None = None,
                 journal: Journal | None = None,
                 heartbeat_timeout_s: float = 0.0,
                 wall_timeout_s: float = 0.0,
                 kill_grace_s: float = 10.0,
                 poll_s: float = 0.1,
                 preempt_grace_s: float = 30.0,
                 seed: int | None = None,
                 elastic: bool = False,
                 worker_tiled: bool = False,
                 workdir: str = "/tmp/fleet",
                 health_path: str | None = None,
                 skew_lag_steps: int = 3,
                 skew_time_ratio: float = 4.0,
                 ledger_path: str | None = None,
                 http: bool = False,
                 http_timeout_s: float = 0.25,
                 reprobe_on_relaunch: bool = True):
        if num_ranks < 1:
            raise ValueError(f"num_ranks {num_ranks} must be >= 1")
        self.num_ranks = num_ranks
        self.ranks = list(range(num_ranks))     # survivors, original ids
        self.policy = policy or RetryPolicy()
        self.journal = journal or Journal(None)
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.wall_timeout_s = wall_timeout_s
        self.kill_grace_s = kill_grace_s
        self.poll_s = poll_s
        self.preempt_grace_s = preempt_grace_s
        self.elastic = elastic
        self.worker_tiled = worker_tiled
        # A standalone fleet regrows itself before every elastic
        # relaunch; under the scheduler this is False — regrowing
        # consumes mesh devices the scheduler may have backfilled, so
        # only its capacity-gated grow policy may widen the gang.
        self.reprobe_on_relaunch = reprobe_on_relaunch
        self.workdir = os.path.abspath(workdir)
        os.makedirs(self.workdir, exist_ok=True)
        # Fleet-level health.json (obs/anomaly.py contract): None means
        # the workdir default; "" disables the aggregate write (the
        # per-rank reads still feed gauges + journal annotations).
        self.health_path = (os.path.join(self.workdir, "health.json")
                            if health_path is None else health_path)
        self.skew_lag_steps = skew_lag_steps
        self.skew_time_ratio = skew_time_ratio
        # Skew needs step DELTAS between polls, not just positions:
        # reading per-rank health more often than it changes is wasted
        # IO, and the detection-latency bound the drill asserts (<= 3
        # steps of a 0.25 s/step straggler) only needs ~0.5 s cadence.
        self._health_poll_s = max(poll_s, 0.5)
        self._rng = random.Random(seed)
        # Run ledger (obs/ledger.py): exported to every rank (each
        # child writes its own run rows there) and written by the fleet
        # itself (gang rows + the resume_agreement annotation) — one
        # RUNS.jsonl holding the whole drill, queryable with
        # tools/obs_query.py.  None = the workdir default; "" disables.
        self.ledger_path = (os.path.join(self.workdir, "RUNS.jsonl")
                            if ledger_path is None else ledger_path)
        # Live scrape (obs/serve.py): with http=True each rank gets an
        # OBS_HTTP_PORT export and the monitor pass prefers scraping
        # /health over reading the per-rank file — the file stays as
        # the fallback, so a rank whose server never bound (port taken,
        # child predates the contract) degrades to exactly the old
        # behavior instead of going dark.
        self.http = http
        self.http_timeout_s = http_timeout_s
        self._http_ports: dict[int, int] = (
            {r: _free_port() for r in range(num_ranks)} if http else {})
        self._scrape_logged: set = set()
        self._http_backoff: dict[int, float] = {}
        # This fleet invocation's ledger disambiguator (see _gang_run).
        self._fleet_run_id = (f"{int(obs_metrics._wall() * 1000):x}"
                              f"-{os.getpid()}")
        # Scheduler-driven clean stop (tools/schedule.py SLO preemption):
        # request_stop() sets this and the monitor loop tears the gang
        # down through the same TERM-grace-KILL path a platform
        # preemption takes — every rank saves and exits 143 — but run()
        # returns "evicted" instead of restarting.
        self._stop = threading.Event()
        self._stop_reason = "evicted"
        # Original rank ids whose host is permanently gone (elastic
        # shrink path); the recovery re-probe re-adds them when their
        # host answers again — see probe_lost_ranks/reprobe_lost_ranks.
        self._lost: set[int] = set()
        # Straggler/flag latches — reset per gang attempt in _run_gang,
        # initialized here so the `stragglers` property (read by the
        # scheduler's heal policy from its tick thread) is safe before
        # the first attempt launches.
        self._stragglers: set = set()
        self._flagged: set = set()
        # One port per ORIGINAL rank, chosen once: a gang restart reuses
        # the same coordinator address, like a real re-scheduled job
        # whose hosts keep their endpoints.
        self._ports = [_free_port() for _ in range(num_ranks)]

    def _ledger_dest(self) -> str:
        return resolve_ledger_dest(self.ledger_path)

    def _ledger_event(self, event: str, **fields) -> None:
        dest = self._ledger_dest()
        if dest:
            obs_ledger.log_event(event, path=dest, src="fleet", **fields)

    def _gang_run(self, name: str, attempt: int) -> str:
        """Gang row id, unique ACROSS fleet invocations: the ledger is
        append-only and may hold months of drills against one workdir,
        and two drills both keyed ``gang:train:a0`` would silently fold
        into one run on read (the second drill's outcome replacing the
        first's).  Same wall-ms+pid disambiguation RunLedger ids use."""
        return f"gang:{name}:{self._fleet_run_id}:a{attempt}"

    # --- per-rank plumbing ------------------------------------------------
    @staticmethod
    def _sub(argv: list[str], rank: int, num_ranks: int) -> list[str]:
        """Substitute ``{rank}``/``{num_ranks}`` in child argv tokens —
        how ONE command line fans out to per-rank workdirs/flags
        (plain str.replace, not str.format: a child argv may carry
        braces of its own, e.g. inline JSON)."""
        return [t.replace("{rank}", str(rank))
                 .replace("{num_ranks}", str(num_ranks)) for t in argv]

    def _hb_path(self, rank: int) -> str:
        return os.path.join(self.workdir, f"hb_rank{rank}")

    def _health_path(self, rank: int) -> str:
        return os.path.join(self.workdir, f"health_rank{rank}.json")

    def _spawn_rank(self, rank: int, index: int, hosts: list[str],
                    argv: list[str], name: str, attempt: int,
                    agreed: int | None, stdout_dir: str | None,
                    env_extra: dict | None) -> subprocess.Popen:
        # The host-loss seam: a fresh tombstone for this rank means its
        # host is down, and the spawn fails with the SAME OSError shape
        # a missing/unexecable binary produces — one rank-lost path for
        # the real failure and the drillable one (faults.py host_loss).
        if self.host_down(rank):
            raise OSError(
                f"rank {rank} host is down (tombstone "
                f"{self._host_down_path(rank)})")
        env = dict(os.environ)
        env["TF_CONFIG"] = tf_config_env(hosts, index)
        env["OBS_RANK"] = str(rank)
        env["FLEET_NUM_RANKS"] = str(len(self.ranks))
        env["SUPERVISE_ATTEMPT"] = str(attempt)
        env.setdefault("OBS_PHASE", name)
        if agreed is not None:
            # Only once an agreement pass ran: the FIRST launch has
            # nothing to agree on (fresh stores), and a child seeing no
            # export restores its own newest — which is then provably
            # common, because nothing has diverged yet.
            env["FLEET_RESUME_STEP"] = str(agreed)
        else:
            # Scrubbed, not inherited: a stale export leaking in from
            # the FLEET's environment (a prior drill's shell, an outer
            # harness) would pin every first-attempt child to a step
            # its fresh store cannot prove — a gang-wide crash loop.
            env.pop("FLEET_RESUME_STEP", None)
        hb = self._hb_path(rank)
        try:
            # Same stale-mtime reset as the single-child supervisor: a
            # beat file from the previous attempt would read as an
            # instant wedge.
            os.remove(hb)
        except OSError:
            pass
        env["SUPERVISE_HEARTBEAT"] = hb
        # Per-rank health.json (training/hooks.AnomalyHook writes it,
        # this fleet's monitor reads it) — always per-rank, never an
        # inherited OBS_HEALTH: N ranks sharing one operator-exported
        # path would overwrite each other's reports.  Stale-file reset
        # for the same reason as the beat: a previous attempt's report
        # would read as an instant regression/skew.
        hp = self._health_path(rank)
        try:
            os.remove(hp)
        except OSError:
            pass
        env["OBS_HEALTH"] = hp
        # The faults.py host_loss seam: the child writes THIS tombstone
        # (then SIGKILLs itself), and the next spawn of this rank fails
        # with the spawn-OSError above — a host loss, drillable from a
        # FaultPlan like every other fault.
        env["FLEET_HOST_DOWN_FILE"] = self._host_down_path(rank)
        if self.ledger_path:
            # setdefault: an operator pointing the whole fleet at one
            # box-wide ledger (their own OBS_LEDGER export) wins.
            env.setdefault("OBS_LEDGER", self.ledger_path)
        if self.http:
            env["OBS_HTTP_PORT"] = str(self._http_ports[rank])
            # Say where each rank serves: the whole point is an
            # operator curling it mid-run.
            _log(f"rank {rank} scrape endpoint: "
                 f"http://127.0.0.1:{self._http_ports[rank]} "
                 f"(/metrics /health /flight /ledger/tail)")
        if self.heartbeat_timeout_s:
            env["SUPERVISE_HEARTBEAT_TIMEOUT_S"] = str(
                self.heartbeat_timeout_s)
        if self.journal.path:
            env.setdefault("SUPERVISE_JOURNAL", self.journal.path)
        if env_extra:
            env.update(env_extra)
        # Write-ahead half of the spawn record: a SIGKILL landing
        # between Popen and the pid row below would otherwise leave an
        # orphan no sweep can find; the intent at least makes the gap
        # visible to the sweeper (which warns — it cannot kill a pid it
        # never learned).
        self.journal.write("rank_spawn_intent", task=name,
                           attempt=attempt, rank=rank)
        out = err = None
        try:
            # stderr appends across attempts (one log per rank, like the
            # supervisor's `2>> $LOG`); stdout is per-attempt — a gang
            # drill needs EVERY attempt's JSON tail, not just the last.
            err = open(os.path.join(self.workdir, f"rank{rank}.log"), "ab")
            if stdout_dir:
                os.makedirs(stdout_dir, exist_ok=True)
                out = open(os.path.join(
                    stdout_dir, f"rank{rank}_attempt{attempt}.out"), "wb")
            # {num_ranks} reflects the LIVE gang size (an elastic
            # restart shrank it — or a recovery re-probe grew it back),
            # matching the FLEET_NUM_RANKS and TF_CONFIG this same
            # spawn exports — a child sharding by the substituted value
            # must divide by the ranks that actually exist.
            proc = subprocess.Popen(
                self._sub(argv, rank, len(self.ranks)), env=env,
                stdout=out or err, stderr=err, start_new_session=True)
        finally:
            # Popen dup'd the fds (or raised); ours must not leak.
            for f in (out, err):
                if f is not None:
                    f.close()
        # The pid lands in the journal so an OUTER control plane
        # (tools/schedule.py) that died with this gang still running can
        # sweep the orphaned process groups on restart — a spawned rank
        # with no matching rank_exit is exactly that orphan.
        self.journal.write("rank_spawn", task=name, attempt=attempt,
                           rank=rank, pid=proc.pid)
        return proc

    # --- host-loss seam + recovery re-probe -------------------------------
    def _host_down_path(self, rank: int) -> str:
        return os.path.join(self.workdir, f"host_down_rank{rank}")

    def host_down(self, rank: int) -> bool:
        """Is this rank's host tombstoned?  The tombstone is a JSON file
        the host_loss fault (resilience/faults.py) writes before the
        process SIGKILLs itself: ``down_s`` > 0 means the host comes
        back after that long (the tombstone self-expires and is
        removed); 0 means down until an operator removes the file.
        Unlike the per-spawn heartbeat/health resets, the tombstone
        deliberately SURVIVES across FleetSupervisor incarnations — a
        re-scheduled job must still see a dead host dead."""
        path = self._host_down_path(rank)
        try:
            with open(path) as f:
                rec = json.load(f)
        except OSError:
            return False
        except ValueError:
            # Half-written tombstone: the host died mid-declaring its
            # own death — still a dead host, not a healthy one.
            return True
        down_s = float(rec.get("down_s") or 0.0)
        if down_s > 0 and obs_metrics._wall() - float(rec.get("ts")
                                                     or 0.0) >= down_s:
            try:
                os.remove(path)
            except OSError:
                pass
            return False
        return True

    @property
    def stragglers(self) -> list[int]:
        """Ranks the CURRENT gang attempt's monitor pass has named
        straggler (lag + slowness evidence, obs/anomaly.detect_skew) —
        what the remediation policy layer (resilience/remediate.py,
        the scheduler's heal pass) reads.  Cross-thread like
        ``lost_ranks``: the writer publishes copy-on-write (rebind,
        never in-place mutation), so this read iterates a set that can
        no longer change size under it."""
        return sorted(self._stragglers)

    @property
    def lost_ranks(self) -> list[int]:
        """Original rank ids dropped by the elastic shrink path and not
        yet recovered — what the scheduler's grow policy watches.  Read
        from the scheduler's tick thread while the fleet's run thread
        updates it — copy-on-write on the writer side, like
        ``stragglers``."""
        return sorted(self._lost)

    def probe_lost_ranks(self, argv: list[str]) -> list[int]:
        """Non-mutating recovery probe: which lost ranks could spawn
        again NOW — no fresh tombstone, and the rank's substituted
        program resolves to something executable (the exact precondition
        of the spawn whose OSError lost the rank).  The scheduler polls
        this each tick to drive grow-on-recovery (cross-thread — hence
        the snapshot copy); the fleet's own retry loop calls the
        mutating half before every elastic relaunch."""
        out = []
        for r in self.lost_ranks:
            if self.host_down(r):
                continue
            prog = self._sub(argv, r, self.num_ranks)[0] if argv else ""
            if not prog or shutil.which(prog) is None:
                continue
            out.append(r)
        return out

    def reprobe_lost_ranks(self, argv: list[str],
                           name: str = "") -> list[int]:
        """The recovery re-probe hook (mutating half): re-add every
        lost rank whose host answers again, restoring the gang — and
        the ``{num_ranks}`` substitution — to full width on the next
        relaunch.  Journaled per rank (``rank_recovered``) and counted,
        so a postmortem shows the shrink AND the grow."""
        recovered = self.probe_lost_ranks(argv)
        for r in recovered:
            self._lost = self._lost - {r}
            self.ranks.append(r)
            self.ranks.sort()
            _RANKS_RECOVERED.inc()
            self.journal.write("rank_recovered", task=name, rank=r,
                               ranks=list(self.ranks))
            self._ledger_event("rank_recovered", task=name, rank=r,
                               ranks=list(self.ranks))
            _log(f"{name}: rank {r} host answered the recovery re-probe "
                 f"— gang grows back to ranks {self.ranks}")
        return recovered

    def request_stop(self, reason: str = "evicted") -> None:
        """Scheduler-driven clean preemption: the monitor loop tears the
        gang down through the normal TERM-grace-KILL escalation (every
        rank's SIGTERM handler saves and exits 143) and ``run()``
        returns status ``evicted`` WITHOUT restarting — the caller
        (tools/schedule.py) requeues the job, and its next launch
        resumes from the snapshots this stop produced.  Thread-safe:
        the scheduler calls it from outside the fleet's run thread."""
        self._stop_reason = reason
        self._stop.set()

    # --- gang teardown ----------------------------------------------------
    def _teardown(self, procs: dict, exited: dict, why: str, name: str,
                  attempt: int, rank: int | None = None) -> None:
        """One rank's failure is a GANG event: TERM every live rank's
        process group in parallel, give them one shared grace window
        (cooperative trainers save + exit 143 inside it), then KILL the
        stragglers — the supervisor's TERM-grace-KILL escalation, fanned
        out so N ranks pay one grace, not N."""
        _KILLS.labels(why=why).inc()
        self.journal.write(
            "gang_teardown", task=name, attempt=attempt, why=why,
            **({"rank": rank} if rank is not None else {}))
        live = [(r, p) for r, p in procs.items() if r not in exited]
        for _, p in live:
            try:
                os.killpg(p.pid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
        deadline = time.monotonic() + self.kill_grace_s
        for r, p in live:
            try:
                p.wait(timeout=max(0.0, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(p.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
                p.wait()
            exited[r] = p.returncode
            self.journal.write("rank_exit", task=name, attempt=attempt,
                               rank=r, rc=p.returncode, reason="teardown")
            _RANK_EXITS.labels(rank=r, outcome="torn_down").inc()
        # The fleet is the informed survivor here (a wedged rank can't
        # dump its own flight); non-terminal so atexit still refreshes.
        obs_recorder.dump_global(f"gang_teardown_{why}", final=False)

    # --- online anomaly monitoring (detection ONLY) -----------------------
    def _read_rank_health(self, rank: int, name: str,
                          attempt: int) -> dict | None:
        """One rank's health payload: HTTP scrape of the rank's
        ``/health`` endpoint first (obs/serve.py, when this fleet
        exported a port), the per-rank file as the fallback.  The first
        read per (rank, mode) per gang attempt journals a
        ``health_scrape`` event, so a postmortem can prove which
        transport the monitor actually used — and see a fallback happen.
        Detection-only contract unchanged: every failure degrades to
        the file, and a missing file is still just None.  A rank whose
        scrape just failed is skipped for ``_HTTP_BACKOFF_S``: these
        urlopens are SERIAL inside the monitor loop, and N wedged-but-
        bound endpoints each eating the full timeout would stall
        rank-exit/SIGTERM polling by N x timeout per pass — exactly
        when the fleet is unhealthy."""
        port = self._http_ports.get(rank) if self.http else None
        if port and time.monotonic() >= self._http_backoff.get(rank, 0.0):
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/health",
                        timeout=self.http_timeout_s) as resp:
                    payload = json.loads(resp.read().decode())
                if isinstance(payload, dict):
                    self._http_backoff.pop(rank, None)
                    if (rank, "http") not in self._scrape_logged:
                        self._scrape_logged.add((rank, "http"))
                        self.journal.write("health_scrape", task=name,
                                           attempt=attempt, rank=rank,
                                           mode="http", port=port)
                    return payload
                # Parseable-but-not-ours (a squatter on the rank's
                # pre-allocated port answering arrays): a failure for
                # backoff purposes too, or every pass re-pays the
                # round-trip the backoff exists to avoid.
                self._http_backoff[rank] = (time.monotonic()
                                            + self._HTTP_BACKOFF_S)
            except Exception:
                # Not bound yet / child gone / wedged: fall back, and
                # give this rank's endpoint a breather before retrying.
                self._http_backoff[rank] = (time.monotonic()
                                            + self._HTTP_BACKOFF_S)
        payload = obs_anomaly.read_health(self._health_path(rank))
        if payload is not None \
                and (rank, "file") not in self._scrape_logged:
            self._scrape_logged.add((rank, "file"))
            self.journal.write("health_scrape", task=name,
                               attempt=attempt, rank=rank, mode="file")
        return payload

    def _stale_beat_span(self, rank: int, now: float) -> float | None:
        """A live rank's no-beat span, reported ONLY when it is stale
        relative to that rank's OWN observed beat cadence (the longest
        mtime-to-mtime gap this monitor has seen, fleet-clocked).  Raw
        heartbeat age is NOT slowness evidence: production trainers beat
        every ~64 steps (trainers/common.py), so a healthy rank's age at
        a random poll is uniform in [0, 64 x step] — far over any
        step-time multiple.  A span > skew_time_ratio x the rank's own
        cadence, while the beat file sits unchanged, is a genuine stall
        (the wedged-but-alive shape).  Needs one observed beat interval
        to calibrate; until then returns None — no evidence, never a
        guess."""
        try:
            mtime = os.path.getmtime(self._hb_path(rank))
        except OSError:
            return None
        prev = self._beat_obs.get(rank)
        if prev is None or mtime != prev[0]:
            interval = prev[2] if prev else None
            if prev is not None:
                seen = now - prev[1]
                interval = max(interval or 0.0, seen)
            self._beat_obs[rank] = (mtime, now, interval)
            return None
        frozen = now - prev[1]
        if prev[2] and frozen > self.skew_time_ratio * prev[2]:
            return round(frozen, 3)
        return None

    def _poll_health(self, name: str, attempt: int, ranks_all: list,
                     exited=()) -> None:
        """Read every live rank's health.json (obs/anomaly.py, written
        by training/hooks.AnomalyHook under the OBS_HEALTH this fleet
        exported), run the cross-rank skew/straggler pass, and surface
        what it finds — gauges, journal ``anomaly`` annotations, an
        aggregate fleet health.json, and a flight dump on a NEW
        straggler.  Detection only, by design: nothing here feeds the
        restart state machine — a false positive must cost a log line,
        never a teardown."""
        now = time.monotonic()
        if now - self._health_polled_t < self._health_poll_s:
            return
        self._health_polled_t = now
        ranks: dict = {}
        payloads: dict = {}
        # ALL ranks of the attempt, not just the live ones: these
        # drills' children don't rendezvous, so a fast rank can finish
        # while the straggler crawls on — its final health report is
        # exactly the "front of the fleet" the skew pass measures
        # against (and a finished rank can never be flagged itself:
        # lagging requires trailing the front).
        for r in ranks_all:
            payload = self._read_rank_health(r, name, attempt)
            if payload is None:
                continue
            payloads[r] = payload
            det = (payload.get("detectors") or {}).get("step_time") or {}
            flags = payload.get("flags") or {}
            if r in exited:
                # A finished rank's beat stops BECAUSE it exited —
                # staleness is not slowness evidence, and a cleanly
                # preempted rank must not be named straggler while the
                # others drain.  Its frozen report still serves as the
                # front/lag datum above.
                hb_age = None
            else:
                hb_age = self._stale_beat_span(r, now)
            ranks[r] = {
                "step": payload.get("step"),
                "step_time_s": det.get("ewma_s"),
                "regression_firing": (flags.get("step_time_regression")
                                      or {}).get("firing"),
                "hb_age_s": hb_age}
            if payload.get("step") is not None:
                _RANK_STEP.labels(rank=r).set(payload["step"])
            # Per-rank detector firings annotate the journal ONCE per
            # (rank, kind) per gang attempt — the postmortem's "rank 1
            # saw nan_loss at step 7" line, next to the lifecycle
            # events it explains.
            for kind, f in flags.items():
                # Latched fired_step, not the live firing flag: a
                # transient firing (z decays in ~0.2 s) between 0.5 s
                # polls must still annotate the journal — the same
                # fired-or-firing read obs_report renders.
                if (f.get("firing") or f.get("fired_step") is not None) \
                        and (r, kind) not in self._flagged:
                    self._flagged.add((r, kind))
                    obs_anomaly.FLAGS_TOTAL.labels(kind=kind,
                                                   rank=r).inc()
                    self.journal.write(
                        "anomaly", task=name, attempt=attempt, rank=r,
                        kind=kind, fired_step=f.get("fired_step"))
                    # Mirrored into the run ledger so the remediation
                    # layer (and obs_query) can consume detections
                    # without the fleet's private journal.
                    self._ledger_event(
                        "anomaly", task=name, attempt=attempt, rank=r,
                        kind=kind, fired_step=f.get("fired_step"))
        skew = obs_anomaly.detect_skew(ranks,
                                       lag_steps=self.skew_lag_steps,
                                       time_ratio=self.skew_time_ratio)
        if skew["lag_steps"]:
            _SKEW.set(max(skew["lag_steps"].values()))
        new = [r for r in skew["stragglers"] if r not in self._stragglers]
        if new:
            # Copy-on-write publish: the scheduler's tick thread reads
            # `stragglers` concurrently — an in-place .add() under its
            # iteration raises "set changed size during iteration";
            # rebinding an already-complete set is atomic.
            self._stragglers = self._stragglers | set(new)
        for r in new:
            _STRAGGLERS.labels(rank=r).inc()
            obs_anomaly.FLAGS_TOTAL.labels(kind="straggler", rank=r).inc()
            self.journal.write(
                "anomaly", task=name, attempt=attempt, rank=r,
                kind="straggler", step=ranks[r].get("step"),
                max_step=skew["max_step"], why=skew["why"].get(r))
            self._ledger_event(
                "anomaly", task=name, attempt=attempt, rank=r,
                kind="straggler", step=ranks[r].get("step"),
                max_step=skew["max_step"], why=skew["why"].get(r))
            _log(f"{name}: rank {r} straggling — {skew['why'].get(r)}")
        if self.health_path and payloads:
            obs_anomaly.write_health(self.health_path, {
                "version": obs_anomaly.HEALTH_VERSION, "kind": "fleet",
                "updated_unix": round(obs_metrics._wall(), 3),
                "attempt": attempt,
                "ranks": {str(r): p for r, p in sorted(payloads.items())},
                "skew": skew,
                "stragglers": sorted(self._stragglers),
                "flags_seen": sorted(f"rank{r}:{k}"
                                     for r, k in self._flagged)})
        if new:
            # The ring should cover the steps AROUND the detection, not
            # whatever the gang later dies on; non-terminal, like every
            # informed-survivor dump.
            obs_recorder.dump_global("straggler_detected", final=False)

    # --- one gang attempt -------------------------------------------------
    def _run_gang(self, argv: list[str], name: str, attempt: int,
                  agreed: int | None, stdout_dir: str | None,
                  env_extra: dict | None) -> tuple[str, str, dict]:
        """Returns (outcome, why, rcs): outcome one of ok | preempted |
        wedged | crash | terminated | rank_lost."""
        hosts = [f"127.0.0.1:{self._ports[r]}" for r in self.ranks]
        procs: dict[int, subprocess.Popen] = {}
        exited: dict[int, int | None] = {}
        if self._stop.is_set():
            # A stop that landed between gang attempts: don't launch a
            # gang just to tear it down one poll later.
            return ("evicted",
                    f"stop requested ({self._stop_reason}) before launch",
                    exited)
        sigterm_seen: list = []
        # Anomaly latches are per gang attempt: a restart is a new run
        # (fresh detectors in every child), so a prior attempt's
        # straggler must not suppress this attempt's journal line.
        self._stragglers: set = set()
        self._flagged: set = set()
        self._scrape_logged = set()     # (rank, transport) per attempt
        self._http_backoff = {}         # fresh children, fresh endpoints
        self._health_polled_t = -float("inf")
        self._beat_obs: dict = {}       # rank -> (mtime, seen_at, interval)
        # Stale-file reset, same reason as the per-rank files at spawn:
        # a previous run's aggregate in a reused workdir would render as
        # THIS run's stragglers (the monitor only rewrites it once some
        # rank reports health).
        if self.health_path:
            try:
                os.remove(self.health_path)
            except OSError:
                pass

        def _on_term(signum, frame):
            sigterm_seen.append(True)

        self.journal.write("gang_start", task=name, attempt=attempt,
                           ranks=list(self.ranks),
                           resume_step=agreed)
        # Gang-level ledger row (each rank writes its own run rows to
        # the same OBS_LEDGER this fleet exported): one row per gang
        # attempt, closed with the outcome in run()'s retry loop.
        self._ledger_event(
            "run_start", run=self._gang_run(name, attempt),
            entrypoint=name, attempt=attempt, ranks=list(self.ranks),
            resume_step=agreed)
        # The handler covers the SPAWN loop too: a SIGTERM landing
        # between two spawns must still reach the children already
        # launched into their own sessions — the default disposition
        # would kill the fleet and orphan them mid-gang.
        with installed_signal_handler(signal.SIGTERM, _on_term):
            for index, rank in enumerate(self.ranks):
                try:
                    procs[rank] = self._spawn_rank(
                        rank, index, hosts, argv, name, attempt, agreed,
                        stdout_dir, env_extra)
                except OSError as e:
                    # Permanently lost host: nothing at this rank's argv
                    # can even exec.  Tear down whatever already
                    # launched, then degrade LOUDLY per the taxonomy.
                    self._teardown(procs, exited, "rank_lost", name,
                                   attempt, rank=rank)
                    _RANKS_LOST.inc()
                    self.journal.write("rank_lost", task=name,
                                       attempt=attempt, rank=rank,
                                       error=str(e))
                    # Ledger mirror: host losses are remediation-layer
                    # input (repeated-offender quarantine policy) and
                    # must be consumable without the fleet journal.
                    self._ledger_event("rank_lost", task=name,
                                       attempt=attempt, rank=rank,
                                       error=str(e))
                    if self.worker_tiled:
                        raise RankLossStructurallyIllegal(rank, attempt,
                                                          str(e)) from e
                    if not self.elastic:
                        raise RankLossRefused(rank, attempt, str(e)) from e
                    self.ranks.remove(rank)
                    self._lost = self._lost | {rank}
                    if not self.ranks:
                        raise RankLossRefused(rank, attempt, str(e)) from e
                    _log(f"{name}: rank {rank} lost ({e}); elastic — "
                         f"continuing with ranks {self.ranks}")
                    return "rank_lost", f"rank {rank} lost: {e}", exited

            start = time.monotonic()
            first_143_t: float | None = None
            while True:
                for r, p in procs.items():
                    if r in exited:
                        continue
                    rc = p.poll()
                    if rc is not None:
                        exited[r] = rc
                        self.journal.write("rank_exit", task=name,
                                           attempt=attempt, rank=r, rc=rc)
                        _RANK_EXITS.labels(rank=r,
                                           outcome=_classify(rc)).inc()
                live = [r for r in procs if r not in exited]
                crashed = [r for r, rc in exited.items()
                           if rc not in (0, RC_PREEMPTED)]
                if not live:
                    rcs = set(exited.values())
                    if rcs == {0}:
                        return "ok", "all ranks done", exited
                    if RC_WEDGED in rcs:
                        return ("wedged",
                                f"rank(s) {sorted(r for r in exited if exited[r] == RC_WEDGED)} "
                                f"reported the backend wedged (rc=3)",
                                exited)
                    if rcs <= {0, RC_PREEMPTED}:
                        # Unanimous-clean: every rank either finished or
                        # preempted-with-save (a finished rank has
                        # nothing left to preempt) — the 143 consensus
                        # path, exempt from the retry budget.
                        return "preempted", "clean preemption", exited
                    return ("crash", f"rank(s) {sorted(crashed)} crashed "
                            f"(rcs {[exited[r] for r in sorted(crashed)]})",
                            exited)
                if sigterm_seen:
                    # The fleet itself is being killed: forward to every
                    # rank group so no child outlives its supervisor.
                    self._teardown(procs, exited, "fleet_sigterm", name,
                                   attempt)
                    return "terminated", "fleet SIGTERM — forwarded", exited
                if self._stop.is_set():
                    # Scheduler-driven clean stop (SLO eviction / grow
                    # relaunch): same TERM-grace-KILL teardown — the
                    # ranks save and exit 143 — but the outcome routes
                    # to run()'s no-restart "evicted" return.
                    self._teardown(procs, exited, self._stop_reason,
                                   name, attempt)
                    return ("evicted",
                            f"stop requested ({self._stop_reason})",
                            exited)
                if crashed:
                    self._teardown(procs, exited, "rank_crash", name,
                                   attempt, rank=crashed[0])
                    if any(exited[r] == RC_WEDGED for r in crashed):
                        return ("wedged", f"rank {crashed[0]} rc=3 — gang "
                                f"torn down", exited)
                    return ("crash", f"rank {crashed[0]} "
                            f"rc={exited[crashed[0]]} — gang torn down",
                            exited)
                preempted_now = [r for r, rc in exited.items()
                                 if rc == RC_PREEMPTED]
                if preempted_now and first_143_t is None:
                    first_143_t = time.monotonic()
                if (first_143_t is not None
                        and time.monotonic() - first_143_t
                        > self.preempt_grace_s):
                    # A real platform preemption TERMs every rank; one
                    # rank exiting 143 while the rest train on is the
                    # gang cleanly losing a member — NOT the unanimous
                    # path, so it goes through the budgeted teardown.
                    self._teardown(procs, exited, "preempt_divergence",
                                   name, attempt, rank=preempted_now[0])
                    return ("crash", f"rank(s) {preempted_now} preempted "
                            f"but rank(s) {live} ran past the "
                            f"{self.preempt_grace_s:.0f}s consensus grace",
                            exited)
                now = time.monotonic()
                if self.wall_timeout_s and now - start > self.wall_timeout_s:
                    self._teardown(procs, exited, "wall_timeout", name,
                                   attempt)
                    return ("crash", f"wall timeout "
                            f"{self.wall_timeout_s:.0f}s", exited)
                if self.heartbeat_timeout_s:
                    for r in live:
                        # Armed per rank once ITS first beat lands —
                        # same opt-in rule as the single-child
                        # supervisor (a beat-less child is the wall
                        # timeout's job).
                        try:
                            hb_age = (time.time() - os.path.getmtime(
                                self._hb_path(r)))
                        except OSError:
                            continue
                        _HB_AGE.labels(rank=r).set(round(hb_age, 3))
                        if hb_age > self.heartbeat_timeout_s:
                            self._teardown(procs, exited, "rank_heartbeat",
                                           name, attempt, rank=r)
                            return ("crash", f"rank {r} heartbeat stale "
                                    f"{hb_age:.1f}s > "
                                    f"{self.heartbeat_timeout_s:.0f}s",
                                    exited)
                self._poll_health(name, attempt, list(procs),
                                  exited=exited)
                time.sleep(self.poll_s)

    # --- resume-step agreement --------------------------------------------
    def _snapshot_dirs(self, snapshot_dir_template: str) -> dict:
        return {r: snapshot_dir_template.replace("{rank}", str(r))
                for r in self.ranks}

    def _discard_all(self, name: str, dirs: dict, agreed: int) -> dict:
        """``discard_newer(agreed)`` on every rank's store — the
        mutation half of the agreement, journaled write-ahead by the
        caller so a supervisor death ANYWHERE in this loop is
        recoverable (:meth:`_replay_agreement` re-applies it; the
        per-store discard is itself idempotent: it only ever removes
        steps > agreed, which a second pass finds already gone).

        ``FLEET_DRILL_DIE_IN_DISCARD=<k>`` is the interrupted-AGREEMENT
        drill seam (ROADMAP fault library): the supervisor "dies"
        (raises) after discarding the k-th rank's store, leaving later
        ranks still holding their divergent newer snapshots — exactly
        the half-discarded state a mid-discard crash leaves, which the
        journal replay must heal before any child resumes."""
        from distributedtensorflowexample_tpu.resilience import (
            snapshot as snap)
        die_after = os.environ.get("FLEET_DRILL_DIE_IN_DISCARD", "")
        discarded = {}
        for i, r in enumerate(sorted(dirs)):
            discarded[r] = snap.SnapshotStore(dirs[r]).discard_newer(
                agreed)
            if die_after and i == int(die_after):
                raise RuntimeError(
                    f"FLEET_DRILL_DIE_IN_DISCARD={die_after}: "
                    f"{name}: supervisor dying mid-discard (rank "
                    f"{r} done, later ranks untouched)")
        return discarded

    def _agree(self, name: str, snapshot_dir_template: str) -> int | None:
        """The agreement pass: max common valid step across every
        surviving rank's store, divergent/torn newer steps discarded
        from disk, result journaled — returns the step to export (0 =
        no common step: fresh start), or None when the run has no
        snapshot surface to agree over.  "Valid" unions both snapshot
        formats (``snapshot.valid_steps``): a row-layout rank's
        quorum-valid shard sets (every 1/D shard digest-intact or
        ring-mirror-recoverable, resilience/shardstore.py) count
        exactly like monolithic payloads — so a rank that lost one
        shard directory within redundancy still votes for that step,
        and the gang does NOT regress past a recoverable save.

        The ``resume_agreement`` record is WRITE-AHEAD: it commits the
        agreed step (and what will be discarded) to the journal BEFORE
        any store is mutated, and ``resume_discard_done`` commits
        completion after.  A supervisor that dies between the two left
        a half-discarded fleet; a restarted supervisor's
        :meth:`_replay_agreement` finds the unmatched intent record and
        re-applies the discard — without the replay, its FIRST launch
        exports no agreed step and every child restores its own newest,
        so the ranks the dead supervisor never reached would silently
        resume the divergent timeline the agreement had already
        condemned."""
        if not snapshot_dir_template:
            return None
        from distributedtensorflowexample_tpu.resilience import (
            snapshot as snap)
        dirs = self._snapshot_dirs(snapshot_dir_template)
        # One validation pass (full payload read + crc32 per snapshot)
        # serves both the journal detail and the intersection — this is
        # newest_common_step's exact rule computed from the per-rank
        # lists already in hand, not a second disk walk.
        per_rank = {r: snap.valid_steps(d) for r, d in dirs.items()}
        common = set.intersection(*(set(v) for v in per_rank.values()))
        agreed = max(common) if common else 0
        # The record's "discarded" is the write-ahead PLAN (valid steps
        # the agreement condemns); the actual sweep — which also drops
        # torn newer payloads per_rank never listed — lands in the
        # resume_discard_done completion record.
        self.journal.write(
            "resume_agreement", task=name, agreed=agreed,
            per_rank={str(r): v for r, v in per_rank.items()},
            discarded={str(r): [s for s in v if s > agreed]
                       for r, v in per_rank.items()})
        discarded = self._discard_all(name, dirs, agreed)
        self.journal.write(
            "resume_discard_done", task=name, agreed=agreed,
            discarded={str(r): v for r, v in discarded.items()})
        _AGREEMENTS.inc()
        # The same agreement lands in the run ledger: obs_query renders
        # it between the attempts it separates, so "what did the gang
        # agree to resume from" is answerable without the journal.
        self._ledger_event(
            "resume_agreement", task=name, agreed=agreed,
            per_rank={str(r): v for r, v in per_rank.items()},
            discarded={str(r): v for r, v in discarded.items()})
        _log(f"{name}: resume-step agreement: "
             + ", ".join(f"rank {r} had {per_rank[r] or 'nothing'}"
                         for r in sorted(per_rank))
             + f" -> agreed step {agreed}"
             + (f" (discarded {discarded})" if any(discarded.values())
                else ""))
        return agreed

    def _replay_agreement(self, name: str,
                          snapshot_dir_template: str) -> int | None:
        """Journal replay of an INTERRUPTED discard: the newest
        ``resume_agreement`` record with no ``resume_discard_done``
        after it means a previous supervisor incarnation died
        mid-:meth:`_discard_all`.  Re-apply the discard (idempotent —
        already-trimmed stores lose nothing) and return the agreed step
        so the first launch exports it; a COMPLETED prior agreement (or
        none at all) returns None and the first launch keeps its normal
        nothing-to-agree-on semantics."""
        if not snapshot_dir_template:
            return None
        pending = None
        for rec in self.journal.events():
            if rec.get("event") == "resume_agreement" \
                    and rec.get("task") == name:
                pending = rec
            elif rec.get("event") == "resume_discard_done" \
                    and rec.get("task") == name:
                pending = None
        if pending is None:
            return None
        agreed = int(pending.get("agreed", 0))
        dirs = self._snapshot_dirs(snapshot_dir_template)
        discarded = self._discard_all(name, dirs, agreed)
        self.journal.write(
            "resume_discard_done", task=name, agreed=agreed, replayed=True,
            discarded={str(r): v for r, v in discarded.items()})
        self._ledger_event(
            "resume_agreement_replayed", task=name, agreed=agreed,
            discarded={str(r): v for r, v in discarded.items()})
        _log(f"{name}: replayed interrupted resume-step agreement "
             f"(agreed step {agreed}; a prior supervisor died "
             f"mid-discard"
             + (f"; discarded {discarded})" if any(discarded.values())
                else ")"))
        return agreed

    # --- the gang retry loop ----------------------------------------------
    def run(self, argv: list[str], name: str = "",
            snapshot_dir_template: str = "",
            stdout_dir: str | None = None,
            env_extra: dict | None = None,
            agree_first: bool = False) -> GangResult:
        """Supervise ``argv`` (with ``{rank}`` substitution) as an
        N-rank gang until it completes, exhausts the crash budget, or
        loses a host.  ``snapshot_dir_template`` names each rank's
        SnapshotStore directory (``{rank}`` substituted) — without it
        no agreement pass runs and restarts are fresh-per-child.
        ``agree_first`` runs the agreement pass BEFORE the first launch
        too: a RESUMED job (the scheduler relaunching an evicted gang)
        starts from stores a previous fleet incarnation wrote, so 'the
        first launch has nothing to agree on' no longer holds — the
        ranks' newest steps may already diverge."""
        name = name or Supervisor._default_name(argv)
        attempt = -1
        failures = 0
        preemptions = 0
        restarts = 0
        # A prior supervisor incarnation that died mid-discard left the
        # fleet half-trimmed; replaying the journaled intent BEFORE the
        # first launch re-applies the discard (idempotent) and pins the
        # first gang to the already-agreed step — otherwise children
        # with no export would restore their own newest, resuming the
        # divergent timeline the dead supervisor had condemned.
        agreed: int | None = self._replay_agreement(
            name, snapshot_dir_template)
        if agreed is None and agree_first and snapshot_dir_template:
            agreed = self._agree(name, snapshot_dir_template)
        agreed_steps: list = []
        reasons: list[str] = []
        last: dict = {}
        try:
            with obs_trace.span("fleet", task=name,
                                ranks=self.num_ranks) as attrs:
                while attempt < self.policy.retries + MAX_PREEMPTIONS:
                    attempt += 1
                    outcome, why, last = self._run_gang(
                        argv, name, attempt, agreed, stdout_dir, env_extra)
                    reasons.append(f"gang attempt {attempt}: {outcome} "
                                   f"({why})")
                    self.journal.write(
                        "gang_end", task=name, attempt=attempt,
                        outcome=outcome, why=why,
                        rcs={str(r): rc for r, rc in sorted(last.items())})
                    self._ledger_event(
                        "run_end", run=self._gang_run(name, attempt),
                        outcome=outcome,
                        rcs={str(r): rc for r, rc in sorted(last.items())})
                    if outcome == "ok":
                        attrs["status"] = "ok"
                        return GangResult("ok", attempt + 1, restarts,
                                          preemptions, agreed_steps, last,
                                          list(self.ranks), reasons)
                    if outcome == "terminated":
                        attrs["status"] = "terminated"
                        return GangResult("terminated", attempt + 1,
                                          restarts, preemptions,
                                          agreed_steps, last,
                                          list(self.ranks), reasons)
                    if outcome == "evicted":
                        # request_stop(): clean preemption on the
                        # scheduler's behalf — no restart; the caller
                        # requeues and relaunches from the snapshots
                        # the teardown's TERM just produced.
                        attrs["status"] = "evicted"
                        return GangResult("evicted", attempt + 1,
                                          restarts, preemptions,
                                          agreed_steps, last,
                                          list(self.ranks), reasons)
                    if outcome == "wedged":
                        # The backend is provably gone under EVERY rank
                        # of this gang; relaunching N processes against
                        # a dead tunnel resolves nothing (supervisor
                        # rc=3 contract).
                        attrs["status"] = "wedged"
                        return GangResult("wedged", attempt + 1, restarts,
                                          preemptions, agreed_steps, last,
                                          list(self.ranks), reasons)
                    if outcome == "preempted":
                        preemptions += 1
                        _log(f"{name}: gang preempted cleanly — "
                             f"restarting (exempt from the retry budget)")
                    else:
                        # crash / rank_lost(elastic): budgeted.
                        failures += 1
                        if failures > self.policy.retries:
                            attrs["status"] = "exhausted"
                            return GangResult(
                                "exhausted", attempt + 1, restarts,
                                preemptions, agreed_steps, last,
                                list(self.ranks), reasons)
                    restarts += 1
                    _GANG_RESTARTS.inc()
                    # Grow-on-recovery: BEFORE the agreement, so a
                    # recovered rank's store participates in (and is
                    # trimmed by) the same pass that pins the resume
                    # step the regrown gang exports.
                    if self.elastic and self._lost \
                            and self.reprobe_on_relaunch:
                        self.reprobe_lost_ranks(argv, name)
                    agreed = self._agree(name, snapshot_dir_template)
                    agreed_steps.append(agreed)
                    if outcome not in ("preempted", "rank_lost"):
                        delay = self.policy.delay_s(max(0, failures - 1),
                                                    self._rng.random())
                        if delay:
                            _log(f"{name}: gang restart "
                                 f"{failures}/{self.policy.retries} in "
                                 f"{delay:.2f}s (resume step {agreed})")
                            time.sleep(delay)
                attrs["status"] = "exhausted"
                return GangResult("exhausted", attempt + 1, restarts,
                                  preemptions, agreed_steps, last,
                                  list(self.ranks), reasons)
        finally:
            self.journal.write("fleet_end", task=name,
                               attempts=attempt + 1, restarts=restarts)
            export_prometheus_collector("fleet")
