"""Anomaly-driven remediation: the policy engine that closes the loop
from the repo's detectors to its actuators.

Since round 10 the fleet *sees* everything — per-rank ``health.json``
flags (obs/anomaly.py), journal/ledger ``anomaly`` annotations, live
``serve_*`` latency gauges — but DESIGN.md §16 pinned the stance as
detection-only: nothing restarts.  This module is the next rung
(ROADMAP direction 5): anomaly detections feed *declared, rate-limited
policies* that map onto actions the repo already knows how to perform
safely:

====================  ====================================================
anomaly kind          default remediation
====================  ====================================================
``straggler`` /       **evict** — loss-free gang stop via
``step_time_          ``FleetSupervisor.request_stop`` (TERM → 143 →
regression``          snapshot); the relaunch resumes bitwise from the
                      agreed step, and a transient slowdown (a noisy
                      neighbor, a flapping NIC) does not ride along
``nan_loss`` /        **rollback** — gang rollback to the pinned
``loss_plateau``      last-good snapshot: the newest step every rank
                      holds VALID (SnapshotStore size+crc) that strictly
                      predates the anomaly's ``fired_step``; everything
                      newer is discarded (``discard_newer``) so the next
                      agreement pass cannot resurrect the condemned tail
``serve_p99_breach``  **slo_tighten** — tighten the serving admission
                      SLO (``SERVE_SLO_MS`` semantics,
                      serving/queue.py): shed load loudly instead of
                      admitting requests to miss
``rank_lost``         **quarantine** (repeated offender, flap-gated):
                      a host that keeps dying is the scheduler's rc-3
                      shape — stop feeding it work
``canary_regression`` **canary_rollback** — revert a canary promotion
                      (serving/promote.Canary) to the baseline snapshot
``serve_overload`` /  **scale_up** / **scale_down** — resize the serve
``serve_underload``   replica fleet against the measured SLO knee
                      (SERVE_lm record): offered load over the fleet's
                      in-SLO capacity grows it, sustained idle shrinks
                      it, both clamped to [min, max] replicas
====================  ====================================================

Every decision is **guarded** — this is the part that makes closing the
loop safe enough to ship:

- **flap damping**: a policy acts only after ``HEAL_FLAP_N`` detections
  of the same (kind, scope) inside ``HEAL_FLAP_WINDOW_S``.  Watchers
  emit one detection per poll *while the condition holds*, so a
  one-poll blip (a z-score grazing the threshold once) never reaches an
  actuator, while a persistent condition crosses the bar in
  ``flap_n`` polls.
- **per-kind cooldown** (``HEAL_COOLDOWN_S``): after acting on a
  (kind, scope), further detections of it are suppressed for the
  cooldown — an action storm against a condition the first action is
  still fixing is worse than the condition.
- **global action budget** (``HEAL_ACTION_BUDGET``): a hard ceiling on
  actions per remediator JOURNAL — WAL replay restores the spent count,
  so a crash-looping (or restarted) remediator cannot mint itself a
  fresh budget over the same workdir; an operator resets it by starting
  a new journal.  Exhaustion degrades to DETECTION-ONLY with one loud
  ``heal_budget_exhausted`` ledger row — a remediator gone wrong must
  converge to round 10's safe stance, not escalate.
- **dry-run** (``HEAL_DRY_RUN``): every decision is journaled as a
  ``heal_dry_run`` row naming the action that *would* have fired;
  no actuator runs.  The commissioning mode: watch the policy engine
  against production telemetry before arming it.

Crash tolerance is the scheduler's WAL pattern (DESIGN.md §21): a
``heal_intent`` journal record commits BEFORE the actuator runs and the
applied ``heal_<action>`` record after, so a remediator SIGKILLed
mid-action replays its journal on construction — unmatched intents
re-apply idempotently (every actuator here is: ``request_stop`` on a
dead gang is a no-op, ``discard_newer`` finds the already-discarded
steps gone, re-tightening an SLO to the same value changes nothing).
Every decision also lands as a ``heal_*`` row in the run ledger, and
``tools/obs_query.py why <scope>`` renders the timeline — the operator
answer to "who restarted my job and why" must come from the ledger
alone.

Importing this module pulls obs/ + stdlib only; the actuator factories
that need jax-adjacent machinery (SnapshotStore) import it lazily, so
a scheduler or drill harness can construct the policy engine without a
backend.
"""

from __future__ import annotations

import dataclasses
import glob as _glob
import json
import math
import os
import re
import sys
import threading
import time

from distributedtensorflowexample_tpu.obs import anomaly as obs_anomaly
from distributedtensorflowexample_tpu.obs import ledger as obs_ledger
from distributedtensorflowexample_tpu.obs import metrics as obs_metrics

# The heal_* ledger-row schema: every decision class the remediator can
# take, written with src="heal" plus a "job" scope field.
# tools/obs_query.py's `why` verb renders exactly this set — the reader
# and this writer must not drift.
# KEEP-IN-SYNC(heal-events) digest=28d0c1dcec37
HEAL_EVENTS = (
    "heal_detect",            # anomaly folded into the policy engine
    "heal_evict",             # loss-free gang stop (TERM→143→resume)
    "heal_rollback",          # gang rollback to the pinned last-good step
    "heal_slo_tighten",       # serving admission SLO tightened / load shed
    "heal_quarantine",        # repeated offender quarantined (rc-3 shape)
    "heal_canary_promote",    # canary window clean: candidate promoted
    "heal_canary_rollback",   # canary regressed: reverted to baseline
    "heal_scale_up",          # serve fleet grown against the SLO knee
    "heal_scale_down",        # serve fleet shrunk (sustained underload)
    "heal_lr_drop",           # plateau -> LR-drop advisory (HEAL_LR_DROP)
    "heal_suppressed",        # guardrail suppressed an action (with why)
    "heal_dry_run",           # dry-run: what WOULD have fired
    "heal_budget_exhausted",  # budget gone: detection-only from here on
)
# KEEP-IN-SYNC-END(heal-events)

#: Actions (the ``heal_<action>`` applied-row suffixes).
HEAL_ACTIONS = ("evict", "rollback", "slo_tighten", "quarantine",
                "canary_promote", "canary_rollback",
                "scale_up", "scale_down", "lr_drop")

_DETECTIONS = obs_metrics.counter(
    "heal_detections_total", "anomaly detections folded into the "
    "remediation policy engine, by kind")
_ACTIONS = obs_metrics.counter(
    "heal_actions_total", "remediation actions applied, by action")
_SUPPRESSED = obs_metrics.counter(
    "heal_suppressed_total", "remediation actions suppressed by a "
    "guardrail, by reason")


def _log(msg: str) -> None:
    print(f"heal: {msg}", file=sys.stderr, flush=True)


# --- env knobs (constant-name reads through one helper each) ---------------

def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, ""))
    except ValueError:
        return default


def dry_run_default() -> bool:
    """``HEAL_DRY_RUN``: 1/true = journal what would fire, run nothing."""
    return str(os.environ.get("HEAL_DRY_RUN", "")).lower() in (
        "1", "true", "t", "yes", "y")


def cooldown_default() -> float:
    """``HEAL_COOLDOWN_S``: per-(kind, scope) quiet period after an
    action (default 30 s)."""
    return _env_float("HEAL_COOLDOWN_S", 30.0)


def lr_drop_enabled() -> bool:
    """``HEAL_LR_DROP``: 1/true = map ``loss_plateau`` to the lr-drop
    advisory stub instead of gang rollback (experimental: the trainer
    consumption seam is not wired yet — the actuator writes an advisory
    file a future LR hook reads at its next consensus poll)."""
    return str(os.environ.get("HEAL_LR_DROP", "")).lower() in (
        "1", "true", "t", "yes", "y")


def newest_heal_record(root: str = "") -> str:
    """Path of the newest checked-in MTTR drill record
    (``HEAL_*_r<NN>.json`` at the repo root — round number sorts
    lexicographically), or ``""`` when none exists."""
    if not root:
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
    paths = sorted(_glob.glob(os.path.join(root, "HEAL_*.json")))
    return paths[-1] if paths else ""


def mttr_seeded_cooldown_s(record_path: str = "", *, margin: float = 2.0,
                           floor_s: float = 5.0) -> float:
    """Cooldown seeded from MEASURED recovery time instead of a
    hardcoded constant: ``margin ×`` the worst end-to-end MTTR the
    newest ``HEAL_*`` drill record proved (detect → act → resumed), so
    the post-action quiet period holds exactly as long as a real heal
    plausibly takes.  A 30 s constant was simultaneously too short for
    a 21 s slow-rank evict+resume and absurdly long for a 54 ms SLO
    tighten; anchoring on the measured tail keeps the guardrail honest
    as the fleet's recovery speed changes.  ``HEAL_COOLDOWN_S`` (via
    :func:`cooldown_default`) still wins when no record is readable."""
    path = record_path or newest_heal_record()
    worst_ms = 0.0
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                if str(row.get("metric", "")).endswith("_mttr_ms"):
                    try:
                        worst_ms = max(worst_ms, float(row["value"]))
                    except (KeyError, TypeError, ValueError):
                        continue
    except OSError:
        return cooldown_default()
    if worst_ms <= 0:
        return cooldown_default()
    return max(floor_s, margin * worst_ms / 1000.0)


def budget_default() -> int:
    """``HEAL_ACTION_BUDGET``: global actions-per-journal ceiling
    (default 8; WAL replay restores the spent count, a new journal
    resets it); exhaustion degrades to detection-only, loudly."""
    return int(_env_float("HEAL_ACTION_BUDGET", 8))


def flap_n_default() -> int:
    """``HEAL_FLAP_N``: detections of one (kind, scope) inside the flap
    window before a policy may act (default 2 — a one-poll blip never
    reaches an actuator)."""
    return max(1, int(_env_float("HEAL_FLAP_N", 2)))


def flap_window_default() -> float:
    """``HEAL_FLAP_WINDOW_S``: the flap-damping window (default 60 s)."""
    return _env_float("HEAL_FLAP_WINDOW_S", 60.0)


# --- events + policy -------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class AnomalyEvent:
    """One detection occurrence handed to the policy engine.

    ``key`` identifies the underlying anomaly (dedup for the
    ``heal_detect`` row: one row per distinct anomaly, however many
    polls re-observe it); ``scope`` labels whose anomaly it is (a job
    id under the scheduler, a task name standalone, "serve" for the
    serving worker) and keys the flap/cooldown guardrails together
    with ``kind``."""
    kind: str
    key: str
    scope: str = ""
    rank: int | None = None
    step: int | None = None
    source: str = ""              # health | ledger | scrape | canary
    # Optional episode label folded into the guardrail key: a watcher
    # that can PROVE recovery between occurrences (ServeWatcher's
    # breach→recover→breach) stamps a fresh episode so the new
    # condition gets a fresh decision instead of a cooldown leftover.
    episode: str = ""
    detail: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass(frozen=True)
class HealRule:
    """kind → action, with an optional per-kind flap override (e.g.
    ``rank_lost`` → quarantine wants "repeated offender", not "first
    offense")."""
    action: str
    flap_n: int | None = None


#: The default policy table (DESIGN.md §23).  A kind with no rule is
#: detection-only: heal_detect rows, counters, nothing else.
DEFAULT_POLICY: dict[str, HealRule] = {
    "straggler": HealRule("evict"),
    "step_time_regression": HealRule("evict"),
    "nan_loss": HealRule("rollback"),
    "loss_plateau": HealRule("rollback"),
    "serve_p99_breach": HealRule("slo_tighten"),
    "rank_lost": HealRule("quarantine", flap_n=3),
    "canary_regression": HealRule("canary_rollback", flap_n=1),
    "serve_overload": HealRule("scale_up"),
    # Shrinking trades capacity for efficiency — demand a LONGER
    # period of proof than growth does (scale-down flaps are the
    # classic autoscaler failure: shed replicas into a lull, then
    # breach the SLO when the next burst lands on the smaller fleet).
    "serve_underload": HealRule("scale_down", flap_n=4),
}


# --- guardrails ------------------------------------------------------------

class Guardrails:
    """Flap damping + per-key cooldown + the global action budget —
    pure bookkeeping, injectable clock, no IO (the Remediator owns the
    rows)."""

    def __init__(self, flap_n: int | None = None,
                 flap_window_s: float | None = None,
                 cooldown_s: float | None = None,
                 budget: int | None = None,
                 clock=None):
        self.flap_n = flap_n_default() if flap_n is None else max(1, flap_n)
        self.flap_window_s = (flap_window_default()
                              if flap_window_s is None else flap_window_s)
        self.cooldown_s = (cooldown_default()
                           if cooldown_s is None else cooldown_s)
        self.budget = budget_default() if budget is None else budget
        self.clock = clock or obs_metrics._wall
        self.actions_used = 0
        self._seen: dict = {}         # key -> [detection ts, ...]
        self._acted: dict = {}        # key -> last action ts

    def note(self, key, flap_n: int | None = None) -> str:
        """Record one detection occurrence of ``key`` and return the
        disposition: ``act`` | ``flap`` | ``cooldown`` | ``budget``.
        The caller applies the action (and calls :meth:`acted`) only on
        ``act``."""
        now = self.clock()
        tape = [t for t in self._seen.get(key, [])
                if now - t <= self.flap_window_s]
        tape.append(now)
        self._seen[key] = tape
        last = self._acted.get(key)
        if self.cooldown_s > 0 and last is not None \
                and now - last < self.cooldown_s:
            return "cooldown"
        if len(tape) < (self.flap_n if flap_n is None else max(1, flap_n)):
            return "flap"
        if self.actions_used >= self.budget:
            return "budget"
        return "act"

    def acted(self, key) -> None:
        now = self.clock()
        self.actions_used += 1
        self._acted[key] = now
        self._seen[key] = []          # a fresh episode must re-flap

    def touch_cooldown(self, key) -> None:
        """Anchor the cooldown WITHOUT charging the budget — the
        errored-actuator path: a held condition whose actuator keeps
        crashing must retry once per cooldown, not once per poll
        (~12 fsync'd WAL rows/s), and crashes spend no budget."""
        self._acted[key] = self.clock()

    def restore_action(self, key, ts: float) -> None:
        """Replay half: an applied action from a previous incarnation
        still counts against the budget and still anchors the
        cooldown."""
        self.actions_used += 1
        if ts > self._acted.get(key, -float("inf")):
            self._acted[key] = ts


# --- the remediator --------------------------------------------------------

class Remediator:
    """The policy engine: observe detections, map them through the
    policy table and guardrails, run actuators under a write-ahead
    journal, land every decision as a ``heal_*`` ledger row.

    ``actuators`` maps action name → ``callable(event) -> dict``.  An
    actuator returns a detail dict for the applied row; returning
    ``{"noop": why}`` records a suppression instead (no budget, no
    cooldown) — the "condition true but nothing useful to do" case,
    e.g. a straggling job with no queued work waiting for its devices.
    A missing actuator is detection-only for that action.

    Construction replays the journal: detected keys re-latch, applied
    actions restore the budget/cooldown state, and an unmatched
    ``heal_intent`` — a SIGKILL landed between intent and effect — is
    re-applied idempotently (``replayed: true`` on its applied row)."""

    def __init__(self, journal=None, ledger_path: str = "",
                 *, actuators: dict | None = None,
                 policy: dict[str, HealRule] | None = None,
                 scope: str = "",
                 dry_run: bool | None = None,
                 guardrails: Guardrails | None = None,
                 clock=None):
        from distributedtensorflowexample_tpu.resilience.supervisor import (
            Journal)
        self.journal = journal or Journal(None)
        self.ledger_path = ledger_path
        self.actuators = dict(actuators or {})
        self.policy = dict(DEFAULT_POLICY if policy is None else policy)
        if policy is None and lr_drop_enabled():
            # Experimental (HEAL_LR_DROP): a plateau asks for a smaller
            # LR before it asks for a rollback — the advisory stub;
            # explicit policy tables are never silently rewritten.
            self.policy["loss_plateau"] = HealRule("lr_drop")
        self.scope = scope
        self.dry_run = dry_run_default() if dry_run is None else dry_run
        self.guardrails = guardrails or Guardrails(clock=clock)
        self._seq = 0
        self._detected: set[str] = set()
        # Last suppression reason per key — suppressed rows land once
        # per (key, reason) EPISODE, not once per poll: a held
        # condition re-observed every 0.25 s must not flood the ledger.
        self._last_suppression: dict = {}
        self._replay()

    # --- rows -------------------------------------------------------------
    def _row(self, event: str, *, seq=None, ledger: bool = True,
             **fields) -> None:
        fields.setdefault("job", self.scope or None)
        self.journal.write(event, **({"seq": seq} if seq is not None
                                     else {}), **fields)
        if ledger and self.ledger_path:
            obs_ledger.log_event(event, path=self.ledger_path, src="heal",
                                 **fields)

    def _suppress(self, ev: AnomalyEvent, action: str, reason: str,
                  **fields) -> str:
        _SUPPRESSED.labels(reason=reason).inc()
        if self._last_suppression.get(ev.key) != reason:
            self._last_suppression[ev.key] = reason
            self._row("heal_suppressed", key=ev.key, kind=ev.kind,
                      action=action, reason=reason,
                      job=ev.scope or self.scope or None, **fields)
        return reason

    # --- replay (crash tolerance) -----------------------------------------
    def _replay(self) -> None:
        applied_events = tuple(f"heal_{a}" for a in HEAL_ACTIONS)
        intents: dict[int, dict] = {}
        budget_row_seen = False
        for rec in self.journal.events():
            ev = rec.get("event", "")
            if not ev.startswith("heal_"):
                continue
            seq = rec.get("seq")
            if isinstance(seq, int):
                self._seq = max(self._seq, seq)
            if ev == "heal_detect":
                self._detected.add(rec.get("key") or "")
            elif ev == "heal_intent":
                intents[seq] = rec
            elif ev in applied_events or ev == "heal_suppressed":
                if isinstance(seq, int):
                    intents.pop(seq, None)
                if ev in applied_events and not rec.get("error"):
                    # Error rows balance the WAL but the live path never
                    # charged them (no acted()) — replay must not either,
                    # or a restart after N actuator failures would wake
                    # up budget-exhausted without one action ever run.
                    self.guardrails.restore_action(
                        (rec.get("kind"), rec.get("job") or "",
                         rec.get("episode") or ""),
                        float(rec.get("ts") or 0.0))
            elif ev == "heal_budget_exhausted":
                budget_row_seen = True
        self._budget_row_written = budget_row_seen
        if budget_row_seen and self.guardrails.actions_used \
                >= self.guardrails.budget:
            # The loud row is already on the ledger (written once per
            # journal); say on stderr that THIS incarnation inherits
            # the exhausted state rather than degrading silently.
            _log(f"journal replay restored {self.guardrails.actions_used}"
                 f"/{self.guardrails.budget} actions — starting in "
                 f"detection-only mode (heal_budget_exhausted already "
                 f"on the ledger)")
        for seq in sorted(intents):
            rec = intents[seq]
            action = rec.get("action") or ""
            ev = AnomalyEvent(kind=rec.get("kind") or "",
                              key=rec.get("key") or "",
                              scope=rec.get("job") or self.scope,
                              rank=rec.get("rank"), step=rec.get("step"),
                              episode=rec.get("episode") or "",
                              source="replay")
            _log(f"replaying interrupted heal intent seq={seq} "
                 f"({action} on {ev.key}): a prior remediator died "
                 f"between intent and effect")
            self._apply(ev, action, seq, replayed=True)

    # --- the decision path ------------------------------------------------
    def observe(self, ev: AnomalyEvent) -> str:
        """Fold one detection occurrence in; returns the disposition:
        ``detected`` (no rule) | ``flap`` | ``cooldown`` | ``budget`` |
        ``dry_run`` | ``no_actuator`` | ``noop`` | ``acted`` |
        ``error``."""
        if ev.key not in self._detected:
            self._detected.add(ev.key)
            _DETECTIONS.labels(kind=ev.kind).inc()
            self._row("heal_detect", key=ev.key, kind=ev.kind,
                      rank=ev.rank, step=ev.step, source=ev.source,
                      job=ev.scope or self.scope or None,
                      detail=obs_metrics.json_safe(ev.detail) or None)
        rule = self.policy.get(ev.kind)
        if rule is None:
            return "detected"
        gkey = (ev.kind, ev.scope or self.scope, ev.episode)
        disposition = self.guardrails.note(gkey, flap_n=rule.flap_n)
        if disposition == "flap":
            return self._suppress(ev, rule.action, "flap",
                                  seen=len(self.guardrails._seen[gkey]),
                                  need=(rule.flap_n
                                        or self.guardrails.flap_n))
        if disposition == "cooldown":
            return self._suppress(ev, rule.action, "cooldown",
                                  cooldown_s=self.guardrails.cooldown_s)
        if disposition == "budget":
            if not self._budget_row_written:
                self._budget_row_written = True
                self._row("heal_budget_exhausted",
                          budget=self.guardrails.budget, key=ev.key,
                          kind=ev.kind,
                          job=ev.scope or self.scope or None)
                _log(f"action budget {self.guardrails.budget} exhausted "
                     f"— degrading to detection-only (the round-10 "
                     f"stance); the WAL restores the spent count, so "
                     f"only a fresh journal resets it")
            return self._suppress(ev, rule.action, "budget")
        if self.dry_run:
            if self._last_suppression.get(ev.key) != "dry_run":
                self._last_suppression[ev.key] = "dry_run"
                self._row("heal_dry_run", key=ev.key, kind=ev.kind,
                          action=rule.action, rank=ev.rank, step=ev.step,
                          job=ev.scope or self.scope or None)
            return "dry_run"
        if rule.action not in self.actuators:
            return self._suppress(ev, rule.action, "no_actuator")
        self._seq += 1
        seq = self._seq
        self.journal.write("heal_intent", seq=seq, action=rule.action,
                           key=ev.key, kind=ev.kind, rank=ev.rank,
                           step=ev.step, episode=ev.episode or None,
                           job=ev.scope or self.scope or None)
        return self._apply(ev, rule.action, seq)

    def _apply(self, ev: AnomalyEvent, action: str, seq: int,
               replayed: bool = False) -> str:
        actuator = self.actuators.get(action)
        if actuator is None:
            # Replay path with a narrower actuator set than the dead
            # incarnation's: resolve the intent loudly, don't crash.
            return self._suppress(ev, action, "no_actuator", seq=seq)
        gkey = (ev.kind, ev.scope or self.scope, ev.episode)
        try:
            detail = actuator(ev) or {}
        except Exception as e:       # noqa: BLE001 — a broken actuator
            # must not kill the engine watching everything else; the
            # applied row carries the error so the WAL still balances.
            self._row(f"heal_{action}", seq=seq, key=ev.key, kind=ev.kind,
                      error=str(e), replayed=replayed or None,
                      job=ev.scope or self.scope or None)
            self.guardrails.touch_cooldown(gkey)
            _log(f"actuator {action} failed on {ev.key}: {e} "
                 f"(retrying after the {self.guardrails.cooldown_s:g}s "
                 f"cooldown)")
            return "error"
        if isinstance(detail, dict) and detail.get("noop"):
            return self._suppress(ev, action, f"noop: {detail['noop']}",
                                  seq=seq)
        self.guardrails.acted(gkey)
        self._last_suppression.pop(ev.key, None)
        _ACTIONS.labels(action=action).inc()
        self._row(f"heal_{action}", seq=seq, key=ev.key, kind=ev.kind,
                  rank=ev.rank, step=ev.step,
                  replayed=replayed or None,
                  episode=ev.episode or None,
                  job=ev.scope or self.scope or None,
                  detail=obs_metrics.json_safe(detail) or None)
        _log(f"{action} on {ev.key}"
             + (f" ({detail})" if detail else "")
             + (" [replayed]" if replayed else ""))
        return "acted"


# --- watchers (detection sources) ------------------------------------------

class HealthWatcher:
    """Poll per-rank ``health.json`` files (and the fleet aggregate)
    for firing flags; one event per poll per held condition.

    Flag semantics mirror obs/anomaly.py's payloads: ``nan_loss`` is
    permanent (``fired_step`` set means the run SAW a NaN — the
    condition cannot un-happen, so a post-mortem file still reports
    it); ``step_time_regression``/``loss_plateau`` count only while
    ``firing`` (a decayed blip must stop feeding the flap counter, or
    damping would be vacuous)."""

    def __init__(self, pattern: str, fleet_health: str = "",
                 scope: str = ""):
        self.pattern = pattern            # glob over per-rank files
        self.fleet_health = fleet_health  # aggregate (stragglers)
        self.scope = scope

    @staticmethod
    def _rank_of(payload: dict, path: str) -> int | None:
        r = payload.get("rank")
        if isinstance(r, int):
            return r
        m = re.search(r"health_rank(\d+)", os.path.basename(path))
        return int(m.group(1)) if m else None

    def poll(self) -> list[AnomalyEvent]:
        out: list[AnomalyEvent] = []
        for path in sorted(_glob.glob(self.pattern)):
            payload = obs_anomaly.read_health(path)
            if not payload or payload.get("kind") == "fleet":
                continue
            rank = self._rank_of(payload, path)
            for kind, f in (payload.get("flags") or {}).items():
                fired = f.get("fired_step")
                held = (fired is not None if kind == "nan_loss"
                        else bool(f.get("firing")))
                if not held:
                    continue
                out.append(AnomalyEvent(
                    kind=kind, key=f"rank{rank}:{kind}:{fired}",
                    scope=self.scope, rank=rank,
                    step=fired if fired is not None
                    else payload.get("step"),
                    source="health",
                    detail={"updated_unix": payload.get("updated_unix"),
                            "step": payload.get("step")}))
        if self.fleet_health:
            payload = obs_anomaly.read_health(self.fleet_health)
            if payload and payload.get("kind") == "fleet":
                skew = payload.get("skew") or {}
                for r in payload.get("stragglers") or []:
                    out.append(AnomalyEvent(
                        kind="straggler", key=f"straggler:rank{r}",
                        scope=self.scope, rank=int(r),
                        source="health",
                        detail={"why": (skew.get("why") or {}).get(
                                    str(r), (skew.get("why") or {}).get(r)),
                                "updated_unix": payload.get(
                                    "updated_unix")}))
        return out


class LedgerWatcher:
    """Tail the run ledger for ``anomaly`` / ``rank_lost`` rows — the
    fleet's journal annotations mirrored into RUNS.jsonl.  Tracks how
    many rows it has consumed; each NEW row is one detection
    occurrence (so N losses of one rank accumulate toward the
    repeated-offender flap bar)."""

    def __init__(self, path: str, kinds=("anomaly", "rank_lost"),
                 scope: str = ""):
        self.path = path
        self.kinds = tuple(kinds)
        self.scope = scope
        self._consumed = 0
        self._sizes: tuple = ()

    def _stat_sizes(self) -> tuple:
        out = []
        for p in (self.path, self.path + ".1"):
            try:
                out.append(os.stat(p).st_size)
            except OSError:
                out.append(-1)
        return tuple(out)

    def poll(self) -> list[AnomalyEvent]:
        # Size gate: the watch loop ticks every ~0.25 s against a file
        # that grows every few seconds at most — re-parsing the whole
        # ledger per tick is O(file) work for nothing.  Sizes move on
        # every append AND on rotation (live shrinks, .1 appears), so
        # an unchanged pair means unchanged rows.
        sizes = self._stat_sizes()
        if sizes == self._sizes:
            return []
        if sizes[0] < 0:
            # Mid-rotation window (os.replace moved the live file, the
            # next append hasn't recreated it): keep the cursor and the
            # size snapshot — re-read on the next poll, never reset
            # _consumed to 0 and re-emit history as fresh detections.
            return []
        self._sizes = sizes
        rows, _ = obs_ledger.read_rows(self.path)
        if len(rows) < self._consumed:
            # A second rotation dropped history below the cursor; clamp
            # forward rather than mis-slice — re-emitting old rank_lost
            # rows could quarantine a healthy host.
            self._consumed = len(rows)
            return []
        new, self._consumed = rows[self._consumed:], len(rows)
        out = []
        for i, row in enumerate(new):
            ev = row.get("event")
            if ev not in self.kinds:
                continue
            kind = row.get("kind") if ev == "anomaly" else "rank_lost"
            rank = row.get("rank")
            step = row.get("fired_step") if ev == "anomaly" \
                else row.get("step")
            out.append(AnomalyEvent(
                kind=str(kind), scope=self.scope,
                key=f"ledger:{kind}:rank{rank}:"
                    f"{step if step is not None else self._consumed - len(new) + i}",
                rank=rank, step=step, source="ledger",
                detail={"ts": row.get("ts"), "task": row.get("task"),
                        "why": row.get("why") or row.get("error")}))
        return out


class ServeWatcher:
    """Scrape serving latency (``stats_fn`` → the batcher's stats dict,
    or anything shaped like it) and emit ``serve_p99_breach`` while the
    p99 sits over ``breach_ms``.  Episodes re-arm on recovery: breach →
    heal → p99 back under → a LATER breach is a new key (a re-tightened
    SLO that breaches again deserves a fresh decision, not a cooldown
    leftover)."""

    def __init__(self, stats_fn, breach_ms: float,
                 min_completed: int = 8, scope: str = "serve"):
        self.stats_fn = stats_fn
        self.breach_ms = float(breach_ms)
        self.min_completed = min_completed
        self.scope = scope
        self._episode = 0
        self._in_breach = False

    def poll(self) -> list[AnomalyEvent]:
        try:
            stats = self.stats_fn() or {}
        except Exception:             # noqa: BLE001 — a scrape failing
            return []                 # must read as "no data", never die
        p99 = stats.get("p99_ms")
        completed = stats.get("completed") or 0
        if p99 is None or completed < self.min_completed:
            return []
        if p99 > self.breach_ms:
            self._in_breach = True
            return [AnomalyEvent(
                kind="serve_p99_breach",
                key=f"serve_p99:e{self._episode}", scope=self.scope,
                source="scrape", episode=f"e{self._episode}",
                detail={"p99_ms": p99, "breach_ms": self.breach_ms,
                        "completed": completed})]
        if self._in_breach:
            self._in_breach = False
            self._episode += 1
        return []


class AutoscaleWatcher:
    """Scrape the serve fleet's offered load (``stats_fn`` →
    ``{"offered_per_s", "replicas", ...}``) against the measured SLO
    knee — the best in-SLO per-replica throughput a SERVE_lm record
    proved (``throughput_vs_slo``) — and emit ``serve_overload`` while
    offered load exceeds the fleet's in-SLO capacity
    (``replicas × knee × headroom``) and ``serve_underload`` while the
    fleet idles under ``low_water`` of it.  Both directions carry their
    own recovery-re-armed episodes (ServeWatcher's pattern): load that
    breaches, recovers, and breaches again deserves a fresh decision,
    not a cooldown leftover."""

    def __init__(self, stats_fn, knee_per_replica: float, *,
                 headroom: float = 0.85, low_water: float = 0.35,
                 min_replicas: int = 1, scope: str = "serve"):
        self.stats_fn = stats_fn
        self.knee = float(knee_per_replica)
        self.headroom = headroom
        self.low_water = low_water
        self.min_replicas = min_replicas
        self.scope = scope
        self._episode = {"up": 0, "down": 0}
        self._held = {"up": False, "down": False}

    def _event(self, direction: str, kind: str, offered: float,
               replicas: int, capacity: float) -> AnomalyEvent:
        e = self._episode[direction]
        self._held[direction] = True
        return AnomalyEvent(
            kind=kind, key=f"serve_load:{direction}:e{e}",
            scope=self.scope, source="scrape", episode=f"e{e}",
            detail={"offered_per_s": round(offered, 3),
                    "capacity_per_s": round(capacity, 3),
                    "replicas": replicas,
                    "knee_per_replica": self.knee})

    def _recover(self, direction: str) -> None:
        if self._held[direction]:
            self._held[direction] = False
            self._episode[direction] += 1

    def poll(self) -> list[AnomalyEvent]:
        try:
            stats = self.stats_fn() or {}
        except Exception:             # noqa: BLE001 — a scrape failing
            return []                 # must read as "no data", never die
        offered = stats.get("offered_per_s")
        replicas = stats.get("replicas")
        if offered is None or not replicas:
            return []
        capacity = replicas * self.knee * self.headroom
        if offered > capacity:
            self._recover("down")
            return [self._event("up", "serve_overload", offered,
                                replicas, capacity)]
        if (replicas > self.min_replicas
                and offered < replicas * self.knee * self.low_water):
            self._recover("up")
            return [self._event("down", "serve_underload", offered,
                                replicas, capacity)]
        self._recover("up")
        self._recover("down")
        return []


# --- actuator factories ----------------------------------------------------

class FleetTarget:
    """Late-bound fleet handle: ``run_remediated`` swaps the live
    FleetSupervisor in per relaunch, so actuators built once keep
    pointing at the CURRENT gang."""

    def __init__(self):
        self.fleet = None

    def request_stop(self, reason: str) -> dict:
        fleet = self.fleet
        if fleet is None:
            return {"noop": "no live fleet"}
        fleet.request_stop(reason)
        return {"stopped": reason, "ranks": list(fleet.ranks)}

    def ranks(self) -> list[int]:
        return list(self.fleet.ranks) if self.fleet is not None else []


def make_evict_actuator(target: FleetTarget, reason: str = "heal_evict"):
    """Straggler/regression → loss-free gang stop: every rank saves and
    exits 143; the caller's relaunch resumes bitwise from the agreed
    step.  Idempotent: stopping a stopped (or finished) gang is a
    no-op."""
    def evict(ev: AnomalyEvent) -> dict:
        return target.request_stop(reason)
    return evict


def make_rollback_actuator(snapshot_dir_template: str,
                           target: FleetTarget | None = None,
                           ranks=None):
    """NaN/plateau → gang rollback: pin the last-good step (newest step
    EVERY rank holds valid that strictly predates the anomaly's
    ``fired_step``), discard everything newer on every rank, and stop
    the gang so the relaunch's agreement pass lands exactly there.
    "Valid" is ``snapshot.valid_steps`` — monolithic-valid UNION
    quorum-valid shard sets (resilience/shardstore.py), so a row-layout
    run rolls back to a step whose every 1/D shard is digest-intact (or
    ring-mirror-recoverable), and the discard covers both formats.
    Idempotent end to end: ``discard_newer`` finds already-discarded
    steps gone, and re-pinning the same step re-derives the same
    answer."""
    def rollback(ev: AnomalyEvent) -> dict:
        from distributedtensorflowexample_tpu.resilience import (
            snapshot as snap)
        rs = list(ranks) if ranks is not None else (
            target.ranks() if target is not None else [0])
        if not rs:
            rs = [0]
        dirs = {r: snapshot_dir_template.replace("{rank}", str(r))
                for r in rs}
        per_rank = {r: snap.valid_steps(d) for r, d in dirs.items()}
        common = set.intersection(*(set(v) for v in per_rank.values())) \
            if per_rank else set()
        good = [s for s in common
                if ev.step is None or s < ev.step]
        last_good = max(good) if good else 0
        discarded = {r: snap.SnapshotStore(d).discard_newer(last_good)
                     for r, d in dirs.items()}
        detail = {"last_good": last_good, "bad_from": ev.step,
                  "discarded": {str(r): v for r, v in discarded.items()}}
        if target is not None:
            detail.update(target.request_stop("heal_rollback"))
            detail.pop("noop", None)    # a dead gang still got rolled back
        return detail
    return rollback


def make_quarantine_actuator(target: FleetTarget):
    """Repeated-offender rank → quarantine: tombstone the rank's host
    down-forever (``mark_host_down(down_s=0)``), so neither the fleet's
    recovery re-probe nor the scheduler's grow policy ever hands it
    work again — the supervisor protocol's rc-3 "stop burning the
    window" rule, applied to one host.  An operator removes the
    tombstone to parole it.  Idempotent: re-tombstoning a tombstoned
    host rewrites the same file."""
    def quarantine(ev: AnomalyEvent) -> dict:
        from distributedtensorflowexample_tpu.resilience.faults import (
            mark_host_down)
        fleet = target.fleet
        if fleet is None or ev.rank is None:
            return {"noop": "no live fleet / event names no rank"}
        path = fleet._host_down_path(ev.rank)
        mark_host_down(path, down_s=0.0, rank=ev.rank)
        return {"rank": ev.rank, "tombstone": path}
    return quarantine


def make_slo_actuator(get_slo, set_slo, target_ms: float):
    """Serving p99 breach → tighten admission: clamp the live SLO down
    to ``target_ms`` (never loosen — that direction is an operator
    decision).  Idempotent: re-clamping to the same value is a no-op
    with a truthful row."""
    def tighten(ev: AnomalyEvent) -> dict:
        current = get_slo()
        new = target_ms if not current or current <= 0 \
            else min(current, target_ms)
        set_slo(new)
        return {"slo_ms": new, "was": current,
                "p99_ms": ev.detail.get("p99_ms")}
    return tighten


def make_lr_drop_actuator(advisory_path: str, factor: float = 0.5):
    """Plateau → LR-drop advisory (stub, behind ``HEAL_LR_DROP``): no
    live trainer seam consumes this yet, so the actuator's whole effect
    is one advisory file — ``{"scale", "fired_step", "kind"}`` — that a
    future LR hook reads at its next consensus poll, plus the
    ``heal_lr_drop`` ledger row.  Idempotent: rewriting the same
    advisory is a no-op in effect; repeated plateaus compound the scale
    so each action asks for a genuinely smaller LR."""
    def lr_drop(ev: AnomalyEvent) -> dict:
        prior = 1.0
        try:
            with open(advisory_path, encoding="utf-8") as f:
                prior = float((json.load(f) or {}).get("scale", 1.0))
        except (OSError, ValueError):
            pass
        scale = prior * factor
        tmp = advisory_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"scale": scale, "fired_step": ev.step,
                       "kind": ev.kind}, f)
        os.replace(tmp, advisory_path)
        return {"advisory": advisory_path, "scale": scale,
                "factor": factor, "stub": True}
    return lr_drop


def make_autoscale_actuator(get_replicas, set_replicas, *,
                            knee_per_replica: float,
                            min_replicas: int = 1,
                            max_replicas: int = 8,
                            headroom: float = 0.85):
    """Overload/underload → resize the serve replica fleet against the
    measured knee: the target is the replica count whose in-SLO
    capacity (``replicas × knee × headroom``) covers the offered load,
    clamped to ``[min_replicas, max_replicas]`` and to ONE step per
    action in the shrink direction (an autoscaler may chase a spike up
    quickly, but giving capacity back is done a replica at a time — a
    mis-measured lull must not halve the fleet).  At the max-replica
    ceiling an overload answers ``noop`` — the loud "policy cannot help
    further, operator must grow the ceiling" refusal, which costs no
    budget and no cooldown.  Idempotent: re-scaling to the current
    count is a no-op with a truthful row."""
    def scale(ev: AnomalyEvent) -> dict:
        current = int(get_replicas())
        offered = float(ev.detail.get("offered_per_s") or 0.0)
        want = max(min_replicas, math.ceil(
            offered / (knee_per_replica * headroom))
            if offered > 0 else min_replicas)
        if ev.kind == "serve_overload":
            target = min(max_replicas, max(current + 1, want))
            if current >= max_replicas:
                return {"noop": f"already at max_replicas "
                                f"{max_replicas} — the policy cannot "
                                f"add capacity; raise the ceiling or "
                                f"shed load (slo_tighten)"}
        else:
            target = max(min_replicas, min(current - 1, want))
            if current <= min_replicas:
                return {"noop": f"already at min_replicas "
                                f"{min_replicas}"}
        if target == current:
            return {"noop": f"already at target {current} replica(s)"}
        set_replicas(target)
        return {"replicas": target, "was": current,
                "offered_per_s": round(offered, 3),
                "knee_per_replica": knee_per_replica}
    return scale


# --- the self-healing fleet runner -----------------------------------------

def run_remediated(make_fleet, argv: list[str], remediator: Remediator,
                   watchers: list, *, target: FleetTarget | None = None,
                   name: str = "", snapshot_dir_template: str = "",
                   stdout_dir: str | None = None,
                   env_extra: dict | None = None,
                   poll_s: float = 0.25, max_heals: int = 4,
                   drain_polls: int = 3) -> dict:
    """Drive a gang to completion under remediation: launch via
    ``make_fleet()``, poll the watchers into the remediator while the
    gang runs, and relaunch (``agree_first`` — resuming over stores a
    previous incarnation wrote) whenever a heal action stopped it or a
    post-mortem poll healed a dead one, up to ``max_heals`` relaunches.

    Heal relaunches export ``SUPERVISE_ATTEMPT=<launch>`` so transient
    FaultPlans (tools/faultline.py) stay cleared across the new
    FleetSupervisor incarnation — the same "a retry models recovered
    hardware" semantics an in-fleet restart has.

    Returns ``{"results": [GangResult...], "healed": int,
    "timeline": [(wall_ts, what)...], "status": <final>}``."""
    results = []
    timeline: list = []
    launch = 0
    while True:
        fleet = make_fleet()
        if target is not None:
            target.fleet = fleet
        extra = dict(env_extra or {})
        if launch > 0:
            extra.setdefault("SUPERVISE_ATTEMPT", str(launch))
        # Per-launch stdout: each incarnation restarts the fleet's
        # attempt numbering at 0, and a healed relaunch must not
        # clobber the evicted launch's JSON tails (both are evidence —
        # the drill's zero-lost-steps proof reads all of them).
        out_dir = (os.path.join(stdout_dir, f"launch{launch}")
                   if stdout_dir else None)
        timeline.append((obs_metrics._wall(), f"launch{launch}"))
        box: list = []

        def _run(fleet=fleet, extra=extra, launch=launch,
                 out_dir=out_dir):
            try:
                box.append(fleet.run(
                    argv, name=name,
                    snapshot_dir_template=snapshot_dir_template,
                    stdout_dir=out_dir, env_extra=extra or None,
                    agree_first=launch > 0))
            except BaseException as e:   # noqa: BLE001 — surfaced below
                box.append(e)

        t = threading.Thread(target=_run, daemon=True,
                             name=f"heal-fleet-{launch}")
        actions_before = remediator.guardrails.actions_used
        t.start()
        while t.is_alive():
            for w in watchers:
                for ev in w.poll():
                    remediator.observe(ev)
            time.sleep(poll_s)
        t.join()
        # Post-mortem polls: a NaN child dies fast, but its health.json
        # survives — the rollback decision happens HERE, after the gang
        # is already gone (request_stop degrades to a no-op).
        for _ in range(drain_polls):
            for w in watchers:
                for ev in w.poll():
                    remediator.observe(ev)
        res = box[0] if box else None
        if isinstance(res, BaseException):
            raise res
        results.append(res)
        healed_now = remediator.guardrails.actions_used - actions_before
        timeline.append((obs_metrics._wall(),
                         f"result{launch}:{res.status if res else '?'}"))
        done = res is not None and res.status == "ok"
        if done or healed_now == 0 or launch >= max_heals:
            return {"results": results, "healed": launch,
                    "timeline": timeline,
                    "status": res.status if res else "unknown"}
        launch += 1
