# graftlint: stdlib-only
"""The serve-traffic world model: piecewise-constant offered load
(stepped by ``serve_load`` scenario events) against a replica count
the autoscale actuator moves.

This is the sim's stand-in for ``serving/``'s admission telemetry: the
:class:`~distributedtensorflowexample_tpu.resilience.remediate.
AutoscaleWatcher` polls :meth:`stats`, and
``make_autoscale_actuator`` calls :meth:`set_replicas` — both the REAL
policy objects, wired to simulated physics.  The model also keeps the
books the policy is judged on: seconds spent offered-above-capacity
(SLO breach exposure) and replica-seconds (the capacity bill), sampled
at every load/replica transition so the integral is exact, not
polled."""

from __future__ import annotations


class TrafficModel:
    def __init__(self, clock, *, replicas: int,
                 knee_per_replica: float):
        self.clock = clock
        self.knee = float(knee_per_replica)
        self._replicas = int(replicas)
        self._offered = 0.0
        self._last_t = 0.0
        self.breach_s = 0.0          # seconds with offered > capacity
        self.replica_s = 0.0         # integral of replicas over time
        #: (virtual_ts, offered_per_s, replicas) at every transition —
        #: the Perfetto timeline's serve track.
        self.timeline: list[tuple] = []
        self._mark()

    def _accrue(self) -> None:
        now = self.clock.now()
        dt = max(0.0, now - self._last_t)
        if self._offered > self._replicas * self.knee:
            self.breach_s += dt
        self.replica_s += dt * self._replicas
        self._last_t = now

    def _mark(self) -> None:
        self.timeline.append(
            (self.clock.now(), self._offered, self._replicas))

    # --- the world side (scenario events) ------------------------------

    def set_offered(self, offered_per_s: float) -> None:
        self._accrue()
        self._offered = float(offered_per_s)
        self._mark()

    # --- the policy side (watcher + actuator) --------------------------

    def stats(self) -> dict:
        return {"offered_per_s": self._offered,
                "replicas": self._replicas}

    def get_replicas(self) -> int:
        return self._replicas

    def set_replicas(self, n: int) -> None:
        self._accrue()
        self._replicas = int(n)
        self._mark()

    # --- the books -----------------------------------------------------

    def finalize(self) -> dict:
        """Close the integrals at the current virtual time."""
        self._accrue()
        self._mark()
        return {"breach_s": round(self.breach_s, 6),
                "replica_s": round(self.replica_s, 6),
                "final_replicas": self._replicas,
                "final_offered_per_s": self._offered}
