# graftlint: stdlib-only
"""The seeded discrete-event queue: a heap of ``(virtual_ts,
push_seq, callback)``.

``push_seq`` is the total order that makes the sim deterministic: two
events at the same virtual timestamp fire in the order they were
scheduled, never in heap-internal or thread-arrival order.  Callbacks
may push further events (a gang completion schedules the traffic
model's next sample; a ``request_stop`` supersedes a pending
completion), which is why consumption is pop-one-at-a-time from the
virtual sleep loop, not a drained batch.
"""

from __future__ import annotations

import heapq


class EventQueue:
    def __init__(self):
        self._heap: list[tuple] = []
        self._seq = 0

    def push(self, ts: float, fn, label: str = "") -> int:
        """Schedule ``fn()`` at virtual time ``ts``; returns the push
        seq (useful for logging/generation checks)."""
        self._seq += 1
        heapq.heappush(self._heap, (ts, self._seq, label, fn))
        return self._seq

    def peek_ts(self) -> float | None:
        return self._heap[0][0] if self._heap else None

    def pop(self) -> tuple:
        """(ts, seq, label, fn) of the earliest event."""
        return heapq.heappop(self._heap)

    def __len__(self) -> int:
        return len(self._heap)
