"""The simulated fleet: gang objects with the ``FleetSupervisor``
surface (``ranks``/``lost_ranks``/``stragglers``/``run``/
``request_stop``/``probe_lost_ranks``) whose lifecycle is an event on
the virtual queue instead of 10,000 subprocesses.

The determinism protocol (DESIGN.md §25):

* **Registration is synchronous.** The scheduler calls the factory
  inside ``_launch`` on its own thread; the factory registers the gang
  with the hub and schedules its completion event THEN — before the
  fleet thread even starts — so event order depends only on virtual
  time + push seq, never on thread scheduling.
* **``run()`` is a rendezvous, not a loop.** The scheduler's per-gang
  thread enters ``run()``, flags ``_entered``, and blocks on ``_done``.
  The hub's completion handler (fired from the virtual sleep on the
  scheduler thread) waits for ``_entered``, deposits the result, sets
  ``_done``, then JOINS the gang thread — so the very next ``_reap``
  sees ``st.thread.is_alive() == False`` deterministically.
* **Completions carry a generation.** ``request_stop`` / a host loss /
  a straggler changes the gang's future, so it bumps ``_gen`` and
  schedules a superseding completion; a stale event checks the
  generation and no-ops.

The work model: a gang at full width retires ``1/est_step_time_s``
steps per virtual second, scaled by ``width/full_width`` when an
elastic gang shrinks and by ``straggle_factor`` while any rank is a
named straggler.  Progress (``steps_done``) lives on the PERSISTENT
job record in the hub, not on the placement — an evicted gang's
relaunch resumes exactly where the snapshot agreement left it, which
is what makes ``*_steps_lost == 0`` an invariant the metrics pass can
assert rather than assume.

The simulated gang writes NOTHING to its fleet journal or the ledger:
the rows under test are the control plane's own (``sched_*`` /
``heal_*``), and an absent ``fleet.jsonl`` short-circuits the
scheduler's orphan sweep exactly like a first launch does live.
"""

from __future__ import annotations

import math
import os
import threading

from distributedtensorflowexample_tpu.resilience.fleet import (
    GangResult, RankLostError)


class SimGang:
    """One placement of one job.  Mimics the ``FleetSupervisor``
    surface the Scheduler reads; all mutation happens on the scheduler
    thread (factory call, scripted events, ``request_stop``) or is a
    plain read from the gang thread."""

    def __init__(self, hub, job_id: str, num_ranks: int, *,
                 elastic: bool, policy, wall_timeout_s: float):
        self.hub = hub
        self.job_id = job_id
        self.full_width = num_ranks
        self.ranks = list(range(num_ranks))
        self.lost_ranks: list[int] = []
        self.stragglers: list[int] = []
        self.elastic = elastic
        self.policy = policy
        self.wall_timeout_s = wall_timeout_s
        # run()/completion rendezvous (see module docstring).
        self._entered = threading.Event()
        self._done = threading.Event()
        self._thread: threading.Thread | None = None
        self._result = None          # GangResult | BaseException
        self._stopped = False
        # Work model state (all virtual-time).
        self._gen = 0                # completion-event generation
        self._rate_t = 0.0           # virtual ts of the last rate change
        self._started = False        # past startup, accruing steps
        self._up_at = 0.0            # virtual ts startup latency ends
        self._restarts = 0
        self._recoverable: list[int] = []

    # --- the FleetSupervisor surface ----------------------------------

    def run(self, argv, name="", snapshot_dir_template="",
            stdout_dir="", env_extra=None, agree_first=False):
        """Block until the hub delivers this placement's outcome.
        The scheduler's _run wrapper catches the raise; everything
        else about the placement already happened synchronously."""
        self._thread = threading.current_thread()
        self._entered.set()
        self._done.wait()
        if isinstance(self._result, BaseException):
            raise self._result
        return self._result

    def request_stop(self, reason: str = "") -> None:
        """Clean TERM→snapshot→143 stop: freeze progress now, retire
        the pending completion, and schedule the unanimous-143 exit
        after the scripted teardown latency."""
        if self._stopped or self._done.is_set():
            return
        self._stopped = True
        self.hub.on_request_stop(self, reason)

    def probe_lost_ranks(self, argv) -> list[int]:
        """Non-mutating recovery probe: which lost ranks would answer
        again (scripted by ``host_recover`` events)."""
        return [r for r in self._recoverable if r in self.lost_ranks]


class SimFleetFactory:
    """The spawn-seam injectable: callable with ``FleetSupervisor``'s
    constructor signature.  Parses the job id from the scheduler's
    per-job workdir (``.../jobs/<job>/fleet``) and hands the gang to
    the hub, which schedules its completion synchronously."""

    def __init__(self, hub):
        self.hub = hub

    def __call__(self, num_ranks, *, policy=None, journal=None,
                 heartbeat_timeout_s=0.0, wall_timeout_s=0.0,
                 kill_grace_s=0.0, poll_s=0.05, seed=0, elastic=False,
                 worker_tiled=False, workdir="", ledger_path="",
                 reprobe_on_relaunch=True):
        job_id = os.path.basename(os.path.dirname(
            os.path.abspath(workdir or "job")))
        gang = SimGang(self.hub, job_id, num_ranks, elastic=elastic,
                       policy=policy, wall_timeout_s=wall_timeout_s)
        self.hub.on_place(gang)
        return gang


class FleetHub:
    """Owns every live gang + per-job persistent progress; translates
    scenario events into gang futures.  Single-threaded by contract:
    every method runs on the scheduler thread (factory calls and
    ``request_stop`` from the tick loop, event callbacks from the
    virtual sleep)."""

    #: request_stop → unanimous-143 latency when the scenario doesn't
    #: script one (also the env override for drills).
    TEARDOWN_S = float(os.environ.get("SIM_TEARDOWN_S", "1.0"))

    def __init__(self, clock, queue, scenario):
        self.clock = clock
        self.queue = queue
        self.scenario = scenario
        self.gangs: dict[str, SimGang] = {}     # job id -> LIVE gang
        self.steps_done: dict[str, float] = {
            j.job: 0.0 for j in scenario.jobs}
        self.jobs = {j.job: j for j in scenario.jobs}
        #: (job, steps credited at done) — the metrics pass proves
        #: credited == job.steps, i.e. zero steps lost to evictions.
        self.done_credits: dict[str, float] = {}
        #: Snapshot world model (``snapshot_loss`` events): which ranks
        #: have lost their shard since the last quorum-valid step, the
        #: step the fleet agreement would fall back to if redundancy
        #: runs out, and the tallies the harness surfaces when a
        #: scenario scripts any loss.  Mirrors resilience/shardstore:
        #: a loss WITHIN redundancy is a reconstruction (no progress
        #: impact); losses at or past ``SNAPSHOT_REDUNDANCY`` roll the
        #: job back to the quorum floor and re-run the gap — time is
        #: lost, steps are re-earned, ``steps_lost()`` stays 0.
        self.shard_losses: dict[str, set] = {}
        self.quorum_floor: dict[str, int] = {}
        self.snap_stats = {"losses": 0, "reconstructs": 0, "rollbacks": 0}
        self.snapshot_redundancy = max(
            1, int(os.environ.get("SNAPSHOT_REDUNDANCY", "") or 2))

    # --- work model ----------------------------------------------------

    def _knobs(self, job_id: str) -> dict:
        return self.scenario.sim_jobs[job_id]

    def _rate(self, gang: SimGang) -> float:
        """Steps per virtual second, given current width/stragglers."""
        job = self.jobs[gang.job_id]
        rate = 1.0 / job.est_step_time_s
        if gang.full_width:
            rate *= len(gang.ranks) / gang.full_width
        if gang.stragglers:
            rate *= self._knobs(gang.job_id)["straggle_factor"]
        return rate

    def _settle(self, gang: SimGang) -> None:
        """Credit progress accrued since the last rate change at the
        OLD rate; call before every rate/width/future change."""
        now = self.clock.now()
        if gang._started and not gang._done.is_set():
            dt = max(0.0, now - gang._rate_t)
            job = self.jobs[gang.job_id]
            self.steps_done[gang.job_id] = min(
                float(job.steps),
                self.steps_done[gang.job_id] + dt * self._rate(gang))
        gang._rate_t = now

    def _reschedule(self, gang: SimGang) -> None:
        """Retire the pending completion (generation bump) and push a
        fresh one from current progress at the current rate."""
        gang._gen += 1
        gen = gang._gen
        job = self.jobs[gang.job_id]
        remaining = float(job.steps) - self.steps_done[gang.job_id]
        rate = self._rate(gang)
        if rate <= 0 or not gang.ranks:
            return          # a widthless gang makes no progress
        lead = (0.0 if gang._started
                else max(0.0, gang._up_at - self.clock.now()))
        eta = self.clock.now() + lead + remaining / rate
        self.queue.push(
            eta, lambda: self._complete(gang, gen, "ok"),
            label=f"done:{gang.job_id}")

    # --- gang lifecycle ------------------------------------------------

    def on_place(self, gang: SimGang) -> None:
        """Factory-call time (synchronous, scheduler thread): register
        the placement, mark startup, schedule its natural completion."""
        self.gangs[gang.job_id] = gang
        gang._rate_t = self.clock.now()
        knobs = self._knobs(gang.job_id)
        gang._up_at = self.clock.now() + knobs["startup_s"]
        self._reschedule(gang)
        # Startup latency ends once; after it the gang accrues steps.
        gen = gang._gen

        def _up():
            if gang._gen == gen and not gang._done.is_set():
                gang._started = True
                gang._rate_t = self.clock.now()
        self.queue.push(gang._up_at, _up, label=f"up:{gang.job_id}")

    def on_request_stop(self, gang: SimGang, reason: str) -> None:
        self._settle(gang)
        # Snapshot agreement floors progress to a whole step — the
        # relaunch resumes from an agreed step, not a fraction — and
        # TERM'd ranks stop stepping, so no progress accrues during
        # teardown.
        self.steps_done[gang.job_id] = math.floor(
            self.steps_done[gang.job_id])
        gang._started = False
        gang._gen += 1
        gen = gang._gen
        teardown = self._knobs(gang.job_id).get(
            "teardown_s", self.TEARDOWN_S)
        self.queue.push(
            self.clock.now() + teardown,
            lambda: self._complete(gang, gen, "evicted"),
            label=f"stop:{gang.job_id}")

    def _complete(self, gang: SimGang, gen: int, status: str,
                  result=None) -> None:
        """Deliver the placement outcome to the blocked gang thread
        and join it (see the determinism protocol)."""
        if gang._gen != gen or gang._done.is_set():
            return                              # superseded
        self._settle(gang)
        job = self.jobs[gang.job_id]
        if result is None:
            if status == "ok":
                self.steps_done[gang.job_id] = float(job.steps)
                self.done_credits[gang.job_id] = float(job.steps)
                rcs = {r: 0 for r in gang.ranks}
            else:                               # evicted (clean 143s)
                rcs = {r: 143 for r in gang.ranks}
            result = GangResult(
                status, 1, gang._restarts, 0,
                [int(self.steps_done[gang.job_id])], rcs,
                list(gang.ranks), [])
        gang._result = result
        if self.gangs.get(gang.job_id) is gang:
            del self.gangs[gang.job_id]
        # The gang thread must have entered run() by now — _launch
        # starts it before the tick loop ever sleeps.  The wait is
        # wall-clock but bounds only delivery latency, never virtual
        # order.
        if not gang._entered.wait(timeout=30.0):
            raise RuntimeError(
                f"sim gang {gang.job_id}: fleet thread never entered "
                f"run() — scheduler wiring broke")
        gang._done.set()
        if gang._thread is not None:
            gang._thread.join(timeout=30.0)
            if gang._thread.is_alive():
                raise RuntimeError(
                    f"sim gang {gang.job_id}: fleet thread failed to "
                    f"exit after result delivery")

    # --- scripted world events ----------------------------------------

    def apply(self, ev) -> None:
        """Fire one scenario event against the current fleet.  Events
        addressing a job with no live gang no-op (the storm outran the
        placement) — the scenario scripts the WORLD, and a dead host
        in an empty rack is weather, not an error."""
        gang = self.gangs.get(ev.job)
        if gang is None or gang._done.is_set():
            return
        if ev.kind == "host_loss":
            rank = ev.rank if ev.rank is not None else gang.ranks[-1]
            if rank not in gang.ranks:
                return
            self._settle(gang)
            if not gang.elastic:
                # Non-elastic: the placement is lost; the scheduler's
                # reap turns this into a budgeted retry.
                gang._gen += 1
                gen = gang._gen
                self.queue.push(
                    self.clock.now(),
                    lambda: self._complete(
                        gang, gen, "lost",
                        result=RankLostError(
                            rank, 1, "host_down",
                            f"rank {rank} lost: scripted host loss")),
                    label=f"lost:{ev.job}")
                return
            gang.ranks = [r for r in gang.ranks if r != rank]
            gang.lost_ranks = gang.lost_ranks + [rank]
            gang._restarts += 1
            self._reschedule(gang)
        elif ev.kind == "host_recover":
            if ev.rank in gang.lost_ranks \
                    and ev.rank not in gang._recoverable:
                gang._recoverable = gang._recoverable + [ev.rank]
        elif ev.kind == "straggler":
            rank = ev.rank if ev.rank is not None else gang.ranks[0]
            if rank in gang.stragglers:
                return
            self._settle(gang)
            gang.stragglers = gang.stragglers + [rank]
            self._reschedule(gang)
        elif ev.kind == "straggler_clear":
            if ev.rank not in gang.stragglers:
                return
            self._settle(gang)
            gang.stragglers = [r for r in gang.stragglers
                               if r != ev.rank]
            self._reschedule(gang)
        elif ev.kind == "gang_crash":
            self._settle(gang)
            retries = gang.policy.retries if gang.policy else 0
            gang._gen += 1
            gen = gang._gen
            rcs = {r: 1 for r in gang.ranks}
            res = GangResult(
                "exhausted", retries + 1, retries, 0,
                [int(self.steps_done[gang.job_id])], rcs,
                list(gang.ranks),
                [f"gang attempt {retries + 1}: crash (scripted)"])
            self.queue.push(
                self.clock.now(),
                lambda: self._complete(gang, gen, "exhausted",
                                       result=res),
                label=f"crash:{ev.job}")
        elif ev.kind == "gang_wedge":
            self._settle(gang)
            gang._gen += 1
            gen = gang._gen
            rcs = {r: (3 if r == gang.ranks[0] else 143)
                   for r in gang.ranks}
            res = GangResult(
                "wedged", 1, gang._restarts, 0,
                [int(self.steps_done[gang.job_id])], rcs,
                list(gang.ranks),
                ["rank reported backend wedged (rc 3, scripted)"])
            self.queue.push(
                self.clock.now(),
                lambda: self._complete(gang, gen, "wedged", result=res),
                label=f"wedge:{ev.job}")
        elif ev.kind == "snapshot_loss":
            rank = ev.rank if ev.rank is not None else gang.ranks[0]
            self._settle(gang)
            lost = self.shard_losses.setdefault(ev.job, set())
            if not lost:
                # First loss since the last intact set: the newest
                # quorum-valid step is frozen HERE — ring mirrors cover
                # further losses until redundancy runs out.
                self.quorum_floor[ev.job] = math.floor(
                    self.steps_done[ev.job])
            lost.add(rank)
            self.snap_stats["losses"] += 1
            if len(lost) >= self.snapshot_redundancy:
                # Past redundancy: the newest shard set is
                # unrecoverable.  Roll the job back to the quorum floor
                # and relaunch through the scheduler — the gap re-runs,
                # so the rollback costs TIME, never credited steps.
                self.snap_stats["rollbacks"] += 1
                self.steps_done[ev.job] = min(
                    self.steps_done[ev.job],
                    float(self.quorum_floor.pop(ev.job, 0)))
                lost.clear()
                gang.request_stop("snapshot_loss")
            else:
                # Within redundancy: the mirror rebuilds the shard
                # out-of-band; training never notices.
                self.snap_stats["reconstructs"] += 1
        else:
            raise ValueError(f"unhandled scenario event {ev.kind!r}")

    def steps_lost(self) -> float:
        """Across every job that finished: steps the job was credited
        minus steps it was asked to run.  The snapshot-resume contract
        says this is EXACTLY zero."""
        return sum(float(self.jobs[j].steps) - credited
                   for j, credited in self.done_credits.items())
