"""Distill a sim run into ratchet-format record rows — computed FROM
the ledger and journal rows the REAL control plane wrote, never from
sim-internal state (the whole point is that the evidence trail is the
live one).

Row families:

* **queue waits** — every ``sched_submit``/``sched_evict``/
  ``sched_retry``/``sched_grow`` opens a wait; the job's next
  ``sched_place`` closes it.  p50/p90/p99/max over all waits.
* **preemption storms** — total evictions + the worst count inside any
  sliding ``STORM_WINDOW_S`` virtual window.
* **MTTR tails** — ``heal_detect`` (straggler) → the scoped job's next
  ``sched_place``: detection-to-recovered-placement, the sim analogue
  of PR 16's measured MTTR drills.
* **suppression ledger** — ``heal_suppressed`` counts by reason
  (flap/cooldown/budget/noop): proof the guardrails BOUND under storm.
* **must-be-zero invariants** — ``sim_fleet_steps_lost`` (snapshot
  resume forgot work) and ``sim_wal_unbalanced_violations`` (a
  ``sched_intent`` whose effect never landed) end in the suffixes
  ``tools/bench_ratchet.py`` refuses to let regress above zero.
"""

from __future__ import annotations

from distributedtensorflowexample_tpu.obs import ledger as obs_ledger

#: Sliding window for the preemption-storm peak (virtual seconds).
STORM_WINDOW_S = 60.0

_REQUEUE = ("sched_submit", "sched_evict", "sched_retry", "sched_grow")


def _pct(values: list[float], q: float) -> float:
    if not values:
        return 0.0
    v = sorted(values)
    idx = min(len(v) - 1, max(0, round(q * (len(v) - 1))))
    return v[idx]


def _row(metric: str, value, unit: str, **detail) -> dict:
    return {"metric": metric, "value": value, "unit": unit,
            "platform": "cpu", "detail": detail or None}


def queue_waits(rows: list[dict]) -> list[float]:
    open_at: dict[str, float] = {}
    waits: list[float] = []
    for r in rows:
        job, ev, ts = r.get("job"), r.get("event"), r.get("ts")
        if not job or ts is None:
            continue
        if ev in _REQUEUE:
            open_at[job] = ts
        elif ev == "sched_place" and job in open_at:
            waits.append(round(ts - open_at.pop(job), 6))
    return waits


def storm_peak(rows: list[dict]) -> int:
    evs = sorted(r["ts"] for r in rows
                 if r.get("event") == "sched_evict")
    peak = lo = 0
    for hi in range(len(evs)):
        while evs[hi] - evs[lo] > STORM_WINDOW_S:
            lo += 1
        peak = max(peak, hi - lo + 1)
    return peak


def mttr_tails(rows: list[dict]) -> list[float]:
    """heal_detect → the same job's next sched_place (the healed
    relaunch), per detection key."""
    pending: dict[str, float] = {}      # job -> earliest open detect ts
    tails: list[float] = []
    for r in rows:
        ev, job, ts = r.get("event"), r.get("job"), r.get("ts")
        if ev == "heal_detect" and job and job != "serve":
            pending.setdefault(job, ts)
        elif ev == "sched_place" and job in pending:
            tails.append(round(ts - pending.pop(job), 6))
    return tails


def suppressed_by_reason(rows: list[dict]) -> dict:
    out: dict[str, int] = {}
    for r in rows:
        if r.get("event") == "heal_suppressed":
            reason = r.get("reason") or "unknown"
            out[reason] = out.get(reason, 0) + 1
    return out


def wal_unbalanced(journal_events) -> int:
    """Intents whose effect never landed: a ``sched_intent`` seq with
    no later same-seq applied/superseded row.  The live WAL contract
    says this is zero at quiescence."""
    intents: set = set()
    for rec in journal_events:
        ev = rec.get("event", "")
        seq = rec.get("seq")
        if ev == "sched_intent":
            intents.add(seq)
        elif ev.startswith("sched_") and isinstance(seq, int):
            intents.discard(seq)
    return len(intents)


def distill(world, prefix: str = "sim") -> list[dict]:
    """SimWorld (after ``run()``) → ratchet record rows.  ``prefix``
    namespaces the metric names per scenario (``sim_fleet10k_...``) so
    a battery's rows coexist in one record file."""
    summary = world.summary or {}
    rows, torn = obs_ledger.read_rows(world.ledger_path)
    waits = queue_waits(rows)
    tails = mttr_tails(rows)
    sup = suppressed_by_reason(rows)
    counts = (summary.get("summary") or {}).get("counts") or {}
    out = [
        _row(f"{prefix}_ranks", summary.get("total_ranks", 0), "ranks",
             scenario=summary.get("scenario"),
             seed=summary.get("seed")),
        _row(f"{prefix}_virtual_s", summary.get("virtual_s", 0.0), "s"),
        _row(f"{prefix}_jobs_done", counts.get("done", 0), "jobs",
             counts=counts),
        _row(f"{prefix}_queue_wait_p50_s", _pct(waits, 0.50), "s",
             n=len(waits)),
        _row(f"{prefix}_queue_wait_p99_s", _pct(waits, 0.99), "s",
             p90=_pct(waits, 0.90), max=max(waits) if waits else 0.0),
        _row(f"{prefix}_evictions",
             sum(1 for r in rows if r.get("event") == "sched_evict"),
             "evictions", storm_peak=storm_peak(rows),
             storm_window_s=STORM_WINDOW_S),
        _row(f"{prefix}_mttr_p50_s", _pct(tails, 0.50), "s",
             n=len(tails)),
        _row(f"{prefix}_mttr_max_s", max(tails) if tails else 0.0, "s"),
        _row(f"{prefix}_heal_suppressed", sum(sup.values()),
             "suppressions", by_reason=sup or None),
        _row(f"{prefix}_fleet_steps_lost",
             summary.get("steps_lost", 0.0), "steps"),
        _row(f"{prefix}_wal_unbalanced_violations",
             wal_unbalanced(world.scheduler.journal.events()
                            if world.scheduler else []),
             "intents", torn_ledger_lines=torn),
    ]
    serve = summary.get("serve")
    if serve:
        ups = sum(1 for r in rows if r.get("event") == "heal_scale_up")
        downs = sum(1 for r in rows
                    if r.get("event") == "heal_scale_down")
        out.append(_row(
            f"{prefix}_autoscale_actions", ups + downs, "actions",
            scale_up=ups, scale_down=downs,
            actions_used=serve.get("actions_used"),
            final_replicas=serve.get("final_replicas")))
        out.append(_row(
            f"{prefix}_serve_breach_s", serve.get("breach_s", 0.0),
            "s", replica_s=serve.get("replica_s")))
    return out
