"""sim/ — a deterministic discrete-event simulator that drives the
REAL control plane at fleet scale.

The point of this package is what it does NOT contain: a scheduler.
The simulated fleet runs the unmodified
:class:`~distributedtensorflowexample_tpu.resilience.scheduler.Scheduler`
and :class:`~distributedtensorflowexample_tpu.resilience.remediate.
Remediator` — the same classes, the same WAL rows, the same
``obs_query why`` verdicts the live 4-process queue produces — against
10,000 simulated ranks, because every decision those classes make
already flows through two narrow seams:

* the **clock seam** (``obs/metrics._now``/``_wall`` + the scheduler's
  module-level ``_sleep``), proven bare-read-free by graftlint's
  clock-seam rule over ``obs/`` AND ``resilience/scheduler.py`` /
  ``resilience/remediate.py``;
* the **spawn seam** (``Scheduler(fleet_factory=...)``), where
  :class:`sim.fleet.SimFleetFactory` returns gang objects with the
  ``FleetSupervisor`` surface (``ranks``/``lost_ranks``/
  ``stragglers``/``run``/``request_stop``/``probe_lost_ranks``) whose
  lifecycles are scripted by a scenario file instead of subprocesses.

Everything is single-threaded-deterministic: a seeded event queue
ordered by ``(virtual_ts, push_seq)``, a virtual clock that only moves
when the scheduler's tick loop sleeps, and zero wall-clock reads — so
the same seed + scenario produces bitwise-identical journal and ledger
bytes, run after run.  DESIGN.md §25 holds the event model, the clock
contract, and the fidelity argument.
"""

from distributedtensorflowexample_tpu.sim.clock import (  # noqa: F401
    VirtualClock, installed_clock)
from distributedtensorflowexample_tpu.sim.events import (  # noqa: F401
    EventQueue)
from distributedtensorflowexample_tpu.sim.fleet import (  # noqa: F401
    FleetHub, SimFleetFactory)
from distributedtensorflowexample_tpu.sim.scenario import (  # noqa: F401
    SCENARIO_EVENTS, Scenario, load_scenario)
from distributedtensorflowexample_tpu.sim.harness import (  # noqa: F401
    SimWorld)
from distributedtensorflowexample_tpu.sim import (  # noqa: F401
    metrics as sim_metrics)
