"""The scenario DSL: a JSON file scripting what the simulated WORLD
does, never what the control plane decides.

Schema (all times in virtual seconds from sim start)::

    {
      "name": "fleet10k",             # stamps records + workdir
      "seed": 0,                      # Scheduler + jitter RNG seed
      "tick_s": 0.5,                  # scheduler policy-loop cadence
      "horizon_s": 3600,              # hard virtual-time ceiling
      "slices": {"podA": 2048, ...},  # multi-slice mesh (or "devices")
      "collective_fit": {"alpha_s":…, "beta_bytes_per_s":…},  # optional
      "jobs": [                       # resilience.scheduler.Job fields
        {"job": "t1", "kind": "train", "ranks": 256, "steps": 800,
         "est_step_time_s": 0.5, "state_bytes": 4194304,
         "sim": {"startup_s": 3.0, "teardown_s": 1.0}}, ...
      ],
      "serve": {                      # autoscale loop (optional)
        "replicas": 4, "knee_per_replica": 3779.67,
        "min_replicas": 1, "max_replicas": 16, "poll_s": 5.0,
        "headroom": 0.85, "low_water": 0.35,
        "flap_n": 2, "flap_window_s": 60, "cooldown_s": 60,
        "budget": 8                   # cooldown_s omitted -> seeded
      },                              # from the measured HEAL_* MTTR
                                      # record (remediate.
                                      # mttr_seeded_cooldown_s)
      "events": [                     # the scripted world
        {"at": 120, "kind": "host_loss", "job": "t1", "rank": 3}, ...
      ]
    }

``jobs[*].argv`` defaults to ``["sim"]`` — simulated gangs spawn no
processes, but the Job dataclass (and the grow probe's "does the
program resolve" check) wants a token.  ``jobs[*].sim`` holds the
world-model knobs the live scheduler never sees: gang startup/teardown
latency and the straggler slowdown factor.

Event kinds are the closed set below; an unknown kind refuses loudly
at load (a typo'd scenario must not silently run a milder storm).
``tools/sim_run.py`` mirrors this table for its ``--help``/validation
surface — the KEEP-IN-SYNC digest pair keeps writer and reader from
drifting.
"""

from __future__ import annotations

import dataclasses
import json
import os

from distributedtensorflowexample_tpu.resilience.scheduler import Job

# What the simulated world can DO to the fleet, one line each.
# KEEP-IN-SYNC(sim-scenario) digest=caa363679294
SCENARIO_EVENTS = (
    "host_loss",         # rank's host dies (elastic: shrink; else lost)
    "host_recover",      # lost host answers the recovery probe again
    "straggler",         # rank named straggler; gang slows by factor
    "straggler_clear",   # straggler recovers; gang speed restored
    "gang_crash",        # whole gang crashes (rcs 1 → budgeted retry)
    "gang_wedge",        # gang reports backend wedged (rc 3 quarantine)
    "serve_load",        # offered serve traffic steps to a new level
    "snapshot_loss",     # rank's snapshot shard lost (mirror or rollback)
)
# KEEP-IN-SYNC-END(sim-scenario)

#: Per-job world-model knobs (the ``sim`` sub-dict of a scenario job).
#: ``teardown_s`` (request_stop → unanimous-143 latency) is absent on
#: purpose: unset, it falls back to ``FleetHub.TEARDOWN_S`` so the
#: SIM_TEARDOWN_S env knob can stretch every teardown for drills.
SIM_JOB_DEFAULTS = {
    "startup_s": 2.0,       # place → first step latency
    "straggle_factor": 0.5,  # gang rate multiplier while straggling
}


@dataclasses.dataclass(frozen=True)
class SimEvent:
    at: float
    kind: str
    job: str = ""
    rank: int | None = None
    offered_per_s: float | None = None   # serve_load only


@dataclasses.dataclass
class Scenario:
    name: str
    seed: int
    tick_s: float
    horizon_s: float
    slices: dict | None          # name -> capacity; None = single mesh
    devices: int                 # single-mesh width (slices is None)
    collective_fit: dict | None
    jobs: list[Job]
    sim_jobs: dict               # job id -> resolved sim knobs
    serve: dict | None
    events: list[SimEvent]

    @property
    def total_ranks(self) -> int:
        return sum(j.ranks for j in self.jobs)


def load_scenario(source) -> Scenario:
    """Parse + validate a scenario: a path to a JSON file or an
    already-loaded dict.  Validation is loud and total — every event
    kind, every job reference, every time must check out before the
    sim runs a single tick."""
    if isinstance(source, str):
        with open(source) as f:
            payload = json.load(f)
    else:
        payload = dict(source)
    name = payload.get("name") or (
        os.path.splitext(os.path.basename(source))[0]
        if isinstance(source, str) else "scenario")
    horizon = float(payload.get("horizon_s") or 3600.0)
    jobs: list[Job] = []
    sim_jobs: dict = {}
    for rec in payload.get("jobs") or []:
        rec = dict(rec)
        sim_knobs = dict(SIM_JOB_DEFAULTS)
        sim_knobs.update(rec.pop("sim", None) or {})
        rec.setdefault("argv", ["sim"])
        job = Job.from_dict(rec)
        if not job.steps or not job.est_step_time_s:
            raise ValueError(
                f"scenario {name}: job {job.job!r} needs steps and "
                f"est_step_time_s — the sim's world model derives the "
                f"gang's runtime from them")
        jobs.append(job)
        sim_jobs[job.job] = sim_knobs
    if not jobs:
        raise ValueError(f"scenario {name}: no jobs")
    ids = {j.job for j in jobs}
    events: list[SimEvent] = []
    for rec in payload.get("events") or []:
        kind = rec.get("kind")
        if kind not in SCENARIO_EVENTS:
            raise ValueError(
                f"scenario {name}: unknown event kind {kind!r} "
                f"(known: {', '.join(SCENARIO_EVENTS)})")
        if kind != "serve_load" and rec.get("job") not in ids:
            raise ValueError(
                f"scenario {name}: event {kind!r} at {rec.get('at')} "
                f"names unknown job {rec.get('job')!r}")
        at = float(rec.get("at", -1))
        if not 0 <= at <= horizon:
            raise ValueError(
                f"scenario {name}: event {kind!r} at {at} is outside "
                f"[0, horizon_s {horizon}]")
        events.append(SimEvent(
            at=at, kind=kind, job=rec.get("job") or "",
            rank=rec.get("rank"),
            offered_per_s=rec.get("offered_per_s")))
    events.sort(key=lambda e: (e.at, e.kind, e.job, e.rank or -1))
    slices = payload.get("slices")
    if slices is not None:
        slices = {str(k): int(v) for k, v in slices.items()}
    serve = payload.get("serve")
    if serve is not None and not serve.get("knee_per_replica"):
        raise ValueError(
            f"scenario {name}: serve.knee_per_replica is required — "
            f"the autoscale policy prices capacity from the measured "
            f"SLO knee (SERVE_lm record), not a guess")
    return Scenario(
        name=name,
        seed=int(payload.get("seed") or 0),
        tick_s=float(payload.get("tick_s") or 0.5),
        horizon_s=horizon,
        slices=slices,
        devices=int(payload.get("devices") or 0) or (
            sum(slices.values()) if slices else 8),
        collective_fit=payload.get("collective_fit"),
        jobs=jobs, sim_jobs=sim_jobs, serve=serve, events=events)
