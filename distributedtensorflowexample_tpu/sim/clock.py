"""The virtual clock and its installation into the repo's seams.

The clock contract (DESIGN.md §25): the control plane reads time ONLY
through ``obs/metrics._now`` (monotonic) / ``_wall`` (epoch) and sleeps
ONLY through ``resilience/scheduler._sleep`` — graftlint's clock-seam
rule proves the read half statically.  ``installed_clock`` swaps all
three for the virtual clock and the event-pumping sleep, and restores
the real ones on exit, so a sim run and a live run execute the same
decision code with different physics.

Install BEFORE constructing the Scheduler/Remediator: ``Guardrails``
binds ``obs_metrics._wall`` at construction time (``clock or
obs_metrics._wall``), so a late install would leave the remediator's
flap/cooldown windows on the wall clock while everything else runs on
virtual time.
"""

from __future__ import annotations

import contextlib

from distributedtensorflowexample_tpu.obs import metrics as obs_metrics
from distributedtensorflowexample_tpu.resilience import (
    scheduler as sched_mod)


class VirtualClock:
    """Monotonic virtual seconds since sim start, plus a fixed epoch
    anchor so wall timestamps (journal/ledger ``ts`` fields) are
    deterministic and human-plausible.  Time NEVER moves on its own —
    only :meth:`advance_to`, called from the virtual sleep, moves it."""

    #: Deterministic epoch anchor (2020-09-13T12:26:40Z): same-seed
    #: runs must stamp identical wall ts; the real date would differ
    #: per run.
    EPOCH = 1_600_000_000.0

    def __init__(self, start_wall: float = EPOCH):
        self._mono = 0.0
        self._wall0 = float(start_wall)

    def now(self) -> float:
        return self._mono

    def wall(self) -> float:
        return self._wall0 + self._mono

    def advance_to(self, t: float) -> None:
        """Move to virtual time ``t`` (never backwards — an event
        popped at a ts the clock already passed fires 'now')."""
        if t > self._mono:
            self._mono = t


@contextlib.contextmanager
def installed_clock(clock: VirtualClock, sleep_fn):
    """Patch the three seams (``obs_metrics._now``/``_wall``,
    ``scheduler._sleep``) to the virtual clock + event-pumping sleep;
    restore the real clock on exit no matter how the sim ends."""
    saved = (obs_metrics._now, obs_metrics._wall, sched_mod._sleep)
    obs_metrics._now = clock.now
    obs_metrics._wall = clock.wall
    sched_mod._sleep = sleep_fn
    try:
        yield clock
    finally:
        obs_metrics._now, obs_metrics._wall, sched_mod._sleep = saved
