"""SimWorld: wire a scenario to the REAL control plane and run it to
quiescence on virtual time.

What runs here is the unmodified
:class:`~distributedtensorflowexample_tpu.resilience.scheduler.
Scheduler` (tick loop, packer, eviction pricing, grow/heal drives —
constructed with the sim's fleet factory) and, when the scenario has a
``serve`` section, a second REAL
:class:`~distributedtensorflowexample_tpu.resilience.remediate.
Remediator` running the autoscale policy against the traffic model.
The sim contributes only physics: the virtual clock, the scripted
events, and the simulated gangs.  ``SimWorld.run()`` must be called on
the MAIN thread — the scheduler installs its SIGTERM handler there,
exactly like the live ``tools/schedule.py`` entrypoint.

The virtual sleep is the sim's engine: every time the scheduler's tick
loop sleeps, the queue pumps every event due before the wake target,
advancing the clock to each event's timestamp in ``(virtual_ts,
push_seq)`` order.  Virtual time therefore moves ONLY inside the
scheduler's own sleeps — between them the control plane computes at a
frozen instant, which is what pins journal/ledger timestamps to the
decision that produced them.

``SIM_MAX_VIRTUAL_S`` (env) caps total virtual time — a scenario that
livelocks the queue (eviction ping-pong, a gate that never opens) dies
loudly at the cap instead of spinning the event loop forever.  Default:
10x the scenario horizon.
"""

from __future__ import annotations

import os

from distributedtensorflowexample_tpu.resilience import (
    remediate as heal_mod)
from distributedtensorflowexample_tpu.resilience.scheduler import (
    Scheduler)
from distributedtensorflowexample_tpu.resilience.supervisor import (
    Journal)
from distributedtensorflowexample_tpu.sim.clock import (
    VirtualClock, installed_clock)
from distributedtensorflowexample_tpu.sim.events import EventQueue
from distributedtensorflowexample_tpu.sim.fleet import (
    FleetHub, SimFleetFactory)
from distributedtensorflowexample_tpu.sim.scenario import (
    Scenario, load_scenario)
from distributedtensorflowexample_tpu.sim.traffic import TrafficModel


class SimWorld:
    def __init__(self, scenario, workdir: str):
        self.scenario: Scenario = (
            scenario if isinstance(scenario, Scenario)
            else load_scenario(scenario))
        self.workdir = os.path.abspath(workdir)
        os.makedirs(self.workdir, exist_ok=True)
        self.ledger_path = os.path.join(self.workdir, "RUNS.jsonl")
        self.clock = VirtualClock()
        self.queue = EventQueue()
        self.hub = FleetHub(self.clock, self.queue, self.scenario)
        self.max_virtual_s = float(
            os.environ.get("SIM_MAX_VIRTUAL_S", "0") or 0
        ) or self.scenario.horizon_s * 10.0
        self.traffic: TrafficModel | None = None
        self.scheduler: Scheduler | None = None
        self.serve_remediator: heal_mod.Remediator | None = None
        self.summary: dict | None = None

    # --- the engine ----------------------------------------------------

    def _virtual_sleep(self, dt: float) -> None:
        """The scheduler's ``_sleep`` replacement: advance virtual time
        by ``dt``, firing every event due on the way, in ``(ts, seq)``
        order."""
        target = self.clock.now() + dt
        if target > self.max_virtual_s:
            raise RuntimeError(
                f"sim exceeded SIM_MAX_VIRTUAL_S={self.max_virtual_s:g}"
                f"s of virtual time (scenario "
                f"{self.scenario.name!r}, horizon "
                f"{self.scenario.horizon_s:g}s) — the queue is "
                f"livelocked or the ceiling is too tight")
        while True:
            ts = self.queue.peek_ts()
            if ts is None or ts > target:
                break
            ts, _seq, _label, fn = self.queue.pop()
            self.clock.advance_to(ts)
            fn()
        self.clock.advance_to(target)

    # --- serve-side wiring ---------------------------------------------

    def _wire_serve(self) -> None:
        serve = self.scenario.serve
        if not serve:
            return
        knee = float(serve["knee_per_replica"])
        # Cooldown default: seeded from the newest measured HEAL_*
        # MTTR record (2x the worst proven detect->recovered tail)
        # rather than a hardcoded constant — a scenario that names
        # cooldown_s still wins, and the seed is deterministic (the
        # record is checked in), so same-seed runs stay bitwise.
        cooldown_s = serve.get("cooldown_s")
        if cooldown_s is None:
            cooldown_s = heal_mod.mttr_seeded_cooldown_s()
        self.traffic = TrafficModel(
            self.clock, replicas=int(serve.get("replicas", 1)),
            knee_per_replica=knee)
        actuator = heal_mod.make_autoscale_actuator(
            self.traffic.get_replicas, self.traffic.set_replicas,
            knee_per_replica=knee,
            min_replicas=int(serve.get("min_replicas", 1)),
            max_replicas=int(serve.get("max_replicas", 8)),
            headroom=float(serve.get("headroom", 0.85)))
        self.serve_remediator = heal_mod.Remediator(
            journal=Journal(os.path.join(self.workdir,
                                         "serve_heal.jsonl")),
            ledger_path=self.ledger_path,
            scope="serve",
            dry_run=False,
            actuators={"scale_up": actuator, "scale_down": actuator},
            policy={
                "serve_overload": heal_mod.HealRule("scale_up"),
                "serve_underload": heal_mod.HealRule(
                    "scale_down",
                    flap_n=int(serve.get("scale_down_flap_n", 4))),
            },
            guardrails=heal_mod.Guardrails(
                flap_n=serve.get("flap_n"),
                flap_window_s=serve.get("flap_window_s"),
                cooldown_s=cooldown_s,
                budget=serve.get("budget"),
                clock=self.clock.wall))
        watcher = heal_mod.AutoscaleWatcher(
            self.traffic.stats, knee,
            headroom=float(serve.get("headroom", 0.85)),
            low_water=float(serve.get("low_water", 0.35)),
            min_replicas=int(serve.get("min_replicas", 1)))
        poll_s = float(serve.get("poll_s", 5.0))

        def _poll():
            for ev in watcher.poll():
                self.serve_remediator.observe(ev)
            nxt = self.clock.now() + poll_s
            if nxt <= self.scenario.horizon_s:
                self.queue.push(nxt, _poll, label="serve:poll")
        self.queue.push(poll_s, _poll, label="serve:poll")

    # --- the run -------------------------------------------------------

    def run(self) -> dict:
        sc = self.scenario
        for ev in sc.events:
            if ev.kind == "serve_load":
                if self.scenario.serve is None:
                    raise ValueError(
                        f"scenario {sc.name}: serve_load event at "
                        f"{ev.at} but no serve section")
                self.queue.push(
                    ev.at,
                    lambda ev=ev: self.traffic.set_offered(
                        ev.offered_per_s or 0.0),
                    label=f"world:serve_load@{ev.at:g}")
            else:
                self.queue.push(
                    ev.at, lambda ev=ev: self.hub.apply(ev),
                    label=f"world:{ev.kind}:{ev.job}@{ev.at:g}")
        # Install the clock BEFORE constructing anything that binds
        # obs_metrics._wall at construction (Guardrails does).
        with installed_clock(self.clock, self._virtual_sleep):
            self._wire_serve()
            self.scheduler = Scheduler(
                list(sc.jobs),
                devices=sc.devices,
                workdir=os.path.join(self.workdir, "sched"),
                ledger_path=self.ledger_path,
                tick_s=sc.tick_s,
                poll_s=min(sc.tick_s, 0.25),
                seed=sc.seed,
                slices=dict(sc.slices) if sc.slices else None,
                collective_fit=sc.collective_fit,
                fleet_factory=SimFleetFactory(self.hub))
            summary = self.scheduler.run()
        out = {
            "scenario": sc.name,
            "seed": sc.seed,
            "virtual_s": round(self.clock.now(), 6),
            "total_ranks": sc.total_ranks,
            "steps_lost": self.hub.steps_lost(),
            "summary": summary,
        }
        if self.hub.snap_stats["losses"]:
            # Only when the scenario scripted snapshot_loss — scenarios
            # without one keep their exact summary shape.
            out["snapshots"] = dict(self.hub.snap_stats)
        if self.traffic is not None:
            out["serve"] = self.traffic.finalize()
            out["serve"]["actions_used"] = (
                self.serve_remediator.guardrails.actions_used)
        self.summary = out
        return out
