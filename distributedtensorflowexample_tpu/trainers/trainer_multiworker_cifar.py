"""Config 5 — multi-host data-parallel CIFAR-10 ResNet-20
(BASELINE.json configs[4]).

Reference stack (SURVEY.md §3d): ``MultiWorkerMirroredStrategy`` with
``TF_CONFIG`` cluster resolution and collective all-reduce across 2 hosts.
Rebuild: same SPMD program on every process — ``TF_CONFIG`` (or
``--worker_hosts``/``--coordinator_address``) resolves to
``jax.distributed.initialize``; the mesh spans all hosts' chips and the
gradient psum rides ICI within a slice / DCN across hosts.  Chief-only
logging/checkpointing == process 0 (the reference's chief semantics).
"""

from __future__ import annotations

import sys

from distributedtensorflowexample_tpu.config import parse_flags
from distributedtensorflowexample_tpu.engine import Engine, RunSpec


def main(argv=None) -> dict:
    cfg = parse_flags(argv, description=__doc__,
                      batch_size=128, train_steps=5000, learning_rate=0.1,
                      momentum=0.9, weight_decay=1e-4, lr_schedule="step",
                      warmup_steps=200, dataset="cifar10", job_name="worker")
    return Engine(RunSpec(model="resnet20", dataset="cifar10",
                          config=cfg, augment=True)).run()


if __name__ == "__main__":
    summary = main(sys.argv[1:])
    if not summary.get("exited"):
        print(f"final accuracy: {summary.get('final_accuracy', float('nan')):.4f}")
