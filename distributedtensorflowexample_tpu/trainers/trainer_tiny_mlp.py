"""The Engine payoff demo (DESIGN.md §26): a brand-new workload in
~50 lines.  No wiring — a TinyMLP module, a blob input_fn, and a
RunSpec; the Engine supplies the mesh, replication mode, collectives,
checkpointing, supervision, and telemetry the six reference trainers
share, so ``--sync_mode``, ``--bucket_grads``, SIGTERM preemption →
resume, and the obs ledger all work here unchanged.

  python -m distributedtensorflowexample_tpu.trainers.trainer_tiny_mlp \
      --train_steps 200
"""

from __future__ import annotations

import sys

import flax.linen as nn

from distributedtensorflowexample_tpu.config import parse_flags
from distributedtensorflowexample_tpu.data.synthetic import make_synthetic
from distributedtensorflowexample_tpu.engine import Engine, RunSpec

NUM_CLASSES = 4
FEATURES = (8, 8, 1)     # image-shaped so the shared eval path applies


class TinyMLP(nn.Module):
    hidden: int = 32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(self.hidden, name="hidden")(x))
        return nn.Dense(NUM_CLASSES, name="logits")(x)


def blobs(cfg, split):
    """Deterministic learnable blobs; train/test share templates
    (seed) and differ in draws (sample_seed) so accuracy generalizes."""
    return make_synthetic(4096 if split == "train" else 512, FEATURES,
                          NUM_CLASSES, seed=cfg.seed,
                          sample_seed=cfg.seed + (split == "test"))


def main(argv=None) -> dict:
    cfg = parse_flags(argv, description=__doc__, batch_size=32,
                      train_steps=300, learning_rate=0.1, momentum=0.9,
                      dataset="tiny_blobs", dropout=0.0)
    spec = RunSpec(model="tiny_mlp", dataset="tiny_blobs", config=cfg,
                   model_fn=lambda cfg: TinyMLP(), input_fn=blobs)
    return Engine(spec).run()


if __name__ == "__main__":
    summary = main(sys.argv[1:])
    print(f"final accuracy: {summary.get('final_accuracy', float('nan')):.4f}")
