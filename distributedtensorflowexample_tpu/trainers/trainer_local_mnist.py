"""Config 1 — single-process local MNIST softmax (BASELINE.json configs[0]).

Reference stack (SURVEY.md §3a): build softmax graph, ``sess.run(train_op,
feed_dict=...)`` per minibatch, final accuracy eval.  Rebuild: one jitted
SGD step on device-resident batches; runs unchanged on CPU or a single TPU
chip (``--num_devices=1``).
"""

from __future__ import annotations

import sys

from distributedtensorflowexample_tpu.config import parse_flags
from distributedtensorflowexample_tpu.engine import Engine, RunSpec


def main(argv=None) -> dict:
    cfg = parse_flags(argv, description=__doc__,
                      batch_size=100, train_steps=1000, learning_rate=0.5,
                      num_devices=1, dataset="mnist")
    return Engine(RunSpec(model="softmax", dataset="mnist",
                          config=cfg)).run()


if __name__ == "__main__":
    summary = main(sys.argv[1:])
    print(f"final accuracy: {summary.get('final_accuracy', float('nan')):.4f}")
