"""graft-LM — the flagship transformer workload (ROADMAP direction #5).

A decoder-only LM (models/transformer_lm.py) on the deterministic
synthetic token corpus (data/lm.py), run through the SAME shared trainer
runner as every reference config — so sync, async-PS emulation,
``--remat block``, ``--shard_update``, ``--bucket_grads``,
``--shard_params`` (ZeRO-3), device-resident (uint8 token) data,
checkpoints, supervision, and telemetry all apply unchanged.  BN-free
by construction: the bucketing/ZeRO BatchNorm refusals never trigger.

  python -m distributedtensorflowexample_tpu.trainers.trainer_lm \
      --size lm_tiny --train_steps 600
  python -m ...trainer_lm --size lm_base --shard_update true \
      --bucket_grads auto --remat block      # the knobs, where they bind
  python -m ...trainer_lm --size lm_base --shard_params true \
      --bucket_grads auto                    # ZeRO-3: params+grads+opt
                                             # resident 1/D per device,
                                             # double-buffered per-bucket
                                             # all-gather prefetch; NOTE
                                             # the checkpoint layout
                                             # becomes zero3_rows (resume
                                             # needs the same knobs+D)

``--size`` selects the ladder rung (lm_tiny | lm_small | lm_base —
models.LM_SIZES); everything else is the standard flag surface.
"""

from __future__ import annotations

import argparse
import sys

from distributedtensorflowexample_tpu.config import parse_flags
from distributedtensorflowexample_tpu.engine import Engine, RunSpec
from distributedtensorflowexample_tpu.models import LM_SIZES


def main(argv=None) -> dict:
    sp = argparse.ArgumentParser(add_help=False)
    sp.add_argument("--size", default="lm_tiny", choices=sorted(LM_SIZES))
    ns, rest = sp.parse_known_args(argv)
    overrides = dict(batch_size=16, train_steps=600, learning_rate=0.1,
                     momentum=0.9, dataset="lm", dropout=0.0,
                     log_every=100)
    if ns.size == "lm_base":
        # Measurement-driven defaults (BENCH_lm_cpu_r08.json A/B matrix
        # at lm_base/D=4): remat=block cut the per-device temp arena
        # 24.6% at bit-equal forward math (no measurable CPU cost
        # beyond contention noise), and bucket_grads fused 104
        # per-parameter all-reduces into 68 knee-sized ones at
        # unchanged math.  Both are parity-safe knobs; --shard_update
        # stays opt-in because it changes the checkpoint's
        # optimizer-state layout (a resume contract, not just a
        # schedule).  Explicit flags still win — these are argparse
        # defaults.
        overrides.update(remat="block", bucket_grads="auto")
    cfg = parse_flags(rest, description=__doc__, **overrides)
    return Engine(RunSpec(model=ns.size, dataset="lm", config=cfg)).run()


if __name__ == "__main__":
    summary = main(sys.argv[1:])
    print(f"final accuracy: {summary.get('final_accuracy', float('nan')):.4f}")
