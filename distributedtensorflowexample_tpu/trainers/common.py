"""Shared trainer runner — now a declaration adapter over engine/.

What every reference trainer.py script did (SURVEY.md §3 call stacks
L5→L4→L3→L2) lived here as ~600 lines of hand-wired flow until PR 19
moved it into :class:`~distributedtensorflowexample_tpu.engine.engine.
Engine` (ROADMAP direction 4, arXiv:1902.00465): each entrypoint script
supplies flag defaults, ``run_training`` wraps them into a
:class:`~distributedtensorflowexample_tpu.engine.spec.RunSpec`, and the
Engine owns mesh construction, replication-mode selection, layout
passes, the hook stack, and the loop.  The wiring moved with operation
order preserved — loss tapes and collective multisets are
bitwise-identical to the pre-engine runner (tests/test_engine.py).

``auto_steps_per_loop`` and ``_refuse_incompatible_restore`` are
re-exported from their new home for the tests and tools that import
them from here.
"""

from __future__ import annotations

from distributedtensorflowexample_tpu.config import RunConfig
from distributedtensorflowexample_tpu.engine.engine import (  # noqa: F401
    _SAMPLE_SHAPES, Engine, _load_dataset, _refuse_incompatible_restore,
    auto_steps_per_loop)
from distributedtensorflowexample_tpu.engine.spec import RunSpec


def run_training(cfg: RunConfig, model_name: str, dataset_name: str,
                 augment: bool = False) -> dict:
    """Train per config; returns a summary dict (used by tests and
    bench).  Equivalent declaration: ``Engine(RunSpec(...)).run()``."""
    return Engine(RunSpec(model=model_name, dataset=dataset_name,
                          config=cfg, augment=augment)).run()
