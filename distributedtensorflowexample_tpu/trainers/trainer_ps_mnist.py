"""Config 2 — async parameter-server MNIST CNN (BASELINE.json configs[1]).

Reference stack (SURVEY.md §3b): ``ClusterSpec({"ps": [...], "worker":
[...]})``, ``tf.train.Server``, ``replica_device_setter`` pinning variables
to PS tasks, each worker stepping asynchronously against shared variables
(stale gradients by design).

Rebuild (SURVEY.md §7 step 6): there are no PS processes — ``--job_name=ps``
exits with a notice; the full ClusterSpec CLI is accepted as compatibility
aliases.  The workload defaults to ``--sync_mode=async``: a local-SGD
emulation of async staleness in which per-replica parameter copies step
independently and average every ``--async_period`` steps (bounded,
deterministic staleness replacing the reference's unbounded PS write
races).  ``--sync_mode=sync`` opts into the deterministic sync-SPMD path,
making this entrypoint equivalent to config 3.
"""

from __future__ import annotations

import sys

from distributedtensorflowexample_tpu.config import parse_flags
from distributedtensorflowexample_tpu.engine import Engine, RunSpec


def main(argv=None) -> dict:
    cfg = parse_flags(argv, description=__doc__,
                      batch_size=64, train_steps=2000, learning_rate=0.05,
                      momentum=0.9, dataset="mnist", sync_mode="async")
    return Engine(RunSpec(model="mnist_cnn", dataset="mnist",
                          config=cfg)).run()


if __name__ == "__main__":
    summary = main(sys.argv[1:])
    if not summary.get("exited"):
        print(f"final accuracy: {summary.get('final_accuracy', float('nan')):.4f}")
