"""Config 3 — sync-SGD MNIST CNN (BASELINE.json configs[2]).

Reference stack (SURVEY.md §3c): ``tf.train.SyncReplicasOptimizer`` with
PS-side gradient accumulators + token-queue barrier over 2 workers.
Rebuild: the barrier IS the XLA psum inside one jitted step over the mesh.
By default every replica's gradient enters every update (exact sync — the
SPMD program has no stragglers to tolerate); ``--replicas_to_aggregate R``
restores SyncReplicasOptimizer's partial aggregation as a deterministic
rotating subset of R replica gradients per step (parallel/sync.py).
"""

from __future__ import annotations

import sys

from distributedtensorflowexample_tpu.config import parse_flags
from distributedtensorflowexample_tpu.engine import Engine, RunSpec


def main(argv=None) -> dict:
    cfg = parse_flags(argv, description=__doc__,
                      batch_size=64, train_steps=2000, learning_rate=0.05,
                      momentum=0.9, dataset="mnist", sync_mode="sync")
    return Engine(RunSpec(model="mnist_cnn", dataset="mnist",
                          config=cfg)).run()


if __name__ == "__main__":
    summary = main(sys.argv[1:])
    print(f"final accuracy: {summary.get('final_accuracy', float('nan')):.4f}")
