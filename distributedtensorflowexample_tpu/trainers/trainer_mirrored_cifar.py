"""Config 4 — single-host data-parallel CIFAR-10 ResNet-20
(BASELINE.json configs[3]).

Reference stack (SURVEY.md §3d): ``tf.distribute.MirroredStrategy`` — N GPU
replicas, NCCL ring all-reduce of gradients.  Rebuild: one mesh over the
host's TPU chips; the all-reduce is the XLA psum over ICI inside the jitted
step, overlapped with backprop by the compiler.
"""

from __future__ import annotations

import sys

from distributedtensorflowexample_tpu.config import parse_flags
from distributedtensorflowexample_tpu.engine import Engine, RunSpec


def main(argv=None) -> dict:
    cfg = parse_flags(argv, description=__doc__,
                      batch_size=128, train_steps=5000, learning_rate=0.1,
                      momentum=0.9, weight_decay=1e-4, lr_schedule="step",
                      warmup_steps=200, dataset="cifar10")
    return Engine(RunSpec(model="resnet20", dataset="cifar10",
                          config=cfg, augment=True)).run()


if __name__ == "__main__":
    summary = main(sys.argv[1:])
    print(f"final accuracy: {summary.get('final_accuracy', float('nan')):.4f}")
