"""Sync data-parallel train/eval steps — the SPMD replacement for the
reference's SyncReplicasOptimizer barrier (SURVEY.md §3c), MirroredStrategy
NCCL ring (§3d), and MultiWorkerMirroredStrategy collectives.

One jitted function is traced once and compiled for the whole mesh.  The
batch arrives sharded along ``DATA_AXIS``; params are replicated.  The loss
mean over the batch axis makes XLA emit a psum over ICI for the gradients —
that single collective IS the reference's gradient-aggregation machinery
(PS accumulators + token queues, or the NCCL ring), compiler-scheduled and
overlapped with backprop.

The train state is donated: parameters are updated in place in HBM, no
realloc per step.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import optax

from distributedtensorflowexample_tpu.data.pipeline import put_global_batch
from distributedtensorflowexample_tpu.parallel.mesh import DATA_AXIS
from distributedtensorflowexample_tpu.ops.losses import accuracy
from distributedtensorflowexample_tpu.training.state import TrainState

# What the compiled default sync step must look like, checked by
# analysis/hlo_lint.py against the lowered module text (PR 13): one
# gradient all-reduce per param leaf plus the two scalar metric
# all-reduces and nothing else on the wire, state donation actually
# aliased (in-place HBM update — the claim in this module's docstring),
# and no float upcast past f32 (the quantized input paths dequantize to
# f32, never f64).  Symbols resolve at check time: P = param leaves.
HLO_CONTRACT = {
    "mode": "sync_dp",
    "collective_budget": {"all-reduce": "P+2"},
    "require_alias": True,
    "dtype_ceiling": "f32",
}


def _per_example_rows(impl: Callable) -> Callable:
    """Adapt a [rows, C] loss kernel to ALSO accept sequence logits
    [B, T, C] / labels [B, T] (the transformer-LM head): tokens flatten
    into rows — row-major, so a batch-axis sharding of B carries over to
    B*T contiguously — and fold back to ONE per-EXAMPLE value (mean over
    T).  Returning [B] keeps every downstream consumer (batch mean,
    partial aggregation's per-replica row weights, the bucketed step's
    sum/global_batch) shape-identical to the image models'."""
    def rows(logits, labels):
        if logits.ndim == 3:
            b = logits.shape[0]
            r = impl(logits.reshape(-1, logits.shape[-1]),
                     labels.reshape(-1))
            return jnp.mean(r.reshape(b, -1), axis=1)
        return impl(logits, labels)
    return rows


def make_loss_rows(label_smoothing: float = 0.0, ce_impl: str = "xla",
                   mesh=None) -> Callable:
    """Per-example loss head [B,C] -> [B] (or [B,T,C]/[B,T] -> [B] for
    sequence models — see :func:`_per_example_rows`), shared by the sync
    and async step builders.

    ``ce_impl="pallas"`` uses the fused Pallas kernel.  A ``pallas_call``
    is a custom call XLA cannot auto-partition, so on a multi-device mesh
    the kernel runs per-shard under ``jax.shard_map`` over the batch axis;
    reductions outside it remain ordinary jnp ops, keeping the gradient
    psum identical to the XLA path.
    """
    if ce_impl not in ("xla", "pallas"):
        raise ValueError(f"unknown ce_impl {ce_impl!r}")
    if ce_impl == "xla":
        from distributedtensorflowexample_tpu.ops.losses import (
            softmax_cross_entropy_rows)
        return _per_example_rows(
            lambda l, y: softmax_cross_entropy_rows(l, y, label_smoothing))
    from distributedtensorflowexample_tpu.ops.pallas import (
        fused_softmax_cross_entropy_rows)
    # The token-flatten adapter sits INSIDE the shard_map: the kernel
    # sees its shard's [local_b * T, C] rows, reductions over T stay
    # per-example and local.
    fused = _per_example_rows(
        lambda l, y: fused_softmax_cross_entropy_rows(l, y,
                                                      label_smoothing))
    if mesh is not None and mesh.size > 1:
        from jax.sharding import PartitionSpec as P
        from distributedtensorflowexample_tpu.compat import shard_map
        fused = shard_map(fused, mesh=mesh,
                          in_specs=(P(DATA_AXIS), P(DATA_AXIS)),
                          out_specs=P(DATA_AXIS), check_vma=False)
    return fused


def _resolve_num_slots(unroll_steps: int, steps_per_epoch: int,
                       num_slots: int | None) -> int:
    """Default + validate a step factory's perm-ring size against the ONE
    sizing rule (DeviceDataset.ring_slots_for)."""
    from distributedtensorflowexample_tpu.data.device_dataset import (
        DeviceDataset)
    if unroll_steps < 1:
        raise ValueError(f"unroll_steps {unroll_steps} must be >= 1")
    needed = DeviceDataset.ring_slots_for(unroll_steps, steps_per_epoch)
    if num_slots is None:
        return needed
    if num_slots < needed:
        raise ValueError(
            f"num_slots {num_slots} cannot hold a {unroll_steps}-step "
            f"window over {steps_per_epoch}-step epochs (needs {needed})")
    return num_slots


def _dequant_gathered(img, data, dequant_impl: str):
    """Dequantize a gathered uint8 batch: the ONE dispatch both indexed
    gathers share.  The constants ride in the data pytree (affine/pallas
    datasets carry ``dq_scale``/``dq_bias``, LUT-family datasets carry
    the 256-entry ``lut``), so which family runs is static at trace time
    and no call site can silently train on raw bytes; ``dequant_impl``
    only refines WITHIN the LUT family (one-hot matmul vs the
    known-slow elementwise gather diagnostic) and catches a
    factory/dataset mismatch as a trace-time error instead of a wrong
    kernel."""
    if "tokens" in data:
        # Token split (DeviceDataset token_data=True): the uint8 batch
        # is ids, not quantized pixels — the model upcasts after the
        # gather.  Static dispatch on pytree structure, like the
        # dq_scale/lut families.
        return img
    if img.dtype != jnp.uint8:
        return img
    from distributedtensorflowexample_tpu.data.device_dataset import (
        apply_dequant_affine, apply_dequant_gather, apply_dequant_lut)
    if "dq_scale" in data:
        if dequant_impl in ("onehot", "lut"):
            raise ValueError(
                f"step factory asked for dequant_impl={dequant_impl!r} but "
                f"the dataset resolved to the affine family (it carries "
                f"dq_scale/dq_bias) — pass the same dequant_impl to "
                f"DeviceDataset and the step factory")
        return apply_dequant_affine(img, data["dq_scale"], data["dq_bias"])
    if "lut" in data:
        if dequant_impl in ("affine", "pallas"):
            raise ValueError(
                f"step factory asked for dequant_impl={dequant_impl!r} but "
                f"the dataset resolved to the LUT family (it carries lut) "
                f"— pass the same dequant_impl to DeviceDataset and the "
                f"step factory")
        if dequant_impl == "lut":
            return apply_dequant_gather(img, data["lut"])
        return apply_dequant_lut(img, data["lut"])
    raise TypeError("gathered batch is uint8 but the data pytree carries "
                    "no dequant constants (not a DeviceDataset product?)")


def make_device_gather(batch_size: int, steps_per_epoch: int,
                       augment: str = "none", mesh=None, *,
                       num_slots: int,
                       data_sharding: str = "replicated",
                       dequant_impl: str = "auto") -> Callable:
    """(step, rng, data) -> batch: the on-device minibatch gather from a
    resident split (see ``data.DeviceDataset``), shared by the sync and
    async indexed step builders.  ``num_slots`` must equal the dataset's
    perm-ring size (``ds.num_slots``).

    A uint8-resident split (4x less gather traffic) dequantizes on the
    gathered batch only: the dequant constants ride in the data pytree
    and the dispatch is on the pytree structure (static at trace time),
    so quantization needs NO step-factory plumbing and no call site can
    silently train on raw bytes.  ``dequant_impl`` mirrors the dataset's
    knob (``data.device_dataset.DEQUANT_IMPLS``): ``auto`` follows the
    pytree (the affine fast path for both shipped loader specs);
    ``pallas`` fuses the row gather and the affine dequant into ONE
    kernel pass (ops/pallas/dequant.py — replicated datasets only);
    ``lut`` forces the elementwise-gather diagnostic the bench uses to
    keep the round-5 dequant tax attested.

    ``data_sharding="sharded"`` pairs with a row-sharded
    ``DeviceDataset(data_sharding="sharded")``: each device gathers its
    batch shard from ITS row block under ``shard_map`` — local indices,
    zero collectives (the dataset's interleaved per-shard permutation
    guarantees every position a device reads lives in its block).  The
    returned batch is sharded along the batch axis exactly like the
    replicated gather's, so the step body downstream is unchanged."""
    if augment not in ("none", "cifar"):
        raise ValueError(f"unknown augment {augment!r}")
    if data_sharding not in ("replicated", "sharded"):
        raise ValueError(f"unknown data_sharding {data_sharding!r}")
    from distributedtensorflowexample_tpu.data.device_dataset import (
        DEQUANT_IMPLS)
    if dequant_impl not in DEQUANT_IMPLS:
        raise ValueError(f"unknown dequant_impl {dequant_impl!r} "
                         f"(one of {DEQUANT_IMPLS})")
    if data_sharding == "sharded":
        if mesh is None:
            raise ValueError("data_sharding='sharded' requires a mesh")
        if dequant_impl == "pallas":
            raise ValueError(
                "dequant_impl='pallas' fuses the gather over the WHOLE "
                "resident split; pair it with data_sharding='replicated'")
        return _make_sharded_gather(batch_size, steps_per_epoch, augment,
                                    mesh, num_slots=num_slots,
                                    dequant_impl=dequant_impl)

    def gather(step, rng, data):
        # In-epoch position from the global step; modulo first so the
        # int32 product can't overflow on long runs.  The epoch names its
        # slot in the perm ring (see DeviceDataset).
        slot = (step // steps_per_epoch) % num_slots
        pos = (step % steps_per_epoch) * batch_size
        idx = jax.lax.dynamic_slice(data["perm"], (slot, pos),
                                    (1, batch_size))[0]
        if dequant_impl == "pallas" and "dq_scale" in data:
            # Fused row-gather + affine dequant: uint8 rows leave HBM
            # once and arrive as the float32 batch — no materialized u8
            # minibatch, no second dequant pass (VERDICT r4 #3, the
            # profile-chosen kernel).  Augment (if any) runs after, on
            # f32 — bitwise-commutable, the selectors route exactly.
            from distributedtensorflowexample_tpu.ops.pallas import (
                fused_gather_dequant)
            img = fused_gather_dequant(data["images"], idx,
                                       data["dq_scale"], data["dq_bias"])
            if augment == "cifar":
                from distributedtensorflowexample_tpu.data.augment_device import (
                    cifar_augment_device)
                akey = jax.random.fold_in(
                    jax.random.fold_in(rng, 0x5EED), step)
                img = cifar_augment_device(img, akey)
        else:
            img = jnp.take(data["images"], idx, axis=0)
            if augment == "cifar":
                # On-device crop/flip (data/augment_device.py): a
                # dedicated stream folded from the state rng — disjoint
                # from the dropout stream, which folds in only the step.
                # Runs BEFORE dequantization: crop/flip only rearranges
                # pixels, so it commutes bitwise with the elementwise
                # dequant, and on a uint8-resident split any materialized
                # pad/crop intermediate is 4x smaller.  On the affine
                # path the dequant is FUSED into the selector matmuls'
                # f32 output (one pass, no u8 cast-back — the round-5
                # ResNet input-share fix).
                akey = jax.random.fold_in(
                    jax.random.fold_in(rng, 0x5EED), step)
                # Forced LUT-family impls skip the fused form so the
                # dequant below runs the kernel the caller named (or
                # raises the family mismatch) instead of silently
                # measuring affine.
                if (img.dtype == jnp.uint8 and "dq_scale" in data
                        and dequant_impl not in ("onehot", "lut")):
                    from distributedtensorflowexample_tpu.data.augment_device import (
                        cifar_augment_dequant_device)
                    img = cifar_augment_dequant_device(
                        img, akey, data["dq_scale"], data["dq_bias"])
                else:
                    from distributedtensorflowexample_tpu.data.augment_device import (
                        cifar_augment_device)
                    img = cifar_augment_device(img, akey)
            img = _dequant_gathered(img, data, dequant_impl)
        batch = {"image": img,
                 "label": jnp.take(data["labels"], idx, axis=0)}
        if mesh is not None and mesh.size > 1:
            # Dataset + perm are replicated, so the gather is local on
            # every device; the constraint re-shards the minibatch along
            # the batch axis (slice-keeping, no collective) so the rest of
            # the step runs data-parallel exactly like the host-fed path.
            from distributedtensorflowexample_tpu.parallel.mesh import (
                batch_sharding)
            batch = jax.lax.with_sharding_constraint(batch,
                                                     batch_sharding(mesh))
        return batch

    return gather


def _make_sharded_gather(batch_size: int, steps_per_epoch: int,
                         augment: str, mesh, *, num_slots: int,
                         dequant_impl: str = "auto") -> Callable:
    """The ``data_sharding="sharded"`` gather (see ``make_device_gather``):
    runs under ``shard_map`` over the data axis, each device slicing its
    bpd positions out of the (replicated) perm ring and translating them
    into its local row space — index math only, no collective."""
    from jax.sharding import PartitionSpec as P

    D = mesh.shape[DATA_AXIS]
    if batch_size % D:
        raise ValueError(f"sharded data: batch {batch_size} must divide "
                         f"across {D} devices")
    bpd = batch_size // D

    def gather(step, rng, data):
        has_lut = "lut" in data
        has_affine = "dq_scale" in data
        has_tokens = "tokens" in data

        def local(step, rng, images, labels, perm, *dq):
            d = jax.lax.axis_index(DATA_AXIS)
            rows = images.shape[0]              # this device's row block
            slot = (step // steps_per_epoch) % num_slots
            pos = (step % steps_per_epoch) * batch_size + d * bpd
            idx = jax.lax.dynamic_slice(perm, (slot, pos), (1, bpd))[0]
            idx = idx - d * rows                # global -> local row space
            img = jnp.take(images, idx, axis=0)
            dq_data = ({"lut": dq[0]} if has_lut else
                       {"dq_scale": dq[0], "dq_bias": dq[1]} if has_affine
                       else {})
            if augment == "cifar":
                # Same stream layout as the replicated gather, plus the
                # device index: each shard draws independent crops/flips
                # (same distribution; draws differ from replicated mode).
                akey = jax.random.fold_in(
                    jax.random.fold_in(jax.random.fold_in(rng, 0x5EED), step),
                    d)
                if (img.dtype == jnp.uint8 and has_affine
                        and dequant_impl not in ("onehot", "lut")):
                    # Affine dequant fused into the selector matmuls'
                    # f32 output — same one-pass form as the replicated
                    # gather (see make_device_gather); a forced LUT-
                    # family impl takes the plain route so the dequant
                    # below runs (or rejects) the named kernel.
                    from distributedtensorflowexample_tpu.data.augment_device import (
                        cifar_augment_dequant_device)
                    img = cifar_augment_dequant_device(img, akey,
                                                       dq[0], dq[1])
                else:
                    from distributedtensorflowexample_tpu.data.augment_device import (
                        cifar_augment_device)
                    img = cifar_augment_device(img, akey)
            if not has_tokens:          # token ids pass through raw
                img = _dequant_gathered(img, dq_data, dequant_impl)
            return img, jnp.take(labels, idx, axis=0)

        args = [step, rng, data["images"], data["labels"], data["perm"]]
        in_specs = [P(), P(), P(DATA_AXIS), P(DATA_AXIS), P()]
        if has_lut:
            args.append(data["lut"])
            in_specs.append(P())
        elif has_affine:
            args.extend([data["dq_scale"], data["dq_bias"]])
            in_specs.extend([P(), P()])
        from distributedtensorflowexample_tpu.compat import shard_map
        img, lab = shard_map(
            local, mesh=mesh, in_specs=tuple(in_specs),
            out_specs=(P(DATA_AXIS), P(DATA_AXIS)), check_vma=False)(*args)
        return {"image": img, "label": lab}

    return gather


def _build_step_fn(label_smoothing: float = 0.0, ce_impl: str = "xla",
                   mesh=None, num_replicas: int = 1,
                   replicas_to_aggregate: int = 0,
                   bucket_bytes: int | None = None,
                   bucket_shard_update: bool = False,
                   zero3_layout=None, zero3_overlap: bool = True) -> Callable:
    """The un-jitted (state, batch) -> (state, metrics) step body, shared
    by the plain and the device-resident (indexed) step factories.

    ``ce_impl="pallas"`` swaps the loss head for the fused Pallas kernel
    (ops/pallas/cross_entropy.py).  A ``pallas_call`` is a custom call XLA
    cannot auto-partition, so on a multi-device mesh the kernel runs
    per-shard under ``jax.shard_map`` over the batch axis; the batch mean
    outside it remains an ordinary jnp op, keeping the gradient psum
    identical to the XLA path.

    ``replicas_to_aggregate=R`` (with ``0 < R < num_replicas``) implements
    SyncReplicasOptimizer's partial aggregation: each step only R of the N
    replicas' gradients enter the update.  The reference aggregated the
    first R gradients to *arrive* (backup workers absorbing stragglers —
    a race); lockstep SPMD has no stragglers to drop, so the TPU-native
    analog selects a deterministic rotating subset — replica ``i``
    contributes at step ``s`` iff ``(i - s) mod N < R`` — which preserves
    the statistical semantics (each step averages R replica gradients;
    every replica contributes equally over any N consecutive steps).
    Implemented as a per-row weight on the loss, so the gradient psum
    stays the one XLA collective; unselected replicas' rows carry zero
    weight and their gradient contribution vanishes.

    ``bucket_bytes`` (the ``--bucket_grads`` knob) swaps this body for
    the bucketed shard_map step (parallel/bucketing.py): per-parameter
    gradient all-reduces fuse into knee-sized buckets, and with
    ``bucket_shard_update`` the explicit per-bucket reduce-scatter +
    sharded-update + all-gather ZeRO-1 schedule.  On a single-device
    mesh there is nothing to reduce, so the knob falls through to this
    plain body.

    ``zero3_layout`` (the ``--shard_params`` knob, parallel/zero3.py)
    goes one stage further: params AND grads live as 1/D bucket rows,
    each bucket's params all-gathered just before use (double-buffered
    prefetch unless ``zero3_overlap`` is off) and reduce-scattered in
    the backward by the gather's own transpose.  Takes precedence over
    the ZeRO-1 schedule (it subsumes it); same single-device
    fall-through.
    """
    if zero3_layout is not None and mesh is not None \
            and mesh.shape[DATA_AXIS] > 1:
        from distributedtensorflowexample_tpu.parallel.zero3 import (
            build_zero3_step_fn)
        return build_zero3_step_fn(label_smoothing, ce_impl, mesh,
                                   num_replicas, replicas_to_aggregate,
                                   zero3_layout, overlap=zero3_overlap)
    if bucket_bytes and mesh is not None and mesh.shape[DATA_AXIS] > 1:
        from distributedtensorflowexample_tpu.parallel.bucketing import (
            build_bucketed_step_fn)
        return build_bucketed_step_fn(label_smoothing, ce_impl, mesh,
                                      num_replicas, replicas_to_aggregate,
                                      bucket_bytes,
                                      shard_update=bucket_shard_update)
    R, N = int(replicas_to_aggregate), max(1, int(num_replicas))
    if not 0 <= R <= N:
        raise ValueError(
            f"replicas_to_aggregate {R} must be in [0, {N}] (0 = all)")
    partial_agg = 0 < R < N
    loss_rows = make_loss_rows(label_smoothing, ce_impl, mesh)

    def compute_loss(logits, labels, step):
        rows = loss_rows(logits, labels)
        if not partial_agg:
            return jnp.mean(rows)
        batch = logits.shape[0]
        per_shard = batch // N
        replica_of_row = jnp.arange(batch, dtype=jnp.int32) // per_shard
        selected = ((replica_of_row - step) % N) < R
        return jnp.sum(rows * selected.astype(rows.dtype)) / (R * per_shard)

    def step(state: TrainState, batch) -> tuple[TrainState, dict]:
        step_rng = jax.random.fold_in(state.rng, state.step)
        has_bn = bool(state.batch_stats)

        def loss_fn(params):
            variables = {"params": params}
            if has_bn:
                variables["batch_stats"] = state.batch_stats
                logits, updated = state.apply_fn(
                    variables, batch["image"], train=True,
                    rngs={"dropout": step_rng}, mutable=["batch_stats"])
                new_stats = updated["batch_stats"]
            else:
                logits = state.apply_fn(variables, batch["image"], train=True,
                                        rngs={"dropout": step_rng})
                new_stats = state.batch_stats
            loss = compute_loss(logits, batch["label"], state.step)
            return loss, (logits, new_stats)

        (loss, (logits, new_stats)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        updates, new_opt_state = state.tx.update(grads, state.opt_state,
                                                 state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_state = state.replace(step=state.step + 1, params=new_params,
                                  opt_state=new_opt_state,
                                  batch_stats=new_stats)
        metrics = {"loss": loss, "accuracy": accuracy(logits, batch["label"])}
        return new_state, metrics

    return step


def dequant_host_batch(batch, dequant: str | None,
                       dequant_impl: str = "auto", quantize: str = "auto"):
    """Dequantize a HOST-FED uint8 batch in-step (4x less H2D per step
    than uploading float32).  Float batches pass through.  A uint8 batch
    with no spec is a TRACE-TIME error: silently training on raw 0-255
    bytes is the failure this guard exists to prevent — pass
    ``dequant=batcher.dequant`` (``data.pipeline.Batcher``).

    ``dequant_impl`` resolves through the SAME rule as the resident path
    (``data.device_dataset.resolve_dequant_impl``), so host-fed and
    resident training dequantize through the same kernel — the affine
    fast path for both shipped loader specs.  ``pallas`` degenerates to
    affine here: there is no gather to fuse with on an uploaded batch."""
    img = batch["image"]
    if img.dtype != jnp.uint8:
        return batch
    if dequant is None:
        raise TypeError(
            "host-fed batch images are uint8 but the train step was "
            "built without dequant=; pass dequant=batcher.dequant")
    from distributedtensorflowexample_tpu.data.device_dataset import (
        dequantize_images, resolve_dequant_impl)
    # quantize travels too: the rule's speed-over-bits escape for
    # non-affine-representable specs (quantize="scale") must resolve
    # identically here and on the resident path.
    impl = resolve_dequant_impl(dequant, dequant_impl, quantize)
    impl = "affine" if impl == "pallas" else impl
    return dict(batch, image=dequantize_images(img, dequant, impl))


def make_train_step(label_smoothing: float = 0.0, ce_impl: str = "xla",
                    mesh=None, num_replicas: int = 1,
                    replicas_to_aggregate: int = 0,
                    dequant: str | None = None,
                    dequant_impl: str = "auto",
                    quantize: str = "auto",
                    bucket_bytes: int | None = None,
                    bucket_shard_update: bool = False,
                    zero3_layout=None,
                    zero3_overlap: bool = True) -> Callable:
    """Build the jitted (state, batch) -> (state, metrics) step.

    ``dequant``: spec for HOST-FED uint8 batches (``batcher.dequant``);
    the resident/indexed path dequantizes in its gather instead.
    ``dequant_impl``/``quantize``: the in-step dequant kernel knobs (same
    resolution rule as the resident path — see ``dequant_host_batch``).
    ``bucket_bytes``/``bucket_shard_update``: the ``--bucket_grads``
    collective schedule; ``zero3_layout``/``zero3_overlap``: the
    ``--shard_params`` ZeRO-3 schedule (see ``_build_step_fn``)."""
    inner = _build_step_fn(label_smoothing, ce_impl, mesh,
                           num_replicas, replicas_to_aggregate,
                           bucket_bytes=bucket_bytes,
                           bucket_shard_update=bucket_shard_update,
                           zero3_layout=zero3_layout,
                           zero3_overlap=zero3_overlap)

    def step(state: TrainState, batch):
        return inner(state, dequant_host_batch(batch, dequant, dequant_impl,
                                               quantize))

    return jax.jit(step, donate_argnums=0)


def make_indexed_train_step(batch_size: int, steps_per_epoch: int,
                            label_smoothing: float = 0.0,
                            ce_impl: str = "xla", mesh=None,
                            unroll_steps: int = 1,
                            augment: str = "none", num_replicas: int = 1,
                            replicas_to_aggregate: int = 0,
                            num_slots: int | None = None,
                            data_sharding: str = "replicated",
                            dequant_impl: str = "auto",
                            bucket_bytes: int | None = None,
                            bucket_shard_update: bool = False,
                            zero3_layout=None,
                            zero3_overlap: bool = True) -> Callable:
    """Step over a device-resident dataset (see ``data.DeviceDataset``).

    The batch is GATHERED ON DEVICE from the resident split: the step
    receives ``{"images", "labels", "perm"}`` (full arrays + a two-slot
    epoch permutation pair) and slices its minibatch out of the right
    perm row at the position derived from ``state.step`` — so the host
    transfers nothing per step.  This is the TPU-native kill for the
    feed_dict/H2D per-step copy (SURVEY.md §3a, §7 "hard parts"): at
    MNIST-sized step times the transfer IS the bottleneck (measured
    ~1.4 ms vs a ~0.07 ms step on a v5e chip through the host tunnel).

    Semantics match the host Batcher exactly: shuffled epochs without
    replacement, batch_size rows per step, global step drives the epoch
    position (deterministic across resume).

    ``unroll_steps=K`` fuses K consecutive SGD updates into one compiled
    call with ``lax.scan`` — K full, sequential, per-batch updates (same
    math, the global step advances by K), one host dispatch.  When the
    device is reached through a high-latency link the dispatch round-trip
    dominates MNIST-sized steps, and this divides it by K — the TPU-native
    analog of Keras ``steps_per_execution``.  Each scanned sub-step picks
    its epoch's perm slot (``(step // steps_per_epoch) % num_slots``) so a
    window may cross epoch boundaries — ANY ``K >= 1`` works, even
    multi-epoch windows (the dataset sizes its perm ring to match; pass
    the same ``unroll_steps`` as its ``steps_per_next`` and, if you
    constructed the dataset yourself, ``num_slots=ds.num_slots``);
    returned metrics are the mean over the K updates.
    """
    num_slots = _resolve_num_slots(unroll_steps, steps_per_epoch, num_slots)
    inner = _build_step_fn(label_smoothing, ce_impl, mesh, num_replicas,
                           replicas_to_aggregate,
                           bucket_bytes=bucket_bytes,
                           bucket_shard_update=bucket_shard_update,
                           zero3_layout=zero3_layout,
                           zero3_overlap=zero3_overlap)
    gather = make_device_gather(batch_size, steps_per_epoch, augment, mesh,
                                num_slots=num_slots,
                                data_sharding=data_sharding,
                                dequant_impl=dequant_impl)

    def one(state: TrainState, data) -> tuple[TrainState, dict]:
        return inner(state, gather(state.step, state.rng, data))

    if unroll_steps == 1:
        return jax.jit(one, donate_argnums=0)

    def step(state: TrainState, data) -> tuple[TrainState, dict]:
        new_state, stacked = jax.lax.scan(
            lambda st, _: one(st, data), state, None, length=unroll_steps)
        return new_state, jax.tree.map(lambda m: jnp.mean(m, axis=0), stacked)

    return jax.jit(step, donate_argnums=0)


_EVAL_STEP = None


def make_eval_step() -> Callable:
    """Jitted (state, batch) -> (sum correct, count) for exact test accuracy.

    A single module-level jitted function: jax caches compilations per
    (apply_fn, shapes), so periodic evals reuse the compiled graph instead
    of rebuilding a fresh closure (and recompiling) per eval.
    """
    global _EVAL_STEP
    if _EVAL_STEP is not None:
        return _EVAL_STEP

    def step(state: TrainState, batch):
        variables = {"params": state.params}
        if state.batch_stats:
            variables["batch_stats"] = state.batch_stats
        logits = state.apply_fn(variables, batch["image"], train=False)
        correct = jnp.sum(
            (jnp.argmax(logits, axis=-1) == batch["label"]).astype(jnp.int32))
        return correct, batch["label"].shape[0]

    _EVAL_STEP = jax.jit(step)
    return _EVAL_STEP


def make_resident_eval(images, labels, batch_size: int = 1000,
                       mesh=None, quantize: str = "auto",
                       dequant_impl: str = "auto",
                       token_data: bool = False) -> Callable:
    """Device-resident exact-accuracy eval: ONE dispatch per eval.

    The host-fed ``evaluate`` re-uploads the split 1000 rows at a time on
    every call — through a high-latency link that wall time pollutes the
    training window.  The test split fits in HBM exactly like the train
    split does, so this uploads it once (padded to a whole number of
    batches, pad labels -1 so they never match an argmax), shards each
    batch row-wise over the mesh, and jits a ``lax.scan`` over the batches
    — the whole eval is a single compiled call returning one scalar.
    Like the train split, a quantizable split is held as uint8 (4x less
    HBM + upload) and dequantized in the scan body.  ``quantize`` and
    ``dequant_impl`` mirror the train-path flags and resolve through the
    SAME rule (``data.device_dataset.resolve_dequant_impl``), so a
    bitwise train/eval parity check exercises one kernel, not two
    (``pallas`` degenerates to affine here: the scan slices resident
    batches, there is no row gather to fuse).

    ``token_data=True`` (the LM family): the split is integer ids — no
    dequant machinery runs, the model upcasts, and accuracy normalizes
    per LABEL ELEMENT (per token for [N, T] targets; identical to the
    per-example count for [N] image labels).

    Returns ``eval_fn(state) -> float`` (exact accuracy over the split).
    """
    import numpy as np

    from distributedtensorflowexample_tpu.data.device_dataset import (
        _try_quantize, dequantize_images, resolve_dequant_impl)

    if quantize not in ("auto", "off", "exact", "scale"):
        raise ValueError(f"unknown quantize mode {quantize!r}")
    dequant = None
    if not token_data and quantize != "off":
        q = _try_quantize(np.asarray(images))
        if q is not None:
            images, dequant = q
    impl = (resolve_dequant_impl(dequant, dequant_impl, quantize)
            if dequant is not None else None)
    impl = "affine" if impl == "pallas" else impl

    n = len(labels)
    # Accuracy denominator: label ELEMENTS of the real split (tokens for
    # a [N, T] LM split; == n for [N] image labels).  Pad labels are -1
    # and never match an argmax, so only the denominator needs care.
    denom = int(np.asarray(labels).size)
    if mesh is not None and batch_size % mesh.size:
        raise ValueError(f"eval batch {batch_size} must divide across "
                         f"{mesh.size} devices")
    num_batches = -(-n // batch_size)
    pad = num_batches * batch_size - n
    if pad:
        images = np.concatenate(
            [images, np.zeros((pad,) + images.shape[1:], images.dtype)])
        labels = np.concatenate(
            [labels, np.full((pad,) + labels.shape[1:], -1, labels.dtype)])
    xs = np.ascontiguousarray(
        images.reshape((num_batches, batch_size) + images.shape[1:]))
    ys = np.ascontiguousarray(
        labels.reshape((num_batches, batch_size) + labels.shape[1:]))

    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P
        shard = NamedSharding(mesh, P(None, DATA_AXIS))
        if jax.process_count() > 1:
            # Every process holds the full split; its devices own a
            # contiguous slice of the (sharded) batch axis — mesh device
            # order groups devices by process (see put_global_batch).
            pc, pi = jax.process_count(), jax.process_index()
            per = batch_size // pc
            put = lambda a: jax.make_array_from_process_local_data(
                shard, np.ascontiguousarray(a[:, pi * per:(pi + 1) * per]))
        else:
            put = lambda a: jax.device_put(a, shard)
    else:
        put = jax.device_put
    xs, ys = put(xs), put(ys)

    @jax.jit
    def run(state: TrainState, xs, ys):
        variables = {"params": state.params}
        if state.batch_stats:
            variables["batch_stats"] = state.batch_stats

        def body(total, xy):
            bx, by = xy
            if dequant is not None:
                bx = dequantize_images(bx, dequant, impl)
            logits = state.apply_fn(variables, bx, train=False)
            correct = jnp.sum(
                (jnp.argmax(logits, axis=-1) == by).astype(jnp.int32))
            return total + correct, None

        total, _ = jax.lax.scan(body, jnp.zeros((), jnp.int32), (xs, ys))
        return total

    return lambda state: int(run(state, xs, ys)) / denom


def evaluate(state: TrainState, images, labels, batch_size: int = 1000,
             sharding=None) -> float:
    """Exact accuracy over a full split, batched to bound HBM use.

    Every process holds the full split (the reference's eval behavior);
    under multi-host the batch helper keeps only locally-owned rows.
    Host-fed — see ``make_resident_eval`` for the device-resident path
    the trainers use by default.
    """
    eval_step = make_eval_step()
    n = len(labels)
    usable = (n // batch_size) * batch_size
    total_correct = 0

    def put(batch):
        return put_global_batch(batch, sharding) if sharding is not None else batch

    for i in range(0, usable, batch_size):
        batch = put({"image": images[i:i + batch_size],
                     "label": labels[i:i + batch_size]})
        correct, _ = eval_step(state, batch)
        total_correct += int(correct)
    # Remainder evaluated shape-stable by padding to batch_size with
    # label -1 (never matches an argmax class).
    rem = n - usable
    if rem:
        import numpy as np
        pad = batch_size - rem
        batch = put({"image": np.concatenate(
                         [images[usable:],
                          np.zeros((pad,) + images.shape[1:], images.dtype)]),
                     "label": np.concatenate(
                         [labels[usable:],
                          np.full((pad,), -1, labels.dtype)])})
        correct, _ = eval_step(state, batch)
        total_correct += int(correct)
    return total_correct / n
