"""Sync data-parallel train/eval steps — the SPMD replacement for the
reference's SyncReplicasOptimizer barrier (SURVEY.md §3c), MirroredStrategy
NCCL ring (§3d), and MultiWorkerMirroredStrategy collectives.

One jitted function is traced once and compiled for the whole mesh.  The
batch arrives sharded along ``DATA_AXIS``; params are replicated.  The loss
mean over the batch axis makes XLA emit a psum over ICI for the gradients —
that single collective IS the reference's gradient-aggregation machinery
(PS accumulators + token queues, or the NCCL ring), compiler-scheduled and
overlapped with backprop.

The train state is donated: parameters are updated in place in HBM, no
realloc per step.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import optax

from distributedtensorflowexample_tpu.data.pipeline import put_global_batch
from distributedtensorflowexample_tpu.parallel.mesh import DATA_AXIS
from distributedtensorflowexample_tpu.ops.losses import (
    accuracy, softmax_cross_entropy)
from distributedtensorflowexample_tpu.training.state import TrainState


def _build_step_fn(label_smoothing: float = 0.0, ce_impl: str = "xla",
                   mesh=None) -> Callable:
    """The un-jitted (state, batch) -> (state, metrics) step body, shared
    by the plain and the device-resident (indexed) step factories.

    ``ce_impl="pallas"`` swaps the loss head for the fused Pallas kernel
    (ops/pallas/cross_entropy.py).  A ``pallas_call`` is a custom call XLA
    cannot auto-partition, so on a multi-device mesh the kernel runs
    per-shard under ``jax.shard_map`` over the batch axis; the batch mean
    outside it remains an ordinary jnp op, keeping the gradient psum
    identical to the XLA path.
    """
    if ce_impl not in ("xla", "pallas"):
        raise ValueError(f"unknown ce_impl {ce_impl!r}")

    def compute_loss(logits, labels):
        if ce_impl == "xla":
            return softmax_cross_entropy(logits, labels, label_smoothing)
        from distributedtensorflowexample_tpu.ops.pallas import (
            fused_softmax_cross_entropy_rows)
        fused = lambda l, y: fused_softmax_cross_entropy_rows(
            l, y, label_smoothing)
        if mesh is not None and mesh.size > 1:
            from jax.sharding import PartitionSpec as P
            fused = jax.shard_map(fused, mesh=mesh,
                                  in_specs=(P(DATA_AXIS), P(DATA_AXIS)),
                                  out_specs=P(DATA_AXIS), check_vma=False)
        return jnp.mean(fused(logits, labels))

    def step(state: TrainState, batch) -> tuple[TrainState, dict]:
        step_rng = jax.random.fold_in(state.rng, state.step)
        has_bn = bool(state.batch_stats)

        def loss_fn(params):
            variables = {"params": params}
            if has_bn:
                variables["batch_stats"] = state.batch_stats
                logits, updated = state.apply_fn(
                    variables, batch["image"], train=True,
                    rngs={"dropout": step_rng}, mutable=["batch_stats"])
                new_stats = updated["batch_stats"]
            else:
                logits = state.apply_fn(variables, batch["image"], train=True,
                                        rngs={"dropout": step_rng})
                new_stats = state.batch_stats
            loss = compute_loss(logits, batch["label"])
            return loss, (logits, new_stats)

        (loss, (logits, new_stats)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        updates, new_opt_state = state.tx.update(grads, state.opt_state,
                                                 state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_state = state.replace(step=state.step + 1, params=new_params,
                                  opt_state=new_opt_state,
                                  batch_stats=new_stats)
        metrics = {"loss": loss, "accuracy": accuracy(logits, batch["label"])}
        return new_state, metrics

    return step


def make_train_step(label_smoothing: float = 0.0, ce_impl: str = "xla",
                    mesh=None) -> Callable:
    """Build the jitted (state, batch) -> (state, metrics) step."""
    return jax.jit(_build_step_fn(label_smoothing, ce_impl, mesh),
                   donate_argnums=0)


def make_indexed_train_step(batch_size: int, steps_per_epoch: int,
                            label_smoothing: float = 0.0,
                            ce_impl: str = "xla", mesh=None,
                            unroll_steps: int = 1,
                            augment: str = "none") -> Callable:
    """Step over a device-resident dataset (see ``data.DeviceDataset``).

    The batch is GATHERED ON DEVICE from the resident split: the step
    receives ``{"images", "labels", "perm"}`` (full arrays + this epoch's
    shuffled index order) and slices its minibatch out of ``perm`` at the
    position derived from ``state.step`` — so the host transfers nothing
    per step.  This is the TPU-native kill for the feed_dict/H2D per-step
    copy (SURVEY.md §3a, §7 "hard parts"): at MNIST-sized step times the
    transfer IS the bottleneck (measured ~1.4 ms vs a ~0.07 ms step on a
    v5e chip through the host tunnel).

    Semantics match the host Batcher exactly: shuffled epochs without
    replacement, batch_size rows per step, global step drives the epoch
    position (deterministic across resume).

    ``unroll_steps=K`` fuses K consecutive SGD updates into one compiled
    call with ``lax.scan`` — K full, sequential, per-batch updates (same
    math, the global step advances by K), one host dispatch.  When the
    device is reached through a high-latency link the dispatch round-trip
    dominates MNIST-sized steps, and this divides it by K — the TPU-native
    analog of Keras ``steps_per_execution``.  Requires
    ``steps_per_epoch % K == 0`` so a scan window never crosses an epoch
    boundary (the host swaps the permutation between calls); returned
    metrics are the mean over the K updates.
    """
    if unroll_steps < 1 or (unroll_steps & (unroll_steps - 1)):
        raise ValueError(
            f"unroll_steps must be a power of two >= 1, got {unroll_steps}")
    if steps_per_epoch % unroll_steps:
        raise ValueError(
            f"unroll_steps {unroll_steps} must divide steps_per_epoch "
            f"{steps_per_epoch} — pass the same value as DeviceDataset's "
            f"steps_per_next (see DeviceDataset.epoch_multiple)")
    if augment not in ("none", "cifar"):
        raise ValueError(f"unknown augment {augment!r}")
    inner = _build_step_fn(label_smoothing, ce_impl, mesh)

    def one(state: TrainState, data) -> tuple[TrainState, dict]:
        # In-epoch position from the global step; modulo first so the
        # int32 product can't overflow on long runs.
        pos = (state.step % steps_per_epoch) * batch_size
        idx = jax.lax.dynamic_slice(data["perm"], (pos,), (batch_size,))
        batch = {"image": jnp.take(data["images"], idx, axis=0),
                 "label": jnp.take(data["labels"], idx, axis=0)}
        if augment == "cifar":
            # On-device crop/flip (data/augment_device.py): a dedicated
            # stream folded from the state rng — disjoint from the
            # dropout stream, which folds in only the step.
            from distributedtensorflowexample_tpu.data.augment_device import (
                cifar_augment_device)
            akey = jax.random.fold_in(
                jax.random.fold_in(state.rng, 0x5EED), state.step)
            batch["image"] = cifar_augment_device(batch["image"], akey)
        if mesh is not None and mesh.size > 1:
            # Dataset + perm are replicated, so the gather is local on
            # every device; the constraint re-shards the minibatch along
            # the batch axis (slice-keeping, no collective) so the rest of
            # the step runs data-parallel exactly like the host-fed path.
            from distributedtensorflowexample_tpu.parallel.mesh import (
                batch_sharding)
            batch = jax.lax.with_sharding_constraint(batch,
                                                     batch_sharding(mesh))
        return inner(state, batch)

    if unroll_steps == 1:
        return jax.jit(one, donate_argnums=0)

    def step(state: TrainState, data) -> tuple[TrainState, dict]:
        new_state, stacked = jax.lax.scan(
            lambda st, _: one(st, data), state, None, length=unroll_steps)
        return new_state, jax.tree.map(lambda m: jnp.mean(m, axis=0), stacked)

    return jax.jit(step, donate_argnums=0)


_EVAL_STEP = None


def make_eval_step() -> Callable:
    """Jitted (state, batch) -> (sum correct, count) for exact test accuracy.

    A single module-level jitted function: jax caches compilations per
    (apply_fn, shapes), so periodic evals reuse the compiled graph instead
    of rebuilding a fresh closure (and recompiling) per eval.
    """
    global _EVAL_STEP
    if _EVAL_STEP is not None:
        return _EVAL_STEP

    def step(state: TrainState, batch):
        variables = {"params": state.params}
        if state.batch_stats:
            variables["batch_stats"] = state.batch_stats
        logits = state.apply_fn(variables, batch["image"], train=False)
        correct = jnp.sum(
            (jnp.argmax(logits, axis=-1) == batch["label"]).astype(jnp.int32))
        return correct, batch["label"].shape[0]

    _EVAL_STEP = jax.jit(step)
    return _EVAL_STEP


def evaluate(state: TrainState, images, labels, batch_size: int = 1000,
             sharding=None) -> float:
    """Exact accuracy over a full split, batched to bound HBM use.

    Every process holds the full split (the reference's eval behavior);
    under multi-host the batch helper keeps only locally-owned rows.
    """
    eval_step = make_eval_step()
    n = len(labels)
    usable = (n // batch_size) * batch_size
    total_correct = 0

    def put(batch):
        return put_global_batch(batch, sharding) if sharding is not None else batch

    for i in range(0, usable, batch_size):
        batch = put({"image": images[i:i + batch_size],
                     "label": labels[i:i + batch_size]})
        correct, _ = eval_step(state, batch)
        total_correct += int(correct)
    # Remainder evaluated shape-stable by padding to batch_size with
    # label -1 (never matches an argmax class).
    rem = n - usable
    if rem:
        import numpy as np
        pad = batch_size - rem
        batch = put({"image": np.concatenate(
                         [images[usable:],
                          np.zeros((pad,) + images.shape[1:], images.dtype)]),
                     "label": np.concatenate(
                         [labels[usable:],
                          np.full((pad,), -1, labels.dtype)])})
        correct, _ = eval_step(state, batch)
        total_correct += int(correct)
    return total_correct / n
