"""Device mesh + sharding helpers — the SPMD core.

This single abstraction replaces all four of the reference's distribution
mechanisms (SURVEY.md §2 strategy inventory): ``replica_device_setter`` PS
placement, ``SyncReplicasOptimizer`` aggregation, single-host NCCL
MirroredStrategy, and multi-host collective all-reduce.  Parameters get a
fully-replicated ``NamedSharding``; batches are sharded along ``DATA_AXIS``;
XLA inserts the psum over ICI when the jitted step reduces across the batch.

The mesh is 1-D today (the reference is data-parallel only) but axis naming
keeps the door open for tensor/pipeline axes later.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"


def make_mesh(num_devices: int = 0, devices=None) -> Mesh:
    """A 1-D data-parallel mesh over the first ``num_devices`` devices."""
    if devices is None:
        devices = jax.devices()
    if num_devices and num_devices > 0:
        if num_devices > len(devices):
            raise ValueError(
                f"requested {num_devices} devices, only {len(devices)} visible")
        devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (DATA_AXIS,))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch) dim across the data axis."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully replicated — what 'mirrored variables' become on a mesh."""
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, batch):
    """Device_put a host batch onto the mesh, sharded along DATA_AXIS."""
    return jax.device_put(batch, batch_sharding(mesh))
