"""ZeRO-3 / FSDP: full param+grad sharding with a double-buffered
all-gather/compute overlap schedule (``--shard_params``).

ZeRO-1 (parallel/bucketing.py's composed ``--bucket_grads`` +
``--shard_update`` schedule) shards the OPTIMIZER state 1/D and gathers
the updated params back to a replicated tree every step — params and
grads still cost a full copy per device, which is what caps the lm
ladder at "what one device holds".  This module extends the same
knee-sized, dtype-homogeneous bucket-row layout (arXiv:2004.13336 §ZeRO
stage 3) to params and grads:

* **Resident layout**: params live as per-bucket flat rows — bucket b
  is the ``[D, ceil(n_b/D)]`` layout of PR 6 (`_bucket_flat2d`: each
  leaf zero-padded to a multiple of D, split into D row blocks,
  concatenated column-wise) raveled to one ``[D*W_b]`` array sharded
  one row per device along the data axis.  Optimizer state lives in the
  SAME rows (``init_bucketed_opt_state`` — unchanged from ZeRO-1).
  Per-device persistent state is therefore (params + opt moments)/D
  (+ the reported row padding); nothing params-shaped is resident.

* **Gather-before-use, free-after-last-use**: the forward all-gathers
  each bucket's row just before the model consumes its leaves; the
  gathered full leaves are step-local TEMPORARIES (XLA frees them after
  their last backward use, and the donated row buffers alias in place),
  so the full tree never exists as persistent state — the compiler
  memory analysis shows it in ``temp_bytes``, not ``argument_bytes``
  (the measured form of the 1/D claim: see
  ``utils/profiling.compiled_program_audit``'s residency section).

* **Grads reduce-scattered per bucket, BY CONSTRUCTION**: the gather is
  differentiated through — ``jax.lax.all_gather``'s transpose IS
  ``psum_scatter`` — so autodiff places one reduce-scatter per bucket
  at exactly the point in the backward pass where that bucket's
  gradient contributions are complete (last-consumed bucket's RS first:
  the overlappable schedule falls out of the chain rule).  The gradient
  a device ever holds is its 1/D row; the full gradient tree is never
  materialized, not even transiently as a single object.

* **Double-buffered prefetch** (``overlap=True``, the default): bucket
  i's all-gather is chained — through a ``custom_vjp`` identity whose
  forward is ``lax.optimization_barrier`` (the barrier has no AD rule
  on this jax pin, hence the wrapper) — onto a scalar probe of bucket
  i-2's gathered output, so at most TWO gathered buckets are in flight
  ahead of their consumers: gather i+1 issues while bucket i's leaves
  are being consumed, the classic double buffer.  ``overlap=False``
  chains on bucket i-1 instead (strictly serial gathers) — the A/B
  control ``bench_lm.py`` measures.  XLA:CPU dispatches synchronously,
  so the CPU wall-clock pair only proves the schedule compiles both
  ways; the overlap win itself is armed for the next TPU window
  (BASELINE_SELF.json), where the latency-hiding scheduler turns the
  independent AG-prefetch chain into async collectives hidden under
  block compute — graft-LM's block ladder supplies the gather points
  (leaves flatten embed → block0..blockN → ln_f, so knee-sized buckets
  track block boundaries).

Update: per bucket, ``tx.update`` runs on the 1/D grad row against the
1/D param row and row-layout moments, and the updated row is written
straight back — NO trailing all-gather (ZeRO-1's step-closing AG
disappears; the next step's forward re-gathers, which is the ZeRO-3
trade: one extra AG of params per step in exchange for 1/D residency).

Parity contract: same as the ZeRO-1 bucket schedule and for the same
reasons — the gathered leaves are bitwise the replicated leaves
(concatenate/reshape move bytes, never arithmetic), the RS performs the
same cross-device additions psum_scatter performed, so softmax is
bitwise vs the bucketed baseline and conv/LM models hold to the
documented allclose standard (summation order, not math).  BatchNorm
models are refused by name (the bucketing.py argument verbatim);
dropout folds in the device index (per-shard streams).  The overlap
knob is pure scheduling: overlap on/off is bitwise-identical.

Checkpoint/resume: ``run_meta.update_layout = "zero3_rows"`` — params
AND optimizer state are bucket rows, a function of D, so cross-layout
and cross-mesh-size resumes are refused by name (trainers/common.py),
exactly like ``bucket_rows``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributedtensorflowexample_tpu.parallel.bucketing import (
    _bucket_flat2d, _unbucket_rows, bucket_padding_bytes, plan_buckets)
from distributedtensorflowexample_tpu.parallel.mesh import DATA_AXIS
from distributedtensorflowexample_tpu.refusal import ModeRefusal

# The ZeRO-3 schedule as a compiled-HLO contract (analysis/hlo_lint.py,
# PR 13) — the static form of the claims in the module docstring, each
# previously pinned only by runtime golden multisets: every bucket's
# forward-prefetch all-gather textually PRECEDES its reduce-scatter
# (ag_rs_paired — autodiff's all_gather transpose placed the RS in the
# backward), NO all-gather after the last RS (the updated 1/D row
# writes straight back; a trailing AG would be ZeRO-1's update-closing
# gather leaking into a schedule that promises none), exactly one
# AG + one RS per bucket + the fused metrics pair on the wire, donation
# aliased (the row buffers update in place), no float upcast past f32.
# Symbols resolve at check time: B = buckets in the layout's plan.
HLO_CONTRACT = {
    "mode": "zero3",
    "ag_rs_paired": True,
    "no_trailing_all_gather": True,
    "collective_budget": {"all-gather": "B", "reduce-scatter": "B",
                          "all-reduce": 2},
    "require_alias": True,
    "dtype_ceiling": "f32",
}


@dataclasses.dataclass(frozen=True)
class LeafSpec:
    """Shape+dtype of one param leaf — the static template
    ``_unbucket_rows``/``plan_buckets`` slice against once the real
    leaves live only as bucket rows.  Hashable (jit cache key)."""
    shape: tuple
    dtype: Any          # np.dtype — hashable, itemsize-bearing

    @property
    def size(self) -> int:
        n = 1
        for d in self.shape:
            n *= int(d)
        return n


class Zero3Layout:
    """Everything static about one ZeRO-3 layout: the leaf template, the
    treedef, the bucket plan (PR 6's ``plan_buckets`` over the canonical
    flatten order — pure function of tree + cap, every device/restart
    agrees), and the mesh size.  One instance serves the state
    converters, the step builder, and the eval-side materializer."""

    def __init__(self, params, bucket_bytes: int, mesh):
        if mesh is None or mesh.shape[DATA_AXIS] <= 1:
            raise ValueError(
                "ZeRO-3 param sharding needs a multi-device data mesh "
                "(there is nothing to shard on one device) — callers "
                "fall back to the plain step")
        leaves, self.treedef = jax.tree.flatten(params)
        self.leaf_specs = tuple(
            LeafSpec(tuple(l.shape), np.dtype(l.dtype)) for l in leaves)
        self.plan = tuple(tuple(b)
                          for b in plan_buckets(self.leaf_specs,
                                                bucket_bytes))
        self.bucket_bytes = int(bucket_bytes)
        self.num_devices = int(mesh.shape[DATA_AXIS])
        self.mesh = mesh
        self.padding_bytes = bucket_padding_bytes(self.leaf_specs,
                                                  self.num_devices)
        self._materialize_jit = None

    @property
    def num_buckets(self) -> int:
        return len(self.plan)

    # --- state conversion -------------------------------------------------
    def init_rows(self, params) -> tuple:
        """Full (replicated) params -> the resident row layout: one flat
        ``[D*W_b]`` array per bucket, sharded one row per device.  The
        input is DONATED — converting frees the replicated copy, so the
        full tree stops being resident the moment the layout exists."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        D = self.num_devices
        plan = self.plan

        def to_rows(p):
            lv = jax.tree.leaves(p)
            return tuple(_bucket_flat2d(lv, idxs, D).ravel()
                         for idxs in plan)

        row = NamedSharding(self.mesh, P(DATA_AXIS))
        return jax.jit(to_rows, out_shardings=row,
                       donate_argnums=0)(params)

    def materialize(self, rows: tuple):
        """Rows -> the full params tree (for eval / export — never the
        train step, whose gathers live inside the differentiated body).
        Jitted once per layout; jax re-gathers across the mesh as the
        replicated output sharding demands."""
        if self._materialize_jit is None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            D = self.num_devices
            specs, plan, treedef = self.leaf_specs, self.plan, self.treedef

            def gather(rows):
                leaves: list = [None] * len(specs)
                for bi, idxs in enumerate(plan):
                    full = rows[bi].reshape(D, -1)
                    for i, piece in _unbucket_rows(full, specs,
                                                   idxs).items():
                        leaves[i] = piece
                return jax.tree.unflatten(treedef, leaves)

            repl = NamedSharding(self.mesh, P())
            self._materialize_jit = jax.jit(gather, out_shardings=repl)
        return self._materialize_jit(rows)


# --- the double-buffer tie ------------------------------------------------
# ``lax.optimization_barrier`` pins issue order in the compiled schedule
# but has no differentiation rule on this jax pin, and the gathers it
# must order live INSIDE the differentiated loss.  This custom_vjp
# identity carries the barrier through AD: forward barriers ``x`` on the
# scalar ``probe`` (x cannot be scheduled before probe exists), backward
# passes the cotangent straight through (the probe's is zero — it is a
# scheduling edge, not math).

@jax.custom_vjp
def _tie(x, probe):
    out, _ = jax.lax.optimization_barrier((x, probe))
    return out


def _tie_fwd(x, probe):
    return _tie(x, probe), None


def _tie_bwd(_, ct):
    return ct, jnp.zeros((), jnp.float32)


_tie.defvjp(_tie_fwd, _tie_bwd)


def build_zero3_step_fn(label_smoothing: float, ce_impl: str, mesh,
                        num_replicas: int, replicas_to_aggregate: int,
                        layout: Zero3Layout,
                        overlap: bool = True) -> Callable:
    """The ZeRO-3 (state, batch) -> (state, metrics) step body — the
    shard_map sibling of ``bucketing.build_bucketed_step_fn``.  The
    state's ``params`` must be ``layout.init_rows`` output (and
    ``opt_state`` the matching ``init_bucketed_opt_state`` rows); the
    caller jits it with the same donation the other bodies get.  See
    the module docstring for the schedule and the parity contract."""
    from distributedtensorflowexample_tpu.compat import shard_map
    from distributedtensorflowexample_tpu.parallel.sync import make_loss_rows
    from jax.sharding import PartitionSpec as P

    D = layout.num_devices
    if mesh.shape[DATA_AXIS] != D:
        raise ValueError(f"step mesh size {mesh.shape[DATA_AXIS]} does "
                         f"not match the layout's {D} — the row layout "
                         f"is a function of D")
    R, N = int(replicas_to_aggregate), max(1, int(num_replicas))
    if not 0 <= R <= N:
        raise ValueError(
            f"replicas_to_aggregate {R} must be in [0, {N}] (0 = all)")
    partial_agg = 0 < R < N
    loss_rows = make_loss_rows(label_smoothing, ce_impl, mesh=None)
    specs, plan, treedef = layout.leaf_specs, layout.plan, layout.treedef
    # Double buffer = at most 2 gathered buckets in flight ahead of
    # their consumers; the serial control chains each gather on its
    # predecessor instead.
    depth = 2 if overlap else 1

    def step(state, batch):
        if state.batch_stats:
            raise ModeRefusal(
                "--shard_params cannot run a BatchNorm model: the default "
                "GSPMD step computes global-batch statistics and the "
                "sharded per-device region would silently turn them into "
                "per-shard statistics (a different model, not a different "
                "collective schedule). Use the default fused all-reduce "
                "for BN models")
        if not (isinstance(state.params, tuple)
                and len(state.params) == len(plan)):
            raise ValueError(
                f"ZeRO-3 step expects params as {len(plan)} bucket rows "
                f"(Zero3Layout.init_rows); got "
                f"{type(state.params).__name__} — the state was not "
                f"converted to the resident row layout")

        wspec = P(DATA_AXIS)
        pspec = jax.tree.map(lambda _: wspec, state.params)
        ospec = jax.tree.map(
            lambda x: wspec if getattr(x, "ndim", 0) else P(),
            state.opt_state)

        def body(step_no, rng, p_rows, opt_state, img, lab):
            d = jax.lax.axis_index(DATA_AXIS)
            step_rng = jax.random.fold_in(rng, step_no)
            local_b = img.shape[0]
            global_b = local_b * D

            def loss_fn(p_rows):
                # The AG-prefetch schedule: one tiled all-gather per
                # bucket, issue order pinned by the _tie chain.  Leaves
                # sliced out of the gathered rows are bitwise the
                # replicated leaves; differentiating THROUGH the gather
                # is what places one psum_scatter per bucket in the
                # backward pass (all_gather's transpose).
                full_rows = []
                for bi, row in enumerate(p_rows):
                    j = bi - depth
                    if j >= 0:
                        row = _tie(row, full_rows[j].ravel()[0].astype(
                            jnp.float32))
                    full_rows.append(jax.lax.all_gather(
                        row, DATA_AXIS, axis=0, tiled=True).reshape(D, -1))
                leaves: list = [None] * len(specs)
                for bi, idxs in enumerate(plan):
                    for i, piece in _unbucket_rows(full_rows[bi], specs,
                                                   idxs).items():
                        leaves[i] = piece
                params = jax.tree.unflatten(treedef, leaves)
                logits = state.apply_fn(
                    {"params": params}, img, train=True,
                    rngs={"dropout": jax.random.fold_in(step_rng, d)})
                rows = loss_rows(logits, lab)
                if not partial_agg:
                    return jnp.sum(rows) / global_b, logits
                # SyncReplicasOptimizer partial aggregation in GLOBAL
                # row coordinates (the bucketed-step form, verbatim).
                per_shard = global_b // N
                row_ids = jnp.arange(local_b, dtype=jnp.int32) + d * local_b
                selected = ((row_ids // per_shard - step_no) % N) < R
                return (jnp.sum(rows * selected.astype(rows.dtype))
                        / (R * per_shard), logits)

            (loss_part, logits), g_rows = jax.value_and_grad(
                loss_fn, has_aux=True)(p_rows)
            # g_rows[bi] is this device's 1/D reduce-scattered grad row
            # (psum_scatter placed by the gather's transpose).  The
            # update is pure elementwise on rows; the updated row writes
            # straight back — no step-closing all-gather (the next
            # forward re-gathers: the ZeRO-3 trade).
            new_rows, new_opt = [], []
            for bi in range(len(plan)):
                u_row, st = state.tx.update(g_rows[bi], opt_state[bi],
                                            p_rows[bi])
                new_rows.append(optax.apply_updates(p_rows[bi], u_row))
                new_opt.append(st)
            correct = jnp.sum(
                (jnp.argmax(logits, axis=-1) == lab).astype(jnp.float32))
            # One fused psum pair for both scalar metrics (the bucketed-
            # step idiom).
            loss, correct = jax.lax.psum((loss_part, correct), DATA_AXIS)
            return (tuple(new_rows), tuple(new_opt), loss,
                    correct / (lab.size * D))

        body_m = shard_map(
            body, mesh=mesh,
            in_specs=(P(), P(), pspec, ospec, wspec, wspec),
            out_specs=(pspec, ospec, P(), P()), check_vma=False)
        new_rows, new_opt, loss, acc = body_m(
            state.step, state.rng, state.params, state.opt_state,
            batch["image"], batch["label"])
        new_state = state.replace(step=state.step + 1, params=new_rows,
                                  opt_state=new_opt)
        return new_state, {"loss": loss, "accuracy": acc}

    return step
