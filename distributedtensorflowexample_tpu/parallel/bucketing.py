"""Bucketed, overlap-friendly gradient collectives (``--bucket_grads``).

The GSPMD sync step emits ONE all-reduce PER PARAMETER in the backward
pass (measured on this jax pin: 8 gradient all-reduces + 2 scalar metric
all-reduces for the 8-leaf mnist_cnn step) — every one pays the fixed
per-collective latency alpha.  arXiv:1810.11112's characterization says
collective cost is ``t(S) = alpha + S/beta`` with a message-size knee at
``alpha*beta``: below the knee latency dominates and fusing messages is
nearly free throughput.  ``bench_collectives.py`` measures alpha/beta/knee
for this stack; this module acts on it.

Two modes, selected by ``--shard_update``:

* **bucketed all-reduce** (``--bucket_grads`` alone): the step body runs
  under ``shard_map`` over the data axis — each device computes its local
  partial gradients (bitwise the partials GSPMD computes), the leaves are
  flattened and concatenated into dtype-homogeneous buckets of at most
  ``bucket_bytes``, and each bucket is ONE ``lax.psum``.  Strictly fewer
  all-reduce ops per step, identical total gradient bytes (the metric
  scalars ride their own fused psum pair, as in the async step).

* **explicit ZeRO-1 bucket schedule** (with ``--shard_update``): per
  bucket, leaves are laid out ``[D, ceil(n_i/D)]`` (each leaf padded to a
  multiple of D and split into D row blocks) and concatenated column-wise,
  so ``lax.psum_scatter`` hands device d exactly the d-th block of every
  leaf; the optimizer update runs on that 1/D row (optimizer state lives
  in the SAME row layout — ``init_bucketed_opt_state``), and ONE
  ``lax.all_gather`` of the updated row rebuilds the replicated params.
  This is arXiv:2004.13336's reduce-scatter + sharded-update + all-gather
  schedule made EXPLICIT and bucket-granular: each bucket's reduce-scatter
  depends only on that bucket's gradients, so the scheduler can overlap it
  with the rest of the backward pass (the GSPMD-constraint form of
  ``--shard_update`` hangs everything off the full gradient tree).  The
  collective inventory (utils/profiling.collective_inventory) proves the
  schedule: N_params all-reduces become N_buckets (reduce-scatter,
  all-gather) pairs at unchanged total reduction bytes (+ padding to
  multiples of D, reported by ``plan_buckets``).

The bucket-row machinery here — ``plan_buckets`` (static, order-
preserving membership), ``_rows2d``/``_bucket_flat2d``/``_unbucket_rows``
(the ``[D, ceil(n/D)]`` layout and its inverse), padding accounting, and
``init_bucketed_opt_state`` (optimizer moments AS rows) — is also the
resident layout of the ZeRO-3 step (parallel/zero3.py): same plan, same
rows, with the params themselves joining the optimizer state in 1/D
residency and the all-gather moving to the forward as a prefetch.

Parity contract (the remat/shard_update template): bucketing itself is
bitwise — any two bucket sizes produce identical results (same elementwise
additions, regrouped).  Against the GSPMD default the shard_map backward
may fuse differently, so the gate is bitwise where the program permits
(softmax: pinned bitwise in tests/test_collectives.py, both modes) and
allclose for conv models — the SAME standard ``cross_replica_update_
sharding`` documents for the constraint form, and for the same reason
(summation order, not math).  Dropout models draw per-shard masks (the
rng folds in the device index — the ``_make_sharded_gather`` augment
precedent: same distribution, draws differ from the replicated step).
BatchNorm models are REFUSED by name: the GSPMD step computes
global-batch statistics and a per-shard region would silently change
them to per-shard statistics — a different model, not a different
schedule (run_training refuses before building the step).
"""

from __future__ import annotations

import os
from typing import Callable

import jax
import jax.numpy as jnp
import optax

from distributedtensorflowexample_tpu.parallel.mesh import DATA_AXIS
from distributedtensorflowexample_tpu.refusal import ModeRefusal

# --bucket_grads auto: sized from the measured CPU-mesh all-reduce knee
# (bench_collectives.py: 8-device psum knee 244 KB at r2=0.99,
# suggested_bucket_bytes ~954 KB = 4x knee, where the alpha/latency share
# of t(S) = alpha + S/beta is down to ~20% — BENCH_collectives_cpu_r06.
# json + DESIGN.md §15).  Chip-remeasurable: the capture window's
# --real phase re-fits the knee, and BUCKET_GRADS_AUTO_BYTES overrides
# without a code change.
DEFAULT_BUCKET_BYTES = 1 << 20

# Compiled-schedule contracts, checked by analysis/hlo_lint.py against
# the lowered module text (PR 13) — the static twin of the runtime
# golden multisets in tests/test_collectives.py.  Symbols resolve at
# check time: B = buckets in the plan.
#
# Bucketed all-reduce: N_params gradient ARs collapse to one AR per
# bucket + the fused metrics pair; nothing else may appear on the wire.
BUCKETED_HLO_CONTRACT = {
    "mode": "bucketed_allreduce",
    "collective_budget": {"all-reduce": "B+2"},
    "require_alias": True,
    "dtype_ceiling": "f32",
}
# ZeRO-1 (arXiv:2004.13336): per bucket one reduce-scatter then its
# UPDATE-CLOSING all-gather (rs_ag_paired — the AG textually follows
# its RS: gather the updated row, not the gradient), plus the metrics
# pair.  Contrast zero3.HLO_CONTRACT, where the pairing flips.
ZERO1_HLO_CONTRACT = {
    "mode": "zero1",
    "rs_ag_paired": True,
    "collective_budget": {"reduce-scatter": "B", "all-gather": "B",
                          "all-reduce": 2},
    "require_alias": True,
    "dtype_ceiling": "f32",
}


def resolve_bucket_bytes(flag: str) -> int | None:
    """``--bucket_grads`` resolution: ``""`` = off (None), ``auto`` = the
    measured-knee default (env BUCKET_GRADS_AUTO_BYTES overrides, same
    validation — an override of 0 silently disabling the bucketing the
    flag explicitly asked for would be the worst kind of knob), else a
    positive byte count.  Bad values fail by name at flag-validation
    time, not in the middle of a trace."""
    if not flag:
        return None
    if flag == "auto":
        env = os.environ.get("BUCKET_GRADS_AUTO_BYTES")
        if env is None:
            return DEFAULT_BUCKET_BYTES
        flag, source = env, "BUCKET_GRADS_AUTO_BYTES"
    else:
        source = "--bucket_grads"
    try:
        nbytes = int(flag)
    except ValueError:
        # ModeRefusal even though the flag name rides in `source` (the
        # named-refusal lint can only see literal --tokens): these ARE
        # mode-legality refusals and must stay on the one grep.
        raise ModeRefusal(f"{source} must be 'auto' or a byte count, "
                          f"got {flag!r}") from None
    if nbytes <= 0:
        raise ModeRefusal(f"{source} byte count must be positive, "
                          f"got {nbytes}")
    return nbytes


def plan_buckets(leaves, bucket_bytes: int) -> list[list[int]]:
    """Group leaf INDICES into dtype-homogeneous buckets of at most
    ``bucket_bytes`` (a single leaf over the cap gets its own bucket —
    never split, so leaf<->bucket membership is static).  Order-
    preserving over the canonical ``jax.tree`` flatten order, so the
    plan is a pure function of the param tree + cap: every device, every
    restart, and the opt-state initializer agree on it."""
    buckets: list[list[int]] = []
    cur: list[int] = []
    cur_bytes, cur_dt = 0, None
    for i, leaf in enumerate(leaves):
        nb = leaf.size * leaf.dtype.itemsize
        if cur and (leaf.dtype != cur_dt or cur_bytes + nb > bucket_bytes):
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nb
        cur_dt = leaf.dtype
    if cur:
        buckets.append(cur)
    return buckets


def bucket_padding_bytes(leaves, num_devices: int) -> int:
    """Bytes of zero-padding the ZeRO-1 row layout adds (each leaf padded
    to a multiple of the mesh size) — the "±padding, reported" term in
    the unchanged-total-bytes claim.  Independent of bucket membership:
    padding is per-leaf, whatever bucket the leaf lands in."""
    return sum(((-leaf.size) % num_devices) * leaf.dtype.itemsize
               for leaf in leaves)


def _rows2d(leaf, num_devices: int):
    """Flatten *leaf*, zero-pad to a multiple of ``num_devices``, and
    split into D row blocks: ``[D, ceil(n/D)]``.  Row d is the d-th
    contiguous block — the shard device d owns under the ZeRO-1 layout."""
    flat = leaf.ravel()
    pad = (-flat.size) % num_devices
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat.reshape(num_devices, -1)


def _bucket_flat2d(leaves, idxs, num_devices: int):
    """The bucket's ``[D, W]`` layout: per-leaf row blocks concatenated
    column-wise, so every row holds the SAME leaves' d-th blocks.
    ``ravel()`` of this is exactly the vector ``psum_scatter`` splits
    into per-device rows."""
    return jnp.concatenate([_rows2d(leaves[i], num_devices) for i in idxs],
                           axis=1)


def _unbucket_rows(full_rows, leaves_template, idxs):
    """Inverse of :func:`_bucket_flat2d`: slice the gathered ``[D, W]``
    array back into leaf-shaped arrays (padding dropped)."""
    D = full_rows.shape[0]
    out = {}
    off = 0
    for i in idxs:
        leaf = leaves_template[i]
        w = -(-leaf.size // D)
        out[i] = full_rows[:, off:off + w].ravel()[:leaf.size].reshape(
            leaf.shape)
        off += w
    return out


def init_bucketed_opt_state(tx: optax.GradientTransformation, params,
                            bucket_bytes: int, mesh):
    """Optimizer state for the ZeRO-1 bucket schedule: ``tx.init`` over
    the tuple of per-bucket FLAT row vectors (global shape ``[D*W_b]``,
    sharded one row per device along the data axis), replacing the
    params-shaped state ``TrainState.create_sharded`` laid out.  The
    layout is the step's exact working set — momentum (and any other
    params-shaped moment) lives only as the 1/D row each device updates,
    which is the ZeRO-1 state-residency win made structural instead of
    constraint-hinted.  Scalars (schedule counts) stay replicated."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    D = mesh.shape[DATA_AXIS]
    leaves = jax.tree.leaves(params)
    states = []
    for idxs in plan_buckets(leaves, bucket_bytes):
        flat = _bucket_flat2d(leaves, idxs, D).ravel()
        states.append(tx.init(flat))
    row = NamedSharding(mesh, P(DATA_AXIS))
    repl = NamedSharding(mesh, P())
    return jax.tree.map(
        lambda x: jax.device_put(x, row if getattr(x, "ndim", 0) else repl),
        tuple(states))


def build_bucketed_step_fn(label_smoothing: float, ce_impl: str, mesh,
                           num_replicas: int, replicas_to_aggregate: int,
                           bucket_bytes: int,
                           shard_update: bool = False) -> Callable:
    """The bucketed (state, batch) -> (state, metrics) step body — the
    shard_map twin of ``sync._build_step_fn`` (see module docstring for
    the two modes and the parity contract).  The caller jits it with the
    same donation the plain body gets."""
    from distributedtensorflowexample_tpu.compat import shard_map
    from distributedtensorflowexample_tpu.parallel.sync import make_loss_rows
    from jax.sharding import PartitionSpec as P

    if mesh is None or mesh.shape[DATA_AXIS] <= 1:
        raise ValueError("bucketed gradient collectives need a multi-device "
                         "data mesh (there is nothing to reduce on one "
                         "device) — callers fall back to the plain step")
    D = mesh.shape[DATA_AXIS]
    R, N = int(replicas_to_aggregate), max(1, int(num_replicas))
    if not 0 <= R <= N:
        raise ValueError(
            f"replicas_to_aggregate {R} must be in [0, {N}] (0 = all)")
    partial_agg = 0 < R < N
    # Per-shard loss head (mesh=None): the Pallas CE kernel applies
    # directly on the local rows, exactly as in the async shard_map step.
    loss_rows = make_loss_rows(label_smoothing, ce_impl, mesh=None)

    def step(state, batch):
        if state.batch_stats:
            raise ModeRefusal(
                "--bucket_grads cannot run a BatchNorm model: the default "
                "GSPMD step computes global-batch statistics and the "
                "bucketed per-shard region would silently turn them into "
                "per-shard statistics (a different model, not a different "
                "collective schedule). Use the default fused all-reduce "
                "for BN models")

        wspec = P(DATA_AXIS)
        pspec = jax.tree.map(lambda _: P(), state.params)
        if shard_update:
            # Bucket-row opt state: vectors are one row per device,
            # schedule counts replicated (init_bucketed_opt_state).
            ospec = jax.tree.map(
                lambda x: wspec if getattr(x, "ndim", 0) else P(),
                state.opt_state)
        else:
            ospec = jax.tree.map(lambda _: P(), state.opt_state)

        def body(step_no, rng, params, opt_state, img, lab):
            d = jax.lax.axis_index(DATA_AXIS)
            step_rng = jax.random.fold_in(rng, step_no)
            local_b = img.shape[0]
            global_b = local_b * D

            def loss_fn(p):
                # Per-shard dropout stream: the device index folds in
                # (same distribution as the replicated draw; draws
                # differ — the sharded-gather augment precedent).
                logits = state.apply_fn(
                    {"params": p}, img, train=True,
                    rngs={"dropout": jax.random.fold_in(step_rng, d)})
                rows = loss_rows(logits, lab)
                if not partial_agg:
                    return jnp.sum(rows) / global_b, logits
                # SyncReplicasOptimizer partial aggregation, in GLOBAL
                # row coordinates (batch sharding is contiguous per
                # device, so local row r is global row d*local_b + r).
                per_shard = global_b // N
                row_ids = jnp.arange(local_b, dtype=jnp.int32) + d * local_b
                selected = ((row_ids // per_shard - step_no) % N) < R
                return (jnp.sum(rows * selected.astype(rows.dtype))
                        / (R * per_shard), logits)

            (loss_part, logits), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            gleaves, tdef = jax.tree.flatten(grads)
            buckets = plan_buckets(gleaves, bucket_bytes)

            if not shard_update:
                red = list(gleaves)
                for idxs in buckets:
                    flat = jnp.concatenate([gleaves[i].ravel()
                                            for i in idxs])
                    flat = jax.lax.psum(flat, DATA_AXIS)
                    off = 0
                    for i in idxs:
                        n = gleaves[i].size
                        red[i] = flat[off:off + n].reshape(gleaves[i].shape)
                        off += n
                full_grads = jax.tree.unflatten(tdef, red)
                updates, new_opt = state.tx.update(full_grads, opt_state,
                                                   params)
                new_params = optax.apply_updates(params, updates)
            else:
                pleaves = jax.tree.leaves(params)
                new_leaves = list(pleaves)
                new_opt_list = []
                for bi, idxs in enumerate(buckets):
                    # Reduce-scatter the bucket: row d of the summed
                    # [D, W] layout lands on device d — the 1/D shard
                    # this device updates.
                    g_flat = _bucket_flat2d(gleaves, idxs, D).ravel()
                    g_row = jax.lax.psum_scatter(
                        g_flat, DATA_AXIS, scatter_dimension=0, tiled=True)
                    p_row = jax.lax.dynamic_slice_in_dim(
                        _bucket_flat2d(pleaves, idxs, D), d, 1, 0)[0]
                    u_row, st = state.tx.update(g_row, opt_state[bi], p_row)
                    new_p_row = optax.apply_updates(p_row, u_row)
                    new_opt_list.append(st)
                    # One all-gather of the UPDATED row closes the
                    # bucket; its only dependency is this bucket's
                    # reduce-scatter + elementwise update, so buckets
                    # pipeline instead of meeting at a full-tree barrier.
                    full = jax.lax.all_gather(
                        new_p_row, DATA_AXIS, axis=0,
                        tiled=True).reshape(D, -1)
                    for i, piece in _unbucket_rows(full, pleaves,
                                                   idxs).items():
                        new_leaves[i] = piece
                new_params = jax.tree.unflatten(
                    jax.tree.structure(params), new_leaves)
                new_opt = tuple(new_opt_list)

            correct = jnp.sum(
                (jnp.argmax(logits, axis=-1) == lab).astype(jnp.float32))
            # One fused psum pair for both scalar metrics (async-step
            # idiom) instead of GSPMD's two standalone scalar all-reduces.
            loss, correct = jax.lax.psum((loss_part, correct), DATA_AXIS)
            # Accuracy normalizes per label ELEMENT (tokens for a [b, T]
            # LM shard; == global_b for [b] image labels).
            return new_params, new_opt, loss, correct / (lab.size * D)

        body_m = shard_map(
            body, mesh=mesh,
            in_specs=(P(), P(), pspec, ospec, wspec, wspec),
            out_specs=(pspec, ospec, P(), P()), check_vma=False)
        new_params, new_opt, loss, acc = body_m(
            state.step, state.rng, state.params, state.opt_state,
            batch["image"], batch["label"])
        new_state = state.replace(step=state.step + 1, params=new_params,
                                  opt_state=new_opt)
        return new_state, {"loss": loss, "accuracy": acc}

    return step


def bucketed_tree_psum(tree, bucket_bytes: int, axis_name: str = DATA_AXIS):
    """Fuse a per-leaf tree psum into dtype-homogeneous bucketed psums —
    the same fewer-larger-collectives trade for ANY tree-shaped
    all-reduce (the async step's worker average uses it: its per-leaf
    psum inside ``jax.tree.map`` is exactly the per-parameter pattern
    ``--bucket_grads`` exists to fuse).  Bitwise: concatenation regroups
    which psum carries each element, never the element's cross-device
    addition."""
    leaves, tdef = jax.tree.flatten(tree)
    out = list(leaves)
    for idxs in plan_buckets(leaves, bucket_bytes):
        flat = jax.lax.psum(
            jnp.concatenate([leaves[i].ravel() for i in idxs]), axis_name)
        off = 0
        for i in idxs:
            n = leaves[i].size
            out[i] = flat[off:off + n].reshape(leaves[i].shape)
            off += n
    return jax.tree.unflatten(tdef, out)
