"""Async parameter-server emulation via local SGD (SURVEY.md §7 step 6,
option b — config 2, BASELINE.json configs[1]).

The reference's async mode: each worker pulls variables from the PS, steps
on its own minibatch, and pushes updates with no inter-worker sync — stale
gradients ARE the semantics (SURVEY.md §3b).  True asynchrony has no
XLA-native analog (one program, lockstep devices), so we emulate the
statistical behavior TPU-natively:

* each of the mesh's devices hosts one *virtual worker* — a full parameter
  copy, sharded along ``DATA_AXIS`` on a leading worker axis; on a
  multi-device mesh the per-worker compute runs under ``jax.shard_map``
  over that axis (``_build_shard_map_step``), so every device steps ITS
  workers' params on ITS batch shard with zero cross-device traffic
  between averaging points by construction (letting GSPMD partition a
  plain ``vmap`` instead was measured to all-gather the worker-tiled conv
  weights — see the shard_map builder's docstring);
* every ``period`` steps the copies are averaged (an explicit ``psum``
  over the worker axis, riding ICI) — bounded staleness instead of
  unbounded PS races, same "workers diverge then reconcile" dynamics,
  fully deterministic and restartable.

``period=1`` recovers exact sync SGD; large ``period`` approaches
independent workers.  The branch is a ``lax.cond`` so the whole step stays
one compiled program.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import optax

from distributedtensorflowexample_tpu.ops.losses import accuracy
from distributedtensorflowexample_tpu.parallel.mesh import DATA_AXIS
from distributedtensorflowexample_tpu.parallel.sync import (
    make_device_gather, make_loss_rows)
from distributedtensorflowexample_tpu.training.state import TrainState


def make_worker_state(state: TrainState, num_workers: int, mesh) -> TrainState:
    """Tile replicated state into per-worker copies sharded over the mesh.

    Leading axis = virtual worker id; NamedSharding P(DATA_AXIS) puts one
    worker's copy on each device.
    """
    wshard = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec(DATA_AXIS))

    def tile(x):
        x = jnp.asarray(x)
        tiled = jnp.broadcast_to(x[None], (num_workers,) + x.shape)
        return jax.lax.with_sharding_constraint(tiled, wshard)

    tile_tree = jax.jit(lambda t: jax.tree.map(tile, t), out_shardings=wshard)
    return state.replace(params=tile_tree(state.params),
                         opt_state=tile_tree(state.opt_state),
                         batch_stats=tile_tree(state.batch_stats))


def consolidate(state: TrainState) -> TrainState:
    """Average the worker copies back into one replicated state (for eval,
    checkpoint hand-off to sync mode, or end of training)."""

    def avg(t):
        return jax.tree.map(lambda x: jnp.mean(x.astype(jnp.float32), axis=0)
                            .astype(x.dtype), t)

    return state.replace(params=jax.jit(avg)(state.params),
                         # optimizer moments averaged too (momentum is linear)
                         opt_state=jax.jit(avg)(state.opt_state),
                         batch_stats=jax.jit(avg)(state.batch_stats)
                         if state.batch_stats else state.batch_stats)


def _worker_updates(state: TrainState, loss_rows: Callable, n_workers: int,
                    params, opt_state, stats, images, labels, rngs):
    """One local-SGD update for ``n_workers`` worker copies stacked on the
    leading axis — the per-worker body shared by the vmap (full worker
    axis) and shard_map (device-local slice) paths.

    Per-worker gradients come from ONE ``value_and_grad`` of the summed
    per-worker mean losses: worker ``w``'s parameters only reach
    ``loss_w``, so d(sum)/d(params_w) IS that worker's gradient — same
    math as a per-worker grad transform, but the loss head runs on the
    worker-major flattened [n*Bw, C] logits OUTSIDE the vmap, where the
    Pallas CE kernel can apply (a ``pallas_call`` has no batching rule).

    Returns (new_params, new_opt, new_stats, loss_w, logits) — params
    un-averaged; the caller applies its period-aligned worker average.
    """
    has_bn = bool(stats)

    def fwd(p, st, img, rng):
        variables = {"params": p}
        if has_bn:
            variables["batch_stats"] = st
            logits, updated = state.apply_fn(
                variables, img, train=True,
                rngs={"dropout": rng}, mutable=["batch_stats"])
            return logits, updated["batch_stats"]
        logits = state.apply_fn(variables, img, train=True,
                                rngs={"dropout": rng})
        return logits, st

    def loss_all(params):
        logits, new_stats = jax.vmap(fwd)(params, stats, images, rngs)
        rows = loss_rows(logits.reshape(-1, logits.shape[-1]),
                         labels.reshape(-1))
        loss_w = rows.reshape(n_workers, -1).mean(axis=1)
        return jnp.sum(loss_w), (loss_w, logits, new_stats)

    (_, (loss_w, logits, new_stats)), grads = jax.value_and_grad(
        loss_all, has_aux=True)(params)
    updates, new_opt = jax.vmap(state.tx.update)(grads, opt_state, params)
    new_params = jax.vmap(optax.apply_updates)(params, updates)
    return new_params, new_opt, new_stats, loss_w, logits


def _build_async_step_fn(num_workers: int, period: int,
                         label_smoothing: float = 0.0, ce_impl: str = "xla",
                         mesh=None, bucket_bytes: int | None = None) -> Callable:
    """The un-jitted local-SGD (state, batch) -> (state, metrics) body over
    worker-tiled state, shared by the host-fed and indexed factories.

    The batch arrives as the usual global batch sharded on DATA_AXIS; it
    is reshaped to [workers, per_worker_batch, ...] (device-local, no data
    movement) and stepped by the shared ``_worker_updates`` body.

    On a multi-device mesh the whole per-worker computation runs under
    ``jax.shard_map`` over the worker axis (``_build_shard_map_step``);
    with no mesh (or one device) this plain ``vmap`` body is used.
    """
    period = max(1, int(period))
    if mesh is not None and mesh.size > 1:
        return _build_shard_map_step(num_workers, period, label_smoothing,
                                     ce_impl, mesh,
                                     bucket_bytes=bucket_bytes)
    # Single device: the worker average is local (no collectives), so
    # bucket_bytes has nothing to fuse here.
    loss_rows = make_loss_rows(label_smoothing, ce_impl, mesh)

    def step(state: TrainState, batch) -> tuple[TrainState, dict]:
        W = num_workers

        # [G, ...] -> [W, G/W, ...]; shards are device-local so this is free.
        wbatch = jax.tree.map(
            lambda x: x.reshape((W, x.shape[0] // W) + x.shape[1:]), batch)
        step_rng = jax.random.fold_in(state.rng, state.step)
        worker_rngs = jax.random.split(step_rng, W)
        flat_labels = wbatch["label"].reshape(-1)

        new_params, new_opt, new_stats, loss_w, logits = _worker_updates(
            state, loss_rows, W, state.params, state.opt_state,
            state.batch_stats, wbatch["image"], wbatch["label"], worker_rngs)

        new_step = state.step + 1

        def average(tree):
            return jax.tree.map(
                lambda x: jnp.broadcast_to(
                    jnp.mean(x.astype(jnp.float32), axis=0,
                             keepdims=True).astype(x.dtype), x.shape), tree)

        new_params = jax.lax.cond(new_step % period == 0,
                                  average, lambda t: t, new_params)
        new_state = state.replace(step=new_step, params=new_params,
                                  opt_state=new_opt, batch_stats=new_stats)
        metrics = {"loss": jnp.mean(loss_w),
                   "accuracy": accuracy(
                       logits.reshape(-1, logits.shape[-1]), flat_labels)}
        return new_state, metrics

    return step


def _build_shard_map_step(num_workers: int, period: int,
                          label_smoothing: float, ce_impl: str,
                          mesh, bucket_bytes: int | None = None) -> Callable:
    """Multi-device local-SGD step: the per-worker compute runs under
    ``jax.shard_map`` over the worker axis, so every device steps ONLY its
    own workers' parameter copies — zero collectives between averaging
    points, by construction.

    Why not let GSPMD partition the ``vmap`` body?  Measured on the
    8-device mesh (bench_scaling --mode async, round 2): the vmapped conv
    lowers to one grouped convolution whose worker axis is folded into the
    channel dim, and the SPMD partitioner then ALL-GATHERS the worker-tiled
    conv weights and activations (4 all-gathers sized like the gathered
    operands per step) and re-computes every worker's conv on every device
    — redundant compute and wire traffic that explicit per-device
    ``shard_map`` eliminates.  The cond-gated worker average becomes an
    explicit ``psum`` over the worker axis; everything else is local.

    Math is identical to the vmap body: same per-worker rngs, same
    separable summed-loss gradients, same period-aligned average (floats
    reduce in a different order, so results agree to fp tolerance, not
    bitwise, with the vmap path).
    """
    from jax.sharding import PartitionSpec as P

    D = mesh.size
    if num_workers % D:
        raise ValueError(
            f"num_workers {num_workers} must be a multiple of the mesh "
            f"size {D} (one or more whole virtual workers per device)")
    local_W = num_workers // D
    W = num_workers
    loss_rows = make_loss_rows(label_smoothing, ce_impl, mesh=None)

    def step(state: TrainState, batch) -> tuple[TrainState, dict]:
        wbatch = jax.tree.map(
            lambda x: x.reshape((W, x.shape[0] // W) + x.shape[1:]), batch)
        step_rng = jax.random.fold_in(state.rng, state.step)
        worker_rngs = jax.random.split(step_rng, W)

        def shard_body(step_no, params, opt_state, stats, images, labels,
                       rngs):
            # Everything here is the device's local [local_W, ...] slice.
            new_params, new_opt, new_stats, loss_w, logits = _worker_updates(
                state, loss_rows, local_W, params, opt_state, stats, images,
                labels, rngs)

            def average(tree):
                if bucket_bytes:
                    # The per-leaf tree psum below is the per-parameter
                    # collective pattern --bucket_grads fuses: one psum
                    # per knee-sized bucket of local worker-sums instead
                    # of one per leaf.  Bitwise: concatenation regroups
                    # which psum carries each element, never its
                    # cross-device addition order.
                    from distributedtensorflowexample_tpu.parallel.bucketing import (
                        bucketed_tree_psum)
                    sums = jax.tree.map(
                        lambda x: jnp.sum(x.astype(jnp.float32), axis=0,
                                          keepdims=True), tree)
                    sums = bucketed_tree_psum(sums, bucket_bytes, DATA_AXIS)
                    return jax.tree.map(
                        lambda x, s: jnp.broadcast_to(
                            (s / W).astype(x.dtype), x.shape), tree, sums)

                def avg(x):
                    s = jnp.sum(x.astype(jnp.float32), axis=0, keepdims=True)
                    s = jax.lax.psum(s, DATA_AXIS) / W
                    return jnp.broadcast_to(s.astype(x.dtype), x.shape)
                return jax.tree.map(avg, tree)

            new_params = jax.lax.cond((step_no + 1) % period == 0,
                                      average, lambda t: t, new_params)
            flat_logits = logits.reshape(-1, logits.shape[-1])
            flat_labels = labels.reshape(-1)
            total = flat_labels.shape[0] * D      # static global batch
            local_correct = jnp.sum(
                (jnp.argmax(flat_logits, axis=-1) == flat_labels)
                .astype(jnp.float32))
            # One fused all-reduce for both scalar metrics.
            loss_sum, correct = jax.lax.psum(
                (jnp.sum(loss_w), local_correct), DATA_AXIS)
            return (new_params, new_opt, new_stats, loss_sum / W,
                    correct / total)

        wspec = P(DATA_AXIS)
        from distributedtensorflowexample_tpu.compat import shard_map
        body = shard_map(
            shard_body, mesh=mesh,
            in_specs=(P(), wspec, wspec, wspec, wspec, wspec, wspec),
            out_specs=(wspec, wspec, wspec, P(), P()), check_vma=False)
        new_params, new_opt, new_stats, loss, acc = body(
            state.step, state.params, state.opt_state, state.batch_stats,
            wbatch["image"], wbatch["label"], worker_rngs)
        new_state = state.replace(step=state.step + 1, params=new_params,
                                  opt_state=new_opt, batch_stats=new_stats)
        return new_state, {"loss": loss, "accuracy": acc}

    return step


def make_async_train_step(num_workers: int, period: int,
                          label_smoothing: float = 0.0, ce_impl: str = "xla",
                          mesh=None, dequant: str | None = None,
                          dequant_impl: str = "auto",
                          quantize: str = "auto",
                          bucket_bytes: int | None = None) -> Callable:
    """Build the jitted host-fed local-SGD step over worker-tiled state.

    ``dequant``: spec for host-fed uint8 batches (``batcher.dequant``);
    ``dequant_impl``/``quantize``: the in-step dequant kernel knobs,
    resolved by the same rule as every other path (see
    sync.dequant_host_batch).  ``bucket_bytes`` (--bucket_grads) fuses
    the period-gated worker-average psums into knee-sized buckets."""
    from distributedtensorflowexample_tpu.parallel.sync import (
        dequant_host_batch)
    inner = _build_async_step_fn(num_workers, period, label_smoothing,
                                 ce_impl, mesh, bucket_bytes=bucket_bytes)

    def step(state: TrainState, batch):
        return inner(state, dequant_host_batch(batch, dequant, dequant_impl,
                                               quantize))

    return jax.jit(step, donate_argnums=0)


def make_indexed_async_train_step(num_workers: int, period: int,
                                  batch_size: int, steps_per_epoch: int,
                                  label_smoothing: float = 0.0,
                                  ce_impl: str = "xla", mesh=None,
                                  unroll_steps: int = 1,
                                  augment: str = "none",
                                  num_slots: int | None = None,
                                  data_sharding: str = "replicated",
                                  dequant_impl: str = "auto",
                                  bucket_bytes: int | None = None) -> Callable:
    """Local-SGD step over a device-resident dataset — async's analog of
    ``sync.make_indexed_train_step``: same on-device gather from the
    perm ring (multi-epoch fused windows supported), same ``lax.scan``
    multi-step fusion; the period-aligned worker averaging runs inside
    the scan (``new_step % period`` is exact whatever the unroll), so
    fused windows and averaging periods compose freely."""
    from distributedtensorflowexample_tpu.parallel.sync import (
        _resolve_num_slots)
    num_slots = _resolve_num_slots(unroll_steps, steps_per_epoch, num_slots)
    inner = _build_async_step_fn(num_workers, period, label_smoothing,
                                 ce_impl, mesh, bucket_bytes=bucket_bytes)
    gather = make_device_gather(batch_size, steps_per_epoch, augment, mesh,
                                num_slots=num_slots,
                                data_sharding=data_sharding,
                                dequant_impl=dequant_impl)

    def one(state: TrainState, data) -> tuple[TrainState, dict]:
        return inner(state, gather(state.step, state.rng, data))

    if unroll_steps == 1:
        return jax.jit(one, donate_argnums=0)

    def step(state: TrainState, data) -> tuple[TrainState, dict]:
        new_state, stacked = jax.lax.scan(
            lambda st, _: one(st, data), state, None, length=unroll_steps)
        return new_state, jax.tree.map(lambda m: jnp.mean(m, axis=0), stacked)

    return jax.jit(step, donate_argnums=0)
