"""Async parameter-server emulation via local SGD (SURVEY.md §7 step 6,
option b — config 2, BASELINE.json configs[1]).

The reference's async mode: each worker pulls variables from the PS, steps
on its own minibatch, and pushes updates with no inter-worker sync — stale
gradients ARE the semantics (SURVEY.md §3b).  True asynchrony has no
XLA-native analog (one program, lockstep devices), so we emulate the
statistical behavior TPU-natively:

* each of the mesh's devices hosts one *virtual worker* — a full parameter
  copy, sharded along ``DATA_AXIS`` on a leading worker axis (a vmap over
  the mesh: every device steps ITS worker's params on ITS batch shard,
  zero cross-device traffic);
* every ``period`` steps the copies are averaged (the mean over the worker
  axis lowers to an all-reduce over ICI) — bounded staleness instead of
  unbounded PS races, same "workers diverge then reconcile" dynamics,
  fully deterministic and restartable.

``period=1`` recovers exact sync SGD; large ``period`` approaches
independent workers.  The branch is a ``lax.cond`` so the whole step stays
one compiled program.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import optax

from distributedtensorflowexample_tpu.ops.losses import (
    accuracy, softmax_cross_entropy)
from distributedtensorflowexample_tpu.parallel.mesh import DATA_AXIS
from distributedtensorflowexample_tpu.training.state import TrainState


def make_worker_state(state: TrainState, num_workers: int, mesh) -> TrainState:
    """Tile replicated state into per-worker copies sharded over the mesh.

    Leading axis = virtual worker id; NamedSharding P(DATA_AXIS) puts one
    worker's copy on each device.
    """
    wshard = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec(DATA_AXIS))

    def tile(x):
        x = jnp.asarray(x)
        tiled = jnp.broadcast_to(x[None], (num_workers,) + x.shape)
        return jax.lax.with_sharding_constraint(tiled, wshard)

    tile_tree = jax.jit(lambda t: jax.tree.map(tile, t), out_shardings=wshard)
    return state.replace(params=tile_tree(state.params),
                         opt_state=tile_tree(state.opt_state),
                         batch_stats=tile_tree(state.batch_stats))


def consolidate(state: TrainState) -> TrainState:
    """Average the worker copies back into one replicated state (for eval,
    checkpoint hand-off to sync mode, or end of training)."""

    def avg(t):
        return jax.tree.map(lambda x: jnp.mean(x.astype(jnp.float32), axis=0)
                            .astype(x.dtype), t)

    return state.replace(params=jax.jit(avg)(state.params),
                         # optimizer moments averaged too (momentum is linear)
                         opt_state=jax.jit(avg)(state.opt_state),
                         batch_stats=jax.jit(avg)(state.batch_stats)
                         if state.batch_stats else state.batch_stats)


def make_async_train_step(num_workers: int, period: int,
                          label_smoothing: float = 0.0) -> Callable:
    """Build the jitted local-SGD step over worker-tiled state.

    Batch arrives as the usual global batch sharded on DATA_AXIS; it is
    reshaped to [workers, per_worker_batch, ...] (device-local, no data
    movement) and vmapped.
    """
    period = max(1, int(period))

    def step(state: TrainState, batch) -> tuple[TrainState, dict]:
        has_bn = bool(state.batch_stats)

        def per_worker(params, opt_state, stats, wbatch, rng):
            def loss_fn(p):
                variables = {"params": p}
                if has_bn:
                    variables["batch_stats"] = stats
                    logits, updated = state.apply_fn(
                        variables, wbatch["image"], train=True,
                        rngs={"dropout": rng}, mutable=["batch_stats"])
                    new_stats = updated["batch_stats"]
                else:
                    logits = state.apply_fn(variables, wbatch["image"],
                                            train=True, rngs={"dropout": rng})
                    new_stats = stats
                loss = softmax_cross_entropy(logits, wbatch["label"],
                                             label_smoothing)
                return loss, (logits, new_stats)

            (loss, (logits, new_stats)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            updates, new_opt = state.tx.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            metrics = {"loss": loss,
                       "accuracy": accuracy(logits, wbatch["label"])}
            return new_params, new_opt, new_stats, metrics

        # [G, ...] -> [W, G/W, ...]; shards are device-local so this is free.
        wbatch = jax.tree.map(
            lambda x: x.reshape((num_workers, x.shape[0] // num_workers)
                                + x.shape[1:]), batch)
        step_rng = jax.random.fold_in(state.rng, state.step)
        worker_rngs = jax.random.split(step_rng, num_workers)
        new_params, new_opt, new_stats, metrics = jax.vmap(per_worker)(
            state.params, state.opt_state, state.batch_stats, wbatch,
            worker_rngs)

        new_step = state.step + 1

        def average(tree):
            return jax.tree.map(
                lambda x: jnp.broadcast_to(
                    jnp.mean(x.astype(jnp.float32), axis=0,
                             keepdims=True).astype(x.dtype), x.shape), tree)

        new_params = jax.lax.cond(new_step % period == 0,
                                  average, lambda t: t, new_params)
        new_state = state.replace(step=new_step, params=new_params,
                                  opt_state=new_opt, batch_stats=new_stats)
        metrics = jax.tree.map(lambda m: jnp.mean(m), metrics)
        return new_state, metrics

    return jax.jit(step, donate_argnums=0)
