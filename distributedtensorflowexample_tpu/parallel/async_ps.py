"""Async parameter-server emulation via local SGD (SURVEY.md §7 step 6,
option b — config 2, BASELINE.json configs[1]).

The reference's async mode: each worker pulls variables from the PS, steps
on its own minibatch, and pushes updates with no inter-worker sync — stale
gradients ARE the semantics (SURVEY.md §3b).  True asynchrony has no
XLA-native analog (one program, lockstep devices), so we emulate the
statistical behavior TPU-natively:

* each of the mesh's devices hosts one *virtual worker* — a full parameter
  copy, sharded along ``DATA_AXIS`` on a leading worker axis (a vmap over
  the mesh: every device steps ITS worker's params on ITS batch shard,
  zero cross-device traffic);
* every ``period`` steps the copies are averaged (the mean over the worker
  axis lowers to an all-reduce over ICI) — bounded staleness instead of
  unbounded PS races, same "workers diverge then reconcile" dynamics,
  fully deterministic and restartable.

``period=1`` recovers exact sync SGD; large ``period`` approaches
independent workers.  The branch is a ``lax.cond`` so the whole step stays
one compiled program.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import optax

from distributedtensorflowexample_tpu.ops.losses import accuracy
from distributedtensorflowexample_tpu.parallel.mesh import DATA_AXIS
from distributedtensorflowexample_tpu.parallel.sync import (
    make_device_gather, make_loss_rows)
from distributedtensorflowexample_tpu.training.state import TrainState


def make_worker_state(state: TrainState, num_workers: int, mesh) -> TrainState:
    """Tile replicated state into per-worker copies sharded over the mesh.

    Leading axis = virtual worker id; NamedSharding P(DATA_AXIS) puts one
    worker's copy on each device.
    """
    wshard = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec(DATA_AXIS))

    def tile(x):
        x = jnp.asarray(x)
        tiled = jnp.broadcast_to(x[None], (num_workers,) + x.shape)
        return jax.lax.with_sharding_constraint(tiled, wshard)

    tile_tree = jax.jit(lambda t: jax.tree.map(tile, t), out_shardings=wshard)
    return state.replace(params=tile_tree(state.params),
                         opt_state=tile_tree(state.opt_state),
                         batch_stats=tile_tree(state.batch_stats))


def consolidate(state: TrainState) -> TrainState:
    """Average the worker copies back into one replicated state (for eval,
    checkpoint hand-off to sync mode, or end of training)."""

    def avg(t):
        return jax.tree.map(lambda x: jnp.mean(x.astype(jnp.float32), axis=0)
                            .astype(x.dtype), t)

    return state.replace(params=jax.jit(avg)(state.params),
                         # optimizer moments averaged too (momentum is linear)
                         opt_state=jax.jit(avg)(state.opt_state),
                         batch_stats=jax.jit(avg)(state.batch_stats)
                         if state.batch_stats else state.batch_stats)


def _build_async_step_fn(num_workers: int, period: int,
                         label_smoothing: float = 0.0, ce_impl: str = "xla",
                         mesh=None) -> Callable:
    """The un-jitted local-SGD (state, batch) -> (state, metrics) body over
    worker-tiled state, shared by the host-fed and indexed factories.

    The batch arrives as the usual global batch sharded on DATA_AXIS; it
    is reshaped to [workers, per_worker_batch, ...] (device-local, no data
    movement).  Per-worker gradients come from ONE ``value_and_grad`` of
    the summed per-worker mean losses: worker ``w``'s parameters only
    reach ``loss_w``, so d(sum)/d(params_w) IS that worker's gradient —
    same math as a per-worker grad under vmap, but the loss head runs on
    the worker-major flattened [W*Bw, C] logits OUTSIDE the vmap, which
    lets the Pallas CE kernel apply under its usual shard_map-over-batch
    pattern (a ``pallas_call`` has no batching rule XLA can partition).
    """
    period = max(1, int(period))
    loss_rows = make_loss_rows(label_smoothing, ce_impl, mesh)

    def step(state: TrainState, batch) -> tuple[TrainState, dict]:
        has_bn = bool(state.batch_stats)
        W = num_workers

        # [G, ...] -> [W, G/W, ...]; shards are device-local so this is free.
        wbatch = jax.tree.map(
            lambda x: x.reshape((W, x.shape[0] // W) + x.shape[1:]), batch)
        step_rng = jax.random.fold_in(state.rng, state.step)
        worker_rngs = jax.random.split(step_rng, W)
        flat_labels = wbatch["label"].reshape(-1)

        def loss_all(stacked_params):
            def fwd(params, stats, image, rng):
                variables = {"params": params}
                if has_bn:
                    variables["batch_stats"] = stats
                    logits, updated = state.apply_fn(
                        variables, image, train=True,
                        rngs={"dropout": rng}, mutable=["batch_stats"])
                    return logits, updated["batch_stats"]
                logits = state.apply_fn(variables, image, train=True,
                                        rngs={"dropout": rng})
                return logits, stats

            logits, new_stats = jax.vmap(fwd)(
                stacked_params, state.batch_stats, wbatch["image"],
                worker_rngs)
            rows = loss_rows(logits.reshape(-1, logits.shape[-1]),
                             flat_labels)
            loss_w = rows.reshape(W, -1).mean(axis=1)
            return jnp.sum(loss_w), (loss_w, logits, new_stats)

        (_, (loss_w, logits, new_stats)), grads = jax.value_and_grad(
            loss_all, has_aux=True)(state.params)
        updates, new_opt = jax.vmap(state.tx.update)(
            grads, state.opt_state, state.params)
        new_params = jax.vmap(optax.apply_updates)(state.params, updates)

        new_step = state.step + 1

        def average(tree):
            return jax.tree.map(
                lambda x: jnp.broadcast_to(
                    jnp.mean(x.astype(jnp.float32), axis=0,
                             keepdims=True).astype(x.dtype), x.shape), tree)

        new_params = jax.lax.cond(new_step % period == 0,
                                  average, lambda t: t, new_params)
        new_state = state.replace(step=new_step, params=new_params,
                                  opt_state=new_opt, batch_stats=new_stats)
        metrics = {"loss": jnp.mean(loss_w),
                   "accuracy": accuracy(
                       logits.reshape(-1, logits.shape[-1]), flat_labels)}
        return new_state, metrics

    return step


def make_async_train_step(num_workers: int, period: int,
                          label_smoothing: float = 0.0, ce_impl: str = "xla",
                          mesh=None) -> Callable:
    """Build the jitted host-fed local-SGD step over worker-tiled state."""
    return jax.jit(_build_async_step_fn(num_workers, period, label_smoothing,
                                        ce_impl, mesh), donate_argnums=0)


def make_indexed_async_train_step(num_workers: int, period: int,
                                  batch_size: int, steps_per_epoch: int,
                                  label_smoothing: float = 0.0,
                                  ce_impl: str = "xla", mesh=None,
                                  unroll_steps: int = 1,
                                  augment: str = "none",
                                  num_slots: int | None = None) -> Callable:
    """Local-SGD step over a device-resident dataset — async's analog of
    ``sync.make_indexed_train_step``: same on-device gather from the
    perm ring (multi-epoch fused windows supported), same ``lax.scan``
    multi-step fusion; the period-aligned worker averaging runs inside
    the scan (``new_step % period`` is exact whatever the unroll), so
    fused windows and averaging periods compose freely."""
    from distributedtensorflowexample_tpu.parallel.sync import (
        _resolve_num_slots)
    num_slots = _resolve_num_slots(unroll_steps, steps_per_epoch, num_slots)
    inner = _build_async_step_fn(num_workers, period, label_smoothing,
                                 ce_impl, mesh)
    gather = make_device_gather(batch_size, steps_per_epoch, augment, mesh,
                                num_slots=num_slots)

    def one(state: TrainState, data) -> tuple[TrainState, dict]:
        return inner(state, gather(state.step, state.rng, data))

    if unroll_steps == 1:
        return jax.jit(one, donate_argnums=0)

    def step(state: TrainState, data) -> tuple[TrainState, dict]:
        new_state, stacked = jax.lax.scan(
            lambda st, _: one(st, data), state, None, length=unroll_steps)
        return new_state, jax.tree.map(lambda m: jnp.mean(m, axis=0), stacked)

    return jax.jit(step, donate_argnums=0)
