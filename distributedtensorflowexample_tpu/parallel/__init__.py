from distributedtensorflowexample_tpu.parallel.mesh import (
    make_mesh, batch_sharding, replicated_sharding, shard_batch, DATA_AXIS,
)

__all__ = ["make_mesh", "batch_sharding", "replicated_sharding",
           "shard_batch", "DATA_AXIS"]
