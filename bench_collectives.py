#!/usr/bin/env python
"""Collective latency/bandwidth characterization — message size x mesh
shape curves with a fitted knee (arXiv:1810.11112's CUDA-aware-MPI
methodology applied to this stack).

PR 2 built the instrument that says which OPS carry the HBM bytes; this
is the comms twin's calibration half: for each collective (psum /
reduce-scatter / all-gather / all-to-all) and each 1-D submesh size,
measure wall latency across a message-size sweep and fit

    t(S) = alpha + S / beta          (alpha = fixed cost, beta = bandwidth)

whose knee ``alpha * beta`` is the message size where transfer time
equals fixed cost (50% efficiency).  The knee is what ``--bucket_grads
auto`` sizes gradient buckets to (parallel/bucketing.py): below it,
per-parameter all-reduces pay mostly alpha; fusing to >= ~4x the knee
pushes alpha's share under ~20%.

Default mode runs the identical programs on a forced multi-device CPU
mesh (compat.set_num_cpu_devices — the tests' 8-virtual-device
environment), so the curves are driver-measurable today; ``--real`` uses
the default backend and is the capture-window phase
(tools/supervise.py --capture), re-fitting the knee on chips.

Env/sentinel contract (BASELINE.md "bytes-attribution methodology"):
this container's shell profile exports JAX_PLATFORMS=cpu, under which
``--real`` resolves to the CPU backend — the record labels itself
``platform: cpu`` so CPU curves can never be mistaken for chip numbers.
With the env unset (``env -u JAX_PLATFORMS``) and the backend down,
``--real`` probes with the bench.py env knobs (BENCH_PROBE_TIMEOUT_S /
BENCH_RETRY_BUDGET_S / BENCH_RETRY_INTERVAL_S) and emits a sentinel
record instead of hanging, so the capture queue keeps moving.

Output: one JSON line per measured point, a final BENCH_*-family summary
line, and ``--json`` writes the full record (the BENCH_collectives_*
artifact the capture archives).
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time

_COLLECTIVES = ("psum", "reduce_scatter", "all_gather", "all_to_all")
# Ring-algorithm wire factors: an all-reduce moves 2(n-1)/n of the payload
# per device, the single-phase collectives (n-1)/n.
_BUS_FACTOR = {"psum": lambda n: 2 * (n - 1) / n,
               "reduce_scatter": lambda n: (n - 1) / n,
               "all_gather": lambda n: (n - 1) / n,
               "all_to_all": lambda n: (n - 1) / n}


def fit_latency_bandwidth(sizes_bytes, times_s) -> dict:
    """Least-squares fit of ``t = alpha + S/beta`` over (size, time)
    points.  Returns alpha (s), beta (bytes/s), the knee ``alpha*beta``
    (bytes), and r2 of the fit; degenerate inputs (one point, zero
    variance, non-positive slope) fall back to knee=None so callers
    never size buckets off a meaningless fit."""
    n = len(sizes_bytes)
    out = {"alpha_s": None, "beta_bytes_per_s": None, "knee_bytes": None,
           "r2": None}
    if n < 2:
        return out
    sx = sum(sizes_bytes)
    sy = sum(times_s)
    sxx = sum(s * s for s in sizes_bytes)
    sxy = sum(s * t for s, t in zip(sizes_bytes, times_s))
    den = n * sxx - sx * sx
    if den <= 0:
        return out
    slope = (n * sxy - sx * sy) / den          # 1/beta
    alpha = (sy - slope * sx) / n
    if slope <= 0 or alpha <= 0:
        return out
    mean_t = sy / n
    ss_tot = sum((t - mean_t) ** 2 for t in times_s)
    ss_res = sum((t - (alpha + slope * s)) ** 2
                 for s, t in zip(sizes_bytes, times_s))
    beta = 1.0 / slope
    out.update(alpha_s=alpha, beta_bytes_per_s=beta,
               knee_bytes=int(alpha * beta),
               r2=None if ss_tot == 0 else round(1 - ss_res / ss_tot, 4))
    return out


def suggest_bucket_bytes(knee_bytes: int | None) -> int | None:
    """--bucket_grads auto sizing from a fitted all-reduce knee: ~4x the
    knee (alpha's share of t(S) down to ~20%), clamped to a sane range
    so a pathological fit can't produce a 1-byte or 1-GB bucket."""
    if not knee_bytes or knee_bytes <= 0:
        return None
    return int(min(max(4 * knee_bytes, 256 << 10), 64 << 20))


def _sentinel(args, attempts: list) -> None:
    line = {"metric": "collective_allreduce_knee_bytes", "value": 0.0,
            "unit": "unavailable", "vs_baseline": 0.0,
            "detail": {"error": "backend unreachable — sentinel record; "
                                "probe outcomes supersede this line",
                       "probe_attempts": attempts, "provisional": True}}
    print(json.dumps(line), flush=True)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(line, f, indent=1)


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--real", action="store_true",
                        help="use the default backend's devices (the "
                             "capture-window mode); default forces a "
                             "virtual CPU mesh so curves are measurable "
                             "with the chip down")
    parser.add_argument("--max_devices", type=int, default=8)
    parser.add_argument("--sizes", default="4096,32768,262144,1048576,4194304",
                        help="comma-separated message sizes in BYTES (the "
                             "full payload per collective)")
    parser.add_argument("--collectives", default=",".join(_COLLECTIVES))
    parser.add_argument("--submeshes", default="2,4,8",
                        help="1-D data-mesh sizes to sweep")
    parser.add_argument("--repeats", type=int, default=7,
                        help="timed calls per point (min is reported: "
                             "the latency floor, arXiv:1810.11112 style)")
    parser.add_argument("--json", default="",
                        help="also write the full record here "
                             "(BENCH_collectives_* artifact)")
    args = parser.parse_args()

    if not args.real:
        # Forced CPU mesh, in-process config route (this image's
        # sitecustomize overrides the JAX_PLATFORMS env var — the same
        # block bench_scaling.py uses, before first backend use).
        import jax

        from distributedtensorflowexample_tpu.compat import (
            cpu_collective_flags, set_num_cpu_devices)
        if "collective_call_terminate" not in os.environ.get("XLA_FLAGS", ""):
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + cpu_collective_flags(warn_s=120, terminate_s=600))
        for knob, value in (("jax_platforms", "cpu"),
                            ("jax_cpu_enable_async_dispatch", False)):
            try:
                jax.config.update(knob, value)
            except RuntimeError:
                break
        else:
            try:
                set_num_cpu_devices(args.max_devices)
            except RuntimeError:
                pass
    else:
        # bench.py's probe loop, reused like bench_profile.py does — it
        # carries the contracts a local copy kept losing: the CPU-fallback
        # assert (a backend that silently degrades to CPU must fail the
        # probe, not get measured), TERM-grace-KILL on a hung probe child
        # (a SIGKILL mid-backend-init has wedged the shared tunnel), the
        # jittered sleep between retries, and the JAX_PLATFORMS=cpu /
        # BENCH_SKIP_PROBE skip (an exported CPU pin means there is no
        # tunnel to probe — measure on CPU and SAY so; the record labels
        # platform cpu below).
        import bench
        ok, attempts = bench._wait_for_backend()
        if not ok:
            _sentinel(args, attempts)
            return

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from distributedtensorflowexample_tpu.compat import shard_map
    from distributedtensorflowexample_tpu.obs import ledger as obs_ledger
    from distributedtensorflowexample_tpu.obs import serve as obs_serve

    # Run ledger + live scrape (env-gated; OBS_LEDGER / OBS_HTTP_PORT):
    # the same per-run bookkeeping every bench entrypoint now leaves.
    obs_ledger.maybe_begin("bench_collectives", config=vars(args))
    obs_serve.maybe_start()
    devices = jax.devices()
    platform = jax.default_backend()
    sizes = [int(s) for s in args.sizes.split(",") if s]
    colls = [c for c in args.collectives.split(",") if c]
    for c in colls:
        if c not in _COLLECTIVES:
            parser.error(f"unknown collective {c!r} (one of {_COLLECTIVES})")
    counts = [int(n) for n in args.submeshes.split(",") if n]
    counts = [n for n in counts
              if 1 < n <= min(len(devices), args.max_devices)]
    if not counts:
        if args.real:
            # A single-chip window has no collective mesh to sweep —
            # land a labeled record and keep the capture queue green
            # (multi-chip curves stay armed for a bigger window).
            line = {"metric": "collective_allreduce_knee_bytes",
                    "value": 0.0, "unit": "unavailable",
                    "vs_baseline": 0.0,
                    "detail": {"platform": platform,
                               "error": f"backend exposes "
                                        f"{len(devices)} device(s) — no "
                                        f"multi-device mesh to "
                                        f"characterize; multi-chip "
                                        f"curves stay armed",
                               "provisional": True}}
            print(json.dumps(line), flush=True)
            if args.json:
                with open(args.json, "w") as f:
                    json.dump(line, f, indent=1)
            # A deliberate labeled sentinel IS a reported outcome — the
            # atexit rc=None close is reserved for deaths that never got
            # to say anything.
            obs_ledger.end_global(
                rc=0, note="single-device window sentinel")
            return
        parser.error(f"no usable submesh size (have {len(devices)} devices)")

    axis = "data"

    def make_fn(coll, mesh, n, local_elems):
        if coll == "psum":
            op = lambda x: jax.lax.psum(x, axis)
        elif coll == "reduce_scatter":
            op = lambda x: jax.lax.psum_scatter(
                x, axis, scatter_dimension=0, tiled=True)
        elif coll == "all_gather":
            op = lambda x: jax.lax.all_gather(x, axis, axis=0, tiled=True)
        else:  # all_to_all
            op = lambda x: jax.lax.all_to_all(
                x.reshape(n, -1), axis, split_axis=0,
                concat_axis=0).ravel()
        return jax.jit(shard_map(op, mesh=mesh, in_specs=P(axis),
                                 out_specs=P(axis), check_vma=False))

    points = []
    knees: dict = {}
    for n in counts:
        mesh = Mesh(np.array(devices[:n]), (axis,))
        for coll in colls:
            series = []
            for size in sizes:
                # Full payload = `size` bytes of f32; element count
                # rounded up so every reshape/scatter divides (n*n
                # covers the all_to_all [n, k] split).
                elems = -(-(size // 4) // (n * n)) * (n * n)
                if coll == "all_gather":
                    local = elems // n        # gathers back to `elems`
                else:
                    local = elems
                rng = np.random.default_rng(0)
                host = rng.standard_normal(local * n).astype(np.float32)
                x = jax.device_put(
                    host, NamedSharding(mesh, P(axis)))
                fn = make_fn(coll, mesh, n, local)
                jax.block_until_ready(fn(x))     # compile + warm
                jax.block_until_ready(fn(x))
                best = math.inf
                for _ in range(max(1, args.repeats)):
                    t0 = time.perf_counter()
                    jax.block_until_ready(fn(x))
                    best = min(best, time.perf_counter() - t0)
                payload = elems * 4
                bus = _BUS_FACTOR[coll](n) * payload / best
                point = {"collective": coll, "devices": n,
                         "bytes": payload,
                         "latency_s": round(best, 9),
                         "goodput_bytes_per_s": round(payload / best),
                         "bus_bytes_per_s": round(bus),
                         "platform": platform}
                points.append(point)
                series.append((payload, best))
                print(json.dumps(point), flush=True)
            fit = fit_latency_bandwidth([s for s, _ in series],
                                        [t for _, t in series])
            knees.setdefault(coll, {})[str(n)] = fit

    ar_knee = None
    if "psum" in knees:
        ar_knee = knees["psum"][str(counts[-1])]["knee_bytes"]
    record = {
        "metric": "collective_allreduce_knee_bytes",
        "value": float(ar_knee or 0),
        "unit": "bytes" if ar_knee else "unavailable",
        "vs_baseline": 1.0,
        "detail": {
            "platform": platform,
            "forced_cpu_mesh": not args.real,
            "chip": platform not in ("cpu",),
            "note": ("CPU curves — latency/knee calibrate the CPU mesh "
                     "only, NEVER read as chip numbers; --real in a "
                     "live window re-fits them"
                     if platform == "cpu" else
                     "on-chip curves (capture window)"),
            "devices": counts,
            "sizes_bytes": sizes,
            "repeats": args.repeats,
            "knees": knees,
            "suggested_bucket_bytes": suggest_bucket_bytes(ar_knee),
            "points": points,
        },
    }
    print(json.dumps({k: v for k, v in record.items() if k != "detail"}
                     | {"detail": {k: v for k, v in
                                   record["detail"].items()
                                   if k != "points"}}), flush=True)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(record, f, indent=1)
        print(f"bench_collectives: wrote {args.json}", file=sys.stderr,
              flush=True)
    obs_ledger.end_global(rc=0)


if __name__ == "__main__":
    main()
