#!/usr/bin/env python
"""bench_serving — the serving-path bench family: closed-loop
throughput, p50/p99 latency, and the throughput-vs-SLO curve.

Six instruments over one engine family (serving/):

1. **Supervised headline** (default on): a REAL ``tools/serve_lm.py``
   worker runs as a child of the resilience Supervisor — heartbeat
   watchdog armed, snapshot promoted through the SnapshotStore validity
   path, the in-process closed loop driving it — and its stats JSON
   supplies the headline tokens/sec + p50/p99.  This is the
   end-to-end number: process boundary, supervision, promotion, and
   continuous batching all on the measured path.
2. **Saturation sweep** (in-process, one jax import): closed-loop
   clients 1..K against the same engine — tokens/sec climbs until the
   decode slots saturate, then latency climbs instead.  The knee is
   the capacity number a capacity planner wants.
3. **SLO sweep**: at saturating load, sweep ``--slo_sweep_ms`` through
   the admission knob: in-SLO goodput (tokens/sec of ACCEPTED work),
   p50/p99 of the accepted work, and the rejection rate at each
   operating point — the throughput-vs-SLO curve the round-15 record
   checks in.
4. **Params-stay-sharded point** (round 17): ``promote_sharded`` +
   ``ShardedDecodeEngine`` at a D-device mesh — closed-loop tokens/sec
   with params resident at 1/D, plus the residency measured from LIVE
   shardings (``params_residency``), including the lm_base/D=4
   instrument the round-12 training-side claim used.
5. **Speculative draft-k sweep** (round 17): self-draft (same
   snapshot drafts → full acceptance, the machinery's upper bound)
   against the SAME workload decoded plain-greedy — tokens/sec,
   acceptance length, and a ``*_mismatch`` column tools/bench_ratchet.py
   holds at ZERO (spec output is bitwise greedy by construction).
6. **Batched-prefill amortization** (round 17): one ``prefill_many``
   call over a same-bucket burst vs the same prompts prefilled solo —
   the per-request speedup continuous batching's admission path banks.

CPU numbers calibrate the machinery and arm chip predictions (the
armed_predictions_round15_serving block in BASELINE_SELF.json);
``--real`` re-runs the same instruments on the configured backend at a
window.  Output: JSON lines (bench.py dialect, ``spread_frac`` stamped
from repeats) + ``--json`` writes the SERVE_lm_* artifact
tools/bench_ratchet.py ratchets and folds into BENCH_trajectory.json.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
import traceback

_REPO = os.path.dirname(os.path.abspath(__file__))


def _emit(metric: str, value: float, unit: str, detail: dict,
          lines: list) -> None:
    rec = {"metric": metric, "value": round(float(value), 6),
           "unit": unit, "vs_baseline": 1.0, "detail": detail}
    print(json.dumps(rec), flush=True)
    lines.append(rec)


def _run_point(engine, *, requests: int, clients: int, max_new: int,
               slo_ms: float, seed: int) -> dict:
    """One closed-loop operating point against a fresh queue/batcher
    (the engine and its compiled programs are shared across points)."""
    from distributedtensorflowexample_tpu.serving.loadgen import (
        ClosedLoopLoadGen)
    from distributedtensorflowexample_tpu.serving.queue import (
        ContinuousBatcher, RequestQueue)

    queue = RequestQueue(engine.vocab)
    batcher = ContinuousBatcher(engine, queue, slo_ms=slo_ms)
    gen = ClosedLoopLoadGen(queue, total=requests, clients=clients,
                            max_new=max_new, vocab=engine.vocab,
                            seed=seed)
    done = threading.Event()
    box: dict = {}

    def _drive():
        # Rejected ids re-queue forever under a tight SLO; bound the
        # point by letting each id fail at most a few times.
        box.update(gen.run())
        done.set()

    t = threading.Thread(target=_drive, daemon=True)
    steps0 = engine.decode_steps          # engine is shared across points
    t0 = time.monotonic()
    t.start()
    batcher.run(should_stop=done.is_set)
    t.join(timeout=10)
    wall = time.monotonic() - t0
    stats = batcher.stats()
    stats["decode_steps"] = engine.decode_steps - steps0
    goodput = (stats["tokens"] / wall) if wall > 0 else 0.0
    return {"clients": clients, "slo_ms": slo_ms,
            "requests": requests, "completed": stats["completed"],
            "rejected_slo": stats["rejected"]["slo"],
            "tokens": stats["tokens"], "wall_s": round(wall, 3),
            "goodput_tokens_per_sec": round(goodput, 3),
            "p50_ms": stats["p50_ms"], "p99_ms": stats["p99_ms"],
            "decode_steps": stats["decode_steps"],
            "step_ewma_ms": stats["step_ewma_ms"]}


def _oracle_run(engine, prompts, *, spec=None, repeats=3) -> tuple:
    """Decode ``prompts`` to completion through a fresh batcher
    (optionally speculative): submit-all-then-step keeps the workload
    IDENTICAL across configurations, so the returned token map diffs
    bitwise against another configuration's (the ``*_mismatch``
    column).  Returns ``(tokens_by_rid, [tokens/sec per repeat])`` —
    repeat 0 pays the cold compiles and is dropped by callers."""
    from distributedtensorflowexample_tpu.serving.queue import (
        ContinuousBatcher, RequestQueue)
    toks_by_rid: dict = {}
    rates: list = []
    for _ in range(max(1, repeats)):
        queue = RequestQueue(engine.vocab)
        b = ContinuousBatcher(engine, queue, slo_ms=0.0, spec=spec)
        reqs = [queue.submit(p, m, rid=f"o{i}")
                for i, (p, m) in enumerate(prompts)]
        t0 = time.monotonic()
        while any(not r.done.is_set() for r in reqs):
            b.step()
        wall = time.monotonic() - t0
        prev, toks_by_rid = toks_by_rid, {r.rid: list(r.tokens)
                                          for r in reqs}
        if prev and prev != toks_by_rid:
            raise AssertionError(
                "oracle workload not deterministic across repeats")
        total = sum(len(r.tokens) for r in reqs)
        rates.append(round(total / wall, 3) if wall > 0 else 0.0)
    return toks_by_rid, rates


def _supervised_headline(args, snapshot: str, workdir: str) -> dict:
    """The end-to-end point: serve_lm under the Supervisor, heartbeat
    armed, driven by its own closed loop; returns its stats JSON plus
    the supervision verdict."""
    from distributedtensorflowexample_tpu.resilience.supervisor import (
        Supervisor)
    stats_path = os.path.join(workdir, "serve_stats.json")
    hb_path = os.path.join(workdir, "serve.beat")
    argv = [sys.executable, os.path.join(_REPO, "tools", "serve_lm.py"),
            "--snapshot", snapshot, "--size", args.size,
            "--slots", str(args.slots), "--max_len", str(args.max_len),
            "--drive", str(args.requests),
            "--clients", str(args.clients_sweep[-1]),
            "--drive_max_new", str(args.max_new),
            "--seed", str(args.seed), "--stats", stats_path]
    if args.real:
        argv.append("--real")
    res = Supervisor(heartbeat_timeout_s=180.0).run(
        argv, name="bench_serving_headline",
        stdout_path=os.path.join(workdir, "serve.out"),
        stderr_path=os.path.join(workdir, "serve.err"),
        heartbeat_path=hb_path)
    out = {"supervision": {"status": res.status, "rc": res.returncode,
                           "attempts": res.attempts}}
    try:
        with open(stats_path) as f:
            out["stats"] = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        out["error"] = f"no stats from supervised worker: {e!r}"
    return out


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--size", default="lm_tiny",
                   help="graft-LM size to serve (lm_tiny = CPU-"
                        "measurable; bigger rungs at a window)")
    p.add_argument("--snapshot", default="",
                   help="snapshot dir (default: <workdir>/snaps, "
                        "demo-initialized if empty)")
    p.add_argument("--workdir", default="/tmp/bench_serving")
    p.add_argument("--slots", type=int, default=4)
    p.add_argument("--max_len", type=int, default=64)
    p.add_argument("--requests", type=int, default=0,
                   help="requests per operating point (default "
                        "$SERVE_LOAD_REQUESTS*8 or 128)")
    p.add_argument("--max_new", type=int, default=8)
    p.add_argument("--clients_sweep", default="1,2,4,8")
    p.add_argument("--slo_sweep_ms", default="0,25,50,100")
    p.add_argument("--repeats", type=int, default=3,
                   help="headline-point repeats (spread_frac source)")
    p.add_argument("--supervised_repeats", type=int, default=2,
                   help="supervised end-to-end repeats (its wall "
                        "includes worker cold-start, so its own "
                        "spread_frac matters)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--host_devices", type=int, default=8,
                   help="CPU calibration: force this many host devices "
                        "so the sharded point has a mesh (0 = leave "
                        "XLA_FLAGS alone; ignored under --real)")
    p.add_argument("--sharded_mesh", type=int, default=0,
                   help="mesh size D for the params-stay-sharded point "
                        "(0 = auto: 4 if available, else 2, else skip)")
    p.add_argument("--spec_k_sweep", default="2,4",
                   help="draft window sizes for the speculative sweep "
                        "(empty = skip)")
    p.add_argument("--skip_supervised", action="store_true",
                   help="skip the supervised end-to-end headline "
                        "(in-process sweeps only)")
    p.add_argument("--real", action="store_true",
                   help="serve on the configured backend (default pins "
                        "CPU in-process)")
    p.add_argument("--json", default="",
                   help="write the SERVE_lm_* record here")
    args = p.parse_args(argv)
    args.clients_sweep = [int(x) for x in
                          args.clients_sweep.split(",") if x]
    args.slo_sweep_ms = [float(x) for x in
                         args.slo_sweep_ms.split(",") if x]
    args.spec_k_sweep = [int(x) for x in
                         args.spec_k_sweep.split(",") if x]

    if args.host_devices > 1 and not args.real:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count="
                f"{args.host_devices}").strip()

    import jax
    if not args.real:
        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass

    from distributedtensorflowexample_tpu.obs import ledger as obs_ledger
    from distributedtensorflowexample_tpu.obs import (
        recorder as obs_recorder)
    from distributedtensorflowexample_tpu.obs import serve as obs_serve
    from distributedtensorflowexample_tpu.obs.anomaly import (
        spread_fraction)
    from distributedtensorflowexample_tpu.serving.engine import (
        DecodeEngine)
    from distributedtensorflowexample_tpu.serving.loadgen import (
        load_requests_default)
    from distributedtensorflowexample_tpu.serving.promote import (
        init_lm_snapshot, promote)

    obs_recorder.maybe_install()
    obs_ledger.maybe_begin("bench_serving", config=vars(args))
    obs_serve.maybe_start()
    os.makedirs(args.workdir, exist_ok=True)
    snapshot = args.snapshot or os.path.join(args.workdir, "snaps")
    # Resolve the default BEFORE the supervised section reads
    # args.requests — `--drive 0` tells the worker to serve forever,
    # which turns the headline into a heartbeat-fed hang.
    requests = args.requests = (
        args.requests or max(128, load_requests_default() * 8))
    platform = jax.default_backend()
    size = args.size
    lines: list = []
    errors: dict = {}

    from distributedtensorflowexample_tpu.resilience.snapshot import (
        SnapshotStore)
    if SnapshotStore(snapshot).latest_valid() is None:
        init_lm_snapshot(snapshot, size, seed=args.seed)

    shared = {"platform": platform, "size": size, "slots": args.slots,
              "max_len": args.max_len, "max_new": args.max_new,
              "requests": requests}

    # 1. supervised end-to-end headline -----------------------------------
    if not args.skip_supervised:
        try:
            sup_runs = [
                _supervised_headline(args, snapshot, args.workdir)
                for _ in range(max(1, args.supervised_repeats))]
            rates = [(s.get("stats") or {}).get("tokens_per_sec") or 0.0
                     for s in sup_runs]
            best_i = max(range(len(rates)), key=lambda i: rates[i])
            sup, st = sup_runs[best_i], sup_runs[best_i].get("stats")
            if st and st.get("tokens_per_sec"):
                _emit(f"serve_{size}_supervised_tokens_per_sec",
                      st["tokens_per_sec"], "tokens/sec",
                      {**shared, "supervised": True,
                       "clients": args.clients_sweep[-1],
                       "repeats": rates,
                       "spread_frac": round(spread_fraction(rates), 4),
                       "p50_ms": st.get("p50_ms"),
                       "p99_ms": st.get("p99_ms"),
                       "completed": st.get("completed"),
                       "snapshot_step": st.get("snapshot_step"),
                       "snapshot_layout": st.get("snapshot_layout"),
                       "supervision": sup.get("supervision"),
                       "note": "tools/serve_lm.py under the resilience "
                               "Supervisor (heartbeat armed), driven by "
                               "its in-process closed loop — process "
                               "boundary + promotion + continuous "
                               "batching all on the measured path; the "
                               "wall includes worker cold-start (jax "
                               "import + compiles), so this is the "
                               "relaunch-cost-inclusive number"},
                      lines)
            else:
                errors["supervised"] = sup.get("error") or "no rate"
        except Exception as e:
            errors["supervised"] = repr(e)
            traceback.print_exc()

    # 2 + 3. in-process sweeps (one engine, one compile set) --------------
    pm = engine = None
    try:
        pm = promote(snapshot, size)
        engine = DecodeEngine(pm.model, pm.params, slots=args.slots,
                              cache_len=args.max_len)
        # Warm: compiles (prefill buckets + decode) out of the tape.
        _run_point(engine, requests=max(8, 2 * args.slots),
                   clients=2, max_new=args.max_new, slo_ms=0.0,
                   seed=args.seed + 999)

        sat_clients = args.clients_sweep[-1]
        reps = []
        rep_points = []
        for r in range(max(1, args.repeats)):
            pt = _run_point(engine, requests=requests,
                            clients=sat_clients, max_new=args.max_new,
                            slo_ms=0.0, seed=args.seed)
            reps.append(pt["goodput_tokens_per_sec"])
            rep_points.append(pt)
        best = max(range(len(reps)), key=lambda i: reps[i])
        headline = rep_points[best]
        spread = round(spread_fraction(reps), 4)
        _emit(f"serve_{size}_tokens_per_sec", reps[best], "tokens/sec",
              {**shared, "clients": sat_clients, "repeats": reps,
               "spread_frac": spread, "p50_ms": headline["p50_ms"],
               "p99_ms": headline["p99_ms"],
               "decode_steps": headline["decode_steps"],
               "step_ewma_ms": headline["step_ewma_ms"],
               "snapshot_step": pm.step,
               "snapshot_layout": pm.layout}, lines)
        _emit(f"serve_{size}_p99_ms", headline["p99_ms"], "ms",
              {**shared, "clients": sat_clients, "spread_frac": spread,
               "p50_ms": headline["p50_ms"],
               "repeats_p99_ms": [p["p99_ms"] for p in rep_points]},
              lines)

        curve_clients = [
            _run_point(engine, requests=requests, clients=c,
                       max_new=args.max_new, slo_ms=0.0,
                       seed=args.seed + 1 + c)
            for c in args.clients_sweep]
        curve_slo = [
            _run_point(engine, requests=requests, clients=sat_clients,
                       max_new=args.max_new, slo_ms=s,
                       seed=args.seed + 101 + int(s))
            for s in args.slo_sweep_ms]
        # The curve row's VALUE is a measured scalar — the best in-SLO
        # goodput across the constrained sweep points — never the
        # sweep's point count (a config choice the ratchet would then
        # gate on: changing --slo_sweep_ms must not read as a perf
        # regression).  Its spread_frac comes from REPEATS OF THAT
        # POINT, not from the unconstrained headline's repeats — a
        # record must not report another metric's noise as its own.
        constrained = [p for p in curve_slo if p["slo_ms"] > 0] \
            or curve_slo
        best_pt = max(constrained,
                      key=lambda p: p["goodput_tokens_per_sec"])
        slo_reps = [best_pt["goodput_tokens_per_sec"]] + [
            _run_point(engine, requests=requests, clients=sat_clients,
                       max_new=args.max_new, slo_ms=best_pt["slo_ms"],
                       seed=args.seed + 201 + r
                       )["goodput_tokens_per_sec"]
            for r in range(max(0, args.repeats - 1))]
        _emit(f"serve_{size}_throughput_vs_slo",
              max(slo_reps), "tokens/sec (best in-SLO goodput)",
              {**shared,
               "spread_frac": round(spread_fraction(slo_reps), 4),
               "repeats": slo_reps,
               "best_point_slo_ms": best_pt["slo_ms"],
               "saturation_sweep": curve_clients,
               "slo_sweep": curve_slo,
               "note": "closed-loop curves: saturation_sweep varies "
                       "clients at SLO off; slo_sweep varies the "
                       "admission SLO at saturating load — in-SLO "
                       "goodput vs rejection rate is the serving "
                       "capacity trade"}, lines)
    except Exception as e:
        errors["sweep"] = repr(e)
        traceback.print_exc()

    # 4. params-stay-sharded point ----------------------------------------
    try:
        import numpy as np
        from distributedtensorflowexample_tpu.serving.promote import (
            promote_sharded)
        from distributedtensorflowexample_tpu.serving.sharded import (
            ShardedDecodeEngine)
        ndev = len(jax.devices())
        D = args.sharded_mesh or (4 if ndev >= 4 else 2)
        if ndev < 2 or D > ndev or args.slots % D:
            errors["sharded"] = (f"needs a divisible mesh: devices="
                                 f"{ndev}, D={D}, slots={args.slots}")
        else:
            spm = promote_sharded(snapshot, size, mesh_size=D)
            seng = ShardedDecodeEngine(spm.model, spm.rows, spm.layout,
                                       slots=args.slots,
                                       cache_len=args.max_len)
            res = seng.params_residency()
            _run_point(seng, requests=max(8, 2 * args.slots), clients=2,
                       max_new=args.max_new, slo_ms=0.0,
                       seed=args.seed + 555)       # compiles out of the tape
            sh_reps, sh_pts = [], []
            for r in range(max(1, args.repeats)):
                pt = _run_point(seng, requests=requests,
                                clients=args.clients_sweep[-1],
                                max_new=args.max_new, slo_ms=0.0,
                                seed=args.seed + 31 + r)
                sh_reps.append(pt["goodput_tokens_per_sec"])
                sh_pts.append(pt)
            sb = max(range(len(sh_reps)), key=lambda i: sh_reps[i])
            _emit(f"serve_{size}_sharded_tokens_per_sec", sh_reps[sb],
                  "tokens/sec",
                  {**shared, "mesh_size": D, "repeats": sh_reps,
                   "spread_frac": round(spread_fraction(sh_reps), 4),
                   "p50_ms": sh_pts[sb]["p50_ms"],
                   "p99_ms": sh_pts[sb]["p99_ms"],
                   "residency": res,
                   "snapshot_layout": spm.source_layout,
                   "note": "params resident at 1/D (zero3 bucket rows), "
                           "one all-gather per bucket INSIDE the "
                           "compiled decode step (pinned by "
                           "SHARDED_DECODE_HLO_CONTRACT); the CPU mesh "
                           "is forced host devices, so this calibrates "
                           "the gather machinery, never chip "
                           "throughput"}, lines)
            _emit(f"serve_{size}_sharded_params_frac_per_device",
                  res["frac_per_device"], "fraction",
                  {**shared, "mesh_size": D, **res,
                   "expected": 1.0 / D}, lines)
        if ndev >= 4:
            # lm_base/D=4: the round-12 training-side residency claim
            # re-measured on the SERVING engine's live shardings — the
            # constructor device_puts the rows at 1/D, so reading the
            # placement needs no decode compile of the 57M-param rung.
            import jax.numpy as jnp
            from distributedtensorflowexample_tpu.models.transformer_lm \
                import build_lm
            from distributedtensorflowexample_tpu.parallel.mesh import (
                make_mesh)
            from distributedtensorflowexample_tpu.parallel.zero3 import (
                Zero3Layout)
            bmodel = build_lm("lm_base", max_len=args.max_len)
            bparams = bmodel.init(jax.random.PRNGKey(args.seed),
                                  jnp.zeros((1, 8), jnp.int32))["params"]
            bl = Zero3Layout(bparams, 8 << 20, make_mesh(4))
            beng = ShardedDecodeEngine(bmodel, bl.init_rows(bparams), bl,
                                       slots=4, cache_len=args.max_len)
            bres = beng.params_residency()
            _emit("serve_lm_base_sharded_params_frac_per_device",
                  bres["frac_per_device"], "fraction",
                  {"platform": platform, "size": "lm_base",
                   "mesh_size": 4, **bres, "expected": 0.25,
                   "note": "live-sharding residency of the 57M-param "
                           "rung at D=4 (the acceptance instrument): "
                           "bytes of the addressable shard vs bytes of "
                           "the logical row, summed over buckets"},
                  lines)
            del beng, bl, bparams
    except Exception as e:
        errors["sharded"] = repr(e)
        traceback.print_exc()

    # 5. speculative draft-k sweep ----------------------------------------
    try:
        if engine is not None and args.spec_k_sweep:
            import numpy as np
            from distributedtensorflowexample_tpu.serving.engine import (
                DecodeEngine)
            from distributedtensorflowexample_tpu.serving.spec import (
                SpecDecoder)
            rng = np.random.default_rng(args.seed + 7)
            n_req = max(16, 4 * args.slots)
            prompts = [(rng.integers(1, engine.vocab, size=int(
                rng.integers(4, 13))).astype(np.int32), args.max_new)
                for _ in range(n_req)]
            greedy_toks, greedy_rates = _oracle_run(engine, prompts)
            greedy_tps = max(greedy_rates[1:] or greedy_rates)
            draft = DecodeEngine(pm.model, pm.params, slots=args.slots,
                                 cache_len=args.max_len)
            sweep: list = []
            mismatch_total = 0
            for k in args.spec_k_sweep:
                spec = SpecDecoder(engine, draft, k=k)
                spec_toks, spec_rates = _oracle_run(engine, prompts,
                                                    spec=spec)
                tps = max(spec_rates[1:] or spec_rates)
                mism = sum(1 for rid in greedy_toks
                           if spec_toks.get(rid) != greedy_toks[rid])
                mismatch_total += mism
                st = spec.stats()
                sweep.append({
                    "k": k, "tokens_per_sec": tps,
                    "repeats": spec_rates,
                    "spread_frac": round(
                        spread_fraction(spec_rates[1:] or spec_rates), 4),
                    "accept_len_mean": st["accept_len_mean"],
                    "rounds": st["rounds"], "mismatch": mism,
                    "uplift_vs_greedy": (round(tps / greedy_tps, 4)
                                         if greedy_tps else None)})
            best = max(sweep, key=lambda s: s["tokens_per_sec"])
            _emit(f"serve_{size}_spec_tokens_per_sec",
                  best["tokens_per_sec"], "tokens/sec",
                  {**shared, "k": best["k"], "requests": n_req,
                   "spread_frac": best["spread_frac"],
                   "greedy_tokens_per_sec": greedy_tps,
                   "greedy_repeats": greedy_rates,
                   "uplift_vs_greedy": best["uplift_vs_greedy"],
                   "draft": f"{size} (self-draft)", "k_sweep": sweep,
                   "note": "self-draft (same snapshot) = full "
                           "acceptance, the machinery's upper bound: "
                           "on CPU the draft steps cost target price, "
                           "so the uplift here calibrates batched-"
                           "verify dispatch amortization only — the "
                           "chip prediction arms the LADDER draft "
                           "(lm_tiny drafting lm_base at ~1/50th the "
                           "step cost), see BASELINE_SELF.json"}, lines)
            _emit(f"serve_{size}_spec_accept_len",
                  best["accept_len_mean"] or 0.0, "tokens/round",
                  {**shared, "k": best["k"], "k_sweep": sweep,
                   "note": "mean tokens emitted per slot-round "
                           "(accepted draft prefix + the verify step's "
                           "own token); k+1 = full acceptance"}, lines)
            _emit(f"serve_{size}_spec_mismatch", float(mismatch_total),
                  "requests",
                  {**shared, "requests_per_k": n_req, "k_sweep": sweep,
                   "note": "speculative output vs plain greedy on the "
                           "identical workload — the ratchet's "
                           "must-be-zero family (*_mismatch): any "
                           "nonzero is a broken acceptance rule, "
                           "never noise"}, lines)
    except Exception as e:
        errors["spec"] = repr(e)
        traceback.print_exc()

    # 6. batched-prefill amortization -------------------------------------
    try:
        if engine is not None:
            import numpy as np
            rng = np.random.default_rng(args.seed + 17)
            B = args.slots
            bp = [rng.integers(1, engine.vocab,
                               size=5 + (i % 4)).astype(np.int32)
                  for i in range(B)]       # all land in the same bucket
            for s in range(B):             # warm both shapes
                engine.prefill(s, bp[s], 1)
            engine.prefill_many([(s, bp[s], 1) for s in range(B)])
            solo_times, batch_times = [], []
            for _ in range(5):
                t0 = time.monotonic()
                for s in range(B):
                    engine.prefill(s, bp[s], 1)
                solo_times.append(time.monotonic() - t0)
                t0 = time.monotonic()
                engine.prefill_many([(s, bp[s], 1) for s in range(B)])
                batch_times.append(time.monotonic() - t0)
            solo, batched = min(solo_times), min(batch_times)
            _emit(f"serve_{size}_prefill_batch_amortization",
                  round(solo / batched, 4) if batched > 0 else 0.0, "x",
                  {**shared, "batch": B,
                   "solo_ms_per_request": round(solo / B * 1000.0, 4),
                   "batched_ms_per_request":
                       round(batched / B * 1000.0, 4),
                   "solo_repeats_ms": [round(t * 1000.0, 3)
                                       for t in solo_times],
                   "batched_repeats_ms": [round(t * 1000.0, 3)
                                          for t in batch_times],
                   "note": "one prefill_many call over a same-bucket "
                           "burst vs the same prompts prefilled solo "
                           "(best of 5, warm): the admission path's "
                           "burst amortization, also the term the "
                           "SLO predictor prices per-request"}, lines)
    except Exception as e:
        errors["prefill_batch"] = repr(e)
        traceback.print_exc()

    if args.json:
        meta = {"metric": "serving_bench_meta",
                "value": float(len(lines)), "unit": "lines",
                "vs_baseline": 1.0,
                "detail": {"family": "SERVE_lm", "platform": platform,
                           "provisional": True,   # meta, not a measurement
                           "errors": errors,
                           "note": ("CPU-platform numbers calibrate the "
                                    "serving machinery and arm chip "
                                    "predictions; never read as chip "
                                    "throughput" if platform == "cpu"
                                    else "capture-window record")}}
        with open(args.json, "w") as f:
            for rec in lines + [meta]:
                f.write(json.dumps(rec) + "\n")
        print(f"bench_serving: wrote {args.json}", file=sys.stderr,
              flush=True)
    obs_ledger.end_global(rc=0, errors=errors or None)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
