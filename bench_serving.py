#!/usr/bin/env python
"""bench_serving — the serving-path bench family: closed-loop
throughput, p50/p99 latency, and the throughput-vs-SLO curve.

Three instruments over one engine (serving/):

1. **Supervised headline** (default on): a REAL ``tools/serve_lm.py``
   worker runs as a child of the resilience Supervisor — heartbeat
   watchdog armed, snapshot promoted through the SnapshotStore validity
   path, the in-process closed loop driving it — and its stats JSON
   supplies the headline tokens/sec + p50/p99.  This is the
   end-to-end number: process boundary, supervision, promotion, and
   continuous batching all on the measured path.
2. **Saturation sweep** (in-process, one jax import): closed-loop
   clients 1..K against the same engine — tokens/sec climbs until the
   decode slots saturate, then latency climbs instead.  The knee is
   the capacity number a capacity planner wants.
3. **SLO sweep**: at saturating load, sweep ``--slo_sweep_ms`` through
   the admission knob: in-SLO goodput (tokens/sec of ACCEPTED work),
   p50/p99 of the accepted work, and the rejection rate at each
   operating point — the throughput-vs-SLO curve the round-15 record
   checks in.

CPU numbers calibrate the machinery and arm chip predictions (the
armed_predictions_round15_serving block in BASELINE_SELF.json);
``--real`` re-runs the same instruments on the configured backend at a
window.  Output: JSON lines (bench.py dialect, ``spread_frac`` stamped
from repeats) + ``--json`` writes the SERVE_lm_* artifact
tools/bench_ratchet.py ratchets and folds into BENCH_trajectory.json.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
import traceback

_REPO = os.path.dirname(os.path.abspath(__file__))


def _emit(metric: str, value: float, unit: str, detail: dict,
          lines: list) -> None:
    rec = {"metric": metric, "value": round(float(value), 6),
           "unit": unit, "vs_baseline": 1.0, "detail": detail}
    print(json.dumps(rec), flush=True)
    lines.append(rec)


def _run_point(engine, *, requests: int, clients: int, max_new: int,
               slo_ms: float, seed: int) -> dict:
    """One closed-loop operating point against a fresh queue/batcher
    (the engine and its compiled programs are shared across points)."""
    from distributedtensorflowexample_tpu.serving.loadgen import (
        ClosedLoopLoadGen)
    from distributedtensorflowexample_tpu.serving.queue import (
        ContinuousBatcher, RequestQueue)

    queue = RequestQueue(engine.vocab)
    batcher = ContinuousBatcher(engine, queue, slo_ms=slo_ms)
    gen = ClosedLoopLoadGen(queue, total=requests, clients=clients,
                            max_new=max_new, vocab=engine.vocab,
                            seed=seed)
    done = threading.Event()
    box: dict = {}

    def _drive():
        # Rejected ids re-queue forever under a tight SLO; bound the
        # point by letting each id fail at most a few times.
        box.update(gen.run())
        done.set()

    t = threading.Thread(target=_drive, daemon=True)
    steps0 = engine.decode_steps          # engine is shared across points
    t0 = time.monotonic()
    t.start()
    batcher.run(should_stop=done.is_set)
    t.join(timeout=10)
    wall = time.monotonic() - t0
    stats = batcher.stats()
    stats["decode_steps"] = engine.decode_steps - steps0
    goodput = (stats["tokens"] / wall) if wall > 0 else 0.0
    return {"clients": clients, "slo_ms": slo_ms,
            "requests": requests, "completed": stats["completed"],
            "rejected_slo": stats["rejected"]["slo"],
            "tokens": stats["tokens"], "wall_s": round(wall, 3),
            "goodput_tokens_per_sec": round(goodput, 3),
            "p50_ms": stats["p50_ms"], "p99_ms": stats["p99_ms"],
            "decode_steps": stats["decode_steps"],
            "step_ewma_ms": stats["step_ewma_ms"]}


def _supervised_headline(args, snapshot: str, workdir: str) -> dict:
    """The end-to-end point: serve_lm under the Supervisor, heartbeat
    armed, driven by its own closed loop; returns its stats JSON plus
    the supervision verdict."""
    from distributedtensorflowexample_tpu.resilience.supervisor import (
        Supervisor)
    stats_path = os.path.join(workdir, "serve_stats.json")
    hb_path = os.path.join(workdir, "serve.beat")
    argv = [sys.executable, os.path.join(_REPO, "tools", "serve_lm.py"),
            "--snapshot", snapshot, "--size", args.size,
            "--slots", str(args.slots), "--max_len", str(args.max_len),
            "--drive", str(args.requests),
            "--clients", str(args.clients_sweep[-1]),
            "--drive_max_new", str(args.max_new),
            "--seed", str(args.seed), "--stats", stats_path]
    if args.real:
        argv.append("--real")
    res = Supervisor(heartbeat_timeout_s=180.0).run(
        argv, name="bench_serving_headline",
        stdout_path=os.path.join(workdir, "serve.out"),
        stderr_path=os.path.join(workdir, "serve.err"),
        heartbeat_path=hb_path)
    out = {"supervision": {"status": res.status, "rc": res.returncode,
                           "attempts": res.attempts}}
    try:
        with open(stats_path) as f:
            out["stats"] = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        out["error"] = f"no stats from supervised worker: {e!r}"
    return out


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--size", default="lm_tiny",
                   help="graft-LM size to serve (lm_tiny = CPU-"
                        "measurable; bigger rungs at a window)")
    p.add_argument("--snapshot", default="",
                   help="snapshot dir (default: <workdir>/snaps, "
                        "demo-initialized if empty)")
    p.add_argument("--workdir", default="/tmp/bench_serving")
    p.add_argument("--slots", type=int, default=4)
    p.add_argument("--max_len", type=int, default=64)
    p.add_argument("--requests", type=int, default=0,
                   help="requests per operating point (default "
                        "$SERVE_LOAD_REQUESTS*8 or 128)")
    p.add_argument("--max_new", type=int, default=8)
    p.add_argument("--clients_sweep", default="1,2,4,8")
    p.add_argument("--slo_sweep_ms", default="0,25,50,100")
    p.add_argument("--repeats", type=int, default=3,
                   help="headline-point repeats (spread_frac source)")
    p.add_argument("--supervised_repeats", type=int, default=2,
                   help="supervised end-to-end repeats (its wall "
                        "includes worker cold-start, so its own "
                        "spread_frac matters)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--skip_supervised", action="store_true",
                   help="skip the supervised end-to-end headline "
                        "(in-process sweeps only)")
    p.add_argument("--real", action="store_true",
                   help="serve on the configured backend (default pins "
                        "CPU in-process)")
    p.add_argument("--json", default="",
                   help="write the SERVE_lm_* record here")
    args = p.parse_args(argv)
    args.clients_sweep = [int(x) for x in
                          args.clients_sweep.split(",") if x]
    args.slo_sweep_ms = [float(x) for x in
                         args.slo_sweep_ms.split(",") if x]

    import jax
    if not args.real:
        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass

    from distributedtensorflowexample_tpu.obs import ledger as obs_ledger
    from distributedtensorflowexample_tpu.obs import (
        recorder as obs_recorder)
    from distributedtensorflowexample_tpu.obs import serve as obs_serve
    from distributedtensorflowexample_tpu.obs.anomaly import (
        spread_fraction)
    from distributedtensorflowexample_tpu.serving.engine import (
        DecodeEngine)
    from distributedtensorflowexample_tpu.serving.loadgen import (
        load_requests_default)
    from distributedtensorflowexample_tpu.serving.promote import (
        init_lm_snapshot, promote)

    obs_recorder.maybe_install()
    obs_ledger.maybe_begin("bench_serving", config=vars(args))
    obs_serve.maybe_start()
    os.makedirs(args.workdir, exist_ok=True)
    snapshot = args.snapshot or os.path.join(args.workdir, "snaps")
    requests = args.requests or max(128, load_requests_default() * 8)
    platform = jax.default_backend()
    size = args.size
    lines: list = []
    errors: dict = {}

    from distributedtensorflowexample_tpu.resilience.snapshot import (
        SnapshotStore)
    if SnapshotStore(snapshot).latest_valid() is None:
        init_lm_snapshot(snapshot, size, seed=args.seed)

    shared = {"platform": platform, "size": size, "slots": args.slots,
              "max_len": args.max_len, "max_new": args.max_new,
              "requests": requests}

    # 1. supervised end-to-end headline -----------------------------------
    if not args.skip_supervised:
        try:
            sup_runs = [
                _supervised_headline(args, snapshot, args.workdir)
                for _ in range(max(1, args.supervised_repeats))]
            rates = [(s.get("stats") or {}).get("tokens_per_sec") or 0.0
                     for s in sup_runs]
            best_i = max(range(len(rates)), key=lambda i: rates[i])
            sup, st = sup_runs[best_i], sup_runs[best_i].get("stats")
            if st and st.get("tokens_per_sec"):
                _emit(f"serve_{size}_supervised_tokens_per_sec",
                      st["tokens_per_sec"], "tokens/sec",
                      {**shared, "supervised": True,
                       "clients": args.clients_sweep[-1],
                       "repeats": rates,
                       "spread_frac": round(spread_fraction(rates), 4),
                       "p50_ms": st.get("p50_ms"),
                       "p99_ms": st.get("p99_ms"),
                       "completed": st.get("completed"),
                       "snapshot_step": st.get("snapshot_step"),
                       "snapshot_layout": st.get("snapshot_layout"),
                       "supervision": sup.get("supervision"),
                       "note": "tools/serve_lm.py under the resilience "
                               "Supervisor (heartbeat armed), driven by "
                               "its in-process closed loop — process "
                               "boundary + promotion + continuous "
                               "batching all on the measured path; the "
                               "wall includes worker cold-start (jax "
                               "import + compiles), so this is the "
                               "relaunch-cost-inclusive number"},
                      lines)
            else:
                errors["supervised"] = sup.get("error") or "no rate"
        except Exception as e:
            errors["supervised"] = repr(e)
            traceback.print_exc()

    # 2 + 3. in-process sweeps (one engine, one compile set) --------------
    try:
        pm = promote(snapshot, size)
        engine = DecodeEngine(pm.model, pm.params, slots=args.slots,
                              cache_len=args.max_len)
        # Warm: compiles (prefill buckets + decode) out of the tape.
        _run_point(engine, requests=max(8, 2 * args.slots),
                   clients=2, max_new=args.max_new, slo_ms=0.0,
                   seed=args.seed + 999)

        sat_clients = args.clients_sweep[-1]
        reps = []
        rep_points = []
        for r in range(max(1, args.repeats)):
            pt = _run_point(engine, requests=requests,
                            clients=sat_clients, max_new=args.max_new,
                            slo_ms=0.0, seed=args.seed)
            reps.append(pt["goodput_tokens_per_sec"])
            rep_points.append(pt)
        best = max(range(len(reps)), key=lambda i: reps[i])
        headline = rep_points[best]
        spread = round(spread_fraction(reps), 4)
        _emit(f"serve_{size}_tokens_per_sec", reps[best], "tokens/sec",
              {**shared, "clients": sat_clients, "repeats": reps,
               "spread_frac": spread, "p50_ms": headline["p50_ms"],
               "p99_ms": headline["p99_ms"],
               "decode_steps": headline["decode_steps"],
               "step_ewma_ms": headline["step_ewma_ms"],
               "snapshot_step": pm.step,
               "snapshot_layout": pm.layout}, lines)
        _emit(f"serve_{size}_p99_ms", headline["p99_ms"], "ms",
              {**shared, "clients": sat_clients, "spread_frac": spread,
               "p50_ms": headline["p50_ms"],
               "repeats_p99_ms": [p["p99_ms"] for p in rep_points]},
              lines)

        curve_clients = [
            _run_point(engine, requests=requests, clients=c,
                       max_new=args.max_new, slo_ms=0.0,
                       seed=args.seed + 1 + c)
            for c in args.clients_sweep]
        curve_slo = [
            _run_point(engine, requests=requests, clients=sat_clients,
                       max_new=args.max_new, slo_ms=s,
                       seed=args.seed + 101 + int(s))
            for s in args.slo_sweep_ms]
        # The curve row's VALUE is a measured scalar — the best in-SLO
        # goodput across the constrained sweep points — never the
        # sweep's point count (a config choice the ratchet would then
        # gate on: changing --slo_sweep_ms must not read as a perf
        # regression).  Its spread_frac comes from REPEATS OF THAT
        # POINT, not from the unconstrained headline's repeats — a
        # record must not report another metric's noise as its own.
        constrained = [p for p in curve_slo if p["slo_ms"] > 0] \
            or curve_slo
        best_pt = max(constrained,
                      key=lambda p: p["goodput_tokens_per_sec"])
        slo_reps = [best_pt["goodput_tokens_per_sec"]] + [
            _run_point(engine, requests=requests, clients=sat_clients,
                       max_new=args.max_new, slo_ms=best_pt["slo_ms"],
                       seed=args.seed + 201 + r
                       )["goodput_tokens_per_sec"]
            for r in range(max(0, args.repeats - 1))]
        _emit(f"serve_{size}_throughput_vs_slo",
              max(slo_reps), "tokens/sec (best in-SLO goodput)",
              {**shared,
               "spread_frac": round(spread_fraction(slo_reps), 4),
               "repeats": slo_reps,
               "best_point_slo_ms": best_pt["slo_ms"],
               "saturation_sweep": curve_clients,
               "slo_sweep": curve_slo,
               "note": "closed-loop curves: saturation_sweep varies "
                       "clients at SLO off; slo_sweep varies the "
                       "admission SLO at saturating load — in-SLO "
                       "goodput vs rejection rate is the serving "
                       "capacity trade"}, lines)
    except Exception as e:
        errors["sweep"] = repr(e)
        traceback.print_exc()

    if args.json:
        meta = {"metric": "serving_bench_meta",
                "value": float(len(lines)), "unit": "lines",
                "vs_baseline": 1.0,
                "detail": {"family": "SERVE_lm", "platform": platform,
                           "provisional": True,   # meta, not a measurement
                           "errors": errors,
                           "note": ("CPU-platform numbers calibrate the "
                                    "serving machinery and arm chip "
                                    "predictions; never read as chip "
                                    "throughput" if platform == "cpu"
                                    else "capture-window record")}}
        with open(args.json, "w") as f:
            for rec in lines + [meta]:
                f.write(json.dumps(rec) + "\n")
        print(f"bench_serving: wrote {args.json}", file=sys.stderr,
              flush=True)
    obs_ledger.end_global(rc=0, errors=errors or None)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
