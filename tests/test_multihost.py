"""Multi-host bootstrap over real OS processes (SURVEY.md §4: the rebuild's
version of the reference's 'N processes on localhost' launch).

Spawns 2 python processes with a reference-style TF_CONFIG; each resolves
the cluster, calls jax.distributed.initialize (Gloo CPU collectives), forms
one 2-device mesh, and trains config 5 for a few steps.
"""

import os
import socket
import subprocess
import sys

_WORKER_SCRIPT = """
import jax
jax.config.update("jax_platforms", "cpu")
from distributedtensorflowexample_tpu.trainers import trainer_multiworker_cifar
s = trainer_multiworker_cifar.main([
    "--train_steps", "4", "--batch_size", "4", "--log_dir", {logdir!r},
    "--data_dir", "/nonexistent", "--resume", "false", "--log_every", "2",
])
print("SUMMARY steps=%d replicas=%d acc=%.4f"
      % (s["steps"], s["num_replicas"], s["final_accuracy"]))
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_tf_config_training(tmp_path):
    port = _free_port()
    workers = [f"127.0.0.1:{port}", f"127.0.0.1:{_free_port()}"]
    procs = []
    for idx in range(2):
        env = dict(os.environ)
        env["PALLAS_AXON_POOL_IPS"] = ""   # skip axon TPU registration
        env["TF_CONFIG"] = (
            '{"cluster": {"worker": ["%s", "%s"]}, '
            '"task": {"type": "worker", "index": %d}}'
            % (workers[0], workers[1], idx))
        script = _WORKER_SCRIPT.format(logdir=str(tmp_path / f"w{idx}"))
        procs.append(subprocess.Popen(
            [sys.executable, "-c", script],
            env=env, cwd=os.path.dirname(os.path.dirname(__file__)),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outputs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=280)
            outputs.append(out)
    finally:
        for p in procs:   # never leak workers if one hangs
            if p.poll() is None:
                p.kill()
                p.wait()
    for idx, (p, out) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"worker {idx} failed:\n{out}"
        assert "SUMMARY steps=4 replicas=2" in out, out
    # Chief-only logging: step lines from process 0 only.
    assert "step 2:" in outputs[0]
    assert "step 2:" not in outputs[1]
