"""Multi-host bootstrap over real OS processes (SURVEY.md §4: the rebuild's
version of the reference's 'N processes on localhost' launch).

Spawns 2 python processes with a reference-style TF_CONFIG; each resolves
the cluster, calls jax.distributed.initialize (Gloo CPU collectives), forms
one 2-device mesh, and runs the workload under test.
"""

import os
import socket
import subprocess
import sys

import jax
import pytest

# Cross-process SPMD on the CPU backend postdates 0.4.x: there a jitted
# computation over a multi-process mesh raises XlaRuntimeError
# "Multiprocess computations aren't implemented on the CPU backend" in
# every worker (the Gloo bootstrap itself succeeds — see
# cluster.maybe_initialize_distributed).  Nothing to test until the
# backend can run the program.
pytestmark = pytest.mark.skipif(
    jax.__version_info__ < (0, 5, 0),
    reason="multiprocess SPMD unimplemented on this jax's CPU backend")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_two_workers(script_template: str, tmp_path,
                       devices_per_proc: int = 1, shared_logdir: bool = False,
                       unbuffered: bool = False) -> list:
    """Launch 2 OS worker processes with a reference-style TF_CONFIG and
    return the running Popens (the ONE spawn contract every multihost
    test shares).  ``devices_per_proc`` > 1 gives each process that many
    virtual CPU devices; ``shared_logdir`` formats the same {logdir} into
    both workers (the real multi-host checkpointing shape) instead of a
    per-worker scratch dir."""
    workers = [f"127.0.0.1:{_free_port()}", f"127.0.0.1:{_free_port()}"]
    procs = []
    for idx in range(2):
        env = dict(os.environ)
        env["PALLAS_AXON_POOL_IPS"] = ""   # skip axon TPU registration
        env["JAX_NUM_CPU_DEVICES"] = str(devices_per_proc)
        env["TF_CONFIG"] = (
            '{"cluster": {"worker": ["%s", "%s"]}, '
            '"task": {"type": "worker", "index": %d}}'
            % (workers[0], workers[1], idx))
        logdir = str(tmp_path / ("shared" if shared_logdir else f"w{idx}"))
        script = script_template.format(logdir=logdir,
                                        ndev=devices_per_proc)
        argv = [sys.executable] + (["-u"] if unbuffered else []) + \
            ["-c", script]
        procs.append(subprocess.Popen(
            argv, env=env, cwd=os.path.dirname(os.path.dirname(__file__)),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    return procs


def _run_two_workers(script_template: str, tmp_path,
                     devices_per_proc: int = 1,
                     timeout: int = 280) -> list[str]:
    """Spawn (see _spawn_two_workers), wait for both, assert both exited
    0, and return their outputs."""
    procs = _spawn_two_workers(script_template, tmp_path, devices_per_proc)
    outputs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outputs.append(out)
    finally:
        for p in procs:   # never leak workers if one hangs
            if p.poll() is None:
                p.kill()
                p.wait()
    for idx, (p, out) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"worker {idx} failed:\n{out}"
    return outputs


_WORKER_SCRIPT = """
import jax
jax.config.update("jax_platforms", "cpu")
from distributedtensorflowexample_tpu.data import cifar10
cifar10._SYNTH_SIZES = {{"train": 512, "test": 256}}
from distributedtensorflowexample_tpu.trainers import trainer_multiworker_cifar
s = trainer_multiworker_cifar.main([
    "--train_steps", "4", "--batch_size", "4", "--log_dir", {logdir!r},
    "--data_dir", "/nonexistent", "--dataset", "synthetic",
    "--resume", "false", "--log_every", "2",
])
print("SUMMARY steps=%d replicas=%d acc=%.4f"
      % (s["steps"], s["num_replicas"], s["final_accuracy"]))
"""


def test_two_process_tf_config_training(tmp_path):
    outputs = _run_two_workers(_WORKER_SCRIPT, tmp_path)
    for out in outputs:
        assert "SUMMARY steps=4 replicas=2" in out, out
    # Chief-only logging: step lines from process 0 only.
    assert "step 2:" in outputs[0]
    assert "step 2:" not in outputs[1]
    # Sanity: the collective program returns one global accuracy, so both
    # processes must report the identical summary value.  (Slice
    # correctness of the resident eval is pinned by the dedicated test
    # below, which compares against the host-fed evaluate.)
    accs = [out.split("acc=")[1].split()[0] for out in outputs]
    assert accs[0] == accs[1], f"process accuracies diverged: {accs}"
    assert 0.0 <= float(accs[0]) <= 1.0


_ASYNC_SCRIPT = """
import jax
jax.config.update("jax_platforms", "cpu")
from distributedtensorflowexample_tpu.data import mnist
mnist._SYNTH_SIZES = {{"train": 256, "test": 128}}
from distributedtensorflowexample_tpu.trainers import trainer_ps_mnist
s = trainer_ps_mnist.main([
    "--train_steps", "8", "--batch_size", "8", "--global_batch", "true",
    "--steps_per_loop", "2", "--async_period", "4",
    "--log_dir", {logdir!r}, "--data_dir", "/nonexistent",
    "--dataset", "synthetic",
    "--resume", "false", "--log_every", "4", "--learning_rate", "0.05",
])
print("SUMMARY steps=%d replicas=%d acc=%.4f"
      % (s["steps"], s["num_replicas"], s["final_accuracy"]))
"""


def test_two_process_async_local_sgd(tmp_path):
    """Config 2 (async local-SGD, device-resident, fused steps) over 2 real
    OS processes: worker-tiled state spans the 2-device mesh, the periodic
    averaging all-reduce crosses the process boundary, and the consolidated
    eval agrees."""
    outputs = _run_two_workers(_ASYNC_SCRIPT, tmp_path)
    for out in outputs:
        assert "SUMMARY steps=8 replicas=2" in out, out
    accs = [out.split("acc=")[1].split()[0] for out in outputs]
    assert accs[0] == accs[1], f"process accuracies diverged: {accs}"


_EVAL_SCRIPT = """
import jax
jax.config.update("jax_platforms", "cpu")
from distributedtensorflowexample_tpu import cluster
from distributedtensorflowexample_tpu.config import RunConfig
info = cluster.resolve(RunConfig())            # TF_CONFIG from the env
cluster.maybe_initialize_distributed(info)
import optax
from distributedtensorflowexample_tpu.data import mnist
mnist._SYNTH_SIZES = {{"train": 512, "test": 256}}
from distributedtensorflowexample_tpu.data.mnist import load_mnist
from distributedtensorflowexample_tpu.models import build_model
from distributedtensorflowexample_tpu.parallel import (
    batch_sharding, make_mesh, replicated_sharding)
from distributedtensorflowexample_tpu.parallel.sync import (
    evaluate, make_resident_eval)
from distributedtensorflowexample_tpu.training.state import TrainState
mesh = make_mesh()
assert mesh.size == 2 and jax.process_count() == 2
x, y = load_mnist("/nonexistent", "test", source="synthetic")
state = TrainState.create_sharded(build_model("softmax"), optax.sgd(0.1),
                                  (64, 28, 28, 1), 3,
                                  replicated_sharding(mesh))
with mesh:
    host = evaluate(state, x, y, batch_size=64,
                    sharding=batch_sharding(mesh))
    res = make_resident_eval(x, y, batch_size=64, mesh=mesh)(state)
print("EVALS host=%.6f resident=%.6f" % (host, res))
assert abs(host - res) < 1e-9, (host, res)
print("EVAL_OK {logdir}")
"""


def test_two_process_resident_eval_matches_host_eval(tmp_path):
    """The device-resident eval's per-process COLUMN slices of the test
    split must reproduce the host-fed evaluate() exactly over 2 real
    processes — a wrong local slice shows up as a different accuracy."""
    outputs = _run_two_workers(_EVAL_SCRIPT, tmp_path)
    for out in outputs:
        assert "EVAL_OK" in out, out


# ---- N processes x M devices/process (VERDICT r2 item 4) ----------------
# All round-2 multihost coverage ran 2 procs x 1 device; the device-order
# assumptions (put_global_batch's contiguous row-range per process,
# make_resident_eval's per-process column slices, async worker tiling
# spanning processes) only bite when M > 1.

_NXM_TRAIN_SCRIPT = """
import jax
jax.config.update("jax_platforms", "cpu")
from distributedtensorflowexample_tpu.compat import set_num_cpu_devices
set_num_cpu_devices({ndev})
jax.config.update("jax_cpu_enable_async_dispatch", False)
from distributedtensorflowexample_tpu.data import mnist
mnist._SYNTH_SIZES = {{"train": 256, "test": 128}}
from distributedtensorflowexample_tpu.trainers import (
    trainer_ps_mnist, trainer_sync_mnist)
common = ["--train_steps", "4", "--batch_size", "8", "--global_batch",
          "true", "--data_dir", "/nonexistent", "--dataset", "synthetic",
          "--resume", "false",
          "--log_every", "2", "--learning_rate", "0.05"]
s = trainer_sync_mnist.main(
    common + ["--steps_per_loop", "2", "--log_dir", {logdir!r} + "/sync"])
print("SYNC steps=%d replicas=%d acc=%.6f"
      % (s["steps"], s["num_replicas"], s["final_accuracy"]))
s = trainer_sync_mnist.main(
    common + ["--device_data", "off", "--log_dir", {logdir!r} + "/host"])
print("HOSTFED steps=%d replicas=%d acc=%.6f"
      % (s["steps"], s["num_replicas"], s["final_accuracy"]))
s = trainer_ps_mnist.main(
    common + ["--steps_per_loop", "2", "--async_period", "2",
              "--log_dir", {logdir!r} + "/async"])
print("ASYNC steps=%d replicas=%d acc=%.6f"
      % (s["steps"], s["num_replicas"], s["final_accuracy"]))
s = trainer_sync_mnist.main(
    common + ["--steps_per_loop", "2", "--data_sharding", "sharded",
              "--log_dir", {logdir!r} + "/shard"])
print("SHARDED steps=%d replicas=%d acc=%.6f"
      % (s["steps"], s["num_replicas"], s["final_accuracy"]))
"""


def test_nxm_training_all_modes(tmp_path):
    """2 procs x 4 devices: sync device-resident, sync host-fed
    (Batcher + put_local_batch), async local-SGD (8 worker tiles
    spanning 2 processes), and sharded-resident (each process uploads
    only ITS devices' row blocks) all train and agree bitwise across
    processes."""
    # 4 trainings x several compiles per worker: give the launch the time
    # budget of four ordinary multihost tests (was 840 for three).
    outputs = _run_two_workers(_NXM_TRAIN_SCRIPT, tmp_path,
                               devices_per_proc=4, timeout=1120)
    for tag in ("SYNC", "HOSTFED", "ASYNC", "SHARDED"):
        lines = [l for out in outputs for l in out.splitlines()
                 if l.startswith(tag + " ")]
        assert len(lines) == 2, outputs
        assert all("steps=4 replicas=8" in l for l in lines), lines
        accs = {l.split("acc=")[1] for l in lines}
        assert len(accs) == 1, f"{tag} diverged across processes: {lines}"


_PREEMPT_SCRIPT = """
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_enable_async_dispatch", False)
from distributedtensorflowexample_tpu.data import mnist
mnist._SYNTH_SIZES = {{"train": 256, "test": 128}}
from distributedtensorflowexample_tpu.trainers import trainer_sync_mnist
trainer_sync_mnist.main([
    "--train_steps", "100000", "--batch_size", "8", "--global_batch",
    "true", "--steps_per_loop", "1", "--log_every", "5",
    "--log_dir", {logdir!r}, "--data_dir", "/nonexistent",
    "--dataset", "synthetic", "--resume", "true",
    "--learning_rate", "0.05",
])
"""


def test_two_process_preemption_consensus(tmp_path):
    """SIGTERM delivered to ONE worker only: the per-boundary stop
    consensus (process_allgather of the local flag) must stop BOTH
    processes at the same step — the un-signaled worker exits 143 too,
    and the collective checkpoint save (ONE shared --log_dir, the real
    multi-host deployment shape) completes instead of hanging in a
    half-abandoned psum."""
    import threading

    procs = _spawn_two_workers(_PREEMPT_SCRIPT, tmp_path,
                               shared_logdir=True, unbuffered=True)
    logs = [[], []]
    progressed = threading.Event()

    def drain(i):
        for line in procs[i].stdout:
            logs[i].append(line)
            if i == 0 and line.startswith("step ") and "loss" in line:
                progressed.set()
        if i == 0:
            progressed.set()           # EOF: unblock the waiter

    threads = [threading.Thread(target=drain, args=(i,), daemon=True)
               for i in range(2)]
    try:
        for t in threads:
            t.start()
        assert progressed.wait(timeout=300), "no training progress"
        assert procs[0].poll() is None, "".join(logs[0])[-2000:]
        procs[0].terminate()           # ONLY worker 0 is preempted
        for p in procs:
            p.wait(timeout=280)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
        for t in threads:
            t.join(timeout=30)
    out0, out1 = "".join(logs[0]), "".join(logs[1])
    assert procs[0].returncode == 143, (procs[0].returncode, out0[-2000:])
    assert procs[1].returncode == 143, (procs[1].returncode, out1[-2000:])
    # Chief (worker 0) announces the save; the collective checkpoint
    # landed in the shared directory.
    assert "SIGTERM at step" in out0, out0[-2000:]
    assert "SIGTERM at step" not in out1          # chief-only notice
    saved_dirs = [d for d in (tmp_path / "shared" / "checkpoints").iterdir()
                  if d.name.isdigit()]
    assert saved_dirs, out0[-1000:]


def test_divergent_checkpoint_dirs_fail_by_name(tmp_path):
    """Processes pointed at DIFFERENT --log_dir values with checkpointing
    on must fail with the named error up front — the alternative is a
    split-brain Orbax barrier that wedges the first save (observed)."""
    procs = _spawn_two_workers(_PREEMPT_SCRIPT, tmp_path, unbuffered=True)
    outs = []
    try:
        for p in procs:
            outs.append(p.communicate(timeout=280)[0])
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    for idx, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode != 0, f"worker {idx} unexpectedly succeeded"
        assert "differs across the 2 processes" in out, (idx, out[-2000:])


_NXM_EVAL_SCRIPT = """
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
from distributedtensorflowexample_tpu.compat import set_num_cpu_devices
set_num_cpu_devices({ndev})
jax.config.update("jax_cpu_enable_async_dispatch", False)
from distributedtensorflowexample_tpu import cluster
from distributedtensorflowexample_tpu.config import RunConfig
info = cluster.resolve(RunConfig())            # TF_CONFIG from the env
cluster.maybe_initialize_distributed(info)
import optax
from distributedtensorflowexample_tpu.data import mnist
mnist._SYNTH_SIZES = {{"train": 512, "test": 256}}
from distributedtensorflowexample_tpu.data.mnist import load_mnist
from distributedtensorflowexample_tpu.data.pipeline import put_global_batch
from distributedtensorflowexample_tpu.models import build_model
from distributedtensorflowexample_tpu.parallel import (
    batch_sharding, make_mesh, replicated_sharding)
from distributedtensorflowexample_tpu.parallel.sync import (
    evaluate, make_resident_eval)
from distributedtensorflowexample_tpu.training.state import TrainState
mesh = make_mesh()
assert mesh.size == 2 * {ndev} and jax.process_count() == 2

# put_global_batch: every process holds the same global array; each of the
# 2*M shards must get exactly its global row-range (the contiguous
# row-range-per-process assumption).
x = np.arange(16 * 3, dtype=np.float32).reshape(16, 3)
arr = put_global_batch({{"v": x}}, batch_sharding(mesh))["v"]
for shard in arr.addressable_shards:
    np.testing.assert_array_equal(np.asarray(shard.data), x[shard.index])
print("PUT_GLOBAL_OK")

xs, ys = load_mnist("/nonexistent", "test", source="synthetic")
state = TrainState.create_sharded(build_model("softmax"), optax.sgd(0.1),
                                  (64, 28, 28, 1), 3,
                                  replicated_sharding(mesh))
with mesh:
    host = evaluate(state, xs, ys, batch_size=64,
                    sharding=batch_sharding(mesh))
    res = make_resident_eval(xs, ys, batch_size=64, mesh=mesh)(state)
print("EVALS host=%.6f resident=%.6f" % (host, res))
assert abs(host - res) < 1e-9, (host, res)
print("EVAL_OK {logdir}")
"""


def test_nxm_put_global_batch_and_resident_eval(tmp_path):
    """2 procs x 4 devices: put_global_batch's per-shard rows are exactly
    the global row-ranges, and the resident eval's column slices reproduce
    the host-fed evaluate bitwise."""
    outputs = _run_two_workers(_NXM_EVAL_SCRIPT, tmp_path,
                               devices_per_proc=4, timeout=560)
    for out in outputs:
        assert "PUT_GLOBAL_OK" in out, out
        assert "EVAL_OK" in out, out
