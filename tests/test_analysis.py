"""graftlint self-tests (PR 13): one seeded violation per rule, the
real-repo zero-findings gate, the --fix round trip, and the compiled
mode suite honoring its declared HLO contracts.

The seeded trees plant EXACTLY one violation each and assert the exact
finding key fires — a rule that silently stops matching is itself the
regression these tests exist to catch.  The repo gate
(test_repo_src_lint_is_clean...) is the tier-1 wiring: it runs the same
rules the CLI runs and fails on any unwaived finding, so an invariant
break fails the suite inline, not in a tool nobody ran.

Marker strings for the keep-in-sync tests are built by concatenation so
THIS file never contains a literal marker the repo-wide scan would
pick up.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap

import pytest

from distributedtensorflowexample_tpu.analysis import (
    WAIVER_BUDGET, apply_waivers, load_waivers, src_lint, waivers_path)

pytestmark = pytest.mark.lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = "distributedtensorflowexample_tpu"
GRAFTLINT = os.path.join(REPO, "tools", "graftlint.py")

_MARK = "KEEP-IN-" + "SYNC"     # never a literal marker in this file


def _seed(tmp_path, files: dict) -> str:
    """Materialize a seeded repo tree with package ``seedpkg``."""
    root = tmp_path / "seedrepo"
    for rel, text in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(text))
    init = root / "seedpkg" / "__init__.py"
    if not init.exists():
        init.parent.mkdir(parents=True, exist_ok=True)
        init.write_text("")
    return str(root)


def _keys(findings, rule=None):
    return [f.key for f in findings if rule is None or f.rule == rule]


# --- stdlib-only (import graph) --------------------------------------------

def test_stdlib_only_rule_fires_with_import_chain(tmp_path):
    """obs/ reaching numpy directly AND a tagged module reaching jax
    through an intermediate package module both fire, with the chain
    in the message (the part the old subprocess probe could not say)."""
    root = _seed(tmp_path, {
        "seedpkg/obs/__init__.py": "",
        "seedpkg/obs/bad.py": "import numpy\n",
        "seedpkg/util.py": "import jax\n",
        "seedpkg/tagged.py": ("# graftlint: stdlib-only\n"
                              "from seedpkg.util import thing\n"),
    })
    fs = src_lint.run_src_lint(root, "seedpkg", rules=("stdlib-only",))
    keys = _keys(fs)
    assert "stdlib-only:obs.bad:numpy" in keys
    assert "stdlib-only:util:jax" in keys
    chain = [f for f in fs if f.key == "stdlib-only:util:jax"][0]
    assert "tagged" in chain.message and "util" in chain.message
    # A clean tree is clean: function-level imports are lazy, not
    # import-time reachability.
    clean = _seed(tmp_path / "c", {
        "seedpkg/obs/__init__.py": "",
        "seedpkg/obs/ok.py": ("import json\n"
                              "def lazy():\n    import numpy\n"),
    })
    assert src_lint.run_src_lint(clean, "seedpkg",
                                 rules=("stdlib-only",)) == []


# --- env registry -----------------------------------------------------------

_ENV_SEED = {
    "seedpkg/knobs.py": """\
        import os

        def _env_float(name, default):
            try:
                return float(os.environ.get(name, ""))
            except ValueError:
                return default

        READ = os.environ.get("SEED_UNDECLARED")
        VIA_HELPER = _env_float("SEED_VIA_HELPER", 1.0)

        def orphan_helper(name):
            return os.environ[name]
        """,
    "seedpkg/aliased_knobs.py": """\
        from os import environ as _e, getenv

        A = _e.get("SEED_FROM_IMPORT")
        B = getenv("SEED_GETENV")
        """,
    "seedpkg/analysis/env_registry.py": """\
        ENV_REGISTRY: dict[str, str] = {
            "SEED_DEAD_KNOB": "never read anywhere.",
        }
        """,
}


def test_env_registry_rules_fire(tmp_path):
    root = _seed(tmp_path, _ENV_SEED)
    fs = src_lint.run_src_lint(
        root, "seedpkg", rules=("env-registry", "env-dynamic", "env-dead"))
    keys = _keys(fs)
    # Named read not in registry; helper call sites resolve to a name
    # (also unregistered); a helper nothing calls with a constant is a
    # dynamic finding; the registry's orphan entry is a dead knob.
    assert "env-registry:SEED_UNDECLARED" in keys
    assert "env-registry:SEED_VIA_HELPER" in keys
    # Import aliases don't launder a knob read (from os import environ
    # as _e / bare getenv — the idioms the registry claim must cover).
    assert "env-registry:SEED_FROM_IMPORT" in keys
    assert "env-registry:SEED_GETENV" in keys
    assert "env-dynamic:seedpkg/knobs.py:orphan_helper" in keys
    assert "env-dead:SEED_DEAD_KNOB" in keys
    assert len(keys) == 6


def test_fix_inserts_registry_stubs_and_relints_clean(tmp_path):
    root = _seed(tmp_path, _ENV_SEED)
    applied = src_lint.apply_fixes(root, "seedpkg")
    assert any("SEED_UNDECLARED" in a for a in applied)
    fs = src_lint.run_src_lint(root, "seedpkg",
                               rules=("env-registry", "env-dynamic"))
    # The two mechanical findings are gone; the dynamic orphan (not
    # mechanical) survives --fix, as it should.
    assert _keys(fs, "env-registry") == []
    assert _keys(fs, "env-dynamic") == [
        "env-dynamic:seedpkg/knobs.py:orphan_helper"]
    text = (tmp_path / "seedrepo/seedpkg/analysis/env_registry.py"
            ).read_text()
    assert '"SEED_UNDECLARED"' in text and "TODO" in text


def test_fix_handles_one_liner_registry(tmp_path):
    """A hand-written `ENV_REGISTRY: dict[str, str] = {}` one-liner
    (no bare closing-brace line) must not crash --fix."""
    root = _seed(tmp_path, {
        "seedpkg/m.py": 'import os\nX = os.environ.get("SEED_ONE")\n',
        "seedpkg/analysis/env_registry.py":
            "ENV_REGISTRY: dict[str, str] = {}\n",
    })
    applied = src_lint.apply_fixes(root, "seedpkg")
    assert any("SEED_ONE" in a for a in applied)
    assert src_lint.run_src_lint(root, "seedpkg",
                                 rules=("env-registry",)) == []


# --- named refusal ----------------------------------------------------------

def test_named_refusal_rule_fires_on_flag_bearing_valueerror(tmp_path):
    root = _seed(tmp_path, {
        "seedpkg/modes.py": """\
            class ModeRefusal(ValueError):
                pass

            def check(flag):
                if flag == "bad":
                    raise ValueError(
                        "--seed_knob cannot run with --other_knob")
                if flag == "ok":
                    raise ModeRefusal("--seed_knob refused by name")
                raise ValueError(f"unknown flag {flag!r}")
            """,
    })
    fs = src_lint.run_src_lint(root, "seedpkg", rules=("named-refusal",))
    assert len(fs) == 1                       # only the bare ValueError
    assert fs[0].key.startswith("named-refusal:seedpkg/modes.py:")
    assert "--seed_knob" in fs[0].message


# --- clock seam -------------------------------------------------------------

def test_clock_seam_rule_fires_outside_metrics(tmp_path):
    root = _seed(tmp_path, {
        "seedpkg/obs/__init__.py": "",
        # The seam's home is exempt: it ASSIGNS the clocks, tests
        # monkeypatch it.
        "seedpkg/obs/metrics.py": ("import time\n"
                                   "_now = time.monotonic\n"
                                   "_wall = time.time\n"
                                   "def stamp():\n"
                                   "    return time.time()\n"),
        "seedpkg/obs/leaky.py": ("import time\n"
                                 "from datetime import datetime\n"
                                 "def stamp():\n"
                                 "    return time.time()\n"
                                 "def when():\n"
                                 "    return datetime.now()\n"),
        # Aliases don't launder the clock; a same-named LOCAL helper
        # (no time/datetime import behind it) is not a finding.
        "seedpkg/obs/aliased.py": ("import time as _t\n"
                                   "from time import time as _wallclock\n"
                                   "def a():\n"
                                   "    return _t.monotonic()\n"
                                   "def b():\n"
                                   "    return _wallclock()\n"
                                   "def now():\n"
                                   "    return 0\n"
                                   "def c():\n"
                                   "    return now()\n"),
    })
    fs = src_lint.run_src_lint(root, "seedpkg", rules=("clock-seam",))
    keys = _keys(fs)
    assert any("leaky.py:time.time" in k for k in keys)
    assert any("datetime.now" in k for k in keys)
    assert any("aliased.py:time.monotonic" in k for k in keys)
    assert any("aliased.py:time:" in k for k in keys)   # _wallclock()
    assert not any("metrics" in k for k in keys)
    assert len(keys) == 4


# --- keep-in-sync -----------------------------------------------------------

def _sync_pair(tmp_path, body_a="alpha\n", stamp=""):
    return _seed(tmp_path, {
        "a.py": (f"# {_MARK}(pairdemo){stamp}\n"
                 f"# {body_a}"
                 f"# {_MARK}-END(pairdemo)\n"),
        "b.sh": (f"# {_MARK}(pairdemo){stamp}\n"
                 f"# alpha\n"
                 f"# {_MARK}-END(pairdemo)\n"),
    })


def test_keep_in_sync_digest_lifecycle(tmp_path):
    root = _sync_pair(tmp_path)
    fs = src_lint.run_src_lint(root, "seedpkg", rules=("keep-in-sync",))
    assert sorted(_keys(fs)) == ["keep-in-sync:pairdemo:a.py",
                                 "keep-in-sync:pairdemo:b.sh"]
    assert all(f.fixable for f in fs)
    # --fix stamps both sides with one digest; re-lint is clean.
    src_lint.apply_fixes(root, "seedpkg")
    assert src_lint.run_src_lint(root, "seedpkg",
                                 rules=("keep-in-sync",)) == []
    # Content drift on ONE side stales BOTH digests (the rule's point:
    # an edit must acknowledge the partner), and --fix re-converges.
    a = os.path.join(root, "a.py")
    with open(a) as f:
        drifted = f.read().replace("# alpha", "# beta")
    with open(a, "w") as f:
        f.write(drifted)
    fs = src_lint.run_src_lint(root, "seedpkg", rules=("keep-in-sync",))
    assert sorted(_keys(fs)) == ["keep-in-sync:pairdemo:a.py",
                                 "keep-in-sync:pairdemo:b.sh"]
    assert all("drifted" in f.message for f in fs)
    src_lint.apply_fixes(root, "seedpkg")
    assert src_lint.run_src_lint(root, "seedpkg",
                                 rules=("keep-in-sync",)) == []


def test_keep_in_sync_unpaired_and_unterminated(tmp_path):
    root = _seed(tmp_path, {
        "solo.py": (f"# {_MARK}(loner)\n# body\n# {_MARK}-END(loner)\n"),
        "open.py": f"# {_MARK}(never)\n# body\n",
    })
    keys = _keys(src_lint.run_src_lint(root, "seedpkg",
                                       rules=("keep-in-sync",)))
    assert "keep-in-sync:loner:unpaired" in keys
    assert "keep-in-sync:never:unterminated" in keys


# --- engine-owns-wiring -----------------------------------------------------

def test_engine_owns_wiring_rule(tmp_path):
    """Raw step-wiring names outside engine/ and parallel/ fire — from
    module- AND function-level imports (lazy wiring is still wiring)
    and bare attribute references — while engine/, parallel/, and
    docstring prose stay clean, and tools/ scripts are in scope."""
    root = _seed(tmp_path, {
        "seedpkg/trainers/__init__.py": "",
        "seedpkg/trainers/bad.py": """\
            def build():
                from seedpkg.parallel.zero3 import Zero3Layout
                return Zero3Layout
        """,
        "seedpkg/serving/__init__.py": "",
        "seedpkg/serving/attr.py": """\
            import jax

            def f(x):
                return jax.shard_map(x)
        """,
        "seedpkg/engine/__init__.py": "",
        "seedpkg/engine/engine.py":
            "from seedpkg.parallel.sync import make_train_step\n",
        "seedpkg/parallel/__init__.py": "",
        "seedpkg/parallel/sync.py": "def make_train_step():\n    pass\n",
        "seedpkg/docs_only.py":
            '"""Prose may mention make_train_step and shard_map."""\n',
        "tools/wired.py":
            "from seedpkg.parallel.sync import make_train_step\n",
    })
    keys = _keys(src_lint.run_src_lint(root, "seedpkg",
                                       rules=("engine-owns-wiring",)))
    assert ("engine-owns-wiring:seedpkg/trainers/bad.py:Zero3Layout"
            in keys)
    assert "engine-owns-wiring:seedpkg/serving/attr.py:shard_map" in keys
    assert "engine-owns-wiring:tools/wired.py:make_train_step" in keys
    assert not any("engine/" in k or "parallel/" in k or "docs_only" in k
                   for k in keys)


# --- waiver machinery -------------------------------------------------------

def test_waiver_validation_staleness_and_budget(tmp_path):
    from distributedtensorflowexample_tpu.analysis import Finding
    wpath = str(tmp_path / "waivers.json")
    with open(wpath, "w") as f:
        json.dump({"waivers": [
            {"key": "env-registry:LIVE", "reason": "r", "date":
             "2026-08-04"},
            {"key": "env-registry:GONE", "reason": "r", "date":
             "2026-08-04"},
            {"key": "env-registry:NODATE", "reason": "r"},
            {"key": "hlo-budget:zero9:x", "reason": "r", "date":
             "2026-08-04"},
        ]}, f)
    waivers, wfs = load_waivers(wpath)
    assert _keys(wfs) == ["waiver-invalid:2"]      # the dateless one
    live = Finding("env-registry", "p.py", 1, "env-registry:LIVE", "m")
    unwaived, waived, stale = apply_waivers(
        [live], waivers, ran_rules={"env-registry"})
    assert unwaived == [] and _keys(waived) == ["env-registry:LIVE"]
    # GONE is stale (its rule ran, nothing matched); the hlo waiver is
    # NOT judged stale — that front did not run.
    assert _keys(stale) == ["waiver-stale:env-registry:GONE"]
    # Budget: more than WAIVER_BUDGET well-formed waivers is a finding.
    many = [{"key": f"k:{i}", "reason": "r", "date": "2026-08-04"}
            for i in range(WAIVER_BUDGET + 1)]
    with open(wpath, "w") as f:
        json.dump({"waivers": many}, f)
    _, wfs = load_waivers(wpath)
    assert _keys(wfs) == ["waiver-budget"]


# --- the repo gate (tier-1 wiring) ------------------------------------------

def test_repo_src_lint_is_clean_under_checked_in_waivers():
    """THE inline tier-1 gate: the full source front over the real repo
    must report zero unwaived findings given the checked-in waiver
    file.  Breaking an invariant (an undeclared env knob, a bare
    flag-bearing ValueError, obs/ importing numpy, marker drift) fails
    the suite right here."""
    findings = src_lint.run_src_lint(REPO, PKG)
    waivers, wfs = load_waivers(waivers_path(REPO, PKG))
    assert wfs == [], [f.message for f in wfs]
    assert len(waivers) <= WAIVER_BUDGET
    unwaived, _waived, stale = apply_waivers(
        findings, waivers, ran_rules=set(src_lint.SRC_RULES))
    assert unwaived == [], "\n".join(
        f"{f.rule} {f.path}:{f.line} {f.message}" for f in unwaived)
    assert stale == [], [f.key for f in stale]


def test_graftlint_cli_src_front_and_seeded_exit_codes(tmp_path):
    """CLI smokes: `python -m tools.graftlint --front src` exits 0 on
    the repo and 1 on a seeded violation; --json carries the finding."""
    out = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--front", "src"],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "clean" in out.stdout

    root = _seed(tmp_path, {
        "seedpkg/m.py": 'import os\nX = os.environ.get("SEED_NOPE")\n'})
    out = subprocess.run(
        [sys.executable, GRAFTLINT, "--front", "src", "--root", root,
         "--package", "seedpkg", "--json", "-"],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert out.returncode == 1, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert not payload["ok"]
    assert any(f["key"] == "env-registry:SEED_NOPE"
               for f in payload["unwaived"])


# --- HLO contract rules on synthetic text -----------------------------------

_ALIASED_HEADER = ("HloModule seeded, is_scheduled=true, "
                   "input_output_alias={ {0}: (0, {}, may-alias) }")


def _hlo(body_lines, header=_ALIASED_HEADER, params="p0: f32[8]"):
    body = "\n".join(f"  {ln}" for ln in body_lines)
    return (f"{header}\n\nENTRY %main ({params}) -> f32[8] {{\n"
            f"  %p0 = f32[8]{{0}} parameter(0)\n{body}\n"
            f"  ROOT %r = f32[8]{{0}} add(f32[8]{{0}} %p0, "
            f"f32[8]{{0}} %p0)\n}}\n")


_AG = "%ag{n} = f32[8]{{0}} all-gather(f32[1]{{0}} %p0), dimensions={{0}}"
_RS = ("%rs{n} = f32[1]{{0}} reduce-scatter(f32[8]{{0}} %p0), "
       "dimensions={{0}}")


def test_hlo_zero3_shape_rules_fire_on_seeded_violations():
    from distributedtensorflowexample_tpu.analysis.hlo_lint import (
        check_contract)
    from distributedtensorflowexample_tpu.parallel.zero3 import (
        HLO_CONTRACT as Z3)
    sym = {"B": 1}
    # Clean: AG before RS, nothing trailing.
    ok = _hlo([_AG.format(n=0), _RS.format(n=0)])
    assert check_contract(ok, Z3, symbols=sym) == []
    # Violation 1: the RS precedes its AG — the prefetch inverted.
    bad = _hlo([_RS.format(n=0), _AG.format(n=0)])
    keys = _keys(check_contract(bad, Z3, symbols=sym))
    assert "hlo-ag-before-rs:zero3:0" in keys
    # Violation 2: a step-closing AG after the last RS (ZeRO-1 leak).
    trailing = _hlo([_AG.format(n=0), _RS.format(n=0), _AG.format(n=1)])
    keys = _keys(check_contract(trailing, Z3, symbols=sym))
    assert "hlo-trailing-ag:zero3" in keys
    assert any(k.startswith("hlo-budget:zero3:all-gather")
               for k in keys)            # 2 AGs also bust the B=1 budget
    # Violation 3: the schedule vanished entirely (zero collectives).
    # NOT a vacuous pass: B buckets promise exactly B pairs, and the
    # symbol-valued budgets are exact.
    keys = _keys(check_contract(_hlo([]), Z3, symbols=sym))
    assert "hlo-ag-before-rs:zero3:buckets" in keys
    assert any(k.startswith("hlo-budget:zero3:") for k in keys)


def test_hlo_zero1_pair_and_budget_rules_fire():
    from distributedtensorflowexample_tpu.analysis.hlo_lint import (
        check_contract)
    from distributedtensorflowexample_tpu.parallel.bucketing import (
        ZERO1_HLO_CONTRACT as Z1)
    sym = {"B": 1}
    ok = _hlo([_RS.format(n=0), _AG.format(n=0)])
    assert check_contract(ok, Z1, symbols=sym) == []
    # Missing the update-closing AG entirely.
    keys = _keys(check_contract(_hlo([_RS.format(n=0)]), Z1, symbols=sym))
    assert "hlo-rs-ag-pair:zero1:count" in keys
    # A collective outside the declared budget (an all-to-all appears).
    a2a = ("%x = f32[8]{0} all-to-all(f32[8]{0} %p0), "
           "dimensions={0}")
    keys = _keys(check_contract(
        _hlo([_RS.format(n=0), _AG.format(n=0), a2a]), Z1, symbols=sym))
    assert "hlo-budget:zero1:all-to-all" in keys


def test_hlo_donation_and_dtype_ceiling_rules_fire():
    from distributedtensorflowexample_tpu.analysis.hlo_lint import (
        check_contract)
    contract = {"mode": "seeded", "require_alias": True,
                "no_donated_copy": True, "dtype_ceiling": "f32"}
    # Clean: aliased, no copies, no f64.
    assert check_contract(_hlo([]), contract) == []
    # No alias map at all: donation aliased nothing.
    plain = "HloModule seeded, is_scheduled=true"
    keys = _keys(check_contract(_hlo([], header=plain), contract))
    assert "hlo-donation:seeded:alias" in keys
    # Donated param copied in ENTRY.
    cp = "%cp = f32[8]{0} copy(f32[8]{0} %p0)"
    keys = _keys(check_contract(_hlo([cp]), contract))
    assert "hlo-donation:seeded:copy:p0" in keys
    # A DIFFERENT instruction whose name merely extends the donated
    # param's (%p0.1 — HLO's dotted suffixes) is not a copy of it.
    other = ("%p0.1 = f32[8]{0} add(f32[8]{0} %p0, f32[8]{0} %p0)",
             "%cp = f32[8]{0} copy(f32[8]{0} %p0.1)")
    assert check_contract(_hlo(list(other)), contract) == []
    # Upcast past the declared f32 ceiling.
    up = "%up = f64[8]{0} convert(f32[8]{0} %p0)"
    keys = _keys(check_contract(_hlo([up]), contract))
    assert "hlo-dtype-ceiling:seeded:f64" in keys
    # A misspelled ceiling must surface as a config finding, never
    # silently disable the check.
    bad = dict(contract, dtype_ceiling="float32")
    keys = _keys(check_contract(_hlo([up]), bad))
    assert "hlo-dtype-ceiling:seeded:config" in keys


# --- the compiled mode suite (the acceptance proof) -------------------------

def test_compiled_mode_suite_honors_declared_contracts():
    """zero3's AG-before-RS prefetch (no step-closing AG) and zero1's
    RS+AG pair are proven by HLO CONTRACT RULES on freshly compiled
    modules — not only by the runtime golden multisets in
    tests/test_collectives.py.  Also pins the suite's shape: a 2-bucket
    ladder, so the pairing rules check a real schedule."""
    from distributedtensorflowexample_tpu.analysis import hlo_lint
    progs = hlo_lint.mode_suite()
    assert [p["mode"] for p in progs] == [
        "sync_dp", "bucketed_allreduce", "zero1", "zero3"]
    by_mode = {p["mode"]: p for p in progs}
    assert by_mode["zero3"]["symbols"]["B"] == 2      # a real ladder
    for p in progs:
        fs = hlo_lint.check_contract(p["hlo"], p["contract"],
                                     symbols=p["symbols"])
        assert fs == [], (p["mode"], [f.message for f in fs])
    # The schedule shapes themselves, through the lint's own parser:
    seq3 = [op for op, _ in
            hlo_lint.collective_schedule(by_mode["zero3"]["hlo"])]
    assert seq3.count("all-gather") == 2
    assert seq3.count("reduce-scatter") == 2
    ags = [i for i, op in enumerate(seq3) if op == "all-gather"]
    rss = [i for i, op in enumerate(seq3) if op == "reduce-scatter"]
    assert max(ags) < min(rss)       # every prefetch AG precedes every RS
    seq1 = [op for op, _ in
            hlo_lint.collective_schedule(by_mode["zero1"]["hlo"])]
    first_rs = seq1.index("reduce-scatter")
    assert "all-gather" in seq1[first_rs:]   # update-closing AG follows
