"""The deterministic fleet simulator (sim/ + tools/sim_run.py): the
REAL scheduler + remediator on a virtual clock.

The claims under test, in order of importance:

1. **identity** — the sim executes the unmodified control plane:
   ``type(world.scheduler) is Scheduler`` (not a subclass, not a
   reimplementation), same for the remediation engine.
2. **fidelity** — a tiny queue run BOTH ways (live: real
   FleetSupervisor + stdlib children; sim: virtual clock + SimGang)
   produces the same per-job decision sequence in the ledger, and
   ``obs_query why`` tells the same story from either run's rows.
3. **determinism** — two same-seed runs produce bitwise-identical
   ledger AND write-ahead-journal bytes, even through a storm that
   exercises shrink/grow, heal eviction, SLO preemption, and the
   serve autoscale loop.
4. **scale** — 10,000 simulated ranks on a 4-slice mesh finish inside
   the tier-1 budget (<60 s wall for ~220 virtual seconds).

Everything here asserts against rows the REAL code wrote — never
against sim-internal state.
"""

import io
import json
import os
import sys
import textwrap
import time
from contextlib import redirect_stdout

import pytest

from distributedtensorflowexample_tpu.resilience.remediate import (
    Remediator)
from distributedtensorflowexample_tpu.resilience.scheduler import (
    Job, Scheduler)
from distributedtensorflowexample_tpu.resilience.supervisor import (
    Journal, RetryPolicy)
from distributedtensorflowexample_tpu.sim import (
    SimWorld, load_scenario, sim_metrics)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.sim


def _tool(name):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


def _world(tmp_path, scenario, sub="sim"):
    world = SimWorld(load_scenario(dict(scenario)), str(tmp_path / sub))
    world.run()
    return world


def _rows(ledger_path) -> list[dict]:
    with open(ledger_path) as f:
        return [json.loads(line) for line in f if line.strip()]


def _kinds(rows, job, prefix="sched_") -> list[str]:
    return [r["event"] for r in rows
            if r.get("job") == job
            and str(r.get("event", "")).startswith(prefix)]


def _evict_rows(rows, job) -> list[dict]:
    return [r for r in rows if r.get("event") == "sched_evict"
            and r.get("job") == job]


# ---- the scenario DSL refuses quietly-wrong input ------------------------

def test_scenario_validation_is_loud():
    base = {"name": "x", "jobs": [{"job": "a", "steps": 4,
                                   "est_step_time_s": 0.5}]}
    with pytest.raises(ValueError, match="unknown event kind"):
        load_scenario(dict(base, events=[{"at": 1, "kind": "meteor",
                                          "job": "a"}]))
    with pytest.raises(ValueError, match="unknown job"):
        load_scenario(dict(base, events=[{"at": 1, "kind": "host_loss",
                                          "job": "nope"}]))
    with pytest.raises(ValueError, match="outside"):
        load_scenario(dict(base, horizon_s=10,
                           events=[{"at": 99, "kind": "host_loss",
                                    "job": "a"}]))
    with pytest.raises(ValueError, match="needs steps"):
        load_scenario({"name": "x", "jobs": [{"job": "a"}]})
    with pytest.raises(ValueError, match="knee_per_replica"):
        load_scenario(dict(base, serve={"replicas": 2}))


def test_sim_max_virtual_s_ceiling_dies_loudly(tmp_path, monkeypatch):
    """SIM_MAX_VIRTUAL_S: a scenario that cannot quiesce inside the
    ceiling raises instead of spinning the event loop forever."""
    monkeypatch.setenv("SIM_MAX_VIRTUAL_S", "5")
    scenario = {"name": "livelock", "horizon_s": 50, "devices": 2,
                "jobs": [{"job": "a", "ranks": 1, "steps": 1000,
                          "est_step_time_s": 1.0}]}
    world = SimWorld(load_scenario(scenario), str(tmp_path / "lv"))
    assert world.max_virtual_s == 5.0
    with pytest.raises(RuntimeError, match="SIM_MAX_VIRTUAL_S"):
        world.run()


def test_snapshot_loss_reconstructs_then_rolls_back(tmp_path):
    """The snapshot_loss world model mirrors resilience/shardstore.py:
    a single shard loss is absorbed by the ring mirror (R=2 default —
    no progress impact); a SECOND loss on the same job exceeds
    redundancy, rolls progress back to the quorum floor pinned at the
    first loss, and relaunches through the real scheduler's eviction
    path — time is lost, steps are re-earned, steps_lost stays 0."""
    scenario = {
        "name": "snaploss", "seed": 5, "tick_s": 0.25, "horizon_s": 300,
        "devices": 2,
        "jobs": [{"job": "t", "kind": "train", "ranks": 2, "steps": 30,
                  "est_step_time_s": 0.5, "retries": 3}],
        "events": [
            {"at": 4.0, "kind": "snapshot_loss", "job": "t", "rank": 0},
            {"at": 8.0, "kind": "snapshot_loss", "job": "t", "rank": 1},
        ],
    }
    world = _world(tmp_path, scenario, "snap")
    assert world.summary["summary"]["jobs"] == {"t": "done"}
    assert world.summary["snapshots"] == {
        "losses": 2, "reconstructs": 1, "rollbacks": 1}
    assert world.hub.steps_lost() == 0.0
    # Scenarios without a scripted snapshot_loss keep their exact
    # summary shape (no "snapshots" key) — pinned by every other test's
    # summary assertions staying unchanged.


# ---- bitwise determinism through a storm ---------------------------------

def _storm_scenario() -> dict:
    """A small storm touching every decision family at once: elastic
    shrink + grow (host_loss/recover), anomaly heal eviction
    (straggler + a queued beneficiary), SLO preemption (late serve
    job), and the autoscale loop (serve_load steps)."""
    return {
        "name": "storm", "seed": 3, "tick_s": 0.25, "horizon_s": 400,
        "devices": 4,
        "jobs": [
            {"job": "t1", "kind": "train", "ranks": 2, "steps": 60,
             "est_step_time_s": 0.5, "retries": 3, "elastic": True},
            {"job": "t2", "kind": "bench", "ranks": 2, "steps": 60,
             "est_step_time_s": 0.5, "retries": 3},
            {"job": "w1", "kind": "train", "ranks": 2, "steps": 6,
             "est_step_time_s": 0.5, "start_after_s": 6.0},
            {"job": "s1", "kind": "serve", "ranks": 2, "steps": 6,
             "est_step_time_s": 0.5, "start_after_s": 8.0},
        ],
        "serve": {"replicas": 1, "knee_per_replica": 100.0,
                  "max_replicas": 4, "poll_s": 5.0, "flap_n": 2,
                  "flap_window_s": 60, "cooldown_s": 15, "budget": 8},
        "events": [
            {"at": 5.0, "kind": "host_loss", "job": "t1", "rank": 1},
            {"at": 12.0, "kind": "host_recover", "job": "t1", "rank": 1},
            {"at": 10.0, "kind": "straggler", "job": "t2", "rank": 0},
            {"at": 30.0, "kind": "serve_load", "offered_per_s": 350.0},
            {"at": 60.0, "kind": "serve_load", "offered_per_s": 20.0},
        ],
    }


def _run_bytes(tmp_path, scenario, sub):
    world = _world(tmp_path, scenario, sub)
    with open(world.ledger_path, "rb") as f:
        ledger = f.read()
    wal = os.path.join(world.workdir, "sched", "sched.jsonl")
    with open(wal, "rb") as f:
        return world, ledger, f.read()


def test_same_seed_is_bitwise_identical(tmp_path):
    scenario = _storm_scenario()
    w1, ledger1, wal1 = _run_bytes(tmp_path, scenario, "r1")
    w2, ledger2, wal2 = _run_bytes(tmp_path, scenario, "r2")
    assert ledger1 and wal1                     # the storm wrote rows
    assert ledger1 == ledger2                   # ledger: bitwise
    assert wal1 == wal2                         # WAL: bitwise
    assert w1.summary == w2.summary
    assert w1.hub.steps_lost() == 0.0           # resume forgot nothing
    # the distilled record is pure function of those bytes
    rows1 = sim_metrics.distill(w1, prefix="sim_storm")
    rows2 = sim_metrics.distill(w2, prefix="sim_storm")
    assert rows1 == rows2
    by_name = {r["metric"]: r["value"] for r in rows1}
    assert by_name["sim_storm_fleet_steps_lost"] == 0.0
    assert by_name["sim_storm_wal_unbalanced_violations"] == 0
    assert by_name["sim_storm_evictions"] >= 1
    assert by_name["sim_storm_jobs_done"] == 4


# ---- identity + the self-healed timeline, rendered like live -------------

def test_sim_runs_the_real_control_plane_and_why_reads_like_live(
        tmp_path):
    """A straggler named mid-run with a queued beneficiary: the REAL
    remediation engine detects, flap-guards, then evicts through the
    REAL scheduler WAL; the relaunch sheds the straggle and completes.
    `obs_query why` renders the same self-healed timeline the live
    straggler test asserts — same strings, same ledger grammar."""
    scenario = {
        "name": "heal", "seed": 0, "tick_s": 0.25, "horizon_s": 400,
        "devices": 2,
        "jobs": [
            {"job": "bench1", "kind": "bench", "ranks": 2, "steps": 60,
             "est_step_time_s": 0.5, "retries": 2},
            {"job": "train1", "kind": "train", "ranks": 2, "steps": 4,
             "est_step_time_s": 0.5, "priority": 20,
             "start_after_s": 6.0},
        ],
        "events": [{"at": 8.0, "kind": "straggler", "job": "bench1",
                    "rank": 1}],
    }
    world = _world(tmp_path, scenario)
    # identity: the sim did not subclass or reimplement the control
    # plane — the decisions came from the same code a live run executes
    assert type(world.scheduler) is Scheduler
    assert type(world.scheduler._remediator) is Remediator
    assert world.scheduler.fleet_factory is not None
    summary = world.summary["summary"]
    assert summary["jobs"] == {"bench1": "done", "train1": "done"}
    rows = _rows(world.ledger_path)
    evict = _evict_rows(rows, "bench1")
    assert len(evict) == 1 and evict[0]["for_job"] == "train1"
    assert "straggler" in evict[0]["why"]
    assert evict[0]["clean"] is True and evict[0]["rcs"] == {"0": 143,
                                                             "1": 143}
    heal_kinds = _kinds(rows, "bench1", prefix="heal_")
    assert "heal_detect" in heal_kinds and "heal_evict" in heal_kinds
    he = next(r for r in rows if r.get("event") == "heal_evict")
    assert he["detail"]["for_job"] == "train1"
    assert world.hub.steps_lost() == 0.0
    # the resumed placement starts at the snapshotted step
    places = [r for r in rows if r.get("event") == "sched_place"
              and r.get("job") == "bench1"]
    assert [p["resumed"] for p in places] == [False, True]
    # obs_query why: the same renderer, the same verdict strings the
    # LIVE straggler test asserts (tests/test_scheduler.py)
    obs_query = _tool("obs_query")
    buf = io.StringIO()
    with redirect_stdout(buf):
        assert obs_query.main(["why", "bench1", "--ledger",
                               world.ledger_path]) == 0
    out = buf.getvalue()
    assert "anomaly detected: straggler" in out
    assert "HEALED by eviction" in out
    assert "self-healed 1x (evict)" in out
    assert "finally completed" in out


# ---- fidelity: the same queue, live children vs simulated gangs ----------

def test_live_and_sim_make_the_same_decisions(tmp_path):
    """One tiny queue, run twice: LIVE (real FleetSupervisor, stdlib
    children, wall clock) and SIMULATED (SimGang, virtual clock).  The
    per-job sched_* decision sequences in the two ledgers must be
    identical — same admission, same eviction (same for_job, same
    clean-143 teardown), same resume, same completion."""
    py = sys.executable
    prog = str(tmp_path / "progress")
    victim = tmp_path / "victim.py"
    victim.write_text(textwrap.dedent("""
        import os, signal, sys, time
        prog = os.environ["PROG"]
        signal.signal(signal.SIGTERM, lambda s, f: sys.exit(143))
        while True:
            n = sum(1 for _ in open(prog)) if os.path.exists(prog) else 0
            if n >= 10:
                sys.exit(0)
            with open(prog, "a") as f:
                f.write(f"i{n}\\n")
            time.sleep(0.15)
    """))
    live_jobs = [
        Job(job="a", argv=[py, str(victim)], kind="bench",
            env={"PROG": prog}),
        Job(job="b", argv=[py, "-c", "pass"], kind="serve", ranks=2,
            start_after_s=0.6),
    ]
    live = Scheduler(live_jobs, devices=2,
                     workdir=str(tmp_path / "live"),
                     tick_s=0.05, poll_s=0.02, seed=0,
                     retry_policy=RetryPolicy(retries=3,
                                              backoff_base_s=0.05,
                                              backoff_max_s=0.1))
    live_summary = live.run()
    assert live_summary["jobs"] == {"a": "done", "b": "done"}
    live_rows = _rows(str(tmp_path / "live" / "RUNS.jsonl"))

    sim_scenario = {
        "name": "mirror", "seed": 0, "tick_s": 0.25, "horizon_s": 400,
        "devices": 2,
        "jobs": [
            {"job": "a", "kind": "bench", "steps": 40,
             "est_step_time_s": 0.5},
            {"job": "b", "kind": "serve", "ranks": 2, "steps": 4,
             "est_step_time_s": 0.5, "start_after_s": 5.0},
        ],
    }
    world = _world(tmp_path, sim_scenario)
    assert world.summary["summary"]["jobs"] == {"a": "done",
                                                "b": "done"}
    sim_rows = _rows(world.ledger_path)

    # the decision sequences are identical, job by job
    for job in ("a", "b"):
        assert _kinds(live_rows, job) == _kinds(sim_rows, job), job
    # and the evictions agree on every field policy decided
    ev_live, = _evict_rows(live_rows, "a")
    ev_sim, = _evict_rows(sim_rows, "a")
    for field in ("for_job", "clean", "rcs"):
        assert ev_live[field] == ev_sim[field], field
    for rows in (live_rows, sim_rows):
        places = [r for r in rows if r.get("event") == "sched_place"
                  and r.get("job") == "a"]
        assert [p["resumed"] for p in places] == [False, True]
    # the live victim's progress tape stayed exact (the sim's analogue
    # is steps_lost == 0)
    assert open(prog).read().split() == [f"i{i}" for i in range(10)]
    assert world.hub.steps_lost() == 0.0


# ---- multi-slice packing, refusal, and priced cross-slice eviction -------

def test_multi_slice_packing_refusal_and_priced_eviction(tmp_path):
    """Two 4-device slices: gangs pack best-fit onto slices (a gang
    holds ONE slice), a job wider than the widest slice is REFUSED
    with the slice table in the row, and the late serve job's eviction
    plan prices the victim's snapshot migration with the fitted
    collective model (price_s in the sched_evict row)."""
    scenario = {
        "name": "slices", "seed": 0, "tick_s": 0.25, "horizon_s": 600,
        "slices": {"podA": 4, "podB": 4},
        "collective_fit": {"alpha_s": 0.00035273878968362894,
                           "beta_bytes_per_s": 692186226.9354594},
        "jobs": [
            {"job": "t1", "kind": "train", "ranks": 4, "steps": 60,
             "est_step_time_s": 0.5, "state_bytes": 1 << 26,
             "retries": 2},
            {"job": "t2", "kind": "train", "ranks": 4, "steps": 60,
             "est_step_time_s": 0.5, "state_bytes": 1 << 26,
             "retries": 2},
            {"job": "wide", "kind": "train", "ranks": 6, "steps": 4,
             "est_step_time_s": 0.5},
            {"job": "s1", "kind": "serve", "ranks": 4, "steps": 4,
             "est_step_time_s": 0.5, "start_after_s": 6.0},
        ],
    }
    world = _world(tmp_path, scenario)
    summary = world.summary["summary"]
    assert summary["jobs"]["wide"] == "refused"
    assert sorted(v for k, v in summary["jobs"].items()
                  if k != "wide") == ["done", "done", "done"]
    rows = _rows(world.ledger_path)
    # refusal: wider than the widest slice, and the row says so
    refuse, = [r for r in rows if r.get("event") == "sched_refuse"]
    assert refuse["job"] == "wide"
    assert "widest slice has 4" in refuse["why"]
    assert refuse["slices"] == {"podA": 4, "podB": 4}
    # packing: both slices held, every placement names its slice
    places = [r for r in rows if r.get("event") == "sched_place"]
    assert all(p.get("slice") in ("podA", "podB") for p in places)
    assert {p["slice"] for p in places} == {"podA", "podB"}
    # the serve job preempted one trainer; the eviction is priced by
    # the fitted collective model (the victim's state may move slices)
    evicts = [r for r in rows if r.get("event") == "sched_evict"]
    assert len(evicts) == 1 and evicts[0]["for_job"] == "s1"
    assert evicts[0]["slice"] in ("podA", "podB")
    assert evicts[0]["price_s"] > 0.0
    assert world.hub.steps_lost() == 0.0


# ---- the autoscale policy against the measured knee ----------------------

def test_autoscale_spike_scales_up_refuses_past_max_then_scales_down(
        tmp_path):
    """The serve remediation policy end-to-end on virtual time: a
    traffic spike scales replicas up (heal_scale_up rows in the SAME
    ledger), a spike past max_replicas is REFUSED as a noop (the
    guardrail row says the ceiling bound), and sustained underload
    flap-filters before scaling down."""
    knee = 100.0
    scenario = {
        "name": "spike", "seed": 0, "tick_s": 0.25, "horizon_s": 420,
        "devices": 2,
        "jobs": [{"job": "anchor", "kind": "serve", "ranks": 2,
                  "steps": 800, "est_step_time_s": 0.5}],
        "serve": {"replicas": 1, "knee_per_replica": knee,
                  "min_replicas": 1, "max_replicas": 3, "poll_s": 5.0,
                  "flap_n": 2, "flap_window_s": 120, "cooldown_s": 20,
                  "budget": 10},
        "events": [
            {"at": 30.0, "kind": "serve_load",
             "offered_per_s": 10 * knee},        # past max capacity
            {"at": 240.0, "kind": "serve_load",
             "offered_per_s": 0.1 * knee},       # collapse
        ],
    }
    world = _world(tmp_path, scenario)
    assert type(world.serve_remediator) is Remediator
    serve = world.summary["serve"]
    assert serve["final_replicas"] == 1          # scaled down at the end
    assert serve["breach_s"] > 0.0               # the spike was real
    assert serve["actions_used"] <= 10
    rows = _rows(world.ledger_path)
    ups = [r for r in rows if r.get("event") == "heal_scale_up"]
    downs = [r for r in rows if r.get("event") == "heal_scale_down"]
    assert ups and downs
    sup = [r for r in rows if r.get("event") == "heal_suppressed"]
    reasons = [r.get("reason", "") for r in sup]
    # the ceiling refusal: overload persists at max_replicas and the
    # actuator answers noop instead of scaling into thin air
    assert any("max_replicas" in w for w in reasons)
    # the flap guardrail bound at least once (first detections filter)
    assert any(w.startswith("flap") for w in reasons)
    # determinism holds with the serve loop in play too
    world2 = _world(tmp_path, scenario, "again")
    assert world2.summary["serve"] == serve


# ---- 10,000 ranks inside the tier-1 budget -------------------------------

def test_ten_thousand_ranks_under_a_minute(tmp_path):
    """The battery's host-loss-wave scenario, run once: 24 jobs /
    10,000 ranks over four 2600-device slices, three rolling loss
    waves — the REAL scheduler drives every placement, shrink, and
    grow, and the whole thing quiesces in seconds of wall time."""
    sim_run = _tool("sim_run")
    scenario = sim_run.battery_scenarios()[0]
    assert scenario["name"] == "fleet10k"
    t0 = time.monotonic()
    world = _world(tmp_path, scenario)
    elapsed = time.monotonic() - t0
    assert elapsed < 60.0, f"10k-rank sim took {elapsed:.1f}s wall"
    assert world.summary["total_ranks"] == 10_000
    assert type(world.scheduler) is Scheduler
    summary = world.summary["summary"]
    assert summary["counts"]["done"] == 24
    assert summary["shrinks"] >= 1               # the loss waves landed
    assert world.hub.steps_lost() == 0.0
    rows = _rows(world.ledger_path)
    assert {r.get("slice") for r in rows
            if r.get("event") == "sched_place"} == {
                "podA", "podB", "podC", "podD"}
    assert sim_metrics.wal_unbalanced(
        world.scheduler.journal.events()) == 0


# ---- the full battery + record kit (slow) --------------------------------

@pytest.mark.slow
def test_battery_record_and_determinism_gate(tmp_path):
    """tools/sim_run.py --battery: all four storms, each run twice for
    the same-seed byte comparison; rc 0 means every must-be-zero
    invariant (determinism, steps_lost, WAL balance) held."""
    sim_run = _tool("sim_run")
    out = str(tmp_path / "SIM_fleet_cpu_r18.json")
    rc = sim_run.main(["--battery", "--workdir",
                       str(tmp_path / "battery"), "--out", out])
    assert rc == 0
    recs = [json.loads(line) for line in open(out)]
    by_name = {r["metric"]: r["value"] for r in recs}
    for name in ("fleet10k", "epidemic10k", "servespike", "cascade10k"):
        assert by_name[f"sim_{name}_determinism_violations"] == 0
        assert by_name[f"sim_{name}_fleet_steps_lost"] == 0.0
        assert by_name[f"sim_{name}_wal_unbalanced_violations"] == 0
    assert by_name["sim_epidemic10k_evictions"] >= 1
    assert by_name["sim_servespike_autoscale_actions"] >= 2


# ---- the record family rides the ratchet ---------------------------------

def test_bench_ratchet_recognizes_sim_family(tmp_path):
    """SIM_* records load, their *_violations metrics are must-be-zero
    (a nonzero value fails the zero-invariant check), and the
    trajectory builder folds the family in."""
    bench_ratchet = _tool("bench_ratchet")
    rec = tmp_path / "SIM_fleet_cpu_r18.json"
    rows = [
        {"metric": "sim_fleet10k_ranks", "value": 10000,
         "unit": "ranks", "platform": "cpu", "detail": None},
        {"metric": "sim_fleet10k_determinism_violations", "value": 0,
         "unit": "runs", "platform": "cpu", "detail": None},
    ]
    rec.write_text("".join(json.dumps(r, sort_keys=True) + "\n"
                           for r in rows))
    recs = bench_ratchet.load_records([str(rec)])
    assert {r["metric"] for r in recs} == {
        "sim_fleet10k_ranks", "sim_fleet10k_determinism_violations"}
    assert bench_ratchet.check_zero_invariants(recs) == []
    recs[1]["value"] = 1
    bad = bench_ratchet.check_zero_invariants(recs)
    assert bad and "determinism_violations" in bad[0]["metric"]
    assert bad[0]["severity"] == "regression"
    traj = bench_ratchet.build_trajectory(str(tmp_path))
    fam = [r for r in traj if r["family"] == "SIM_fleet_cpu"]
    assert len(fam) == 1 and fam[0]["round"] == 18
    assert fam[0]["metrics"]["sim_fleet10k_ranks"] == 10000
