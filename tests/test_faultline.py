"""tools/faultline.py — the CLI that makes every injected-fault scenario
reproducible (satellite: the tier-1-safe smoke invocation), plus the
acceptance-criterion end-to-end: a run preempted at step k and RESUMED
VIA THE SUPERVISOR produces a bitwise-identical state digest to an
uninterrupted run of the same total steps, on CPU, no TPU required.

Inline on purpose (single CPU device, no collectives).  The in-process
smokes share the pytest process's jit cache; only the supervisor test
pays subprocess jax imports, because the supervisor IS a subprocess
runner — that's the thing under test.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
import faultline  # noqa: E402
sys.path.pop(0)

pytestmark = pytest.mark.faults


def _run_inproc(capsys, *args) -> tuple[int, dict]:
    rc = faultline.main(list(args))
    captured = capsys.readouterr()
    out = [l for l in captured.out.splitlines() if l.strip()]
    rec = json.loads(out[-1])
    rec["_stderr"] = captured.err
    return rc, rec


def test_faultline_smoke_preempt_resume_bitwise(tmp_path, capsys):
    """The CLI smoke the issue asks to wire into the tier-1 set:
    --plan preempt fires SIGTERM mid-run (seed-addressed step), saves,
    exits 143; the second invocation resumes from the snapshot and the
    final digest + loss-tape suffix are bitwise-identical to a straight
    run."""
    wd, wd2 = str(tmp_path / "faulted"), str(tmp_path / "straight")
    rc, first = _run_inproc(capsys, "--plan", "preempt", "--steps", "6",
                            "--workdir", wd, "--seed", "0")
    assert rc == 143 and first["status"] == "preempted"
    k = first["step"]
    assert 1 <= k < 6          # mid-run, never the final step

    os.environ["SUPERVISE_ATTEMPT"] = "1"   # transient: fault spent
    try:
        rc, resumed = _run_inproc(capsys, "--plan", "preempt", "--steps",
                                  "6", "--workdir", wd, "--seed", "0")
    finally:
        del os.environ["SUPERVISE_ATTEMPT"]
    assert rc == 0 and resumed["status"] == "ok"
    assert resumed["start_step"] == k and resumed["step"] == 6

    rc, straight = _run_inproc(capsys, "--plan", "none", "--steps", "6",
                               "--workdir", wd2, "--seed", "0")
    assert rc == 0
    # bitwise: the digest covers every leaf of params/opt_state/rng/step
    assert resumed["digest"] == straight["digest"]
    # metric trajectory: the resumed tape is exactly the straight tape's
    # suffix past the preemption step
    assert first["losses"] == straight["losses"][:k]
    assert resumed["losses"] == straight["losses"][k:]


def test_faultline_torn_snapshot_falls_back_and_still_converges(tmp_path,
                                                                capsys):
    """torn_snapshot = final write torn mid-file + preemption: the
    resume discards the torn newest snapshot, falls back to the
    previous manifest-valid one, REDOES the lost step, and still lands
    bitwise-identical to the straight run."""
    wd = str(tmp_path / "torn")
    rc, first = _run_inproc(capsys, "--plan", "torn_snapshot", "--steps",
                            "6", "--workdir", wd, "--seed", "0")
    assert rc == 143
    k = first["step"]

    os.environ["SUPERVISE_ATTEMPT"] = "1"
    try:
        rc, resumed = _run_inproc(capsys, "--plan", "torn_snapshot",
                                  "--steps", "6", "--workdir", wd,
                                  "--seed", "0")
    finally:
        del os.environ["SUPERVISE_ATTEMPT"]
    assert rc == 0
    assert resumed["start_step"] == k - 1      # fell back one snapshot
    assert f"discarding snapshot {k}" in resumed["_stderr"]

    rc, straight = _run_inproc(capsys, "--plan", "none", "--steps", "6",
                               "--workdir", str(tmp_path / "s"), "--seed",
                               "0")
    assert resumed["digest"] == straight["digest"]


def test_faultline_nan_fault_exits_nonzero_keeps_healthy_snapshot(
        tmp_path, capsys):
    wd = str(tmp_path / "nan")
    rc, rec = _run_inproc(capsys, "--plan", "nan_loss@2", "--steps", "4",
                          "--workdir", wd, "--seed", "0")
    assert rc == 1 and rec["status"] == "fault"
    # resume starts from the last HEALTHY step (1), not the poisoned 2
    os.environ["SUPERVISE_ATTEMPT"] = "1"
    try:
        rc, resumed = _run_inproc(capsys, "--plan", "nan_loss@2",
                                  "--steps", "4", "--workdir", wd,
                                  "--seed", "0")
    finally:
        del os.environ["SUPERVISE_ATTEMPT"]
    assert rc == 0 and resumed["start_step"] == 1 and resumed["step"] == 4


def test_acceptance_supervised_resume_is_bitwise_identical(tmp_path,
                                                           capsys):
    """ACCEPTANCE: preempt at step k, restart + resume handled entirely
    by the supervisor (tools/supervise.py machinery), final state
    bitwise-identical to an uninterrupted run.  The supervised half runs
    as real subprocesses — that is the supervisor's actual mode."""
    from distributedtensorflowexample_tpu.resilience import (
        RetryPolicy, Supervisor)

    wd = str(tmp_path / "sup")
    out = str(tmp_path / "out.json")
    sup = Supervisor(policy=RetryPolicy(retries=2, backoff_base_s=0.01),
                     seed=0)
    res = sup.run(
        [sys.executable, os.path.join(REPO, "tools", "faultline.py"),
         "--plan", "preempt", "--steps", "6", "--workdir", wd,
         "--seed", "0"],
        name="faultline", stdout_path=out)
    assert res.status == "ok" and res.attempts == 2    # 143 then 0
    final = json.loads(open(out).read().strip().splitlines()[-1])
    assert final["status"] == "ok" and final["step"] == 6
    assert final["start_step"] >= 1                    # genuinely resumed

    rc, straight = _run_inproc(capsys, "--plan", "none", "--steps", "6",
                               "--workdir", str(tmp_path / "straight"),
                               "--seed", "0")
    assert rc == 0
    assert final["digest"] == straight["digest"]


def test_faultline_cli_help_runs():
    """The smoke entry exists as a CLI: --help must not import jax (it
    parses first), so this is cheap."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "faultline.py"),
         "--help"], capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0 and "--plan" in proc.stdout
