"""Model output shapes + param counts (SURVEY.md C8/C9/C9')."""

import jax
import jax.numpy as jnp

from distributedtensorflowexample_tpu.models import (
    MnistCNN, ResNet20, SoftmaxRegression, build_model)


def _init_and_apply(model, shape, train=False):
    rng = jax.random.PRNGKey(0)
    x = jnp.zeros(shape, jnp.float32)
    variables = model.init({"params": rng, "dropout": rng}, x, train=train)
    if train and "batch_stats" in variables:
        out, _ = model.apply(variables, x, train=True,
                             rngs={"dropout": rng}, mutable=["batch_stats"])
        return variables, out
    out = model.apply(variables, x, train=train, rngs={"dropout": rng})
    return variables, out


def test_softmax_shapes():
    _, out = _init_and_apply(SoftmaxRegression(), (4, 28, 28, 1))
    assert out.shape == (4, 10)


def test_softmax_param_count():
    variables, _ = _init_and_apply(SoftmaxRegression(), (1, 28, 28, 1))
    n = sum(x.size for x in jax.tree.leaves(variables["params"]))
    assert n == 784 * 10 + 10


def test_mnist_cnn_shapes_and_dtype():
    _, out = _init_and_apply(MnistCNN(), (4, 28, 28, 1), train=True)
    assert out.shape == (4, 10)
    assert out.dtype == jnp.float32  # logits upcast for a stable loss


def test_resnet20_shapes():
    _, out = _init_and_apply(ResNet20(), (2, 32, 32, 3))
    assert out.shape == (2, 10)


def test_resnet20_has_bn_stats_and_plausible_size():
    variables, _ = _init_and_apply(ResNet20(), (1, 32, 32, 3))
    assert "batch_stats" in variables
    n = sum(x.size for x in jax.tree.leaves(variables["params"]))
    assert 0.25e6 < n < 0.31e6  # ResNet-20 is ~0.27M params


def test_registry():
    assert isinstance(build_model("softmax"), SoftmaxRegression)
    assert isinstance(build_model("mnist_cnn"), MnistCNN)
    assert build_model("mnist_cnn", dropout=0.3).dropout_rate == 0.3
