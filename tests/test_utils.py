"""utils/: profiling trace capture, device-honest timing, chief logging."""

import glob
import os

import jax
import jax.numpy as jnp

from distributedtensorflowexample_tpu.utils import (
    ProfilerHook, RateMeter, Timer, chief_print, timed_block, trace_context)


class _FakeTime:
    """Settable clock standing in for the metrics module's ``time``."""

    def __init__(self):
        self.now = 0.0

    def perf_counter(self):
        return self.now


def test_metrics_logger_excludes_hook_time(monkeypatch):
    """steps_per_sec is a TRAINING rate: hook wall time reported via
    exclude() must not depress the next window, and over-discounting must
    skip the rate rather than emit a bogus one (deterministic fake clock)."""
    from distributedtensorflowexample_tpu.training import metrics as m

    clock = _FakeTime()
    monkeypatch.setattr(m, "time", clock)
    logger = m.MetricsLogger(log_every=100)
    logger.start(0)

    clock.now = 10.0                       # 100 steps in 10s of training
    logger.maybe_log(100, {"loss": jnp.asarray(1.0)})
    assert logger.last_steps_per_sec == 10.0

    logger.exclude(5.0)                    # a 5s eval/checkpoint hook
    clock.now = 25.0                       # +10s training, +5s hook
    logger.maybe_log(200, {"loss": jnp.asarray(1.0)})
    assert logger.last_steps_per_sec == 10.0   # hook time discounted

    logger.exclude(100.0)                  # hook outlived the window
    clock.now = 30.0
    logger.maybe_log(300, {"loss": jnp.asarray(1.0)})
    assert logger.last_steps_per_sec == 10.0   # bogus rate skipped


def test_trace_context_writes_xplane(tmp_path):
    logdir = str(tmp_path / "trace")
    with trace_context(logdir):
        jax.block_until_ready(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    assert glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                     recursive=True), "no xplane trace written"


def test_profiler_hook_window(tmp_path):
    logdir = str(tmp_path / "hooktrace")
    hook = ProfilerHook(logdir, start_step=2, num_steps=2)
    m = jnp.zeros(())
    for step in range(1, 6):
        hook.after_step(step, None, m)
    hook.end(None)
    assert glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                     recursive=True)


def test_profiler_hook_slides_window_on_resume(tmp_path):
    """A run resuming past the configured window still captures a trace."""
    logdir = str(tmp_path / "resumed")
    hook = ProfilerHook(logdir, start_step=2, num_steps=2)
    m = jnp.zeros(())
    for step in range(50, 56):  # checkpoint resume landed at step 50
        hook.after_step(step, None, m)
    hook.end(None)
    assert glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                     recursive=True)


def test_profiler_hook_is_one_shot(tmp_path, monkeypatch):
    """After the window completes, tracing must never re-arm."""
    import distributedtensorflowexample_tpu.utils.profiling as prof
    starts = []
    monkeypatch.setattr(prof.jax.profiler, "start_trace",
                        lambda d: starts.append(d))
    monkeypatch.setattr(prof.jax.profiler, "stop_trace", lambda: None)
    hook = ProfilerHook(str(tmp_path), start_step=2, num_steps=2)
    m = jnp.zeros(())
    for step in range(1, 30):
        hook.after_step(step, None, m)
    hook.end(None)
    assert len(starts) == 1


def test_profiler_hook_stops_on_early_end(tmp_path):
    logdir = str(tmp_path / "early")
    hook = ProfilerHook(logdir, start_step=1, num_steps=100)
    hook.after_step(1, None, jnp.zeros(()))
    hook.end(None)  # loop stopped inside window; must not leak active trace
    assert glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                     recursive=True)


def test_timer_measures_and_counts():
    t = Timer()
    for _ in range(3):
        with t.measure() as out:
            out["result"] = jnp.ones((16, 16)) @ jnp.ones((16, 16))
    assert t.count == 3
    assert t.total > 0
    assert abs(t.mean - t.total / 3) < 1e-12


def test_timed_block_sink():
    sink = []
    with timed_block("x", sink=sink) as out:
        out["result"] = jnp.ones((4,)) * 2
    assert len(sink) == 1 and sink[0][0] == "x" and sink[0][1] > 0


def test_rate_meter():
    m = RateMeter(window=4)
    assert m.rate == 0.0
    for _ in range(5):
        m.tick()
    assert m.rate > 0


def test_chief_print(capsys):
    chief_print("hello-chief")
    assert "hello-chief" in capsys.readouterr().out


def test_conftest_xla_flags_accepted_by_backend():
    """An UNKNOWN name in XLA_FLAGS fatally aborts the process at first
    backend init, and pytest capture eats the `F... Unknown flag` log —
    the whole suite dies with rc=1 and ZERO output (round-3 incident:
    a plausible-but-wrong flag rename killed every device test silently).
    Pin that the conftest's exact flag string is known to this jaxlib by
    touching a collective in a subprocess."""
    import os
    import subprocess
    import sys

    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu');"
        "from distributedtensorflowexample_tpu.compat import ("
        "    set_num_cpu_devices, shard_map);"
        "set_num_cpu_devices(2);"
        "import numpy as np; import jax.numpy as jnp;"
        "from jax.sharding import Mesh, PartitionSpec as P;"
        "m = Mesh(np.array(jax.devices()), ('d',));"
        "f = shard_map(lambda x: jax.lax.psum(x, 'd'), mesh=m,"
        "              in_specs=P('d'), out_specs=P());"
        "print('FLAGS_OK', float(f(jnp.ones(4))[0]))"
    )
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""   # no axon in the subprocess
    from distributedtensorflowexample_tpu.compat import cpu_collective_flags
    if cpu_collective_flags():
        assert "--xla_cpu_collective_call" in env.get("XLA_FLAGS", ""), \
            "conftest did not install the rendezvous flags"
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=240)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "FLAGS_OK" in r.stdout
    assert "Unknown flag" not in r.stderr
