"""CLI flag surface: reference-compatible names parse (SURVEY.md C6)."""

import dataclasses

from distributedtensorflowexample_tpu.config import (
    _FLAG_HELP, RunConfig, build_parser, parse_flags)
from distributedtensorflowexample_tpu import cluster


def test_defaults():
    cfg = parse_flags([])
    assert cfg.batch_size == 100
    assert cfg.train_steps == 1000
    assert cfg.job_name == ""


def test_reference_cluster_flags_parse():
    cfg = parse_flags([
        "--job_name", "worker", "--task_index", "1",
        "--ps_hosts", "h1:2222,h2:2222",
        "--worker_hosts", "h3:2222,h4:2222",
        "--batch_size", "64", "--train_steps", "500",
        "--learning_rate", "0.01", "--data_dir", "/tmp/d",
        "--log_dir", "/tmp/l",
    ])
    assert cfg.job_name == "worker"
    assert cfg.task_index == 1
    assert cfg.ps_host_list == ["h1:2222", "h2:2222"]
    assert cfg.worker_host_list == ["h3:2222", "h4:2222"]


def test_overrides_win_over_defaults():
    cfg = parse_flags([], batch_size=7)
    assert cfg.batch_size == 7
    cfg = parse_flags(["--batch_size", "9"], batch_size=7)
    assert cfg.batch_size == 9


def test_ps_role_resolution():
    cfg = parse_flags(["--job_name", "ps", "--task_index", "0",
                       "--ps_hosts", "h1:2222", "--worker_hosts", "h2:2222"])
    info = cluster.resolve(cfg)
    assert info.role == "ps"
    assert not info.is_chief


def test_worker_hosts_resolution():
    cfg = parse_flags(["--job_name", "worker", "--task_index", "1",
                       "--worker_hosts", "h1:2222,h2:2222"])
    info = cluster.resolve(cfg)
    assert info.num_processes == 2
    assert info.process_id == 1
    assert not info.is_chief
    assert info.coordinator_address == "h1:2222"


def test_tf_config_resolution(monkeypatch):
    monkeypatch.setenv(
        "TF_CONFIG",
        '{"cluster": {"worker": ["a:1", "b:2"]}, '
        '"task": {"type": "worker", "index": 1}}')
    cfg = parse_flags([])
    info = cluster.resolve(cfg)
    assert info.num_processes == 2
    assert info.process_id == 1
    assert info.coordinator_address == "a:1"


def test_tf_config_ps_task_routes_to_ps_role(monkeypatch):
    monkeypatch.setenv(
        "TF_CONFIG",
        '{"cluster": {"ps": ["p:1"], "worker": ["a:1"]}, '
        '"task": {"type": "ps", "index": 0}}')
    info = cluster.resolve(parse_flags([]))
    assert info.role == "ps"
    assert not info.is_chief


def test_tf_config_chief_job(monkeypatch):
    monkeypatch.setenv(
        "TF_CONFIG",
        '{"cluster": {"chief": ["c:1"], "worker": ["a:1", "b:2"]}, '
        '"task": {"type": "worker", "index": 1}}')
    info = cluster.resolve(parse_flags([]))
    # chief occupies process 0; worker 1 is process 2 of 3.
    assert info.num_processes == 3
    assert info.process_id == 2
    assert info.coordinator_address == "c:1"
    assert not info.is_chief


def test_every_flag_has_help_text():
    """--help must describe every flag, and the text must track behavior:
    the round-2 verdict caught device_data's help still claiming the
    round-1 "auto = sync mode without augmentation" fencing after auto
    became equivalent to on in every mode."""
    field_names = {f.name for f in dataclasses.fields(RunConfig)}
    assert field_names == set(_FLAG_HELP), (
        field_names ^ set(_FLAG_HELP))
    assert "every mode" in _FLAG_HELP["device_data"]
    assert "sync mode without augmentation" not in _FLAG_HELP["device_data"]
    helptext = " ".join(build_parser().format_help().split())
    assert "auto is equivalent to on in every mode" in helptext
    assert "default: auto" in helptext


def test_every_trainer_help_exits_clean(capsys):
    """--help works on all five entrypoints (catches flag-definition and
    import-time breakage in one sweep)."""
    import importlib

    import pytest

    for name in ("trainer_local_mnist", "trainer_ps_mnist",
                 "trainer_sync_mnist", "trainer_mirrored_cifar",
                 "trainer_multiworker_cifar"):
        mod = importlib.import_module(
            f"distributedtensorflowexample_tpu.trainers.{name}")
        with pytest.raises(SystemExit) as exc:
            mod.main(["--help"])
        assert exc.value.code == 0
        assert "--train_steps" in capsys.readouterr().out


def test_quantize_flag_parses_and_validates():
    cfg = parse_flags(["--quantize", "off"])
    assert cfg.quantize == "off"
    assert RunConfig().quantize == "auto"


def test_round5_flag_defaults_and_parsing():
    """Round-5 surface: auto unroll is the shipped default, sharded
    storage is opt-in, and both parse from the CLI."""
    cfg = parse_flags([])
    assert cfg.steps_per_loop == 0          # 0 = auto
    assert cfg.data_sharding == "replicated"
    cfg = parse_flags(["--steps_per_loop", "1",
                       "--data_sharding", "sharded"])
    assert cfg.steps_per_loop == 1
    assert cfg.data_sharding == "sharded"
