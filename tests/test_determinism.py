"""Determinism under SPMD (SURVEY.md §5 race-detection row).

The reference's async-PS mode embraced write races; our sync modes are
deterministic under XLA by design.  These tests pin that down: same seed
⇒ bit-identical parameters across independent runs and across input
paths; different seed ⇒ different trajectory.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributedtensorflowexample_tpu.data import DeviceDataset
from distributedtensorflowexample_tpu.data.synthetic import make_synthetic
from distributedtensorflowexample_tpu.models import build_model
from distributedtensorflowexample_tpu.parallel import (
    batch_sharding, make_mesh, replicated_sharding)
from distributedtensorflowexample_tpu.parallel.sync import (
    make_indexed_train_step, make_train_step)
from distributedtensorflowexample_tpu.training.state import TrainState


def _run(seed: int, steps: int = 10, data_sharding: str = "replicated"):
    """A short sync-DP training run on the mesh, returning final params."""
    mesh = make_mesh()
    x, y = make_synthetic(512, (28, 28, 1), 10, seed=0)
    b = 64
    ds = DeviceDataset(x, y, b, mesh=mesh, seed=seed,
                       data_sharding=data_sharding)
    state = TrainState.create_sharded(
        build_model("mnist_cnn", dropout=0.5), optax.sgd(0.05, momentum=0.9),
        (b, 28, 28, 1), seed, replicated_sharding(mesh))
    step = make_indexed_train_step(b, ds.steps_per_epoch, mesh=mesh,
                                   num_slots=ds.num_slots,
                                   data_sharding=data_sharding)
    with mesh:
        for _ in range(steps):
            state, m = step(state, next(ds))
        jax.block_until_ready(m)
    return state.params


def test_same_seed_bitwise_identical():
    p1, p2 = _run(seed=3), _run(seed=3)
    jax.tree.map(lambda a, c: np.testing.assert_array_equal(a, c), p1, p2)


def test_same_seed_bitwise_identical_sharded_storage():
    """The determinism contract holds for the sharded-resident layout
    too: same seed ⇒ bit-identical params across independent runs."""
    p1 = _run(seed=3, data_sharding="sharded")
    p2 = _run(seed=3, data_sharding="sharded")
    jax.tree.map(lambda a, c: np.testing.assert_array_equal(a, c), p1, p2)


def test_different_seed_diverges():
    p1, p2 = _run(seed=3), _run(seed=4)
    diffs = jax.tree.leaves(
        jax.tree.map(lambda a, c: float(jnp.max(jnp.abs(a - c))), p1, p2))
    assert max(diffs) > 0.0


def test_replicas_agree_after_training():
    """Every device's copy of every replicated parameter is identical after
    sharded training — the sync-SGD invariant the reference enforced with
    its PS barrier, enforced here by construction and verified directly."""
    mesh = make_mesh()
    x, y = make_synthetic(256, (28, 28, 1), 10, seed=0)
    batch = jax.device_put({"image": x[:64], "label": y[:64]},
                           batch_sharding(mesh))
    state = TrainState.create_sharded(
        build_model("softmax"), optax.sgd(0.5), (64, 28, 28, 1), 0,
        replicated_sharding(mesh))
    step = make_train_step(mesh=mesh)
    with mesh:
        for _ in range(5):
            state, m = step(state, batch)
    for leaf in jax.tree.leaves(state.params):
        shards = [np.asarray(s.data) for s in leaf.addressable_shards]
        for s in shards[1:]:
            np.testing.assert_array_equal(shards[0], s)
