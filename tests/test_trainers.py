"""End-to-end trainer runs (tiny) — the reference's run-to-verify checks
as real tests (SURVEY.md §4 convergence smoke tests)."""

import jax
import numpy as np
import pytest

from distributedtensorflowexample_tpu.trainers import (
    trainer_local_mnist, trainer_mirrored_cifar, trainer_ps_mnist,
    trainer_sync_mnist)


def _common_flags(tmp_log_dir, extra=()):
    return ["--log_dir", tmp_log_dir, "--data_dir", "/nonexistent",
            "--dataset", "synthetic",   # explicit opt-in: no real bytes here
            "--resume", "false", "--log_every", "20", *extra]


def test_local_softmax_converges(tmp_log_dir):
    summary = trainer_local_mnist.main(_common_flags(
        tmp_log_dir, ["--train_steps", "150", "--batch_size", "64"]))
    assert summary["final_accuracy"] > 0.9
    assert summary["steps"] == 150


def test_sync_cnn_smoke(tmp_log_dir):
    summary = trainer_sync_mnist.main(_common_flags(
        tmp_log_dir, ["--train_steps", "30", "--batch_size", "16",
                      "--learning_rate", "0.02"]))
    assert summary["steps"] == 30
    assert summary["num_replicas"] == jax.device_count()
    assert np.isfinite(summary["final_accuracy"])


def test_eval_every_writes_scalars(tmp_log_dir, small_synthetic):
    """--eval_every wires the EvalHook: periodic eval_accuracy scalars in
    scalars.jsonl at the boundary-crossing steps."""
    import json
    import os

    trainer_local_mnist.main(_common_flags(
        tmp_log_dir, ["--train_steps", "40", "--batch_size", "32",
                      "--eval_every", "20"]))
    with open(os.path.join(tmp_log_dir, "scalars.jsonl")) as f:
        scalars = [json.loads(l) for l in f]
    evals = [s for s in scalars if "eval_accuracy" in s]
    assert [s["step"] for s in evals] == [20, 40]
    assert all(0.0 <= s["eval_accuracy"] <= 1.0 for s in evals)


def test_missing_real_data_is_a_crisp_error(tmp_log_dir):
    """Without --dataset synthetic, an empty --data_dir must fail by name
    (VERDICT r4 #5) — never silently train on substituted data."""
    with pytest.raises(FileNotFoundError, match="--dataset synthetic"):
        trainer_local_mnist.main(
            ["--log_dir", tmp_log_dir, "--data_dir", "/nonexistent",
             "--resume", "false", "--train_steps", "1"])


def test_dataset_trainer_mismatch_is_an_error(tmp_log_dir):
    """--dataset cifar10 on an MNIST trainer is a config error, caught
    before any data is read."""
    with pytest.raises(ValueError, match="does not match"):
        trainer_local_mnist.main(
            ["--log_dir", tmp_log_dir, "--dataset", "cifar10",
             "--resume", "false", "--train_steps", "1"])


def test_ps_role_exits_with_notice(tmp_log_dir, capsys):
    summary = trainer_ps_mnist.main(
        ["--job_name", "ps", "--task_index", "0",
         "--ps_hosts", "h:1", "--worker_hosts", "h:2"])
    assert summary["exited"]
    assert "exit" in capsys.readouterr().out.lower()


def test_mirrored_resnet_smoke(tmp_log_dir, small_synthetic):
    summary = trainer_mirrored_cifar.main(_common_flags(
        tmp_log_dir, ["--train_steps", "10", "--batch_size", "8",
                      "--warmup_steps", "2"]))
    assert summary["steps"] == 10
    assert np.isfinite(summary["final_accuracy"])


def test_sigterm_preemption_saves_and_resumes(tmp_path):
    """TPU preemption parity (SURVEY §5 failure recovery): the platform
    sends SIGTERM before reclaiming a slice — the trainer must write a
    final checkpoint, exit 143, and auto-resume on restart.  Subprocess
    test: signal handlers need the trainee's own main thread."""
    import os
    import re
    import subprocess
    import sys

    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""   # CPU backend in the child
    env["JAX_PLATFORMS"] = "cpu"
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    args = [sys.executable, "-u", "-m",
            "distributedtensorflowexample_tpu.trainers.trainer_sync_mnist",
            "--batch_size", "32", "--dataset", "synthetic",
            "--steps_per_loop", "1", "--log_every", "5",
            "--log_dir", str(tmp_path), "--learning_rate", "0.01"]
    import threading

    p = subprocess.Popen(args + ["--train_steps", "100000"], env=env,
                         cwd=root, stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT, text=True)
    saw = []
    got_step = threading.Event()

    def drain():
        # Deadline-safe: a blocking for-line read on the main thread
        # could hang the whole session if the child wedges pre-output.
        for line in p.stdout:
            saw.append(line)
            if line.startswith("step ") and "loss" in line:
                got_step.set()
        got_step.set()                 # EOF: unblock the waiter either way

    t = threading.Thread(target=drain, daemon=True)
    t.start()
    try:
        assert got_step.wait(timeout=300), "no output within deadline"
        assert p.poll() is None, (
            "trainer exited early:\n" + "".join(saw)[-2000:])
        p.terminate()                  # the platform's preemption signal
        p.wait(timeout=240)
    finally:
        if p.poll() is None:
            p.kill()
            p.wait()
        t.join(timeout=30)
    full = "".join(saw)
    assert p.returncode == 143, (p.returncode, full[-2000:])
    m = re.search(r"SIGTERM at step (\d+): checkpoint saved", full)
    assert m, full[-2000:]
    saved = int(m.group(1))
    assert saved >= 5

    r = subprocess.run(args + ["--train_steps", str(saved + 10)], env=env,
                       cwd=root, capture_output=True, text=True,
                       timeout=600)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-500:]
    assert f"resumed from checkpoint at step {saved}" in r.stdout, \
        r.stdout[-2000:]
    assert f"step {saved + 10}: final_accuracy" in r.stdout


def test_multiworker_trainer_single_process(tmp_log_dir, small_synthetic):
    """Config 5 entrypoint degenerates correctly to one process (the same
    SPMD program; the mesh simply spans one host's devices)."""
    from distributedtensorflowexample_tpu.trainers import (
        trainer_multiworker_cifar)

    summary = trainer_multiworker_cifar.main(_common_flags(
        tmp_log_dir, ["--train_steps", "6", "--batch_size", "8",
                      "--num_processes", "1", "--warmup_steps", "2",
                      "--log_every", "3"]))
    assert summary["steps"] == 6
    assert summary["num_replicas"] == jax.device_count()
    assert np.isfinite(summary["final_accuracy"])
