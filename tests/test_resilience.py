"""resilience/ — fault injection, crash-consistent snapshots, supervised
recovery (ISSUE 3 tentpole).

The contract under test is the repo's parity discipline applied to
failure: a run interrupted by any injected fault and resumed from a
snapshot must be BITWISE identical — params, optimizer state, and the
step-by-step metric trajectory — to an uninterrupted run of the same
total steps, on CPU, with the torn-write and poisoned-state edges
refusing to restore rather than silently diverging.

These tests are deliberately INLINE (not in tests/isolation_list.py):
single-device, no collectives, and the resume-parity gate must land
ahead of the isolated wrappers inside the tier-1 budget.
"""

import json
import os
import signal
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributedtensorflowexample_tpu.data.synthetic import make_synthetic
from distributedtensorflowexample_tpu.models import build_model
from distributedtensorflowexample_tpu.parallel.sync import make_train_step
from distributedtensorflowexample_tpu.resilience import (
    FaultInjectionHook, FaultPlan, FaultSpec, FaultyBatches, MetricsTapeHook,
    NaNGuardHook, RetryPolicy, SnapshotHook, SnapshotStore, Supervisor, Task,
    TaskQueue)
from distributedtensorflowexample_tpu.resilience.supervisor import Journal
from distributedtensorflowexample_tpu.training.hooks import HeartbeatHook
from distributedtensorflowexample_tpu.training.loop import TrainLoop
from distributedtensorflowexample_tpu.utils.signals import sigterm_flag


def _fresh_state(model_name: str = "softmax", seed: int = 0):
    from distributedtensorflowexample_tpu.training.state import TrainState
    return TrainState.create(build_model(model_name),
                             optax.sgd(0.1, momentum=0.9),
                             jnp.zeros((8, 28, 28, 1), jnp.float32),
                             seed=seed)


def _batches(n: int, batch: int = 8):
    x, y = make_synthetic(batch * n, (28, 28, 1), 10, seed=3)
    return [{"image": jnp.asarray(x[i * batch:(i + 1) * batch]),
             "label": jnp.asarray(y[i * batch:(i + 1) * batch])}
            for i in range(n)]


def _trees_equal(a, b) -> bool:
    leaves = zip(jax.tree.leaves(a), jax.tree.leaves(b), strict=True)
    return all(np.array_equal(np.asarray(x), np.asarray(y)) for x, y in leaves)


@pytest.fixture(scope="module")
def sgd_step():
    # One jitted fn for the whole module: the jit cache keys on input
    # structure, so softmax and mnist_cnn states each compile once.
    return make_train_step()


# --- SnapshotStore ---------------------------------------------------------

def test_snapshot_roundtrip_bitwise(tmp_path, sgd_step):
    state = _fresh_state()
    for b in _batches(3):
        state, _ = sgd_step(state, b)
    store = SnapshotStore(str(tmp_path / "snaps"))
    assert store.latest_valid() is None           # empty store
    empty = _fresh_state(seed=5)
    assert store.restore(empty) is empty          # identity on empty dir
    assert store.save(state, cursor={"seed": 0, "step": 3})
    assert not store.save(state)                  # duplicate step no-op
    assert store.steps() == [3]
    restored = store.restore(_fresh_state(seed=99))
    assert int(restored.step) == 3
    assert _trees_equal(restored.params, state.params)
    assert _trees_equal(restored.opt_state, state.opt_state)
    assert np.array_equal(np.asarray(restored.rng), np.asarray(state.rng))
    man = store.manifest(3)
    assert man["cursor"] == {"seed": 0, "step": 3}
    assert man["nbytes"] > 0 and "crc32" in man


def test_snapshot_rotation_keeps_newest(tmp_path, sgd_step):
    state = _fresh_state()
    store = SnapshotStore(str(tmp_path / "snaps"), keep=2)
    for b in _batches(3):
        state, _ = sgd_step(state, b)
        store.save(state)
    assert store.steps() == [2, 3]


def test_torn_payload_discarded_with_log_and_fallback(tmp_path, sgd_step,
                                                      capsys):
    """Satellite: truncate the newest snapshot; recovery falls back to
    the previous manifest-valid one and logs the discard."""
    store = SnapshotStore(str(tmp_path / "snaps"))
    state = _fresh_state()
    params_at = {}
    for b in _batches(3):
        state, _ = sgd_step(state, b)
        store.save(state)
        # host copy NOW: the next step call donates (deletes) this state
        params_at[int(state.step)] = jax.tree.map(np.asarray, state.params)
    assert store.tear_latest() == 3
    ok, why = store.validate(3)
    assert not ok and "torn" in why
    assert store.latest_valid() == 2
    err = capsys.readouterr().err
    assert "discarding snapshot 3" in err and "falling back" in err
    restored = store.restore(_fresh_state(seed=9))
    assert int(restored.step) == 2
    assert _trees_equal(restored.params, params_at[2])


def test_redo_save_heals_torn_snapshot_at_same_step(tmp_path, sgd_step,
                                                    capsys):
    """The duplicate-step dedupe must not protect a TORN snapshot from
    its own repair: after a fallback-and-redo reaches the torn step
    again, the save overwrites it."""
    store = SnapshotStore(str(tmp_path / "snaps"))
    state = _fresh_state()
    state, _ = sgd_step(state, _batches(1)[0])
    store.save(state)
    store.tear_latest()
    assert store.latest_valid() is None
    assert store.save(state)                   # heals, not deduped away
    assert "re-writing invalid snapshot 1" in capsys.readouterr().err
    assert store.latest_valid() == 1
    assert not store.save(state)               # valid now: dedupe again


def test_crc_mismatch_detected(tmp_path, sgd_step):
    """Same-length corruption (a flipped byte, not a truncation) is
    caught by the crc — size alone would pass."""
    store = SnapshotStore(str(tmp_path / "snaps"))
    state = _fresh_state()
    state, _ = sgd_step(state, _batches(1)[0])
    store.save(state)
    path = store._payload_path(1)
    blob = bytearray(open(path, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    open(path, "wb").write(bytes(blob))
    ok, why = store.validate(1)
    assert not ok and "crc32" in why
    assert store.latest_valid() is None


# --- FaultPlan -------------------------------------------------------------

def test_fault_plan_is_seed_addressable():
    a = FaultPlan.parse("preempt", 100, seed=0)
    b = FaultPlan.parse("preempt", 100, seed=0)
    c = FaultPlan.parse("preempt", 100, seed=1)
    assert [s.step for s in a.specs] == [s.step for s in b.specs]
    assert 1 <= a.specs[0].step < 100
    assert 1 <= c.specs[0].step < 100   # different seed: still in range
    # explicit pins and args parse
    p = FaultPlan.parse("preemption@3,wedge@5:0.25", 10, seed=0)
    assert [(s.kind, s.step, s.arg) for s in p.specs] == [
        ("preemption", 3, 0.0), ("wedge", 5, 0.25)]
    # torn_snapshot expands to tear + preempt at the SAME anchor step
    t = FaultPlan.parse("torn_snapshot", 50, seed=4)
    steps = {s.step for s in t.specs}
    assert len(steps) == 1 and {s.kind for s in t.specs} == {
        "torn_snapshot", "preemption"}
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultSpec("meteor", 3)


def test_faulty_batches_corrupts_exact_window():
    plan = FaultPlan.parse("nan_loss@3", 6, seed=0)
    clean = _batches(3)
    # steps_per_next=2: windows cover steps (1,2), (3,4), (5,6) — only
    # the window containing step 3 may be poisoned.
    fb = FaultyBatches(iter(clean), plan, steps_per_next=2)
    w1, w2, w3 = next(fb), next(fb), next(fb)
    assert np.isfinite(np.asarray(w1["image"])).all()
    assert np.isnan(np.asarray(w2["image"])).all()
    assert np.isfinite(np.asarray(w3["image"])).all()
    # a resumed wrapper whose start_step already passed the fault does
    # not re-fire it
    fb2 = FaultyBatches(iter(clean), plan, start_step=4)
    assert np.isfinite(np.asarray(next(fb2)["image"])).all()


def test_nan_loss_on_uint8_batch_is_refused():
    """nan_loss has no uint8 representation; degrading silently to
    legal random bytes would let the NaN-guard drill pass without the
    guard ever firing — refuse loudly instead."""
    img = np.zeros((4, 2, 2, 1), np.uint8)
    batch = {"image": img, "label": np.zeros((4,), np.int32)}
    fb = FaultyBatches(iter([batch]),
                       FaultPlan.parse("nan_loss@1", 4, seed=0))
    with pytest.raises(ValueError, match="uint8"):
        next(fb)


def test_corrupt_uint8_batch_is_deterministic():
    img = np.zeros((4, 2, 2, 1), np.uint8)
    batch = {"image": img, "label": np.zeros((4,), np.int32)}
    out1 = FaultyBatches(iter([batch]), FaultPlan.parse(
        "corrupt_batch@1", 4, seed=7))
    out2 = FaultyBatches(iter([batch]), FaultPlan.parse(
        "corrupt_batch@1", 4, seed=7))
    a, b = next(out1)["image"], next(out2)["image"]
    assert np.asarray(a).dtype == np.uint8
    assert np.array_equal(np.asarray(a), np.asarray(b))
    assert not np.array_equal(np.asarray(a), img)   # actually corrupted


# --- the resume-parity contract (satellite: mnist_cnn) ---------------------

def test_preemption_resume_parity_mnist_cnn(tmp_path, sgd_step):
    """Interrupt mnist_cnn at step 3 via injected SIGTERM preemption,
    resume from the snapshot, and assert BITWISE equality of params,
    optimizer state, and the full metric trajectory against an
    uninterrupted 6-step run (acceptance criterion; CPU only)."""
    batches = _batches(6)

    straight_tape = MetricsTapeHook()
    straight = TrainLoop(sgd_step, iter(batches), 6,
                         hooks=[straight_tape]).run(
        _fresh_state("mnist_cnn"))

    store = SnapshotStore(str(tmp_path / "snaps"))
    plan = FaultPlan.parse("preemption@3", 6, seed=0)
    tape1 = MetricsTapeHook()
    with sigterm_flag() as preempted:
        loop = TrainLoop(
            sgd_step, iter(batches), 6,
            hooks=[tape1, SnapshotHook(store, every=1, cursor={"seed": 0}),
                   FaultInjectionHook(plan)],
            should_stop=preempted)
        first = loop.run(_fresh_state("mnist_cnn"))
    assert bool(preempted) and int(first.step) == 3

    resumed = store.restore(_fresh_state("mnist_cnn", seed=42))
    assert int(resumed.step) == 3
    # the manifest's dataset cursor names the resume position
    assert store.manifest(store.latest_valid())["cursor"] == {
        "seed": 0, "step": 3}
    tape2 = MetricsTapeHook()
    resumed = TrainLoop(sgd_step, iter(batches[3:]), 6,
                        hooks=[tape2]).run(resumed)

    assert int(resumed.step) == int(straight.step) == 6
    assert _trees_equal(resumed.params, straight.params)
    assert _trees_equal(resumed.opt_state, straight.opt_state)
    # metric trajectory: interrupted + resumed tapes concatenate to the
    # uninterrupted tape EXACTLY (same steps, bit-equal losses)
    assert tape1.tape + tape2.tape == straight_tape.tape


def test_nan_guard_refuses_to_snapshot_poisoned_state(tmp_path, sgd_step):
    """An injected NaN batch kills the run at the poisoned step and the
    newest snapshot on disk is the LAST HEALTHY step — never the
    poisoned one."""
    plan = FaultPlan.parse("nan_loss@2", 6, seed=0)
    store = SnapshotStore(str(tmp_path / "snaps"))
    batches = FaultyBatches(iter(_batches(6)), plan)
    # guard BEFORE the snapshot hook: the raise must beat the save
    loop = TrainLoop(sgd_step, batches, 6,
                     hooks=[NaNGuardHook(),
                            SnapshotHook(store, every=1)])
    with pytest.raises(FloatingPointError, match="non-finite loss"):
        loop.run(_fresh_state())
    assert store.latest_valid() == 1


# --- supervisor ------------------------------------------------------------

def _script(tmp_path, name: str, body: str) -> list[str]:
    path = tmp_path / name
    path.write_text(body)
    return [sys.executable, str(path)]


def test_default_task_name_resolves_module_children():
    dn = Supervisor._default_name
    assert dn(["python", "-m",
               "distributedtensorflowexample_tpu.trainers."
               "trainer_sync_mnist", "--train_steps", "5"]) == \
        "trainer_sync_mnist"
    assert dn(["env", "JAX_PLATFORMS=cpu", "python", "bench.py"]) == \
        "bench.py"
    assert dn(["/usr/bin/python3", "tools/faultline.py"]) == "faultline.py"


def test_retry_policy_backoff_math():
    p = RetryPolicy(backoff_base_s=1.0, backoff_factor=2.0,
                    backoff_max_s=5.0, jitter=0.5)
    assert p.delay_s(0, 0.5) == 1.0          # rand 0.5 -> no jitter
    assert p.delay_s(1, 0.5) == 2.0
    assert p.delay_s(10, 0.5) == 5.0         # capped
    assert 0.5 <= p.delay_s(0, 0.0) <= 1.5   # jitter bounds
    assert p.delay_s(0, 1.0) == 1.5


def test_supervisor_retries_until_success(tmp_path):
    """Crash on attempts 0-1, succeed on attempt 2 — the supervisor's
    SUPERVISE_ATTEMPT env is what the child keys on (the same contract
    faultline's transient faults use)."""
    argv = _script(tmp_path, "flaky.py", """
import os, sys
sys.exit(0 if int(os.environ["SUPERVISE_ATTEMPT"]) >= 2 else 1)
""")
    sup = Supervisor(policy=RetryPolicy(retries=3, backoff_base_s=0.01,
                                        backoff_max_s=0.02), seed=0)
    res = sup.run(argv, name="flaky")
    assert res.status == "ok" and res.attempts == 3


def test_supervisor_exhausts_bounded_retries(tmp_path):
    argv = _script(tmp_path, "dead.py", "raise SystemExit(1)")
    sup = Supervisor(policy=RetryPolicy(retries=2, backoff_base_s=0.01,
                                        backoff_max_s=0.02), seed=0)
    res = sup.run(argv, name="dead")
    assert res.status == "exhausted" and res.attempts == 3
    assert res.returncode == 1


def test_supervisor_wedge_verdict_is_not_retried(tmp_path):
    """rc=3 is bench's watchdog 'backend provably wedged' — retrying
    burns the recovery window against a dead tunnel."""
    argv = _script(tmp_path, "wedged.py", "raise SystemExit(3)")
    sup = Supervisor(policy=RetryPolicy(retries=5, backoff_base_s=0.01),
                     seed=0)
    res = sup.run(argv, name="wedged")
    assert res.status == "wedged" and res.attempts == 1


def test_supervisor_heartbeat_watchdog_kills_wedged_child(tmp_path):
    """Attempt 0 beats once then wedges mid-run (the round-3 'blocked
    >60 min without raising' shape); the heartbeat watchdog kills the
    process group and the retry succeeds."""
    hb = str(tmp_path / "beat")
    argv = _script(tmp_path, "wedge_then_ok.py", """
import os, sys, time
open(os.environ["SUPERVISE_HEARTBEAT"], "a").close()   # first beat
if os.environ["SUPERVISE_ATTEMPT"] == "0":
    time.sleep(60)      # wedged mid-run: beats stop
sys.exit(0)
""")
    sup = Supervisor(policy=RetryPolicy(retries=1, backoff_base_s=0.01),
                     heartbeat_timeout_s=1.0, kill_grace_s=0.2,
                     poll_s=0.05, seed=0)
    t0 = time.monotonic()
    res = sup.run(argv, name="wedge", heartbeat_path=hb)
    assert res.status == "ok" and res.attempts == 2
    assert "heartbeat_timeout" in " ".join(res.reasons)
    assert time.monotonic() - t0 < 30       # killed in ~1s, not 60


def test_supervisor_heartbeat_not_armed_for_beatless_child(tmp_path):
    """A child that never opts into the heartbeat protocol (bench.py's
    shape: healthy but beat-less, e.g. deep in its probe-retry budget)
    must NOT be killed on heartbeat grounds — arming waits for the
    first beat; bounding a beat-less child is the wall timeout's job."""
    argv = _script(tmp_path, "beatless.py",
                   "import time; time.sleep(2.5)")
    sup = Supervisor(policy=RetryPolicy(retries=0),
                     heartbeat_timeout_s=1.0, kill_grace_s=0.2,
                     poll_s=0.05, seed=0)
    res = sup.run(argv, name="beatless",
                  heartbeat_path=str(tmp_path / "beat"))
    assert res.status == "ok", res.reasons


def test_supervisor_preemptions_do_not_consume_crash_budget(tmp_path):
    """A run preempted more times than --retries still completes: each
    143 saved state and made progress — only crashes are bounded."""
    argv = _script(tmp_path, "preempt_storm.py", """
import os, sys
sys.exit(143 if int(os.environ["SUPERVISE_ATTEMPT"]) < 3 else 0)
""")
    sup = Supervisor(policy=RetryPolicy(retries=1, backoff_base_s=0.01),
                     seed=0)
    res = sup.run(argv, name="storm")
    assert res.status == "ok" and res.attempts == 4   # 3 preempts + ok


def test_supervisor_stale_heartbeat_file_does_not_kill_fresh_child(
        tmp_path):
    """A heartbeat file left by a previous run has a stale mtime; the
    supervisor must reset it at spawn or the first poll reads the fresh
    child as wedged and kills it before it can write its first beat."""
    hb = tmp_path / "beat"
    hb.write_text("")
    stale = time.time() - 3600
    os.utime(hb, (stale, stale))
    argv = _script(tmp_path, "slow_start.py", """
import os, time
time.sleep(0.5)     # longer than poll_s: a stale-mtime bug kills here
open(os.environ["SUPERVISE_HEARTBEAT"], "a").close()
""")
    sup = Supervisor(policy=RetryPolicy(retries=0),
                     heartbeat_timeout_s=2.0, kill_grace_s=0.2,
                     poll_s=0.05, seed=0)
    res = sup.run(argv, name="slow", heartbeat_path=str(hb))
    assert res.status == "ok", res.reasons


def test_supervisor_preempted_restart_and_stdout_keep(tmp_path):
    """rc=143 (preempted-with-save) restarts immediately; an attempt
    that wrote nothing to stdout must not clobber the previous
    attempt's kept output."""
    out = str(tmp_path / "out.json")
    argv = _script(tmp_path, "preempt_then_quiet.py", """
import os, sys
if os.environ["SUPERVISE_ATTEMPT"] == "0":
    print('{"partial": true}')
    sys.exit(143)
sys.exit(0)         # attempt 1: succeeds but prints NOTHING
""")
    sup = Supervisor(policy=RetryPolicy(retries=2, backoff_base_s=0.01),
                     seed=0)
    res = sup.run(argv, name="preempt", stdout_path=out)
    assert res.status == "ok" and res.attempts == 2
    # attempt 0's partial output survived attempt 1's empty stdout
    assert json.load(open(out)) == {"partial": True}


def test_supervisor_sigterm_forwards_to_child_group(tmp_path):
    """The watcher's stale-capture sweep TERMs the SUPERVISOR's group;
    children live in their own sessions, so the supervisor must forward
    the TERM to the child group — a dead supervisor must never leave a
    live chip-holding phase orphaned behind it."""
    child_pid_file = tmp_path / "child.pid"
    runner = tmp_path / "runner.py"
    runner.write_text(f"""
import sys
sys.path.insert(0, {REPO!r})
from distributedtensorflowexample_tpu.resilience import (
    RetryPolicy, Supervisor)
sup = Supervisor(policy=RetryPolicy(retries=0), poll_s=0.05,
                 kill_grace_s=0.2, seed=0)
res = sup.run([sys.executable, "-c",
               "import os, time;"
               "open({str(child_pid_file)!r}, 'w').write(str(os.getpid()));"
               "time.sleep(60)"], name="holder")
print(res.status)
""")
    proc = subprocess.Popen([sys.executable, str(runner)],
                            stdout=subprocess.PIPE, text=True)
    deadline = time.time() + 20
    while time.time() < deadline and not child_pid_file.exists():
        time.sleep(0.1)
    child_pid = int(child_pid_file.read_text())
    proc.terminate()                       # the watcher's TERM
    out, _ = proc.communicate(timeout=30)
    assert "terminated" in out
    # the child must be gone too (forwarded kill), not orphaned
    deadline = time.time() + 10
    while time.time() < deadline:
        try:
            os.kill(child_pid, 0)
        except ProcessLookupError:
            break
        time.sleep(0.1)
    else:
        os.kill(child_pid, signal.SIGKILL)
        pytest.fail(f"child {child_pid} survived the supervisor's death")


def test_task_queue_stops_on_terminated_supervisor(tmp_path, monkeypatch):
    """A terminated supervisor must stop the queue WITHOUT journaling
    completion — the next window resumes from the interrupted task."""
    from distributedtensorflowexample_tpu.resilience import (
        supervisor as sup_mod)
    sup = Supervisor(policy=RetryPolicy(retries=0),
                     journal=Journal(str(tmp_path / "j.jsonl")), seed=0)
    monkeypatch.setattr(
        sup, "run",
        lambda *a, **k: sup_mod.SupervisedResult("terminated", None, 1))
    tasks = [Task("a", ["true"], priority=1),
             Task("b", ["true"], priority=2)]
    results = TaskQueue(tasks, sup).run()
    assert results == {"a": "terminated"}      # b never attempted
    assert sup.journal.replay()["done"] == set()


def test_journal_replay_skips_torn_tail(tmp_path):
    j = Journal(str(tmp_path / "j.jsonl"))
    j.write("task_done", task="a")
    with open(j._path, "a") as f:
        f.write('{"event": "task_done", "task": "b"')   # torn mid-write
    state = j.replay()
    assert state["done"] == {"a"} and not state["wedged"]


def test_task_queue_priority_wedge_and_journal_resume(tmp_path):
    """Priority order; a wedge verdict skips later chip-bound tasks but
    NOT the CPU-only one; a second queue over the same journal resumes
    with done/wedged state intact (the two-window capture story)."""
    jpath = str(tmp_path / "q.jsonl")
    mark = lambda n: _script(
        tmp_path, f"{n}.py",
        f"open({str(tmp_path / (n + '.ran'))!r}, 'w').write('x')")
    tasks = [
        Task("first", mark("first"), priority=10),
        Task("wedger", _script(tmp_path, "wedger.py",
                               "raise SystemExit(3)"), priority=20),
        Task("chip_bound", mark("chip"), priority=30),
        Task("cpu_only", mark("cpu"), priority=25, needs_chip=False),
        Task("gated", mark("gated"), priority=15, gate=lambda: False),
    ]
    sup = Supervisor(policy=RetryPolicy(retries=0), journal=Journal(jpath),
                     seed=0)
    results = TaskQueue(tasks, sup).run()
    assert results == {"first": "done", "gated": "skipped_gate",
                       "wedger": "wedged", "cpu_only": "done",
                       "chip_bound": "skipped_wedged"}
    assert (tmp_path / "first.ran").exists()
    assert (tmp_path / "cpu.ran").exists()
    assert not (tmp_path / "chip.ran").exists()
    # second window: same journal — done tasks skip, wedge persists
    (tmp_path / "first.ran").unlink()
    sup2 = Supervisor(policy=RetryPolicy(retries=0), journal=Journal(jpath),
                      seed=0)
    results2 = TaskQueue(tasks, sup2).run()
    assert results2["first"] == "done_prior"
    assert results2["chip_bound"] == "skipped_wedged"
    assert not (tmp_path / "first.ran").exists()    # truly skipped


def test_heartbeat_hook_touches_at_boundaries(tmp_path, sgd_step):
    hb = str(tmp_path / "beat")
    loop = TrainLoop(sgd_step, iter(_batches(3)), 3,
                     hooks=[HeartbeatHook(hb, every=1)])
    assert not os.path.exists(hb)
    loop.run(_fresh_state())
    assert os.path.exists(hb)


# --- supervised capture queue (tools/supervise.py) -------------------------

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_supervise_capture_queue_shape(monkeypatch, tmp_path):
    """The capture queue mirrors bench_capture.sh: artifact-value phase
    order, env-knob surface, bytes-audit chip independence, phase-4
    freshness gate."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import supervise
    finally:
        sys.path.pop(0)
    monkeypatch.setenv("OUT", str(tmp_path / "out.json"))
    # start_ts slightly in the past: real captures write OUT minutes
    # after start, and this host's fs truncates mtimes to seconds
    tasks = supervise._capture_tasks(start_ts=time.time() - 5)
    names = [t.name for t in sorted(tasks, key=lambda t: t.priority)]
    assert names == ["headline_bench", "profile", "bytes_audit_cpu",
                     "collectives", "lm", "full_bench", "cli_trainer"]
    by_name = {t.name: t for t in tasks}
    assert by_name["headline_bench"].env["BENCH_HEADLINE_ONLY"] == "1"
    assert not by_name["bytes_audit_cpu"].needs_chip
    # collectives phase: --real (the chip re-fit), keep() post promoting
    # the .tmp artifact, sentinel-capable so it can't wedge the queue
    assert "--real" in by_name["collectives"].argv
    assert by_name["collectives"].post is not None
    # lm phase (2d): same --real/keep()/sentinel discipline as 2c
    assert "--real" in by_name["lm"].argv
    assert by_name["lm"].post is not None
    assert "bench_lm.py" in " ".join(by_name["lm"].argv)
    assert by_name["cli_trainer"].wall_timeout_s > 0
    # gate: no fresh measured OUT -> phase 4 must not run
    assert by_name["cli_trainer"].gate() is False
    with open(tmp_path / "out.json", "w") as f:
        f.write('{"unit": "steps/sec/chip"}')
    assert by_name["cli_trainer"].gate() is True
    # journal-resumed window: OUT predates start_ts but full_bench is
    # done_prior — the gate must still pass (it IS this capture's
    # artifact), else phase 4 becomes permanently unobtainable
    old = time.time() - 3600
    os.utime(tmp_path / "out.json", (old, old))
    resumed = supervise._capture_tasks(start_ts=time.time() - 5,
                                       full_bench_done_prior=True)
    gates = {t.name: t for t in resumed}
    assert gates["cli_trainer"].gate() is True
    stale = supervise._capture_tasks(start_ts=time.time() - 5)
    assert {t.name: t for t in stale}["cli_trainer"].gate() is False
    # journal rotation predicate: an ENDED capture run (complete or
    # wedged) must rotate; a mid-run death (no capture_end) must resume
    ended = tmp_path / "ended.jsonl"
    ended.write_text('{"event": "task_done", "task": "headline_bench"}\n'
                     '{"event": "capture_end", "results": {}}\n')
    midrun = tmp_path / "midrun.jsonl"
    midrun.write_text('{"event": "task_done", "task": "headline_bench"}\n')
    assert supervise._capture_ended(str(ended)) is True
    assert supervise._capture_ended(str(midrun)) is False
    assert supervise._capture_ended(str(tmp_path / "absent.jsonl")) is False


def test_supervise_cli_generic_mode(tmp_path):
    """tools/supervise.py -- CMD: exit code mirrors the child's final
    verdict and the journal records each attempt."""
    script = tmp_path / "child.py"
    script.write_text("""
import os, sys
sys.exit(0 if int(os.environ["SUPERVISE_ATTEMPT"]) >= 1 else 7)
""")
    jpath = tmp_path / "j.jsonl"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "supervise.py"),
         "--retries", "2", "--backoff_base_s", "0.01", "--seed", "0",
         "--journal", str(jpath), "--",
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    events = [json.loads(l)["event"] for l in open(jpath)]
    assert events == ["attempt_start", "attempt_end",
                      "attempt_start", "attempt_end"]


def test_supervise_cli_derives_heartbeat_path(tmp_path):
    """--heartbeat_timeout_s without --heartbeat must still arm the
    watchdog (derived path exported as SUPERVISE_HEARTBEAT) — the
    advertised one-liner must not silently run unprotected."""
    script = tmp_path / "child.py"
    script.write_text("""
import os, sys
sys.exit(0 if os.environ.get("SUPERVISE_HEARTBEAT") else 9)
""")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "supervise.py"),
         "--retries", "0", "--heartbeat_timeout_s", "30", "--",
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "heartbeat file defaulted" in proc.stderr
