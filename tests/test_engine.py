"""Engine front-end parity (PR 19, arXiv:1902.00465).

The tentpole's acceptance gate: the declarative Engine produces the
SAME programs, trajectories, and telemetry rows the per-caller wiring
used to hand-build — bitwise, per ported replication mode.  Each
parametrized case builds one mode twice: ground truth via the raw
``parallel/`` builders (the pre-engine wiring, reproduced here on
purpose — tests/ are exempt from the ``engine-owns-wiring`` source
rule for exactly this), and the same declaration through
``Engine(spec).build()``; the loss tape and final params must match
bit-for-bit, the compiled step's collective multiset must be
identical, and the ledger rows the full ``run()`` surface writes must
carry the schema ``tools/obs_query.py diff`` derives
``update_layout`` from.

The payoff demo (trainers/trainer_tiny_mlp.py) is held to its
promises too: ~50 lines, a full hook stack resolved via
``describe()`` (``jax.eval_shape`` — zero FLOPs, nothing compiled),
and the complete SIGTERM preemption -> resume drill.
"""

import os
import re
import subprocess
import sys
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedtensorflowexample_tpu.config import RunConfig
from distributedtensorflowexample_tpu.data import DeviceDataset
from distributedtensorflowexample_tpu.data.synthetic import make_synthetic
from distributedtensorflowexample_tpu.engine import (
    Engine, RunSpec, resolve_update_layout)
from distributedtensorflowexample_tpu.models import build_model
from distributedtensorflowexample_tpu.obs import ledger as obs_ledger
from distributedtensorflowexample_tpu.parallel import (
    make_mesh, replicated_sharding)
from distributedtensorflowexample_tpu.parallel.async_ps import (
    make_indexed_async_train_step, make_worker_state)
from distributedtensorflowexample_tpu.parallel.bucketing import (
    init_bucketed_opt_state, resolve_bucket_bytes)
from distributedtensorflowexample_tpu.parallel.sync import (
    make_indexed_train_step)
from distributedtensorflowexample_tpu.parallel.zero3 import Zero3Layout
from distributedtensorflowexample_tpu.training.optimizers import (
    build_optimizer, update_shardings)
from distributedtensorflowexample_tpu.training.state import TrainState
from distributedtensorflowexample_tpu.utils.profiling import (
    collective_inventory_of)

pytestmark = pytest.mark.engine

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEMO = os.path.join(REPO, "distributedtensorflowexample_tpu", "trainers",
                    "trainer_tiny_mlp.py")
STEPS = 4

#: (case id, config overrides, resolved mode, update layout, collective
#: ops the mode's compiled schedule must contain — None: no fixed
#: contract to pin beyond parity, the async worker average is
#: cond-gated).
MODES = [
    ("sync_dp", {}, "sync_dp", "tree", {"all-reduce"}),
    ("sync_dp_gspmd_update", {"shard_update": True}, "sync_dp", "tree",
     {"all-reduce"}),
    ("async_ps", {"sync_mode": "async", "async_period": 2}, "async_ps",
     "tree", None),
    ("bucketed", {"bucket_grads": "4096"}, "bucketed", "tree",
     {"all-reduce"}),
    ("zero1", {"bucket_grads": "4096", "shard_update": True}, "zero1",
     "bucket_rows", {"reduce-scatter", "all-gather"}),
    ("zero3", {"bucket_grads": "4096", "shard_params": True}, "zero3",
     "zero3_rows", {"reduce-scatter", "all-gather"}),
]

_IDS = [m[0] for m in MODES]


def _cfg(**kw):
    kw.setdefault("batch_size", 8)
    kw.setdefault("train_steps", STEPS)
    kw.setdefault("learning_rate", 0.1)
    kw.setdefault("momentum", 0.9)
    kw.setdefault("dropout", 0.0)
    kw.setdefault("dataset", "synthetic")
    kw.setdefault("seed", 0)
    return RunConfig(**kw)


def _blobs(cfg, split):
    return make_synthetic(256 if split == "train" else 128, (8, 8, 1),
                          10, seed=cfg.seed,
                          sample_seed=cfg.seed + (split == "test"))


def _spec(cfg):
    return RunSpec(model="softmax", dataset="mnist", config=cfg,
                   input_fn=_blobs)


def _tape(step, ds, state, mesh, steps=STEPS):
    """Loss tape + final state + compiled collective multiset for one
    (step, dataset, state) triple — the three parity surfaces."""
    inv = collective_inventory_of(step, (state, ds.peek()), unroll=1)
    losses = []
    with mesh:
        for _ in range(steps):
            state, m = step(state, next(ds))
            losses.append(np.asarray(m["loss"]))
    jax.block_until_ready(state)
    return np.stack(losses), state, inv["multiset"]


def _ground_truth(cfg, steps=STEPS):
    """The pre-engine wiring, verbatim: the exact construction order
    (seed usage, state creation, layout pass, step factory) the
    trainers' shared runner and the bench builders hand-applied before
    PR 19 moved it into Engine."""
    mesh = make_mesh(cfg.num_devices)
    num = mesh.size
    gb = cfg.batch_size * num
    x, y = _blobs(cfg, "train")
    ds = DeviceDataset(x, y, gb, mesh=mesh, seed=cfg.seed)
    bucket_bytes = resolve_bucket_bytes(cfg.bucket_grads)
    sync = cfg.sync_mode == "sync"
    zero3_on = (cfg.shard_params and bool(bucket_bytes) and num > 1
                and sync)
    zero1_on = (bool(bucket_bytes) and cfg.shard_update and num > 1
                and sync and not zero3_on)
    model = build_model("softmax", dropout=cfg.dropout,
                        dtype=jnp.dtype(cfg.dtype), remat=cfg.remat)
    tx = build_optimizer(cfg, mesh=mesh,
                         wrap_shard_update=not (zero1_on or zero3_on))
    state = TrainState.create_sharded(model, tx, (gb,) + x.shape[1:],
                                      cfg.seed, replicated_sharding(mesh))
    z3 = None
    if zero3_on:
        z3 = Zero3Layout(state.params, bucket_bytes, mesh)
        state = state.replace(opt_state=init_bucketed_opt_state(
            tx, state.params, bucket_bytes, mesh))
        state = state.replace(params=z3.init_rows(state.params))
    elif zero1_on:
        state = state.replace(opt_state=init_bucketed_opt_state(
            tx, state.params, bucket_bytes, mesh))
    elif cfg.shard_update:
        state = state.replace(opt_state=jax.device_put(
            state.opt_state, update_shardings(state.opt_state, mesh)))
    if not sync:
        state = make_worker_state(state, num, mesh)
        step = make_indexed_async_train_step(
            num, cfg.async_period, gb, ds.steps_per_epoch, mesh=mesh,
            num_slots=ds.num_slots, bucket_bytes=bucket_bytes)
    else:
        step = make_indexed_train_step(
            gb, ds.steps_per_epoch, mesh=mesh, num_replicas=num,
            num_slots=ds.num_slots, bucket_bytes=bucket_bytes,
            bucket_shard_update=zero1_on, zero3_layout=z3,
            zero3_overlap=cfg.zero3_overlap)
    return _tape(step, ds, state, mesh, steps)


# --- the bitwise parity gate, per ported mode -------------------------------

@pytest.mark.parametrize("case,overrides,mode,layout,ops", MODES,
                         ids=_IDS)
def test_engine_build_matches_raw_wiring_bitwise(case, overrides, mode,
                                                 layout, ops):
    """Engine.build vs the raw builders: same loss tape (bitwise), same
    final params (bitwise), same compiled collective multiset."""
    gt_losses, gt_state, gt_ms = _ground_truth(_cfg(**overrides))
    eb = Engine(_spec(_cfg(**overrides))).build()
    assert eb.mode == mode
    en_losses, en_state, en_ms = _tape(eb.step, eb.ds, eb.state, eb.mesh)
    np.testing.assert_array_equal(gt_losses, en_losses)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(a, b),
                 gt_state.params, en_state.params)
    assert gt_ms == en_ms
    if ops is not None:
        assert ops <= set(en_ms), en_ms


# --- describe(): resolution without compilation, per mode -------------------

@pytest.mark.parametrize("case,overrides,mode,layout,ops", MODES,
                         ids=_IDS)
def test_describe_and_stdlib_layout_resolution(case, overrides, mode,
                                               layout, ops):
    """describe() and the stdlib resolve_update_layout agree with the
    mode registry — including on a raw ledger config DICT, which is
    what obs_query's diff feeds it."""
    import dataclasses
    cfg = _cfg(**overrides)
    d = Engine(_spec(cfg)).describe()
    assert d["mode"] == mode
    assert d["update_layout"] == layout
    assert d["mesh_size"] == jax.device_count()
    assert resolve_update_layout(cfg, jax.device_count()) == layout
    assert resolve_update_layout(dataclasses.asdict(cfg),
                                 jax.device_count()) == layout


def test_spec_module_is_importable_without_jax():
    """The obs_query seam: resolve_update_layout must import (and run)
    in a stdlib-only process — jax poisoned outright."""
    code = ("import sys; sys.modules['jax'] = None; "
            "from distributedtensorflowexample_tpu.engine import "
            "resolve_update_layout; "
            "print(resolve_update_layout({'sync_mode': 'sync'}, 8))")
    r = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr[-1000:]
    assert r.stdout.strip() == "tree"


# --- the full run() surface: ledger row schema, per mode --------------------

@pytest.mark.parametrize("case,overrides,mode,layout,ops", MODES,
                         ids=_IDS)
def test_run_ledger_rows_per_mode(case, overrides, mode, layout, ops,
                                  tmp_path, monkeypatch):
    """Engine.run writes the run_start/run_end rows obs_query consumes:
    the resolved config + top-level mesh_size (enough to DERIVE the
    update layout — the diff table's first row), and a clean rc=0 end
    at the declared step count."""
    path = str(tmp_path / "RUNS.jsonl")
    monkeypatch.setenv("OBS_LEDGER", path)
    monkeypatch.setattr(obs_ledger, "_GLOBAL", None)
    cfg = _cfg(log_dir=str(tmp_path / "logs"), checkpoint_every=0,
               resume=False, **overrides)
    summary = Engine(_spec(cfg)).run()
    assert summary["steps"] == STEPS
    assert np.isfinite(summary["final_accuracy"])
    rows, torn = obs_ledger.read_rows(path)
    assert torn == 0
    start = [r for r in rows if r["event"] == "run_start"][0]
    end = [r for r in rows if r["event"] == "run_end"][0]
    assert {"v", "ts", "event", "run", "entrypoint", "config",
            "config_digest", "platform", "mesh_size", "num_processes",
            "dataset"} <= set(start)
    assert start["entrypoint"] == "trainer:softmax"
    assert start["mesh_size"] == jax.device_count()
    assert resolve_update_layout(start["config"],
                                 int(start["mesh_size"])) == layout
    assert end["rc"] == 0 and end["final_step"] == STEPS
    monkeypatch.setattr(obs_ledger, "_GLOBAL", None)


# --- the ~50-line payoff demo -----------------------------------------------

def test_demo_stays_small():
    """The tentpole's headline number: a new workload is a declaration,
    ~50 lines all-in."""
    with open(DEMO, encoding="utf-8") as f:
        assert len(f.read().splitlines()) <= 60


def test_demo_describe_pins_full_hook_stack(monkeypatch):
    """The demo's declaration resolves to the COMPLETE supervised
    surface — checkpoint/eval/heartbeat/metrics/anomaly hooks and the
    abstract TrainState — via eval_shape, with nothing compiled."""
    from distributedtensorflowexample_tpu.config import parse_flags
    from distributedtensorflowexample_tpu.trainers import trainer_tiny_mlp
    monkeypatch.setenv("SUPERVISE_HEARTBEAT", "/tmp/hb")
    cfg = parse_flags(["--checkpoint_every", "50", "--eval_every", "100"],
                      batch_size=32, train_steps=300, learning_rate=0.1,
                      momentum=0.9, dataset="tiny_blobs", dropout=0.0)
    spec = RunSpec(model="tiny_mlp", dataset="tiny_blobs", config=cfg,
                   model_fn=lambda cfg: trainer_tiny_mlp.TinyMLP(),
                   input_fn=trainer_tiny_mlp.blobs)
    d = Engine(spec).describe(sample_shape=(32, 8, 8, 1))
    assert d["hooks"] == ["CheckpointHook", "EvalHook", "HeartbeatHook",
                          "MetricsHook", "AnomalyHook"]
    assert d["mode"] == "sync_dp" and d["update_layout"] == "tree"
    assert d["checkpointing"] and not d["token_data"]
    shapes = jax.tree.map(lambda s: s.shape, d["abstract_state"].params)
    assert shapes == {"hidden": {"kernel": (64, 32), "bias": (32,)},
                      "logits": {"kernel": (32, 4), "bias": (4,)}}


def test_demo_sigterm_preemption_saves_and_resumes(tmp_path):
    """The acceptance drill: the 50-line declaration gets the six
    trainers' preemption story for free — SIGTERM -> final checkpoint
    -> exit 143 -> restart auto-resumes from the saved step.
    Subprocess: signal handlers need the trainee's own main thread."""
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""   # CPU backend in the child
    env["JAX_PLATFORMS"] = "cpu"
    args = [sys.executable, "-u", "-m",
            "distributedtensorflowexample_tpu.trainers.trainer_tiny_mlp",
            "--batch_size", "16", "--steps_per_loop", "1",
            "--log_every", "5", "--log_dir", str(tmp_path)]

    p = subprocess.Popen(args + ["--train_steps", "100000"], env=env,
                         cwd=REPO, stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT, text=True)
    saw = []
    got_step = threading.Event()

    def drain():
        # Deadline-safe: a blocking for-line read on the main thread
        # could hang the whole session if the child wedges pre-output.
        for line in p.stdout:
            saw.append(line)
            if line.startswith("step ") and "loss" in line:
                got_step.set()
        got_step.set()                 # EOF: unblock the waiter either way

    t = threading.Thread(target=drain, daemon=True)
    t.start()
    try:
        assert got_step.wait(timeout=300), "no output within deadline"
        assert p.poll() is None, (
            "trainer exited early:\n" + "".join(saw)[-2000:])
        p.terminate()                  # the platform's preemption signal
        p.wait(timeout=240)
    finally:
        if p.poll() is None:
            p.kill()
            p.wait()
        t.join(timeout=30)
    full = "".join(saw)
    assert p.returncode == 143, (p.returncode, full[-2000:])
    m = re.search(r"SIGTERM at step (\d+): checkpoint saved", full)
    assert m, full[-2000:]
    saved = int(m.group(1))
    assert saved >= 5

    r = subprocess.run(args + ["--train_steps", str(saved + 10)], env=env,
                       cwd=REPO, capture_output=True, text=True,
                       timeout=600)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-500:]
    assert f"resumed from checkpoint at step {saved}" in r.stdout, \
        r.stdout[-2000:]
    assert "final accuracy:" in r.stdout
