"""bench.py machinery smoke tests (CPU, tiny sizes).

The driver runs bench.py exactly once per round on the real chip; a crash
there silently costs the round's numbers (round 2 lost its headline to a
mid-run tunnel outage).  These tests execute every bench helper — build,
measure, roofline probe, flops probe, collective parsing — on the virtual
mesh so breakage surfaces in CI, not at measurement time.
"""

import json

import pytest

import bench
import bench_scaling
from distributedtensorflowexample_tpu.parallel import make_mesh


@pytest.fixture()
def tiny_mnist(small_synthetic, tmp_path):
    """Shared synthetic shrink (conftest.small_synthetic) + an empty data
    dir so a real MNIST download in /tmp/data can never bypass it."""
    return str(tmp_path)


def test_make_and_measure_sync(tiny_mnist):
    mesh = make_mesh()
    step, ds, state, u = bench._make("softmax", "mnist", 8, 4, mesh,
                                     momentum=0.0, lr=0.5,
                                     data_dir=tiny_mnist)
    assert u == 4
    with mesh:
        best, rates, state = bench._measure(step, ds, state, 8, u,
                                            warmup_calls=1)
    assert best > 0 and len(rates) == bench.REPEATS
    # 1 warmup call + REPEATS x (8 // 4) calls, 4 steps each.
    assert int(state.step) == (1 + bench.REPEATS * 2) * 4


def test_make_async_variant(tiny_mnist):
    mesh = make_mesh()
    step, ds, state, u = bench._make("softmax", "mnist", 8, 4, mesh,
                                     sync=False, data_dir=tiny_mnist)
    with mesh:
        best, rates, _ = bench._measure(step, ds, state, 4, u,
                                        warmup_calls=1)
    assert best > 0


def test_make_pallas_and_fused_variants(tiny_mnist):
    mesh = make_mesh()
    for kw in ({"ce_impl": "pallas"}, {"fused_opt": True}):
        step, ds, state, u = bench._make("softmax", "mnist", 8, 4, mesh,
                                         data_dir=tiny_mnist, **kw)
        with mesh:
            best, _, _ = bench._measure(step, ds, state, 4, u,
                                        warmup_calls=1)
        assert best > 0


def test_flops_probe_uses_peek(tiny_mnist):
    mesh = make_mesh()
    step, ds, state, u = bench._make("softmax", "mnist", 8, 4, mesh,
                                     data_dir=tiny_mnist)
    with mesh:
        before = ds._step
        flops = bench._flops_per_step(step, state, ds.peek(), u)
        assert ds._step == before          # probe must not consume
    # cost_analysis works on the CPU backend: a None here means the probe
    # itself broke (the thing this test exists to catch pre-chip).
    assert flops is not None and flops > 0


def test_roofline_probe(tiny_mnist):
    mesh = make_mesh()
    cost = {}
    with mesh:
        rates = bench._roofline_probe(mesh, 4, length=4, cost_out=cost)
    assert len(rates) == bench.REPEATS and all(r > 0 for r in rates)
    # The probe's own per-step cost — the denominator of the measured-
    # vs-roofline byte decomposition (VERDICT r3 #5 softmax attribution).
    assert cost.get("flops", 0) > 0
    assert cost.get("bytes_accessed", 0) > 0


def test_sweep_fault_isolation(tiny_mnist):
    """_sweep records a failing point into errors and keeps going; the
    all-fail case returns best_unroll=None (config4 then emits nothing)."""
    mesh = make_mesh()

    def mk(unroll):
        if unroll == 2:
            raise RuntimeError("boom")
        return bench._make("softmax", "mnist", 8, unroll, mesh,
                           momentum=0.0, lr=0.5, data_dir=tiny_mnist)

    errors = {}
    with mesh:
        best, best_u, rates, sweep = bench._sweep(
            {2, 4}, mk, lambda u: u, "p_", errors)
    assert best > 0 and best_u == 4 and list(sweep) == ["4"]
    assert "p_2" in errors and "boom" in errors["p_2"]

    errors = {}
    best, best_u, rates, sweep = bench._sweep(
        {2}, mk, lambda u: u, "p_", errors)
    assert best == 0.0 and best_u is None and sweep == {} and "p_2" in errors


def test_affine_dequant_not_slower_than_lut_gather():
    """The round-5 regression guard, as a CPU microbench: the fused
    affine dequant of a fixed headline-sized batch must not be slower
    than the elementwise LUT gather it replaced (the round-4 default the
    on-chip window measured at 4.1x the step time — AB_quantize_r05).  A
    refactor that silently re-routes the default back through the gather
    shows up here as a timing inversion, before it costs a TPU window.
    CPU magnitudes differ from TPU but the ordering holds at this batch
    shape on the per-channel spec (measured ~5x; 1.5x slack for CI
    noise)."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributedtensorflowexample_tpu.data.dequant import (
        make_dequant_affine, make_dequant_lut)
    from distributedtensorflowexample_tpu.data.device_dataset import (
        apply_dequant_affine, apply_dequant_gather)

    u = jnp.asarray(np.random.RandomState(0).randint(
        0, 256, (bench.BATCH["resnet"], 32, 32, 3), dtype=np.uint8))
    s, b = (jnp.asarray(v) for v in make_dequant_affine("cifar"))
    lut = jnp.asarray(make_dequant_lut("cifar"))
    f_affine = jax.jit(lambda u: apply_dequant_affine(u, s, b))
    f_gather = jax.jit(lambda u: apply_dequant_gather(u, lut))

    def best_of(f, reps=7):
        f(u).block_until_ready()           # compile + warm
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            f(u).block_until_ready()
            times.append(time.perf_counter() - t0)
        return min(times)

    t_affine, t_gather = best_of(f_affine), best_of(f_gather)
    # 3x slack: the regression this guards against is a ≥4x tax (the
    # gather re-appearing in the fast path), and min-of-7 on the
    # contended shared CI host still jitters — the deterministic
    # no-256-gather jaxpr check in test_dequant.py catches structure;
    # this one only has to catch a wholesale speed inversion.
    assert t_affine <= t_gather * 3.0, (
        f"affine dequant ({t_affine * 1e6:.0f}us) slower than the LUT "
        f"gather ({t_gather * 1e6:.0f}us): the round-5 dequant tax is "
        f"back — check the auto lowering in data.device_dataset")


def test_dequant_ab_auto_selects_winning_impl(monkeypatch, capsys):
    """--dequant auto promotes tools/ab_quantize.py's sweep into the
    official record: the alternatives are measured at the winning unroll,
    the fastest supersedes the resolved default (detail.dequant names
    it), every alternative's repeats land in detail.dequant_ab, and the
    promoted line re-probes its roofline in its own window."""
    probes = []

    class FakeDs:
        def __init__(self, impl):
            self.dequant_impl = impl

    def fake_make(model, dataset, b, unroll, mesh, **kw):
        impl = kw.get("dequant_impl", "auto")
        if impl in bench.DEQUANT_AB_IMPLS:
            return ("step", FakeDs(impl), "state", unroll)
        raise RuntimeError("side workload down")   # sides fail fast

    def fake_measure(step, ds, state, steps, u, warmup_calls=2):
        rate = {"onehot": 60.0, "lut": 5.0, "pallas": 55.0}[ds.dequant_impl]
        return rate, [rate], state

    def fake_roofline(*a, **k):
        probes.append(1)
        return [80.0] if len(probes) == 1 else [120.0]

    def fake_sweep(unrolls, make_fn, steps_for, err_prefix, errors):
        if err_prefix != "sweep_":
            return (0.0, None, [], {})      # resnet's sweep: fail
        return (50.0, 16, [50.0], {"16": [50.0]})

    monkeypatch.setattr(bench, "_sweep", fake_sweep)
    monkeypatch.setattr(bench, "_make", fake_make)
    monkeypatch.setattr(bench, "_measure", fake_measure)
    monkeypatch.setattr(bench, "_roofline_probe", fake_roofline)

    bench.main()
    lines = [json.loads(l) for l in capsys.readouterr().out.splitlines()]
    line = lines[-1]
    assert line["metric"] == "mnist_cnn_sync_steps_per_sec_per_chip"
    # onehot (60) beat the held default (50) and pallas (55): promoted.
    assert line["value"] == round(60.0 / make_mesh().size, 2)
    assert line["detail"]["dequant"] == "onehot"
    assert line["detail"]["dequant_ab"] == {
        "onehot": [60.0], "lut": [5.0], "pallas": [55.0]}
    # Fresh same-window probe for the promoted line: 60/120 = 0.5.
    assert line["detail"]["vs_roofline"] == 0.5
    assert len(probes) == 2


def test_dequant_forced_impl_skips_ab(monkeypatch, capsys):
    """A named --dequant impl forces the kernel and runs NO A/B (each
    alternative is a compile the operator asked to skip)."""
    def fake_make(*a, **k):
        raise RuntimeError("side workload down")

    def fake_sweep(unrolls, make_fn, steps_for, err_prefix, errors):
        if err_prefix != "sweep_":
            return (0.0, None, [], {})
        return (50.0, 16, [50.0], {"16": [50.0]})

    monkeypatch.setattr(bench, "DEQUANT", "affine")
    monkeypatch.setattr(bench, "_sweep", fake_sweep)
    monkeypatch.setattr(bench, "_make", fake_make)
    monkeypatch.setattr(bench, "_roofline_probe", lambda *a, **k: [100.0])

    bench.main()
    lines = [json.loads(l) for l in capsys.readouterr().out.splitlines()]
    line = lines[-1]
    assert line["unit"] == "steps/sec/chip"
    assert "dequant_ab" not in line["detail"]
    assert not any(k.startswith("dequant_ab") for k in
                   line["detail"].get("errors", {}))


def test_emit_shape(capsys):
    bench._emit("some_metric", 123.456, {"some_metric": 100.0},
                {"repeats": [1.0]})
    line = json.loads(capsys.readouterr().out.strip())
    assert line["metric"] == "some_metric"
    assert line["value"] == 123.46
    assert line["unit"] == "steps/sec/chip"
    assert line["vs_baseline"] == pytest.approx(1.2346, abs=1e-4)
    assert line["detail"]["repeats"] == [1.0]


def test_scaling_async_mode(monkeypatch, capsys):
    """bench_scaling --mode async end-to-end on tiny sizes: emits per-count
    lines with period-amortized collective bytes and the summary line."""
    monkeypatch.setattr("sys.argv", [
        "bench_scaling.py", "--mode", "async", "--async_period", "2",
        "--max_devices", "2", "--batch_per_chip", "4", "--unroll", "2",
        "--steps", "4"])
    bench_scaling.main()
    lines = [json.loads(l) for l in capsys.readouterr().out.splitlines()]
    summary = lines[-1]
    assert summary["metric"] == "async_sgd_weak_scaling"
    assert summary["detail"]["mode"] == "async"
    per_count = [l for l in lines[:-1] if l.get("mode") == "async"]
    assert [l["devices"] for l in per_count] == [1, 2]
    two = per_count[-1]
    # The 2-device worker average is an all-reduce in the program; its
    # sustained cost is parsed bytes / period.
    assert "all-reduce" in two["collectives_per_step"]
    assert two["amortized_bytes_per_step"]["all-reduce"] == round(
        two["collectives_per_step"]["all-reduce"]["bytes"] / 2)


def test_bench_input_stages(capsys):
    """bench_input's three stages run end-to-end on tiny sizes (each
    asserts native/numpy bit-identity itself before timing)."""
    import bench_input
    from distributedtensorflowexample_tpu import native

    if not native.available():
        pytest.skip("native loader unavailable on this host")
    bench_input.bench_cifar_parse(n_records=50)
    bench_input.bench_idx_parse(n=200)
    bench_input.bench_gather_augment(n_src=300, batch=16)
    bench_input.bench_gather_augment_u8(n_src=300, batch=16)
    lines = [json.loads(l) for l in capsys.readouterr().out.splitlines()]
    assert [l["metric"] for l in lines] == [
        "cifar_parse_native_mb_per_sec", "idx_parse_native_mb_per_sec",
        "gather_augment_native_images_per_sec",
        "gather_augment_native_u8_images_per_sec"]
    assert all(l["value"] > 0 and l["vs_baseline"] > 0 for l in lines)


def test_bench_profile_end_to_end(tiny_mnist, tmp_path, monkeypatch,
                                  capsys):
    """bench_profile.py (the on-chip ResNet attribution harness) runs its
    full pipeline — both augment variants, flops probe, profiler trace,
    roofline, attribution summary — on the virtual mesh, so breakage
    surfaces in CI rather than mid-availability-window on the chip."""
    import bench_profile
    from distributedtensorflowexample_tpu.data import cifar10

    monkeypatch.setattr(cifar10, "_SYNTH_SIZES",
                        {"train": 256, "test": 128})
    monkeypatch.setattr("sys.argv", [
        "bench_profile.py", "--unroll", "2", "--steps", "4",
        "--batch_per_chip", "4", "--roofline_length", "4",
        "--trace_dir", str(tmp_path / "trace")])
    bench_profile.main()
    lines = [json.loads(l) for l in capsys.readouterr().out.splitlines()]
    by_metric = {l["metric"]: l for l in lines}
    assert by_metric["resnet20_profile_augment"]["value"] > 0
    assert by_metric["resnet20_profile_no_augment"]["value"] > 0
    assert by_metric["resnet20_roofline"]["value"] > 0
    aug_detail = by_metric["resnet20_profile_augment"]["detail"]
    assert aug_detail["flops_per_step"]
    # PR-2 bytes attribution rides every variant line: per-op table +
    # the effective (phantom-corrected) bandwidth roofline.
    audit = aug_detail["bytes_audit"]
    assert audit["bytes_effective_per_step"] > 0
    assert audit["phantom_gather_bytes_per_step"] > 0
    assert audit["by_category_per_step"].get("conv", 0) > 0
    assert audit["top_ops"]
    # Effective vs raw compares within the PARSED convention only (the
    # raw bw_roofline key uses XLA's aggregate, which this tiny program
    # undershoots — agreement is size-dependent, see test_bytes.py).
    assert audit["bytes_effective_per_step"] <= audit["bytes_per_step"]
    assert aug_detail["bw_roofline_effective_steps_per_sec"] > 0
    traced = by_metric["resnet20_traced_window"]
    assert traced["value"] > 0 and traced["detail"]["trace_bytes"] > 0
    att = by_metric["resnet20_attribution"]["detail"]
    assert "augment_share" in att and "input_dispatch_share" in att


def test_main_emits_headline_when_backend_unreachable(monkeypatch, capsys):
    """A mid-outage driver run must still print one valid headline line —
    with the sentinel unit "unavailable" so it can never be read as a
    measured 100% regression — pointing at the recorded manual run."""
    from distributedtensorflowexample_tpu import parallel

    def boom(*a, **k):
        raise RuntimeError("UNAVAILABLE: TPU backend setup/compile error")

    monkeypatch.setattr(parallel, "make_mesh", boom)
    bench.main()
    lines = [json.loads(l) for l in capsys.readouterr().out.splitlines()]
    # Line 0 is the always-first provisional sentinel (VERDICT r3 #1a);
    # the real record is the LAST line — the order the driver parses.
    assert len(lines) == 2
    assert lines[0]["detail"]["provisional"] is True
    assert lines[0]["unit"] == "unavailable"
    last = lines[-1]
    assert last["metric"] == "mnist_cnn_sync_steps_per_sec_per_chip"
    assert last["value"] == 0.0
    assert last["unit"] == "unavailable"
    assert "provisional" not in last["detail"]
    assert "UNAVAILABLE" in last["detail"]["error"]
    assert "BENCH_manual_r02" in last["detail"]["see"]
    assert last["detail"]["probe_attempts"]  # skip notice (cpu pin)


def test_main_emits_sentinel_when_backend_dies_mid_run(monkeypatch, capsys):
    """Round-3 failure shape: the up-front probe succeeds, then the tunnel
    dies DURING the run so every sweep point fails.  The headline must be
    the explicit unavailable sentinel (not a measured-looking 0.0), with
    the per-point errors attached for diagnosis."""
    def boom(*a, **k):
        raise RuntimeError("UNAVAILABLE: remote_compile connection refused")

    monkeypatch.setattr(bench, "_make", boom)
    monkeypatch.setattr(bench, "_roofline_probe", boom)
    bench.main()
    lines = [json.loads(l) for l in capsys.readouterr().out.splitlines()]
    # provisional sentinel + ONE final sentinel, no workload lines
    assert len(lines) == 2
    line = lines[-1]
    assert line["metric"] == "mnist_cnn_sync_steps_per_sec_per_chip"
    assert line["unit"] == "unavailable" and line["value"] == 0.0
    assert "every headline sweep point failed" in line["detail"]["error"]
    # The HEADLINE sweep's own per-point errors must survive (sweep_16 is
    # a headline key; resnet's are prefixed resnet_sweep_) alongside the
    # earlier workloads' errors.
    assert "sweep_16" in line["detail"]["errors"]
    assert any(k.startswith("resnet_sweep_") for k in line["detail"]["errors"])


def test_watchdog_fires_on_wedged_measurement():
    """Round-3 failure the probe can't catch: the backend dies minutes
    AFTER a successful probe and the next call blocks >60 min without
    raising.  The watchdog thread must emit the sentinel headline and
    hard-exit 3 (observable only from a real subprocess — os._exit)."""
    import os
    import subprocess
    import sys

    code = (
        "import sys, time\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "import bench\n"
        "bench.TOTAL_BUDGET_S = 1.0\n"
        "bench._make = lambda *a, **k: time.sleep(600)\n"
        "bench._roofline_probe = lambda *a, **k: time.sleep(600)\n"
        "bench.main()\n"
    )
    # FORCE_WATCHDOG: the CPU pin would otherwise (correctly) skip arming.
    env = dict(os.environ, PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
               BENCH_FORCE_WATCHDOG="1")
    p = subprocess.run([sys.executable, "-c", code],
                       cwd=os.path.dirname(os.path.dirname(__file__)),
                       capture_output=True, text=True, timeout=120, env=env)
    assert p.returncode == 3, (p.returncode, p.stdout, p.stderr[-500:])
    last = json.loads(p.stdout.splitlines()[-1])
    assert last["metric"] == "mnist_cnn_sync_steps_per_sec_per_chip"
    assert last["unit"] == "unavailable" and last["value"] == 0.0
    assert "watchdog" in last["detail"]["error"]


def test_headline_promoted_when_first_sweep_point_fails(monkeypatch, capsys):
    """The deepest-unroll point runs first (short-window priority); if it
    fails but a later point succeeds, the later point must be promoted to
    the headline with its own same-window roofline attached."""
    calls = []

    def fake_sweep(unrolls, make_fn, steps_for, err_prefix, errors):
        calls.append(err_prefix)
        if err_prefix != "sweep_":
            return (0.0, None, [], {})            # resnet's sweep: fail
        if len([c for c in calls if c == "sweep_"]) == 1:
            return (0.0, None, [], {})            # deepest point failed
        return (50.0, 4, [50.0], {"4": [50.0]})   # a later point landed

    monkeypatch.setattr(bench, "_sweep", fake_sweep)
    monkeypatch.setattr(bench, "_roofline_probe", lambda *a, **k: [100.0])

    def boom(*a, **k):
        raise RuntimeError("side workload down")
    monkeypatch.setattr(bench, "_make", boom)

    bench.main()
    lines = [json.loads(l) for l in capsys.readouterr().out.splitlines()]
    assert len(lines) == 2       # provisional + headline (sides failed fast)
    line = lines[-1]
    assert line["metric"] == "mnist_cnn_sync_steps_per_sec_per_chip"
    assert line["unit"] == "steps/sec/chip"
    assert line["value"] == round(50.0 / make_mesh().size, 2)
    assert line["detail"]["best_unroll"] == 4
    assert line["detail"]["vs_roofline"] == 0.5
    assert line["detail"]["errors"]      # side-workload failures attached
    assert calls.count("sweep_") == 2    # both headline sweep halves ran


def test_headline_promotion_reprobes_roofline(monkeypatch, capsys):
    """First point succeeds, a later point beats it: the promoted line
    must RE-probe the roofline in its own window (a stale probe from the
    first point's window can make vs_roofline a cross-window artifact,
    even > 1.0)."""
    sweeps, probes = [], []

    def fake_sweep(unrolls, make_fn, steps_for, err_prefix, errors):
        sweeps.append(err_prefix)
        if err_prefix != "sweep_":
            return (0.0, None, [], {})
        if len([c for c in sweeps if c == "sweep_"]) == 1:
            return (40.0, 16, [40.0], {"16": [40.0]})   # first point
        return (50.0, 4, [50.0], {"4": [50.0]})         # later, faster

    def fake_roofline(*a, **k):
        probes.append(1)
        return [80.0] if len(probes) == 1 else [100.0]

    monkeypatch.setattr(bench, "_sweep", fake_sweep)
    monkeypatch.setattr(bench, "_roofline_probe", fake_roofline)

    def boom(*a, **k):
        raise RuntimeError("side workload down")
    monkeypatch.setattr(bench, "_make", boom)

    bench.main()
    lines = [json.loads(l) for l in capsys.readouterr().out.splitlines()]
    assert len(lines) == 2
    line = lines[-1]
    assert line["value"] == round(50.0 / make_mesh().size, 2)
    assert line["detail"]["best_unroll"] == 4
    # Fresh probe (100.0), not the first window's 80.0: 50/100 = 0.5.
    assert line["detail"]["roofline_probe"] == [100.0]
    assert line["detail"]["vs_roofline"] == 0.5
    assert len(probes) == 2


def test_watchdog_emits_held_headline_when_side_workload_wedges():
    """The headline is measured first and held; if a LATER side workload
    wedges, the watchdog must emit the real measured headline (tagged
    with detail.watchdog), never discard it for the 0.0 sentinel."""
    import os
    import subprocess
    import sys

    code = (
        "import sys, time\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "import bench\n"
        "bench.TOTAL_BUDGET_S = 8.0\n"   # > make_mesh+fakes, << sleep(600)
        "bench._sweep = lambda *a, **k: (100.0, 16, [100.0],"
        " {'16': [100.0]})\n"
        "bench._roofline_probe = lambda *a, **k: [200.0]\n"
        "bench._make = lambda *a, **k: time.sleep(600)\n"
        "bench.main()\n"
    )
    env = _bench_subprocess_env(BENCH_FORCE_WATCHDOG="1")
    p = subprocess.run([sys.executable, "-c", code],
                       cwd=os.path.dirname(os.path.dirname(__file__)),
                       capture_output=True, text=True, timeout=120, env=env)
    assert p.returncode == 3, (p.returncode, p.stdout, p.stderr[-500:])
    last = json.loads(p.stdout.splitlines()[-1])
    assert last["metric"] == "mnist_cnn_sync_steps_per_sec_per_chip"
    assert last["unit"] == "steps/sec/chip" and last["value"] == 100.0
    assert "watchdog" in last["detail"]
    assert last["detail"]["vs_roofline"] == 0.5


def test_watchdog_disarmed_on_completion():
    """A normal completion sets the event before the budget expires; the
    armed thread must not fire afterwards (no spurious sentinel).  The
    exit is injected so a regression can't take down the test process."""
    import time
    fired, exits = [], []
    done = bench._arm_watchdog(0.2, lambda: fired.append(1),
                               _exit=lambda code: exits.append(code))
    done.set()
    time.sleep(0.4)
    assert not fired and not exits

    fired2, exits2 = [], []
    bench._arm_watchdog(0.05, lambda: fired2.append(1),
                        _exit=lambda code: exits2.append(code))
    time.sleep(0.3)
    assert fired2 == [1] and exits2 == [3]


def _bench_subprocess_env(**extra):
    """Env for a real bench.main() subprocess: CPU-pinned, with any
    device-count pin inherited from THIS pytest process stripped.  On
    jax versions without the ``jax_num_cpu_devices`` config, conftest's
    compat shim exports ``--xla_force_host_platform_device_count=8``
    into ``XLA_FLAGS``, which a child would inherit — but these tests
    model the driver's clean shell, where bench sees ONE cpu device
    (the per-chip division then leaves the mocked rates unscaled)."""
    import os

    env = dict(os.environ, PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
               **extra)
    flags = [t for t in env.get("XLA_FLAGS", "").split()
             if not t.startswith("--xla_force_host_platform_device_count")]
    env["XLA_FLAGS"] = " ".join(flags)
    return env


def _spawn_bench(extra_code: str):
    """Run the REAL bench.main() in a subprocess (CPU-pinned via
    jax.config, like the other subprocess tests) with ``extra_code``
    applied between import and main().  Pipes kept open for
    deterministic kill timing."""
    import os
    import subprocess
    import sys

    code = ("import sys, time\n"
            "import jax\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            "import bench\n" + extra_code + "bench.main()\n")
    env = _bench_subprocess_env()
    return subprocess.Popen(
        [sys.executable, "-c", code],
        cwd=os.path.dirname(os.path.dirname(__file__)),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)


def test_sigterm_mid_probe_retry_still_leaves_parseable_record():
    """THE round-3 official-record killer (VERDICT r3 #1): the driver's
    outer `timeout` TERM/KILLed bench while it slept in the probe-retry
    loop with nothing yet on stdout (BENCH_r03.json: rc=124, parsed
    null).  Same kill mechanism (SIGTERM to the process), deterministic
    timing: TERM lands after the provisional line, which mirrors the
    driver (its ~23-min budget dwarfs startup).  Captured stdout must
    parse — provisional line first, SIGTERM sentinel last, rc=143."""
    import signal as sig
    import time

    p = _spawn_bench(
        "bench._cpu_pinned = lambda: False\n"   # enter the real retry loop
        "bench._probe_backend = "
        "lambda timeout_s=None: (False, 'down (test)')\n"
        "bench.PROBE_TIMEOUT_S = 0.0\n"
        "bench.RETRY_INTERVAL_S = 600.0\n"      # guarantee death mid-sleep
        "bench.RETRY_BUDGET_S = 3600.0\n")
    first = p.stdout.readline()          # blocks until the provisional line
    assert json.loads(first)["detail"]["provisional"] is True
    time.sleep(1.0)                      # probe fails instantly -> sleeping
    p.send_signal(sig.SIGTERM)
    out, err = p.communicate(timeout=60)
    assert p.returncode == 143, (p.returncode, out, err[-500:])
    # The handler prints a blank guard line first (torn-line terminator).
    lines = [json.loads(l) for l in ([first] + out.splitlines())
             if l.strip()]
    last = lines[-1]
    assert last["metric"] == "mnist_cnn_sync_steps_per_sec_per_chip"
    assert last["unit"] == "unavailable" and last["value"] == 0.0
    assert "sigterm" in last["detail"]["error"]
    # The failed probe attempt made it into the record.
    assert any("down (test)" in a for a in last["detail"]["probe_attempts"])


def test_sigkill_leaves_provisional_record():
    """Survival layer 1 alone: a straight SIGKILL (no handler can run)
    must still leave a parseable stdout, because the provisional
    sentinel is flushed before any backend touch."""
    p = _spawn_bench(
        "bench._cpu_pinned = lambda: False\n"
        "bench._probe_backend = "
        "lambda timeout_s=None: (time.sleep(600), (False, 'x'))[1]\n")
    first = p.stdout.readline()
    p.kill()
    out, _ = p.communicate(timeout=60)
    assert p.returncode == -9
    line = json.loads(first)
    assert line["metric"] == "mnist_cnn_sync_steps_per_sec_per_chip"
    assert line["unit"] == "unavailable" and line["value"] == 0.0
    assert line["detail"]["provisional"] is True


def test_sigterm_emits_held_measured_headline():
    """A kill AFTER the headline measured but before the normal emit
    must put the MEASURED line on stdout (tagged detail.sigterm), never
    discard it for the sentinel — the driver's timeout can land during
    any side workload."""
    import signal as sig

    p = _spawn_bench(
        "bench._sweep = lambda *a, **k: "
        "(100.0, 16, [100.0], {'16': [100.0]})\n"
        "bench._roofline_probe = lambda *a, **k: [200.0]\n"
        "def _wedge(*a, **k):\n"
        "    print('WEDGED', file=sys.stderr, flush=True)\n"
        "    time.sleep(600)\n"
        "bench._make = _wedge\n")
    first = p.stdout.readline()          # provisional
    assert json.loads(first)["detail"]["provisional"] is True
    line = ""
    for _ in range(500):                 # skip jax warnings on stderr
        line = p.stderr.readline()
        if not line or "WEDGED" in line:
            break
    assert "WEDGED" in line              # headline held, side wedged
    p.send_signal(sig.SIGTERM)
    out, err = p.communicate(timeout=60)
    assert p.returncode == 143, (p.returncode, out, err[-500:])
    last = json.loads(out.splitlines()[-1])
    assert last["metric"] == "mnist_cnn_sync_steps_per_sec_per_chip"
    assert last["unit"] == "steps/sec/chip" and last["value"] == 100.0
    assert "sigterm" in last["detail"]
    assert last["detail"]["vs_roofline"] == 0.5


def test_probe_skipped_when_cpu_pinned():
    """The CPU-pinned test process must never spawn an axon-init
    subprocess (conftest pins via jax.config, not JAX_PLATFORMS)."""
    assert bench._cpu_pinned()
    ok, attempts = bench._wait_for_backend()
    assert ok and "skipped" in attempts[0]


def test_probe_backend_subprocess(monkeypatch):
    """_probe_backend runs real code in a real subprocess with a hard
    timeout; exercise success, failure, and timeout via the probe code."""
    monkeypatch.setattr(bench, "_PROBE_CODE", "print('PROBE_OK 1')")
    ok, info = bench._probe_backend(timeout_s=30)
    assert ok and "PROBE_OK" in info

    monkeypatch.setattr(bench, "_PROBE_CODE",
                        "raise RuntimeError('UNAVAILABLE: down')")
    ok, info = bench._probe_backend(timeout_s=30)
    assert not ok and "UNAVAILABLE" in info

    monkeypatch.setattr(bench, "_PROBE_CODE", "import time; time.sleep(60)")
    ok, info = bench._probe_backend(timeout_s=1)
    assert not ok and "timed out" in info


def test_wait_for_backend_retries_within_budget(monkeypatch):
    """Failure path: retries on the interval, gives up inside the budget,
    and returns the attempt log; success path: returns on first OK."""
    monkeypatch.setattr(bench, "_cpu_pinned", lambda: False)
    monkeypatch.setattr(bench, "RETRY_BUDGET_S", 10.0)
    monkeypatch.setattr(bench, "RETRY_INTERVAL_S", 0.01)
    monkeypatch.setattr(bench, "PROBE_TIMEOUT_S", 0.01)
    calls = []

    def probe(timeout_s=None):
        calls.append(1)
        return (len(calls) >= 3), f"attempt {len(calls)}"
    monkeypatch.setattr(bench, "_probe_backend", probe)
    ok, attempts = bench._wait_for_backend()
    assert ok and len(calls) == 3 and len(attempts) == 3

    calls.clear()
    monkeypatch.setattr(bench, "_probe_backend",
                        lambda timeout_s=None: (False, "down"))
    monkeypatch.setattr(bench, "RETRY_BUDGET_S", 0.05)
    ok, attempts = bench._wait_for_backend()
    assert not ok and attempts


def test_collective_traffic_parsing():
    hlo = """
  %x = f32[256,10]{1,0} all-reduce(f32[256,10]{1,0} %a), replica_groups={}
  %y = (f32[64]{0}, bf16[128]{0}) all-reduce(%b, %c), channel_id=1
  %z = f32[8,4]{1,0} all-gather(f32[8,2]{1,0} %d), dimensions={1}
  %notacollective = f32[2]{0} add(f32[2]{0} %e, f32[2]{0} %f)
"""
    out = bench_scaling.collective_traffic(hlo)
    assert out["all-reduce"]["count"] == 2
    assert out["all-reduce"]["bytes"] == 256 * 10 * 4 + 64 * 4 + 128 * 2
    assert out["all-gather"]["count"] == 1
    assert out["all-gather"]["bytes"] == 8 * 4 * 4
    assert "collective-permute" not in out


def test_headline_only_mode(monkeypatch, capsys):
    """BENCH_HEADLINE_ONLY=1 (capture phase 1): the contract metric +
    same-window roofline only — one sweep half, no side workloads — so
    a short recovery window spends its first minutes on the headline
    and the never-yet-captured ResNet profile, not the full run."""
    calls = []

    def fake_sweep(unrolls, make_fn, steps_for, err_prefix, errors):
        calls.append(err_prefix)
        return (50.0, 16, [50.0], {"16": [50.0]})

    def boom(*a, **k):
        raise AssertionError("side workload must not run in headline-only")

    monkeypatch.setattr(bench, "HEADLINE_ONLY", True)
    monkeypatch.setattr(bench, "_sweep", fake_sweep)
    monkeypatch.setattr(bench, "_roofline_probe", lambda *a, **k: [100.0])
    monkeypatch.setattr(bench, "_make", boom)
    bench.main()
    lines = [json.loads(l) for l in capsys.readouterr().out.splitlines()]
    assert len(lines) == 2          # provisional + headline, nothing else
    line = lines[-1]
    assert line["metric"] == "mnist_cnn_sync_steps_per_sec_per_chip"
    assert line["unit"] == "steps/sec/chip"
    assert line["detail"]["headline_only"] is True
    assert line["detail"]["vs_roofline"] == 0.5
    assert "errors" not in line["detail"]   # no side workload ever ran
    assert calls == ["sweep_"]              # exactly one sweep half
