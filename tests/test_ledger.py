"""obs/ledger.py + obs/serve.py + tools/obs_query.py (ISSUE 10
tentpole): the cross-run ledger's append/torn-tail/rotation semantics
and row-schema goldens, the live HTTP scrape surface against a real
serving thread (including the fleet's HTTP-scrape-with-file-fallback
monitor path), obs_query list/show/diff/trajectory CLI smokes, the
bench_ratchet --trajectory artifact, obs_report's --ledger section,
the whole-package stdlib-only import guard, and the overhead guard
keeping ledger sampling + serve idle cost under the MetricsHook budget
(< 1% of the CPU bench step).

Deliberately INLINE (not in tests/isolation_list.py): single-device,
no collectives — these verdicts must land ahead of the isolated
wrappers inside the tier-1 budget.
"""

import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from distributedtensorflowexample_tpu.obs import ledger as obs_ledger
from distributedtensorflowexample_tpu.obs import metrics as obs_metrics
from distributedtensorflowexample_tpu.obs import recorder as obs_recorder
from distributedtensorflowexample_tpu.obs import serve as obs_serve

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")

pytestmark = [pytest.mark.ledger, pytest.mark.obs]


def _fetch(url: str, timeout: float = 5.0) -> tuple[int, bytes]:
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


@pytest.fixture()
def fresh_registry(monkeypatch):
    """An isolated registry so cross-test counter state can't leak into
    snapshots/deltas under assertion."""
    reg = obs_metrics.MetricsRegistry()
    return reg


# --- ledger: append / read / schema ----------------------------------------

def test_ledger_roundtrip_and_row_schema_golden(tmp_path, fresh_registry,
                                                monkeypatch):
    """The three row kinds carry exactly the documented fields — the
    schema golden obs_query and every future reader rely on."""
    monkeypatch.setattr(obs_metrics, "_wall", lambda: 1700000000.0)
    monkeypatch.setattr(obs_metrics, "_now", lambda: 50.0)
    monkeypatch.setenv("OBS_RANK", "1")
    monkeypatch.setenv("SUPERVISE_ATTEMPT", "2")
    path = str(tmp_path / "RUNS.jsonl")
    led = obs_ledger.RunLedger(path, sample_min_s=0,
                               registry=fresh_registry)
    assert led.run_id.endswith("-r1-a2")
    led.start("trainer:softmax", config={"seed": 0, "train_steps": 8},
              platform="cpu", mesh_size=4)
    fresh_registry.counter("train_steps_total").inc(5)
    assert led.sample(step=5)
    led.end(rc=0, final_step=8)
    rows, torn = obs_ledger.read_rows(path)
    assert torn == 0
    start, sample, end = rows
    assert set(start) == {"v", "ts", "event", "run", "entrypoint",
                          "config", "config_digest", "pid", "argv",
                          "rank", "attempt", "phase", "platform",
                          "mesh_size"}
    assert start["event"] == "run_start"
    assert start["rank"] == 1 and start["attempt"] == 2
    assert start["config_digest"] == obs_ledger.config_digest(
        {"seed": 0, "train_steps": 8})
    assert set(sample) == {"v", "ts", "event", "run", "step", "delta"}
    assert sample["delta"]["counters"] == {"train_steps_total": 5}
    assert set(end) == {"v", "ts", "event", "run", "rc", "final_step",
                        "loss_tail", "anomaly_flags", "flight",
                        "counters", "samples"}
    assert end["rc"] == 0 and end["final_step"] == 8
    assert end["counters"]["train_steps_total"] == 5
    assert {r["run"] for r in rows} == {led.run_id}
    # end() is idempotent: a second call (the atexit safety) is a no-op.
    led.end(rc=1)
    assert len(obs_ledger.read_rows(path)[0]) == 3


def test_ledger_heals_torn_tail_and_reader_skips(tmp_path,
                                                 fresh_registry):
    path = str(tmp_path / "RUNS.jsonl")
    led = obs_ledger.RunLedger(path, sample_min_s=0,
                               registry=fresh_registry)
    led.start("a")
    # A row that died mid-write: no trailing newline.
    with open(path, "a") as f:
        f.write('{"event": "run_end", "run": "torn-vic')
    led.sample(step=1, force=True)
    rows, torn = obs_ledger.read_rows(path)
    # The fragment is skipped AND the live sample row survived intact —
    # healing prepended the newline before appending.
    assert torn == 1
    assert [r["event"] for r in rows] == ["run_start", "sample"]


def test_ledger_rotation_and_cross_file_read(tmp_path, fresh_registry,
                                             monkeypatch):
    monkeypatch.setenv("OBS_LEDGER_MAX_BYTES", "2000")
    path = str(tmp_path / "RUNS.jsonl")
    led = obs_ledger.RunLedger(path, sample_min_s=0,
                               registry=fresh_registry)
    led.start("rotates")
    # Sample until the size bound trips ONE rotation, then a few more
    # rows into the fresh live file.
    n = 0
    while not os.path.exists(path + ".1"):
        led.sample(step=n, force=True)
        n += 1
        assert n < 200, "rotation never triggered"
    for _ in range(3):
        led.sample(step=n, force=True)
        n += 1
    led.end(rc=0, final_step=n)
    # The reader spans the rotation edge: run_start (rotated out) and
    # run_end (live file) fold back into ONE run with every sample.
    folded = obs_ledger.runs(path)
    assert folded["order"] == [led.run_id]
    group = folded["runs"][led.run_id]
    assert group["start"] is not None and group["end"] is not None
    assert len(group["samples"]) == n
    # Without the rotated file only the live half remains.
    live_rows, _ = obs_ledger.read_rows(path, include_rotated=False)
    assert 0 < len(live_rows) < n + 2


def test_ledger_sampling_is_time_bounded(tmp_path, fresh_registry):
    path = str(tmp_path / "RUNS.jsonl")
    led = obs_ledger.RunLedger(path, sample_min_s=3600,
                               registry=fresh_registry)
    led.start("bounded")
    assert led.sample(step=1)           # first always lands
    for step in range(2, 50):
        assert not led.sample(step=step)    # inside the bound: skipped
    assert led.sample(step=99, force=True)
    rows, _ = obs_ledger.read_rows(path)
    assert [r.get("step") for r in rows
            if r["event"] == "sample"] == [1, 99]


def test_maybe_begin_env_gate_and_log_event(tmp_path, monkeypatch):
    monkeypatch.delenv("OBS_LEDGER", raising=False)
    monkeypatch.setattr(obs_ledger, "_GLOBAL", None)
    assert obs_ledger.maybe_begin("gated") is None
    obs_ledger.log_event("resume_agreement", agreed=4)     # no-op
    path = str(tmp_path / "RUNS.jsonl")
    monkeypatch.setenv("OBS_LEDGER", path)
    led = obs_ledger.maybe_begin("gated", config={"x": 1})
    assert led is not None
    assert obs_ledger.maybe_begin("other") is led          # idempotent
    obs_ledger.log_event("resume_agreement", agreed=4,
                         per_rank={"0": [4], "1": [4]})
    obs_ledger.end_global(rc=0)
    monkeypatch.setattr(obs_ledger, "_GLOBAL", None)
    folded = obs_ledger.runs(path)
    assert [e["event"] for e in folded["events"]] == ["resume_agreement"]
    table = obs_ledger.run_table(path)
    assert len(table) == 1 and table[0]["outcome"] == "ok"


def test_run_table_outcome_classes(tmp_path):
    path = str(tmp_path / "RUNS.jsonl")
    for run, rc in (("r-ok", 0), ("r-preempt", 143), ("r-crash", 7),
                    ("r-unreported", None)):
        obs_ledger.log_event("run_start", path=path, run=run,
                             entrypoint="t")
        obs_ledger.log_event("run_end", path=path, run=run, rc=rc)
    obs_ledger.log_event("run_start", path=path, run="r-live",
                         entrypoint="t")
    table = {r["run"]: r["outcome"] for r in obs_ledger.run_table(path)}
    assert table == {"r-ok": "ok", "r-preempt": "preempted",
                     "r-crash": "rc=7", "r-unreported": "unreported",
                     "r-live": "running/lost"}


def test_tail_rows_reads_a_bounded_chunk(tmp_path):
    """The /ledger/tail handler runs inside the observed process: it
    must read a bounded tail chunk, drop the (almost surely partial)
    first line of a mid-file seek, and still return the last n rows."""
    path = str(tmp_path / "RUNS.jsonl")
    with open(path, "w") as f:
        for i in range(200):
            f.write(json.dumps({"event": "sample", "run": "r",
                                "step": i, "pad": "x" * 64}) + "\n")
    rows, torn = obs_ledger.tail_rows(path, 5, max_bytes=1024)
    assert torn == 0
    assert [r["step"] for r in rows] == [195, 196, 197, 198, 199]
    # Small file, no seek: nothing dropped.
    rows, _ = obs_ledger.tail_rows(path, 500, max_bytes=10**7)
    assert len(rows) == 200
    assert obs_ledger.tail_rows(str(tmp_path / "missing"), 5) == ([], 0)


# --- serve: endpoint smokes against a live thread --------------------------

def test_serve_endpoints_smoke(tmp_path, monkeypatch):
    path = str(tmp_path / "RUNS.jsonl")
    obs_ledger.log_event("run_start", path=path, run="r1",
                         entrypoint="serve-smoke")
    monkeypatch.setenv("OBS_LEDGER", path)
    monkeypatch.setattr(obs_serve, "_health_source",
                        lambda: {"version": 1, "kind": "rank", "step": 7})
    rec = obs_recorder.FlightRecorder()
    rec.record_loss(3, 0.5)
    monkeypatch.setattr(obs_recorder, "_GLOBAL", rec)
    server = obs_serve.ObsServer(0).start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        code, body = _fetch(f"{base}/metrics")
        assert code == 200
        text = body.decode()
        assert "# TYPE anomaly_flags_total counter" in text
        code, body = _fetch(f"{base}/health")
        assert code == 200
        assert json.loads(body) == {"version": 1, "kind": "rank",
                                    "step": 7}
        code, body = _fetch(f"{base}/flight")
        assert code == 200
        flight = json.loads(body)
        assert flight["reason"] == "http"
        assert flight["loss_tail"] == [[3, 0.5]]
        code, body = _fetch(f"{base}/ledger/tail?n=5")
        assert code == 200
        tail = json.loads(body)
        assert [r["event"] for r in tail["rows"]] == ["run_start"]
        code, body = _fetch(f"{base}/nope")
        assert code == 404
        assert "/metrics" in json.loads(body)["paths"]
    finally:
        server.stop()


def test_serve_health_falls_back_to_file_then_503(tmp_path, monkeypatch):
    monkeypatch.setattr(obs_serve, "_health_source", None)
    hp = tmp_path / "health.json"
    hp.write_text(json.dumps({"version": 1, "step": 3}))
    monkeypatch.setenv("OBS_HEALTH", str(hp))
    server = obs_serve.ObsServer(0).start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        code, body = _fetch(f"{base}/health")
        assert code == 200 and json.loads(body)["step"] == 3
        monkeypatch.delenv("OBS_HEALTH")
        code, body = _fetch(f"{base}/health")
        assert code == 503 and "no health source" in json.loads(
            body)["error"]
    finally:
        server.stop()


def test_serve_maybe_start_env_gate(monkeypatch, capsys):
    monkeypatch.setattr(obs_serve, "_GLOBAL", None)
    monkeypatch.delenv("OBS_HTTP_PORT", raising=False)
    assert obs_serve.maybe_start() is None
    monkeypatch.setenv("OBS_HTTP_PORT", "notaport")
    assert obs_serve.maybe_start() is None
    assert "not a port" in capsys.readouterr().err
    monkeypatch.setenv("OBS_HTTP_PORT", "0")
    assert obs_serve.maybe_start() is None      # 0/neg = explicit off
    # Out-of-range port: socket.bind raises OverflowError (NOT an
    # OSError) — the refusal must still be a stderr note, never a raise.
    monkeypatch.setenv("OBS_HTTP_PORT", "70000")
    assert obs_serve.maybe_start() is None
    assert "out of range" in capsys.readouterr().err
    monkeypatch.setattr(obs_serve, "_GLOBAL", None)


# --- fleet monitor: HTTP scrape with file fallback -------------------------

@pytest.mark.fleet
def test_fleet_health_scrape_prefers_http_falls_back_to_file(tmp_path,
                                                             monkeypatch):
    """The monitor's transport choice: a rank with a live endpoint is
    scraped over HTTP (journaled mode=http), a rank whose server is
    gone degrades to the per-rank file (journaled mode=file) — the
    detection pass never goes dark because a port died."""
    from distributedtensorflowexample_tpu.obs import anomaly as obs_anomaly
    from distributedtensorflowexample_tpu.resilience.fleet import (
        FleetSupervisor)
    from distributedtensorflowexample_tpu.resilience.supervisor import (
        Journal)
    monkeypatch.setattr(obs_serve, "_health_source",
                        lambda: {"version": 1, "kind": "rank", "rank": 0,
                                 "step": 9, "via": "http"})
    server = obs_serve.ObsServer(0).start()
    try:
        journal_path = str(tmp_path / "fleet.jsonl")
        fleet = FleetSupervisor(
            2, journal=Journal(journal_path),
            workdir=str(tmp_path / "wd"), http=True, seed=0)
        # Rank 0's endpoint is the live server; rank 1's port has no
        # listener (freshly picked free port, nothing bound).
        fleet._http_ports[0] = server.port
        fleet._scrape_logged = set()
        obs_anomaly.write_health(
            fleet._health_path(1),
            {"version": 1, "kind": "rank", "rank": 1, "step": 4,
             "via": "file"})
        p0 = fleet._read_rank_health(0, "drill", 0)
        p1 = fleet._read_rank_health(1, "drill", 0)
        assert p0["via"] == "http" and p0["step"] == 9
        assert p1["via"] == "file" and p1["step"] == 4
        # Second read: journal events stay once-per-(rank, mode).
        fleet._read_rank_health(0, "drill", 0)
        # The failed endpoint earned a backoff (serial urlopens must
        # not stall the monitor loop on a wedged rank every pass);
        # the healthy one did not.
        assert 1 in fleet._http_backoff and 0 not in fleet._http_backoff
        with open(journal_path) as f:
            scrapes = [json.loads(line) for line in f
                       if '"health_scrape"' in line]
        assert [(s["rank"], s["mode"]) for s in scrapes] == [
            (0, "http"), (1, "file")]
        assert scrapes[0]["port"] == server.port
    finally:
        server.stop()


def test_fleet_exports_ledger_and_http_port(tmp_path, monkeypatch):
    """The spawn env surface: children inherit OBS_LEDGER (workdir
    default) and, under http=True, a per-rank OBS_HTTP_PORT — the
    contract the live drill scrapes against."""
    monkeypatch.delenv("OBS_LEDGER", raising=False)
    from distributedtensorflowexample_tpu.resilience.fleet import (
        FleetSupervisor)
    captured = {}
    import subprocess as sp
    real_popen = sp.Popen

    def fake_popen(argv, env=None, **kw):
        captured["env"] = env
        return real_popen([sys.executable, "-c", "pass"], env=env, **kw)

    fleet = FleetSupervisor(1, workdir=str(tmp_path / "wd"), http=True,
                            seed=0)
    import unittest.mock as mock
    with mock.patch.object(sp, "Popen", fake_popen):
        proc = fleet._spawn_rank(0, 0, ["127.0.0.1:1"], ["true"],
                                 "t", 0, None, None, None)
    proc.wait()
    env = captured["env"]
    assert env["OBS_LEDGER"] == os.path.join(str(tmp_path / "wd"),
                                             "RUNS.jsonl")
    assert int(env["OBS_HTTP_PORT"]) == fleet._http_ports[0]


def test_fleet_ledger_dest_follows_env_and_none_disables(tmp_path,
                                                         monkeypatch):
    """One drill, ONE file: a box-wide OBS_LEDGER export routes the
    fleet's gang rows to the same ledger the ranks inherit (not the
    workdir default), and a disabled ledger writes nothing — the env
    fallback inside log_event must not resurrect it."""
    from distributedtensorflowexample_tpu.resilience.fleet import (
        FleetSupervisor)
    box = str(tmp_path / "box_RUNS.jsonl")
    monkeypatch.setenv("OBS_LEDGER", box)
    fleet = FleetSupervisor(1, workdir=str(tmp_path / "wd"), seed=0)
    assert fleet._ledger_dest() == box
    fleet._ledger_event("run_start", run="gang:t:a0", entrypoint="t")
    rows, _ = obs_ledger.read_rows(box)
    assert rows and rows[0]["src"] == "fleet"
    assert not os.path.exists(os.path.join(str(tmp_path / "wd"),
                                           "RUNS.jsonl"))
    # A PRESENT-but-empty export means "disabled" to the children
    # (setdefault skips a present key, maybe_begin treats "" as off) —
    # the fleet must read it the same way, not fall to its default.
    monkeypatch.setenv("OBS_LEDGER", "")
    assert fleet._ledger_dest() == ""
    monkeypatch.delenv("OBS_LEDGER")
    off = FleetSupervisor(1, workdir=str(tmp_path / "wd2"),
                          ledger_path="", seed=0)
    assert off._ledger_dest() == ""
    off._ledger_event("run_start", run="gang:t:a0")
    assert not os.path.exists(os.path.join(str(tmp_path / "wd2"),
                                           "RUNS.jsonl"))


# --- obs_query CLI ---------------------------------------------------------

def _obs_query(*argv):
    out = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "obs_query.py"), *argv],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    return out.stdout


def _two_run_ledger(path: str) -> tuple[str, str]:
    for run, cfg, rc, digest in (
            ("aaa1-1", {"seed": 0, "lr": 0.1}, 0, "d1"),
            ("bbb2-2", {"seed": 1, "lr": 0.1}, 143, "d2")):
        obs_ledger.log_event("run_start", path=path, run=run,
                             entrypoint="trainer:softmax", config=cfg,
                             config_digest=obs_ledger.config_digest(cfg))
        obs_ledger.log_event(
            "run_end", path=path, run=run, rc=rc, final_step=8,
            counters={"train_steps_total": 8 if rc == 0 else 5},
            loss_tail={"n": 3, "last": [8, 0.1], "sha256": digest})
    obs_ledger.log_event("resume_agreement", path=path, agreed=4,
                         per_rank={"0": [2, 4], "1": [4]},
                         discarded={"0": [], "1": [6]})
    return "aaa1-1", "bbb2-2"


def test_obs_query_list_show_diff_smoke(tmp_path):
    path = str(tmp_path / "RUNS.jsonl")
    run_a, run_b = _two_run_ledger(path)
    text = _obs_query("list", "--ledger", path)
    assert "trainer:softmax" in text and "preempted" in text
    assert "agreed step **4**" in text
    payload = json.loads(_obs_query("list", "--ledger", path,
                                    "--format", "json"))
    assert [r["run"] for r in payload["runs"]] == [run_a, run_b]
    assert payload["agreements"][0]["agreed"] == 4
    # outcome filter
    payload = json.loads(_obs_query("list", "--ledger", path,
                                    "--outcome", "ok",
                                    "--format", "json"))
    assert [r["run"] for r in payload["runs"]] == [run_a]
    # show by unique prefix
    text = _obs_query("show", "--ledger", path, "aaa")
    assert "run_start" in text and "run_end" in text
    # diff: config + counter deltas + trajectory verdict
    diff = json.loads(_obs_query("diff", "--ledger", path, "aaa", "bbb",
                                 "--format", "json"))
    assert diff["config_diff"] == {"seed": {"a": 0, "b": 1}}
    assert diff["counter_deltas"]["train_steps_total"]["delta"] == -3
    assert diff["outcome"]["b"]["rc"] == 143
    assert diff["loss_tail"]["same_trajectory"] is False
    md = _obs_query("diff", "--ledger", path, "aaa", "bbb")
    assert "| seed | 0 | 1 |" in md


def test_obs_query_trajectory_smoke(tmp_path):
    rec_dir = tmp_path / "records"
    rec_dir.mkdir()
    for rnd, value in ((1, 100.0), (2, 140.0)):
        (rec_dir / f"BENCH_fam_r{rnd:02d}.json").write_text(json.dumps({
            "metric": "fam_steps_per_sec", "value": value,
            "unit": "steps/sec/chip", "detail": {"platform": "cpu"}})
            + "\n")
    payload = json.loads(_obs_query("trajectory", "--records_dir",
                                    str(rec_dir), "--format", "json"))
    assert [(r["family"], r["round"]) for r in payload] == [
        ("BENCH_fam", 1), ("BENCH_fam", 2)]
    assert payload[1]["metrics"] == {"fam_steps_per_sec": 140.0}
    md = _obs_query("trajectory", "--records_dir", str(rec_dir))
    assert "## BENCH_fam r02" in md


# --- bench_ratchet --trajectory artifact -----------------------------------

def test_bench_ratchet_trajectory_rows_and_checked_in_artifact(tmp_path):
    sys.path.insert(0, TOOLS)
    try:
        import bench_ratchet
    finally:
        sys.path.remove(TOOLS)
    rec_dir = tmp_path / "records"
    rec_dir.mkdir()
    (rec_dir / "BENCH_x_r01.json").write_text(
        json.dumps({"metric": "m", "value": 1.0, "unit": "u",
                    "detail": {"platform": "cpu"}}) + "\n"
        # provisional lines never enter the trajectory
        + json.dumps({"metric": "m2", "value": 0.0,
                      "unit": "unavailable", "detail": {}}) + "\n")
    # A pretty-printed SINGLE-JSON record file (bench_collectives'
    # indent=1 shape): per-line parsing yields nothing, and the family
    # must NOT silently vanish from the trajectory/ratchet.
    (rec_dir / "BENCH_coll_r02.json").write_text(json.dumps(
        {"metric": "knee_bytes", "value": 244160.0, "unit": "bytes",
         "detail": {"platform": "cpu"}}, indent=1) + "\n")
    (rec_dir / "SCALING_r01_sync.json").write_text(
        json.dumps({"devices": 2, "steps_per_sec": 3.5}) + "\n")
    (rec_dir / "BASELINE_SELF.json").write_text(
        json.dumps({"note": "text ignored", "m": 2.0}))
    out = rec_dir / "BENCH_trajectory.json"
    n = bench_ratchet.write_trajectory(str(rec_dir), str(out))
    rows = [json.loads(line) for line in
            out.read_text().splitlines()]
    assert n == len(rows) == 4
    by_family = {r["family"]: r for r in rows}
    assert by_family["BENCH_x"]["metrics"] == {"m": 1.0}
    assert by_family["BENCH_coll"]["metrics"] == {"knee_bytes": 244160.0}
    assert by_family["SCALING_sync"]["metrics"] == {
        "2dev_steps_per_sec": 3.5}
    assert by_family["BASELINE_SELF"]["metrics"] == {"m": 2.0}
    assert by_family["BASELINE_SELF"]["round"] is None
    # Regeneration is deterministic AND the artifact is never its own
    # source (a second build over a dir already holding the output
    # produces identical rows).
    assert bench_ratchet.write_trajectory(str(rec_dir), str(out)) == 4
    assert [json.loads(line) for line in
            out.read_text().splitlines()] == rows
    # The checked-in repo artifact matches a regeneration from the
    # checked-in records — the "canonical view" claim, kept honest:
    # adding a record file means re-running bench_ratchet --trajectory.
    repo_rows = bench_ratchet.build_trajectory(REPO)
    with open(os.path.join(REPO, "BENCH_trajectory.json")) as f:
        checked_in = [json.loads(line) for line in f.read().splitlines()]
    assert checked_in == repo_rows


def test_bench_ratchet_recognizes_zero3_lm_rows():
    """PR 12: the zero3 bench rows ride the lm family like any other —
    the checked-in BENCH_lm_cpu_r12.json parses into metric records
    (the residency-shrink line and the overlap wall-clock pair), and
    the regenerated trajectory's lm r12 row carries them, so the
    ratchet compares them across rounds exactly like the r08 columns
    (the byte-identical-regeneration gate above covers determinism)."""
    sys.path.insert(0, TOOLS)
    try:
        import bench_ratchet
    finally:
        sys.path.remove(TOOLS)
    recs = bench_ratchet.load_records(
        [os.path.join(REPO, "BENCH_lm_cpu_r12.json")])
    metrics = {r["metric"]: r for r in recs}
    assert "lm_base_zero3_state_residency_shrink_x" in metrics
    assert "lm_base_zero3_overlap_speedup_x" in metrics
    shrink = metrics["lm_base_zero3_state_residency_shrink_x"]
    assert shrink["value"] == 4.0          # 1/D at D=4, measured
    assert shrink["detail"]["state_bytes_per_device_zero3"] * 4 == \
        shrink["detail"]["state_bytes_per_device_base"]
    row = next(r for r in bench_ratchet.build_trajectory(REPO)
               if r["family"] == "BENCH_lm_cpu" and r["round"] == 12)
    assert "lm_base_zero3_state_residency_shrink_x" in row["metrics"]
    assert "lm_base_zero3_overlap_speedup_x" in row["metrics"]


# --- obs_report --ledger ----------------------------------------------------

def test_obs_report_renders_ledger_section(tmp_path):
    path = str(tmp_path / "RUNS.jsonl")
    _two_run_ledger(path)
    out = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "obs_report.py"),
         "--ledger", path],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "## Run ledger" in out.stdout
    assert "trainer:softmax" in out.stdout
    assert "resume agreement" in out.stdout
    # Missing ledger renders a note, never a crash (mid-outage rule).
    out = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "obs_report.py"),
         "--ledger", str(tmp_path / "missing.jsonl")],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "does not exist" in out.stdout


# --- whole-package stdlib-only import guard --------------------------------

def test_obs_import_graph_is_stdlib_only_statically():
    """PR 4's "importing obs never pulls jax" contract, upgraded from a
    per-module subprocess walk to graftlint's whole-import-graph proof
    (PR 13, analysis/src_lint.py): every obs/ module — including ones
    later PRs add, which join the graph's roots automatically — is
    statically shown to never reach jax/numpy through any module-level
    import chain.  Stronger than the probe it replaces: a violation
    names the chain, and modules nothing imports yet are still covered.
    Load-bearing for bench.py's handler-before-import ordering;
    ledger.py and serve.py are born under it."""
    from distributedtensorflowexample_tpu.analysis import src_lint
    findings = src_lint.check_stdlib_only(REPO,
                                          "distributedtensorflowexample_tpu")
    assert findings == [], "\n".join(f.message for f in findings)
    # The graph must actually cover the package (8 obs modules as of
    # PR 10): an empty-roots bug would vacuously pass.
    obs_dir = os.path.join(REPO, "distributedtensorflowexample_tpu", "obs")
    mods = [f for f in os.listdir(obs_dir) if f.endswith(".py")]
    assert len(mods) >= 8


def test_obs_package_import_is_stdlib_only_subprocess_smoke():
    """Belt-and-braces runtime smoke behind the static proof above: ONE
    clean interpreter imports every obs module (list computed from the
    directory HERE, so modules later PRs add — re-exported by __init__
    or not — stay covered) and asserts jax/numpy never entered
    sys.modules.  Catches what static analysis can't by construction —
    dynamic imports, import-time side effects."""
    obs_dir = os.path.join(REPO, "distributedtensorflowexample_tpu", "obs")
    names = sorted(f[:-3] for f in os.listdir(obs_dir)
                   if f.endswith(".py") and f != "__init__.py")
    assert len(names) >= 8, names
    imports = "\n".join(
        f"import distributedtensorflowexample_tpu.obs.{n}" for n in names)
    code = (
        "import sys\n"
        "import distributedtensorflowexample_tpu.obs\n"
        f"{imports}\n"
        "banned = sorted(m for m in sys.modules\n"
        "                if m == 'jax' or m.startswith('jax.')\n"
        "                or m == 'numpy' or m.startswith('numpy.'))\n"
        "assert not banned, f'obs import pulled {banned}'\n"
        "print('OK')\n")
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=120, cwd=REPO,
        env={**os.environ, "PYTHONPATH": REPO})
    assert out.returncode == 0, out.stderr
    assert out.stdout.strip() == "OK"


# --- overhead guard ---------------------------------------------------------

def test_ledger_and_serve_overhead_under_1pct_of_bench_step(tmp_path,
                                                            monkeypatch):
    """Same budget, same methodology as MetricsHook's guard
    (tests/test_obs.py): the full production boundary stack — Metrics +
    Anomaly hooks — WITH a global ledger armed and an idle serve thread
    bound must stay under 1% of the measured CPU bench step."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from distributedtensorflowexample_tpu.data.synthetic import (
        make_synthetic)
    from distributedtensorflowexample_tpu.models import build_model
    from distributedtensorflowexample_tpu.parallel.sync import (
        make_train_step)
    from distributedtensorflowexample_tpu.training.hooks import (
        AnomalyHook, MetricsHook)
    from distributedtensorflowexample_tpu.training.state import TrainState

    step_fn = make_train_step()
    state = TrainState.create(build_model("mnist_cnn"),
                              optax.sgd(0.1, momentum=0.9),
                              jnp.zeros((8, 28, 28, 1), jnp.float32),
                              seed=0)
    x, y = make_synthetic(8, (28, 28, 1), 10, seed=3)
    batch = {"image": jnp.asarray(x), "label": jnp.asarray(y)}
    state, metrics = step_fn(state, batch)      # compile
    jax.block_until_ready(metrics)
    times = []
    for _ in range(5):
        t0 = time.perf_counter()
        state, metrics = step_fn(state, batch)
        jax.block_until_ready(metrics)
        times.append(time.perf_counter() - t0)
    step_s = min(times)

    led = obs_ledger.RunLedger(str(tmp_path / "RUNS.jsonl"))
    led.start("overhead-guard")
    monkeypatch.setattr(obs_ledger, "_GLOBAL", led)
    server = obs_serve.ObsServer(0).start()     # idle: bound, unscraped

    class _FakeLoop:
        start_step = 0

    try:
        hook = MetricsHook(every=100)
        anom = AnomalyHook(every=100)
        hook.begin(_FakeLoop())
        anom.begin(_FakeLoop())
        fetched = {"loss": np.asarray(metrics["loss"])}
        n = 1000
        t0 = time.perf_counter()
        for i in range(1, n + 1):
            hook.after_step(i, state, fetched)
            anom.after_step(i, state, fetched)
        hook_s = (time.perf_counter() - t0) / n
    finally:
        server.stop()
    # The default 30 s sample bound means ~1 ledger append across the
    # 1000 boundaries — the amortized cost the budget must absorb.
    assert led.samples >= 1
    assert hook_s < 0.01 * step_s, (
        f"hooks+ledger+serve {hook_s * 1e6:.2f}us/boundary >= 1% of "
        f"the {step_s * 1e3:.1f}ms CPU bench step")
