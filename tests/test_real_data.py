"""Real-dataset accuracy gates (VERDICT r2 item 5).

Every accuracy this repo has ever recorded ran on the synthetic fallback
(this image ships no MNIST/CIFAR bytes and has no network).  These tests
pin the reference's implicit real-data convergence contract — MNIST
softmax >=0.92, MNIST CNN >=0.97, CIFAR-10 ResNet-20 >=0.90 — and SKIP
unless the real bytes are present under DISTTF_TPU_DATA_DIR (default
/tmp/data).  One-command fetch + expected layout: README 'Real datasets'.

The loaders themselves shout when they fall back (warn_synthetic), so a
run that silently trained on synthetic data can no longer be mistaken for
a real-data result.
"""

import os

import numpy as np
import pytest

from distributedtensorflowexample_tpu.data.mnist import _FILES

DATA_DIR = os.environ.get("DISTTF_TPU_DATA_DIR", "/tmp/data")


def _mnist_present() -> bool:
    img, _ = _FILES["train"]
    p = os.path.join(DATA_DIR, img)
    return os.path.exists(p) or os.path.exists(p + ".gz")


def _cifar_present() -> bool:
    """Stat-based only — a full _load_binary_batches here would parse the
    ~150 MB train split at pytest collection time on every run."""
    for sub in ("", "cifar-10-batches-py", "cifar-10-batches-bin"):
        d = os.path.join(DATA_DIR, sub)
        if os.path.isdir(d) and any(
                n.startswith("data_batch") for n in os.listdir(d)):
            return True
    return any(os.path.exists(os.path.join(DATA_DIR, t))
               for t in ("cifar-10-python.tar.gz", "cifar-10-python.tar"))


needs_mnist = pytest.mark.skipif(
    not _mnist_present(),
    reason=f"real MNIST IDX files not present in {DATA_DIR} "
           "(see README 'Real datasets' for the one-command fetch)")
needs_cifar = pytest.mark.skipif(
    not _cifar_present(),
    reason=f"real CIFAR-10 batches not present in {DATA_DIR} "
           "(see README 'Real datasets' for the one-command fetch)")


def _flags(tmp_log_dir, extra):
    return ["--log_dir", tmp_log_dir, "--data_dir", DATA_DIR,
            "--resume", "false", "--log_every", "200", *extra]


@needs_mnist
def test_real_mnist_softmax_accuracy(tmp_log_dir):
    """Config-1 contract on the real bytes: softmax regression >=0.92."""
    from distributedtensorflowexample_tpu.trainers import trainer_local_mnist

    summary = trainer_local_mnist.main(_flags(
        tmp_log_dir, ["--train_steps", "2000", "--batch_size", "100",
                      "--learning_rate", "0.5"]))
    assert summary["final_accuracy"] >= 0.92, summary


@needs_mnist
def test_real_mnist_cnn_accuracy(tmp_log_dir):
    """Config-3 contract on the real bytes: the conv/pool x2 + FC CNN
    reaches >=0.97 within ~4 epochs."""
    from distributedtensorflowexample_tpu.trainers import trainer_sync_mnist

    summary = trainer_sync_mnist.main(_flags(
        tmp_log_dir, ["--train_steps", "3000", "--batch_size", "64",
                      "--learning_rate", "0.05", "--momentum", "0.9",
                      "--steps_per_loop", "50"]))
    assert summary["final_accuracy"] >= 0.97, summary


@needs_cifar
@pytest.mark.slow
def test_real_cifar_resnet20_accuracy(tmp_log_dir):
    """Config-4 contract on the real bytes: ResNet-20 + augmentation +
    cosine schedule >=0.90 (canonical recipe lands ~0.91 around epoch 60;
    hours on CPU — run on the chip)."""
    from distributedtensorflowexample_tpu.trainers import (
        trainer_mirrored_cifar)

    steps = 60 * (50000 // 256)   # ~60 epochs at global batch 256
    summary = trainer_mirrored_cifar.main(_flags(
        tmp_log_dir, ["--train_steps", str(steps), "--batch_size", "256",
                      "--global_batch", "true", "--learning_rate", "0.1",
                      "--momentum", "0.9", "--weight_decay", "5e-4",
                      "--lr_schedule", "cosine", "--warmup_steps", "400",
                      "--steps_per_loop", "65"]))
    assert summary["final_accuracy"] >= 0.90, summary


def test_synthetic_fallback_warns_once(tmp_path, capfd):
    """The fallback is LOUD (stderr) and once per (dataset, split)."""
    from distributedtensorflowexample_tpu.data import synthetic
    from distributedtensorflowexample_tpu.data.mnist import load_mnist

    synthetic._warned.clear()
    load_mnist(str(tmp_path), "train", synthetic_size=64,
               source="fallback")
    load_mnist(str(tmp_path), "train", synthetic_size=64,   # deduped
               source="fallback")
    load_mnist(str(tmp_path), "test", synthetic_size=64,    # new split
               source="fallback")
    err = capfd.readouterr().err
    assert err.count("DETERMINISTIC SYNTHETIC") == 2
    assert "MNIST 'train' bytes not found" in err


def test_synthetic_fallback_warning_suppressible(tmp_path, capfd,
                                                 monkeypatch):
    from distributedtensorflowexample_tpu.data import synthetic
    from distributedtensorflowexample_tpu.data.cifar10 import load_cifar10

    synthetic._warned.clear()
    monkeypatch.setenv("DISTTF_TPU_QUIET_SYNTHETIC", "1")
    load_cifar10(str(tmp_path), "train", synthetic_size=64,
                 source="fallback")
    assert "SYNTHETIC" not in capfd.readouterr().err
