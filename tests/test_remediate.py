"""The self-healing layer (resilience/remediate.py): policy mapping,
guardrail semantics (flap damping, cooldown, budget, dry-run), WAL
replay after a SIGKILL, the watcher sources, the actuator factories,
canary promotion verdicts, the heal_* ledger rows obs_query renders,
and the HEAL_* bench-record family's ratchet rules.

Inline on purpose: the policy engine is stdlib+obs, the watchers read
plain JSON files, and the one jax-touching test (rollback pinning over
a real SnapshotStore) uses the cheap softmax state — verdicts land
inside the tier-1 budget.  The end-to-end fleet drills (faultline
children, bitwise-resume parity) live in tests/test_heal_drill.py,
which runs as an isolated subprocess (tests/isolation_list.py).
"""

import io
import json
import os
import sys
from contextlib import redirect_stdout

import pytest

from distributedtensorflowexample_tpu.obs import anomaly as obs_anomaly
from distributedtensorflowexample_tpu.obs import ledger as obs_ledger
from distributedtensorflowexample_tpu.resilience.remediate import (
    DEFAULT_POLICY, HEAL_ACTIONS, HEAL_EVENTS, AnomalyEvent, FleetTarget,
    Guardrails, HealRule, HealthWatcher, LedgerWatcher, Remediator,
    ServeWatcher, budget_default, cooldown_default, dry_run_default,
    flap_n_default, flap_window_default, make_rollback_actuator,
    make_slo_actuator)
from distributedtensorflowexample_tpu.resilience.supervisor import Journal

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.heal


def _rem(tmp_path, actuators, *, clock=None, dry_run=False, scope="job1",
         policy=None, **guard_kw):
    guard_kw.setdefault("flap_n", 2)
    guard_kw.setdefault("flap_window_s", 30.0)
    guard_kw.setdefault("cooldown_s", 10.0)
    guard_kw.setdefault("budget", 4)
    return Remediator(
        Journal(str(tmp_path / "heal.jsonl")),
        str(tmp_path / "RUNS.jsonl"),
        actuators=actuators, scope=scope, dry_run=dry_run,
        policy=policy,
        guardrails=Guardrails(clock=clock, **guard_kw))


def _rows(tmp_path, event=None):
    path = tmp_path / "RUNS.jsonl"
    if not path.exists():
        return []
    rows = [json.loads(l) for l in open(path) if l.strip()]
    if event is not None:
        rows = [r for r in rows if r.get("event") == event]
    return rows


def _ev(kind="straggler", key=None, **kw):
    return AnomalyEvent(kind=kind, key=key or f"{kind}:rank1",
                        scope="job1", rank=1, **kw)


# ---- env knobs -----------------------------------------------------------

def test_env_knob_defaults(monkeypatch):
    for name in ("HEAL_DRY_RUN", "HEAL_COOLDOWN_S", "HEAL_ACTION_BUDGET",
                 "HEAL_FLAP_N", "HEAL_FLAP_WINDOW_S"):
        monkeypatch.delenv(name, raising=False)
    assert dry_run_default() is False
    assert cooldown_default() == 30.0
    assert budget_default() == 8
    assert flap_n_default() == 2
    assert flap_window_default() == 60.0
    monkeypatch.setenv("HEAL_DRY_RUN", "1")
    monkeypatch.setenv("HEAL_COOLDOWN_S", "5")
    monkeypatch.setenv("HEAL_ACTION_BUDGET", "3")
    monkeypatch.setenv("HEAL_FLAP_N", "0")      # floored at 1
    assert dry_run_default() is True
    assert cooldown_default() == 5.0
    assert budget_default() == 3
    assert flap_n_default() == 1


# ---- guardrails ----------------------------------------------------------

def test_flap_damping_suppresses_one_shot_blip(tmp_path):
    """One detection (a z-score grazing the threshold for one poll)
    never reaches the actuator; a HELD condition crosses the bar on the
    flap_n-th observation inside the window."""
    calls = []
    clock = [0.0]
    rem = _rem(tmp_path, {"evict": lambda ev: calls.append(ev) or {}},
               clock=lambda: clock[0])
    assert rem.observe(_ev()) == "flap"
    # the blip decays; the window expires with no second detection
    clock[0] += 60.0
    assert not calls
    sup = _rows(tmp_path, "heal_suppressed")
    assert sup and sup[0]["reason"] == "flap"
    # a held condition: two polls inside the window -> action
    assert rem.observe(_ev()) == "flap"        # window restarted
    clock[0] += 1.0
    assert rem.observe(_ev()) == "acted"
    assert len(calls) == 1
    assert len(_rows(tmp_path, "heal_evict")) == 1
    # exactly one detect row for the one distinct anomaly key
    assert len(_rows(tmp_path, "heal_detect")) == 1


def test_cooldown_prevents_action_storm(tmp_path):
    calls = []
    clock = [0.0]
    rem = _rem(tmp_path, {"evict": lambda ev: calls.append(ev) or {}},
               clock=lambda: clock[0], flap_n=1)
    assert rem.observe(_ev()) == "acted"
    for _ in range(5):
        clock[0] += 1.0
        assert rem.observe(_ev()) == "cooldown"
    assert len(calls) == 1
    # suppression rows are per-episode, not per-poll: ONE cooldown row
    sup = _rows(tmp_path, "heal_suppressed")
    assert [r["reason"] for r in sup] == ["cooldown"]
    clock[0] += 10.0
    assert rem.observe(_ev()) == "acted"
    assert len(calls) == 2


def test_budget_exhaustion_degrades_to_detection_only(tmp_path):
    calls = []
    clock = [0.0]
    rem = _rem(tmp_path, {"evict": lambda ev: calls.append(ev) or {}},
               clock=lambda: clock[0], flap_n=1, budget=2,
               cooldown_s=0.0)
    for i in range(2):
        assert rem.observe(_ev(key=f"s:{i}")) == "acted"
        clock[0] += 1.0
    # budget gone: loud row ONCE, then detection-only forever
    assert rem.observe(_ev(key="s:2")) == "budget"
    assert rem.observe(_ev(key="s:3")) == "budget"
    assert len(calls) == 2
    loud = _rows(tmp_path, "heal_budget_exhausted")
    assert len(loud) == 1 and loud[0]["budget"] == 2
    # detections still land (the round-10 stance survives)
    assert len(_rows(tmp_path, "heal_detect")) == 4


def test_dry_run_fires_no_actuator(tmp_path):
    calls = []
    rem = _rem(tmp_path, {"evict": lambda ev: calls.append(ev) or {}},
               dry_run=True, flap_n=1)
    assert rem.observe(_ev()) == "dry_run"
    assert rem.observe(_ev()) == "dry_run"
    assert not calls
    dry = _rows(tmp_path, "heal_dry_run")
    assert len(dry) == 1 and dry[0]["action"] == "evict"
    assert not _rows(tmp_path, "heal_evict")


def test_noop_actuator_spends_no_budget(tmp_path):
    rem = _rem(tmp_path, {"evict": lambda ev: {"noop": "nothing waits"}},
               flap_n=1, budget=2)
    assert rem.observe(_ev()) == "noop: nothing waits"
    assert rem.guardrails.actions_used == 0
    sup = _rows(tmp_path, "heal_suppressed")
    assert sup and sup[-1]["reason"].startswith("noop")


def test_errored_actuator_retries_on_cooldown_not_every_poll(tmp_path):
    """A crashing actuator anchors the cooldown (budget uncharged): a
    held condition retries once per cooldown, not once per 0.25s poll
    — which would flood the WAL with fsync'd intent/error rows."""
    calls = []
    clock = [0.0]

    def boom(ev):
        calls.append(ev)
        raise RuntimeError("down")

    rem = _rem(tmp_path, {"evict": boom}, flap_n=1, cooldown_s=10.0,
               clock=lambda: clock[0])
    assert rem.observe(_ev()) == "error"
    clock[0] += 1.0
    assert rem.observe(_ev()) == "cooldown"      # not retried per poll
    assert len(calls) == 1
    clock[0] += 10.0
    assert rem.observe(_ev()) == "error"         # retried post-cooldown
    assert len(calls) == 2
    assert rem.guardrails.actions_used == 0      # crashes spend nothing


def test_unmatched_policy_kind_is_detection_only(tmp_path):
    rem = _rem(tmp_path, {}, flap_n=1)
    assert rem.observe(_ev(kind="weird_new_kind")) == "detected"
    assert _rows(tmp_path, "heal_detect")
    assert not _rows(tmp_path, "heal_suppressed")


def test_missing_actuator_is_loud_detection_only(tmp_path):
    rem = _rem(tmp_path, {}, flap_n=1)       # policy maps, no actuator
    assert rem.observe(_ev()) == "no_actuator"
    sup = _rows(tmp_path, "heal_suppressed")
    assert sup and sup[0]["reason"] == "no_actuator"


# ---- WAL replay (SIGKILL between intent and effect) ----------------------

def test_wal_replay_reapplies_unmatched_intent_idempotently(tmp_path):
    """A remediator SIGKILLed between journaling heal_intent and
    running the actuator: the restarted incarnation re-applies the
    intent exactly once (replayed=true on its applied row), and a THIRD
    incarnation — the intent now matched — re-applies nothing."""
    jp = str(tmp_path / "heal.jsonl")
    journal = Journal(jp)
    # the dead incarnation's tail: detect + intent, no applied row
    journal.write("heal_detect", key="s:rank1", kind="straggler",
                  job="job1")
    journal.write("heal_intent", seq=1, action="evict", key="s:rank1",
                  kind="straggler", job="job1")
    calls = []
    rem = Remediator(Journal(jp), str(tmp_path / "RUNS.jsonl"),
                     actuators={"evict": lambda ev: calls.append(ev)
                                or {"ok": 1}},
                     guardrails=Guardrails(flap_n=1, budget=4,
                                           clock=lambda: 0.0))
    assert len(calls) == 1                    # re-applied exactly once
    applied = _rows(tmp_path, "heal_evict")
    assert len(applied) == 1 and applied[0]["replayed"] is True
    assert rem.guardrails.actions_used == 1   # counts against budget
    calls2 = []
    rem2 = Remediator(Journal(jp), str(tmp_path / "RUNS.jsonl"),
                      actuators={"evict": lambda ev: calls2.append(ev)
                                 or {}},
                      guardrails=Guardrails(flap_n=1, budget=4,
                                            clock=lambda: 0.0))
    assert not calls2                         # idempotent: matched now
    assert rem2.guardrails.actions_used == 1  # budget restored, once
    assert "s:rank1" in rem2._detected        # detect latch restored


def test_replay_restores_budget_and_detect_latch(tmp_path):
    clock = [0.0]
    rem = _rem(tmp_path, {"evict": lambda ev: {}}, flap_n=1, budget=2,
               cooldown_s=0.0, clock=lambda: clock[0])
    rem.observe(_ev(key="a"))
    clock[0] += 1
    rem.observe(_ev(key="b"))
    rem2 = Remediator(
        Journal(str(tmp_path / "heal.jsonl")),
        str(tmp_path / "RUNS.jsonl"),
        actuators={"evict": lambda ev: {}}, scope="job1",
        guardrails=Guardrails(flap_n=1, budget=2, cooldown_s=0.0,
                              clock=lambda: clock[0]))
    # budget already spent by the previous incarnation: first new
    # observation trips the loud exhaustion row, not an action
    assert rem2.observe(_ev(key="c")) == "budget"
    assert len(_rows(tmp_path, "heal_budget_exhausted")) == 1


def test_replay_does_not_charge_errored_actions(tmp_path):
    """Actuator failures write error rows to balance the WAL but spend
    no budget live — a restarted incarnation must not count them
    either, or N failures + a restart would wake up budget-exhausted
    with zero actions ever actually run."""
    def boom(ev):
        raise RuntimeError("actuator down")
    clock = [0.0]
    rem = _rem(tmp_path, {"evict": boom}, flap_n=1, budget=2,
               cooldown_s=0.0, clock=lambda: clock[0])
    assert rem.observe(_ev(key="a")) == "error"
    clock[0] += 1
    assert rem.observe(_ev(key="b")) == "error"
    assert rem.guardrails.actions_used == 0
    rem2 = Remediator(
        Journal(str(tmp_path / "heal.jsonl")),
        str(tmp_path / "RUNS.jsonl"),
        actuators={"evict": lambda ev: {}}, scope="job1",
        guardrails=Guardrails(flap_n=1, budget=2, cooldown_s=0.0,
                              clock=lambda: clock[0]))
    assert rem2.guardrails.actions_used == 0
    assert rem2.observe(_ev(key="c")) == "acted"


# ---- watchers ------------------------------------------------------------

def _write_health(path, rank, step, *, nan_step=None, firing=False,
                  fired_step=None, ewma=0.01):
    payload = {
        "version": obs_anomaly.HEALTH_VERSION, "kind": "rank",
        "rank": rank, "step": step, "updated_unix": 123.0,
        "flags": {
            "step_time_regression": {"firing": firing,
                                     "fired_step": fired_step},
            "nan_loss": {"firing": nan_step is not None,
                         "fired_step": nan_step},
            "loss_plateau": {"firing": False, "fired_step": None}},
        "detectors": {"step_time": {"ewma_s": ewma}}}
    obs_anomaly.write_health(str(path), payload)


def test_health_watcher_condition_held_semantics(tmp_path):
    hw = HealthWatcher(str(tmp_path / "health_rank*.json"),
                       scope="job1")
    assert hw.poll() == []
    # a firing regression emits ONE event per poll while held
    _write_health(tmp_path / "health_rank1.json", 1, 10, firing=True,
                  fired_step=8)
    evs = hw.poll()
    assert [e.kind for e in evs] == ["step_time_regression"]
    assert evs[0].rank == 1 and evs[0].step == 8
    assert evs[0].detail["updated_unix"] == 123.0
    assert hw.poll()                          # still held -> re-emitted
    # decayed blip: firing False stops the stream (fired_step latched
    # in the payload must NOT keep feeding the flap counter)
    _write_health(tmp_path / "health_rank1.json", 1, 20, firing=False,
                  fired_step=8)
    assert hw.poll() == []
    # nan is permanent: a post-mortem file still reports it
    _write_health(tmp_path / "health_rank1.json", 1, 12, nan_step=12)
    evs = hw.poll()
    assert [e.kind for e in evs] == ["nan_loss"]
    assert evs[0].step == 12


def test_health_watcher_fleet_stragglers(tmp_path):
    agg = tmp_path / "health.json"
    obs_anomaly.write_health(str(agg), {
        "version": 1, "kind": "fleet", "updated_unix": 5.0,
        "stragglers": [1],
        "skew": {"why": {"1": "lag 4 steps with regression firing"}}})
    hw = HealthWatcher(str(tmp_path / "health_rank*.json"),
                       fleet_health=str(agg), scope="job1")
    evs = hw.poll()
    assert [e.kind for e in evs] == ["straggler"]
    assert evs[0].rank == 1 and "lag 4" in evs[0].detail["why"]


def test_ledger_watcher_tails_new_rows_only(tmp_path):
    lp = str(tmp_path / "RUNS.jsonl")
    lw = LedgerWatcher(lp, scope="job1")
    assert lw.poll() == []
    obs_ledger.log_event("anomaly", path=lp, rank=1, kind="straggler",
                         fired_step=9, task="t")
    obs_ledger.log_event("run_start", path=lp, run="x")   # not a kind
    evs = lw.poll()
    assert [e.kind for e in evs] == ["straggler"]
    assert lw.poll() == []                    # consumed
    obs_ledger.log_event("rank_lost", path=lp, rank=1, task="t",
                         error="host down")
    obs_ledger.log_event("rank_lost", path=lp, rank=1, task="t",
                         error="host down")
    evs = lw.poll()
    assert [e.kind for e in evs] == ["rank_lost", "rank_lost"]
    # distinct keys per occurrence: repeated losses accumulate toward
    # the repeated-offender flap bar instead of deduping to one
    assert len({e.key for e in evs}) == 2


def test_serve_watcher_breach_and_episode_rearm(tmp_path):
    stats = {"p99_ms": 50.0, "completed": 20}
    sw = ServeWatcher(lambda: stats, breach_ms=100.0)
    assert sw.poll() == []
    stats["p99_ms"] = 300.0
    (ev,) = sw.poll()
    assert ev.kind == "serve_p99_breach" and ev.key == "serve_p99:e0"
    assert sw.poll()[0].key == "serve_p99:e0"   # same episode
    stats["p99_ms"] = 80.0
    assert sw.poll() == []                      # recovered
    stats["p99_ms"] = 400.0
    assert sw.poll()[0].key == "serve_p99:e1"   # NEW episode key
    # too few completions = no evidence, and a raising stats_fn is
    # "no data", never a crash
    assert ServeWatcher(lambda: {"p99_ms": 999, "completed": 1},
                        breach_ms=10).poll() == []
    assert ServeWatcher(lambda: 1 / 0, breach_ms=10).poll() == []


def test_serve_new_episode_gets_fresh_decision(tmp_path):
    """The episode label reaches the guardrails: a breach that provably
    recovered and breached AGAIN is a fresh decision, not a cooldown
    leftover — while re-observations of the SAME episode stay damped."""
    calls = []
    clock = [0.0]
    rem = _rem(tmp_path,
               {"slo_tighten": lambda ev: calls.append(ev) or {}},
               scope="serve", flap_n=1, cooldown_s=30.0,
               clock=lambda: clock[0])
    e0 = AnomalyEvent(kind="serve_p99_breach", key="serve_p99:e0",
                      scope="serve", episode="e0")
    assert rem.observe(e0) == "acted"
    clock[0] += 1.0
    assert rem.observe(e0) == "cooldown"        # same episode: damped
    clock[0] += 1.0
    e1 = AnomalyEvent(kind="serve_p99_breach", key="serve_p99:e1",
                      scope="serve", episode="e1")
    assert rem.observe(e1) == "acted"           # new episode: fresh
    assert len(calls) == 2
    # the episode survives the WAL: applied rows carry it
    applied = _rows(tmp_path, "heal_slo_tighten")
    assert [r.get("episode") for r in applied] == ["e0", "e1"]


# ---- actuators -----------------------------------------------------------

def test_slo_actuator_clamps_never_loosens():
    box = {"slo": 0.0}
    act = make_slo_actuator(lambda: box["slo"],
                            lambda v: box.__setitem__("slo", v), 150.0)
    detail = act(AnomalyEvent(kind="serve_p99_breach", key="k",
                              detail={"p99_ms": 400.0}))
    assert box["slo"] == 150.0 and detail["was"] == 0.0
    box["slo"] = 80.0                          # already tighter
    act(AnomalyEvent(kind="serve_p99_breach", key="k2"))
    assert box["slo"] == 80.0                  # never loosened


def test_fleet_target_noop_without_fleet():
    t = FleetTarget()
    assert t.request_stop("heal_evict") == {"noop": "no live fleet"}
    assert t.ranks() == []


def test_rollback_actuator_pins_last_good_below_fired_step(tmp_path):
    """The NaN rollback: newest COMMON valid step strictly below the
    anomaly's fired_step wins; everything newer is discarded on every
    rank — validity-checked through the real SnapshotStore."""
    import jax.numpy as jnp
    import optax

    from distributedtensorflowexample_tpu.models import build_model
    from distributedtensorflowexample_tpu.resilience.snapshot import (
        SnapshotStore, valid_steps)
    from distributedtensorflowexample_tpu.training.state import TrainState

    model = build_model("softmax")
    state = TrainState.create(model, optax.sgd(0.1, momentum=0.9),
                              jnp.zeros((2, 28, 28, 1), jnp.float32))
    template = str(tmp_path / "rank{rank}" / "snaps")
    for rank, steps in ((0, (3, 4, 5, 6)), (1, (3, 4, 5))):
        store = SnapshotStore(template.replace("{rank}", str(rank)),
                              keep=10)
        for s in steps:
            store.save(state.replace(step=jnp.asarray(s)), force=True)
    act = make_rollback_actuator(template, ranks=(0, 1))
    detail = act(AnomalyEvent(kind="nan_loss", key="n", step=5))
    # common valid = {3,4,5}; strictly below fired_step 5 -> 4
    assert detail["last_good"] == 4
    assert detail["discarded"]["0"] == [5, 6]
    assert detail["discarded"]["1"] == [5]
    assert valid_steps(template.replace("{rank}", "0")) == [3, 4]
    assert valid_steps(template.replace("{rank}", "1")) == [3, 4]
    # idempotent: the replayed intent finds the work already done
    detail2 = act(AnomalyEvent(kind="nan_loss", key="n", step=5))
    assert detail2["last_good"] == 4
    assert detail2["discarded"] == {"0": [], "1": []}


# ---- canary promotion ----------------------------------------------------

def test_canary_probe_rejects_nan_params_before_exposure():
    import numpy as np

    from distributedtensorflowexample_tpu.serving.promote import (
        Canary, params_healthy)
    good = {"w": np.ones((2, 2), np.float32),
            "ids": np.arange(4, dtype=np.int32)}    # ints never "NaN"
    bad = {"w": np.array([1.0, np.nan], np.float32)}
    assert params_healthy(good) and not params_healthy(bad)
    c = Canary(0, 1, fraction=0.5, window=4)
    assert c.state == "probing"
    assert c.admit_candidate(bad) is False
    assert c.state == "rolled_back" and "non-finite" in c.reason
    assert c.verdict() == "rollback"
    assert c.route("anything") == "baseline"    # nothing ever routes


def test_canary_p99_regression_rolls_back_clean_window_promotes():
    import numpy as np

    from distributedtensorflowexample_tpu.serving.promote import Canary
    ok_params = {"w": np.ones(2, np.float32)}
    # regression arm
    c = Canary(0, 1, fraction=0.5, window=4, p99_ratio=2.0)
    assert c.admit_candidate(ok_params)
    routes = {c.route(f"req{i}") for i in range(64)}
    assert routes == {"baseline", "canary"}     # both arms see traffic
    assert c.route("req7") == c.route("req7")   # deterministic
    for _ in range(8):
        c.observe("baseline", 0.010)
    for _ in range(4):
        c.observe("canary", 0.100)
    assert c.verdict() == "rollback"
    assert "p99" in c.reason and c.state == "rolled_back"
    # clean arm
    c2 = Canary(0, 1, fraction=0.5, window=4)
    assert c2.admit_candidate(ok_params)
    assert c2.verdict() is None                 # window still filling
    for _ in range(8):
        c2.observe("baseline", 0.010)
    for _ in range(4):
        c2.observe("canary", 0.012)
    assert c2.verdict() == "promote" and c2.state == "promoted"
    # a failed canary request rolls back regardless of latency
    c3 = Canary(0, 1, window=50)
    assert c3.admit_candidate(ok_params)
    c3.observe("canary", 0.01, ok=False)
    assert c3.verdict() == "rollback"
    assert c3.payload()["canary_failures"] == 1


def test_canary_env_knobs(monkeypatch):
    # NB: ``import ...serving.promote as promote`` would bind the
    # re-exported promote() FUNCTION (serving/__init__ shadows the
    # submodule attribute); from-imports resolve the module directly.
    from distributedtensorflowexample_tpu.serving.promote import (
        canary_fraction_default, canary_p99_ratio_default,
        canary_window_default)
    for name in ("HEAL_CANARY_FRACTION", "HEAL_CANARY_WINDOW",
                 "HEAL_CANARY_P99_RATIO"):
        monkeypatch.delenv(name, raising=False)
    assert canary_fraction_default() == 0.25
    assert canary_window_default() == 16
    assert canary_p99_ratio_default() == 2.0
    monkeypatch.setenv("HEAL_CANARY_FRACTION", "0.5")
    monkeypatch.setenv("HEAL_CANARY_WINDOW", "8")
    assert canary_fraction_default() == 0.5
    assert canary_window_default() == 8


def test_batcher_slo_seam_and_recent_p99():
    from distributedtensorflowexample_tpu.serving.queue import (
        Request, recent_p99_ms)
    reqs = []
    for i, lat in enumerate((0.01, 0.02, 0.5)):
        r = Request(rid=f"r{i}", prompt=None, max_new=1, submit_t=0.0)
        r.done_t = lat
        reqs.append(r)
    assert recent_p99_ms(reqs) == 500.0
    assert recent_p99_ms(reqs, window=2) == 500.0
    assert recent_p99_ms([]) is None


# ---- obs_query why + schema closure --------------------------------------

def _obs_query():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import obs_query
    finally:
        sys.path.pop(0)
    return obs_query


def test_heal_events_schema_is_closed():
    """The KEEP-IN-SYNC pair's content contract: obs_query's heal
    renderer covers exactly the declared heal_* row set, and every
    action has its applied event declared."""
    obs_query = _obs_query()
    assert set(obs_query._HEAL_RENDER) == set(HEAL_EVENTS)
    for action in HEAL_ACTIONS:
        assert f"heal_{action}" in HEAL_EVENTS
    for rule in DEFAULT_POLICY.values():
        assert rule.action in HEAL_ACTIONS


def test_obs_query_why_renders_heal_rows(tmp_path):
    """`obs_query why <job>` reconstructs the remediation story from
    ledger rows alone: detections, the applied action, suppressions,
    and a self-healed verdict fragment — interleaved with sched_* rows
    in one timeline."""
    lp = str(tmp_path / "RUNS.jsonl")
    obs_ledger.log_event("sched_place", path=lp, src="sched",
                         job="bench1", ranks=1, devices=2, attempt=1)
    obs_ledger.log_event("heal_detect", path=lp, src="heal",
                         job="bench1", kind="straggler", rank=1,
                         source="fleet", key="bench1:straggler:rank1")
    obs_ledger.log_event("heal_suppressed", path=lp, src="heal",
                         job="bench1", kind="straggler", action="evict",
                         reason="flap", key="bench1:straggler:rank1")
    obs_ledger.log_event("heal_evict", path=lp, src="heal",
                         job="bench1", kind="straggler", rank=1,
                         detail={"for_job": "train1"})
    obs_ledger.log_event("sched_done", path=lp, src="sched",
                         job="bench1", rcs={"0": 0})
    obs_query = _obs_query()
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = obs_query.main(["why", "bench1", "--ledger", lp])
    out = buf.getvalue()
    assert rc == 0
    assert "anomaly detected: straggler on rank 1" in out
    assert "SUPPRESSED by guardrail: flap" in out
    assert "HEALED by eviction" in out
    assert "self-healed 1x (evict)" in out
    assert "finally completed" in out
    # an applied row carrying error= is a crashed actuator, not a heal:
    # rendered as FAILED, never counted into the self-healed verdict
    obs_ledger.log_event("heal_rollback", path=lp, src="heal",
                         job="bench1", kind="nan_loss",
                         error="boom: store unreachable")
    buf = io.StringIO()
    with redirect_stdout(buf):
        assert obs_query.main(["why", "bench1", "--ledger", lp]) == 0
    out = buf.getvalue()
    assert "action rollback FAILED (nan_loss): boom" in out
    assert "self-healed 1x (evict)" in out      # still only the evict


# ---- the HEAL_* record family on the ratchet -----------------------------

def _ratchet():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import bench_ratchet
    finally:
        sys.path.pop(0)
    return bench_ratchet


def test_bench_ratchet_heal_family_and_zero_invariant(tmp_path):
    """HEAL_* rides the trajectory like any family; mttd/mttr gate
    lower-is-better (the *_ms rule), and a nonzero *_lost is an
    UNEXPLAINED finding regardless of tolerance."""
    bench_ratchet = _ratchet()
    rec = tmp_path / "HEAL_lm_cpu_r16.json"
    rows = [
        {"metric": "heal_nan_mttd_ms", "value": 420.0, "unit": "ms",
         "platform": "cpu", "detail": {"platform": "cpu"}},
        {"metric": "heal_nan_steps_lost", "value": 0, "unit": "steps",
         "platform": "cpu", "detail": {"platform": "cpu"}},
    ]
    rec.write_text("".join(json.dumps(r) + "\n" for r in rows))
    recs = bench_ratchet.load_records([str(rec)])
    assert {r["metric"] for r in recs} == {"heal_nan_mttd_ms",
                                           "heal_nan_steps_lost"}
    assert bench_ratchet._lower_is_better("heal_nan_mttd_ms")
    assert bench_ratchet.check_zero_invariants(recs) == []
    # the trajectory builder folds the family in
    traj = bench_ratchet.build_trajectory(str(tmp_path))
    fam = [r for r in traj if r["family"] == "HEAL_lm_cpu"]
    assert len(fam) == 1 and fam[0]["round"] == 16
    assert fam[0]["metrics"]["heal_nan_steps_lost"] == 0
    # a lost step is an invariant violation, not a tolerance question
    bad = dict(rows[1], value=2)
    rec.write_text(json.dumps(rows[0]) + "\n" + json.dumps(bad) + "\n")
    findings = bench_ratchet.check_zero_invariants(
        bench_ratchet.load_records([str(rec)]))
    assert len(findings) == 1
    assert findings[0]["severity"] == "regression"
    assert "must-be-zero" in findings[0]["why"]
    # the invariant gates the NEWEST record only: a later round that
    # fixed the loss clears the red instead of staying red forever
    fixed = tmp_path / "HEAL_lm_cpu_r17.json"
    fixed.write_text(json.dumps(dict(rows[1], value=0)) + "\n")
    assert bench_ratchet.check_zero_invariants(
        bench_ratchet.load_records([str(rec), str(fixed)])) == []
    # and a documented-outage window is explained, like the ratchet
    findings = bench_ratchet.check_zero_invariants(
        bench_ratchet.load_records([str(rec)]), outages={16})
    assert len(findings) == 1
    assert findings[0]["severity"] == "explained"
    # and a *_ms latency regression beyond tolerance gates as usual
    older = tmp_path / "HEAL_lm_cpu_r15.json"
    older.write_text(json.dumps(
        {"metric": "heal_nan_mttd_ms", "value": 100.0, "unit": "ms",
         "platform": "cpu", "detail": {"platform": "cpu"}}) + "\n")
    findings = bench_ratchet.compare_records(
        bench_ratchet.load_records([str(older), str(rec)]),
        tolerance=0.10, noise=0.25)
    assert any(f["metric"] == "heal_nan_mttd_ms"
               and f["severity"] == "regression" for f in findings)


def test_checked_in_heal_record_invariants():
    """The measured drill record ships with the repo: every *_lost line
    is zero, every drill contributed, and the trajectory artifact
    carries the family."""
    bench_ratchet = _ratchet()
    path = os.path.join(REPO, "HEAL_lm_cpu_r16.json")
    assert os.path.exists(path), "HEAL_lm_cpu_r16.json missing"
    recs = bench_ratchet.load_records([path])
    by_metric = {r["metric"]: r for r in recs}
    for drill in ("slow_rank", "nan", "host_loss"):
        assert by_metric[f"heal_{drill}_steps_lost"]["value"] == 0
        assert by_metric[f"heal_{drill}_mttr_ms"]["value"] > 0
        assert by_metric[f"heal_{drill}_mttd_ms"]["value"] is not None
        assert by_metric[f"heal_{drill}_steps_lost"]["detail"][
            "bitwise_resume"] is True
    assert by_metric["heal_serve_slo_requests_lost"]["value"] == 0
    assert by_metric["heal_canary_requests_lost"]["value"] == 0
    assert bench_ratchet.check_zero_invariants(recs) == []
    with open(os.path.join(REPO, "BENCH_trajectory.json")) as f:
        fams = [json.loads(l)["family"] for l in f if l.strip()]
    assert "HEAL_lm_cpu" in fams


# ---- run_remediated with stdlib children ---------------------------------

def test_run_remediated_heals_and_relaunches(tmp_path):
    """End-to-end over stdlib children (no jax): rank 0 writes a
    firing-regression health file on its first launch and sleeps; the
    watcher feeds the engine, the evict actuator stops the gang
    (TERM→143), and the relaunch — which sees the bumped
    SUPERVISE_ATTEMPT, the transient-fault convention — runs clean to
    rc 0.  The heal story is in the ledger."""
    import textwrap

    from distributedtensorflowexample_tpu.resilience import remediate
    from distributedtensorflowexample_tpu.resilience.fleet import (
        FleetSupervisor)
    from distributedtensorflowexample_tpu.resilience.supervisor import (
        RetryPolicy)
    child = tmp_path / "child.py"
    child.write_text(textwrap.dedent("""
        import json, os, signal, sys, time
        attempt = int(os.environ.get("SUPERVISE_ATTEMPT", "0"))
        if attempt == 0:
            signal.signal(signal.SIGTERM, lambda s, f: sys.exit(143))
            hp = os.environ["OBS_HEALTH"]
            payload = {
                "version": 1, "kind": "rank", "rank": 0, "step": 5,
                "updated_unix": time.time(),
                "flags": {"step_time_regression":
                          {"firing": True, "fired_step": 4},
                          "nan_loss": {"firing": False,
                                       "fired_step": None},
                          "loss_plateau": {"firing": False,
                                           "fired_step": None}},
                "detectors": {"step_time": {"ewma_s": 2.0}}}
            with open(hp, "w") as f:
                json.dump(payload, f)
            time.sleep(60)
        sys.exit(0)
    """))
    workdir = str(tmp_path / "fleet")
    journal = Journal(os.path.join(workdir, "fleet.jsonl"))
    ledger = os.path.join(workdir, "RUNS.jsonl")

    def make_fleet():
        return FleetSupervisor(
            1, policy=RetryPolicy(retries=0, backoff_base_s=0.01),
            journal=journal, kill_grace_s=5.0, poll_s=0.02, seed=0,
            workdir=workdir, ledger_path=ledger)

    target = remediate.FleetTarget()
    rem = remediate.Remediator(
        journal=journal, ledger_path=ledger, scope="drill",
        actuators={"evict": remediate.make_evict_actuator(target)},
        guardrails=Guardrails(flap_n=2, cooldown_s=5.0, budget=2,
                              flap_window_s=30.0))
    watchers = [remediate.HealthWatcher(
        os.path.join(workdir, "health_rank*.json"), scope="drill")]
    out = remediate.run_remediated(
        make_fleet, [sys.executable, str(child)], rem, watchers,
        target=target, name="drill", poll_s=0.1, max_heals=2)
    assert out["status"] == "ok"
    assert out["healed"] == 1
    assert out["results"][0].status == "evicted"
    assert out["results"][0].last_rcs == {0: 143}     # loss-free stop
    assert out["results"][1].status == "ok"
    rows = [json.loads(l) for l in open(ledger) if l.strip()]
    events = [r["event"] for r in rows
              if str(r.get("event", "")).startswith("heal_")]
    assert "heal_detect" in events and "heal_evict" in events
