"""serving/ — the continuous-batching inference engine (PR 15): decode
parity with the training forward, mid-decode admission (continuous
batching, not batch-drain), SLO admission, OOV refusal, snapshot →
serving promotion edges (torn-newest fallback, row-layout
materialization), the decode-step HLO contract, and the obs/ import
direction.

Inline and tier-1-safe: lm_tiny at tiny slot/cache geometry,
single-device programs only (no collectives — none of the rendezvous
risk the isolated files carry).  The engine fixture is module-scoped so
its prefill/decode compiles are paid once.  The end-to-end serve_lm
drill (real subprocess, eviction, TERM→143) lives in
tests/test_scheduler.py next to the other control-plane drills.
"""

import json
import os

import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributedtensorflowexample_tpu.models import build_model
from distributedtensorflowexample_tpu.refusal import ModeRefusal
from distributedtensorflowexample_tpu.resilience.snapshot import (
    SnapshotStore)
from distributedtensorflowexample_tpu.serving.engine import (
    DECODE_HLO_CONTRACT, DecodeEngine, serve_slots_default)
from distributedtensorflowexample_tpu.serving.loadgen import (
    DriveFile, make_prompt)
from distributedtensorflowexample_tpu.serving.promote import (
    init_lm_snapshot, promote)
from distributedtensorflowexample_tpu.serving.queue import (
    ContinuousBatcher, RequestQueue, percentile, serve_slo_ms_default)
from distributedtensorflowexample_tpu.training.state import TrainState

pytestmark = pytest.mark.serving

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SIZE = "lm_tiny"
CACHE = 32


def _tx():
    return optax.sgd(0.1, momentum=0.9)


@pytest.fixture(scope="module")
def lm_state():
    model = build_model(SIZE)
    return model, TrainState.create(model, _tx(),
                                    jnp.zeros((1, 8), jnp.int32))


@pytest.fixture(scope="module")
def engine(lm_state):
    model, state = lm_state
    return DecodeEngine(model, state.params, slots=3, cache_len=CACHE)


def _greedy_reference(model, params, prompt, n):
    """Teacher-forced greedy through the TRAINING forward — the truth
    the engine must reproduce token-for-token."""
    seq = list(int(t) for t in prompt)
    out = []
    for _ in range(n):
        logits = model.apply({"params": params},
                             jnp.asarray([seq], jnp.int32), train=False)
        nxt = int(jnp.argmax(logits[0, -1]))
        out.append(nxt)
        seq.append(nxt)
    return out


def _engine_greedy(engine, slot, prompt, n):
    toks = [engine.prefill(slot, np.asarray(prompt, np.int32),
                           max_new=n)]
    while len(toks) < n:
        step = engine.decode()
        toks.append(int(step[slot]))
    return toks


# ---- decode parity -------------------------------------------------------

def test_decode_matches_training_forward_token_exact(lm_state, engine):
    """The KV-cache decode (prefill + single-query steps) generates
    token-for-token what teacher-forced greedy through the training
    model generates: the cache path is the same math, masked rows
    contribute exactly 0.0 after the f32 exp."""
    model, state = lm_state
    prompt = [5, 9, 17, 3, 88, 120, 7]
    want = _greedy_reference(model, state.params, prompt, 6)
    got = _engine_greedy(engine, 0, prompt, 6)
    assert got == want
    # A second prompt through a DIFFERENT slot, same engine, same truth
    # (slot reuse after retirement is the continuous-batching steady
    # state).
    prompt2 = [200, 1, 42]
    want2 = _greedy_reference(model, state.params, prompt2, 5)
    assert _engine_greedy(engine, 2, prompt2, 5) == want2


def test_prefill_bucket_table_and_refusals(engine):
    assert engine.bucket_for(3, 4) == 8          # smallest bucket
    assert engine.bucket_for(9, 4) == 16         # next power of two
    assert engine.bucket_for(CACHE - 4, 4) == CACHE
    with pytest.raises(ModeRefusal, match="--max_len"):
        engine.bucket_for(CACHE - 2, 4)          # can never finish
    with pytest.raises(ModeRefusal, match="--max_len"):
        # a cache longer than the positional table is refused at build
        DecodeEngine(engine.model, engine.params, slots=1,
                     cache_len=engine.model.max_len + 1)


# ---- continuous batching -------------------------------------------------

def test_request_admitted_mid_decode_completes_bitwise(lm_state, engine):
    """THE continuous-batching acceptance: B is admitted while A is
    mid-decode (A visibly unfinished at B's admission) and B's output
    equals B decoded solo — admission into an open slot of a RUNNING
    batch, with zero cross-request contamination."""
    model, state = lm_state
    prompt_a = [10, 20, 30, 40, 50]
    prompt_b = [7, 7, 99]
    solo_b = _engine_greedy(engine, 1, prompt_b, 5)

    queue = RequestQueue(engine.vocab)
    batcher = ContinuousBatcher(engine, queue, slo_ms=0.0)
    ra = queue.submit(prompt_a, 12, rid="A")
    batcher.step()                   # admits A, first decode
    batcher.step()
    assert not ra.done.is_set()      # A is mid-decode
    rb = queue.submit(prompt_b, 5, rid="B")
    batcher.step()                   # B admitted into an open slot NOW
    assert rb.admit_t is not None and not ra.done.is_set(), \
        "B must join while A is still decoding — batch-drain detected"
    while not (ra.done.is_set() and rb.done.is_set()):
        assert batcher.step() > 0
    assert ra.outcome == "ok" and rb.outcome == "ok"
    assert rb.tokens == solo_b       # bitwise: no contamination from A
    assert ra.tokens[:6] == _greedy_reference(model, state.params,
                                              prompt_a, 6)
    assert len(ra.tokens) == 12 and ra.first_token_t <= rb.admit_t


def test_slo_admission_rejects_predicted_misses(engine):
    """A request the step-time EWMA predicts past the SLO is rejected
    loudly at admission — never admitted to miss."""
    queue = RequestQueue(engine.vocab)
    batcher = ContinuousBatcher(engine, queue, slo_ms=50.0)
    batcher._step_ewma_s = 0.050     # 50 ms/step: 8 tokens >> 50 ms SLO
    req = queue.submit([1, 2, 3], 8)
    batcher.step()
    assert req.done.is_set() and req.outcome == "slo_rejected"
    # SLO off admits the same request
    batcher2 = ContinuousBatcher(engine, queue, slo_ms=0.0)
    batcher2._step_ewma_s = 0.050
    req2 = queue.submit([1, 2, 3], 2)
    batcher2.step()
    assert req2.outcome in ("", "ok") and req2.admit_t is not None
    while not req2.done.is_set():
        batcher2.step()
    assert req2.outcome == "ok"


def test_drain_answers_inflight_and_rejects_queued(engine):
    """The TERM half: drain decodes in-flight requests to completion
    and rejects the queued tail as ``drained`` — nothing admitted is
    lost, nothing queued hangs forever."""
    queue = RequestQueue(engine.vocab)
    batcher = ContinuousBatcher(engine, queue, slo_ms=0.0)
    inflight = [queue.submit([3, 1, 4], 6, rid=f"f{i}")
                for i in range(3)]                  # fills all 3 slots
    batcher.step()
    queued = queue.submit([9, 9], 4, rid="tail")    # no slot for it
    batcher.drain()
    assert all(r.done.is_set() and r.outcome == "ok" and
               len(r.tokens) == 6 for r in inflight)
    assert queued.outcome == "drained" and queued.tokens == []
    assert batcher.stats()["rejected"]["drained"] == 1
    # The submit/drain race is closed at the queue: a submit landing
    # AFTER drain is answered 'drained' synchronously — no caller is
    # ever left blocked on a request nothing will decode.
    late = queue.submit([1, 2], 3, rid="late")
    assert late.done.is_set() and late.outcome == "drained"
    assert len(queue) == 0
    # Retired slots are PARKED: decode advances only busy frontiers,
    # so an idle slot cannot drift toward the cache edge.
    assert engine.positions.tolist() == [0] * engine.slots


def test_oversized_request_refused_not_fatal(engine):
    """A request that can never finish inside the cache is refused by
    name AT ADMISSION — one impossible request costs itself, never the
    serving loop (the batcher thread has no handler above it)."""
    queue = RequestQueue(engine.vocab)
    batcher = ContinuousBatcher(engine, queue, slo_ms=0.0)
    bad = queue.submit(list(range(CACHE - 2)), 8)    # 30 + 8 > 32
    ok = queue.submit([1, 2, 3], 3)
    batcher.step()
    assert bad.done.is_set() and bad.outcome == "refused"
    assert "--max_len" in bad.error
    while not ok.done.is_set():
        batcher.step()                               # loop survived
    assert ok.outcome == "ok" and len(ok.tokens) == 3
    assert batcher.stats()["rejected"]["refused"] == 1


def test_ratchet_latency_metrics_gate_in_the_right_direction(tmp_path):
    """``*_ms`` metrics are lower-is-better: the ratchet must flag a
    latency INCREASE and stay quiet on an improvement — the inverse of
    every throughput family."""
    import sys as _sys
    _sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import bench_ratchet
    finally:
        _sys.path.pop(0)

    def rec(value, rnd):
        return {"metric": "serve_x_p99_ms", "value": value,
                "detail": {"platform": "cpu", "spread_frac": 0.0},
                "_file": f"SERVE_x_cpu_r{rnd:02d}.json", "_round": rnd}

    worse = bench_ratchet.compare_records(
        [rec(10.0, 1), rec(16.0, 2)], tolerance=0.10, noise=0.25)
    assert len(worse) == 1 and worse[0]["severity"] == "regression"
    assert worse[0]["drop_frac"] == pytest.approx(0.6)
    better = bench_ratchet.compare_records(
        [rec(10.0, 1), rec(7.0, 2)], tolerance=0.10, noise=0.25)
    assert better == []


def test_oov_request_refused_by_name(engine):
    queue = RequestQueue(engine.vocab)
    with pytest.raises(ModeRefusal, match="out-of-vocab"):
        queue.submit([5, engine.vocab + 7], 4)
    with pytest.raises(ValueError, match="non-empty"):
        queue.submit([], 4)
    with pytest.raises(ValueError, match="integers"):
        queue.submit([1.5, 2.5], 4)
    assert len(queue) == 0           # nothing leaked into the queue


# ---- snapshot -> serving promotion edges ---------------------------------

def test_promotion_falls_back_past_torn_newest(tmp_path, lm_state):
    """A torn newest snapshot must cost one interval of freshness,
    never the worker: promotion discards it (validity machinery) and
    serves the previous valid step."""
    model, state = lm_state
    d = str(tmp_path / "snaps")
    init_lm_snapshot(d, SIZE, seed=0)
    store = SnapshotStore(d)
    newer = state.replace(step=jnp.asarray(7, jnp.int32))
    store.save(newer, meta={"model": SIZE, "update_layout": "tree"})
    assert promote(d, SIZE).step == 7
    store.tear_latest()
    pm = promote(d, SIZE)
    assert pm.step == 0              # fell back, did not die
    # nothing valid left: promotion refuses loudly with a what-to-do
    for s in store.steps():
        os.remove(store._payload_path(s))
    with pytest.raises(ValueError, match="no valid snapshot"):
        promote(d, SIZE)


def test_promotion_refuses_cross_model_by_name(tmp_path):
    d = str(tmp_path / "snaps")
    init_lm_snapshot(d, SIZE, seed=0)
    with pytest.raises(ModeRefusal, match="--size"):
        promote(d, "lm_small")


def test_promotion_materializes_zero3_and_bucket_rows(tmp_path,
                                                      lm_state):
    """Row-layout snapshots (ZeRO-3 zero3_rows: params as 1/D bucket
    rows; ZeRO-1 bucket_rows: optimizer state as rows) promote to the
    BITWISE full param tree through the PR 12 materialize seam."""
    import jax

    from distributedtensorflowexample_tpu.parallel import (
        make_mesh, replicated_sharding)
    from distributedtensorflowexample_tpu.parallel.bucketing import (
        init_bucketed_opt_state)
    from distributedtensorflowexample_tpu.parallel.zero3 import (
        Zero3Layout)
    model, state = lm_state
    mesh = make_mesh(2)
    bucket_bytes = 16 << 10
    full = jax.tree.map(np.asarray, state.params)     # host truth copy
    repl = jax.device_put(state.params, replicated_sharding(mesh))

    # zero3_rows: params AND opt state as rows
    d3 = str(tmp_path / "z3")
    meta3 = {"model": SIZE, "update_layout": "zero3_rows",
             "mesh_size": 2, "bucket_bytes": bucket_bytes}
    layout = Zero3Layout(repl, bucket_bytes, mesh)
    opt = init_bucketed_opt_state(_tx(), repl, bucket_bytes, mesh)
    rows_state = state.replace(opt_state=opt,
                               params=layout.init_rows(repl))
    SnapshotStore(d3).save(rows_state, meta=meta3)
    pm = promote(d3, SIZE)
    assert pm.layout == "zero3_rows"
    got = jax.tree.map(np.asarray, pm.params)
    for a, b in zip(jax.tree.leaves(full), jax.tree.leaves(got)):
        assert a.dtype == b.dtype and np.array_equal(a, b)

    # bucket_rows: tree params, row opt state
    d1 = str(tmp_path / "z1")
    meta1 = {"model": SIZE, "update_layout": "bucket_rows",
             "mesh_size": 2, "bucket_bytes": bucket_bytes}
    z1_state = state.replace(opt_state=init_bucketed_opt_state(
        _tx(), state.params, bucket_bytes, mesh))
    SnapshotStore(d1).save(z1_state, meta=meta1)
    pm1 = promote(d1, SIZE)
    assert pm1.layout == "bucket_rows"
    for a, b in zip(jax.tree.leaves(full),
                    jax.tree.leaves(jax.tree.map(np.asarray,
                                                 pm1.params))):
        assert np.array_equal(a, b)

    # a rows manifest without its geometry meta is refused loudly
    d_bad = str(tmp_path / "bad")
    SnapshotStore(d_bad).save(rows_state, meta={
        "model": SIZE, "update_layout": "zero3_rows"})
    with pytest.raises(ValueError, match="mesh_size"):
        promote(d_bad, SIZE)


# ---- the decode-step HLO contract ----------------------------------------

def test_decode_hlo_contract_holds_and_catches_violations(engine):
    """The compiled decode step honors DECODE_HLO_CONTRACT (donation
    aliased, no donated-buffer copy, zero collectives, f32 ceiling) —
    and the contract actually has teeth against a donation-less
    compile of the same program."""
    import jax

    from distributedtensorflowexample_tpu.analysis.hlo_lint import (
        check_contract)
    hlo = engine.decode_hlo()
    assert check_contract(hlo, DECODE_HLO_CONTRACT) == []
    # Teeth: the SAME step compiled WITHOUT donation must fail the
    # aliasing clause — the contract distinguishes the schedules.
    undonated = jax.jit(engine._decode_fn).lower(
        engine.params, engine._ck, engine._cv, engine.last_tokens,
        engine.positions).compile().as_text()
    findings = check_contract(undonated, DECODE_HLO_CONTRACT)
    assert any(f.rule == "hlo-donation" for f in findings)


def test_serving_suite_is_wired_into_the_hlo_front():
    """graftlint's HLO front includes the serving decode contract, so
    `python -m tools.graftlint` gates it like the ZeRO schedules."""
    from distributedtensorflowexample_tpu.analysis import hlo_lint
    progs = hlo_lint.serving_suite()
    assert [p["mode"] for p in progs] == ["serve_decode"]
    assert progs[0]["contract"] is DECODE_HLO_CONTRACT
    fs = hlo_lint.check_contract(progs[0]["hlo"], progs[0]["contract"])
    assert fs == [], [f.message for f in fs]


# ---- knobs, helpers, import direction ------------------------------------

def test_env_knob_defaults(monkeypatch):
    monkeypatch.delenv("SERVE_SLOTS", raising=False)
    monkeypatch.delenv("SERVE_SLO_MS", raising=False)
    assert serve_slots_default() == 4
    assert serve_slo_ms_default() == 0.0
    monkeypatch.setenv("SERVE_SLOTS", "7")
    monkeypatch.setenv("SERVE_SLO_MS", "125.5")
    assert serve_slots_default() == 7
    assert serve_slo_ms_default() == 125.5
    monkeypatch.setenv("SERVE_SLOTS", "bogus")
    assert serve_slots_default() == 4


def test_percentiles_and_drive_file(tmp_path):
    assert percentile([], 0.5) == 0.0
    tape = sorted([1.0, 2.0, 3.0, 4.0, 100.0])
    assert percentile(tape, 0.5) == 3.0
    assert percentile(tape, 0.99) == 100.0
    df = DriveFile(str(tmp_path / "res.jsonl"))
    assert df.done_ids() == {}
    df.append(3, [1, 2])
    df.append(0, [9])
    with open(df.path, "a") as f:
        f.write('{"id": 7, "tok')          # torn tail: id 7 re-issues
    assert df.done_ids() == {3: [1, 2], 0: [9]}
    # deterministic prompts: same id -> same bytes, ids differ
    a = make_prompt(17, 250, seed=3)
    assert np.array_equal(a, make_prompt(17, 250, seed=3))
    assert not np.array_equal(a, make_prompt(18, 250, seed=3)) \
        or len(a) != len(make_prompt(18, 250, seed=3))


def test_obs_never_imports_serving():
    """The import direction is one-way: serving/ may use obs/ (metrics,
    ledger), obs/ must stay stdlib-only and serving-free — the
    graftlint import-graph proof guards the jax half; this guards the
    package-internal half."""
    import ast
    obs_dir = os.path.join(REPO, "distributedtensorflowexample_tpu",
                           "obs")
    for name in sorted(os.listdir(obs_dir)):
        if not name.endswith(".py"):
            continue
        with open(os.path.join(obs_dir, name)) as f:
            tree = ast.parse(f.read())
        for node in ast.walk(tree):
            mods = []
            if isinstance(node, ast.Import):
                mods = [a.name for a in node.names]
            elif isinstance(node, ast.ImportFrom) and node.module:
                mods = [node.module]
            assert not any(".serving" in m or m == "serving"
                           for m in mods), \
                f"obs/{name} imports serving ({mods})"
