"""serving/ — the continuous-batching inference engine (PR 15 + 17):
decode parity with the training forward, mid-decode admission
(continuous batching, not batch-drain), SLO admission, OOV refusal,
snapshot → serving promotion edges (torn-newest fallback, row-layout
materialization, sharded promotion), the decode-step HLO contracts
(replicated AND params-stay-sharded), speculative decoding's greedy
oracle, batched prefill, per-request sampling lanes, the prefix cache,
and the obs/ import direction.

Inline and tier-1-safe: lm_tiny at tiny slot/cache geometry.  The
sharded tests follow tests/test_collectives.py's precedent — shard_map
collectives over forced host devices run inline.  The engine fixture is
module-scoped so its prefill/decode compiles are paid once.  The
end-to-end serve_lm drill (real subprocess, eviction, TERM→143) lives
in tests/test_scheduler.py next to the other control-plane drills.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributedtensorflowexample_tpu.models import build_model
from distributedtensorflowexample_tpu.refusal import ModeRefusal
from distributedtensorflowexample_tpu.resilience.snapshot import (
    SnapshotStore)
from distributedtensorflowexample_tpu.serving.engine import (
    DECODE_HLO_CONTRACT, DecodeEngine, serve_slots_default)
from distributedtensorflowexample_tpu.serving.loadgen import (
    DriveFile, make_prompt)
from distributedtensorflowexample_tpu.serving.prefix import PrefixCache
from distributedtensorflowexample_tpu.serving.promote import (
    init_lm_snapshot, promote, promote_sharded)
from distributedtensorflowexample_tpu.serving.queue import (
    ContinuousBatcher, RequestQueue, percentile, serve_slo_ms_default)
from distributedtensorflowexample_tpu.serving.sampling import Sampler
from distributedtensorflowexample_tpu.serving.sharded import (
    SHARDED_DECODE_HLO_CONTRACT, ShardedDecodeEngine)
from distributedtensorflowexample_tpu.serving.spec import SpecDecoder
from distributedtensorflowexample_tpu.training.state import TrainState

pytestmark = pytest.mark.serving

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SIZE = "lm_tiny"
CACHE = 32


def _tx():
    return optax.sgd(0.1, momentum=0.9)


@pytest.fixture(scope="module")
def lm_state():
    model = build_model(SIZE)
    return model, TrainState.create(model, _tx(),
                                    jnp.zeros((1, 8), jnp.int32))


@pytest.fixture(scope="module")
def engine(lm_state):
    model, state = lm_state
    return DecodeEngine(model, state.params, slots=3, cache_len=CACHE)


@pytest.fixture(scope="module")
def draft_engine(lm_state):
    """A draft net that genuinely DISAGREES with the target (same
    architecture, params halved) — speculative acceptance must survive
    rejection, not just the self-draft fast path."""
    model, state = lm_state
    scaled = jax.tree.map(lambda a: a * 0.5, state.params)
    return DecodeEngine(model, scaled, slots=3, cache_len=CACHE)


@pytest.fixture(scope="module")
def sharded_engine(lm_state):
    from distributedtensorflowexample_tpu.parallel import (
        make_mesh, replicated_sharding)
    from distributedtensorflowexample_tpu.parallel.zero3 import (
        Zero3Layout)
    model, state = lm_state
    if len(jax.devices()) < 2:
        pytest.skip("params-stay-sharded decode needs >= 2 devices")
    mesh = make_mesh(2)
    # Host round-trip first: init_rows DONATES its input, and a
    # device_put of already-resident buffers may alias them — donating
    # an alias would delete the replicated fixture's params.
    repl = jax.device_put(jax.tree.map(np.asarray, state.params),
                          replicated_sharding(mesh))
    layout = Zero3Layout(repl, 16 << 10, mesh)
    return ShardedDecodeEngine(model, layout.init_rows(repl), layout,
                               slots=2, cache_len=CACHE)


def _greedy_reference(model, params, prompt, n, got=None):
    """Teacher-forced greedy through the TRAINING forward — the truth
    the engine must reproduce token-for-token.  With ``got`` (the
    engine's candidate tokens), verification is ONE forward over
    [prompt + got]: argmax at each position must select the next
    candidate, which by induction proves ``got`` IS the greedy chain —
    n eager growing-prefix forwards collapse to one.  Without ``got``
    it generates the chain the slow sequential way."""
    if got is not None:
        assert len(got) == n
        seq = [int(t) for t in prompt] + [int(t) for t in got]
        logits = model.apply({"params": params},
                             jnp.asarray([seq], jnp.int32), train=False)
        P = len(prompt)
        return [int(jnp.argmax(logits[0, P - 1 + i])) for i in range(n)]
    seq = list(int(t) for t in prompt)
    out = []
    for _ in range(n):
        logits = model.apply({"params": params},
                             jnp.asarray([seq], jnp.int32), train=False)
        nxt = int(jnp.argmax(logits[0, -1]))
        out.append(nxt)
        seq.append(nxt)
    return out


def _engine_greedy(engine, slot, prompt, n):
    toks = [engine.prefill(slot, np.asarray(prompt, np.int32),
                           max_new=n)]
    while len(toks) < n:
        step = engine.decode()
        toks.append(int(step[slot]))
    return toks


# ---- decode parity -------------------------------------------------------

def test_decode_matches_training_forward_token_exact(lm_state, engine):
    """The KV-cache decode (prefill + single-query steps) generates
    token-for-token what teacher-forced greedy through the training
    model generates: the cache path is the same math, masked rows
    contribute exactly 0.0 after the f32 exp."""
    model, state = lm_state
    prompt = [5, 9, 17, 3, 88, 120, 7]
    got = _engine_greedy(engine, 0, prompt, 6)
    assert got == _greedy_reference(model, state.params, prompt, 6,
                                    got=got)
    # A second prompt through a DIFFERENT slot, same engine, same truth
    # (slot reuse after retirement is the continuous-batching steady
    # state).
    prompt2 = [200, 1, 42]
    got2 = _engine_greedy(engine, 2, prompt2, 5)
    assert got2 == _greedy_reference(model, state.params, prompt2, 5,
                                     got=got2)


def test_prefill_bucket_table_and_refusals(engine):
    assert engine.bucket_for(3, 4) == 8          # smallest bucket
    assert engine.bucket_for(9, 4) == 16         # next power of two
    assert engine.bucket_for(CACHE - 4, 4) == CACHE
    with pytest.raises(ModeRefusal, match="--max_len"):
        engine.bucket_for(CACHE - 2, 4)          # can never finish
    with pytest.raises(ModeRefusal, match="--max_len"):
        # a cache longer than the positional table is refused at build
        DecodeEngine(engine.model, engine.params, slots=1,
                     cache_len=engine.model.max_len + 1)


# ---- continuous batching -------------------------------------------------

def test_request_admitted_mid_decode_completes_bitwise(lm_state, engine):
    """THE continuous-batching acceptance: B is admitted while A is
    mid-decode (A visibly unfinished at B's admission) and B's output
    equals B decoded solo — admission into an open slot of a RUNNING
    batch, with zero cross-request contamination."""
    model, state = lm_state
    prompt_a = [10, 20, 30, 40, 50]
    prompt_b = [7, 7, 99]
    solo_b = _engine_greedy(engine, 1, prompt_b, 5)

    queue = RequestQueue(engine.vocab)
    batcher = ContinuousBatcher(engine, queue, slo_ms=0.0)
    ra = queue.submit(prompt_a, 12, rid="A")
    batcher.step()                   # admits A, first decode
    batcher.step()
    assert not ra.done.is_set()      # A is mid-decode
    rb = queue.submit(prompt_b, 5, rid="B")
    batcher.step()                   # B admitted into an open slot NOW
    assert rb.admit_t is not None and not ra.done.is_set(), \
        "B must join while A is still decoding — batch-drain detected"
    while not (ra.done.is_set() and rb.done.is_set()):
        assert batcher.step() > 0
    assert ra.outcome == "ok" and rb.outcome == "ok"
    assert rb.tokens == solo_b       # bitwise: no contamination from A
    assert ra.tokens[:6] == _greedy_reference(
        model, state.params, prompt_a, 6, got=ra.tokens[:6])
    assert len(ra.tokens) == 12 and ra.first_token_t <= rb.admit_t


def test_slo_admission_rejects_predicted_misses(engine):
    """A request the step-time EWMA predicts past the SLO is rejected
    loudly at admission — never admitted to miss."""
    queue = RequestQueue(engine.vocab)
    batcher = ContinuousBatcher(engine, queue, slo_ms=50.0)
    batcher._step_ewma_s = 0.050     # 50 ms/step: 8 tokens >> 50 ms SLO
    req = queue.submit([1, 2, 3], 8)
    batcher.step()
    assert req.done.is_set() and req.outcome == "slo_rejected"
    # SLO off admits the same request
    batcher2 = ContinuousBatcher(engine, queue, slo_ms=0.0)
    batcher2._step_ewma_s = 0.050
    req2 = queue.submit([1, 2, 3], 2)
    batcher2.step()
    assert req2.outcome in ("", "ok") and req2.admit_t is not None
    while not req2.done.is_set():
        batcher2.step()
    assert req2.outcome == "ok"


def test_drain_answers_inflight_and_rejects_queued(engine):
    """The TERM half: drain decodes in-flight requests to completion
    and rejects the queued tail as ``drained`` — nothing admitted is
    lost, nothing queued hangs forever."""
    queue = RequestQueue(engine.vocab)
    batcher = ContinuousBatcher(engine, queue, slo_ms=0.0)
    inflight = [queue.submit([3, 1, 4], 6, rid=f"f{i}")
                for i in range(3)]                  # fills all 3 slots
    batcher.step()
    queued = queue.submit([9, 9], 4, rid="tail")    # no slot for it
    batcher.drain()
    assert all(r.done.is_set() and r.outcome == "ok" and
               len(r.tokens) == 6 for r in inflight)
    assert queued.outcome == "drained" and queued.tokens == []
    assert batcher.stats()["rejected"]["drained"] == 1
    # The submit/drain race is closed at the queue: a submit landing
    # AFTER drain is answered 'drained' synchronously — no caller is
    # ever left blocked on a request nothing will decode.
    late = queue.submit([1, 2], 3, rid="late")
    assert late.done.is_set() and late.outcome == "drained"
    assert len(queue) == 0
    # Retired slots are PARKED: decode advances only busy frontiers,
    # so an idle slot cannot drift toward the cache edge.
    assert engine.positions.tolist() == [0] * engine.slots


def test_oversized_request_refused_not_fatal(engine):
    """A request that can never finish inside the cache is refused by
    name AT ADMISSION — one impossible request costs itself, never the
    serving loop (the batcher thread has no handler above it)."""
    queue = RequestQueue(engine.vocab)
    batcher = ContinuousBatcher(engine, queue, slo_ms=0.0)
    bad = queue.submit(list(range(CACHE - 2)), 8)    # 30 + 8 > 32
    ok = queue.submit([1, 2, 3], 3)
    batcher.step()
    assert bad.done.is_set() and bad.outcome == "refused"
    assert "--max_len" in bad.error
    while not ok.done.is_set():
        batcher.step()                               # loop survived
    assert ok.outcome == "ok" and len(ok.tokens) == 3
    assert batcher.stats()["rejected"]["refused"] == 1


def test_ratchet_latency_metrics_gate_in_the_right_direction(tmp_path):
    """``*_ms`` metrics are lower-is-better: the ratchet must flag a
    latency INCREASE and stay quiet on an improvement — the inverse of
    every throughput family."""
    import sys as _sys
    _sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import bench_ratchet
    finally:
        _sys.path.pop(0)

    def rec(value, rnd):
        return {"metric": "serve_x_p99_ms", "value": value,
                "detail": {"platform": "cpu", "spread_frac": 0.0},
                "_file": f"SERVE_x_cpu_r{rnd:02d}.json", "_round": rnd}

    worse = bench_ratchet.compare_records(
        [rec(10.0, 1), rec(16.0, 2)], tolerance=0.10, noise=0.25)
    assert len(worse) == 1 and worse[0]["severity"] == "regression"
    assert worse[0]["drop_frac"] == pytest.approx(0.6)
    better = bench_ratchet.compare_records(
        [rec(10.0, 1), rec(7.0, 2)], tolerance=0.10, noise=0.25)
    assert better == []


def test_oov_request_refused_by_name(engine):
    queue = RequestQueue(engine.vocab)
    with pytest.raises(ModeRefusal, match="out-of-vocab"):
        queue.submit([5, engine.vocab + 7], 4)
    with pytest.raises(ValueError, match="non-empty"):
        queue.submit([], 4)
    with pytest.raises(ValueError, match="integers"):
        queue.submit([1.5, 2.5], 4)
    assert len(queue) == 0           # nothing leaked into the queue


# ---- snapshot -> serving promotion edges ---------------------------------

def test_promotion_falls_back_past_torn_newest(tmp_path, lm_state):
    """A torn newest snapshot must cost one interval of freshness,
    never the worker: promotion discards it (validity machinery) and
    serves the previous valid step."""
    model, state = lm_state
    d = str(tmp_path / "snaps")
    init_lm_snapshot(d, SIZE, seed=0)
    store = SnapshotStore(d)
    newer = state.replace(step=jnp.asarray(7, jnp.int32))
    store.save(newer, meta={"model": SIZE, "update_layout": "tree"})
    assert promote(d, SIZE).step == 7
    store.tear_latest()
    pm = promote(d, SIZE)
    assert pm.step == 0              # fell back, did not die
    # nothing valid left: promotion refuses loudly with a what-to-do
    for s in store.steps():
        os.remove(store._payload_path(s))
    with pytest.raises(ValueError, match="no valid snapshot"):
        promote(d, SIZE)


def test_promotion_refuses_cross_model_by_name(tmp_path):
    d = str(tmp_path / "snaps")
    init_lm_snapshot(d, SIZE, seed=0)
    with pytest.raises(ModeRefusal, match="--size"):
        promote(d, "lm_small")


def test_promotion_materializes_zero3_and_bucket_rows(tmp_path,
                                                      lm_state):
    """Row-layout snapshots (ZeRO-3 zero3_rows: params as 1/D bucket
    rows; ZeRO-1 bucket_rows: optimizer state as rows) promote to the
    BITWISE full param tree through the PR 12 materialize seam."""
    import jax

    from distributedtensorflowexample_tpu.parallel import (
        make_mesh, replicated_sharding)
    from distributedtensorflowexample_tpu.parallel.bucketing import (
        init_bucketed_opt_state)
    from distributedtensorflowexample_tpu.parallel.zero3 import (
        Zero3Layout)
    model, state = lm_state
    mesh = make_mesh(2)
    bucket_bytes = 16 << 10
    full = jax.tree.map(np.asarray, state.params)     # host truth copy
    repl = jax.device_put(state.params, replicated_sharding(mesh))

    # zero3_rows: params AND opt state as rows
    d3 = str(tmp_path / "z3")
    meta3 = {"model": SIZE, "update_layout": "zero3_rows",
             "mesh_size": 2, "bucket_bytes": bucket_bytes}
    layout = Zero3Layout(repl, bucket_bytes, mesh)
    opt = init_bucketed_opt_state(_tx(), repl, bucket_bytes, mesh)
    rows_state = state.replace(opt_state=opt,
                               params=layout.init_rows(repl))
    SnapshotStore(d3).save(rows_state, meta=meta3)
    pm = promote(d3, SIZE)
    assert pm.layout == "zero3_rows"
    got = jax.tree.map(np.asarray, pm.params)
    for a, b in zip(jax.tree.leaves(full), jax.tree.leaves(got)):
        assert a.dtype == b.dtype and np.array_equal(a, b)

    # bucket_rows: tree params, row opt state
    d1 = str(tmp_path / "z1")
    meta1 = {"model": SIZE, "update_layout": "bucket_rows",
             "mesh_size": 2, "bucket_bytes": bucket_bytes}
    z1_state = state.replace(opt_state=init_bucketed_opt_state(
        _tx(), state.params, bucket_bytes, mesh))
    SnapshotStore(d1).save(z1_state, meta=meta1)
    pm1 = promote(d1, SIZE)
    assert pm1.layout == "bucket_rows"
    for a, b in zip(jax.tree.leaves(full),
                    jax.tree.leaves(jax.tree.map(np.asarray,
                                                 pm1.params))):
        assert np.array_equal(a, b)

    # a rows manifest without its geometry meta is refused loudly
    d_bad = str(tmp_path / "bad")
    SnapshotStore(d_bad).save(rows_state, meta={
        "model": SIZE, "update_layout": "zero3_rows"})
    with pytest.raises(ValueError, match="mesh_size"):
        promote(d_bad, SIZE)


# ---- the decode-step HLO contract ----------------------------------------

def test_decode_hlo_contract_holds_and_catches_violations(engine):
    """The compiled decode step honors DECODE_HLO_CONTRACT (donation
    aliased, no donated-buffer copy, zero collectives, f32 ceiling) —
    and the contract actually has teeth against a donation-less
    compile of the same program."""
    import jax

    from distributedtensorflowexample_tpu.analysis.hlo_lint import (
        check_contract)
    hlo = engine.decode_hlo()
    assert check_contract(hlo, DECODE_HLO_CONTRACT) == []
    # Teeth: the SAME step compiled WITHOUT donation must fail the
    # aliasing clause — the contract distinguishes the schedules.
    undonated = jax.jit(engine._decode_fn).lower(
        engine.params, engine._ck, engine._cv, engine.last_tokens,
        engine.positions).compile().as_text()
    findings = check_contract(undonated, DECODE_HLO_CONTRACT)
    assert any(f.rule == "hlo-donation" for f in findings)


def test_serving_suite_is_wired_into_the_hlo_front():
    """graftlint's HLO front includes BOTH serving decode contracts
    (replicated 0-collective and sharded exactly-B-gathers), so
    `python -m tools.graftlint` gates them like the ZeRO schedules."""
    from distributedtensorflowexample_tpu.analysis import hlo_lint
    progs = hlo_lint.serving_suite()
    assert [p["mode"] for p in progs] == ["serve_decode",
                                          "serve_decode_sharded"]
    assert progs[0]["contract"] is DECODE_HLO_CONTRACT
    assert progs[1]["contract"] is SHARDED_DECODE_HLO_CONTRACT
    assert progs[1]["symbols"]["B"] >= 1
    for prog in progs:
        fs = hlo_lint.check_contract(prog["hlo"], prog["contract"],
                                     symbols=prog["symbols"])
        assert fs == [], [f.message for f in fs]


# ---- params-stay-sharded decode (PR 17 tentpole a) -----------------------

def test_sharded_decode_bitwise_and_resident_at_one_over_d(
        lm_state, engine, sharded_engine):
    """The row-resident engine generates token-for-token what the
    replicated engine generates (both slots live, one per device), and
    its LIVE params residency is exactly 1/D — the full tree is never
    materialized."""
    prompts = ([4, 8, 15, 16, 23], [42, 7])
    want = [_engine_greedy(engine, 0, prompts[0], 6),
            _engine_greedy(engine, 1, prompts[1], 6)]
    got = [[sharded_engine.prefill(s, np.asarray(p, np.int32),
                                   max_new=6)]
           for s, p in enumerate(prompts)]
    for _ in range(5):
        step = sharded_engine.decode(busy=[0, 1])
        got[0].append(int(step[0]))
        got[1].append(int(step[1]))
    assert got == want
    res = sharded_engine.params_residency()
    assert res["num_devices"] == 2
    assert res["frac_per_device"] == 0.5           # exactly 1/D
    assert res["params_bytes_per_device"] * 2 == \
        res["params_bytes_total"]


def test_sharded_engine_refuses_bad_geometry_by_name(sharded_engine):
    with pytest.raises(ModeRefusal, match="--slots"):
        ShardedDecodeEngine(sharded_engine.model, sharded_engine.rows,
                            sharded_engine.layout, slots=3,
                            cache_len=CACHE)       # 3 % 2 != 0
    with pytest.raises(ModeRefusal, match="--max_len"):
        ShardedDecodeEngine(sharded_engine.model, sharded_engine.rows,
                            sharded_engine.layout, slots=2,
                            cache_len=sharded_engine.model.max_len + 1)


def test_sharded_hlo_contract_pins_the_gather_schedule(sharded_engine):
    """Exactly one all-gather per bucket, pinned: the compiled step
    passes its own contract, FAILS the replicated path's 0-collective
    budget (an unbudgeted gather can never slip in silently), and a
    changed bucket count is a finding in either direction."""
    from distributedtensorflowexample_tpu.analysis.hlo_lint import (
        check_contract)
    hlo = sharded_engine.decode_hlo()
    B = sharded_engine.layout.num_buckets
    assert B >= 2                     # the schedule is a real schedule
    assert check_contract(hlo, SHARDED_DECODE_HLO_CONTRACT,
                          symbols={"B": B}) == []
    fs = check_contract(hlo, DECODE_HLO_CONTRACT)
    assert any(f.rule == "hlo-budget" and "all-gather" in f.message
               for f in fs), [f.message for f in fs]
    fs2 = check_contract(hlo, SHARDED_DECODE_HLO_CONTRACT,
                         symbols={"B": B + 1})
    assert any(f.rule == "hlo-budget" for f in fs2)


def test_promote_sharded_keeps_rows_and_serves_bitwise(tmp_path,
                                                       lm_state,
                                                       engine):
    """Sharded promotion from a TREE snapshot hands back rows (never a
    materialized tree on the serving path) that decode bitwise what
    the replicated promotion of the same snapshot decodes."""
    model, state = lm_state
    d = str(tmp_path / "snaps")
    init_lm_snapshot(d, SIZE, seed=0)
    spm = promote_sharded(d, SIZE, mesh_size=2, bucket_bytes=16 << 10)
    assert spm.source_layout == "tree"
    assert spm.layout.num_devices == 2
    seng = ShardedDecodeEngine(spm.model, spm.rows, spm.layout,
                               slots=2, cache_len=CACHE)
    pm = promote(d, SIZE)
    reng = DecodeEngine(pm.model, pm.params, slots=2, cache_len=CACHE)
    prompt = [9, 1, 1, 2, 3, 5, 8]
    want = [reng.prefill(0, np.asarray(prompt, np.int32), max_new=5)]
    got = [seng.prefill(0, np.asarray(prompt, np.int32), max_new=5)]
    for _ in range(4):
        want.append(int(reng.decode(busy=[0])[0]))
        got.append(int(seng.decode(busy=[0])[0]))
    assert got == want
    # a mesh that cannot exist is refused by name, not deadlocked
    with pytest.raises(ModeRefusal, match="--sharded_mesh"):
        promote_sharded(d, SIZE, mesh_size=len(jax.devices()) + 1)


# ---- speculative decoding (PR 17 tentpole b) -----------------------------

def test_spec_decode_is_bitwise_greedy_incl_mid_decode_admission(
        engine, draft_engine):
    """THE speculative acceptance: a disagreeing draft + batched
    verify emits exactly plain greedy's tokens — including for a
    request admitted mid-decode into a running speculative batch."""
    prompt_a, prompt_b = [10, 20, 30, 40, 50], [7, 7, 99]
    solo_a = _engine_greedy(engine, 0, prompt_a, 9)
    solo_b = _engine_greedy(engine, 1, prompt_b, 5)
    queue = RequestQueue(engine.vocab)
    spec = SpecDecoder(engine, draft_engine, k=3)
    batcher = ContinuousBatcher(engine, queue, slo_ms=0.0, spec=spec)
    ra = queue.submit(prompt_a, 9, rid="A")
    batcher.step()                    # admits A, first spec round
    assert not ra.done.is_set()       # A is mid-decode
    rb = queue.submit(prompt_b, 5, rid="B")
    while not (ra.done.is_set() and rb.done.is_set()):
        batcher.step()
    assert ra.outcome == "ok" and rb.outcome == "ok"
    assert ra.tokens == solo_a        # bitwise the greedy oracle
    assert rb.tokens == solo_b
    st = spec.stats()
    assert st["emitted"] == (9 - 1) + (5 - 1)   # first tokens = prefill
    assert st["rounds"] >= 2 and st["drafted"] >= 3 * st["rounds"] // 2
    assert 1.0 <= st["accept_len_mean"] <= 4.0


def test_spec_round_truncates_at_eos_like_greedy(engine, draft_engine):
    """A verify round may emit several tokens at once; an eos inside
    the window must truncate exactly where plain greedy stops — the
    round never hands out tokens greedy would not have produced."""
    prompt = [5, 9, 17, 3]
    ref = _engine_greedy(engine, 0, prompt, 8)
    eos = ref[4]

    def run(spec):
        queue = RequestQueue(engine.vocab)
        b = ContinuousBatcher(engine, queue, slo_ms=0.0, eos_id=eos,
                              spec=spec)
        r = queue.submit(prompt, 8, rid="E")
        while not r.done.is_set():
            b.step()
        return r.tokens

    expected = ref[:ref.index(eos) + 1]
    assert run(None) == expected
    assert run(SpecDecoder(engine, draft_engine, k=3)) == expected


def test_drain_completes_inflight_speculative_batch(engine,
                                                    draft_engine):
    """TERM under speculation: drain keeps drafting+verifying the
    in-flight batch to completion — outputs stay the greedy oracle's,
    and both engines' freed slots end parked."""
    prompts = {0: [3, 1, 4], 1: [2, 7, 1, 8], 2: [6, 6, 6]}
    solo = {s: _engine_greedy(engine, s, p, 7)
            for s, p in prompts.items()}
    queue = RequestQueue(engine.vocab)
    spec = SpecDecoder(engine, draft_engine, k=3)
    batcher = ContinuousBatcher(engine, queue, slo_ms=0.0, spec=spec)
    reqs = [queue.submit(p, 7, rid=f"d{s}")
            for s, p in sorted(prompts.items())]
    batcher.step()                    # admit all 3, one round
    assert not all(r.done.is_set() for r in reqs)
    batcher.drain()
    for s, r in enumerate(reqs):
        assert r.outcome == "ok" and r.tokens == solo[s]
    assert engine.positions.tolist() == [0] * engine.slots
    assert draft_engine.positions.tolist() == [0] * engine.slots


def test_spec_refusals_by_name(engine, draft_engine, lm_state):
    model, state = lm_state
    with pytest.raises(ValueError, match="k 0"):
        SpecDecoder(engine, draft_engine, k=0)
    with pytest.raises(ValueError, match="lockstep"):
        SpecDecoder(engine, DecodeEngine(model, state.params, slots=2,
                                         cache_len=CACHE), k=2)
    with pytest.raises(ModeRefusal, match="--spec_draft"):
        ContinuousBatcher(engine, RequestQueue(engine.vocab),
                          spec=SpecDecoder(engine, draft_engine, k=2),
                          sampler=Sampler(seed=0))


def test_spec_self_draft_full_acceptance_under_slot_churn(lm_state, engine):
    """The bench-shaped regression: MANY mixed-bucket requests churning
    through few slots, self-draft (draft == target params).  Two bugs
    hid here that the short solo oracles missed: (1) a separate
    single-query decode program whose bf16 logits could TIE-FLIP an
    argmax against the verify program's (decode is now the K == 1
    verify window — one program family), and (2) fully-accepted rounds
    (e == k+1) leaving one unwritten draft-cache row below the new
    frontier, collapsing acceptance within a few rounds.  With both
    fixed, a self-draft must match bitwise AND accept every proposal —
    acceptance below 100% here means the program family's numerics
    split again."""
    model, state = lm_state
    rng = np.random.default_rng(7)
    prompts = [(rng.integers(1, engine.vocab, size=int(
        rng.integers(4, 13))).astype(np.int32), 8) for _ in range(16)]

    def run(spec):
        queue = RequestQueue(engine.vocab)
        b = ContinuousBatcher(engine, queue, slo_ms=0.0, spec=spec)
        reqs = [queue.submit(p, m, rid=f"c{i}")
                for i, (p, m) in enumerate(prompts)]
        while any(not r.done.is_set() for r in reqs):
            b.step()
        return {r.rid: list(r.tokens) for r in reqs}

    greedy = run(None)
    # One self-draft engine for both k values: every admission prefills
    # the slot and parked rows are scatter-before-read, so leftover
    # state from the k=2 run cannot leak into k=4 — and the engines'
    # programs are shared process-wide anyway (module-level jit cache).
    draft = DecodeEngine(model, state.params, slots=engine.slots,
                         cache_len=CACHE)
    for k in (2, 4):
        spec = SpecDecoder(engine, draft, k=k)
        assert run(spec) == greedy, f"spec k={k} diverged from greedy"
        st = spec.stats()
        # Self-draft full acceptance is EXACT arithmetic: each request
        # needs 7 round tokens (prefill emits the first), so its rounds
        # emit min(k+1, remaining) until done — k=2: 3+3+1 with two
        # fully-accepted rounds (min(k, e) = 2, 2, 1 accepted), k=4:
        # 5+2 with one (4, 2).  Any shortfall = acceptance loss.
        assert st["emitted"] == 16 * 7
        per_req_accept = {2: 2 + 2 + 1, 4: 4 + 2}[k]
        assert st["accepted_draft"] == 16 * per_req_accept
        assert st["accept_len_mean"] == pytest.approx(
            {2: 7 / 3, 4: 7 / 2}[k], abs=1e-3)


# ---- batched prefill -----------------------------------------------------

def test_batched_prefill_matches_solo(engine):
    """One bucketed prefill_many over a burst produces per-slot exactly
    the solo prefill's token and cache (the continuation proves the
    cache: any cross-slot contamination diverges within a step)."""
    prompts = [[3, 1, 4, 1, 5], [9, 2, 6], [5, 3, 5, 8, 9, 7]]
    solo = [_engine_greedy(engine, 0, p, 4) for p in prompts]
    out = engine.prefill_many([(s, np.asarray(p, np.int32), 4)
                               for s, p in enumerate(prompts)])
    toks = [[int(out[s][0])] for s in range(3)]
    for _ in range(3):
        step = engine.decode(busy=[0, 1, 2])
        for s in range(3):
            toks[s].append(int(step[s]))
    assert toks == solo


# ---- sampling lanes ------------------------------------------------------

def test_sampler_lanes_are_deterministic_and_refuse_bad_knobs():
    rng = np.random.default_rng(0)
    logits = rng.normal(size=64).astype(np.float32)
    s1 = Sampler(temperature=0.8, top_k=5, seed=3)
    s2 = Sampler(temperature=0.8, top_k=5, seed=3)
    draws = [s1.sample("r1", i, logits) for i in range(16)]
    assert draws == [s2.sample("r1", i, logits) for i in range(16)]
    assert draws != [s1.sample("r2", i, logits) for i in range(16)]
    assert Sampler(top_k=1, seed=0).sample("x", 0, logits) == \
        int(np.argmax(logits))        # top-1 degenerates to greedy
    with pytest.raises(ValueError, match="--sample_temp"):
        Sampler(temperature=0.0)
    with pytest.raises(ValueError, match="--sample_top_k"):
        Sampler(top_k=-1)


def test_sampled_serving_is_deterministic_per_request_id(engine):
    """Same rid + same snapshot + same knobs → same tokens, regardless
    of admission order or slot placement (replayed runs agree)."""
    def run():
        queue = RequestQueue(engine.vocab)
        b = ContinuousBatcher(engine, queue, slo_ms=0.0,
                              sampler=Sampler(temperature=0.7,
                                              top_k=10, seed=5))
        r = queue.submit([8, 6, 7], 6, rid="fixed")
        while not r.done.is_set():
            b.step()
        return r.tokens

    a, b = run(), run()
    assert a == b and len(a) == 6


def test_sampler_refused_with_sharded_engine_by_name():
    class _NoLogitsSeam:                 # the sharded engine's shape
        slots = 2
    with pytest.raises(ModeRefusal, match="--sharded_mesh"):
        ContinuousBatcher(_NoLogitsSeam(), RequestQueue(16),
                          sampler=Sampler(seed=0))


# ---- prefix cache --------------------------------------------------------

def test_prefix_cache_full_and_partial_hits_bitwise(engine):
    """A full hit pays zero forward work, a partial hit pays only the
    suffix — both continue bitwise the cold path (the engine's masked
    pad rows make stored rows exact, not approximate)."""
    head = [11, 22, 33, 44, 55]
    ext = head + [66, 77]
    solo_head = _engine_greedy(engine, 0, head, 5)
    solo_ext = _engine_greedy(engine, 0, ext, 5)
    pc = PrefixCache(engine, capacity=8)

    def run(prompt, rid):
        queue = RequestQueue(engine.vocab)
        b = ContinuousBatcher(engine, queue, slo_ms=0.0,
                              prefix_cache=pc)
        r = queue.submit(prompt, 5, rid=rid)
        while not r.done.is_set():
            b.step()
        return r.tokens

    assert run(head, "cold") == solo_head
    assert pc.stats()["misses"] == 1 and pc.stats()["hits"] == 0
    assert run(head, "warm") == solo_head            # full hit
    assert pc.stats()["hits"] == 1
    assert run(ext, "extended") == solo_ext          # partial hit
    st = pc.stats()
    assert st["partial_hits"] == 1
    assert st["rows_reused"] == 2 * len(head)        # full 5 + partial 5
    assert st["entries"] == 2                        # head + ext
    with pytest.raises(ModeRefusal, match="--prefix_cache"):
        PrefixCache(object(), capacity=4)            # sharded-shaped


# ---- knobs, helpers, import direction ------------------------------------

def test_env_knob_defaults(monkeypatch):
    monkeypatch.delenv("SERVE_SLOTS", raising=False)
    monkeypatch.delenv("SERVE_SLO_MS", raising=False)
    assert serve_slots_default() == 4
    assert serve_slo_ms_default() == 0.0
    monkeypatch.setenv("SERVE_SLOTS", "7")
    monkeypatch.setenv("SERVE_SLO_MS", "125.5")
    assert serve_slots_default() == 7
    assert serve_slo_ms_default() == 125.5
    monkeypatch.setenv("SERVE_SLOTS", "bogus")
    assert serve_slots_default() == 4


def test_percentiles_and_drive_file(tmp_path):
    assert percentile([], 0.5) == 0.0
    tape = sorted([1.0, 2.0, 3.0, 4.0, 100.0])
    assert percentile(tape, 0.5) == 3.0
    assert percentile(tape, 0.99) == 100.0
    df = DriveFile(str(tmp_path / "res.jsonl"))
    assert df.done_ids() == {}
    df.append(3, [1, 2])
    df.append(0, [9])
    with open(df.path, "a") as f:
        f.write('{"id": 7, "tok')          # torn tail: id 7 re-issues
    assert df.done_ids() == {3: [1, 2], 0: [9]}
    # deterministic prompts: same id -> same bytes, ids differ
    a = make_prompt(17, 250, seed=3)
    assert np.array_equal(a, make_prompt(17, 250, seed=3))
    assert not np.array_equal(a, make_prompt(18, 250, seed=3)) \
        or len(a) != len(make_prompt(18, 250, seed=3))


def test_obs_never_imports_serving():
    """The import direction is one-way: serving/ may use obs/ (metrics,
    ledger), obs/ must stay stdlib-only and serving-free — the
    graftlint import-graph proof guards the jax half; this guards the
    package-internal half."""
    import ast
    obs_dir = os.path.join(REPO, "distributedtensorflowexample_tpu",
                           "obs")
    for name in sorted(os.listdir(obs_dir)):
        if not name.endswith(".py"):
            continue
        with open(os.path.join(obs_dir, name)) as f:
            tree = ast.parse(f.read())
        for node in ast.walk(tree):
            mods = []
            if isinstance(node, ast.Import):
                mods = [a.name for a in node.names]
            elif isinstance(node, ast.ImportFrom) and node.module:
                mods = [node.module]
            assert not any(".serving" in m or m == "serving"
                           for m in mods), \
                f"obs/{name} imports serving ({mods})"
