"""End-to-end self-healing ACCEPTANCE drills (the ISSUE's criterion),
via the real harness in tools/heal_drill.py: faultline children under a
FleetSupervisor, the remediation engine watching real health files and
ledger rows, real actuators — and the healed timeline proved BITWISE
against an uninterrupted reference run (steps_lost == 0).

Runs on the fast softmax workload (the lm_tiny battery generates the
checked-in HEAL_lm_cpu_r16.json record); each child is a fresh jax
subprocess, so this file runs as an isolated subprocess during
full-suite runs (tests/isolation_list.py) — wall-time containment.
"""

import io
import json
import os
import sys
from contextlib import redirect_stdout

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = [pytest.mark.heal, pytest.mark.faults]


def _heal_drill():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import heal_drill
    finally:
        sys.path.pop(0)
    return heal_drill


def _by_metric(rows):
    return {r["metric"]: r for r in rows}


def test_nan_rollback_drill_bitwise(tmp_path):
    """NaN-poison → the remediator (fleet retries=0: the POLICY owns
    the restart decision) rolls back to the pinned last-good snapshot
    and relaunches; the healed run's digest and concatenated tape are
    bitwise the uninterrupted run's."""
    hd = _heal_drill()
    rows = _by_metric(hd.drill_nan(str(tmp_path), "softmax"))
    rec = rows["heal_nan_steps_lost"]
    assert rec["value"] == 0
    assert rec["detail"]["bitwise_resume"] is True
    assert rec["detail"]["heals"] == 1          # one heal relaunch
    assert rows["heal_nan_mttr_ms"]["value"] > 0
    # the rollback decision is on the ledger, renderable by obs_query
    ledger = os.path.join(str(tmp_path), "nan", "RUNS.jsonl")
    events = [json.loads(l)["event"] for l in open(ledger) if l.strip()]
    assert "heal_detect" in events and "heal_rollback" in events
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import obs_query
    finally:
        sys.path.pop(0)
    buf = io.StringIO()
    with redirect_stdout(buf):
        assert obs_query.main(["why", "drill", "--ledger", ledger]) == 0
    out = buf.getvalue()
    assert "anomaly detected: nan_loss" in out
    assert "HEALED by rollback" in out
    assert "'last_good'" in out                 # the pinned step named


def test_ckpt_shard_fault_drills_bitwise(tmp_path):
    """Shard-redundant checkpointing under REAL fleet recovery: a D=4
    ZeRO-3 gang is preempted, its snapshot set is damaged post-exit
    (one rank's directory deleted; separately one payload byte
    flipped), the resume agreement still votes for that step and the
    relaunch reconstructs the shard from its ring mirror — final state
    bitwise the uninterrupted run, zero steps lost, zero unrecovered
    mismatches."""
    hd = _heal_drill()
    rows = _by_metric(hd.drill_ckpt(str(tmp_path)))
    for plan in ("shard_loss", "bitflip"):
        rec = rows[f"heal_ckpt_{plan}_steps_lost"]
        assert rec["value"] == 0
        assert rec["detail"]["bitwise_resume"] is True
        assert rec["detail"]["reconstructs"] >= 1
        assert rows[f"heal_ckpt_{plan}_mttr_ms"]["value"] is not None
    assert rows["ckpt_shard_restore_failures"]["value"] == 0
    assert rows["ckpt_digest_mismatch_unrecovered"]["value"] == 0
    # the reconstruction (and for bitflip, the rot catch) is on the
    # ledger and renderable by obs_query why
    ledger = os.path.join(str(tmp_path), "ckpt_bitflip", "RUNS.jsonl")
    events = [json.loads(l)["event"] for l in open(ledger) if l.strip()]
    assert "ckpt_digest_mismatch" in events
    assert "ckpt_reconstruct" in events
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import obs_query
    finally:
        sys.path.pop(0)
    buf = io.StringIO()
    with redirect_stdout(buf):
        assert obs_query.main(["why", "drill", "--ledger", ledger]) == 0
    out = buf.getvalue()
    assert "BIT ROT caught" in out
    assert "ring mirror" in out


def test_slow_rank_evict_drill_bitwise(tmp_path):
    """Straggler → loss-free eviction (request_stop → TERM→143) →
    relaunch resumes from the agreed step — bitwise, zero lost steps."""
    hd = _heal_drill()
    rows = _by_metric(hd.drill_slow_rank(str(tmp_path), "softmax",
                                         delay_s=1.5))
    rec = rows["heal_slow_rank_steps_lost"]
    assert rec["value"] == 0
    assert rec["detail"]["bitwise_resume"] is True
    assert rec["detail"]["heals"] >= 1
    assert rec["detail"]["action"] == "heal_evict"
    assert rows["heal_slow_rank_mttd_ms"]["value"] is not None
    ledger = os.path.join(str(tmp_path), "slow_rank", "RUNS.jsonl")
    events = [json.loads(l)["event"] for l in open(ledger) if l.strip()]
    assert "heal_evict" in events
