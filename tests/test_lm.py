"""graft-LM flagship workload (PR 8): model/data/trainer wiring, knob
parity at lm_tiny, the OOV-poison -> NaNGuard path, and the bench/ratchet
surface.

Inline and tier-1-safe: lm_tiny at short sequences, single-digit fused
dispatches per test (the test_collectives discipline).  lm_base-scale
work is bench_lm.py's job (and the one param-count check here uses
eval_shape — no 57M-param init ever runs in tier-1).

Golden collective multisets for the LM trainer live in
tests/test_collectives.py next to the other per-trainer goldens.
"""

import json
import os
import subprocess
import sys

import hashlib

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributedtensorflowexample_tpu.data import DeviceDataset
from distributedtensorflowexample_tpu.data.lm import (
    LM_SEQ_LEN, load_lm, make_synthetic_tokens)
from distributedtensorflowexample_tpu.models import (
    LM_SIZES, LM_VOCAB, build_model)
from distributedtensorflowexample_tpu.parallel import (
    make_mesh, replicated_sharding)
from distributedtensorflowexample_tpu.parallel.bucketing import (
    DEFAULT_BUCKET_BYTES, init_bucketed_opt_state)
from distributedtensorflowexample_tpu.parallel.sync import (
    make_indexed_train_step, make_resident_eval)
from distributedtensorflowexample_tpu.training.state import TrainState

pytestmark = pytest.mark.lm

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SEQ = 32            # short drill sequences; the shipped split is 128


def _data(n=256, seq=SEQ, seed=0):
    return load_lm("", "train", seed=seed, num=n, seq_len=seq)


def _tx():
    return optax.sgd(0.1, momentum=0.9)


def _state(mesh, batch, seq=SEQ, tx=None, **kw):
    model = build_model("lm_tiny", **kw)
    return TrainState.create_sharded(model, tx or _tx(), (batch, seq), 0,
                                     replicated_sharding(mesh))


def _digest(tree) -> str:
    h = hashlib.sha256()
    for leaf in jax.tree.leaves(tree):
        h.update(np.asarray(leaf).tobytes())
    return h.hexdigest()


# ---- model + registry ---------------------------------------------------

def test_registry_sizes_and_lm_base_param_floor():
    """The size ladder is registered, and lm_base clears the >=50M-param
    floor the scale-up exists for — counted via eval_shape (no init)."""
    for size in LM_SIZES:
        assert build_model(size) is not None
    model = build_model("lm_base")
    shapes = jax.eval_shape(
        lambda r: model.init({"params": r, "dropout": r},
                             jnp.zeros((2, 8), jnp.int32), train=False),
        jax.random.PRNGKey(0))
    n_params = sum(int(np.prod(l.shape))
                   for l in jax.tree.leaves(shapes["params"]))
    assert n_params >= 50_000_000, n_params
    # BN-free by construction: no batch_stats collection exists, so the
    # bucket_grads/ZeRO-1 BatchNorm refusals can never trigger.
    assert "batch_stats" not in shapes
    with pytest.raises(ValueError, match="unknown LM size"):
        from distributedtensorflowexample_tpu.models import build_lm
        build_lm("lm_huge")
    with pytest.raises(ValueError, match="remat"):
        build_model("lm_tiny", remat="bogus").init(
            {"params": jax.random.PRNGKey(0)},
            jnp.zeros((1, 4), jnp.int32))


def test_oov_tokens_poison_logits_to_nan():
    """XLA gathers clamp out-of-range ids silently; the LM refuses
    loudly instead — any token >= vocab NaNs the logits, which is what
    hands a corrupt_batch straight to NaNGuardHook."""
    model = build_model("lm_tiny")
    rng = jax.random.PRNGKey(0)
    good = jnp.zeros((2, 8), jnp.int32)
    variables = model.init({"params": rng, "dropout": rng}, good)
    ok = model.apply(variables, good)
    assert bool(jnp.all(jnp.isfinite(ok)))
    bad = good.at[1, 3].set(LM_VOCAB)       # first illegal id
    poisoned = model.apply(variables, bad)
    assert bool(jnp.all(jnp.isnan(poisoned)))
    # uint8 input works too (the resident-split storage dtype).
    ok8 = model.apply(variables, jnp.zeros((2, 8), jnp.uint8))
    np.testing.assert_array_equal(np.asarray(ok8), np.asarray(ok))


# ---- token data path ----------------------------------------------------

def test_token_split_storage_marker_and_quantize_off():
    x, y = _data()
    assert x.dtype == np.uint8 and y.dtype == np.int32
    assert x.shape == (256, SEQ) and y.shape == (256, SEQ)
    # Targets are the 1-shifted inputs (same underlying walk).
    full = make_synthetic_tokens(256, SEQ, LM_VOCAB, 0, sample_seed=1)
    np.testing.assert_array_equal(x, full[:, :-1].astype(np.uint8))
    np.testing.assert_array_equal(y, full[:, 1:])

    ds = DeviceDataset(x, y, 16, token_data=True)
    assert ds.dequant is None and ds.dequant_impl is None
    data = ds.peek()
    assert "tokens" in data and data["images"].dtype == jnp.uint8
    off = DeviceDataset(x, y, 16, token_data=True, quantize="off")
    assert off.peek()["images"].dtype == jnp.int32

    with pytest.raises(ValueError, match="integer token split"):
        DeviceDataset(x.astype(np.float32), y, 16, token_data=True)
    wide = x.astype(np.int32) + 300          # ids past the byte range
    with pytest.raises(ValueError, match="uint8 range"):
        DeviceDataset(wide, y, 16, token_data=True)
    assert DeviceDataset(wide, y, 16, token_data=True,
                         quantize="off").peek()["images"].dtype == jnp.int32


def test_single_device_step_and_resident_eval_token_denominator():
    x, y = _data(n=64, seq=16)
    ds = DeviceDataset(x, y, 16, token_data=True)
    model = build_model("lm_tiny")
    state = TrainState.create(model, _tx(), jnp.zeros((16, 16), jnp.int32))
    step = make_indexed_train_step(16, ds.steps_per_epoch,
                                   num_slots=ds.num_slots)
    state, metrics = step(state, next(ds))
    loss = float(metrics["loss"])
    acc = float(metrics["accuracy"])
    assert np.isfinite(loss) and 0.0 <= acc <= 1.0
    # Resident eval normalizes PER TOKEN: cross-check against a direct
    # argmax count over the full split.
    ev = make_resident_eval(x, y, batch_size=32, token_data=True)
    got = ev(state)
    logits = model.apply({"params": state.params}, jnp.asarray(x))
    want = float(np.mean(np.argmax(np.asarray(logits), -1) == y))
    assert got == pytest.approx(want, abs=1e-9)


# ---- knob parity at lm_tiny (the satellite gates) -----------------------

def _run_pair(mesh, step_a, state_a, step_b, state_b, seq=SEQ, calls=2,
              batch=32, seed=3):
    x, y = _data(seq=seq, seed=seed)
    ds_a = DeviceDataset(x, y, batch, mesh=mesh, seed=seed,
                         token_data=True)
    ds_b = DeviceDataset(x, y, batch, mesh=mesh, seed=seed,
                         token_data=True)
    with mesh:
        for _ in range(calls):
            state_a, m_a = step_a(state_a, next(ds_a))
            state_b, m_b = step_b(state_b, next(ds_b))
    return state_a, m_a, state_b, m_b


# The LM parity standard: the FORWARD pass is bitwise (identical ops,
# identical fusion — pinned via the loss below), but the bf16 einsum
# chain's backward reassociates under remat/shard_map recompilation, so
# gradients (hence params after a step) carry one-bf16-ulp-scale noise
# — measured max |delta| ~4e-5 after 2 steps at lm_tiny.  Same standard
# and reason as the conv models' shard_update gate: summation order,
# not math.  (ResNet's remat stays bitwise on this backend — its conv
# backward compiles identically under remat; the LM's einsum chain is
# what the compiler reassociates.)
_ATOL, _RTOL = 5e-4, 1e-3


def _assert_close(a, b):
    jax.tree.map(lambda p, q: np.testing.assert_allclose(
        np.asarray(p, np.float64), np.asarray(q, np.float64),
        rtol=_RTOL, atol=_ATOL), a, b)


def test_remat_block_parity():
    """remat='block' on the LM: the recomputed forward IS the forward
    (loss bitwise at step one), params to the bf16 parity standard."""
    mesh = make_mesh()
    x, y = _data(seed=3)
    ds = DeviceDataset(x, y, 32, mesh=mesh, seed=3, token_data=True)
    plain = make_indexed_train_step(32, ds.steps_per_epoch, mesh=mesh,
                                    num_slots=ds.num_slots)
    remat = make_indexed_train_step(32, ds.steps_per_epoch, mesh=mesh,
                                    num_slots=ds.num_slots)
    s_p = _state(mesh, 32)
    s_r = _state(mesh, 32, remat="block")
    ds_a = DeviceDataset(x, y, 32, mesh=mesh, seed=3, token_data=True)
    ds_b = DeviceDataset(x, y, 32, mesh=mesh, seed=3, token_data=True)
    with mesh:
        s_p, m_p = plain(s_p, next(ds_a))
        s_r, m_r = remat(s_r, next(ds_b))
        # Step one: SAME initial params -> the forward (and its loss)
        # must be bitwise identical; only the backward reassociates.
        assert float(m_p["loss"]) == float(m_r["loss"])
        s_p, m_p = plain(s_p, next(ds_a))
        s_r, m_r = remat(s_r, next(ds_b))
    _assert_close(s_p.params, s_r.params)


def test_bucket_grads_size_invariance_and_parity():
    """Bucketing is bitwise ACROSS bucket sizes on the LM (same
    additions, regrouped); vs the GSPMD default the shard_map backward
    may fuse the einsum chain differently, so that gate is allclose —
    the conv-model standard, same reason (summation order, not math)."""
    mesh = make_mesh()
    x, y = _data(seed=3)
    ds = DeviceDataset(x, y, 32, mesh=mesh, seed=3, token_data=True)
    mk = lambda bb: make_indexed_train_step(
        32, ds.steps_per_epoch, mesh=mesh, num_slots=ds.num_slots,
        bucket_bytes=bb)
    ref = make_indexed_train_step(32, ds.steps_per_epoch, mesh=mesh,
                                  num_slots=ds.num_slots)
    big, small = mk(DEFAULT_BUCKET_BYTES), mk(16 << 10)
    s_big, s_small, s_ref = (_state(mesh, 32) for _ in range(3))
    s_big, m_big, s_small, m_small = _run_pair(mesh, big, s_big,
                                               small, s_small)
    assert _digest(s_big.params) == _digest(s_small.params)
    assert float(m_big["loss"]) == float(m_small["loss"])
    x2, y2 = _data(seed=3)
    ds_r = DeviceDataset(x2, y2, 32, mesh=mesh, seed=3, token_data=True)
    with mesh:
        for _ in range(2):
            s_ref, m_ref = ref(s_ref, next(ds_r))
    _assert_close(s_ref.params, s_big.params)
    assert float(m_ref["loss"]) == pytest.approx(float(m_big["loss"]),
                                                 abs=1e-3)


def test_composed_zero1_schedule_parity_and_state_residency():
    """--bucket_grads + --shard_update at lm_tiny: the explicit
    per-bucket RS+AG schedule trains the same model (allclose standard)
    while every non-scalar optimizer leaf lives as a 1/D bucket row —
    the measured-at-lm_base residency win, structurally pinned here."""
    mesh = make_mesh()
    D = mesh.size
    x, y = _data(seed=3)
    ds = DeviceDataset(x, y, 32, mesh=mesh, seed=3, token_data=True)
    ref = make_indexed_train_step(32, ds.steps_per_epoch, mesh=mesh,
                                  num_slots=ds.num_slots)
    z1 = make_indexed_train_step(32, ds.steps_per_epoch, mesh=mesh,
                                 num_slots=ds.num_slots,
                                 bucket_bytes=DEFAULT_BUCKET_BYTES,
                                 bucket_shard_update=True)
    s_ref = _state(mesh, 32)
    s_z = _state(mesh, 32)
    s_z = s_z.replace(opt_state=init_bucketed_opt_state(
        _tx(), s_z.params, DEFAULT_BUCKET_BYTES, mesh))
    import bench_lm
    repl = bench_lm.optstate_bytes_per_device(s_ref.opt_state)
    shard = bench_lm.optstate_bytes_per_device(s_z.opt_state)
    assert shard <= repl / D * 1.05 + 64        # 1/D (+row padding)
    s_ref, m_ref, s_z, m_z = _run_pair(mesh, ref, s_ref, z1, s_z)
    _assert_close(s_ref.params, s_z.params)


def test_zero3_schedule_parity_and_full_state_residency():
    """--shard_params at lm_tiny (PR 12): the ZeRO-3 per-bucket AG/RS
    schedule trains the same model (allclose standard — the shard_map
    backward reassociates the einsum chain, same as every other knob)
    while params AND optimizer moments live as 1/D bucket rows — the
    full-state residency win bench_lm measures at lm_base, structurally
    pinned here.  Overlap on/off is checked bitwise-equal in
    tests/test_zero3.py; this gate uses the default double buffer."""
    from distributedtensorflowexample_tpu.parallel.zero3 import Zero3Layout
    from distributedtensorflowexample_tpu.utils.profiling import (
        state_residency_per_device)
    mesh = make_mesh()
    D = mesh.size
    x, y = _data(seed=3)
    ds = DeviceDataset(x, y, 32, mesh=mesh, seed=3, token_data=True)
    ref = make_indexed_train_step(32, ds.steps_per_epoch, mesh=mesh,
                                  num_slots=ds.num_slots)
    s_ref = _state(mesh, 32)
    s_z = _state(mesh, 32)
    repl = state_residency_per_device(s_ref)
    layout = Zero3Layout(s_z.params, DEFAULT_BUCKET_BYTES, mesh)
    z3 = make_indexed_train_step(32, ds.steps_per_epoch, mesh=mesh,
                                 num_slots=ds.num_slots,
                                 zero3_layout=layout)
    s_z = s_z.replace(opt_state=init_bucketed_opt_state(
        _tx(), s_z.params, DEFAULT_BUCKET_BYTES, mesh))
    s_z = s_z.replace(params=layout.init_rows(s_z.params))
    rows = state_residency_per_device(s_z)
    # params+opt both 1/D (+row padding): the FULL-state shrink, not
    # just ZeRO-1's opt-only one.
    assert rows["params_bytes_per_device"] <= \
        repl["params_bytes_per_device"] / D * 1.05 + 64
    assert rows["state_bytes_per_device"] <= \
        repl["state_bytes_per_device"] / D * 1.05 + 128
    s_ref, m_ref, s_z, m_z = _run_pair(mesh, ref, s_ref, z3, s_z)
    full = layout.materialize(s_z.params)
    _assert_close(s_ref.params, full)


def test_shard_update_constraint_form_parity():
    """The GSPMD-constraint --shard_update on the LM: same training
    (allclose — summation order, the documented standard) with the
    optimizer state laid out 1/D per device."""
    from distributedtensorflowexample_tpu.training.optimizers import (
        cross_replica_update_sharding, update_shardings)
    mesh = make_mesh()
    x, y = _data(seed=3)
    ds = DeviceDataset(x, y, 32, mesh=mesh, seed=3, token_data=True)
    ref = make_indexed_train_step(32, ds.steps_per_epoch, mesh=mesh,
                                  num_slots=ds.num_slots)
    su = make_indexed_train_step(32, ds.steps_per_epoch, mesh=mesh,
                                 num_slots=ds.num_slots)
    s_ref = _state(mesh, 32)
    s_su = _state(mesh, 32, tx=cross_replica_update_sharding(_tx(), mesh))
    s_su = s_su.replace(opt_state=jax.device_put(
        s_su.opt_state, update_shardings(s_su.opt_state, mesh)))
    import bench_lm
    assert bench_lm.optstate_bytes_per_device(s_su.opt_state) < \
        bench_lm.optstate_bytes_per_device(s_ref.opt_state)
    s_ref, m_ref, s_su, m_su = _run_pair(mesh, ref, s_ref, su, s_su,
                                         calls=1)
    _assert_close(s_ref.params, s_su.params)


# ---- trainer surface ----------------------------------------------------

def test_trainer_lm_end_to_end(tmp_log_dir):
    from distributedtensorflowexample_tpu.trainers.trainer_lm import main
    summary = main(["--train_steps", "24", "--batch_size", "4",
                    "--log_every", "24", "--log_dir", tmp_log_dir,
                    "--resume", "false", "--eval_every", "0"])
    assert summary["steps"] == 24
    # 24 steps already lift per-token accuracy well above the 1/250
    # uniform floor (the Markov structure is that learnable).
    assert summary["final_accuracy"] > 0.05


def test_trainer_lm_refuses_host_fed_path(tmp_log_dir):
    from distributedtensorflowexample_tpu.trainers.trainer_lm import main
    with pytest.raises(ValueError, match="device-resident"):
        main(["--train_steps", "4", "--batch_size", "4",
              "--device_data", "off", "--log_dir", tmp_log_dir,
              "--resume", "false"])


# ---- faults: corrupt_batch on token pipelines ---------------------------

@pytest.mark.faults
def test_corrupt_batch_token_semantics_and_nan_loss_refusal():
    from distributedtensorflowexample_tpu.resilience import (
        FaultPlan, FaultyBatches)
    tokens = {"image": jnp.zeros((4, 8), jnp.int32),
              "label": jnp.zeros((4, 8), jnp.int32)}
    plan = FaultPlan.parse("corrupt_batch@1", 4)
    fb = FaultyBatches(iter([tokens] * 2), plan)
    bad = np.asarray(next(fb)["image"])
    assert bad.dtype == np.int32
    assert (bad >= LM_VOCAB).any()          # garbage ids land OOV
    # uint8 token batches corrupt to random bytes — still OOV-capable
    # because LM_VOCAB < 256 by design.
    u8 = {"image": jnp.zeros((4, 64), jnp.uint8),
          "label": jnp.zeros((4, 64), jnp.int32)}
    fb8 = FaultyBatches(iter([u8] * 2), FaultPlan.parse("corrupt_batch@1", 4))
    bad8 = np.asarray(next(fb8)["image"])
    assert bad8.dtype == np.uint8 and (bad8 >= LM_VOCAB).any()
    # nan_loss on ANY integer pipeline is refused loudly (no NaN int
    # exists; np.full would wrap to silent garbage).
    nb = FaultyBatches(iter([tokens] * 2), FaultPlan.parse("nan_loss@1", 4))
    with pytest.raises(ValueError, match="no NaN integer"):
        next(nb)


@pytest.mark.faults
def test_named_plan_corrupt_batch_rank_targets_rank_1():
    from distributedtensorflowexample_tpu.resilience import FaultPlan
    plan = FaultPlan.parse("corrupt_batch_rank", 16)
    assert len(plan.specs) == 1 and plan.specs[0].rank == 1
    assert plan.specs[0].kind == "corrupt_batch"
    assert not plan.for_rank(0).specs          # other ranks unaffected
    assert plan.for_rank(1).specs == plan.specs
    # One reproducible scenario: every rank parsing the same (text,
    # steps, seed) triple sees the same seed-drawn mid-run anchor.
    assert plan.specs[0].step == \
        FaultPlan.parse("corrupt_batch_rank", 16).specs[0].step
    assert 1 <= plan.specs[0].step < 16


@pytest.mark.faults
def test_faultline_lm_corrupt_batch_trips_nan_guard(tmp_path):
    """ACCEPTANCE for the fault satellite: corrupt_batch on the LM
    trainer -> garbage ids -> OOV poison -> NaNGuard kills the run
    before a poisoned snapshot, through the real faultline CLI."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "faultline.py"),
         "--plan", "corrupt_batch", "--model", "lm_tiny",
         "--steps", "5", "--workdir", str(tmp_path / "fl")],
        capture_output=True, text=True, timeout=300)
    line = json.loads(out.stdout.strip().splitlines()[-1])
    assert line["status"] == "fault"
    assert "non-finite loss" in line["error"]
    # The healthy prefix made it to the tape; the poisoned step did not.
    assert all(np.isfinite(l) for _, l in line["losses"])


# ---- bench_lm + ratchet surface -----------------------------------------

def test_bench_lm_compile_only_ab_and_record(tmp_path):
    """bench_lm at lm_tiny, base+remat knobs, compile-only A/B: emits
    the tokens/sec + MFU lines with the flops-audit denominator, a
    positive remat activation saving, and a ratchet-parseable JSON-lines
    artifact."""
    import bench_lm
    out = tmp_path / "BENCH_lm_cpu_r99.json"
    rc = bench_lm.main(["--throughput_size", "lm_tiny", "--size",
                        "lm_tiny", "--batch_per_chip", "2", "--steps",
                        "2", "--unroll", "1", "--repeats", "1",
                        "--seq_len", "16", "--ab_batch_per_chip", "2",
                        "--ab_steps", "0", "--knobs", "base,remat",
                        "--json", str(out)])
    assert rc == 0
    recs = [json.loads(l) for l in out.read_text().splitlines()]
    by_metric = {r["metric"]: r for r in recs}
    tput = by_metric["lm_tiny_tokens_per_sec_per_chip"]
    assert tput_positive(tput)
    d = tput["detail"]
    assert d["token_storage"] == "uint8"
    assert d["model_flops_per_step_per_device"] > 0
    assert d["bytes_audit"]["bytes_per_step"] > 0
    mfu = by_metric["lm_tiny_mfu"]
    assert mfu["value"] > 0
    assert mfu["detail"]["model_flops_per_step_per_device"] == \
        d["model_flops_per_step_per_device"]
    # MFU = per-device flops x rate / per-chip peak (no second /n).
    assert mfu["value"] == pytest.approx(
        d["model_flops_per_step_per_device"] * d["steps_per_sec"]
        / mfu["detail"]["peak_flops"], rel=1e-4)
    sav = by_metric["lm_tiny_remat_activation_savings_frac"]
    assert 0 < sav["value"] < 1
    assert by_metric["lm_tiny_knob_ab_matrix"]["detail"]["matrix"][
        "remat"]["memory"]["temp_bytes"] > 0


def tput_positive(rec):
    return rec["unit"] == "tokens/sec/chip" and rec["value"] > 0


def test_bench_lm_sentinel_record_shape(tmp_path):
    """--real with the backend down must land a provisional sentinel
    (the capture queue keeps moving), never hang or write a measured-
    looking record — the bench_collectives discipline."""
    import argparse

    import bench_lm
    path = tmp_path / "sentinel.json"
    bench_lm._sentinel(argparse.Namespace(json=str(path)),
                       ["t+0s: probe timed out"])
    rec = json.loads(path.read_text())
    assert rec["unit"] == "unavailable"
    assert rec["detail"]["provisional"] is True
    assert rec["detail"]["probe_attempts"]


@pytest.mark.timeline
def test_bench_ratchet_recognizes_lm_family(tmp_path):
    """The satellite: BENCH_lm_* records ratchet like the headline
    family — per-(metric, platform) prior-vs-newest comparison, the
    armed_predictions_round11_lm block reported, regressions gated."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import bench_ratchet
    finally:
        sys.path.pop(0)

    def rec(value, spread=0.0):
        return json.dumps({
            "metric": "lm_small_tokens_per_sec_per_chip", "value": value,
            "unit": "tokens/sec/chip", "vs_baseline": 1.0,
            "detail": {"platform": "cpu", "spread_frac": spread,
                       "repeats": [value]}}) + "\n"

    # Rounds PAST the armed round (11): armed blocks report only records
    # newer than the round that armed them.
    (tmp_path / "BENCH_lm_cpu_r12.json").write_text(rec(1000.0))
    (tmp_path / "BENCH_lm_cpu_r13.json").write_text(rec(1100.0))
    (tmp_path / "BASELINE_SELF.json").write_text(json.dumps({
        "armed_predictions_round11_lm": {"note": "lm chip predictions"}}))
    import io
    from contextlib import redirect_stdout
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = bench_ratchet.main(["--records_dir", str(tmp_path), "--json"])
    verdict = json.loads(buf.getvalue())
    assert rc == 0 and verdict["unexplained"] == 0
    armed = {a["key"]: a for a in verdict["armed_predictions"]}
    assert "armed_predictions_round11_lm" in armed
    assert "lm_small_tokens_per_sec_per_chip" in \
        armed["armed_predictions_round11_lm"]["newer_records"]
    # An unexplained lm regression gates exactly like the headline's.
    (tmp_path / "BENCH_lm_cpu_r14.json").write_text(rec(500.0))
    with redirect_stdout(io.StringIO()):
        rc = bench_ratchet.main(["--records_dir", str(tmp_path), "--json"])
    assert rc == 1


def test_compiled_program_audit_sections_on_lm_step():
    """One compile, every instrument: cost keys, bytes audit, the
    dot-flops MFU denominator (>= half of XLA's aggregate flops on this
    dot-dominated step), collectives, and the memory analysis the remat
    A/B reads."""
    from distributedtensorflowexample_tpu.utils.profiling import (
        compiled_program_audit)
    x, y = _data(n=64, seq=16)
    ds = DeviceDataset(x, y, 16, token_data=True)
    state = TrainState.create(build_model("lm_tiny"), _tx(),
                              jnp.zeros((16, 16), jnp.int32))
    step = make_indexed_train_step(16, ds.steps_per_epoch,
                                   num_slots=ds.num_slots)
    audit = compiled_program_audit(step, (state, ds.peek()))
    assert audit["flops"]["flops_per_step"] > 0
    assert audit["flops"]["conv_flops_per_step"] == 0
    if audit["cost"].get("flops"):
        share = audit["flops"]["flops_per_step"] / audit["cost"]["flops"]
        assert 0.5 <= share <= 1.0, share
    assert audit["bytes"]["bytes_per_step"] > 0
    assert audit["memory"]["temp_bytes"] > 0
    # the PR-12 residency section: live-sharding split of the donated
    # state arguments (replicated here: full-size per device)
    res = audit["residency"]
    assert res["params_bytes_per_device"] > 0
    assert res["state_bytes_per_device"] == \
        res["params_bytes_per_device"] + res["opt_state_bytes_per_device"]
    names = [r["op_name"] for r in audit["flops"]["top_ops"]]
    assert any("dot_general" in n for n in names)
