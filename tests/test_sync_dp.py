"""Sync data parallelism on an 8-virtual-device mesh (SURVEY.md §4, §7 step 2).

These run the REAL pjit/NamedSharding/psum path on fake CPU devices —
the rebuild's replacement for the reference's localhost multi-process tests.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedtensorflowexample_tpu.data.synthetic import make_synthetic
from distributedtensorflowexample_tpu.models import build_model
from distributedtensorflowexample_tpu.parallel import (
    batch_sharding, make_mesh, replicated_sharding)
from distributedtensorflowexample_tpu.parallel.sync import (
    evaluate, make_train_step)
from distributedtensorflowexample_tpu.training.state import TrainState
import optax


def _make_state(model_name, sample_shape, mesh, lr=0.1, seed=0):
    model = build_model(model_name)
    tx = optax.sgd(lr)
    return TrainState.create_sharded(model, tx, sample_shape, seed,
                                     replicated_sharding(mesh))


def _batch(mesh, n=64, shape=(28, 28, 1), seed=0):
    x, y = make_synthetic(n, shape, 10, seed=seed)
    return jax.device_put({"image": x, "label": y}, batch_sharding(mesh))


def test_virtual_device_mesh():
    mesh = make_mesh()
    assert mesh.size == jax.device_count()
    assert jax.device_count() >= 4   # DISTTF_TEST_DEVICES retry floor


def test_train_step_runs_sharded():
    mesh = make_mesh()
    state = _make_state("softmax", (64, 28, 28, 1), mesh)
    batch = _batch(mesh)
    step = make_train_step()
    state, metrics = step(state, batch)
    assert int(state.step) == 1
    assert np.isfinite(float(metrics["loss"]))
    # Params stay fully replicated after the step.
    leaf = jax.tree.leaves(state.params)[0]
    assert leaf.sharding.is_fully_replicated


def test_batch_is_actually_sharded():
    mesh = make_mesh()
    batch = _batch(mesh)
    assert len(batch["image"].sharding.device_set) == mesh.size
    assert (batch["image"].addressable_shards[0].data.shape[0]
            == 64 // mesh.size)


def test_loss_decreases_under_dp():
    mesh = make_mesh()
    state = _make_state("softmax", (64, 28, 28, 1), mesh, lr=0.5)
    step = make_train_step()
    x, y = make_synthetic(64 * 30, (28, 28, 1), 10, seed=0)
    losses = []
    for i in range(30):
        sl = slice(i * 64, (i + 1) * 64)
        batch = jax.device_put({"image": x[sl], "label": y[sl]},
                               batch_sharding(mesh))
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.7


def test_one_vs_eight_device_equivalence():
    """Same global batch ⇒ numerically identical update on 1 and all
    visible devices: the determinism guarantee the reference's sync mode
    only approximated."""
    step = make_train_step()
    results = []
    for ndev in (1, jax.device_count()):
        mesh = make_mesh(ndev)
        state = _make_state("softmax", (64, 28, 28, 1), mesh, lr=0.5, seed=7)
        for i in range(3):
            x, y = make_synthetic(64, (28, 28, 1), 10, seed=100 + i)
            batch = jax.device_put({"image": x, "label": y},
                                   batch_sharding(mesh))
            state, _ = step(state, batch)
        results.append(jax.device_get(state.params))
    flat1 = jax.tree.leaves(results[0])
    flat8 = jax.tree.leaves(results[1])
    for a, b in zip(flat1, flat8):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6)


def test_cnn_with_dropout_under_dp():
    mesh = make_mesh()
    state = _make_state("mnist_cnn", (32, 28, 28, 1), mesh, lr=0.05)
    step = make_train_step()
    batch = _batch(mesh, n=32)
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))


def test_resnet_bn_under_dp():
    mesh = make_mesh()
    state = _make_state("resnet20", (16, 32, 32, 3), mesh, lr=0.05)
    step = make_train_step()
    batch = _batch(mesh, n=16, shape=(32, 32, 3))
    old_stats = jax.device_get(state.batch_stats)
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
    new_stats = jax.device_get(state.batch_stats)
    # BN running stats must actually update.
    diffs = jax.tree.map(lambda a, b: float(np.abs(a - b).max()),
                         old_stats, new_stats)
    assert max(jax.tree.leaves(diffs)) > 0


def test_evaluate_exact():
    mesh = make_mesh()
    state = _make_state("softmax", (64, 28, 28, 1), mesh)
    x, y = make_synthetic(2048, (28, 28, 1), 10, seed=1)
    acc = evaluate(state, x, y, batch_size=512, sharding=batch_sharding(mesh))
    assert 0.0 <= acc <= 1.0


def test_resident_eval_matches_host_eval():
    """make_resident_eval (one dispatch, split in HBM) computes the exact
    same accuracy as the host-fed evaluate, including the padded tail."""
    from distributedtensorflowexample_tpu.parallel.sync import (
        make_resident_eval)

    mesh = make_mesh()
    state = _make_state("softmax", (64, 28, 28, 1), mesh)
    x, y = make_synthetic(1100, (28, 28, 1), 10, seed=2)   # non-multiple tail
    want = evaluate(state, x, y, batch_size=512,
                    sharding=batch_sharding(mesh))
    got = make_resident_eval(x, y, batch_size=512, mesh=mesh)(state)
    assert got == pytest.approx(want, abs=1e-9)


def test_resident_eval_batch_must_divide_mesh():
    from distributedtensorflowexample_tpu.parallel.sync import (
        make_resident_eval)

    x, y = make_synthetic(100, (28, 28, 1), 10, seed=2)
    with pytest.raises(ValueError, match="divide"):
        make_resident_eval(x, y, batch_size=50, mesh=make_mesh())


def test_resident_eval_quantize_off_skips_lut_path(monkeypatch):
    """--quantize off reaches eval too (ADVICE r4): the split stays
    float32-resident and _try_quantize is never consulted, while the
    accuracy is identical to the quantized path (which is bitwise by
    construction)."""
    import distributedtensorflowexample_tpu.data.device_dataset as dd
    from distributedtensorflowexample_tpu.parallel.sync import (
        make_resident_eval)

    mesh = make_mesh()
    state = _make_state("softmax", (64, 28, 28, 1), mesh)
    x, y = make_synthetic(1024, (28, 28, 1), 10, seed=3)
    want = make_resident_eval(x, y, batch_size=512, mesh=mesh)(state)

    def boom(*a, **k):
        raise AssertionError("_try_quantize consulted under quantize='off'")
    monkeypatch.setattr(dd, "_try_quantize", boom)
    got = make_resident_eval(x, y, batch_size=512, mesh=mesh,
                             quantize="off")(state)
    assert got == pytest.approx(want, abs=1e-9)
    with pytest.raises(ValueError, match="quantize"):
        make_resident_eval(x, y, batch_size=512, mesh=mesh, quantize="no")


def test_partial_aggregation_uses_rotating_subset():
    """replicas_to_aggregate=R: the update at step s is driven by exactly
    the R replicas with ((i - s) mod N) < R — verified by comparing against
    a manual step on just those replicas' shards."""
    from distributedtensorflowexample_tpu.ops.losses import (
        softmax_cross_entropy)

    mesh = make_mesh()
    N, R, b = 8, 3, 64
    per = b // N
    step = make_train_step(num_replicas=N, replicas_to_aggregate=R)
    x, y = make_synthetic(b, (28, 28, 1), 10, seed=4)

    for s in (0, 1, 5):
        state = _make_state("softmax", (b, 28, 28, 1), mesh, lr=0.5, seed=1)
        state = state.replace(step=jnp.asarray(s, jnp.int32))
        batch = jax.device_put({"image": x, "label": y}, batch_sharding(mesh))
        new_state, _ = step(state, batch)

        # Manual reference: grad of the mean loss over the selected rows.
        sel = [i for i in range(N) if (i - s) % N < R]
        rows = np.concatenate([np.arange(i * per, (i + 1) * per) for i in sel])
        ref = _make_state("softmax", (b, 28, 28, 1), mesh, lr=0.5, seed=1)

        def loss_fn(params):
            logits = ref.apply_fn({"params": params},
                                  jnp.asarray(x[rows]), train=True,
                                  rngs={"dropout": jax.random.fold_in(
                                      ref.rng, s)})
            return softmax_cross_entropy(logits, jnp.asarray(y[rows]))

        grads = jax.grad(loss_fn)(ref.params)
        want = jax.tree.map(lambda p, g: p - 0.5 * g, ref.params, grads)
        jax.tree.map(lambda a, c: np.testing.assert_allclose(a, c, rtol=1e-5,
                                                             atol=1e-6),
                     new_state.params, want)


def test_partial_aggregation_full_r_matches_plain():
    mesh = make_mesh()
    x, y = make_synthetic(64, (28, 28, 1), 10, seed=5)
    batch = lambda: jax.device_put({"image": x, "label": y},
                                   batch_sharding(mesh))
    s1 = _make_state("softmax", (64, 28, 28, 1), mesh, lr=0.5, seed=2)
    s2 = _make_state("softmax", (64, 28, 28, 1), mesh, lr=0.5, seed=2)
    s1, _ = make_train_step()(s1, batch())
    s2, _ = make_train_step(num_replicas=8, replicas_to_aggregate=8)(
        s2, batch())
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(a, b),
                 s1.params, s2.params)


def test_partial_aggregation_validation():
    with pytest.raises(ValueError, match="replicas_to_aggregate"):
        make_train_step(num_replicas=4, replicas_to_aggregate=5)
