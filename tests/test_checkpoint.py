"""Checkpoint/resume (SURVEY.md §5 row "Checkpoint / resume").

The reference relied on ``MonitoredTrainingSession`` hooks + ``Saver``:
periodic saves, keep-N rotation, auto-restore-from-latest.  These tests pin
the Orbax-backed equivalent to the same observable behavior, plus the
guarantee TF never gave: resumed training is BITWISE identical to an
uninterrupted run (deterministic rng-from-step folding).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributedtensorflowexample_tpu.data.synthetic import make_synthetic
from distributedtensorflowexample_tpu.models import build_model
from distributedtensorflowexample_tpu.parallel import (
    batch_sharding, make_mesh, replicated_sharding, shard_batch)
from distributedtensorflowexample_tpu.parallel.sync import make_train_step
from distributedtensorflowexample_tpu.training.checkpoint import CheckpointManager
from distributedtensorflowexample_tpu.training.hooks import CheckpointHook
from distributedtensorflowexample_tpu.training.loop import TrainLoop
from distributedtensorflowexample_tpu.training.state import TrainState


def _fresh_state(seed: int = 0) -> TrainState:
    model = build_model("softmax")
    return TrainState.create(model, optax.sgd(0.1, momentum=0.9),
                             jnp.zeros((8, 28, 28, 1), jnp.float32), seed=seed)


def _batches(n: int, batch: int = 8):
    x, y = make_synthetic(batch * n, (28, 28, 1), 10, seed=3)
    return [{"image": jnp.asarray(x[i * batch:(i + 1) * batch]),
             "label": jnp.asarray(y[i * batch:(i + 1) * batch])}
            for i in range(n)]


def _trees_equal(a, b) -> bool:
    leaves = zip(jax.tree.leaves(a), jax.tree.leaves(b), strict=True)
    return all(np.array_equal(np.asarray(x), np.asarray(y)) for x, y in leaves)


def test_save_restore_roundtrip(tmp_path):
    state = _fresh_state()
    step = make_train_step()
    for b in _batches(3):
        state, _ = step(state, b)

    mgr = CheckpointManager(str(tmp_path / "ckpt"), async_save=False)
    assert mgr.latest_step() is None          # empty dir → nothing to restore
    assert mgr.save(int(state.step), state)
    mgr.wait()

    restored = mgr.restore(_fresh_state(seed=99))
    assert int(restored.step) == 3
    assert _trees_equal(restored.params, state.params)
    assert _trees_equal(restored.opt_state, state.opt_state)
    mgr.close()


def test_restore_on_empty_dir_is_identity(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "ckpt"), async_save=False)
    state = _fresh_state()
    assert mgr.restore(state) is state
    mgr.close()


def test_resume_matches_uninterrupted_run(tmp_path):
    """Save at step 3, restore into a fresh state, continue on the same
    batch stream → parameters bitwise-equal to a straight 6-step run.
    This is the determinism test SURVEY.md §5 calls for (race-detection row).
    """
    batches = _batches(6)
    step = make_train_step()

    straight = _fresh_state()
    for b in batches:
        straight, _ = step(straight, b)

    first = _fresh_state()
    for b in batches[:3]:
        first, _ = step(first, b)
    mgr = CheckpointManager(str(tmp_path / "ckpt"), async_save=False)
    mgr.save(int(first.step), first)
    mgr.wait()

    resumed = mgr.restore(_fresh_state(seed=7))
    for b in batches[3:]:
        resumed, _ = step(resumed, b)

    assert int(resumed.step) == int(straight.step) == 6
    assert _trees_equal(resumed.params, straight.params)
    assert _trees_equal(resumed.opt_state, straight.opt_state)
    mgr.close()


def test_keep_n_rotation(tmp_path):
    """max_to_keep=2 keeps only the newest two checkpoints (Saver semantics)."""
    state = _fresh_state()
    step = make_train_step()
    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=2,
                            async_save=False)
    for b in _batches(3):
        state, _ = step(state, b)
        mgr.save(int(state.step), state)
    mgr.wait()
    assert mgr.latest_step() == 3
    assert sorted(mgr._mgr.all_steps()) == [2, 3]
    mgr.close()


def test_duplicate_step_save_is_noop(tmp_path):
    state = _fresh_state()
    mgr = CheckpointManager(str(tmp_path / "ckpt"), async_save=False)
    assert mgr.save(0, state)
    mgr.wait()
    assert not mgr.save(0, state)
    mgr.close()


def test_checkpoint_hook_saves_periodically_and_at_end(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=10,
                            async_save=False)
    state = _fresh_state()
    loop = TrainLoop(make_train_step(), iter(_batches(5)), 5,
                     hooks=[CheckpointHook(mgr, every=2)])
    state = loop.run(state)
    assert int(state.step) == 5
    # periodic at 2 and 4, final forced at 5
    assert sorted(mgr._mgr.all_steps()) == [2, 4, 5]
    restored = mgr.restore(_fresh_state(seed=5))
    assert _trees_equal(restored.params, state.params)
    mgr.close()


def test_restore_preserves_sharding(tmp_path):
    """Restoring into a mesh-sharded template keeps the NamedSharding —
    the multi-host-safe path (every process restores its own shards)."""
    mesh = make_mesh()
    model = build_model("softmax")
    repl = replicated_sharding(mesh)
    state = TrainState.create_sharded(model, optax.sgd(0.1),
                                      (16, 28, 28, 1), 0, repl)
    step = make_train_step()
    x, y = make_synthetic(16, (28, 28, 1), 10, seed=1)
    batch = shard_batch(mesh, {"image": x, "label": y})
    with mesh:
        state, _ = step(state, batch)

    mgr = CheckpointManager(str(tmp_path / "ckpt"), async_save=False)
    mgr.save(int(state.step), state)
    mgr.wait()

    template = TrainState.create_sharded(model, optax.sgd(0.1),
                                         (16, 28, 28, 1), 42, repl)
    restored = mgr.restore(template)
    w = restored.params["logits"]["kernel"]
    assert w.sharding.is_equivalent_to(repl, w.ndim)
    assert _trees_equal(restored.params, state.params)
    mgr.close()


def test_async_worker_tiled_resume_matches_uninterrupted(tmp_path):
    """Orbax round-trips the worker-tiled (P(DATA_AXIS)) async state and a
    resumed local-SGD run is bitwise-identical to an uninterrupted one —
    including across an averaging point (period 3, boundary inside the
    resumed half)."""
    from distributedtensorflowexample_tpu.parallel.async_ps import (
        make_async_train_step, make_worker_state)

    mesh = make_mesh()
    model = build_model("softmax")

    def fresh(seed):
        st = TrainState.create_sharded(model, optax.sgd(0.1, momentum=0.9),
                                       (16, 28, 28, 1), seed,
                                       replicated_sharding(mesh))
        return make_worker_state(st, mesh.size, mesh)

    step = make_async_train_step(mesh.size, period=3, mesh=mesh)
    x, y = make_synthetic(16 * 6, (28, 28, 1), 10, seed=3)
    batches = [shard_batch(mesh, {"image": x[i * 16:(i + 1) * 16],
                                  "label": y[i * 16:(i + 1) * 16]})
               for i in range(6)]
    with mesh:
        straight = fresh(0)
        for b in batches:
            straight, _ = step(straight, b)

        first = fresh(0)
        for b in batches[:3]:
            first, _ = step(first, b)
        mgr = CheckpointManager(str(tmp_path / "ckpt"), async_save=False)
        mgr.save(int(first.step), first)
        mgr.wait()

        resumed = mgr.restore(fresh(9))
        for b in batches[3:]:
            resumed, _ = step(resumed, b)

    assert int(resumed.step) == int(straight.step) == 6
    leaf = jax.tree.leaves(resumed.params)[0]
    assert leaf.shape[0] == mesh.size          # still worker-tiled
    assert _trees_equal(resumed.params, straight.params)
    assert _trees_equal(resumed.opt_state, straight.opt_state)
    mgr.close()


def test_interrupt_still_checkpoints_final_state(tmp_path):
    """Ctrl-C mid-run: end-hooks save the last completed step before the
    KeyboardInterrupt propagates (MonitoredTrainingSession's exit-save)."""

    from distributedtensorflowexample_tpu.training.hooks import Hook

    class InterruptAt(Hook):
        def after_step(self, step, state, metrics):
            if step == 3:
                raise KeyboardInterrupt
            return False

    mgr = CheckpointManager(str(tmp_path / "ckpt"), async_save=False)
    loop = TrainLoop(make_train_step(), iter(_batches(6)), 6,
                     hooks=[InterruptAt(), CheckpointHook(mgr, every=0)])
    with pytest.raises(KeyboardInterrupt):
        loop.run(_fresh_state())
    assert mgr.latest_step() == 3
    mgr.close()


def test_second_interrupt_during_exit_hooks_still_saves(tmp_path):
    """A second Ctrl-C delivered inside the exit-hook pass (the ADVICE r2
    residual window) must not skip the remaining exit hooks: the final
    checkpoint still lands, then KeyboardInterrupt propagates."""

    from distributedtensorflowexample_tpu.training.hooks import Hook

    class InterruptOnEnd(Hook):
        def end(self, state):
            raise KeyboardInterrupt

    mgr = CheckpointManager(str(tmp_path / "ckpt"), async_save=False)
    # Interrupting hook runs FIRST so the save hook exercises the
    # keep-going path.
    loop = TrainLoop(make_train_step(), iter(_batches(4)), 2,
                     hooks=[InterruptOnEnd(), CheckpointHook(mgr, every=0)])
    with pytest.raises(KeyboardInterrupt):
        loop.run(_fresh_state())
    assert mgr.latest_step() == 2
    mgr.close()


def test_sync_checkpoint_flag_writes_checkpoints(tmp_path, small_synthetic):
    """--async_checkpoint false (the reference Saver's synchronous
    behavior) plumbs through run_training and still produces restorable
    periodic checkpoints."""
    from distributedtensorflowexample_tpu.config import RunConfig
    from distributedtensorflowexample_tpu.trainers.common import run_training
    from distributedtensorflowexample_tpu.training.optimizers import (
        build_optimizer)

    cfg = RunConfig(
        train_steps=4, checkpoint_every=2, resume=False,
        async_checkpoint=False, batch_size=64, global_batch=True,
        dataset="synthetic",
        data_dir=str(tmp_path), log_dir=str(tmp_path / "logs"),
        log_every=50, seed=1)
    out = run_training(cfg, "softmax", "mnist")
    assert out["steps"] == 4
    mgr = CheckpointManager(str(tmp_path / "logs" / "checkpoints"),
                            async_save=False)
    # Periodic save at 2 AND the forced final at 4 — latest alone would
    # also pass if the periodic path silently broke.
    assert sorted(mgr._mgr.all_steps()) == [2, 4]
    # Restore round-trip into a template built with the run's own
    # optimizer (build_optimizer — a bare sgd's opt_state would mismatch).
    template = TrainState.create(build_model("softmax"),
                                 build_optimizer(cfg),
                                 jnp.zeros((8, 28, 28, 1), jnp.float32),
                                 seed=11)
    restored = mgr.restore(template)
    assert int(restored.step) == 4
    mgr.close()


def test_run_metadata_roundtrip(tmp_path):
    d = str(tmp_path / "ckpt")
    mgr = CheckpointManager(d, run_metadata={"sync_mode": "sync"})
    assert mgr.saved_run_metadata() is None      # nothing saved yet
    mgr.save(1, _fresh_state(), force=True)
    mgr.wait()
    assert mgr.saved_run_metadata() == {"sync_mode": "sync"}
    # A second manager over the same dir reads the original writer's mode.
    again = CheckpointManager(d, run_metadata={"sync_mode": "async"})
    assert again.saved_run_metadata() == {"sync_mode": "sync"}


def test_async_worker_count_restore_is_refused(tmp_path, small_synthetic):
    """An async checkpoint is worker-tiled (leading axis = num_workers):
    restoring it on a different worker count must fail with an error
    naming both counts, not an Orbax shape mismatch (VERDICT r2 item 6)."""
    from distributedtensorflowexample_tpu.config import RunConfig
    from distributedtensorflowexample_tpu.trainers.common import run_training

    common = dict(batch_size=64, global_batch=True, dataset="synthetic",
                  data_dir=str(tmp_path), log_dir=str(tmp_path / "logs"),
                  log_every=50, seed=1, sync_mode="async", async_period=2)
    run_training(RunConfig(train_steps=4, checkpoint_every=4, resume=False,
                           num_devices=2, **common), "softmax", "mnist")
    with pytest.raises(ValueError, match="num_workers=2.*num_workers=4"):
        run_training(RunConfig(train_steps=8, resume=True, num_devices=4,
                               **common), "softmax", "mnist")


def test_sync_mesh_size_restore_is_allowed(tmp_path, small_synthetic, capsys):
    """Sync-mode state is replicated, so resuming on a different mesh size
    is legitimate (scale-up resume); the guard notes it and proceeds."""
    from distributedtensorflowexample_tpu.config import RunConfig
    from distributedtensorflowexample_tpu.trainers.common import run_training

    common = dict(batch_size=64, global_batch=True, dataset="synthetic",
                  data_dir=str(tmp_path), log_dir=str(tmp_path / "logs"),
                  log_every=50, seed=1)
    run_training(RunConfig(train_steps=4, checkpoint_every=4, resume=False,
                           num_devices=2, **common), "softmax", "mnist")
    out = run_training(RunConfig(train_steps=8, resume=True, num_devices=4,
                                 **common), "softmax", "mnist")
    assert out["steps"] == 8
    assert "resuming a mesh_size=2 checkpoint" in capsys.readouterr().out


def test_cross_mode_restore_is_refused(tmp_path, small_synthetic):
    """A sync-run checkpoint restored into an async run must fail with a
    clear error naming the saved mode, not an Orbax shape mismatch."""
    from distributedtensorflowexample_tpu.config import RunConfig
    from distributedtensorflowexample_tpu.trainers.common import run_training

    common = dict(batch_size=64, global_batch=True, dataset="synthetic",
                  data_dir=str(tmp_path), log_dir=str(tmp_path / "logs"),
                  log_every=50, seed=1)
    run_training(RunConfig(train_steps=4, checkpoint_every=4, resume=False,
                           **common), "softmax", "mnist")
    with pytest.raises(ValueError, match="sync_mode='sync'"):
        run_training(RunConfig(train_steps=8, resume=True, sync_mode="async",
                               **common), "softmax", "mnist")


# --- shard-redundant snapshots (resilience/shardstore.py) -------------------

_BB = 1 << 20     # one bucket per dtype for the tiny softmax model


def _trained_rows(tmp_path, D: int = 4, steps: int = 4, name: str = "store"):
    """Train a D-wide ZeRO-3 softmax run a few steps, save one
    shard-redundant snapshot set; return everything a restore needs."""
    from distributedtensorflowexample_tpu.engine.engine import (
        apply_update_layout)
    from distributedtensorflowexample_tpu.resilience.shardstore import (
        ShardLayout, ShardStore)

    mesh = make_mesh(D)
    tx = optax.sgd(0.1, momentum=0.9)
    state = _fresh_state()
    layout = ShardLayout.for_params("zero3_rows", _BB, state.params, D)
    rows, z3 = apply_update_layout(state, tx, update_layout="zero3_rows",
                                   bucket_bytes=_BB, mesh=mesh)
    step_fn = make_train_step(mesh=mesh, zero3_layout=z3)
    with mesh:
        for b in _batches(steps):
            rows, _ = step_fn(rows, b)
    store_dir = str(tmp_path / name)
    store = ShardStore(store_dir, layout=layout)
    step = store.save(rows, cursor={"seed": 0})
    return store_dir, rows, z3, mesh, tx, step


def test_shard_restore_survives_any_single_rank_loss(tmp_path):
    """R=2 ring mirroring: delete ANY one rank's whole shard directory —
    every rank in turn — and restore still reconstructs that shard from
    its neighbor's mirror, bitwise."""
    import shutil

    from distributedtensorflowexample_tpu.resilience.shardstore import (
        ShardStore)

    store_dir, rows, _z3, _mesh, tx, step = _trained_rows(tmp_path)
    for rank in range(4):
        wd = str(tmp_path / f"loss_{rank}")
        shutil.copytree(store_dir, wd)
        hurt = ShardStore(wd)
        assert hurt.drop_rank_dir(rank) == step
        ok, _why = hurt.validate(step)
        assert ok                        # one loss is within R=2 quorum
        mesh = make_mesh(4)
        restored, aux = ShardStore(wd).restore_elastic(
            _fresh_state(seed=9), tx, mesh=mesh)
        assert aux["step"] == step and aux["reconstructed"] == [rank]
        assert _trees_equal(restored, rows)


def test_shard_bitflip_detected_and_reconstructed(tmp_path):
    """Silent bit rot: one payload byte flipped in place.  The sha256
    census refuses that copy, restores from the ring mirror instead, and
    the result is still bitwise — the rot is never restored silently."""
    from distributedtensorflowexample_tpu.resilience.shardstore import (
        ShardStore)

    store_dir, rows, _z3, _mesh, tx, step = _trained_rows(tmp_path)
    hurt = ShardStore(store_dir)
    flipped_step, _off = hurt.flip_payload_byte(1)
    assert flipped_step == step
    assert hurt.validate(step)[0]        # mirror intact → still quorum
    mesh = make_mesh(4)
    restored, aux = ShardStore(store_dir).restore_elastic(
        _fresh_state(seed=9), tx, mesh=mesh)
    assert aux["reconstructed"] == [1]
    assert _trees_equal(restored, rows)


def test_shard_loss_past_redundancy_refuses_by_name(tmp_path):
    """Losing a shard's own copy AND its only ring mirror (R=2) must
    refuse loudly, naming the shard, the census, and the remedy — never
    restore a partial state."""
    from distributedtensorflowexample_tpu.refusal import ModeRefusal
    from distributedtensorflowexample_tpu.resilience.shardstore import (
        ShardStore)

    store_dir, _rows, _z3, _mesh, tx, step = _trained_rows(tmp_path)
    hurt = ShardStore(store_dir)
    hurt.drop_rank_dir(2)                # shard 2's own copy
    hurt.drop_rank_dir(3)                # rank 3 held shard 2's mirror
    ok, why = hurt.validate(step)
    assert not ok and "no intact copy" in why
    mesh = make_mesh(4)
    # The step must be PINNED: unpinned restore sees no quorum-valid
    # step at all (a different, also-loud error).
    with pytest.raises(ModeRefusal, match="exceeds redundancy R=2"):
        ShardStore(store_dir).restore_elastic(
            _fresh_state(seed=9), tx, mesh=mesh, step=step)


def test_elastic_restore_d4_d2_d4_roundtrip_bitwise(tmp_path):
    """A D=4 shard set restored onto a D=2 mesh (and back) through the
    engine layout pass: per-leaf row padding is the ONLY D-dependence,
    so the materialized state — and the full round-tripped row state —
    is bitwise the original."""
    from distributedtensorflowexample_tpu.resilience.shardstore import (
        ShardLayout, ShardStore)

    store_dir, rows4, z3_4, _mesh, tx, step = _trained_rows(tmp_path)
    mesh2 = make_mesh(2)
    rows2, aux2 = ShardStore(store_dir).restore_elastic(
        _fresh_state(seed=9), optax.sgd(0.1, momentum=0.9), mesh=mesh2)
    assert aux2["step"] == step
    assert aux2["from_ranks"] == 4 and mesh2.size == 2
    z3_2 = aux2["zero3_layout"]
    full4 = jax.tree.leaves(z3_4.materialize(rows4.params))
    full2 = jax.tree.leaves(z3_2.materialize(rows2.params))
    assert all(np.array_equal(np.asarray(a), np.asarray(b))
               for a, b in zip(full4, full2, strict=True))
    # ... and back up to D=4: the full row state (params AND bucketed
    # optimizer moments) is bitwise what was first saved.
    lay2 = ShardLayout.for_params("zero3_rows", _BB,
                                  _fresh_state().params, 2)
    d2_dir = str(tmp_path / "store_d2")
    ShardStore(d2_dir, layout=lay2).save(rows2, cursor={"seed": 0})
    mesh4 = make_mesh(4)
    rows4b, aux4 = ShardStore(d2_dir).restore_elastic(
        _fresh_state(seed=9), optax.sgd(0.1, momentum=0.9), mesh=mesh4)
    assert aux4["from_ranks"] == 2
    assert _trees_equal(rows4b, rows4)
