"""bench.main() END-TO-END on the virtual mesh — the real driver path.

Every other bench test fakes some piece of main() (a sweep, a make, a
probe); the driver's once-per-round run executes the REAL path, so this
file runs the whole thing at shrunken sizes: same code, same workload
set, same emit contract — only the module-level sizing knobs change.

OPT-IN, not part of the default suite: even at minimal sizes the run
costs many minutes on this host — each scanned step pays
collective-rendezvous spin on the oversubscribed virtual mesh, and that
cost is execution, not compile, so the persistent cache can't absorb
it.  Run it after any bench.py change:

    DISTTF_BENCH_E2E=1 DISTTF_INNER_PYTEST=1 DISTTF_TEST_DEVICES=1 \\
        python -m pytest tests/test_bench_e2e.py -q

DISTTF_TEST_DEVICES matters (sizing adapts to any count, cost doesn't):
1 virtual device is BOTH the fastest (~9 min warm — no collectives at
all) AND the driver's actual bench topology (one real chip = mesh of
1), so it is the default recommendation (round-3 weak item: the CI
config didn't match the driver's).  2 devices (~14 min) additionally
exercises the collective path end-to-end; at the conftest default of 8
the per-step rendezvous cost quadruples and a run was still going at
77 minutes.
"""

import json
import os

import pytest

import bench
from distributedtensorflowexample_tpu.data import cifar10, mnist

pytestmark = pytest.mark.skipif(
    os.environ.get("DISTTF_BENCH_E2E") != "1",
    reason="~20 min even warm (rendezvous-bound); opt in with "
           "DISTTF_BENCH_E2E=1 — see module docstring")

ALL_METRICS = {
    "mnist_cnn_sync_steps_per_sec_per_chip",
    "cifar_resnet20_steps_per_sec_per_chip",
    "mnist_cnn_async_steps_per_sec_per_chip",
    "mnist_softmax_steps_per_sec_per_chip",
    "mnist_cnn_sync_pallas_ce_steps_per_sec_per_chip",
    "mnist_cnn_sync_fused_sgd_steps_per_sec_per_chip",
}


def test_bench_main_success_path(small_synthetic, monkeypatch, capsys,
                                 tmp_path):
    # Shrink the SAME knobs the driver's run uses at defaults; nothing
    # in main() itself is faked or stubbed.  Two costs bound the sizing
    # (measured, round 3): every distinct unroll is a fresh multi-minute
    # XLA compile on this 1-core host, so the sweeps are thinned to one
    # extra point each (multi-point iteration logic is covered by the
    # faked-sweep tests in test_bench.py); and every SCANNED STEP costs
    # ~0.5s of collective-rendezvous spin on the oversubscribed virtual
    # mesh, so TRAIN_N is tiny — it drives spe and with it every unroll
    # and step count (total across all workloads lands near ~500 steps).
    # Sized from the live device count so the run works at any
    # DISTTF_TEST_DEVICES (2 recommended for speed — module docstring):
    # spe = TRAIN_N // (8 * ndev) = 2 for every ndev.
    import jax
    ndev = jax.device_count()
    monkeypatch.setattr(mnist, "_SYNTH_SIZES",
                        {"train": 32 * ndev, "test": 16 * ndev})
    monkeypatch.setattr(cifar10, "_SYNTH_SIZES",
                        {"train": 32 * ndev, "test": 16 * ndev})
    monkeypatch.setattr(bench, "DATA_DIR", str(tmp_path))
    monkeypatch.setattr(bench, "REPEATS", 1)
    monkeypatch.setattr(bench, "TRAIN_N",
                        {"mnist": 16 * ndev, "cifar10": 16 * ndev})
    monkeypatch.setattr(bench, "BATCH",
                        {"cnn": 8, "softmax": 8, "resnet": 8})
    monkeypatch.setattr(bench, "MIN_STEPS", {"headline": 8, "resnet": 4})
    monkeypatch.setattr(bench, "ROOFLINE_LEN",
                        {"headline": 8, "softmax": 8, "resnet": 4})
    monkeypatch.setattr(bench, "HEADLINE_REST_UNROLLS", lambda spe: {spe})
    monkeypatch.setattr(bench, "RESNET_UNROLLS", lambda spe: {spe})
    # One A/B alternative (each impl is a fresh multi-minute compile
    # here); the full impl set's selection logic is covered by the faked
    # tests in test_bench.py.
    monkeypatch.setattr(bench, "DEQUANT_AB_IMPLS", ("lut",))

    bench.main()

    lines = [json.loads(l) for l in capsys.readouterr().out.splitlines()]
    # Line 0 is the always-first provisional sentinel (VERDICT r3 #1a);
    # on the success path it must be the ONLY unavailable-unit line.
    assert lines[0]["detail"].get("provisional") is True
    assert sum(l["unit"] == "unavailable" for l in lines) == 1
    lines = lines[1:]
    metrics = [l["metric"] for l in lines]
    assert set(metrics) == ALL_METRICS and len(metrics) == len(ALL_METRICS)
    # Headline LAST — the output contract the driver parses.
    assert metrics[-1] == "mnist_cnn_sync_steps_per_sec_per_chip"
    for line in lines:
        assert line["unit"] == "steps/sec/chip", line
        assert line["value"] > 0, line
        assert line["detail"]["repeats"], line

    headline = lines[-1]
    # Both sweep halves ran: the deepest point + the thinned rest.
    assert len(headline["detail"]["unroll_sweep"]) == 2
    assert headline["detail"]["best_unroll"] is not None
    assert 0 < headline["detail"]["vs_roofline"]
    assert headline["detail"]["roofline_probe"]
    # Dequant attestation (round-5 satellite): the record names the impl
    # that ran (auto resolves to affine; the A/B may promote the thinned
    # alternative on this noisy host — both attest a real measurement)
    # and carries the measured alternative's rates.
    assert headline["detail"]["dequant"] in ("affine", "lut")
    assert list(headline["detail"]["dequant_ab"]) == ["lut"]
    # The success path must be clean — any per-workload error means a
    # real breakage the driver would hit.
    assert "errors" not in headline["detail"], headline["detail"]["errors"]

    resnet = next(l for l in lines
                  if l["metric"] == "cifar_resnet20_steps_per_sec_per_chip")
    assert resnet["detail"]["flops_per_step"] > 0     # cost probe worked
    assert resnet["detail"]["mfu"] is not None
    assert resnet["detail"]["vs_roofline"] > 0
    assert len(resnet["detail"]["unroll_sweep"]) == 1

    softmax = next(l for l in lines
                   if l["metric"] == "mnist_softmax_steps_per_sec_per_chip")
    assert softmax["detail"]["vs_roofline"] > 0
    # Same-window cost decomposition (VERDICT r3 #5): the measured step
    # and roofline step both carry flops/bytes, and the bytes ratio that
    # attributes the vs_roofline gap is derived from them.
    assert softmax["detail"]["cost_per_step"]["bytes_accessed"] > 0
    assert softmax["detail"]["roofline_cost_per_step"]["bytes_accessed"] > 0
    assert softmax["detail"]["roofline_bytes_ratio"] > 0
