"""Device-resident input path (data/device_dataset.py + indexed step).

Checks the semantics the host Batcher guarantees — shuffled epochs without
replacement, deterministic resume alignment — carry over to the on-device
gather path, on the 8-virtual-device mesh (SURVEY.md §4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributedtensorflowexample_tpu.data import DeviceDataset
from distributedtensorflowexample_tpu.data.synthetic import make_synthetic
from distributedtensorflowexample_tpu.models import build_model
from distributedtensorflowexample_tpu.parallel import (
    make_mesh, replicated_sharding)
from distributedtensorflowexample_tpu.parallel.sync import (
    make_indexed_train_step, make_train_step)
from distributedtensorflowexample_tpu.training.state import TrainState


def _data(n=520, shape=(28, 28, 1)):
    return make_synthetic(n, shape, 10, seed=0)


def test_epoch_is_permutation_without_replacement():
    x, y = _data()
    mesh = make_mesh()
    ds = DeviceDataset(x, y, 64, mesh=mesh, seed=3)
    assert ds.steps_per_epoch == 520 // 64
    assert ds.num_slots == 3                           # spn=1: 1 epoch + 2
    ring = np.asarray(next(ds)["perm"])
    assert ring.shape == (3, ds.epoch_len)
    for row in ring[:2]:                               # epochs 0,1 resident
        assert len(np.unique(row)) == ds.epoch_len     # no replacement
    assert not np.array_equal(ring[0], ring[1])        # distinct epochs
    # The ring persists within the epoch; crossing into epoch 1 prefetches
    # epoch 2 into slot 2, leaving epochs 0 and 1 untouched.
    for _ in range(ds.steps_per_epoch - 1):
        np.testing.assert_array_equal(np.asarray(next(ds)["perm"]), ring)
    ring2 = np.asarray(next(ds)["perm"])
    np.testing.assert_array_equal(ring2[0], ring[0])
    np.testing.assert_array_equal(ring2[1], ring[1])
    assert len(np.unique(ring2[2])) == ds.epoch_len    # epoch 2 prefetched


@pytest.mark.parametrize("data_sharding", ["replicated", "sharded"])
def test_start_step_alignment_matches_fresh_run(data_sharding):
    """A dataset started at step k yields the same perm schedule a fresh
    dataset reaches after k nexts — resume determinism, in both storage
    layouts (sharded: the per-shard epoch streams are deterministic
    functions of (seed, epoch, device)).  Only the rows the step can read
    (current epoch + prefetch) are compared: a resumed ring doesn't
    backfill slots of epochs that already passed."""
    x, y = _data()
    mesh = make_mesh()
    k = 11
    mk = lambda **kw: DeviceDataset(x, y, 64, mesh=mesh, seed=5,
                                    data_sharding=data_sharding, **kw)
    fresh = mk()
    for _ in range(k):
        next(fresh)
    resumed = mk(start_step=k)
    assert fresh.num_slots == resumed.num_slots
    spe, S = fresh.steps_per_epoch, fresh.num_slots
    assert spe == resumed.steps_per_epoch
    for i in range(5):
        rf = np.asarray(next(fresh)["perm"])
        rr = np.asarray(next(resumed)["perm"])
        epoch = (k + i) // spe
        for e in (epoch, epoch + 1):
            np.testing.assert_array_equal(rf[e % S], rr[e % S])


def test_indexed_step_consumes_each_epoch_row_once():
    """One epoch of the position arithmetic covers every dataset row once;
    a real step execution is cross-checked against the host-gathered batch
    in test_indexed_step_gather_matches_host_batch."""
    n, b = 256, 32
    x = np.zeros((n, 8, 8, 1), np.float32)
    y = np.arange(n, dtype=np.int32)        # label == row id
    mesh = make_mesh()
    ds = DeviceDataset(x, y, b, mesh=mesh, seed=7)

    seen = []
    for i in range(ds.steps_per_epoch):
        data = next(ds)
        pos = (i % ds.steps_per_epoch) * b
        idx = np.asarray(data["perm"])[0, pos:pos + b]   # epoch 0 -> slot 0
        seen.extend(np.asarray(y)[idx].tolist())
    assert sorted(seen) == list(range(n))


def test_indexed_step_gather_matches_host_batch():
    """The device gather feeds the step the exact rows the perm arithmetic
    names: an indexed step and a plain step fed the manually-gathered batch
    produce identical params from identical initial state."""
    mesh = make_mesh()
    x, y = _data(256)
    b = 64
    ds = DeviceDataset(x, y, b, mesh=mesh, seed=4)
    make_state = lambda: TrainState.create_sharded(
        build_model("softmax"), optax.sgd(0.2), (b, 28, 28, 1), 0,
        replicated_sharding(mesh))
    s_idx, s_ref = make_state(), make_state()
    data = next(ds)
    perm = np.asarray(data["perm"])[0]                  # epoch 0 -> slot 0
    host_batch = {"image": jnp.asarray(x[perm[:b]]),
                  "label": jnp.asarray(y[perm[:b]])}
    with mesh:
        s_idx, m_idx = make_indexed_train_step(b, ds.steps_per_epoch)(
            s_idx, data)
        s_ref, m_ref = make_train_step()(s_ref, host_batch)
    np.testing.assert_allclose(float(m_idx["loss"]), float(m_ref["loss"]),
                               rtol=1e-6)
    jax.tree.map(lambda a, c: np.testing.assert_array_equal(a, c),
                 s_idx.params, s_ref.params)


def test_indexed_step_trains_on_mesh():
    mesh = make_mesh()
    x, y = _data(512)
    b = 64
    ds = DeviceDataset(x, y, b, mesh=mesh, seed=0)
    state = TrainState.create_sharded(
        build_model("softmax"), optax.sgd(0.5), (b, 28, 28, 1), 0,
        replicated_sharding(mesh))
    step = make_indexed_train_step(b, ds.steps_per_epoch, mesh=mesh)
    losses = []
    with mesh:
        for _ in range(30):
            state, m = step(state, next(ds))
            losses.append(float(m["loss"]))
    assert int(state.step) == 30
    assert np.mean(losses[-5:]) < np.mean(losses[:5])
    # Params stay replicated; the gathered batch resharding is internal.
    assert jax.tree.leaves(state.params)[0].sharding.is_fully_replicated


def test_device_data_flag_validation(tmp_path, small_synthetic):
    from distributedtensorflowexample_tpu.config import RunConfig
    from distributedtensorflowexample_tpu.trainers.common import run_training

    cfg = RunConfig(device_data="bogus", train_steps=1,
                    batch_size=64, global_batch=True,
                    data_dir=str(tmp_path), log_dir=str(tmp_path / "l"),
                    resume=False)
    with pytest.raises(ValueError, match="device_data"):
        run_training(cfg, "softmax", "mnist")


def test_run_training_device_data_end_to_end(tmp_path, small_synthetic):
    """run_training on the auto (device-resident) path: trains, evals,
    checkpoints, and resumes with aligned epochs.

    steps_per_loop=10: this was the suite's only dispatch-per-step
    multi-device e2e (80 bare dispatches = 80 collective rendezvous) and
    the reliable victim of XLA:CPU's under-load rendezvous race (judge
    r2 + three round-3 load runs, always this test).  Fused windows cut
    the rendezvous count ~10x without weakening what the test pins —
    train/eval/checkpoint/resume epoch alignment; per-step dispatch
    semantics are covered by the single-step tests above and on real
    hardware by bench.py."""
    from distributedtensorflowexample_tpu.config import RunConfig
    from distributedtensorflowexample_tpu.trainers.common import run_training

    common = dict(batch_size=64, global_batch=True, learning_rate=0.5,
                  data_dir=str(tmp_path), log_dir=str(tmp_path / "logs"),
                  dataset="synthetic", log_every=50, seed=1, steps_per_loop=10)
    out = run_training(RunConfig(train_steps=60, checkpoint_every=50,
                                 resume=False, **common), "softmax", "mnist")
    assert out["steps"] == 60
    assert out["final_accuracy"] > 0.8
    out2 = run_training(RunConfig(train_steps=80, resume=True, **common),
                        "softmax", "mnist")
    assert out2["steps"] == 80


def test_unrolled_step_matches_stepwise():
    """K fused updates == K separate updates, bit-for-bit on params."""
    mesh = make_mesh()
    x, y = _data(512)
    b, K = 64, 4
    mk = lambda spn: DeviceDataset(x, y, b, mesh=mesh, seed=2,
                                   steps_per_next=spn)
    state_kw = dict()
    make_state = lambda: TrainState.create_sharded(
        build_model("softmax"), optax.sgd(0.1), (b, 28, 28, 1), 0,
        replicated_sharding(mesh))

    ds1, dsK = mk(1), mk(K)
    assert ds1.steps_per_epoch == dsK.steps_per_epoch  # 512//64=8, K|8
    s1, sK = make_state(), make_state()
    one = make_indexed_train_step(b, ds1.steps_per_epoch)
    fused = make_indexed_train_step(b, dsK.steps_per_epoch, unroll_steps=K)
    with mesh:
        for _ in range(2 * K):
            s1, m1 = one(s1, next(ds1))
        sK, mK = fused(sK, next(dsK))
        sK, mK = fused(sK, next(dsK))
    assert int(s1.step) == int(sK.step) == 2 * K
    jax.tree.map(lambda a, c: np.testing.assert_array_equal(a, c),
                 s1.params, sK.params)


def test_run_training_steps_per_loop(tmp_path, small_synthetic):
    from distributedtensorflowexample_tpu.config import RunConfig
    from distributedtensorflowexample_tpu.trainers.common import run_training

    common = dict(batch_size=64, global_batch=True, learning_rate=0.5,
                  data_dir=str(tmp_path), log_dir=str(tmp_path / "logs"),
                  dataset="synthetic", log_every=20, seed=1, resume=False)
    out = run_training(RunConfig(train_steps=60, steps_per_loop=4, **common),
                       "softmax", "mnist")
    assert out["steps"] == 60
    assert out["final_accuracy"] > 0.8
    with pytest.raises(ValueError, match="multiple"):
        run_training(RunConfig(train_steps=61, steps_per_loop=4, **common),
                     "softmax", "mnist")


def test_auto_steps_per_loop_value():
    """--steps_per_loop 0 picks the largest divisor of the remaining steps
    bounded by the cap and the epoch length (VERDICT r4 #4)."""
    from distributedtensorflowexample_tpu.trainers.common import (
        auto_steps_per_loop)

    assert auto_steps_per_loop(60, 32) == 30       # <= min(64, 32, 60)
    assert auto_steps_per_loop(64, 100) == 64      # cap itself divides
    assert auto_steps_per_loop(61, 100) == 61      # remaining <= cap
    assert auto_steps_per_loop(122, 100) == 61     # largest divisor <= 64
    assert auto_steps_per_loop(127, 100) == 1      # prime > cap
    assert auto_steps_per_loop(1, 32) == 1
    assert auto_steps_per_loop(40, 8) == 8         # epoch length caps
    assert auto_steps_per_loop(1000, 8, cap=64) == 8
    # Periodic hooks constrain the unroll: it must divide every positive
    # interval so eval/checkpoint/log marks land on exact steps.
    assert auto_steps_per_loop(40, 64, intervals=(100, 20, 0)) == 20
    assert auto_steps_per_loop(4, 32, intervals=(50, 0, 2)) == 2
    assert auto_steps_per_loop(60, 32, intervals=(1,)) == 1   # per-step logs
    assert auto_steps_per_loop(1000, 937, intervals=(100,)) == 50
    # Resume offset: boundaries are start + k*d, so d must divide the
    # start too or interval marks drift (e.g. fire at 73/83/93 not
    # 70/80/90 after resuming from an odd step).
    assert auto_steps_per_loop(30, 100, intervals=(10,), start=60) == 10
    assert auto_steps_per_loop(30, 100, intervals=(10,), start=63) == 1
    assert auto_steps_per_loop(20, 32, start=60) == 20
    # Always a divisor: the default CLI can never hit the multiple error.
    for remaining in range(1, 200):
        for spe in (1, 7, 32):
            assert remaining % auto_steps_per_loop(remaining, spe) == 0
            assert remaining % auto_steps_per_loop(
                remaining, spe, intervals=(20, 7)) == 0


def test_run_training_auto_unroll_default(tmp_path, small_synthetic,
                                          capsys):
    """The shipped default (steps_per_loop=0 -> auto): exact target step
    count, hooks/logs at the fused boundaries, a chief notice naming the
    chosen unroll, and a resume whose new remaining count re-picks a
    valid divisor."""
    from distributedtensorflowexample_tpu.config import RunConfig
    from distributedtensorflowexample_tpu.trainers.common import run_training

    common = dict(batch_size=64, global_batch=True, learning_rate=0.5,
                  data_dir=str(tmp_path), log_dir=str(tmp_path / "logs"),
                  dataset="synthetic", log_every=20, seed=1)
    out = run_training(RunConfig(train_steps=60, checkpoint_every=50,
                                 resume=False, **common), "softmax", "mnist")
    assert out["steps"] == 60          # auto unroll divides 60 exactly
    assert out["final_accuracy"] > 0.8
    assert "steps_per_loop auto: fusing" in capsys.readouterr().out
    out2 = run_training(RunConfig(train_steps=80, resume=True, **common),
                        "softmax", "mnist")
    assert out2["steps"] == 80         # remaining 20 re-picked cleanly


def test_unrolled_step_across_epoch_boundary_matches_stepwise():
    """A fused window that straddles an epoch boundary (spe=6, K=4: the
    window [4,8) crosses at step 6) must match the stepwise run bitwise —
    the slot-select gather reads the new epoch's perm mid-scan."""
    mesh = make_mesh()
    x, y = _data(384)
    b, K, total = 64, 4, 12
    ds1 = DeviceDataset(x, y, b, mesh=mesh, seed=9)
    dsK = DeviceDataset(x, y, b, mesh=mesh, seed=9, steps_per_next=K)
    assert ds1.steps_per_epoch == 6 and total % K == 0
    make_state = lambda: TrainState.create_sharded(
        build_model("softmax"), optax.sgd(0.1), (b, 28, 28, 1), 0,
        replicated_sharding(mesh))
    s1, sK = make_state(), make_state()
    one = make_indexed_train_step(b, 6)
    fused = make_indexed_train_step(b, 6, unroll_steps=K)
    with mesh:
        for _ in range(total):
            s1, _ = one(s1, next(ds1))
        for _ in range(total // K):
            sK, _ = fused(sK, next(dsK))
    assert int(s1.step) == int(sK.step) == total        # 2 epochs crossed
    jax.tree.map(lambda a, c: np.testing.assert_array_equal(a, c),
                 s1.params, sK.params)


def test_resume_mid_epoch_with_multi_epoch_windows():
    """Resume at a mid-epoch step with a window longer than an epoch
    (spe=6, K=15): the resumed dataset + step must continue the fresh
    run's trajectory bitwise."""
    mesh = make_mesh()
    x, y = _data(384)
    b, K = 64, 15
    make_state = lambda: TrainState.create_sharded(
        build_model("softmax"), optax.sgd(0.1), (b, 28, 28, 1), 0,
        replicated_sharding(mesh))
    step = make_indexed_train_step(b, 6, unroll_steps=K)

    ds_full = DeviceDataset(x, y, b, mesh=mesh, seed=13, steps_per_next=K)
    assert ds_full.steps_per_epoch == 6   # the literal the step was built on
    s_full = make_state()
    with mesh:
        for _ in range(3):
            s_full, _ = step(s_full, next(ds_full))

    # "Resume": replay the first window, then continue with a dataset
    # constructed at start_step=K (mid-epoch: 15 % 6 = 3).
    ds_head = DeviceDataset(x, y, b, mesh=mesh, seed=13, steps_per_next=K)
    s_res = make_state()
    with mesh:
        s_res, _ = step(s_res, next(ds_head))
        ds_resumed = DeviceDataset(x, y, b, mesh=mesh, seed=13,
                                   start_step=K, steps_per_next=K)
        for _ in range(2):
            s_res, _ = step(s_res, next(ds_resumed))
    assert int(s_full.step) == int(s_res.step) == 3 * K
    jax.tree.map(lambda a, c: np.testing.assert_array_equal(a, c),
                 s_full.params, s_res.params)


def test_no_truncation_and_unshuffled_order():
    """Epochs keep every whole batch (only the sub-batch remainder drops,
    matching the host Batcher) and shuffle=False yields identity order."""
    x, y = _data(n=33 * 64 + 17)
    mesh = make_mesh()
    ds = DeviceDataset(x, y, 64, mesh=mesh, shuffle=False)
    assert ds.steps_per_epoch == 33
    pair = np.asarray(next(ds)["perm"])
    np.testing.assert_array_equal(pair[0], np.arange(33 * 64))


def test_steps_per_next_bounds_and_ring_sizing():
    # Ring sized for TWO consecutive windows (ceil(2K/spe) + 2): prefetch
    # computes the next window's permutations while the current window is
    # in flight — see DeviceDataset.ring_slots_for.
    x, y = _data(384)   # 6 steps/epoch at batch 64
    mesh = make_mesh()
    assert DeviceDataset(x, y, 64, mesh=mesh, steps_per_next=6).num_slots == 4
    assert DeviceDataset(x, y, 64, mesh=mesh, steps_per_next=7).num_slots == 5
    assert DeviceDataset(x, y, 64, mesh=mesh,
                         steps_per_next=24).num_slots == 10
    with pytest.raises(ValueError, match="steps_per_next"):
        DeviceDataset(x, y, 64, mesh=mesh, steps_per_next=0)


def test_multi_epoch_fused_window_matches_stepwise():
    """A single fused window spanning MULTIPLE epochs (spe=6, K=15: three
    boundary crossings in one compiled call) matches stepwise bitwise —
    the perm ring holds every epoch the window touches."""
    mesh = make_mesh()
    x, y = _data(384)
    b, K = 64, 15
    ds1 = DeviceDataset(x, y, b, mesh=mesh, seed=11)
    dsK = DeviceDataset(x, y, b, mesh=mesh, seed=11, steps_per_next=K)
    assert dsK.num_slots == 7                  # two 15-step windows + margin
    make_state = lambda: TrainState.create_sharded(
        build_model("softmax"), optax.sgd(0.1), (b, 28, 28, 1), 0,
        replicated_sharding(mesh))
    s1, sK = make_state(), make_state()
    one = make_indexed_train_step(b, 6)
    fused = make_indexed_train_step(b, 6, unroll_steps=K)
    with mesh:
        for _ in range(2 * K):
            s1, _ = one(s1, next(ds1))
        for _ in range(2):
            sK, _ = fused(sK, next(dsK))
    assert int(s1.step) == int(sK.step) == 2 * K       # 5 epochs covered
    jax.tree.map(lambda a, c: np.testing.assert_array_equal(a, c),
                 s1.params, sK.params)


# ---- uint8-resident storage + in-step dequant (round 4) -----------------
# The gather is the resident path's main HBM traffic; storing the split
# uint8 (auto-detected, bitwise-verified) cuts those bytes 4x, and the
# in-step dequant must reproduce the loader's float32 values EXACTLY so
# nothing downstream can tell the difference.

def test_auto_quantize_stores_uint8_and_dequant_is_bitwise():
    x, y = _data()
    assert x.dtype == np.float32
    mesh = make_mesh()
    ds = DeviceDataset(x, y, 64, mesh=mesh, seed=3)
    assert ds.dequant == "unit"
    assert np.asarray(ds.images).dtype == np.uint8
    ds_f = DeviceDataset(x, y, 64, mesh=mesh, seed=3, quantize="off")
    assert ds_f.dequant is None
    assert np.asarray(ds_f.images).dtype == np.float32

    from distributedtensorflowexample_tpu.parallel.sync import (
        make_device_gather)
    # No dequant plumbing: the constants ride in the data pytree and the
    # gather dtype-dispatches, so the same factory serves both.  The
    # default impl resolves to the affine fast path (round 5), so the
    # quantized pytree carries dq_scale/dq_bias, not a LUT.
    g_u = jax.jit(make_device_gather(64, ds.steps_per_epoch, mesh=mesh,
                                     num_slots=ds.num_slots))
    g_f = jax.jit(make_device_gather(64, ds_f.steps_per_epoch, mesh=mesh,
                                     num_slots=ds_f.num_slots))
    peeked = ds.peek()
    assert "dq_scale" in peeked and "dq_bias" in peeked
    assert "lut" not in peeked
    peeked_f = ds_f.peek()
    assert "lut" not in peeked_f and "dq_scale" not in peeked_f
    step0 = jnp.asarray(0, jnp.int32)
    rng = jax.random.PRNGKey(0)
    with mesh:
        bu = g_u(step0, rng, next(ds))
        bf = g_f(step0, rng, next(ds_f))
    assert np.asarray(bu["image"]).dtype == np.float32
    np.testing.assert_array_equal(np.asarray(bu["image"]),
                                  np.asarray(bf["image"]))
    np.testing.assert_array_equal(np.asarray(bu["label"]),
                                  np.asarray(bf["label"]))


def test_auto_quantize_recovers_cifar_normalization():
    from distributedtensorflowexample_tpu.data.device_dataset import (
        _dequant_numpy)
    x, y = make_synthetic(256, (32, 32, 3), 10, seed=1)
    # The loader's exact arithmetic (load_cifar10 normalize=True): recover
    # the bytes and apply the canonical single-rounding affine — NOT a
    # separate f32 (x - MEAN) / STD, which double-rounds off the affine
    # grid and would (correctly) fail byte recovery.
    xn = _dequant_numpy(np.rint(x * 255.0).astype(np.uint8), "cifar")
    ds = DeviceDataset(xn, y, 32, mesh=make_mesh())
    assert ds.dequant == "cifar"
    u8 = np.asarray(ds.images)
    assert u8.dtype == np.uint8
    np.testing.assert_array_equal(_dequant_numpy(u8, "cifar"), xn)


def test_non_grid_floats_stay_float_resident():
    """Anything not byte-exact under a known pipeline must stay float32 —
    quantization may never silently change values."""
    x, y = _data()
    ds = DeviceDataset((x * 0.937).astype(np.float32), y, 64,
                       mesh=make_mesh())
    assert ds.dequant is None
    assert np.asarray(ds.images).dtype == np.float32


# ---- sharded-resident split (round 5, VERDICT r4 #8) --------------------
# data_sharding="sharded": the split is stored row-wise across the mesh
# (1/D of the HBM per device); the interleaved per-shard permutation keeps
# the gather collective-free.


def test_sharded_perm_positions_stay_in_shard_blocks():
    """Every position device d reads (batch columns [d*bpd,(d+1)*bpd) of
    each step) must name a row in d's block — the invariant that makes the
    local-index gather correct with zero collectives."""
    mesh = make_mesh()
    D = mesh.size
    x, y = _data(520)                      # truncates to 520, L=65/device
    ds = DeviceDataset(x, y, 64, mesh=mesh, seed=3, data_sharding="sharded")
    L, bpd = 520 // D, 64 // D
    assert ds.steps_per_epoch == L // bpd
    perm = np.asarray(next(ds)["perm"])
    for row in perm[:2]:                   # epochs 0, 1 resident
        grid = row.reshape(ds.steps_per_epoch, D, bpd)
        for d in range(D):
            block = grid[:, d, :].ravel()
            assert block.min() >= d * L and block.max() < (d + 1) * L
            # Per-shard epochs are without replacement too.
            assert len(np.unique(block)) == block.size


def test_sharded_gather_matches_host_rows():
    """The shard_map gather returns exactly the rows the interleaved perm
    names — bitwise, including the uint8->LUT dequantization."""
    from distributedtensorflowexample_tpu.parallel.sync import (
        make_device_gather)

    mesh = make_mesh()
    x, y = _data(512)
    ds = DeviceDataset(x, y, 64, mesh=mesh, seed=4, data_sharding="sharded")
    assert ds.dequant == "unit"            # synthetic snaps to 8-bit grid
    gather = make_device_gather(64, ds.steps_per_epoch, mesh=mesh,
                                num_slots=ds.num_slots,
                                data_sharding="sharded")
    g = jax.jit(lambda s, data: gather(s, jax.random.PRNGKey(0), data))
    with mesh:
        for step in (0, 3, ds.steps_per_epoch - 1):
            data = ds.peek()
            perm = np.asarray(data["perm"])
            idx = perm[0, step * 64:(step + 1) * 64]    # epoch 0 -> slot 0
            batch = g(jnp.asarray(step, jnp.int32), data)
            np.testing.assert_array_equal(np.asarray(batch["image"]), x[idx])
            np.testing.assert_array_equal(np.asarray(batch["label"]), y[idx])


def test_sharded_training_matches_host_fed_bitwise():
    """10 steps on the sharded-resident path == 10 steps of the plain
    host-fed step on the identical rows, bit-for-bit on params."""
    from distributedtensorflowexample_tpu.data.pipeline import (
        put_global_batch)
    from distributedtensorflowexample_tpu.parallel.mesh import batch_sharding

    mesh = make_mesh()
    x, y = _data(512)
    b, steps = 64, 10
    ds = DeviceDataset(x, y, b, mesh=mesh, seed=2, data_sharding="sharded")
    make_state = lambda: TrainState.create_sharded(
        build_model("softmax"), optax.sgd(0.2), (b, 28, 28, 1), 0,
        replicated_sharding(mesh))
    s_sh, s_ref = make_state(), make_state()
    step_sh = make_indexed_train_step(b, ds.steps_per_epoch, mesh=mesh,
                                      num_slots=ds.num_slots,
                                      data_sharding="sharded")
    step_ref = make_train_step(mesh=mesh)
    shard = batch_sharding(mesh)
    with mesh:
        for i in range(steps):
            data = next(ds)
            perm = np.asarray(data["perm"])
            spe, S = ds.steps_per_epoch, ds.num_slots
            idx = perm[(i // spe) % S, (i % spe) * b:(i % spe) * b + b]
            s_sh, m_sh = step_sh(s_sh, data)
            host = put_global_batch({"image": x[idx], "label": y[idx]},
                                    shard)
            s_ref, m_ref = step_ref(s_ref, host)
    assert int(s_sh.step) == int(s_ref.step) == steps
    jax.tree.map(lambda a, c: np.testing.assert_array_equal(a, c),
                 s_sh.params, s_ref.params)


def test_sharded_gather_with_device_augment():
    """The sharded gather's CIFAR augment branch: labels are the exact
    perm rows (augment never touches them), images keep shape/dtype and
    are a crop/flip rearrangement of the named rows (uint8-resident:
    every output pixel exists in the source row's padded reflection),
    and draws are deterministic per (rng, step)."""
    from distributedtensorflowexample_tpu.parallel.sync import (
        make_device_gather)

    mesh = make_mesh()
    x, y = _data(512, shape=(32, 32, 3))
    ds = DeviceDataset(x, y, 64, mesh=mesh, seed=6, data_sharding="sharded")
    assert ds.dequant == "unit"      # uint8-resident: LUT branch is live
    gather = make_device_gather(64, ds.steps_per_epoch, augment="cifar",
                                mesh=mesh, num_slots=ds.num_slots,
                                data_sharding="sharded")
    g = jax.jit(lambda s, r, data: gather(s, r, data))
    rng = jax.random.PRNGKey(1)
    with mesh:
        data = ds.peek()
        perm = np.asarray(data["perm"])
        idx = perm[0, :64]
        b1 = g(jnp.asarray(0, jnp.int32), rng, data)
        b2 = g(jnp.asarray(0, jnp.int32), rng, data)
    np.testing.assert_array_equal(np.asarray(b1["label"]), y[idx])
    assert b1["image"].shape == (64, 32, 32, 3)
    assert b1["image"].dtype == jnp.float32          # dequantized
    # Deterministic per (rng, step); crop/flip only rearranges pixels, so
    # every augmented pixel value already exists in its source row.
    np.testing.assert_array_equal(np.asarray(b1["image"]),
                                  np.asarray(b2["image"]))
    for row, src in zip(np.asarray(b1["image"])[:8], x[idx[:8]]):
        assert set(np.unique(row)) <= set(np.unique(src))


def test_sharded_gather_adds_no_collectives():
    """The design claim, pinned in the compiled HLO: the sharded-resident
    gather is collective-free — the full train step's collective set is
    IDENTICAL to the replicated-storage step's (the one fused gradient
    all-reduce), no all-gather/all-to-all introduced by the row-sharded
    operands."""
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from bench_scaling import collective_traffic

    mesh = make_mesh()
    x, y = _data(512)
    b = 64

    def compiled_traffic(data_sharding):
        ds = DeviceDataset(x, y, b, mesh=mesh, seed=0,
                           data_sharding=data_sharding)
        state = TrainState.create_sharded(
            build_model("softmax"), optax.sgd(0.1), (b, 28, 28, 1), 0,
            replicated_sharding(mesh))
        step = make_indexed_train_step(b, ds.steps_per_epoch, mesh=mesh,
                                       num_slots=ds.num_slots,
                                       data_sharding=data_sharding)
        with mesh:
            hlo = step.lower(state, ds.peek()).compile().as_text()
        return {op: c for op, c in collective_traffic(hlo).items()
                if c["count"]}

    repl = compiled_traffic("replicated")
    shard = compiled_traffic("sharded")
    assert repl == shard, (repl, shard)
    assert set(repl) <= {"all-reduce"}, repl   # just the gradient psum


def test_sharded_dataset_reduces_per_device_bytes():
    """The whole point: per-device HBM for the split is 1/D of the
    replicated footprint (same totals, same dtype)."""
    mesh = make_mesh()
    D = mesh.size
    x, y = _data(512)
    ds_r = DeviceDataset(x, y, 64, mesh=mesh, seed=0)
    ds_s = DeviceDataset(x, y, 64, mesh=mesh, seed=0,
                         data_sharding="sharded")
    rb = ds_r.images.addressable_shards[0].data.nbytes
    sb = ds_s.images.addressable_shards[0].data.nbytes
    assert sb * D == rb
    assert len({s.data.nbytes for s in ds_s.images.addressable_shards}) == 1


def test_sharded_flag_validation_and_quantize_off():
    """Bad batch/mesh combinations fail by name; quantize='off' keeps the
    sharded split float32 and training still runs."""
    from distributedtensorflowexample_tpu.parallel.sync import (
        make_device_gather)

    mesh = make_mesh()
    x, y = _data(512)
    with pytest.raises(ValueError, match="divide"):
        DeviceDataset(x, y, mesh.size + 1, mesh=mesh,
                      data_sharding="sharded")
    with pytest.raises(ValueError, match="mesh"):
        DeviceDataset(x, y, 64, data_sharding="sharded")   # no mesh
    with pytest.raises(ValueError, match="data_sharding"):
        DeviceDataset(x, y, 64, mesh=mesh, data_sharding="bogus")
    with pytest.raises(ValueError, match="divide"):
        make_device_gather(mesh.size + 1, 4, mesh=mesh, num_slots=3,
                           data_sharding="sharded")

    ds = DeviceDataset(x, y, 64, mesh=mesh, seed=1, data_sharding="sharded",
                       quantize="off")
    assert ds.dequant is None
    assert np.asarray(ds.images).dtype == np.float32
    step = make_indexed_train_step(64, ds.steps_per_epoch, mesh=mesh,
                                   num_slots=ds.num_slots,
                                   data_sharding="sharded")
    state = TrainState.create_sharded(
        build_model("softmax"), optax.sgd(0.1), (64, 28, 28, 1), 0,
        replicated_sharding(mesh))
    with mesh:
        state, m = step(state, next(ds))
    assert np.isfinite(float(m["loss"]))


def test_sharded_async_composes():
    """Sharded-resident gather under the async local-SGD shard_map step:
    workers still diverge and reconcile; the device-local batch shard is
    exactly its worker's rows."""
    from distributedtensorflowexample_tpu.parallel.async_ps import (
        make_indexed_async_train_step, make_worker_state)

    mesh = make_mesh()
    x, y = _data(512)
    b = 64
    ds = DeviceDataset(x, y, b, mesh=mesh, seed=5, steps_per_next=4,
                       data_sharding="sharded")
    state = TrainState.create_sharded(
        build_model("softmax"), optax.sgd(0.1), (b, 28, 28, 1), 0,
        replicated_sharding(mesh))
    state = make_worker_state(state, mesh.size, mesh)
    step = make_indexed_async_train_step(
        mesh.size, 8, b, ds.steps_per_epoch, mesh=mesh, unroll_steps=4,
        num_slots=ds.num_slots, data_sharding="sharded")
    with mesh:
        state, m = step(state, next(ds))      # step 4: mid-period
        leaf = np.asarray(jax.tree.leaves(state.params)[0])
        assert not np.array_equal(leaf[0], leaf[1])   # diverged
        state, m = step(state, next(ds))      # step 8: averaging point
        leaf = np.asarray(jax.tree.leaves(state.params)[0])
        np.testing.assert_allclose(leaf[0], leaf[-1], rtol=1e-6, atol=1e-7)
    assert int(state.step) == 8
    assert np.isfinite(float(m["loss"]))


def test_run_training_sharded_end_to_end(tmp_path, small_synthetic):
    """--data_sharding sharded through the full trainer path (auto unroll,
    eval, exact step count) + the device_data=off incompatibility error."""
    from distributedtensorflowexample_tpu.config import RunConfig
    from distributedtensorflowexample_tpu.trainers.common import run_training

    common = dict(batch_size=64, global_batch=True, learning_rate=0.5,
                  data_dir=str(tmp_path), log_dir=str(tmp_path / "logs"),
                  dataset="synthetic", log_every=20, seed=1, resume=False)
    out = run_training(RunConfig(train_steps=60, data_sharding="sharded",
                                 **common), "softmax", "mnist")
    assert out["steps"] == 60
    assert out["final_accuracy"] > 0.8
    with pytest.raises(ValueError, match="data_sharding"):
        run_training(RunConfig(train_steps=60, data_sharding="sharded",
                               device_data="off", **common),
                     "softmax", "mnist")


def test_empty_split_fails_with_size_message_not_reduction_error():
    """A zero-length split must hit the 'smaller than batch' validation,
    not a ValueError from min()/max() inside _try_quantize (ADVICE r4)."""
    from distributedtensorflowexample_tpu.data.device_dataset import (
        _try_quantize)

    empty = np.zeros((0, 28, 28, 1), np.float32)
    assert _try_quantize(empty) is None
    with pytest.raises(ValueError, match="smaller than"):
        DeviceDataset(empty, np.zeros((0,), np.int32), 64)


def test_quantized_training_bitwise_parity():
    """12 real fused sync steps: uint8-resident and float32-resident runs
    end with BITWISE-identical parameters and loss."""
    x, y = _data(256)
    mesh = make_mesh()
    model = build_model("softmax")

    def run(quantize):
        ds = DeviceDataset(x, y, 32, mesh=mesh, seed=2, quantize=quantize,
                           steps_per_next=4)
        state = TrainState.create_sharded(model, optax.sgd(0.1),
                                          (32, 28, 28, 1), 0,
                                          replicated_sharding(mesh))
        step = make_indexed_train_step(32, ds.steps_per_epoch, mesh=mesh,
                                       unroll_steps=4,
                                       num_slots=ds.num_slots)
        with mesh:
            for _ in range(3):
                state, metrics = step(state, next(ds))
            jax.block_until_ready(metrics)
        return (np.asarray(jax.tree.leaves(state.params)[0]),
                float(metrics["loss"]))

    p_u, l_u = run("auto")
    p_f, l_f = run("off")
    assert l_u == l_f
    np.testing.assert_array_equal(p_u, p_f)


def test_quantized_gather_reduces_bytes_accessed():
    """The point of the uint8 store: the compiled step touches
    substantially fewer bytes (the gather reads 1/4 the data)."""
    import bench
    x, y = _data(512)
    mesh = make_mesh()
    model = build_model("softmax")

    def cost(quantize):
        ds = DeviceDataset(x, y, 64, mesh=mesh, seed=0, quantize=quantize,
                           steps_per_next=4)
        state = TrainState.create_sharded(model, optax.sgd(0.1),
                                          (64, 28, 28, 1), 0,
                                          replicated_sharding(mesh))
        step = make_indexed_train_step(64, ds.steps_per_epoch, mesh=mesh,
                                       unroll_steps=4,
                                       num_slots=ds.num_slots)
        with mesh:
            return bench._cost_per_step(step, state, ds.peek(), 4)

    c_u, c_f = cost("auto"), cost("off")
    assert c_u.get("bytes_accessed") and c_f.get("bytes_accessed")
    assert c_u["bytes_accessed"] < 0.75 * c_f["bytes_accessed"], (c_u, c_f)
