"""End-to-end gang drills (the ISSUE's ACCEPTANCE criterion): a 2-rank
sync mnist_cnn fleet where a rank-targeted FaultPlan kills one rank
mid-run — gang teardown, resume-step agreement, gang restart — and the
resumed params/opt-state/loss-tape are BITWISE-equal to an
uninterrupted run, with per-rank flights + the fleet journal
cross-checking the restart count and the agreed step.

Each rank is a real OS process running tools/faultline.py (a fresh jax
import per child), so this file runs as an isolated subprocess during
full-suite runs (tests/isolation_list.py) — wall-time containment, not
abort risk.
"""

import glob
import json
import os
import sys

import pytest

from distributedtensorflowexample_tpu.resilience.fleet import FleetSupervisor
from distributedtensorflowexample_tpu.resilience.supervisor import (
    Journal, RetryPolicy)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FAULTLINE = os.path.join(REPO, "tools", "faultline.py")

pytestmark = [pytest.mark.fleet, pytest.mark.faults]


def _straight_run(capsys, workdir: str, steps: int) -> dict:
    """The uninterrupted reference, in-process (shares the warm jit
    cache): same model/seed/steps, no faults."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import faultline
    finally:
        sys.path.pop(0)
    rc = faultline.main(["--plan", "none", "--steps", str(steps),
                         "--model", "mnist_cnn", "--workdir", workdir,
                         "--keep", "10", "--seed", "0"])
    out = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert rc == 0
    return json.loads(out[-1])


def _rank_argv(base, plan: str, steps: int) -> list[str]:
    return [sys.executable, FAULTLINE, "--plan", plan,
            "--steps", str(steps), "--model", "mnist_cnn",
            "--workdir", os.path.join(str(base), "rank{rank}"),
            "--keep", "10", "--seed", "0"]


def _journal_events(path: str) -> list[dict]:
    with open(path) as f:
        return [json.loads(line) for line in f]


def _last_json(path: str) -> dict:
    with open(path) as f:
        lines = [l for l in f.read().splitlines() if l.strip()]
    return json.loads(lines[-1])


def test_acceptance_rank_kill_gang_restart_bitwise(tmp_path, capsys):
    """ACCEPTANCE: rank 1 SIGKILLed at step 4 by `kill@4%1` (no save, no
    exit hooks — a lost host, not a preemption).  The fleet tears down
    rank 0 (which saves cooperatively under TERM), agrees on the max
    common valid step, discards rank 0's divergent newer snapshots,
    restarts the gang with FLEET_RESUME_STEP exported — and every
    rank's final digest and loss tape reproduce the uninterrupted run
    exactly."""
    steps = 8
    journal_path = str(tmp_path / "fleet.jsonl")
    flight_dir = str(tmp_path / "flight")
    fleet = FleetSupervisor(
        2, policy=RetryPolicy(retries=2, backoff_base_s=0.01,
                              backoff_max_s=0.02),
        journal=Journal(journal_path),
        kill_grace_s=30.0,          # must cover rank 0's save-on-TERM
        poll_s=0.1, seed=0, workdir=str(tmp_path / "fleet"))
    res = fleet.run(
        _rank_argv(tmp_path, "kill@4%1", steps), name="drill",
        snapshot_dir_template=os.path.join(str(tmp_path), "rank{rank}",
                                           "snapshots"),
        stdout_dir=str(tmp_path / "out"),
        env_extra={"OBS_DIR": flight_dir})
    assert res.status == "ok", res.reasons
    assert res.gang_attempts == 2 and res.restarts == 1
    assert res.last_rcs == {0: 0, 1: 0}

    # the agreement: rank 1 died at 4 with step 4 already snapshotted
    # (SnapshotHook runs before FaultInjectionHook), rank 0 was torn
    # down somewhere >= its own last save — agreed step is what the
    # journal says, and it is a real mid-run step
    events = _journal_events(journal_path)
    agree = next(e for e in events if e["event"] == "resume_agreement")
    agreed = agree["agreed"]
    assert 1 <= agreed <= 4, agree
    assert res.agreed_steps == [agreed]
    assert max(agree["per_rank"]["1"]) == 4     # rank 1's last save
    # rank 1's SIGKILL death is journaled with its signal rc; when rank
    # 0 was still mid-run (the usual case) the whole gang was torn down
    # — but mnist_cnn steps are sub-millisecond post-compile, so rank 0
    # finishing all 8 inside one poll window is a legal race too.
    assert any(e["event"] == "rank_exit" and e.get("rank") == 1
               and e.get("rc") == -9 for e in events)
    for tear in (e for e in events if e["event"] == "gang_teardown"):
        assert tear["why"] == "rank_crash" and tear["rank"] == 1

    straight = _straight_run(capsys, str(tmp_path / "straight"), steps)

    for rank in (0, 1):
        final = _last_json(
            str(tmp_path / "out" / f"rank{rank}_attempt1.out"))
        assert final["status"] == "ok" and final["step"] == steps
        assert final["start_step"] == agreed      # resumed the AGREED step
        # bitwise: every state leaf (params, opt state, rng, step)
        assert final["digest"] == straight["digest"], f"rank {rank}"
        # loss tape: the resumed tape is exactly the straight tape's
        # suffix past the agreed step
        assert final["losses"] == straight["losses"][agreed:], f"rank {rank}"
    # rank 0's first attempt ran PAST the kill (torn down mid-run ->
    # "preempted", or finished inside the poll window -> "ok"); either
    # way its emitted tape is a bitwise prefix of the straight tape —
    # the overlap with the redone steps reproduces exactly
    first0 = _last_json(str(tmp_path / "out" / "rank0_attempt0.out"))
    assert first0["status"] in ("preempted", "ok")
    n = len(first0["losses"])
    assert n >= 1 and first0["losses"] == straight["losses"][:n]

    # per-rank flights (flight_<rank>_<pid>.json): every rank left at
    # least one postmortem whose attempt/rank fields line up with the
    # journal's two gang attempts
    for rank in (0, 1):
        flights = [json.load(open(p)) for p in
                   glob.glob(os.path.join(flight_dir,
                                          f"flight_{rank}_*.json"))]
        assert flights, f"rank {rank} left no flight"
        assert {f["rank"] for f in flights} == {rank}
        assert max(f["attempt"] for f in flights) == 1
    # rank 0's attempt-0 flight documents how that attempt ended
    # ("preempted" when torn down mid-run, "exit" when it finished)
    r0_reasons = {f["attempt"]: f["reason"] for f in
                  (json.load(open(p)) for p in
                   glob.glob(os.path.join(flight_dir, "flight_0_*.json")))}
    assert r0_reasons.get(0) in ("preempted", "exit")


def test_wedged_rank_heartbeat_drill_restarts_bitwise(tmp_path, capsys):
    """'wedge rank 0's heartbeat': rank 0 blocks in-dispatch at step 3
    (beats stop, process lives) while rank 1 races ahead; the per-rank
    watchdog tears the gang down, the agreement rolls rank 1 BACK to
    rank 0's last provable step (discarding rank 1's newer snapshots),
    and the restarted gang still lands bitwise on the straight run."""
    steps = 6
    journal_path = str(tmp_path / "fleet.jsonl")
    fleet = FleetSupervisor(
        2, policy=RetryPolicy(retries=2, backoff_base_s=0.01,
                              backoff_max_s=0.02),
        journal=Journal(journal_path),
        # The timeout must comfortably exceed the child's jax compile
        # (the stretch between the arming first beat and the first
        # boundary beat — several seconds here, tens under suite load):
        # a tight edge kills HEALTHY ranks mid-compile, which is
        # exactly the supervisor's beat-vs-wall lesson.  The wedge arg
        # (240 s) must in turn exceed timeout+grace so the watchdog,
        # not the sleep running out, is what ends the attempt.
        heartbeat_timeout_s=60.0,
        # the wedged rank sleeps through TERM (PEP 475 resumes the
        # sleep), so the grace only delays its SIGKILL — keep it short;
        # rank 1 is long finished by the time the watchdog fires
        kill_grace_s=6.0,
        poll_s=0.1, seed=0, workdir=str(tmp_path / "fleet"))
    res = fleet.run(
        _rank_argv(tmp_path, "wedge@3:240%0", steps), name="wedge_drill",
        snapshot_dir_template=os.path.join(str(tmp_path), "rank{rank}",
                                           "snapshots"),
        stdout_dir=str(tmp_path / "out"))
    assert res.status == "ok", res.reasons
    assert res.gang_attempts == 2 and res.restarts == 1
    tear = next(e for e in _journal_events(journal_path)
                if e["event"] == "gang_teardown")
    assert tear["why"] == "rank_heartbeat" and tear["rank"] == 0
    agree = next(e for e in _journal_events(journal_path)
                 if e["event"] == "resume_agreement")
    agreed = agree["agreed"]
    # 0 is legal: rank 1 TERM'd before its first completed step has
    # nothing valid, and the agreement degrades to a full fresh start
    assert 0 <= agreed <= 3

    straight = _straight_run(capsys, str(tmp_path / "straight"), steps)
    for rank in (0, 1):
        final = _last_json(
            str(tmp_path / "out" / f"rank{rank}_attempt1.out"))
        assert final["status"] == "ok" and final["step"] == steps
        assert final["start_step"] == agreed
        assert final["digest"] == straight["digest"], f"rank {rank}"
        assert final["losses"] == straight["losses"][agreed:], f"rank {rank}"
