"""End-to-end gang drills (the ISSUE's ACCEPTANCE criterion): a 2-rank
sync mnist_cnn fleet where a rank-targeted FaultPlan kills one rank
mid-run — gang teardown, resume-step agreement, gang restart — and the
resumed params/opt-state/loss-tape are BITWISE-equal to an
uninterrupted run, with per-rank flights + the fleet journal
cross-checking the restart count and the agreed step.

Each rank is a real OS process running tools/faultline.py (a fresh jax
import per child), so this file runs as an isolated subprocess during
full-suite runs (tests/isolation_list.py) — wall-time containment, not
abort risk.
"""

import glob
import json
import os
import sys

import pytest

from distributedtensorflowexample_tpu.obs import anomaly as obs_anomaly
from distributedtensorflowexample_tpu.obs import timeline as obs_timeline
from distributedtensorflowexample_tpu.resilience.fleet import FleetSupervisor
from distributedtensorflowexample_tpu.resilience.supervisor import (
    Journal, RetryPolicy)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FAULTLINE = os.path.join(REPO, "tools", "faultline.py")

pytestmark = [pytest.mark.fleet, pytest.mark.faults]


def _straight_run(capsys, workdir: str, steps: int) -> dict:
    """The uninterrupted reference, in-process (shares the warm jit
    cache): same model/seed/steps, no faults."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import faultline
    finally:
        sys.path.pop(0)
    rc = faultline.main(["--plan", "none", "--steps", str(steps),
                         "--model", "mnist_cnn", "--workdir", workdir,
                         "--keep", "10", "--seed", "0"])
    out = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert rc == 0
    return json.loads(out[-1])


def _rank_argv(base, plan: str, steps: int) -> list[str]:
    return [sys.executable, FAULTLINE, "--plan", plan,
            "--steps", str(steps), "--model", "mnist_cnn",
            "--workdir", os.path.join(str(base), "rank{rank}"),
            "--keep", "10", "--seed", "0"]


def _journal_events(path: str) -> list[dict]:
    with open(path) as f:
        return [json.loads(line) for line in f]


def _last_json(path: str) -> dict:
    with open(path) as f:
        lines = [l for l in f.read().splitlines() if l.strip()]
    return json.loads(lines[-1])


def test_acceptance_rank_kill_gang_restart_bitwise(tmp_path, capsys):
    """ACCEPTANCE: rank 1 SIGKILLed at step 4 by `kill@4%1` (no save, no
    exit hooks — a lost host, not a preemption).  The fleet tears down
    rank 0 (which saves cooperatively under TERM), agrees on the max
    common valid step, discards rank 0's divergent newer snapshots,
    restarts the gang with FLEET_RESUME_STEP exported — and every
    rank's final digest and loss tape reproduce the uninterrupted run
    exactly."""
    steps = 8
    journal_path = str(tmp_path / "fleet.jsonl")
    flight_dir = str(tmp_path / "flight")
    fleet = FleetSupervisor(
        2, policy=RetryPolicy(retries=2, backoff_base_s=0.01,
                              backoff_max_s=0.02),
        journal=Journal(journal_path),
        kill_grace_s=30.0,          # must cover rank 0's save-on-TERM
        poll_s=0.1, seed=0, workdir=str(tmp_path / "fleet"))
    res = fleet.run(
        _rank_argv(tmp_path, "kill@4%1", steps), name="drill",
        snapshot_dir_template=os.path.join(str(tmp_path), "rank{rank}",
                                           "snapshots"),
        stdout_dir=str(tmp_path / "out"),
        env_extra={"OBS_DIR": flight_dir})
    assert res.status == "ok", res.reasons
    assert res.gang_attempts == 2 and res.restarts == 1
    assert res.last_rcs == {0: 0, 1: 0}

    # the agreement: rank 1 died at 4 with step 4 already snapshotted
    # (SnapshotHook runs before FaultInjectionHook), rank 0 was torn
    # down somewhere >= its own last save — agreed step is what the
    # journal says, and it is a real mid-run step
    events = _journal_events(journal_path)
    agree = next(e for e in events if e["event"] == "resume_agreement")
    agreed = agree["agreed"]
    assert 1 <= agreed <= 4, agree
    assert res.agreed_steps == [agreed]
    assert max(agree["per_rank"]["1"]) == 4     # rank 1's last save
    # rank 1's SIGKILL death is journaled with its signal rc; when rank
    # 0 was still mid-run (the usual case) the whole gang was torn down
    # — but mnist_cnn steps are sub-millisecond post-compile, so rank 0
    # finishing all 8 inside one poll window is a legal race too.
    assert any(e["event"] == "rank_exit" and e.get("rank") == 1
               and e.get("rc") == -9 for e in events)
    for tear in (e for e in events if e["event"] == "gang_teardown"):
        assert tear["why"] == "rank_crash" and tear["rank"] == 1

    straight = _straight_run(capsys, str(tmp_path / "straight"), steps)

    for rank in (0, 1):
        final = _last_json(
            str(tmp_path / "out" / f"rank{rank}_attempt1.out"))
        assert final["status"] == "ok" and final["step"] == steps
        assert final["start_step"] == agreed      # resumed the AGREED step
        # bitwise: every state leaf (params, opt state, rng, step)
        assert final["digest"] == straight["digest"], f"rank {rank}"
        # loss tape: the resumed tape is exactly the straight tape's
        # suffix past the agreed step
        assert final["losses"] == straight["losses"][agreed:], f"rank {rank}"
    # rank 0's first attempt ran PAST the kill (torn down mid-run ->
    # "preempted", or finished inside the poll window -> "ok"); either
    # way its emitted tape is a bitwise prefix of the straight tape —
    # the overlap with the redone steps reproduces exactly
    first0 = _last_json(str(tmp_path / "out" / "rank0_attempt0.out"))
    assert first0["status"] in ("preempted", "ok")
    n = len(first0["losses"])
    assert n >= 1 and first0["losses"] == straight["losses"][:n]

    # per-rank flights (flight_<rank>_<pid>.json): every rank left at
    # least one postmortem whose attempt/rank fields line up with the
    # journal's two gang attempts
    for rank in (0, 1):
        flights = [json.load(open(p)) for p in
                   glob.glob(os.path.join(flight_dir,
                                          f"flight_{rank}_*.json"))]
        assert flights, f"rank {rank} left no flight"
        assert {f["rank"] for f in flights} == {rank}
        assert max(f["attempt"] for f in flights) == 1
    # rank 0's attempt-0 flight documents how that attempt ended
    # ("preempted" when torn down mid-run, "exit" when it finished)
    r0_reasons = {f["attempt"]: f["reason"] for f in
                  (json.load(open(p)) for p in
                   glob.glob(os.path.join(flight_dir, "flight_0_*.json")))}
    assert r0_reasons.get(0) in ("preempted", "exit")


def test_wedged_rank_heartbeat_drill_restarts_bitwise(tmp_path, capsys):
    """'wedge rank 0's heartbeat': rank 0 blocks in-dispatch at step 3
    (beats stop, process lives) while rank 1 races ahead; the per-rank
    watchdog tears the gang down, the agreement rolls rank 1 BACK to
    rank 0's last provable step (discarding rank 1's newer snapshots),
    and the restarted gang still lands bitwise on the straight run."""
    steps = 6
    journal_path = str(tmp_path / "fleet.jsonl")
    fleet = FleetSupervisor(
        2, policy=RetryPolicy(retries=2, backoff_base_s=0.01,
                              backoff_max_s=0.02),
        journal=Journal(journal_path),
        # The timeout must comfortably exceed the child's jax compile
        # (the stretch between the arming first beat and the first
        # boundary beat — several seconds here, tens under suite load):
        # a tight edge kills HEALTHY ranks mid-compile, which is
        # exactly the supervisor's beat-vs-wall lesson.  The wedge arg
        # (240 s) must in turn exceed timeout+grace so the watchdog,
        # not the sleep running out, is what ends the attempt.
        heartbeat_timeout_s=60.0,
        # the wedged rank sleeps through TERM (PEP 475 resumes the
        # sleep), so the grace only delays its SIGKILL — keep it short;
        # rank 1 is long finished by the time the watchdog fires
        kill_grace_s=6.0,
        poll_s=0.1, seed=0, workdir=str(tmp_path / "fleet"))
    res = fleet.run(
        _rank_argv(tmp_path, "wedge@3:240%0", steps), name="wedge_drill",
        snapshot_dir_template=os.path.join(str(tmp_path), "rank{rank}",
                                           "snapshots"),
        stdout_dir=str(tmp_path / "out"))
    assert res.status == "ok", res.reasons
    assert res.gang_attempts == 2 and res.restarts == 1
    tear = next(e for e in _journal_events(journal_path)
                if e["event"] == "gang_teardown")
    assert tear["why"] == "rank_heartbeat" and tear["rank"] == 0
    agree = next(e for e in _journal_events(journal_path)
                 if e["event"] == "resume_agreement")
    agreed = agree["agreed"]
    # 0 is legal: rank 1 TERM'd before its first completed step has
    # nothing valid, and the agreement degrades to a full fresh start
    assert 0 <= agreed <= 3

    straight = _straight_run(capsys, str(tmp_path / "straight"), steps)
    for rank in (0, 1):
        final = _last_json(
            str(tmp_path / "out" / f"rank{rank}_attempt1.out"))
        assert final["status"] == "ok" and final["step"] == steps
        assert final["start_step"] == agreed
        assert final["digest"] == straight["digest"], f"rank {rank}"
        assert final["losses"] == straight["losses"][agreed:], f"rank {rank}"


@pytest.mark.timeline
def test_acceptance_slow_rank_straggler_named_and_timeline_skew(tmp_path):
    """ACCEPTANCE (round 10): a 2-rank mnist_cnn fleet where a
    rank-targeted `slow_rank` fault turns rank 1 into a persistent
    straggler mid-run — no crash, no restart.  The online detectors
    must (a) fire rank 1's step-time regression within 3 steps of
    injection (its baseline is pinned over its OWN healthy warmup; the
    injection boundary's delay lands in the NEXT window sample), (b)
    name rank 1 — and only rank 1 — a straggler in the fleet
    health.json and journal, with lag evidence, and (c) leave flights
    whose merged timeline makes the skew visible: rank 1's
    post-injection steps are seconds wide where rank 0's stay sub-
    second, in a Perfetto trace carrying both rank lanes.

    The injected delay (3 s) and the OBS_ANOMALY_* drill knobs are
    scaled to THIS box: two contending jax processes step mnist_cnn in
    ~0.1-0.6 s with heavy scheduler jitter (measured while building
    round 10), so the live criterion's 0.25 s — 100x a TPU step — is
    inside CPU noise here.  The detector math is pinned in
    tests/test_obs.py; this drill pins the end-to-end wiring."""
    steps = 12
    inject = 8
    workdir = str(tmp_path / "fleet")
    journal_path = os.path.join(workdir, "fleet.jsonl")
    flight_dir = os.path.join(workdir, "flight")
    os.makedirs(workdir, exist_ok=True)
    fleet = FleetSupervisor(
        2, policy=RetryPolicy(retries=0, backoff_base_s=0.01,
                              backoff_max_s=0.02),
        journal=Journal(journal_path),
        kill_grace_s=30.0, poll_s=0.1, seed=0, workdir=workdir)
    argv = _rank_argv(tmp_path, f"slow_rank@{inject}:3.0%1", steps)
    argv += ["--snapshot_every", "100"]     # no snapshot noise in windows
    res = fleet.run(
        argv, name="straggler_drill",
        stdout_dir=str(tmp_path / "out"),
        # skip=2 drops the compile-dominated boundaries, warmup=3 pins
        # the baseline over boundaries 3-5 (steady state, before the
        # step-8 injection), z=5 clears contended-CPU sigma with the
        # 3 s delta in <= 2 slowed windows.  Production keeps the env
        # defaults (skip 1, warmup 16, z 8).
        env_extra={"OBS_DIR": flight_dir, "OBS_ANOMALY_WARMUP": "3",
                   "OBS_ANOMALY_SKIP": "2", "OBS_ANOMALY_Z": "5"})
    assert res.status == "ok", res.reasons
    assert res.gang_attempts == 1 and res.restarts == 0   # detection ONLY
    assert res.last_rcs == {0: 0, 1: 0}

    # (a) rank 1's own health.json: regression fired within <= 3 steps
    # of the injection (the delay at boundary `inject` lands in the
    # window ENDING at inject+1 — FaultInjectionHook runs last)
    h1 = obs_anomaly.read_health(os.path.join(workdir,
                                              "health_rank1.json"))
    reg = h1["flags"]["step_time_regression"]
    assert reg["fired_step"] is not None, h1["detectors"]["step_time"]
    assert inject + 1 <= reg["fired_step"] <= inject + 3, reg
    # rank 0's health reported too (a spurious regression there is
    # tolerated — one scheduler hiccup on sub-ms steps can score — but
    # it can never be named straggler: it IS the front rank)
    h0 = obs_anomaly.read_health(os.path.join(workdir,
                                              "health_rank0.json"))
    assert h0["step"] == steps

    # (b) the fleet monitor named rank 1 — journal annotation with lag
    # evidence, aggregate health.json straggler list, and only rank 1
    events = _journal_events(journal_path)
    strag = [e for e in events if e["event"] == "anomaly"
             and e["kind"] == "straggler"]
    assert [e["rank"] for e in strag] == [1]
    assert strag[0]["max_step"] - strag[0]["step"] >= 3   # real lag
    assert 4 <= strag[0]["step"] <= steps
    assert "lag" in strag[0]["why"]
    assert any(e["event"] == "anomaly" and e["rank"] == 1
               and e["kind"] == "step_time_regression" for e in events)
    fleet_health = obs_anomaly.read_health(os.path.join(workdir,
                                                        "health.json"))
    assert fleet_health["kind"] == "fleet"
    assert fleet_health["stragglers"] == [1]
    assert "1" in {str(k) for k in fleet_health["skew"]["lag_steps"]}

    # (c) merged timeline: both rank lanes present, skew visible in the
    # per-step anatomy (rank 1's slowed windows vs rank 0's), Perfetto
    # export carries both lanes + the straggler journal marker
    sources = obs_timeline.fleet_dir_sources(flight_dir=flight_dir,
                                             journal=journal_path)
    assert os.path.join(workdir, "health.json") in sources["health_paths"]
    merged = obs_timeline.merge(**sources)
    assert merged["coverage"]["ranks_present"] == [0, 1]
    assert not merged["coverage"]["unreadable"]
    anatomy = obs_timeline.step_anatomy(merged)
    slow = [r for r in anatomy
            if r["rank"] == 1 and r["step_to"] > inject]
    fast = [r for r in anatomy
            if r["rank"] == 0 and r["step_to"] > inject]
    assert slow and fast
    # every post-injection rank-1 window absorbs a 3 s boundary delay;
    # rank 0's contended-CPU windows stay well under half of that
    assert all(r["window_s"] >= 1.5 for r in slow), slow
    assert all(r["window_s"] < 1.5 for r in fast), fast
    trace = obs_timeline.chrome_trace(merged)
    lanes = {e["pid"] for e in trace["traceEvents"] if e.get("ph") == "X"}
    assert {0, 1} <= lanes
    assert any(e.get("ph") == "i" and e.get("name") == "anomaly"
               for e in trace["traceEvents"])
